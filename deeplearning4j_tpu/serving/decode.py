"""Continuous-batching KV-cache decode scheduler (Orca, OSDI'22).

Static request batching decodes a gang of requests until the LAST one
finishes: a 5-token reply waits for the 200-token reply it shares a batch
with, and its slot emits padding the whole time. Iteration-level
("continuous") batching reschedules at TOKEN granularity instead — a
fixed-slot decode program (`models.zoo.transformer.make_slot_decode_fn`)
runs one token for every occupied slot per dispatch, and requests join or
leave slots BETWEEN dispatches. Prefill and decode are separated: a
joining request's prompt runs through a per-prompt-length-bucket prefill
program (`make_prefill_fn`) whose cache rows are scattered into the free
slot, then the request rides the shared decode program.

Determinism pin (tests/test_serving.py): a request's token stream is
bit-identical whether it decodes alone or joins a running batch — every
slot's row math touches only its own cache/pos/token rows, and inactive
slots' cache writes are gated. So continuous batching is a pure
throughput lever, not an accuracy trade.

Hot swap keeps MULTIPLE param versions live while draining (one per
undrained swap, typically two): slots keep the version they started with
(a compiled program takes params as arguments, so versions share ONE
executable), each iteration dispatches once per live version with the
active mask restricted to that version's slots, and new requests route
to the newest version immediately — zero admission stall, zero dropped
in-flight requests. Drained versions are released on request completion
AND on idle iterations, so repeated swaps never accumulate dead params.

Speculative decoding (`speculate=`, serving/speculate.py): the 1-token
step is replaced by a K-wide verify program (`make_slot_verify_fn`) —
each iteration drafts K-1 tokens per slot (host-side n-gram lookup or a
small draft model) and ONE dispatch accepts 1..K of them per slot.
Slots advance VARIABLE token counts per iteration (the per-slot
positions already support ragged advance), streams stay bit-identical
to plain greedy decode (the accepted tokens are the verify program's
own argmax chain by construction; cross-width argmax parity is pinned
by test — see speculate.py), and speculation composes with the
dual-version swap drain (verify runs under the slot's pinned version;
the draft needs no pinning — it can only cost acceptance).

Deadlines are enforced mid-decode, not just at admission: a slot whose
request outlives its latency budget is evicted between iterations
(future fails with DeadlineExceededError, shed counted, slot refilled
the same iteration).

Paged KV cache (`paged=True`, serving/kvpool.py + the zoo's
`make_paged_decode_fn` / `make_paged_prefill_fn`): the fixed-slot cache
reserves `max_len` rows per slot, so concurrency is bounded by
WORST-CASE length. Paged mode keeps one flat block arena instead; every
request holds a block table, admission is gated by FREE BLOCKS (a
request that cannot get its blocks waits in a memory queue — counted
`blocked_on_memory` — while slots are a pure scheduling width), and
prompt prefixes shared across requests (system prompts, few-shot
templates) map to ONE physical copy with copy-on-write before any
divergent append. Prefill is two programs — a pure prefill returning
k/v panels plus a small DONATED install scatter (mirroring the fixed
path; a fused install would copy the whole undonated arena); decode
stays one dispatch per iteration — paging adds ZERO device dispatches
per token (pinned by counter A/B in tests/test_paged.py), and the
join==solo determinism pin carries over unchanged. `paged=True` +
`speculate=` raises at construction: the K-wide verify program indexes
the fixed-slot cache layout, and silently composing it with a block
table is exactly the wrong-cache failure mode to block.
"""
from __future__ import annotations

import collections
import concurrent.futures as cf
import logging
import queue
import threading
import time

import numpy as np

from .. import obs
from .server import (DeadlineExceededError, ServerClosedError,
                     ServerOverloadedError, _RequestLoop)

log = logging.getLogger(__name__)


def _fail_future(fut, exc):
    """set_exception unless the caller already resolved/cancelled it.
    The done() pre-check alone races a concurrent cancel() — and several
    call sites run OUTSIDE _loop_once's try, where an InvalidStateError
    would kill the serve thread permanently. Returns True when the
    exception was delivered (callers count metrics only then)."""
    try:
        if not fut.done():
            fut.set_exception(exc)
            return True
    except cf.InvalidStateError:
        pass
    return False


def _resolve_future(fut, result):
    """set_result, tolerating a concurrently cancel()ed future."""
    try:
        if not fut.done():
            fut.set_result(result)
            return True
    except cf.InvalidStateError:
        pass
    return False


class _DecodeRequest:
    __slots__ = ("prompt", "max_new", "future", "deadline", "t_submit",
                 "generated", "slot", "version", "req_id", "t_last_tok",
                 "alloc", "mem_blocked")

    def __init__(self, prompt, max_new, deadline):
        self.prompt = prompt
        self.max_new = int(max_new)
        self.future = cf.Future()
        self.deadline = deadline
        self.t_submit = time.monotonic()
        self.generated = []
        self.slot = None
        self.version = None
        self.req_id = None      # assigned at submit (the trace/request id)
        self.t_last_tok = None  # when this request's last token landed
        self.alloc = None       # paged mode: kvpool.PagedAllocation
        self.mem_blocked = False    # counted blocked_on_memory once


class ContinuousDecodeServer(_RequestLoop):
    """Token-granularity serving endpoint over a TransformerLM.

    `submit(prompt, max_new_tokens)` returns a Future resolving to the
    full token list (prompt + generated, greedy decode — the
    `generate_batch` contract). `static_batching=True` degrades scheduling
    to gang admission (a new batch only forms when every slot is free) —
    the A/B baseline `tools/serve_ab.py` measures against, through the
    exact same machinery.
    """

    _thread_name = "continuous-decode"
    _default_stop_timeout = 60.0

    def __init__(self, lm, slots=4, prompt_buckets=(8, 16, 32),
                 max_queue=64, fault_injector=None, retry_policy=None,
                 metrics=None, stats_reporter=None, report_every=64,
                 static_batching=False, speculate=None, tracer=None,
                 flight_recorder=None, paged=False, block_size=16,
                 n_blocks=None, prefix_cache=True,
                 max_blocks_per_slot=None):
        from ..models.zoo.transformer import (make_block_copy_fn,
                                              make_paged_decode_fn,
                                              make_paged_install_fn,
                                              make_paged_prefill_fn,
                                              make_prefill_fn,
                                              make_slot_decode_fn)
        from .speculate import as_speculator
        import jax

        self._tracer = tracer if tracer is not None else obs.TRACER
        self._flight = flight_recorder
        self.lm = lm
        self.slots = int(slots)
        self.max_len = int(lm.aux["pos"].shape[0])
        self.prompt_buckets = tuple(sorted(int(b) for b in prompt_buckets))
        if self.prompt_buckets[-1] > self.max_len:
            raise ValueError(f"largest prompt bucket "
                             f"{self.prompt_buckets[-1]} > model max_len "
                             f"{self.max_len}")
        self._injector = fault_injector
        self._retry = retry_policy
        from .metrics import ServingMetrics
        self.metrics = metrics or ServingMetrics()
        self._reporter = stats_reporter
        self._report_every = max(1, int(report_every))
        self._static = bool(static_batching)

        n_heads = lm.n_heads
        self._n_heads = n_heads
        self._d_model = int(lm.aux["tok"].shape[1])
        self._cache_dtype = lm.aux["tok"].dtype
        self._n_layers = len(lm.blocks)
        self._versions = [(lm.aux, lm.blocks)]   # index = param version

        # paged KV cache (module docstring): arena + block tables
        # replace the fixed per-slot cache; admission gates on free
        # blocks. Config resolves BEFORE _reset_device_state builds the
        # device state from it.
        self._paged = bool(paged)
        if self._paged and speculate is not None:
            # the verify program indexes the FIXED-SLOT cache layout;
            # running it against a block arena would read/write the
            # wrong physical rows and corrupt neighbouring streams —
            # fail at construction, never silently
            raise ValueError(
                "paged=True does not compose with speculate=: the "
                "K-wide verify program addresses the fixed-slot cache "
                "layout, not the block table (make the verify program "
                "paged, or drop one of the two flags)")
        self._block_size = int(block_size)
        if self._paged and self._block_size < 1:
            raise ValueError(f"need block_size >= 1, got {block_size}")
        # default arena == the fixed-slot footprint at the same slot
        # count (equal bytes); callers scale slots/arena independently
        self._n_blocks = (int(n_blocks) if n_blocks is not None else
                          -(-self.slots * self.max_len
                            // self._block_size))
        # per-slot logical capacity: enough table entries for max_len
        # rows (the submit() length guard caps every stream there)
        self._nb_slot = (int(max_blocks_per_slot)
                         if max_blocks_per_slot is not None else
                         -(-self.max_len // self._block_size))
        self._prefix_cache = bool(prefix_cache)
        self._mem_wait = collections.deque()     # blocked on FREE BLOCKS

        self._reset_device_state()
        # ONE decode program for the life of the server (fixed slot count;
        # params are arguments, so hot swap reuses it). Cache and pos are
        # donated — they are THE device state, rebound every iteration.
        if self._paged:
            # (aux, blocks, cache, btabs, pos, tok, active)
            self._step = jax.jit(
                make_paged_decode_fn(n_heads, self._block_size),
                donate_argnums=(2, 4))
        else:
            self._step = jax.jit(make_slot_decode_fn(n_heads),
                                 donate_argnums=(2, 3))
        # speculative decoding (serving/speculate.py): ONE K-wide verify
        # program replaces the 1-token step for every iteration — drafts
        # in, 1..K accepted tokens out per slot per dispatch, token
        # streams pinned bit-identical to the plain step. The program is
        # the model's OWN cached verify jit (`_spec_verify`), shared with
        # generate(draft=...) so the same (model, K) never compiles twice.
        self._spec = as_speculator(speculate)
        self._verify = (None if self._spec is None else
                        lm._spec_verify(self._spec.k))
        self._prefills = {}                      # bucket -> jitted program
        # Paged prefill mirrors the fixed path's two-program shape:
        # a pure-compute prefill returning panels (no arena argument —
        # an admission-time failure must fail ONLY that request, and a
        # program that neither takes nor returns the arena trivially
        # leaves it valid) plus a small DONATED install scatter that
        # aliases the arena in place. Fusing install into the prefill
        # would force the arena through an UNDONATED output and copy
        # every untouched row — the whole pool's bytes — per admission.
        # The CoW copy is donated for the same reason; it runs inside
        # _decode_iteration, whose failure path — like the donated
        # decode step's — resets the entire device state anyway.
        if self._paged:
            self._make_prefill = lambda: jax.jit(make_paged_prefill_fn(
                n_heads))
            self._paged_install = jax.jit(
                make_paged_install_fn(self._block_size),
                donate_argnums=(0,))
            self._cow_copy = jax.jit(
                make_block_copy_fn(self._block_size),
                donate_argnums=(0,))
        else:
            self._make_prefill = lambda: jax.jit(make_prefill_fn(
                n_heads, self.max_len))

            def install(cache, rows, s):
                return [{"k": c["k"].at[s].set(r["k"][0]),
                         "v": c["v"].at[s].set(r["v"][0])}
                        for c, r in zip(cache, rows)]
            # only the cache is donated: its buffers alias the output
            # exactly, while the [1, L, H, hd] prefill rows never could
            self._install = jax.jit(install, donate_argnums=(0,))

        self._swap_lock = threading.Lock()
        self._init_loop(max_queue)

    # -- client API ----------------------------------------------------
    def submit(self, prompt, max_new_tokens, deadline_ms=None):
        """Enqueue one decode request; Future resolves to the full token
        list (prompt + `max_new_tokens` greedy continuations)."""
        if not self._running:
            raise ServerClosedError("server is not running")
        prompt = [int(t) for t in np.asarray(prompt).ravel()]
        if not prompt:
            raise ValueError("prompt must contain at least one token")
        if int(max_new_tokens) < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}")
        if len(prompt) > self.prompt_buckets[-1]:
            raise ValueError(f"prompt length {len(prompt)} exceeds the "
                             f"largest bucket {self.prompt_buckets[-1]}")
        if len(prompt) + int(max_new_tokens) > self.max_len:
            raise ValueError(
                f"prompt+new tokens ({len(prompt)}+{max_new_tokens}) "
                f"exceed max_len {self.max_len}")
        if self._paged:
            # never-fits check: a request whose worst-case block table
            # exceeds the WHOLE pool would wait forever in the memory
            # queue — shed it loudly at submit instead
            need = self._pool.blocks_needed(
                len(prompt) + int(max_new_tokens) - 1)
            if need > self._n_blocks:
                self.metrics.count("shed_blocks")
                raise ServerOverloadedError(
                    f"request needs {need} KV blocks but the pool holds "
                    f"{self._n_blocks} (block_size="
                    f"{self._block_size})")
            if need > self._nb_slot:
                # the per-slot block TABLE is the other hard ceiling: a
                # caller-tuned max_blocks_per_slot below ceil(max_len/bs)
                # must shed here, not crash the admission thread on the
                # table write
                self.metrics.count("shed_blocks")
                raise ServerOverloadedError(
                    f"request needs {need} KV blocks but a slot's table "
                    f"holds {self._nb_slot} (max_blocks_per_slot)")
        if self._injector is not None:
            self._injector.fire("serve.request")
        self.metrics.count("received")
        dl = (time.monotonic() + deadline_ms / 1e3
              if deadline_ms is not None else None)
        return self._enqueue(_DecodeRequest(prompt, max_new_tokens, dl))

    def generate(self, prompt, max_new_tokens, deadline_ms=None,
                 timeout=None):
        """Blocking convenience wrapper over submit()."""
        return self.submit(prompt, max_new_tokens,
                           deadline_ms=deadline_ms).result(timeout)

    # -- hot swap ------------------------------------------------------
    def swap(self, new_lm):
        """Route NEW requests to `new_lm`'s params while slots already
        decoding drain on the version they started with (dual-version
        dispatch — module docstring). Structure/shape mismatch raises."""
        import jax
        with self._swap_lock:
            if self._injector is not None:
                self._injector.fire("serve.swap")
            new = (new_lm.aux, new_lm.blocks)
            old_l, old_t = jax.tree_util.tree_flatten(self._versions[-1])
            new_l, new_t = jax.tree_util.tree_flatten(new)
            if old_t != new_t:
                raise ValueError("swap rejected: param tree structure "
                                 "differs from the serving model")
            for o, n in zip(old_l, new_l):
                if o.shape != n.shape or o.dtype != n.dtype:
                    raise ValueError(f"swap rejected: leaf mismatch "
                                     f"{n.shape}/{n.dtype} vs serving "
                                     f"{o.shape}/{o.dtype}")
            self._versions.append(new)
            self.metrics.count("swaps")

    # -- scheduler internals -------------------------------------------
    def _complete(self, req, t_now):
        """Resolve one finished request: future, latency + SLO metrics,
        the request-timeline span, and the flight-recorder feed. ONE
        implementation for the three completion sites (prefill-only,
        plain iteration, speculative iteration) so SLO accounting cannot
        drift between them."""
        if not _resolve_future(req.future,
                               list(req.prompt) + req.generated):
            return
        total_ms = (t_now - req.t_submit) * 1e3
        self.metrics.record_request(
            total_ms, tokens=len(req.generated),
            deadline_met=(None if req.deadline is None
                          else t_now <= req.deadline))
        tr = self._tracer
        if tr.enabled:
            t0 = int(req.t_submit * 1e9)
            tr.emit("serve.request", t0, int(total_ms * 1e6), cat="serve",
                    track=f"req-{req.req_id}", trace_id=req.req_id,
                    args={"tokens": len(req.generated)})
        if self._flight is not None:
            self._flight.observe(total_ms)

    def _reset_device_state(self):
        """Fresh slot state: the KV cache, per-slot positions/tokens, and
        host-side occupancy. Called at construction and after a decode
        dispatch fails terminally (the donated cache/pos buffers may have
        been consumed by the failed call — they cannot be trusted)."""
        import jax.numpy as jnp

        from ..models.zoo.transformer import (init_kv_cache,
                                              init_paged_kv_cache)
        if self._paged:
            from .kvpool import BlockPool
            self._cache = init_paged_kv_cache(
                self._n_layers, self._n_blocks, self._block_size,
                self._d_model, self._n_heads, self._cache_dtype)
            # the pool dies with the arena: every allocation referenced
            # rows in buffers that no longer exist
            self._pool = BlockPool(self._n_blocks, self._block_size,
                                   prefix_cache=self._prefix_cache)
            self._btabs = np.zeros((self.slots, self._nb_slot), np.int32)
        else:
            self._cache = init_kv_cache(self._n_layers, self.slots,
                                        self.max_len, self._d_model,
                                        self._n_heads, self._cache_dtype)
        self._pos = jnp.zeros((self.slots,), jnp.int32)
        self._tok = jnp.zeros((self.slots,), jnp.int32)
        self._slot_req = [None] * self.slots     # host-side occupancy
        spec = getattr(self, "_spec", None)      # unset on first call
        if spec is not None:
            for s in range(self.slots):          # idempotent stops
                spec.draft.stop(s)

    @property
    def prefill_programs(self):
        """bucket -> compiled prefill program (compile-cache pin)."""
        return dict(self._prefills)

    def _prompt_bucket(self, n):
        for b in self.prompt_buckets:
            if b >= n:
                return b
        return self.prompt_buckets[-1]

    def _admit(self, req, slot, alloc=None, version=None):
        """Prefill `req`'s prompt and install it into `slot` (paged
        mode: through `alloc`'s block table — a pure prefill dispatch
        plus the donated install scatter on success). `version` is the
        (vidx, aux, blocks) the PAGED caller
        already bound when it tagged the pool admission — prefill must
        run under exactly the params the prefix match was tagged with,
        or a swap racing the admission could share old-version rows
        into a new-version stream."""
        import jax.numpy as jnp
        tr = self._tracer
        if tr.enabled:
            # queue wait ends at ADMISSION here (a decode request's
            # "batch formation" is winning a slot)
            t0 = int(req.t_submit * 1e9)
            tr.emit("serve.queue_wait", t0, time.monotonic_ns() - t0,
                    cat="serve", track=f"req-{req.req_id}",
                    trace_id=req.req_id)
        bucket = self._prompt_bucket(len(req.prompt))
        prog = self._prefills.get(bucket)
        if prog is None:
            prog = self._prefills[bucket] = self._make_prefill()
            log.info("compiled prefill program for prompt bucket %d",
                     bucket)
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :len(req.prompt)] = req.prompt
        if version is not None:
            vidx, aux, blocks = version
        else:
            with self._swap_lock:   # version index + params read atomically
                vidx = len(self._versions) - 1
                aux, blocks = self._versions[vidx]

        def dispatch():
            if self._injector is not None:
                self._injector.fire("serve.batch")
            return prog(aux, blocks, jnp.asarray(padded),
                        jnp.asarray(len(req.prompt), jnp.int32))

        with self._tracer.span("decode.prefill", cat="serve",
                               track="server", trace_id=req.req_id,
                               bucket=bucket, slot=slot):
            if self._retry is not None:
                logits, rows = self._retry.call(
                    dispatch,
                    on_retry=lambda a, e, d: self.metrics.count("retries"))
            else:
                logits, rows = dispatch()
        if self._paged:
            # `rows` are the prompt's k/v panels: scatter them to their
            # block-table rows in the DONATED install (arena aliased in
            # place — a prefill failure above leaves it untouched). Only
            # now are the prompt blocks really filled, so only now may
            # they enter the prefix index — commit() BEFORE this point
            # would let a failed prefill leave garbage blocks matchable
            tab = np.zeros((self._nb_slot,), np.int32)
            tab[:len(alloc.ids)] = alloc.ids
            self._cache = self._paged_install(
                self._cache, rows, jnp.asarray(tab),
                jnp.asarray(len(req.prompt), jnp.int32),
                jnp.asarray(alloc.shared_rows, jnp.int32))
            self._pool.commit(alloc)
            self.metrics.count("prefix_rows_total", len(req.prompt))
            if alloc.shared_rows:
                self.metrics.count("prefix_rows_hit", alloc.shared_rows)
        first = int(np.argmax(np.asarray(logits)[0]))
        req.generated.append(first)
        # TTFT closes HERE: prefill's argmax IS the first generated
        # token, whether or not the request goes on to occupy a slot
        req.t_last_tok = time.monotonic()
        self.metrics.record_ttft((req.t_last_tok - req.t_submit) * 1e3)
        if len(req.generated) >= req.max_new:
            # one-token request: done at prefill, never occupies a slot
            # (paged: its blocks release immediately — and a shared
            # partial block it rode needed no CoW, the zero-copy case)
            self._complete(req, time.monotonic())
            if self._paged:
                self._pool.release(alloc)
            return
        if self._paged:
            req.alloc = alloc
            self._btabs[slot, :] = 0
            self._btabs[slot, :len(alloc.ids)] = alloc.ids
        else:
            self._cache = self._install(self._cache, rows, slot)
        self._pos = self._pos.at[slot].set(len(req.prompt))
        self._tok = self._tok.at[slot].set(first)
        req.slot = slot
        req.version = vidx
        self._slot_req[slot] = req
        if self._spec is not None:
            # draft stream keyed by slot: full context so far (slot reuse
            # is safe — start() resets the key, _free_slot stops it)
            self._spec.draft.start(slot, list(req.prompt) + req.generated)

    def _next_request(self, wait):
        """Head of the admission line: memory-blocked requests first
        (FIFO — a small late request must not starve a big early one),
        then the submit queue."""
        if self._mem_wait:
            return self._mem_wait.popleft()
        try:
            return self._q.get(timeout=wait) if wait \
                else self._q.get_nowait()
        except queue.Empty:
            return None

    def _admit_pending(self, timeout=0.0):
        """Fill free slots from the queue. `timeout` blocks on the FIRST
        get only — the idle loop's way of waiting for work on the queue
        itself instead of busy-polling at the 1 ms decode tick. Paged
        mode adds the MEMORY gate: a request that cannot get its blocks
        parks at the head of the line (`blocked_on_memory` counted once)
        and admission stops until completions free blocks."""
        if not self._running and not self._drain_on_stop:
            # fail-fast stop: queued requests must NOT be admitted into
            # freed slots — the loop's final drain fails them once the
            # busy slots finish. The memory-wait line is failed HERE,
            # not at loop exit: parked requests count as _busy(), so
            # leaving them parked would keep the loop alive (and their
            # futures unresolved) forever once the slots drain.
            self._fail_mem_wait(ServerClosedError("server stopped"))
            return
        free = [s for s in range(self.slots) if self._slot_req[s] is None]
        if self._static and len(free) < self.slots:
            return      # gang scheduling: wait for the whole batch
        wait = float(timeout)
        for s in free:
            req, alloc = None, None
            while req is None:
                req = self._next_request(wait)
                wait = 0.0
                if req is None:
                    return
                if req.future.done():   # failed by a raced submit/stop
                    req = None
                elif req.deadline is not None and \
                        time.monotonic() > req.deadline:
                    if _fail_future(req.future, DeadlineExceededError(
                            "deadline expired before prefill")):
                        self.metrics.count("shed_deadline")
                        self.metrics.record_slo_miss()
                    req = None
                elif self._paged:
                    # admission gated by FREE BLOCKS, not free slots:
                    # reserve everything the request will ever write
                    # (prompt + decode rows, minus any shared prefix).
                    # The param version is bound HERE, before the prefix
                    # match: the match is tagged with it and the prefill
                    # below runs under the same params, so a swap racing
                    # this admission cannot share old-version rows into
                    # a new-version stream.
                    with self._swap_lock:
                        vidx = len(self._versions) - 1
                        aux, blocks = self._versions[vidx]
                    version = (vidx, aux, blocks)
                    alloc = self._pool.admit(
                        req.prompt, len(req.prompt) + req.max_new - 1,
                        will_append=req.max_new > 1, tag=vidx)
                    if alloc is None:
                        if not req.mem_blocked:
                            req.mem_blocked = True
                            self.metrics.count("blocked_on_memory")
                        self._mem_wait.appendleft(req)
                        return
            try:
                self._admit(req, s, alloc,
                            version=version if self._paged else None)
            except BaseException as e:  # noqa: BLE001 — fail THIS request
                if alloc is not None:
                    self._pool.release(alloc)
                _fail_future(req.future, e)
                self.metrics.count("failed")

    def _free_slot(self, slot):
        """Release `slot`'s host-side occupancy (and its draft stream,
        and — paged — its block-table allocation back to the pool).
        Device rows/pos are left stale on purpose: the next admission
        resets pos and decode overwrites rows before attending (the
        dead-row contract); a freed slot's stale block table is inert
        because inactive slots' writes are index-dropped."""
        req = self._slot_req[slot]
        self._slot_req[slot] = None
        if self._paged and req is not None and req.alloc is not None:
            self._pool.release(req.alloc)
            req.alloc = None
            self._btabs[slot, :] = 0
        if self._spec is not None:
            self._spec.draft.stop(slot)

    def _expire_mem_wait(self, now):
        """Deadline enforcement for requests parked on the MEMORY gate:
        blocked-on-blocks is queue wait too, and a request must not
        outlive its budget just because it never won blocks."""
        if not self._mem_wait:
            return
        keep = collections.deque()
        while self._mem_wait:
            r = self._mem_wait.popleft()
            if r.future.done():
                continue
            if r.deadline is not None and now > r.deadline:
                if _fail_future(r.future, DeadlineExceededError(
                        "deadline expired while blocked on KV blocks")):
                    self.metrics.count("shed_deadline")
                    self.metrics.record_slo_miss()
            else:
                keep.append(r)
        self._mem_wait = keep

    def _evict_expired(self):
        """Mid-decode deadline enforcement: a slot whose request deadline
        has passed is evicted BETWEEN iterations — future fails with
        DeadlineExceededError, the shed is counted, and the slot frees
        THIS iteration (the following `_admit_pending` can refill it).
        Admission-time shedding (`_admit_pending`) only protects requests
        that expire in the queue; this protects the slots themselves from
        requests whose token budget outlives their latency budget."""
        now = time.monotonic()
        self._expire_mem_wait(now)
        evicted = False
        for s, r in enumerate(self._slot_req):
            if r is None or r.deadline is None or now <= r.deadline:
                continue
            if _fail_future(r.future, DeadlineExceededError(
                    f"deadline expired mid-decode after "
                    f"{len(r.generated)} tokens")):
                self.metrics.count("shed_deadline")
                self.metrics.count("evicted_mid_decode")
                self.metrics.record_slo_miss()
            self._free_slot(s)
            evicted = True
        if evicted:
            self._gc_versions()

    def _materialize_cow(self, live):
        """Lazy copy-on-write, at exactly the FIRST divergent append: a
        live slot whose next write lands in a block it still SHARES gets
        its private copy now — the spare was reserved at admission, so
        this can never fail for lack of blocks. One small device copy
        per CoW event (per REQUEST, not per token — the per-token
        dispatch count is pinned unchanged by tests/test_paged.py)."""
        import jax.numpy as jnp
        for s, r in live:
            if r.alloc is None or r.alloc.cow is None:
                continue
            src, dst = self._pool.cow(r.alloc)
            self._btabs[s, :len(r.alloc.ids)] = r.alloc.ids
            with self._tracer.span("decode.cow", cat="serve",
                                   track="server", src=src, dst=dst):
                self._cache = self._cow_copy(
                    self._cache, jnp.asarray(src, jnp.int32),
                    jnp.asarray(dst, jnp.int32))
            self.metrics.count("cow_copies")

    def _fail_mem_wait(self, exc):
        while self._mem_wait:
            r = self._mem_wait.popleft()
            if _fail_future(r.future, exc):
                self.metrics.count("failed")

    def _fail_queued(self, exc):
        """Queued = the submit queue AND the paged memory-wait line."""
        self._fail_mem_wait(exc)
        super()._fail_queued(exc)

    def _decode_iteration(self):
        """One scheduling iteration for every occupied slot: one dispatch
        per live param version, active mask restricted to that version's
        slots. Plain mode advances every slot exactly one token;
        speculative mode (`speculate=`) advances each slot 1..K tokens
        per dispatch (per-slot positions already support ragged
        advance)."""
        import jax.numpy as jnp
        live = [(s, r) for s, r in enumerate(self._slot_req)
                if r is not None]
        if not live:
            return False
        if self._spec is not None:
            return self._spec_iteration(live)
        tr = self._tracer
        t_iter0 = time.monotonic_ns() if tr.enabled else None
        self.metrics.record_occupancy(len(live), self.slots)
        self.metrics.record_live_streams(len(live))
        if self._paged:
            self._materialize_cow(live)
            self.metrics.record_pool(self._pool.blocks_in_use,
                                     self._pool.capacity)
        versions = sorted({r.version for _, r in live})
        new_tok = {}
        for v in versions:
            active = np.zeros((self.slots,), bool)
            for s, r in live:
                if r.version == v:
                    active[s] = True
            aux, blocks = self._versions[v]

            def dispatch():
                if self._injector is not None:
                    self._injector.fire("serve.batch")
                if self._paged:
                    return self._step(aux, blocks, self._cache,
                                      jnp.asarray(self._btabs),
                                      self._pos, self._tok,
                                      jnp.asarray(active))
                return self._step(aux, blocks, self._cache, self._pos,
                                  self._tok, jnp.asarray(active))

            # NOTE on retry composition: cache/pos are donated, so a
            # failure INSIDE the compiled call is not retryable at this
            # level (the buffers are gone) — the injector site sits before
            # the call, which is exactly the transient class (tunnel
            # hiccup before dispatch) retries exist for.
            with tr.span("decode.dispatch", cat="serve", track="server",
                         version=v):
                if self._retry is not None:
                    nxt, _, self._cache, self._pos = self._retry.call(
                        dispatch,
                        on_retry=lambda a, e, d: self.metrics.count(
                            "retries"))
                else:
                    nxt, _, self._cache, self._pos = dispatch()
            self.metrics.count("dispatches")
            nxt = np.asarray(nxt)
            for s, r in live:
                if r.version == v:
                    new_tok[s] = int(nxt[s])
        self._tok = jnp.asarray(
            [new_tok.get(s, 0) for s in range(self.slots)], jnp.int32)
        self.metrics.count("tokens_out", len(live))
        done_any = False
        t_now = time.monotonic()
        for s, r in live:
            r.generated.append(new_tok[s])
            # one inter-token sample per decode iteration per slot
            if r.t_last_tok is not None:
                self.metrics.record_inter_token(
                    (t_now - r.t_last_tok) * 1e3)
            r.t_last_tok = t_now
            if len(r.generated) >= r.max_new:
                # the final token needs no decode step (generate() makes
                # the same point): resolve and free the slot
                r.generated = r.generated[:r.max_new]
                self._complete(r, t_now)
                self._free_slot(s)
                done_any = True
        if t_iter0 is not None:
            # one span per scheduling iteration, tagged with the two
            # numbers head-of-line surgery needs: how full the machine
            # was and how many tokens the iteration produced
            tr.emit("decode.iteration", t_iter0,
                    time.monotonic_ns() - t_iter0, cat="serve",
                    track="server",
                    args={"slot_occupancy": len(live) / self.slots,
                          "accepted": len(live)})
        if done_any:
            self._gc_versions()
        self._after_iteration()
        return True

    def _spec_iteration(self, live):
        """One SPECULATIVE iteration: per live version, gather each
        slot's draft (K-1 tokens, zero-padded — padding costs acceptance,
        never correctness), run ONE K-wide verify dispatch, and advance
        each slot by its accepted count (matched prefix + bonus). The
        emitted stream is the verify program's own greedy argmax chain —
        acceptance only decides the dispatch count; bit-identity with
        the plain step's stream is pinned by test (cross-width argmax
        parity, speculate.py). Draft and verify are both evaluated
        under the slot's pinned param version (`r.version`); the draft
        source itself needs no pinning because a mismatched draft cannot
        alter accepted tokens."""
        import jax.numpy as jnp
        tr = self._tracer
        t_iter0 = time.monotonic_ns() if tr.enabled else None
        n_accepted = 0
        self.metrics.record_occupancy(len(live), self.slots)
        self.metrics.record_live_streams(len(live))
        K = self._spec.k
        draft = self._spec.draft
        d0 = getattr(draft, "dispatch_count", 0)   # ModelDraft device cost
        versions = sorted({r.version for _, r in live})
        done_any = False
        for v in versions:
            live_v = [(s, r) for s, r in live if r.version == v]
            active = np.zeros((self.slots,), bool)
            toks = np.zeros((self.slots, K), np.int32)
            n_dr = {}
            for s, r in live_v:
                active[s] = True
                # never request drafts past the request's remaining token
                # budget: a ModelDraft would pay real dispatches for
                # tokens that can never be accepted, and the acceptance
                # reservoir would log them as misses
                n_want = r.max_new - len(r.generated)
                dr = list(draft.propose(
                    s, min(K - 1, n_want - 1)))[:K - 1]
                n_dr[s] = len(dr)
                toks[s, :1 + len(dr)] = [r.generated[-1]] + dr
            aux, blocks = self._versions[v]

            def dispatch():
                if self._injector is not None:
                    self._injector.fire("serve.batch")
                return self._verify(aux, blocks, self._cache, self._pos,
                                    jnp.asarray(toks), jnp.asarray(active))

            # same donated-buffer retry contract as the plain step: the
            # injector site sits BEFORE the compiled call (the transient
            # tunnel-hiccup class); a failure inside it is terminal here
            with tr.span("decode.verify", cat="serve", track="server",
                         version=v, k=K):
                if self._retry is not None:
                    nxt, n_acc, _, self._cache, self._pos = \
                        self._retry.call(
                            dispatch,
                            on_retry=lambda a, e, d: self.metrics.count(
                                "retries"))
                else:
                    nxt, n_acc, _, self._cache, self._pos = dispatch()
            self.metrics.count("dispatches")
            nxt = np.asarray(nxt)
            n_acc = np.asarray(n_acc)
            t_now = time.monotonic()
            for s, r in live_v:
                want = r.max_new - len(r.generated)
                take = min(int(n_acc[s]) + 1, want)
                acc = [int(t) for t in nxt[s, :take]]
                r.generated.extend(acc)
                # a speculative iteration lands `take` tokens at once:
                # record the PER-TOKEN stream rate (delta / take), one
                # sample per iteration per slot like the plain step
                if take and r.t_last_tok is not None:
                    self.metrics.record_inter_token(
                        (t_now - r.t_last_tok) * 1e3 / take)
                r.t_last_tok = t_now
                n_accepted += take
                self.metrics.count("tokens_out", take)
                # drafted = REAL draft tokens (zero-padding is not a
                # draft); matched likewise capped — a pad that happens to
                # equal the argmax is accepted (it IS the argmax) but
                # credits luck, not the draft
                self.metrics.record_speculation(
                    take, n_dr[s], min(int(n_acc[s]), take, n_dr[s]))
                if len(r.generated) >= r.max_new:
                    self._complete(r, t_now)
                    self._free_slot(s)
                    done_any = True
                else:
                    draft.observe(s, acc)
        dd = getattr(draft, "dispatch_count", 0) - d0
        if dd:
            # a ModelDraft pays real device dispatches for its proposals;
            # count them so dispatch amortization stays honest (NGramDraft
            # never moves this — host-only)
            self.metrics.count("draft_dispatches", dd)
        if t_iter0 is not None:
            tr.emit("decode.iteration", t_iter0,
                    time.monotonic_ns() - t_iter0, cat="serve",
                    track="server",
                    args={"slot_occupancy": len(live) / self.slots,
                          "accepted": n_accepted,
                          "draft_dispatches": dd})
        if done_any:
            self._gc_versions()
        self._after_iteration()
        return True

    def _after_iteration(self):
        self.metrics.count("batches")       # decode iterations
        if self._reporter is not None and \
                self.metrics.count_value("batches") % self._report_every \
                == 0:
            self._reporter.report(self.metrics.snapshot())

    def _gc_versions(self):
        """Drop drained old param versions (keep indices stable: only a
        fully-drained PREFIX below the newest can be released)."""
        with self._swap_lock:
            in_use = {r.version for r in self._slot_req if r is not None}
            newest = len(self._versions) - 1
            for v in range(newest):
                if v not in in_use and self._versions[v] is not None:
                    self._versions[v] = None

    def _busy(self):
        return any(r is not None for r in self._slot_req) \
            or bool(self._mem_wait)

    def _loop_once(self):
        # evict deadline-expired slots FIRST so the admit below can refill
        # them in the same iteration
        self._evict_expired()
        # idle (no slot occupied): block on the queue up to 50 ms instead
        # of spinning at the decode tick; busy: drain the queue non-blocking
        self._admit_pending(timeout=0.0 if self._busy() else 0.05)
        try:
            busy = self._decode_iteration()
        except BaseException as e:  # noqa: BLE001 — fail slots, survive
            # a decode dispatch failed terminally (non-retryable, or
            # retries exhausted). The donated cache/pos buffers cannot be
            # trusted after a failed call, so every occupied request
            # fails LOUDLY and the slot state resets — the server keeps
            # serving instead of stranding all future requests on a dead
            # thread.
            n_failed = 0
            for r in self._slot_req:
                if r is not None and _fail_future(r.future, e):
                    n_failed += 1
            if n_failed:
                self.metrics.count("failed", n_failed)
            self._reset_device_state()
            self._gc_versions()
            return
        if not busy:
            # idle: still GC param versions (repeated swaps on an idle
            # server must not accumulate dead params); the next loop's
            # blocking admit is the idle wait, no sleep needed
            self._gc_versions()
