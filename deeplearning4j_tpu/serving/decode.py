"""Continuous-batching KV-cache decode scheduler (Orca, OSDI'22).

Static request batching decodes a gang of requests until the LAST one
finishes: a 5-token reply waits for the 200-token reply it shares a batch
with, and its slot emits padding the whole time. Iteration-level
("continuous") batching reschedules at TOKEN granularity instead — a
fixed-slot decode program (`models.zoo.transformer.make_slot_decode_fn`)
runs one token for every occupied slot per dispatch, and requests join or
leave slots BETWEEN dispatches. Prefill and decode are separated: a
joining request's prompt runs through a per-prompt-length-bucket prefill
program (`make_prefill_fn`) whose cache rows are scattered into the free
slot, then the request rides the shared decode program.

Determinism pin (tests/test_serving.py): a request's token stream is
bit-identical whether it decodes alone or joins a running batch — every
slot's row math touches only its own cache/pos/token rows, and inactive
slots' cache writes are gated. So continuous batching is a pure
throughput lever, not an accuracy trade.

Hot swap keeps MULTIPLE param versions live while draining (one per
undrained swap, typically two): slots keep the version they started with
(a compiled program takes params as arguments, so versions share ONE
executable), each iteration dispatches once per live version with the
active mask restricted to that version's slots, and new requests route
to the newest version immediately — zero admission stall, zero dropped
in-flight requests. Drained versions are released on request completion
AND on idle iterations, so repeated swaps never accumulate dead params.

Speculative decoding (`speculate=`, serving/speculate.py): the 1-token
step is replaced by a K-wide verify program (`make_slot_verify_fn`) —
each iteration drafts K-1 tokens per slot (host-side n-gram lookup or a
small draft model) and ONE dispatch accepts 1..K of them per slot.
Slots advance VARIABLE token counts per iteration (the per-slot
positions already support ragged advance), streams stay bit-identical
to plain greedy decode (the accepted tokens are the verify program's
own argmax chain by construction; cross-width argmax parity is pinned
by test — see speculate.py), and speculation composes with the
dual-version swap drain (verify runs under the slot's pinned version;
the draft needs no pinning — it can only cost acceptance).

Deadlines are enforced mid-decode, not just at admission: a slot whose
request outlives its latency budget is evicted between iterations
(future fails with DeadlineExceededError, shed counted, slot refilled
the same iteration).
"""
from __future__ import annotations

import concurrent.futures as cf
import logging
import queue
import threading
import time

import numpy as np

from .. import obs
from .server import (DeadlineExceededError, ServerClosedError,
                     _RequestLoop)

log = logging.getLogger(__name__)


def _fail_future(fut, exc):
    """set_exception unless the caller already resolved/cancelled it.
    The done() pre-check alone races a concurrent cancel() — and several
    call sites run OUTSIDE _loop_once's try, where an InvalidStateError
    would kill the serve thread permanently. Returns True when the
    exception was delivered (callers count metrics only then)."""
    try:
        if not fut.done():
            fut.set_exception(exc)
            return True
    except cf.InvalidStateError:
        pass
    return False


def _resolve_future(fut, result):
    """set_result, tolerating a concurrently cancel()ed future."""
    try:
        if not fut.done():
            fut.set_result(result)
            return True
    except cf.InvalidStateError:
        pass
    return False


class _DecodeRequest:
    __slots__ = ("prompt", "max_new", "future", "deadline", "t_submit",
                 "generated", "slot", "version", "req_id", "t_last_tok")

    def __init__(self, prompt, max_new, deadline):
        self.prompt = prompt
        self.max_new = int(max_new)
        self.future = cf.Future()
        self.deadline = deadline
        self.t_submit = time.monotonic()
        self.generated = []
        self.slot = None
        self.version = None
        self.req_id = None      # assigned at submit (the trace/request id)
        self.t_last_tok = None  # when this request's last token landed


class ContinuousDecodeServer(_RequestLoop):
    """Token-granularity serving endpoint over a TransformerLM.

    `submit(prompt, max_new_tokens)` returns a Future resolving to the
    full token list (prompt + generated, greedy decode — the
    `generate_batch` contract). `static_batching=True` degrades scheduling
    to gang admission (a new batch only forms when every slot is free) —
    the A/B baseline `tools/serve_ab.py` measures against, through the
    exact same machinery.
    """

    _thread_name = "continuous-decode"
    _default_stop_timeout = 60.0

    def __init__(self, lm, slots=4, prompt_buckets=(8, 16, 32),
                 max_queue=64, fault_injector=None, retry_policy=None,
                 metrics=None, stats_reporter=None, report_every=64,
                 static_batching=False, speculate=None, tracer=None,
                 flight_recorder=None):
        from ..models.zoo.transformer import (make_prefill_fn,
                                              make_slot_decode_fn)
        from .speculate import as_speculator
        import jax

        self._tracer = tracer if tracer is not None else obs.TRACER
        self._flight = flight_recorder
        self.lm = lm
        self.slots = int(slots)
        self.max_len = int(lm.aux["pos"].shape[0])
        self.prompt_buckets = tuple(sorted(int(b) for b in prompt_buckets))
        if self.prompt_buckets[-1] > self.max_len:
            raise ValueError(f"largest prompt bucket "
                             f"{self.prompt_buckets[-1]} > model max_len "
                             f"{self.max_len}")
        self._injector = fault_injector
        self._retry = retry_policy
        from .metrics import ServingMetrics
        self.metrics = metrics or ServingMetrics()
        self._reporter = stats_reporter
        self._report_every = max(1, int(report_every))
        self._static = bool(static_batching)

        n_heads = lm.n_heads
        self._n_heads = n_heads
        self._d_model = int(lm.aux["tok"].shape[1])
        self._cache_dtype = lm.aux["tok"].dtype
        self._n_layers = len(lm.blocks)
        self._versions = [(lm.aux, lm.blocks)]   # index = param version
        self._reset_device_state()
        # ONE decode program for the life of the server (fixed slot count;
        # params are arguments, so hot swap reuses it). Cache and pos are
        # donated — they are THE device state, rebound every iteration.
        self._step = jax.jit(make_slot_decode_fn(n_heads),
                             donate_argnums=(2, 3))
        # speculative decoding (serving/speculate.py): ONE K-wide verify
        # program replaces the 1-token step for every iteration — drafts
        # in, 1..K accepted tokens out per slot per dispatch, token
        # streams pinned bit-identical to the plain step. The program is
        # the model's OWN cached verify jit (`_spec_verify`), shared with
        # generate(draft=...) so the same (model, K) never compiles twice.
        self._spec = as_speculator(speculate)
        self._verify = (None if self._spec is None else
                        lm._spec_verify(self._spec.k))
        self._prefills = {}                      # bucket -> jitted program
        self._make_prefill = lambda: jax.jit(make_prefill_fn(
            n_heads, self.max_len))

        def install(cache, rows, s):
            return [{"k": c["k"].at[s].set(r["k"][0]),
                     "v": c["v"].at[s].set(r["v"][0])}
                    for c, r in zip(cache, rows)]
        # only the cache is donated: its buffers alias the output exactly,
        # while the [1, L, H, hd] prefill rows never could
        self._install = jax.jit(install, donate_argnums=(0,))

        self._swap_lock = threading.Lock()
        self._init_loop(max_queue)

    # -- client API ----------------------------------------------------
    def submit(self, prompt, max_new_tokens, deadline_ms=None):
        """Enqueue one decode request; Future resolves to the full token
        list (prompt + `max_new_tokens` greedy continuations)."""
        if not self._running:
            raise ServerClosedError("server is not running")
        prompt = [int(t) for t in np.asarray(prompt).ravel()]
        if not prompt:
            raise ValueError("prompt must contain at least one token")
        if int(max_new_tokens) < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}")
        if len(prompt) > self.prompt_buckets[-1]:
            raise ValueError(f"prompt length {len(prompt)} exceeds the "
                             f"largest bucket {self.prompt_buckets[-1]}")
        if len(prompt) + int(max_new_tokens) > self.max_len:
            raise ValueError(
                f"prompt+new tokens ({len(prompt)}+{max_new_tokens}) "
                f"exceed max_len {self.max_len}")
        if self._injector is not None:
            self._injector.fire("serve.request")
        self.metrics.count("received")
        dl = (time.monotonic() + deadline_ms / 1e3
              if deadline_ms is not None else None)
        return self._enqueue(_DecodeRequest(prompt, max_new_tokens, dl))

    def generate(self, prompt, max_new_tokens, deadline_ms=None,
                 timeout=None):
        """Blocking convenience wrapper over submit()."""
        return self.submit(prompt, max_new_tokens,
                           deadline_ms=deadline_ms).result(timeout)

    # -- hot swap ------------------------------------------------------
    def swap(self, new_lm):
        """Route NEW requests to `new_lm`'s params while slots already
        decoding drain on the version they started with (dual-version
        dispatch — module docstring). Structure/shape mismatch raises."""
        import jax
        with self._swap_lock:
            if self._injector is not None:
                self._injector.fire("serve.swap")
            new = (new_lm.aux, new_lm.blocks)
            old_l, old_t = jax.tree_util.tree_flatten(self._versions[-1])
            new_l, new_t = jax.tree_util.tree_flatten(new)
            if old_t != new_t:
                raise ValueError("swap rejected: param tree structure "
                                 "differs from the serving model")
            for o, n in zip(old_l, new_l):
                if o.shape != n.shape or o.dtype != n.dtype:
                    raise ValueError(f"swap rejected: leaf mismatch "
                                     f"{n.shape}/{n.dtype} vs serving "
                                     f"{o.shape}/{o.dtype}")
            self._versions.append(new)
            self.metrics.count("swaps")

    # -- scheduler internals -------------------------------------------
    def _complete(self, req, t_now):
        """Resolve one finished request: future, latency + SLO metrics,
        the request-timeline span, and the flight-recorder feed. ONE
        implementation for the three completion sites (prefill-only,
        plain iteration, speculative iteration) so SLO accounting cannot
        drift between them."""
        if not _resolve_future(req.future,
                               list(req.prompt) + req.generated):
            return
        total_ms = (t_now - req.t_submit) * 1e3
        self.metrics.record_request(
            total_ms, tokens=len(req.generated),
            deadline_met=(None if req.deadline is None
                          else t_now <= req.deadline))
        tr = self._tracer
        if tr.enabled:
            t0 = int(req.t_submit * 1e9)
            tr.emit("serve.request", t0, int(total_ms * 1e6), cat="serve",
                    track=f"req-{req.req_id}", trace_id=req.req_id,
                    args={"tokens": len(req.generated)})
        if self._flight is not None:
            self._flight.observe(total_ms)

    def _reset_device_state(self):
        """Fresh slot state: the KV cache, per-slot positions/tokens, and
        host-side occupancy. Called at construction and after a decode
        dispatch fails terminally (the donated cache/pos buffers may have
        been consumed by the failed call — they cannot be trusted)."""
        import jax.numpy as jnp

        from ..models.zoo.transformer import init_kv_cache
        self._cache = init_kv_cache(self._n_layers, self.slots,
                                    self.max_len, self._d_model,
                                    self._n_heads, self._cache_dtype)
        self._pos = jnp.zeros((self.slots,), jnp.int32)
        self._tok = jnp.zeros((self.slots,), jnp.int32)
        self._slot_req = [None] * self.slots     # host-side occupancy
        spec = getattr(self, "_spec", None)      # unset on first call
        if spec is not None:
            for s in range(self.slots):          # idempotent stops
                spec.draft.stop(s)

    @property
    def prefill_programs(self):
        """bucket -> compiled prefill program (compile-cache pin)."""
        return dict(self._prefills)

    def _prompt_bucket(self, n):
        for b in self.prompt_buckets:
            if b >= n:
                return b
        return self.prompt_buckets[-1]

    def _admit(self, req, slot):
        """Prefill `req`'s prompt and install it into `slot`."""
        import jax.numpy as jnp
        tr = self._tracer
        if tr.enabled:
            # queue wait ends at ADMISSION here (a decode request's
            # "batch formation" is winning a slot)
            t0 = int(req.t_submit * 1e9)
            tr.emit("serve.queue_wait", t0, time.monotonic_ns() - t0,
                    cat="serve", track=f"req-{req.req_id}",
                    trace_id=req.req_id)
        bucket = self._prompt_bucket(len(req.prompt))
        prog = self._prefills.get(bucket)
        if prog is None:
            prog = self._prefills[bucket] = self._make_prefill()
            log.info("compiled prefill program for prompt bucket %d",
                     bucket)
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :len(req.prompt)] = req.prompt
        with self._swap_lock:       # version index + params read atomically
            vidx = len(self._versions) - 1
            aux, blocks = self._versions[vidx]

        def dispatch():
            if self._injector is not None:
                self._injector.fire("serve.batch")
            return prog(aux, blocks, jnp.asarray(padded),
                        jnp.asarray(len(req.prompt), jnp.int32))

        with self._tracer.span("decode.prefill", cat="serve",
                               track="server", trace_id=req.req_id,
                               bucket=bucket, slot=slot):
            if self._retry is not None:
                logits, rows = self._retry.call(
                    dispatch,
                    on_retry=lambda a, e, d: self.metrics.count("retries"))
            else:
                logits, rows = dispatch()
        first = int(np.argmax(np.asarray(logits)[0]))
        req.generated.append(first)
        # TTFT closes HERE: prefill's argmax IS the first generated
        # token, whether or not the request goes on to occupy a slot
        req.t_last_tok = time.monotonic()
        self.metrics.record_ttft((req.t_last_tok - req.t_submit) * 1e3)
        if len(req.generated) >= req.max_new:
            # one-token request: done at prefill, never occupies a slot
            self._complete(req, time.monotonic())
            return
        self._cache = self._install(self._cache, rows, slot)
        self._pos = self._pos.at[slot].set(len(req.prompt))
        self._tok = self._tok.at[slot].set(first)
        req.slot = slot
        req.version = vidx
        self._slot_req[slot] = req
        if self._spec is not None:
            # draft stream keyed by slot: full context so far (slot reuse
            # is safe — start() resets the key, _free_slot stops it)
            self._spec.draft.start(slot, list(req.prompt) + req.generated)

    def _admit_pending(self, timeout=0.0):
        """Fill free slots from the queue. `timeout` blocks on the FIRST
        get only — the idle loop's way of waiting for work on the queue
        itself instead of busy-polling at the 1 ms decode tick."""
        if not self._running and not self._drain_on_stop:
            return      # fail-fast stop: queued requests must NOT be
            #             admitted into freed slots — the loop's final
            #             drain fails them once the busy slots finish
        free = [s for s in range(self.slots) if self._slot_req[s] is None]
        if self._static and len(free) < self.slots:
            return      # gang scheduling: wait for the whole batch
        wait = float(timeout)
        for s in free:
            req = None
            while req is None:
                try:
                    req = (self._q.get(timeout=wait) if wait
                           else self._q.get_nowait())
                except queue.Empty:
                    return
                wait = 0.0
                if req.future.done():   # failed by a raced submit/stop
                    req = None
                elif req.deadline is not None and \
                        time.monotonic() > req.deadline:
                    if _fail_future(req.future, DeadlineExceededError(
                            "deadline expired before prefill")):
                        self.metrics.count("shed_deadline")
                        self.metrics.record_slo_miss()
                    req = None
            try:
                self._admit(req, s)
            except BaseException as e:  # noqa: BLE001 — fail THIS request
                _fail_future(req.future, e)
                self.metrics.count("failed")

    def _free_slot(self, slot):
        """Release `slot`'s host-side occupancy (and its draft stream).
        Device rows/pos are left stale on purpose: the next admission
        resets pos and decode overwrites rows before attending (the
        dead-row contract)."""
        self._slot_req[slot] = None
        if self._spec is not None:
            self._spec.draft.stop(slot)

    def _evict_expired(self):
        """Mid-decode deadline enforcement: a slot whose request deadline
        has passed is evicted BETWEEN iterations — future fails with
        DeadlineExceededError, the shed is counted, and the slot frees
        THIS iteration (the following `_admit_pending` can refill it).
        Admission-time shedding (`_admit_pending`) only protects requests
        that expire in the queue; this protects the slots themselves from
        requests whose token budget outlives their latency budget."""
        now = time.monotonic()
        evicted = False
        for s, r in enumerate(self._slot_req):
            if r is None or r.deadline is None or now <= r.deadline:
                continue
            if _fail_future(r.future, DeadlineExceededError(
                    f"deadline expired mid-decode after "
                    f"{len(r.generated)} tokens")):
                self.metrics.count("shed_deadline")
                self.metrics.count("evicted_mid_decode")
                self.metrics.record_slo_miss()
            self._free_slot(s)
            evicted = True
        if evicted:
            self._gc_versions()

    def _decode_iteration(self):
        """One scheduling iteration for every occupied slot: one dispatch
        per live param version, active mask restricted to that version's
        slots. Plain mode advances every slot exactly one token;
        speculative mode (`speculate=`) advances each slot 1..K tokens
        per dispatch (per-slot positions already support ragged
        advance)."""
        import jax.numpy as jnp
        live = [(s, r) for s, r in enumerate(self._slot_req)
                if r is not None]
        if not live:
            return False
        if self._spec is not None:
            return self._spec_iteration(live)
        tr = self._tracer
        t_iter0 = time.monotonic_ns() if tr.enabled else None
        self.metrics.record_occupancy(len(live), self.slots)
        versions = sorted({r.version for _, r in live})
        new_tok = {}
        for v in versions:
            active = np.zeros((self.slots,), bool)
            for s, r in live:
                if r.version == v:
                    active[s] = True
            aux, blocks = self._versions[v]

            def dispatch():
                if self._injector is not None:
                    self._injector.fire("serve.batch")
                return self._step(aux, blocks, self._cache, self._pos,
                                  self._tok, jnp.asarray(active))

            # NOTE on retry composition: cache/pos are donated, so a
            # failure INSIDE the compiled call is not retryable at this
            # level (the buffers are gone) — the injector site sits before
            # the call, which is exactly the transient class (tunnel
            # hiccup before dispatch) retries exist for.
            with tr.span("decode.dispatch", cat="serve", track="server",
                         version=v):
                if self._retry is not None:
                    nxt, _, self._cache, self._pos = self._retry.call(
                        dispatch,
                        on_retry=lambda a, e, d: self.metrics.count(
                            "retries"))
                else:
                    nxt, _, self._cache, self._pos = dispatch()
            self.metrics.count("dispatches")
            nxt = np.asarray(nxt)
            for s, r in live:
                if r.version == v:
                    new_tok[s] = int(nxt[s])
        self._tok = jnp.asarray(
            [new_tok.get(s, 0) for s in range(self.slots)], jnp.int32)
        self.metrics.count("tokens_out", len(live))
        done_any = False
        t_now = time.monotonic()
        for s, r in live:
            r.generated.append(new_tok[s])
            # one inter-token sample per decode iteration per slot
            if r.t_last_tok is not None:
                self.metrics.record_inter_token(
                    (t_now - r.t_last_tok) * 1e3)
            r.t_last_tok = t_now
            if len(r.generated) >= r.max_new:
                # the final token needs no decode step (generate() makes
                # the same point): resolve and free the slot
                r.generated = r.generated[:r.max_new]
                self._complete(r, t_now)
                self._free_slot(s)
                done_any = True
        if t_iter0 is not None:
            # one span per scheduling iteration, tagged with the two
            # numbers head-of-line surgery needs: how full the machine
            # was and how many tokens the iteration produced
            tr.emit("decode.iteration", t_iter0,
                    time.monotonic_ns() - t_iter0, cat="serve",
                    track="server",
                    args={"slot_occupancy": len(live) / self.slots,
                          "accepted": len(live)})
        if done_any:
            self._gc_versions()
        self._after_iteration()
        return True

    def _spec_iteration(self, live):
        """One SPECULATIVE iteration: per live version, gather each
        slot's draft (K-1 tokens, zero-padded — padding costs acceptance,
        never correctness), run ONE K-wide verify dispatch, and advance
        each slot by its accepted count (matched prefix + bonus). The
        emitted stream is the verify program's own greedy argmax chain —
        acceptance only decides the dispatch count; bit-identity with
        the plain step's stream is pinned by test (cross-width argmax
        parity, speculate.py). Draft and verify are both evaluated
        under the slot's pinned param version (`r.version`); the draft
        source itself needs no pinning because a mismatched draft cannot
        alter accepted tokens."""
        import jax.numpy as jnp
        tr = self._tracer
        t_iter0 = time.monotonic_ns() if tr.enabled else None
        n_accepted = 0
        self.metrics.record_occupancy(len(live), self.slots)
        K = self._spec.k
        draft = self._spec.draft
        d0 = getattr(draft, "dispatch_count", 0)   # ModelDraft device cost
        versions = sorted({r.version for _, r in live})
        done_any = False
        for v in versions:
            live_v = [(s, r) for s, r in live if r.version == v]
            active = np.zeros((self.slots,), bool)
            toks = np.zeros((self.slots, K), np.int32)
            n_dr = {}
            for s, r in live_v:
                active[s] = True
                # never request drafts past the request's remaining token
                # budget: a ModelDraft would pay real dispatches for
                # tokens that can never be accepted, and the acceptance
                # reservoir would log them as misses
                n_want = r.max_new - len(r.generated)
                dr = list(draft.propose(
                    s, min(K - 1, n_want - 1)))[:K - 1]
                n_dr[s] = len(dr)
                toks[s, :1 + len(dr)] = [r.generated[-1]] + dr
            aux, blocks = self._versions[v]

            def dispatch():
                if self._injector is not None:
                    self._injector.fire("serve.batch")
                return self._verify(aux, blocks, self._cache, self._pos,
                                    jnp.asarray(toks), jnp.asarray(active))

            # same donated-buffer retry contract as the plain step: the
            # injector site sits BEFORE the compiled call (the transient
            # tunnel-hiccup class); a failure inside it is terminal here
            with tr.span("decode.verify", cat="serve", track="server",
                         version=v, k=K):
                if self._retry is not None:
                    nxt, n_acc, _, self._cache, self._pos = \
                        self._retry.call(
                            dispatch,
                            on_retry=lambda a, e, d: self.metrics.count(
                                "retries"))
                else:
                    nxt, n_acc, _, self._cache, self._pos = dispatch()
            self.metrics.count("dispatches")
            nxt = np.asarray(nxt)
            n_acc = np.asarray(n_acc)
            t_now = time.monotonic()
            for s, r in live_v:
                want = r.max_new - len(r.generated)
                take = min(int(n_acc[s]) + 1, want)
                acc = [int(t) for t in nxt[s, :take]]
                r.generated.extend(acc)
                # a speculative iteration lands `take` tokens at once:
                # record the PER-TOKEN stream rate (delta / take), one
                # sample per iteration per slot like the plain step
                if take and r.t_last_tok is not None:
                    self.metrics.record_inter_token(
                        (t_now - r.t_last_tok) * 1e3 / take)
                r.t_last_tok = t_now
                n_accepted += take
                self.metrics.count("tokens_out", take)
                # drafted = REAL draft tokens (zero-padding is not a
                # draft); matched likewise capped — a pad that happens to
                # equal the argmax is accepted (it IS the argmax) but
                # credits luck, not the draft
                self.metrics.record_speculation(
                    take, n_dr[s], min(int(n_acc[s]), take, n_dr[s]))
                if len(r.generated) >= r.max_new:
                    self._complete(r, t_now)
                    self._free_slot(s)
                    done_any = True
                else:
                    draft.observe(s, acc)
        dd = getattr(draft, "dispatch_count", 0) - d0
        if dd:
            # a ModelDraft pays real device dispatches for its proposals;
            # count them so dispatch amortization stays honest (NGramDraft
            # never moves this — host-only)
            self.metrics.count("draft_dispatches", dd)
        if t_iter0 is not None:
            tr.emit("decode.iteration", t_iter0,
                    time.monotonic_ns() - t_iter0, cat="serve",
                    track="server",
                    args={"slot_occupancy": len(live) / self.slots,
                          "accepted": n_accepted,
                          "draft_dispatches": dd})
        if done_any:
            self._gc_versions()
        self._after_iteration()
        return True

    def _after_iteration(self):
        self.metrics.count("batches")       # decode iterations
        if self._reporter is not None and \
                self.metrics.count_value("batches") % self._report_every \
                == 0:
            self._reporter.report(self.metrics.snapshot())

    def _gc_versions(self):
        """Drop drained old param versions (keep indices stable: only a
        fully-drained PREFIX below the newest can be released)."""
        with self._swap_lock:
            in_use = {r.version for r in self._slot_req if r is not None}
            newest = len(self._versions) - 1
            for v in range(newest):
                if v not in in_use and self._versions[v] is not None:
                    self._versions[v] = None

    def _busy(self):
        return any(r is not None for r in self._slot_req)

    def _loop_once(self):
        # evict deadline-expired slots FIRST so the admit below can refill
        # them in the same iteration
        self._evict_expired()
        # idle (no slot occupied): block on the queue up to 50 ms instead
        # of spinning at the decode tick; busy: drain the queue non-blocking
        self._admit_pending(timeout=0.0 if self._busy() else 0.05)
        try:
            busy = self._decode_iteration()
        except BaseException as e:  # noqa: BLE001 — fail slots, survive
            # a decode dispatch failed terminally (non-retryable, or
            # retries exhausted). The donated cache/pos buffers cannot be
            # trusted after a failed call, so every occupied request
            # fails LOUDLY and the slot state resets — the server keeps
            # serving instead of stranding all future requests on a dead
            # thread.
            n_failed = 0
            for r in self._slot_req:
                if r is not None and _fail_future(r.future, e):
                    n_failed += 1
            if n_failed:
                self.metrics.count("failed", n_failed)
            self._reset_device_state()
            self._gc_versions()
            return
        if not busy:
            # idle: still GC param versions (repeated swaps on an idle
            # server must not accumulate dead params); the next loop's
            # blocking admit is the idle wait, no sleep needed
            self._gc_versions()
