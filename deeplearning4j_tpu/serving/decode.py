"""Continuous-batching KV-cache decode scheduler (Orca, OSDI'22).

Static request batching decodes a gang of requests until the LAST one
finishes: a 5-token reply waits for the 200-token reply it shares a batch
with, and its slot emits padding the whole time. Iteration-level
("continuous") batching reschedules at TOKEN granularity instead — a
fixed-slot decode program (`models.zoo.transformer.make_slot_decode_fn`)
runs one token for every occupied slot per dispatch, and requests join or
leave slots BETWEEN dispatches. Prefill and decode are separated: a
joining request's prompt runs through a per-prompt-length-bucket prefill
program (`make_prefill_fn`) whose cache rows are scattered into the free
slot, then the request rides the shared decode program.

Determinism pin (tests/test_serving.py): a request's token stream is
bit-identical whether it decodes alone or joins a running batch — every
slot's row math touches only its own cache/pos/token rows, and inactive
slots' cache writes are gated. So continuous batching is a pure
throughput lever, not an accuracy trade.

Hot swap keeps MULTIPLE param versions live while draining (one per
undrained swap, typically two): slots keep the version they started with
(a compiled program takes params as arguments, so versions share ONE
executable), each iteration dispatches once per live version with the
active mask restricted to that version's slots, and new requests route
to the newest version immediately — zero admission stall, zero dropped
in-flight requests. Drained versions are released on request completion
AND on idle iterations, so repeated swaps never accumulate dead params.
"""
from __future__ import annotations

import concurrent.futures as cf
import logging
import queue
import threading
import time

import numpy as np

from .server import (DeadlineExceededError, ServerClosedError,
                     _RequestLoop)

log = logging.getLogger(__name__)


class _DecodeRequest:
    __slots__ = ("prompt", "max_new", "future", "deadline", "t_submit",
                 "generated", "slot", "version")

    def __init__(self, prompt, max_new, deadline):
        self.prompt = prompt
        self.max_new = int(max_new)
        self.future = cf.Future()
        self.deadline = deadline
        self.t_submit = time.monotonic()
        self.generated = []
        self.slot = None
        self.version = None


class ContinuousDecodeServer(_RequestLoop):
    """Token-granularity serving endpoint over a TransformerLM.

    `submit(prompt, max_new_tokens)` returns a Future resolving to the
    full token list (prompt + generated, greedy decode — the
    `generate_batch` contract). `static_batching=True` degrades scheduling
    to gang admission (a new batch only forms when every slot is free) —
    the A/B baseline `tools/serve_ab.py` measures against, through the
    exact same machinery.
    """

    _thread_name = "continuous-decode"
    _default_stop_timeout = 60.0

    def __init__(self, lm, slots=4, prompt_buckets=(8, 16, 32),
                 max_queue=64, fault_injector=None, retry_policy=None,
                 metrics=None, stats_reporter=None, report_every=64,
                 static_batching=False):
        from ..models.zoo.transformer import (make_prefill_fn,
                                              make_slot_decode_fn)
        import jax

        self.lm = lm
        self.slots = int(slots)
        self.max_len = int(lm.aux["pos"].shape[0])
        self.prompt_buckets = tuple(sorted(int(b) for b in prompt_buckets))
        if self.prompt_buckets[-1] > self.max_len:
            raise ValueError(f"largest prompt bucket "
                             f"{self.prompt_buckets[-1]} > model max_len "
                             f"{self.max_len}")
        self._injector = fault_injector
        self._retry = retry_policy
        from .metrics import ServingMetrics
        self.metrics = metrics or ServingMetrics()
        self._reporter = stats_reporter
        self._report_every = max(1, int(report_every))
        self._static = bool(static_batching)

        n_heads = lm.n_heads
        self._n_heads = n_heads
        self._d_model = int(lm.aux["tok"].shape[1])
        self._cache_dtype = lm.aux["tok"].dtype
        self._n_layers = len(lm.blocks)
        self._versions = [(lm.aux, lm.blocks)]   # index = param version
        self._reset_device_state()
        # ONE decode program for the life of the server (fixed slot count;
        # params are arguments, so hot swap reuses it). Cache and pos are
        # donated — they are THE device state, rebound every iteration.
        self._step = jax.jit(make_slot_decode_fn(n_heads),
                             donate_argnums=(2, 3))
        self._prefills = {}                      # bucket -> jitted program
        self._make_prefill = lambda: jax.jit(make_prefill_fn(
            n_heads, self.max_len))

        def install(cache, rows, s):
            return [{"k": c["k"].at[s].set(r["k"][0]),
                     "v": c["v"].at[s].set(r["v"][0])}
                    for c, r in zip(cache, rows)]
        # only the cache is donated: its buffers alias the output exactly,
        # while the [1, L, H, hd] prefill rows never could
        self._install = jax.jit(install, donate_argnums=(0,))

        self._swap_lock = threading.Lock()
        self._init_loop(max_queue)

    # -- client API ----------------------------------------------------
    def submit(self, prompt, max_new_tokens, deadline_ms=None):
        """Enqueue one decode request; Future resolves to the full token
        list (prompt + `max_new_tokens` greedy continuations)."""
        if not self._running:
            raise ServerClosedError("server is not running")
        prompt = [int(t) for t in np.asarray(prompt).ravel()]
        if not prompt:
            raise ValueError("prompt must contain at least one token")
        if int(max_new_tokens) < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}")
        if len(prompt) > self.prompt_buckets[-1]:
            raise ValueError(f"prompt length {len(prompt)} exceeds the "
                             f"largest bucket {self.prompt_buckets[-1]}")
        if len(prompt) + int(max_new_tokens) > self.max_len:
            raise ValueError(
                f"prompt+new tokens ({len(prompt)}+{max_new_tokens}) "
                f"exceed max_len {self.max_len}")
        if self._injector is not None:
            self._injector.fire("serve.request")
        self.metrics.count("received")
        dl = (time.monotonic() + deadline_ms / 1e3
              if deadline_ms is not None else None)
        return self._enqueue(_DecodeRequest(prompt, max_new_tokens, dl))

    def generate(self, prompt, max_new_tokens, deadline_ms=None,
                 timeout=None):
        """Blocking convenience wrapper over submit()."""
        return self.submit(prompt, max_new_tokens,
                           deadline_ms=deadline_ms).result(timeout)

    # -- hot swap ------------------------------------------------------
    def swap(self, new_lm):
        """Route NEW requests to `new_lm`'s params while slots already
        decoding drain on the version they started with (dual-version
        dispatch — module docstring). Structure/shape mismatch raises."""
        import jax
        with self._swap_lock:
            if self._injector is not None:
                self._injector.fire("serve.swap")
            new = (new_lm.aux, new_lm.blocks)
            old_l, old_t = jax.tree_util.tree_flatten(self._versions[-1])
            new_l, new_t = jax.tree_util.tree_flatten(new)
            if old_t != new_t:
                raise ValueError("swap rejected: param tree structure "
                                 "differs from the serving model")
            for o, n in zip(old_l, new_l):
                if o.shape != n.shape or o.dtype != n.dtype:
                    raise ValueError(f"swap rejected: leaf mismatch "
                                     f"{n.shape}/{n.dtype} vs serving "
                                     f"{o.shape}/{o.dtype}")
            self._versions.append(new)
            self.metrics.count("swaps")

    # -- scheduler internals -------------------------------------------
    def _reset_device_state(self):
        """Fresh slot state: the KV cache, per-slot positions/tokens, and
        host-side occupancy. Called at construction and after a decode
        dispatch fails terminally (the donated cache/pos buffers may have
        been consumed by the failed call — they cannot be trusted)."""
        import jax.numpy as jnp

        from ..models.zoo.transformer import init_kv_cache
        self._cache = init_kv_cache(self._n_layers, self.slots,
                                    self.max_len, self._d_model,
                                    self._n_heads, self._cache_dtype)
        self._pos = jnp.zeros((self.slots,), jnp.int32)
        self._tok = jnp.zeros((self.slots,), jnp.int32)
        self._slot_req = [None] * self.slots     # host-side occupancy

    @property
    def prefill_programs(self):
        """bucket -> compiled prefill program (compile-cache pin)."""
        return dict(self._prefills)

    def _prompt_bucket(self, n):
        for b in self.prompt_buckets:
            if b >= n:
                return b
        return self.prompt_buckets[-1]

    def _admit(self, req, slot):
        """Prefill `req`'s prompt and install it into `slot`."""
        import jax.numpy as jnp
        bucket = self._prompt_bucket(len(req.prompt))
        prog = self._prefills.get(bucket)
        if prog is None:
            prog = self._prefills[bucket] = self._make_prefill()
            log.info("compiled prefill program for prompt bucket %d",
                     bucket)
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :len(req.prompt)] = req.prompt
        with self._swap_lock:       # version index + params read atomically
            vidx = len(self._versions) - 1
            aux, blocks = self._versions[vidx]

        def dispatch():
            if self._injector is not None:
                self._injector.fire("serve.batch")
            return prog(aux, blocks, jnp.asarray(padded),
                        jnp.asarray(len(req.prompt), jnp.int32))

        if self._retry is not None:
            logits, rows = self._retry.call(
                dispatch,
                on_retry=lambda a, e, d: self.metrics.count("retries"))
        else:
            logits, rows = dispatch()
        first = int(np.argmax(np.asarray(logits)[0]))
        req.generated.append(first)
        if len(req.generated) >= req.max_new:
            # one-token request: done at prefill, never occupies a slot
            req.future.set_result(list(req.prompt) + req.generated)
            self.metrics.record_request(
                (time.monotonic() - req.t_submit) * 1e3)
            return
        self._cache = self._install(self._cache, rows, slot)
        self._pos = self._pos.at[slot].set(len(req.prompt))
        self._tok = self._tok.at[slot].set(first)
        req.slot = slot
        req.version = vidx
        self._slot_req[slot] = req

    def _admit_pending(self, timeout=0.0):
        """Fill free slots from the queue. `timeout` blocks on the FIRST
        get only — the idle loop's way of waiting for work on the queue
        itself instead of busy-polling at the 1 ms decode tick."""
        if not self._running and not self._drain_on_stop:
            return      # fail-fast stop: queued requests must NOT be
            #             admitted into freed slots — the loop's final
            #             drain fails them once the busy slots finish
        free = [s for s in range(self.slots) if self._slot_req[s] is None]
        if self._static and len(free) < self.slots:
            return      # gang scheduling: wait for the whole batch
        wait = float(timeout)
        for s in free:
            req = None
            while req is None:
                try:
                    req = (self._q.get(timeout=wait) if wait
                           else self._q.get_nowait())
                except queue.Empty:
                    return
                wait = 0.0
                if req.future.done():   # failed by a raced submit/stop
                    req = None
                elif req.deadline is not None and \
                        time.monotonic() > req.deadline:
                    req.future.set_exception(DeadlineExceededError(
                        "deadline expired before prefill"))
                    self.metrics.count("shed_deadline")
                    req = None
            try:
                self._admit(req, s)
            except BaseException as e:  # noqa: BLE001 — fail THIS request
                req.future.set_exception(e)
                self.metrics.count("failed")

    def _decode_iteration(self):
        """One token for every occupied slot: one dispatch per live param
        version, active mask restricted to that version's slots."""
        import jax.numpy as jnp
        live = [(s, r) for s, r in enumerate(self._slot_req)
                if r is not None]
        if not live:
            return False
        self.metrics.record_occupancy(len(live), self.slots)
        versions = sorted({r.version for _, r in live})
        new_tok = {}
        for v in versions:
            active = np.zeros((self.slots,), bool)
            for s, r in live:
                if r.version == v:
                    active[s] = True
            aux, blocks = self._versions[v]

            def dispatch():
                if self._injector is not None:
                    self._injector.fire("serve.batch")
                return self._step(aux, blocks, self._cache, self._pos,
                                  self._tok, jnp.asarray(active))

            # NOTE on retry composition: cache/pos are donated, so a
            # failure INSIDE the compiled call is not retryable at this
            # level (the buffers are gone) — the injector site sits before
            # the call, which is exactly the transient class (tunnel
            # hiccup before dispatch) retries exist for.
            if self._retry is not None:
                nxt, _, self._cache, self._pos = self._retry.call(
                    dispatch,
                    on_retry=lambda a, e, d: self.metrics.count("retries"))
            else:
                nxt, _, self._cache, self._pos = dispatch()
            nxt = np.asarray(nxt)
            for s, r in live:
                if r.version == v:
                    new_tok[s] = int(nxt[s])
        self._tok = jnp.asarray(
            [new_tok.get(s, 0) for s in range(self.slots)], jnp.int32)
        done_any = False
        t_now = time.monotonic()
        for s, r in live:
            r.generated.append(new_tok[s])
            if len(r.generated) >= r.max_new:
                # the final token needs no decode step (generate() makes
                # the same point): resolve and free the slot
                r.generated = r.generated[:r.max_new]
                r.future.set_result(list(r.prompt) + r.generated)
                self.metrics.record_request((t_now - r.t_submit) * 1e3)
                self._slot_req[s] = None
                done_any = True
        if done_any:
            self._gc_versions()
        self.metrics.count("batches")       # decode iterations
        if self._reporter is not None and \
                self.metrics.count_value("batches") % self._report_every \
                == 0:
            self._reporter.report(self.metrics.snapshot())
        return True

    def _gc_versions(self):
        """Drop drained old param versions (keep indices stable: only a
        fully-drained PREFIX below the newest can be released)."""
        with self._swap_lock:
            in_use = {r.version for r in self._slot_req if r is not None}
            newest = len(self._versions) - 1
            for v in range(newest):
                if v not in in_use and self._versions[v] is not None:
                    self._versions[v] = None

    def _busy(self):
        return any(r is not None for r in self._slot_req)

    def _loop_once(self):
        # idle (no slot occupied): block on the queue up to 50 ms instead
        # of spinning at the decode tick; busy: drain the queue non-blocking
        self._admit_pending(timeout=0.0 if self._busy() else 0.05)
        try:
            busy = self._decode_iteration()
        except BaseException as e:  # noqa: BLE001 — fail slots, survive
            # a decode dispatch failed terminally (non-retryable, or
            # retries exhausted). The donated cache/pos buffers cannot be
            # trusted after a failed call, so every occupied request
            # fails LOUDLY and the slot state resets — the server keeps
            # serving instead of stranding all future requests on a dead
            # thread.
            n_failed = 0
            for r in self._slot_req:
                if r is not None and not r.future.done():
                    r.future.set_exception(e)
                    n_failed += 1
            if n_failed:
                self.metrics.count("failed", n_failed)
            self._reset_device_state()
            self._gc_versions()
            return
        if not busy:
            # idle: still GC param versions (repeated swaps on an idle
            # server must not accumulate dead params); the next loop's
            # blocking admit is the idle wait, no sleep needed
            self._gc_versions()
