"""deeplearning4j_tpu — a TPU-native deep learning framework.

A from-scratch JAX/XLA/Pallas re-design with the capabilities of
deeplearning4j (reference: OkSerIous/deeplearning4j @ 0.6.1-SNAPSHOT):
layer-based networks (MultiLayerNetwork), DAG networks (ComputationGraph),
configuration DSL with JSON round-trip, data-parallel + sharded training over
TPU meshes, embedding models (Word2Vec family), Keras import, evaluation,
early stopping, checkpointing, and a training UI.

Execution model: whole training steps compile to single XLA programs
(forward + autodiff backward + optimizer, buffers donated); multi-chip
scaling uses jax.sharding.Mesh + XLA collectives over ICI rather than the
reference's parameter-averaging threads / Spark / Aeron parameter server.
"""

__version__ = "0.1.0"

from .nn.conf.computation_graph_configuration import \
    ComputationGraphConfiguration
from .nn.conf.input_type import InputType
from .nn.conf.neural_net_configuration import (MultiLayerConfiguration,
                                               NeuralNetConfiguration)
from .nn.graph import ComputationGraph
from .nn.multilayer import MultiLayerNetwork

__all__ = [
    "ComputationGraph",
    "ComputationGraphConfiguration",
    "InputType",
    "MultiLayerConfiguration",
    "NeuralNetConfiguration",
    "MultiLayerNetwork",
]
