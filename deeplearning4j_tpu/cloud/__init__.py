from .object_store import (GCSObjectStore, LocalFSObjectStore, ObjectStore,
                           ObjectStoreDataSetIterator, S3ObjectStore)
from .provision import (ClusterProvisioner, ClusterSpec, CommandRunner,
                        LocalCommandRunner, SSHCommandRunner,
                        create_instances_command)

__all__ = ["ClusterProvisioner", "ClusterSpec", "CommandRunner",
           "GCSObjectStore", "LocalCommandRunner", "LocalFSObjectStore",
           "ObjectStore", "ObjectStoreDataSetIterator", "S3ObjectStore",
           "SSHCommandRunner", "create_instances_command"]
