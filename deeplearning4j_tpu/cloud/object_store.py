"""Object storage for dataset staging.

TPU-native equivalent of reference deeplearning4j-aws's S3 layer
(aws/s3/reader/S3Downloader.java, uploader/S3Uploader.java,
BaseS3DataSetIterator.java): an ObjectStore SPI with
- LocalFSObjectStore: directory-backed store (test/offline backend, and the
  natural backend for NFS/persistent-disk TPU pods),
- S3ObjectStore / GCSObjectStore: import-gated real backends (boto3 /
  google-cloud-storage are not baked into this image; constructing without
  them raises with instructions),
plus ObjectStoreDataSetIterator streaming serialized DataSets straight out
of a store prefix (the BaseS3DataSetIterator role).
"""
from __future__ import annotations

import os


class ObjectStore:
    def put(self, key, data: bytes):
        raise NotImplementedError

    def get(self, key) -> bytes:
        raise NotImplementedError

    def list_keys(self, prefix=""):
        raise NotImplementedError

    def delete(self, key):
        raise NotImplementedError

    # convenience file helpers (reference S3Uploader.upload / download)
    def upload_file(self, path, key):
        with open(path, "rb") as fh:
            self.put(key, fh.read())

    def download_file(self, key, path):
        data = self.get(key)
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        with open(path, "wb") as fh:
            fh.write(data)


class LocalFSObjectStore(ObjectStore):
    def __init__(self, root):
        self.root = str(root)
        os.makedirs(self.root, exist_ok=True)

    def _path(self, key):
        p = os.path.abspath(os.path.join(self.root, key))
        if not p.startswith(os.path.abspath(self.root) + os.sep):
            raise ValueError(f"key escapes store root: {key!r}")
        return p

    def put(self, key, data):
        p = self._path(key)
        os.makedirs(os.path.dirname(p), exist_ok=True)
        with open(p, "wb") as fh:
            fh.write(data)

    def get(self, key):
        with open(self._path(key), "rb") as fh:
            return fh.read()

    def list_keys(self, prefix=""):
        out = []
        for root, _dirs, names in os.walk(self.root):
            for n in names:
                rel = os.path.relpath(os.path.join(root, n), self.root)
                rel = rel.replace(os.sep, "/")
                if rel.startswith(prefix):
                    out.append(rel)
        return sorted(out)

    def delete(self, key):
        os.remove(self._path(key))


class S3ObjectStore(ObjectStore):
    """reference: aws/s3/ — boto3-backed; gated on the package."""

    def __init__(self, bucket, client=None):
        if client is None:
            try:
                import boto3
            except ImportError as e:
                raise ImportError(
                    "S3ObjectStore needs 'boto3'; install it or use "
                    "LocalFSObjectStore") from e
            client = boto3.client("s3")
        self.bucket = bucket
        self.client = client

    def put(self, key, data):
        self.client.put_object(Bucket=self.bucket, Key=key, Body=data)

    def get(self, key):
        return self.client.get_object(
            Bucket=self.bucket, Key=key)["Body"].read()

    def list_keys(self, prefix=""):
        out = []
        resp = self.client.list_objects_v2(Bucket=self.bucket, Prefix=prefix)
        for item in resp.get("Contents", []):
            out.append(item["Key"])
        return sorted(out)

    def delete(self, key):
        self.client.delete_object(Bucket=self.bucket, Key=key)


class GCSObjectStore(ObjectStore):
    """GCS variant (the natural store next to TPU pods); gated on
    google-cloud-storage."""

    def __init__(self, bucket, client=None):
        if client is None:
            try:
                from google.cloud import storage
            except ImportError as e:
                raise ImportError(
                    "GCSObjectStore needs 'google-cloud-storage'; install "
                    "it or use LocalFSObjectStore") from e
            client = storage.Client()
        self.bucket = client.bucket(bucket) if hasattr(client, "bucket") \
            else bucket
        self._client = client

    def put(self, key, data):
        self.bucket.blob(key).upload_from_string(data)

    def get(self, key):
        return self.bucket.blob(key).download_as_bytes()

    def list_keys(self, prefix=""):
        return sorted(b.name for b in self._client.list_blobs(
            self.bucket, prefix=prefix))

    def delete(self, key):
        self.bucket.blob(key).delete()


class ObjectStoreDataSetIterator:
    """Stream DataSets from serialized .npz objects under a store prefix.
    reference: aws/dataset/BaseS3DataSetIterator.java."""

    def __init__(self, store, prefix=""):
        self.store = store
        self.prefix = prefix
        self.keys = [k for k in store.list_keys(prefix)
                     if k.endswith(".npz")]
        self._pos = 0

    def has_next(self):
        return self._pos < len(self.keys)

    def next_batch(self):
        from ..streaming.serde import decode_dataset
        key = self.keys[self._pos]
        self._pos += 1
        return decode_dataset(self.store.get(key))

    def reset(self):
        self._pos = 0

    def __iter__(self):
        self.reset()
        while self.has_next():
            yield self.next_batch()
