"""Cluster provisioning over pluggable command transports.

TPU-native equivalent of reference deeplearning4j-aws's cluster setup
(aws/ec2/provision/ClusterSetup.java + HostProvisioner.java — create EC2
boxes, then run setup commands / copy files over SSH). The TPU analogue
provisions worker hosts for a multi-host jax.distributed job:

- CommandRunner SPI: LocalCommandRunner (subprocess; used by tests and for
  localhost setups) and SSHCommandRunner (shells out to the system `ssh`/
  `scp`, the HostProvisioner role — no paramiko in this image).
- ClusterSpec + ClusterProvisioner: run a setup script on every host and
  emit per-host launch commands carrying the jax.distributed coordinator
  address / process ids (the Spark-master/worker config the reference
  writes becomes coordinator env vars).

Actual accelerator-VM creation (the Ec2BoxCreator role) is cloud-CLI
specific and intentionally out of scope: `create_instances_command` renders
the gcloud command a TPU operator runs, rather than wrapping half of a
cloud SDK that isn't installed here.
"""
from __future__ import annotations

import shlex
import subprocess


class CommandRunner:
    def run(self, command, timeout=120):
        """Returns (returncode, stdout+stderr)."""
        raise NotImplementedError

    def copy_to(self, local_path, remote_path):
        raise NotImplementedError


class LocalCommandRunner(CommandRunner):
    """reference test pattern: provisioning logic exercised without real
    boxes (the HostProvisioner unit seam)."""

    def run(self, command, timeout=120):
        p = subprocess.run(command, shell=True, capture_output=True,
                           text=True, timeout=timeout)
        return p.returncode, p.stdout + p.stderr

    def copy_to(self, local_path, remote_path):
        import shutil
        shutil.copy(local_path, remote_path)


class SSHCommandRunner(CommandRunner):
    """reference: aws/ec2/provision/HostProvisioner.java (jsch SSH there;
    the system ssh/scp binaries here)."""

    def __init__(self, host, user=None, key_file=None, ssh_options=()):
        self.target = f"{user}@{host}" if user else host
        self.key_args = ["-i", key_file] if key_file else []
        self.extra = list(ssh_options)

    def run(self, command, timeout=120):
        cmd = (["ssh"] + self.key_args + self.extra
               + [self.target, command])
        p = subprocess.run(cmd, capture_output=True, text=True,
                           timeout=timeout)
        return p.returncode, p.stdout + p.stderr

    def copy_to(self, local_path, remote_path):
        cmd = (["scp"] + self.key_args + self.extra
               + [local_path, f"{self.target}:{remote_path}"])
        p = subprocess.run(cmd, capture_output=True, text=True, timeout=300)
        if p.returncode != 0:
            raise RuntimeError(f"scp failed: {p.stdout}{p.stderr}")


class ClusterSpec:
    """reference: the Ec2BoxCreator parameters, reshaped for TPU hosts."""

    def __init__(self, hosts, coordinator_port=8476, setup_commands=(),
                 env=None):
        self.hosts = list(hosts)
        self.coordinator_port = int(coordinator_port)
        self.setup_commands = list(setup_commands)
        self.env = dict(env or {})

    @property
    def coordinator_address(self):
        return f"{self.hosts[0]}:{self.coordinator_port}"

    def launch_env(self, process_id):
        """Per-host environment for a jax.distributed worker (what the
        reference's Spark master/worker config files carried)."""
        env = dict(self.env)
        env.update({
            "DL4J_TPU_COORDINATOR": self.coordinator_address,
            "DL4J_TPU_NUM_PROCESSES": str(len(self.hosts)),
            "DL4J_TPU_PROCESS_ID": str(process_id),
        })
        return env


class ClusterProvisioner:
    """reference: aws/ec2/provision/ClusterSetup.java — provision every
    host, then hand back launch commands."""

    def __init__(self, spec, runner_factory=None):
        self.spec = spec
        self.runner_factory = runner_factory or (
            lambda host: SSHCommandRunner(host))

    def provision(self):
        """Run setup_commands on every host; returns {host: [(rc, out)]}.
        Raises on the first failing command (a half-provisioned cluster is
        an error, matching the reference's fail-fast provisioning)."""
        results = {}
        for host in self.spec.hosts:
            runner = self.runner_factory(host)
            results[host] = []
            for cmd in self.spec.setup_commands:
                rc, out = runner.run(cmd)
                results[host].append((rc, out))
                if rc != 0:
                    raise RuntimeError(
                        f"provisioning {host} failed at {cmd!r}: {out}")
        return results

    def launch_commands(self, worker_command):
        """Per-host shell commands that start `worker_command` with the
        jax.distributed coordinator env applied."""
        out = []
        for pid, host in enumerate(self.spec.hosts):
            env = self.spec.launch_env(pid)
            prefix = " ".join(f"{k}={shlex.quote(v)}"
                              for k, v in sorted(env.items()))
            out.append((host, f"env {prefix} {worker_command}"))
        return out


def create_instances_command(name_prefix, zone, accelerator_type="v5e-8",
                             count=1, image_family="tpu-ubuntu2204-base"):
    """Render the gcloud command that creates TPU VM(s) — the Ec2BoxCreator
    role, rendered instead of executed (no cloud SDK/credentials here)."""
    cmds = []
    for i in range(count):
        cmds.append(
            f"gcloud compute tpus tpu-vm create {name_prefix}-{i} "
            f"--zone={zone} --accelerator-type={accelerator_type} "
            f"--version={image_family}")
    return cmds
