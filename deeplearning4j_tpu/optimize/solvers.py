"""Convex optimizers: LBFGS, ConjugateGradient, LineGradientDescent +
BackTrackLineSearch.

TPU-native equivalent of reference optimize/solvers/ (BaseOptimizer.java:51,
LBFGS.java, ConjugateGradient.java, LineGradientDescent.java,
BackTrackLineSearch.java). SGD is the production path and lives fused inside
the jitted train step (multilayer.py); these full-batch methods drive a
jitted score/gradient function over the flattened parameter vector from the
host — the classic second-order loop shapes don't fit one XLA program, but
every score/grad evaluation is compiled.

Selected via NeuralNetConfiguration.optimization_algo
("lbfgs" | "conjugate_gradient" | "line_gradient_descent"), mirroring
OptimizationAlgorithm (nn/api/OptimizationAlgorithm.java).
"""
from __future__ import annotations

import logging

import jax
import numpy as np

log = logging.getLogger(__name__)


class BackTrackLineSearch:
    """Armijo backtracking line search.
    reference: optimize/solvers/BackTrackLineSearch.java."""

    def __init__(self, score_fn, grad_fn, max_iterations=20, c1=1e-4,
                 rho=0.5, min_step=1e-12):
        self.score_fn = score_fn
        self.grad_fn = grad_fn
        self.max_iterations = int(max_iterations)
        self.c1 = float(c1)
        self.rho = float(rho)
        self.min_step = float(min_step)

    def optimize(self, x, direction, initial_step=1.0):
        """Returns (step, new_x, new_score)."""
        f0 = float(self.score_fn(x))
        g0 = np.asarray(self.grad_fn(x))
        slope = float(g0 @ direction)
        if slope >= 0:
            direction = -g0          # not a descent direction: reset
            slope = float(g0 @ direction)
        step = float(initial_step)
        while step > self.min_step:
            x_new = x + step * direction
            f_new = float(self.score_fn(x_new))
            if np.isfinite(f_new) and f_new <= f0 + self.c1 * step * slope:
                return step, x_new, f_new
            step *= self.rho
        return 0.0, x, f0


class _BaseFlatOptimizer:
    """Drives score/grad over the flattened parameter vector."""

    def __init__(self, net, features, labels, fmask=None, lmask=None,
                 max_iterations=100, tolerance=1e-8):
        self.net = net
        score_fn = net.make_flat_score_fn(features, labels, fmask, lmask,
                                          train=True)
        self.score_fn = score_fn
        self.grad_fn = jax.jit(jax.grad(
            lambda v: score_fn(v)))
        self.max_iterations = int(max_iterations)
        self.tolerance = float(tolerance)
        self.line_search = BackTrackLineSearch(self.score_fn, self.grad_fn)

    def optimize(self):
        raise NotImplementedError

    def _finish(self, x, score):
        self.net.set_params(np.asarray(x))
        self.net._score = float(score)
        return float(score)


class LineGradientDescent(_BaseFlatOptimizer):
    """Steepest descent + line search.
    reference: optimize/solvers/LineGradientDescent.java."""

    def optimize(self):
        x = self.net.params().astype(np.float64)
        score = float(self.score_fn(x))
        for _ in range(self.max_iterations):
            g = np.asarray(self.grad_fn(x), np.float64)
            step, x, new_score = self.line_search.optimize(x, -g)
            if step == 0.0 or abs(score - new_score) < self.tolerance:
                score = new_score
                break
            score = new_score
        return self._finish(x, score)


class ConjugateGradient(_BaseFlatOptimizer):
    """Nonlinear CG (Polak-Ribiere with restarts).
    reference: optimize/solvers/ConjugateGradient.java."""

    def optimize(self):
        x = self.net.params().astype(np.float64)
        g = np.asarray(self.grad_fn(x), np.float64)
        d = -g
        score = float(self.score_fn(x))
        for it in range(self.max_iterations):
            step, x, new_score = self.line_search.optimize(x, d)
            if step == 0.0 or abs(score - new_score) < self.tolerance:
                score = new_score
                break
            score = new_score
            g_new = np.asarray(self.grad_fn(x), np.float64)
            beta = float(g_new @ (g_new - g) / max(g @ g, 1e-300))
            beta = max(0.0, beta)      # PR+ restart
            d = -g_new + beta * d
            g = g_new
            if (it + 1) % x.size == 0:
                d = -g                 # periodic restart
        return self._finish(x, score)


class LBFGS(_BaseFlatOptimizer):
    """Limited-memory BFGS (two-loop recursion, history m).
    reference: optimize/solvers/LBFGS.java."""

    def __init__(self, *args, m=10, **kw):
        super().__init__(*args, **kw)
        self.m = int(m)

    def optimize(self):
        x = self.net.params().astype(np.float64)
        g = np.asarray(self.grad_fn(x), np.float64)
        score = float(self.score_fn(x))
        s_hist, y_hist = [], []
        for _ in range(self.max_iterations):
            # two-loop recursion for H*g
            q = g.copy()
            alphas = []
            for s, y in zip(reversed(s_hist), reversed(y_hist)):
                rho = 1.0 / max(y @ s, 1e-300)
                a = rho * (s @ q)
                alphas.append((a, rho, s, y))
                q -= a * y
            if y_hist:
                s, y = s_hist[-1], y_hist[-1]
                q *= (s @ y) / max(y @ y, 1e-300)
            for a, rho, s, y in reversed(alphas):
                b = rho * (y @ q)
                q += (a - b) * s
            d = -q
            step, x_new, new_score = self.line_search.optimize(x, d)
            if step == 0.0:
                # LBFGS direction rejected: drop history, retry steepest
                s_hist, y_hist = [], []
                step, x_new, new_score = self.line_search.optimize(x, -g)
            if step == 0.0 or abs(score - new_score) < self.tolerance:
                score = new_score
                x = x_new
                break
            g_new = np.asarray(self.grad_fn(x_new), np.float64)
            s, yv = x_new - x, g_new - g
            if s @ yv > 1e-10:          # keep only valid curvature pairs
                s_hist.append(s)
                y_hist.append(yv)
                if len(s_hist) > self.m:
                    s_hist.pop(0)
                    y_hist.pop(0)
            x, g, score = x_new, g_new, new_score
        return self._finish(x, score)


SOLVERS = {
    "lbfgs": LBFGS,
    "conjugate_gradient": ConjugateGradient,
    "line_gradient_descent": LineGradientDescent,
}


class Solver:
    """Facade dispatching on OptimizationAlgorithm.
    reference: optimize/Solver.java:41."""

    def __init__(self, net, algo=None, max_iterations=100):
        self.net = net
        self.algo = (algo or net.conf.global_conf.get(
            "optimization_algo", "stochastic_gradient_descent")).lower()
        self.max_iterations = max_iterations

    def optimize(self, features, labels, fmask=None, lmask=None):
        if self.algo in ("stochastic_gradient_descent", "sgd"):
            from ..datasets.dataset import DataSet
            return self.net.fit(DataSet(features, labels, fmask, lmask))
        cls = SOLVERS.get(self.algo)
        if cls is None:
            raise ValueError(f"Unknown optimization algorithm '{self.algo}'")
        opt = cls(self.net, features, labels, fmask, lmask,
                  max_iterations=self.max_iterations)
        return opt.optimize()
