"""XLA profiler integration — the "deep profiler" (SURVEY.md §5.1).

The reference's profiling story is wall-clock listeners (PerformanceListener,
BaseStatsListener timing, Spark phase timelines). On TPU the equivalent deep
tool is the XLA device trace: this module wraps `jax.profiler` so a trace can
be captured from bench.py or mid-training via a listener, and adds a
host-side summarizer that aggregates device-op time straight from the
captured `.xplane.pb` (so no TensorBoard UI is needed to see where a step's
time goes).

Usage:
    from deeplearning4j_tpu.optimize.profiler import trace, summarize_trace
    with trace("/tmp/prof"):
        net.fit(ds)
    for row in summarize_trace("/tmp/prof")[:20]:
        print(row)

or attach `ProfilerListener("/tmp/prof", start_iteration=5, num_iterations=3)`
to any model — it starts the trace when the start iteration is reached and
stops it `num_iterations` later (the reference pattern of sampling a steady-
state window, not the compile-heavy first steps).
"""
from __future__ import annotations

import contextlib
import glob
import os
from collections import defaultdict

import jax

from .listeners import IterationListener


@contextlib.contextmanager
def trace(logdir):
    """Capture an XLA device trace into `logdir` (TensorBoard-compatible)."""
    jax.profiler.start_trace(logdir)
    try:
        yield logdir
    finally:
        jax.profiler.stop_trace()


class ProfilerListener(IterationListener):
    """Trace a steady-state window of training iterations.

    reference role: PerformanceListener tells you *that* iterations are slow;
    this tells you *why* (per-op device time)."""

    def __init__(self, logdir, start_iteration=5, num_iterations=3):
        self.logdir = str(logdir)
        self.start_iteration = int(start_iteration)
        self.num_iterations = int(num_iterations)
        self._seen = 0
        self._active = False
        self.done = False

    def iteration_done(self, model, iteration):
        self._seen += 1
        if self.done:
            return
        if not self._active and self._seen >= self.start_iteration:
            jax.profiler.start_trace(self.logdir)
            self._active = True
            self._stop_at = self._seen + self.num_iterations
        elif self._active and self._seen >= self._stop_at:
            # barrier so the traced window contains completed device work
            jax.block_until_ready(model._params)
            jax.profiler.stop_trace()
            self._active = False
            self.done = True


def _find(logdir, pattern):
    return sorted(glob.glob(os.path.join(
        str(logdir), "**", pattern), recursive=True))


def _rows_from_totals(totals, counts):
    grand = sum(totals.values()) or 1.0
    rows = [{"name": k, "total_ms": round(v, 3), "count": counts[k],
             "pct": round(100.0 * v / grand, 2)}
            for k, v in totals.items()]
    rows.sort(key=lambda r: -r["total_ms"])
    return rows


def _merge_name(name, merge):
    # strip trailing ".NN" disambiguators so repeated fusions aggregate
    # ("fusion.123" -> "fusion")
    return name.split(".")[0] if (merge and name) else name


def summarize_trace(logdir, merge_fusion_names=True):
    """Aggregate per-op device time from the newest trace under `logdir`.

    Returns a list of dicts sorted by total device time descending:
    {"name", "total_ms", "count", "pct"}. Prefers the Chrome-trace JSON the
    profiler writes alongside the XPlane proto; falls back to parsing the
    raw `.xplane.pb` with TensorFlow's bundled schema. No TensorBoard server
    required either way.
    """
    jsons = _find(logdir, "*.trace.json.gz")
    if jsons:
        import gzip
        import json as _json
        with gzip.open(jsons[-1], "rt") as fh:
            data = _json.load(fh)
        events = data.get("traceEvents", [])
        # map pid -> process name to keep only device (TPU/GPU) op lanes
        pid_name = {}
        for ev in events:
            if ev.get("ph") == "M" and ev.get("name") == "process_name":
                pid_name[ev.get("pid")] = ev.get("args", {}).get("name", "")
        device_pids = {pid for pid, n in pid_name.items()
                       if ("TPU" in n or "GPU" in n) and "host" not in n.lower()}
        totals = defaultdict(float)
        counts = defaultdict(int)
        for ev in events:
            if ev.get("ph") != "X" or ev.get("pid") not in device_pids:
                continue
            name = _merge_name(ev.get("name", ""), merge_fusion_names)
            totals[name] += ev.get("dur", 0) / 1000.0  # us -> ms
            counts[name] += 1
        if totals:
            return _rows_from_totals(totals, counts)

    xplane_pb2 = None
    for mod in ("tensorflow.core.profiler.protobuf.xplane_pb2",
                "tensorflow.tsl.profiler.protobuf.xplane_pb2"):
        try:
            import importlib
            xplane_pb2 = importlib.import_module(mod)
            break
        except Exception:
            continue
    if xplane_pb2 is None:
        raise RuntimeError("no parsable trace found (no trace.json.gz with "
                           "device lanes, no xplane proto schema)")
    paths = _find(logdir, "*.xplane.pb")
    if not paths:
        raise FileNotFoundError(f"no .xplane.pb under {logdir}")
    xspace = xplane_pb2.XSpace()
    with open(paths[-1], "rb") as fh:
        xspace.ParseFromString(fh.read())
    totals = defaultdict(float)
    counts = defaultdict(int)
    for plane in xspace.planes:
        # device planes only; skip host python/thread planes
        if not ("TPU" in plane.name or "GPU" in plane.name
                or "device" in plane.name.lower()):
            continue
        if "host" in plane.name.lower():
            continue
        ev_meta = plane.event_metadata
        for line in plane.lines:
            for ev in line.events:
                meta = ev_meta.get(ev.metadata_id)
                name = _merge_name(meta.name if meta else str(ev.metadata_id),
                                   merge_fusion_names)
                totals[name] += ev.duration_ps / 1e9
                counts[name] += 1
    return _rows_from_totals(totals, counts)
