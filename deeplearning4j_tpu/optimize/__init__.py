from .listeners import (CollectScoresIterationListener,
                        ComposableIterationListener, IterationListener,
                        PerformanceListener, ScoreIterationListener,
                        TrainingListener)
from .solvers import (LBFGS, BackTrackLineSearch, ConjugateGradient,
                      LineGradientDescent, Solver)

__all__ = ["BackTrackLineSearch", "CollectScoresIterationListener",
           "ComposableIterationListener", "ConjugateGradient",
           "IterationListener", "LBFGS", "LineGradientDescent",
           "PerformanceListener", "ScoreIterationListener", "Solver",
           "TrainingListener"]
