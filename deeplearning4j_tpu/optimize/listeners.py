"""Training listeners.

TPU-native equivalent of reference optimize/api/IterationListener +
TrainingListener and the stock implementations in optimize/listeners/
(ScoreIterationListener, PerformanceListener, CollectScoresIterationListener,
ComposableIterationListener).

Listener hooks fire on host between jitted steps; score device->host sync is
deferred (jax async dispatch) unless a listener actually reads it.
"""
from __future__ import annotations

import logging
import time

log = logging.getLogger(__name__)


class IterationListener:
    """reference: optimize/api/IterationListener.java"""

    def iteration_done(self, model, iteration):
        pass


class TrainingListener(IterationListener):
    """reference: optimize/api/TrainingListener.java (epoch/forward/backward hooks)"""

    def on_epoch_start(self, model):
        pass

    def on_epoch_end(self, model):
        pass

    def on_forward_pass(self, model, activations):
        pass

    def on_gradient_calculation(self, model):
        pass

    def on_backward_pass(self, model):
        pass


class ScoreIterationListener(IterationListener):
    """Log score every N iterations (reference:
    optimize/listeners/ScoreIterationListener.java)."""

    def __init__(self, print_iterations=10):
        self.print_iterations = max(1, int(print_iterations))

    def iteration_done(self, model, iteration):
        if iteration % self.print_iterations == 0:
            log.info("Score at iteration %d is %s", iteration, float(model.score()))


class PerformanceListener(IterationListener):
    """Throughput instrumentation (reference:
    optimize/listeners/PerformanceListener.java — time/batch, samples/sec,
    batches/sec). This is the measurement instrument bench.py uses."""

    def __init__(self, frequency=1, report_score=False):
        self.frequency = max(1, int(frequency))
        self.report_score = report_score
        self.last_time = None
        self.samples_per_sec = 0.0
        self.batches_per_sec = 0.0
        self.history = []

    def iteration_done(self, model, iteration):
        now = time.perf_counter()
        if self.last_time is not None:
            dt = now - self.last_time
            batch_size = getattr(model, "_last_batch_size", 0)
            if dt > 0:
                self.samples_per_sec = batch_size / dt
                self.batches_per_sec = 1.0 / dt
                self.history.append((iteration, dt, self.samples_per_sec))
            if iteration % self.frequency == 0:
                msg = (f"iteration {iteration}; iteration time: {dt*1000:.2f} ms; "
                       f"samples/sec: {self.samples_per_sec:.2f}; "
                       f"batches/sec: {self.batches_per_sec:.2f}")
                if self.report_score:
                    msg += f"; score: {float(model.score())}"
                log.info(msg)
        self.last_time = now


class CollectScoresIterationListener(IterationListener):
    """reference: optimize/listeners/CollectScoresIterationListener.java"""

    def __init__(self, frequency=1):
        self.frequency = max(1, int(frequency))
        self.scores = []

    def iteration_done(self, model, iteration):
        if iteration % self.frequency == 0:
            self.scores.append((iteration, float(model.score())))


class ComposableIterationListener(IterationListener):
    """reference: optimize/listeners/ComposableIterationListener.java"""

    def __init__(self, *listeners):
        self.listeners = list(listeners)

    def iteration_done(self, model, iteration):
        for l in self.listeners:
            l.iteration_done(model, iteration)
