"""Mixture-of-Experts / expert-parallelism tests (8-device CPU mesh).

The reference has no MoE (SURVEY.md §2.5 EP: absent/optional); these pin the
TPU-first extension: the all_to_all dispatch == the dense reference exactly,
capacity overflow drops tokens (residual passthrough), and an expert-parallel
MoE transformer trains.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.parallel.moe import (init_moe, load_balance_loss,
                                             make_expert_mesh, moe_mlp_dense,
                                             moe_mlp_sharded,
                                             shard_moe_params)

pytestmark = pytest.mark.skipif(len(jax.devices()) < 8,
                                reason="needs 8 virtual devices")

D, E, F, B = 16, 8, 32, 64


def _setup(seed=0):
    params = init_moe(jax.random.PRNGKey(seed), D, E, F)
    x = jnp.asarray(np.random.default_rng(seed).standard_normal((B, D)),
                    jnp.float32)
    mesh = make_expert_mesh(8)
    return params, shard_moe_params(params, mesh), x, mesh


class TestExpertParallelDispatch:
    def test_matches_dense_reference(self):
        params, ps, x, mesh = _setup()
        y_ep, _ = jax.jit(moe_mlp_sharded(mesh))(ps, x)
        y_dense, _ = moe_mlp_dense(params, x)
        np.testing.assert_allclose(np.asarray(y_ep), np.asarray(y_dense),
                                   atol=1e-5)

    def test_aux_loss_matches_dense_reference(self):
        """The sharded aux loss pmean's f and P separately before forming
        E*sum(f*P), so it equals the dense global-batch loss exactly —
        pmean of per-shard losses would not (the product is nonlinear)."""
        params, ps, x, mesh = _setup(7)
        _, aux_ep = jax.jit(moe_mlp_sharded(mesh))(ps, x)
        _, aux_dense = moe_mlp_dense(params, x)
        np.testing.assert_allclose(float(aux_ep), float(aux_dense),
                                   rtol=1e-6)

    def test_top2_matches_dense_reference(self):
        """k=2 (GShard/Mixtral combine): each token ships to its two
        experts as token-major virtual dispatch units through the same
        all_to_all machinery; the gated sum == the dense k=2 reference,
        with and without capacity drops."""
        params, ps, x, mesh = _setup(3)
        y_ep, aux_ep = jax.jit(moe_mlp_sharded(mesh, k=2))(ps, x)
        y_dense, aux_dense = moe_mlp_dense(params, x, k=2)
        np.testing.assert_allclose(np.asarray(y_ep), np.asarray(y_dense),
                                   atol=1e-5)
        np.testing.assert_allclose(float(aux_ep), float(aux_dense),
                                   rtol=1e-6)
        y_c, _ = jax.jit(moe_mlp_sharded(mesh, capacity=3, k=2))(ps, x)
        y_dc, _ = moe_mlp_dense(params, x, capacity=3, n_shards=8, k=2)
        np.testing.assert_allclose(np.asarray(y_c), np.asarray(y_dc),
                                   atol=1e-5)
        # top-2 combine genuinely differs from top-1 (both experts used)
        y1, _ = moe_mlp_dense(params, x, k=1)
        assert not np.allclose(np.asarray(y_dense), np.asarray(y1),
                               atol=1e-4)

    def test_top2_gates_renormalized(self):
        """k=2 combine weights sum to 1 per token (k=1 keeps the raw
        Switch prob)."""
        from deeplearning4j_tpu.parallel.moe import _route_topk
        params, _, x, _ = _setup(5)
        _, g2, _ = _route_topk(params["gate"], x, 2)
        np.testing.assert_allclose(np.asarray(g2).sum(-1), 1.0, atol=1e-6)
        _, g1, probs = _route_topk(params["gate"], x, 1)
        assert (np.asarray(g1)[:, 0] < 1.0).all()
        np.testing.assert_allclose(np.asarray(g1)[:, 0],
                                   np.asarray(probs).max(-1), atol=1e-6)

    @pytest.mark.slow
    def test_dp_ep_composition_matches_dense(self):
        """dp x ep on a (data=2, expert=4) mesh: batch sharded over both
        axes, each data slice running its own expert all_to_all ring;
        equals the dense reference (aux pmean'd over both axes = the
        global-batch value), at k=1 and k=2, with capacity drops.
        Full tier: the driver's dryrun_multichip asserts the same dp x ep
        top-2 allclose-vs-dense every round, so core keeps only the
        single-axis EP pins."""
        from jax.sharding import Mesh
        mesh = Mesh(np.array(jax.devices()[:8]).reshape(2, 4),
                    ("data", "expert"))
        params = init_moe(jax.random.PRNGKey(4), D, 4, F)
        ps = shard_moe_params(params, mesh)     # gate replicated, experts
        # split over "expert" (implicitly replicated over "data")
        x = jnp.asarray(np.random.default_rng(4).standard_normal((B, D)),
                        jnp.float32)
        for k in (1, 2):
            y, aux = jax.jit(moe_mlp_sharded(mesh, k=k,
                                             data_axis="data"))(ps, x)
            yd, ad = moe_mlp_dense(params, x, k=k)
            np.testing.assert_allclose(np.asarray(y), np.asarray(yd),
                                       atol=1e-5)
            np.testing.assert_allclose(float(aux), float(ad), rtol=1e-6)
            yc, _ = jax.jit(moe_mlp_sharded(
                mesh, capacity=3, k=k, data_axis="data"))(ps, x)
            ydc, _ = moe_mlp_dense(params, x, capacity=3, n_shards=8, k=k)
            np.testing.assert_allclose(np.asarray(yc), np.asarray(ydc),
                                       atol=1e-5)

    def test_capacity_drops_to_residual_zero(self):
        """All-identical tokens route to one expert; capacity=1 keeps one
        token per source shard and zeroes the rest (Switch drop)."""
        params, ps, _, mesh = _setup()
        x = jnp.ones((B, D), jnp.float32)
        y, _ = jax.jit(moe_mlp_sharded(mesh, capacity=1))(ps, x)
        y = np.asarray(y)
        per_shard = y.reshape(8, B // 8, D)
        nonzero = (np.abs(per_shard).max(-1) > 0).sum(-1)
        assert (nonzero == 1).all(), nonzero

    def test_capacity_matches_dense_with_shard_ranking(self):
        """Dense reference with n_shards = mesh size reproduces the sharded
        drop pattern exactly."""
        params, ps, _, mesh = _setup(2)
        rng = np.random.default_rng(5)
        x = jnp.asarray(rng.standard_normal((B, D)), jnp.float32)
        cap = 2
        y_ep, _ = jax.jit(moe_mlp_sharded(mesh, capacity=cap))(ps, x)
        y_ref, _ = moe_mlp_dense(params, x, capacity=cap, n_shards=8)
        np.testing.assert_allclose(np.asarray(y_ep), np.asarray(y_ref),
                                   atol=1e-5)

    def test_grads_flow_and_finite(self):
        params, ps, x, mesh = _setup(1)
        apply_ep = moe_mlp_sharded(mesh)

        def loss(p, x):
            y, aux = apply_ep(p, x)
            return jnp.mean(y ** 2) + 0.01 * aux

        g = jax.jit(jax.grad(loss))(ps, x)
        for leaf in jax.tree.leaves(g):
            assert bool(jnp.isfinite(leaf).all())
        # expert grads stay sharded over the expert axis
        assert "expert" in tuple(g["w1"].sharding.spec)

    def test_load_balance_loss_uniform_is_one(self):
        probs = jnp.full((B, E), 1.0 / E)
        expert = jnp.arange(B) % E
        lb = load_balance_loss(probs, expert, E)
        np.testing.assert_allclose(float(lb), 1.0, atol=1e-6)


class TestMoETransformer:
    @pytest.mark.slow
    def test_ep_moe_transformer_learns(self):
        from deeplearning4j_tpu.models.zoo.transformer import (
            embed_fn, init_moe_block, lm_loss, logits_fn, make_moe_block_fn)
        V, d_model, T = 11, 32, 8
        mesh = make_expert_mesh(8)
        rng = jax.random.PRNGKey(3)
        aux = {
            "tok": jax.random.normal(rng, (V, d_model)) * 0.02,
            "pos": jax.random.normal(jax.random.fold_in(rng, 1),
                                     (T, d_model)) * 0.02,
            "lnf": {"g": jnp.ones(d_model), "b": jnp.zeros(d_model)},
            "head": jax.random.normal(jax.random.fold_in(rng, 2),
                                      (d_model, V)) / np.sqrt(d_model),
        }
        blk = init_moe_block(jax.random.fold_in(rng, 4), d_model,
                             n_heads=4, n_experts=E, d_ff=64)
        blk["moe"] = shard_moe_params(blk["moe"], mesh)
        moe_apply = moe_mlp_sharded(mesh)
        block_fn = make_moe_block_fn(4, moe_apply)

        def loss_fn(aux, blk, x, y):
            h = embed_fn(aux, x)
            h, lb = block_fn(blk, h)
            return lm_loss(aux, h, y) + 0.01 * lb

        rng_np = np.random.default_rng(0)
        x = rng_np.integers(0, V, (16, T)).astype(np.int32)
        y = (x + 1) % V

        lr = 0.2
        @jax.jit
        def step(aux, blk, x, y):
            loss, g = jax.value_and_grad(loss_fn, argnums=(0, 1))(
                aux, blk, x, y)
            aux = jax.tree.map(lambda p, gg: p - lr * gg, aux, g[0])
            blk = jax.tree.map(lambda p, gg: p - lr * gg, blk, g[1])
            return aux, blk, loss

        xj, yj = jnp.asarray(x), jnp.asarray(y)
        aux, blk, first = step(aux, blk, xj, yj)
        for _ in range(120):
            aux, blk, last = step(aux, blk, xj, yj)
        assert float(last) < float(first) * 0.5, (float(first), float(last))
