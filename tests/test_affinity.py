"""Prefix-affinity routing + fleet prefix tier pins (ISSUE 20
acceptance criteria).

  (a) Ring stability: the consistent-hash ring remaps ~1/N of the key
      space when one replica is added — and every moved key moves TO
      the newcomer; removing a replica moves ONLY the keys it owned.
      Exclusion walks clockwise to the next owner; placement is
      process-stable (sha256, never `hash()`).
  (b) Routing: `policy="affinity"` keeps a shared prefix on ONE
      replica (`routed_affinity` counted) while distinct prefixes
      spread; a hot home spills to least-backlog (`routed_spill`
      counted) instead of hotspotting.
  (c) Adoption correctness: a stream served from PULLED blocks
      (`prefix_export` -> `prefix_adopt`) is bit-identical to cold
      compute — solo and co-batched — and the adopter really reuses
      the rows (`prefix_rows_hit`); a STALE pull across a hot swap is
      refused loudly (`KVStateVersionError`, `prefix_pull_refused`
      counted, zero adopted) and the cold path stays correct.
  (d) Fleet tier: the same export/adopt verbs round-trip over a REAL
      loopback socket (OP_PREFIX_PULL / OP_PREFIX_PUSH artifact
      frames, refusals re-raised with their real type); after a
      scale_up remaps keys, `FleetManager.prefetch` re-warms the new
      owner from a warm peer and follow-up traffic hits the adopted
      rows.

The N-replica hit-rate retention + zero-added-dispatch A/B runs as
the tier-1 smoke (`tools/load_sweep.py --affinity`,
tests/test_loadgen.py).
"""
import time

import pytest

from deeplearning4j_tpu.models.zoo.transformer import TransformerLM
from deeplearning4j_tpu.serving import (ContinuousDecodeServer,
                                        FleetManager,
                                        KVStateVersionError,
                                        PrefixCacheArtifact,
                                        RemoteReplica, ReplicaServer,
                                        ServingMetrics)
from deeplearning4j_tpu.serving.fleet import (_build_ring, _ring_hash,
                                              _ring_lookup)


def _lm(seed=3):
    return TransformerLM(64, d_model=32, n_heads=2, n_layers=2,
                         max_len=64, seed=seed)


def _lm_small(seed=3):
    return TransformerLM(64, d_model=16, n_heads=2, n_layers=1,
                         max_len=64, seed=seed)


def _paged(lm, **kw):
    kw.setdefault("slots", 4)
    kw.setdefault("prompt_buckets", (8, 16))
    kw.setdefault("block_size", 4)
    kw.setdefault("n_blocks", 40)
    return ContinuousDecodeServer(lm, paged=True, **kw)


def _factory(lm, **kw):
    def make(name):
        return ContinuousDecodeServer(
            lm, slots=2, prompt_buckets=(8, 16),
            metrics=ServingMetrics(name=name), instance=name, **kw)
    return make


def _wait(pred, timeout=30.0, msg="condition"):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        if pred():
            return
        time.sleep(0.002)
    raise TimeoutError(f"never reached: {msg}")


SYS = list(range(1, 13))    # 3 full blocks at block_size 4


# ---------------------------------------------------------------------------
# (a) ring stability
# ---------------------------------------------------------------------------
class TestRingStability:
    KEYS = [(i, i + 1, i % 7) for i in range(2000)]

    def _owners(self, names):
        ring = _build_ring(names)
        return {k: _ring_lookup(ring, _ring_hash(k))
                for k in self.KEYS}

    def test_add_one_replica_remaps_about_one_over_n(self):
        """The property the policy exists for: growing 8 -> 9 replicas
        moves ~1/9 of the key space — and every moved key moves TO the
        newcomer (an old replica never steals another's arc), so at
        most one replica's worth of cache goes cold per spawn."""
        names = [f"i{j}" for j in range(8)]
        before = self._owners(names)
        after = self._owners(names + ["i8"])
        moved = [k for k in self.KEYS if before[k] != after[k]]
        frac = len(moved) / len(self.KEYS)
        # expectation 1/9 ~ 0.111; wide tolerance for vnode variance
        assert 0.03 < frac < 0.30, frac
        assert all(after[k] == "i8" for k in moved)

    def test_remove_one_replica_remaps_only_its_keys(self):
        """Shrinking moves ONLY the dead replica's keys: every other
        replica's warm set survives untouched."""
        names = [f"i{j}" for j in range(8)]
        before = self._owners(names)
        after = self._owners([n for n in names if n != "i3"])
        for k in self.KEYS:
            if before[k] == "i3":
                assert after[k] != "i3"
            else:
                assert after[k] == before[k]

    def test_lookup_walks_past_excluded_owners(self):
        names = ["a", "b", "c"]
        ring = _build_ring(names)
        kh = _ring_hash((1, 2, 3))
        home = _ring_lookup(ring, kh)
        alt = _ring_lookup(ring, kh, exclude={home})
        assert alt in names and alt != home
        assert _ring_lookup(ring, kh, exclude=set(names)) is None
        assert _ring_lookup([], kh) is None

    def test_placement_is_process_stable(self):
        """Non-bytes keys hash via repr — never `hash()`, whose
        per-process randomization would reshuffle placement (and
        thereby cold-start the fleet) on every restart."""
        key = (4, 5, 6)
        assert _ring_hash(key) == _ring_hash(repr(key).encode())
        ring = _build_ring(["a", "b", "c"])
        assert ring == _build_ring(["a", "b", "c"])


# ---------------------------------------------------------------------------
# (b) routing
# ---------------------------------------------------------------------------
class TestAffinityRouting:
    def test_affinity_key_floors_to_block_boundary(self):
        mgr = FleetManager(lambda name: None, n_replicas=1,
                           affinity_block=4, affinity_blocks=2)
        assert mgr._affinity_key([1, 2, 3]) == (1, 2, 3)
        assert mgr._affinity_key([1, 2, 3, 4, 5]) == (1, 2, 3, 4)
        assert mgr._affinity_key(range(1, 12)) == tuple(range(1, 9))
        # never started: nothing to stop

    def test_same_prefix_sticks_to_one_replica(self):
        lm = _lm_small()
        with FleetManager(_factory(lm), n_replicas=3,
                          policy="affinity", prefix_pull=False,
                          affinity_block=4) as mgr:
            for n in mgr.replicas:
                mgr.replica(n).generate([1, 2, 3], 2, timeout=120)
            base = {n: mgr.replica(n).metrics.count_value("received")
                    for n in mgr.replicas}
            for i in range(6):
                mgr.generate([7, 8, 9, 11, 20 + i], 3, timeout=120)
            recv = sorted(
                mgr.replica(n).metrics.count_value("received")
                - base[n] for n in mgr.replicas)
            assert recv == [0, 0, 6]
            snap = mgr.fleet_snapshot()
            assert snap["fleet_routed_affinity"] >= 6
            assert snap["fleet_routed_spill"] == 0

    def test_distinct_prefixes_spread_across_replicas(self):
        lm = _lm_small()
        with FleetManager(_factory(lm), n_replicas=3,
                          policy="affinity", prefix_pull=False,
                          affinity_block=4) as mgr:
            for n in mgr.replicas:
                mgr.replica(n).generate([1, 2, 3], 2, timeout=120)
            base = {n: mgr.replica(n).metrics.count_value("received")
                    for n in mgr.replicas}
            for i in range(16):
                mgr.generate([3 * i + 1, 3 * i + 2, 3 * i + 3,
                              3 * i + 4], 2, timeout=120)
            recv = [mgr.replica(n).metrics.count_value("received")
                    - base[n] for n in mgr.replicas]
            assert sum(recv) == 16
            assert sum(1 for r in recv if r > 0) >= 2

    def test_hot_home_spills_to_least_backlog(self):
        """Stickiness is a goodput preference, never a hotspot: with
        the spill threshold at zero slack, a second same-prefix
        request arriving while the home decodes routes to the idle
        peer and is COUNTED as a spill."""
        lm = _lm_small()
        with FleetManager(_factory(lm), n_replicas=2,
                          policy="affinity", prefix_pull=False,
                          affinity_block=4, spill_factor=1.0,
                          spill_slack=0) as mgr:
            for n in mgr.replicas:
                mgr.replica(n).generate([1, 2, 3], 2, timeout=120)
            f1 = mgr.submit([5, 6, 7, 8, 30], 32)
            _wait(lambda: any(r.inflight
                              for r in mgr._replicas.values()),
                  msg="first request in flight")
            f2 = mgr.submit([5, 6, 7, 8, 31], 4)
            f1.result(120)
            f2.result(120)
            snap = mgr.fleet_snapshot()
            assert snap["fleet_routed_affinity"] >= 1
            assert snap["fleet_routed_spill"] == 1


# ---------------------------------------------------------------------------
# (c) adoption correctness
# ---------------------------------------------------------------------------
class TestAdoptionCorrectness:
    def _warm_source(self, lm):
        a = _paged(lm, slots=2, prompt_buckets=(16,)).start()
        a.generate(SYS + [20, 21], 8, timeout=120)
        return a

    def test_pulled_stream_bit_identical_to_cold_compute(self):
        lm = _lm()
        prompt = SYS + [22, 23]
        ref = list(lm.generate(prompt, 8))
        a = self._warm_source(lm)
        b = _paged(lm, slots=2, prompt_buckets=(16,)).start()
        try:
            art = a.prefix_export(tuple(SYS))
            assert art is not None and len(art.entries) == 3
            adopted = b.prefix_adopt(art)
            assert adopted == 3
            snap = b.metrics.snapshot()
            assert snap["prefix_pull_hits"] == 3
            assert snap["prefix_pull_bytes"] > 0
            assert snap["prefix_pull_refused"] == 0
            pre = b.metrics.snapshot()
            assert b.generate(prompt, 8, timeout=120) == ref
            post = b.metrics.snapshot()
            # the adopter really SERVED from the pulled rows: all 3
            # blocks (12 rows) matched out of the pool, not recomputed
            assert post["prefix_rows_hit"] - pre["prefix_rows_hit"] \
                >= 12
        finally:
            a.stop(timeout=120)
            b.stop(timeout=120)
        b._pool.check()

    def test_pulled_stream_bit_identical_co_batched(self):
        """The pulled-prefix request decodes CO-BATCHED with unrelated
        traffic on the adopter — sharing the adopted blocks in the
        same scheduling iterations — and every stream stays
        bit-identical to its solo reference."""
        lm = _lm()
        prompts = [SYS + [24, 25], [40, 41, 42], [50, 51, 52, 53]]
        refs = [list(lm.generate(p, 10)) for p in prompts]
        a = self._warm_source(lm)
        b = _paged(lm, slots=4, prompt_buckets=(8, 16)).start()
        try:
            assert b.prefix_adopt(a.prefix_export(tuple(SYS))) == 3
            futs = [b.submit(p, 10) for p in prompts]
            for f, ref in zip(futs, refs):
                assert list(f.result(120)) == ref
            assert b.metrics.snapshot()["prefix_rows_hit"] >= 12
        finally:
            a.stop(timeout=120)
            b.stop(timeout=120)
        b._pool.check()

    def test_stale_pull_refused_across_hot_swap(self):
        """A pull exported under v0 params adopted AFTER the adopter
        hot-swapped to v1 is refused loudly — `KVStateVersionError`,
        `prefix_pull_refused` counted, ZERO blocks adopted — and the
        request degrades to cold compute under the NEW params."""
        lm, lm2 = _lm(), _lm(seed=9)
        prompt = SYS + [26, 27]
        a = self._warm_source(lm)
        b = _paged(lm, slots=2, prompt_buckets=(16,)).start()
        try:
            art = a.prefix_export(tuple(SYS))
            b.swap(lm2)
            with pytest.raises(KVStateVersionError):
                b.prefix_adopt(art)
            snap = b.metrics.snapshot()
            assert snap["prefix_pull_refused"] == 1
            assert snap["prefix_pull_hits"] == 0
            # cold compute under the new params stays correct
            assert b.generate(prompt, 8, timeout=120) \
                == list(lm2.generate(prompt, 8))
        finally:
            a.stop(timeout=120)
            b.stop(timeout=120)
        b._pool.check()

    def test_export_unknown_key_returns_none(self):
        lm = _lm()
        a = self._warm_source(lm)
        try:
            assert a.prefix_export((60, 61, 62, 63)) is None
        finally:
            a.stop(timeout=120)

    def test_export_max_bytes_truncates_parent_first(self):
        """A budgeted export ships a PREFIX of the chain (parent-
        first) — still matchable from the front, never a torn tail."""
        lm = _lm()
        a = self._warm_source(lm)
        try:
            full = a.prefix_export(tuple(SYS))
            assert len(full.entries) == 3
            per_block = full.nbytes // 3
            part = a.prefix_export(tuple(SYS),
                                   max_bytes=2 * per_block)
            assert len(part.entries) == 2
            assert [p for p, _ in part.entries] \
                == [p for p, _ in full.entries[:2]]
        finally:
            a.stop(timeout=120)


# ---------------------------------------------------------------------------
# (d) fleet tier: the wire seam + manager prefetch
# ---------------------------------------------------------------------------
class TestWirePrefixPull:
    def test_pull_round_trips_over_real_socket(self):
        """OP_PREFIX_PULL / OP_PREFIX_PUSH over a REAL loopback
        socket: the artifact ships as `to_bytes` frames, the adopter
        serves the pulled prefix bit-identically, and a stale push
        after a remote hot swap re-raises `KVStateVersionError` with
        its real type (and is counted at the far end)."""
        lm, lm2 = _lm(), _lm(seed=9)
        prompt = SYS + [28, 29]
        ref = list(lm.generate(prompt, 8))
        sa = _paged(lm, slots=2, prompt_buckets=(16,)).start()
        sb = _paged(lm, slots=2, prompt_buckets=(16,)).start()
        rsa, rsb = ReplicaServer(sa), ReplicaServer(sb)
        ra = RemoteReplica("127.0.0.1", rsa.port, name="wa",
                           heartbeat_interval=0.05)
        rb = RemoteReplica("127.0.0.1", rsb.port, name="wb",
                           heartbeat_interval=0.05)
        try:
            ra.generate(SYS + [20, 21], 8, timeout=120)
            art = ra.prefix_export(tuple(SYS))
            assert isinstance(art, PrefixCacheArtifact)
            assert ra.prefix_export((60, 61, 62, 63)) is None
            assert rb.prefix_adopt(art) == 3
            assert list(rb.generate(prompt, 8, timeout=120)) == ref
            assert rb.metrics.snapshot()["prefix_rows_hit"] >= 12
            rb.swap(lm2)
            with pytest.raises(KVStateVersionError):
                rb.prefix_adopt(art)
            assert rb.metrics.snapshot()["prefix_pull_refused"] == 1
        finally:
            ra.stop(timeout=60)     # graceful OP_STOP stops sa/sb too
            rb.stop(timeout=60)
            rsa.close(stop_server=False)
            rsb.close(stop_server=False)


class TestManagerPrefetch:
    def test_prefetch_rewarms_moved_keys_after_scale_up(self):
        """The scale-up companion: after a spawn remaps ~1/N keys,
        `prefetch` synchronously pulls a moved key's blocks from the
        warm old owner into the NEW ring owner (budget + counters
        shared with dispatch-time pulls), so the first routed request
        hits adopted rows instead of recomputing."""
        lm = _lm()
        cands = [[3 * i + 1, 3 * i + 2, 3 * i + 3, 3 * i + 4]
                 for i in range(12)]
        with FleetManager(
                _factory(lm, paged=True, block_size=4, n_blocks=40),
                n_replicas=1, policy="affinity", affinity_block=4,
                max_replicas=4) as mgr:
            for n in mgr.replicas:
                mgr.replica(n).generate([1, 2, 3], 2, timeout=120)
            for c in cands:
                mgr.generate(c + [30, 31], 3, timeout=120)
            # single replica: every owner is already warm -> no-op
            assert mgr.prefetch(cands[0] + [30]) == 0
            assert mgr.prefetch([]) == 0
            old = set(mgr.replicas)
            moved = []
            for _ in range(3):          # ring churn: spawn until a
                mgr.scale_up()          # key provably remaps
                new = [n for n in mgr.replicas if n not in old]
                ring = _build_ring(tuple(mgr.replicas))
                moved = [c for c in cands
                         if _ring_lookup(ring, _ring_hash(tuple(c)))
                         in new]
                if moved:
                    break
                old = set(mgr.replicas)
            assert moved, "no key remapped after 3 spawns"
            for n in new:
                mgr.replica(n).generate([1, 2, 3], 2, timeout=120)
            c = moved[0]
            base = mgr.fleet_snapshot()
            assert mgr.prefetch(c + [33]) >= 1
            snap = mgr.fleet_snapshot()
            assert snap["fleet_prefix_pull_hits"] \
                - base["fleet_prefix_pull_hits"] >= 1
            assert snap["fleet_prefix_pull_bytes"] \
                - base["fleet_prefix_pull_bytes"] > 0
            # already pulled: the second prefetch is a no-op
            assert mgr.prefetch(c + [34]) == 0
            # the re-routed request SERVES from the pulled rows,
            # bit-identical to solo
            owner = _ring_lookup(_build_ring(tuple(mgr.replicas)),
                                 _ring_hash(tuple(c)))
            pre = mgr.replica(owner).metrics.snapshot()
            assert mgr.generate(c + [40], 3, timeout=120) \
                == list(lm.generate(c + [40], 3))
            post = mgr.replica(owner).metrics.snapshot()
            assert post["prefix_rows_hit"] - pre["prefix_rows_hit"] \
                >= 4
