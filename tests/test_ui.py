"""UI/stats: StatsListener collection, storage backends (memory/file),
remote router -> UIServer round trip, overview page served.
Mirrors reference ui-model TestStatsClasses / storage tests."""
import json
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu import InputType, MultiLayerNetwork, NeuralNetConfiguration
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.ui import (FileStatsStorage, InMemoryStatsStorage,
                                   SqliteStatsStorage,
                                   RemoteUIStatsStorageRouter, StatsListener,
                                   StatsUpdateConfiguration, UIServer)


def _net():
    conf = (NeuralNetConfiguration.Builder().seed(1)
            .updater("adam").learning_rate(0.01).list()
            .layer(0, DenseLayer(n_out=8, activation="relu"))
            .layer(1, OutputLayer(n_out=3, activation="softmax",
                                  loss_function="mcxent"))
            .set_input_type(InputType.feed_forward(4))
            .build())
    return MultiLayerNetwork(conf).init()


def _ds(n=16):
    r = np.random.default_rng(0)
    return DataSet(r.random((n, 4)).astype(np.float32),
                   np.eye(3, dtype=np.float32)[r.integers(0, 3, n)])


def test_stats_listener_collects_reports():
    storage = InMemoryStatsStorage()
    net = _net()
    net.set_listeners(StatsListener(
        storage, StatsUpdateConfiguration(collect_histograms=True,
                                          histogram_bins=10)))
    ds = _ds()
    for _ in range(5):
        net.fit(ds)
    sessions = storage.list_session_ids()
    assert len(sessions) == 1
    sid = sessions[0]
    static = storage.get_static_info(sid)
    assert static["model"]["class"] == "MultiLayerNetwork"
    assert static["model"]["numParams"] == net.num_params()
    ups = storage.get_all_updates(sid)
    assert len(ups) == 5
    last = ups[-1]
    assert "score" in last and np.isfinite(last["score"])
    assert last["totalExamples"] == 5 * 16
    assert "0_W" in last["parameters"]
    p = last["parameters"]["0_W"]
    assert {"mean", "stdev", "meanMagnitude", "histogram"} <= set(p)
    assert sum(p["histogram"]["counts"]) == 4 * 8


def test_file_storage_replay(tmp_path):
    path = str(tmp_path / "stats.jsonl")
    storage = FileStatsStorage(path)
    net = _net()
    net.set_listeners(StatsListener(storage, session_id="s1"))
    net.fit(_ds())
    # reopen -> replay from disk
    storage2 = FileStatsStorage(path)
    assert storage2.list_session_ids() == ["s1"]
    assert len(storage2.get_all_updates("s1")) == 1
    assert storage2.get_static_info("s1")["model"]["class"] == \
        "MultiLayerNetwork"


def test_sqlite_storage_persist_and_incremental(tmp_path):
    """J7FileStatsStorage parity: single-file SQLite store reloads across
    opens and serves incremental range queries."""
    path = str(tmp_path / "stats.db")
    storage = SqliteStatsStorage(path)
    net = _net()
    net.set_listeners(StatsListener(storage, session_id="s1"))
    ds = _ds()
    for _ in range(3):
        net.fit(ds)
    assert len(storage.get_all_updates("s1")) == 3
    # incremental poll: nothing after the last seen index
    assert storage.get_updates_since("s1", 2) == []
    inc = storage.get_updates_since("s1", 0)
    assert len(inc) == 2
    assert inc == storage.get_all_updates("s1")[1:]
    storage.close()
    # reopen -> loaded from the database file
    storage2 = SqliteStatsStorage(path)
    assert storage2.list_session_ids() == ["s1"]
    assert len(storage2.get_all_updates("s1")) == 3
    assert storage2.get_static_info("s1")["model"]["class"] == \
        "MultiLayerNetwork"
    # appends after reopen extend the same session
    net.set_listeners(StatsListener(storage2, session_id="s1"))
    net.fit(ds)
    assert len(storage2.get_updates_since("s1", 2)) == 1
    storage2.close()


def test_ui_server_and_remote_router():
    storage = InMemoryStatsStorage()
    server = UIServer(port=0).attach(storage)
    try:
        base = f"http://127.0.0.1:{server.port}"
        # remote router posts into the server
        router = RemoteUIStatsStorageRouter(base)
        router.put_static_info({"sessionId": "remote1", "model": {
            "class": "MultiLayerNetwork", "numParams": 1},
            "machine": {"device": "test"}})
        router.put_update({"sessionId": "remote1", "iteration": 0,
                           "score": 1.5})
        with urllib.request.urlopen(f"{base}/api/sessions") as r:
            assert json.load(r) == ["remote1"]
        with urllib.request.urlopen(f"{base}/api/updates/remote1") as r:
            ups = json.load(r)
        assert ups[0]["score"] == 1.5
        with urllib.request.urlopen(base + "/") as r:
            page = r.read().decode()
        assert "Training overview" in page
    finally:
        server.stop()


def test_ui_pages_served_and_tsne_upload():
    storage = InMemoryStatsStorage()
    net = _net()
    net.set_listeners(StatsListener(
        storage, StatsUpdateConfiguration(collect_histograms=True),
        session_id="s1"))
    for _ in range(3):
        net.fit(_ds())
    server = UIServer(port=0).attach(storage)
    try:
        base = f"http://127.0.0.1:{server.port}"
        for path, marker in [("/train/model", "Model"),
                             ("/train/histogram", "Histograms"),
                             ("/tsne", "t-SNE")]:
            with urllib.request.urlopen(base + path) as r:
                assert marker in r.read().decode()
        # updates carry param + update (delta) summaries for the pages
        ups = storage.get_all_updates("s1")
        assert "parameters" in ups[-1] and "updates" in ups[-1]
        assert "0_W" in ups[-1]["updates"]
        # t-SNE upload + fetch round trip
        coords = {"coords": [[0.0, 1.0], [2.0, 3.0]], "labels": ["a", "b"]}
        req = urllib.request.Request(
            f"{base}/api/tsne/s1", data=json.dumps(coords).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req) as r:
            assert json.load(r)["ok"]
        with urllib.request.urlopen(f"{base}/api/tsne/s1") as r:
            got = json.load(r)
        assert got["coords"] == coords["coords"]
        assert got["labels"] == ["a", "b"]
    finally:
        server.stop()


def test_post_without_storage_returns_503():
    server = UIServer(port=0).start()
    try:
        base = f"http://127.0.0.1:{server.port}"
        req = urllib.request.Request(
            f"{base}/remoteReceive/update",
            data=b'{"sessionId": "x"}',
            headers={"Content-Type": "application/json"})
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req)
        assert ei.value.code == 503
    finally:
        server.stop()


def test_listener_events_push():
    storage = InMemoryStatsStorage()
    events = []
    storage.register_stats_storage_listener(
        lambda kind, payload: events.append(kind))
    net = _net()
    net.set_listeners(StatsListener(storage))
    net.fit(_ds())
    assert events == ["static", "update"]


@pytest.mark.slow
def test_activation_collection_and_new_pages():
    """Flow / conv-activation / system pages + activation capture
    (reference FlowListenerModule, ConvolutionalListenerModule,
    TrainModule system tab — VERDICT r2 item 5)."""
    from deeplearning4j_tpu.nn.conf.layers import (ConvolutionLayer,
                                                   SubsamplingLayer)
    conf = (NeuralNetConfiguration.Builder().seed(1)
            .updater("adam").learning_rate(0.01).list()
            .layer(0, ConvolutionLayer(n_out=4, kernel_size=(3, 3),
                                       activation="relu"))
            .layer(1, SubsamplingLayer(pooling_type="max",
                                       kernel_size=(2, 2)))
            .layer(2, OutputLayer(n_out=3, activation="softmax",
                                  loss_function="mcxent"))
            .set_input_type(InputType.convolutional(10, 10, 1))
            .build())
    net = MultiLayerNetwork(conf).init()
    r = np.random.default_rng(0)
    x = r.random((8, 10, 10, 1)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[r.integers(0, 3, 8)]
    storage = InMemoryStatsStorage()
    net.set_listeners(StatsListener(
        storage,
        StatsUpdateConfiguration(collect_activations=True,
                                 max_activation_channels=3),
        session_id="act1", activation_probe=x[:2]))
    for _ in range(2):
        net.fit(DataSet(x, y))
    ups = storage.get_all_updates("act1")
    acts = ups[-1]["activations"]
    # conv (layer 0) and pool (layer 1) produce 4-D maps; output doesn't
    assert "0" in acts and "1" in acts and "2" not in acts
    a0 = acts["0"]
    assert a0["height"] == 8 and a0["width"] == 8     # 10 - 3 + 1 (truncate)
    assert len(a0["channels"]) == 3
    flat = [v for row in a0["channels"][0] for v in row]
    assert all(0 <= v <= 255 for v in flat)
    server = UIServer(port=0).attach(storage)
    try:
        base = f"http://127.0.0.1:{server.port}"
        for path, marker in [("/train/flow", "Network DAG"),
                             ("/train/activations", "Layer activations"),
                             ("/train/system", "Device memory")]:
            with urllib.request.urlopen(base + path) as r2:
                assert marker in r2.read().decode()
        # the system page's data source: memory in updates
        assert "memory" in ups[-1]
    finally:
        server.stop()


def test_activation_stats_from_fused_step_no_probe():
    """VERDICT r4 item 7: collect_activations=True with NO probe — the
    fused train step emits per-layer summaries of the REAL training batch
    (reference BaseStatsListener.java:273-420 captures from the live
    forward pass). Asserts the reported mean matches a feed_forward on the
    fit batch itself, not any probe data."""
    from deeplearning4j_tpu.nn.conf.layers import ConvolutionLayer
    conf = (NeuralNetConfiguration.Builder().seed(3)
            .updater("sgd").learning_rate(0.01).list()
            .layer(0, ConvolutionLayer(n_out=4, kernel_size=(3, 3),
                                       activation="relu"))
            .layer(1, OutputLayer(n_out=3, activation="softmax",
                                  loss_function="mcxent"))
            .set_input_type(InputType.convolutional(10, 10, 1))
            .build())
    net = MultiLayerNetwork(conf).init()
    r = np.random.default_rng(5)
    x = r.random((8, 10, 10, 1)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[r.integers(0, 3, 8)]
    storage = InMemoryStatsStorage()
    net.set_listeners(StatsListener(
        storage, StatsUpdateConfiguration(collect_activations=True,
                                          max_activation_channels=2),
        session_id="live1"))                       # NO activation_probe
    for _ in range(2):
        net.fit(DataSet(x, y))
    # the step's forward ran on PRE-update params: snapshot them, fit one
    # more iteration, and reproduce the captured forward exactly
    params_before = np.asarray(net.params())
    net.fit(DataSet(x, y))
    ups = storage.get_all_updates("live1")
    # iteration 0 arms the fused step; later reports carry live stats
    assert "activationStats" not in ups[0]
    last = ups[-1]
    stats = last["activationStats"]
    assert set(stats) == {"0"}                     # conv layer summary
    # ground truth: the SAME fit batch through the pre-step params (relu
    # conv has no train-mode stochasticity)
    params_after = np.asarray(net.params())
    net.set_params(params_before)
    conv = np.asarray(net.feed_forward(x, train=False)[1], np.float64)
    assert abs(stats["0"]["mean"] - conv.mean()) < 1e-3
    assert abs(stats["0"]["meanMagnitude"] - np.abs(conv).mean()) < 1e-3
    # and NOT the stats of some other batch (fit-batch identity)
    other = np.asarray(net.feed_forward(
        r.random((8, 10, 10, 1)).astype(np.float32), train=False)[1],
        np.float64)
    assert abs(stats["0"]["mean"] - other.mean()) > 1e-4
    net.set_params(params_after)
    # conv grids captured from the step, downsample/channel caps honored
    g = last["activations"]["0"]
    assert g["height"] == 8 and len(g["channels"]) == 2
    # the model page charts the live per-layer activation stats; verify
    # the full data path the page's JS consumes, for the activation-ONLY
    # configuration (no parameter stats collected — the chart must not be
    # starved by the param guard)
    storage2 = InMemoryStatsStorage()
    net2 = MultiLayerNetwork(conf).init()
    net2.set_listeners(StatsListener(
        storage2, StatsUpdateConfiguration(
            collect_mean=False, collect_stdev=False,
            collect_histograms=False, collect_activations=True),
        session_id="actonly"))
    for _ in range(3):
        net2.fit(DataSet(x, y))
    server = UIServer(port=0).attach(storage2)
    try:
        base = f"http://127.0.0.1:{server.port}"
        with urllib.request.urlopen(base + "/train/model") as r2:
            assert "Activation mean magnitude" in r2.read().decode()
        with urllib.request.urlopen(base + "/api/updates/actonly") as r2:
            ups2 = json.load(r2)
        with_a = [u for u in ups2 if "activationStats" in u]
        assert with_a and all("parameters" not in u for u in ups2)
        # exactly what the JS plots: (iteration, meanMagnitude) points
        assert all(
            isinstance(u["activationStats"]["0"]["meanMagnitude"], float)
            for u in with_a)
    finally:
        server.stop()
    # toggling off restores the fast-path step; the listener must NOT
    # silently re-arm a model the user explicitly disabled
    net.collect_activation_stats(False)
    net.fit(DataSet(x, y))
    net.fit(DataSet(x, y))                     # would re-arm if buggy
    assert "activationStats" not in storage.get_all_updates("live1")[-1]
    assert net._last_activation_stats is None
    assert net._act_stats_cfg is None


def test_activation_arming_mid_fit_over_iterator():
    """The listener arms the model from iteration_done MID-fit; the
    remaining batches of the same fit() call must rebuild the step, not
    crash on a nulled _jit_step (r5 review finding, reproduced: 2-batch
    iterator fit died with TypeError on batch 2)."""
    from deeplearning4j_tpu.datasets.iterators import ListDataSetIterator
    from deeplearning4j_tpu.nn.conf.layers import ConvolutionLayer
    conf = (NeuralNetConfiguration.Builder().seed(8)
            .updater("sgd").learning_rate(0.01).list()
            .layer(0, ConvolutionLayer(n_out=4, kernel_size=(3, 3),
                                       activation="relu"))
            .layer(1, OutputLayer(n_out=3, activation="softmax",
                                  loss_function="mcxent"))
            .set_input_type(InputType.convolutional(10, 10, 1))
            .build())
    net = MultiLayerNetwork(conf).init()
    r = np.random.default_rng(9)
    x = r.random((12, 10, 10, 1)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[r.integers(0, 3, 12)]
    storage = InMemoryStatsStorage()
    net.set_listeners(StatsListener(
        storage, StatsUpdateConfiguration(collect_activations=True),
        session_id="midfit"))
    batches = list(DataSet(x, y).batch_by(4))       # 3 batches, ONE fit
    net.fit(ListDataSetIterator(batches))
    ups = storage.get_all_updates("midfit")
    assert len(ups) == 3
    # armed at iteration 0 -> iterations 1+ carry live stats
    assert "activationStats" in ups[-1]


@pytest.mark.slow
def test_activation_stats_under_parallel_wrapper():
    """The sharded allreduce path honors the activation-stats arming the
    same way the single-chip step does (a PW-trained net with
    collect_activations=True must not be a silent no-op)."""
    from deeplearning4j_tpu.nn.conf.layers import ConvolutionLayer
    from deeplearning4j_tpu.parallel import ParallelWrapper
    conf = (NeuralNetConfiguration.Builder().seed(4)
            .updater("sgd").learning_rate(0.01).list()
            .layer(0, ConvolutionLayer(n_out=4, kernel_size=(3, 3),
                                       activation="relu"))
            .layer(1, OutputLayer(n_out=3, activation="softmax",
                                  loss_function="mcxent"))
            .set_input_type(InputType.convolutional(10, 10, 1))
            .build())
    net = MultiLayerNetwork(conf).init()
    r = np.random.default_rng(6)
    x = r.random((8, 10, 10, 1)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[r.integers(0, 3, 8)]
    storage = InMemoryStatsStorage()
    net.set_listeners(StatsListener(
        storage, StatsUpdateConfiguration(collect_activations=True),
        session_id="pw1"))
    pw = ParallelWrapper.Builder(net).averaging_frequency(1).build()
    # ONE fit over a 3-batch iterator: mid-fit arming must take effect
    # within the same fit call (the step is re-ensured per batch)
    from deeplearning4j_tpu.datasets.iterators import ListDataSetIterator
    batches = list(DataSet(np.concatenate([x, x, x]),
                           np.concatenate([y, y, y])).batch_by(8))
    pw.fit(ListDataSetIterator(batches))
    last = storage.get_all_updates("pw1")[-1]
    assert "activationStats" in last and "0" in last["activationStats"]


@pytest.mark.slow
def test_legacy_listeners_feed_modern_storage():
    """reference deeplearning4j-ui legacy listeners as StatsListener
    presets: histogram listener collects histograms, conv listener
    collects activations, flow listener ships the topology."""
    from deeplearning4j_tpu.nn.conf.layers import ConvolutionLayer
    from deeplearning4j_tpu.ui import (ConvolutionalIterationListener,
                                       FlowIterationListener,
                                       HistogramIterationListener)
    net = _net()
    ds = _ds()
    hl = HistogramIterationListener(session_id="legacy_h")
    net.set_listeners(hl)
    net.fit(ds)
    ups = hl.router.get_all_updates("legacy_h")
    assert any("histogram" in p for u in ups
               for p in u.get("parameters", {}).values())

    conf = (NeuralNetConfiguration.Builder().seed(1)
            .updater("adam").learning_rate(0.01).list()
            .layer(0, ConvolutionLayer(n_out=3, kernel_size=(3, 3),
                                       activation="relu"))
            .layer(1, OutputLayer(n_out=2, activation="softmax",
                                  loss_function="mcxent"))
            .set_input_type(InputType.convolutional(8, 8, 1))
            .build())
    cnet = MultiLayerNetwork(conf).init()
    r = np.random.default_rng(0)
    cx = r.random((4, 8, 8, 1)).astype(np.float32)
    cy = np.eye(2, dtype=np.float32)[r.integers(0, 2, 4)]
    cl = ConvolutionalIterationListener(cx[:1], session_id="legacy_c")
    cnet.set_listeners(cl)
    cnet.fit(DataSet(cx, cy))
    ups = cl.router.get_all_updates("legacy_c")
    assert "activations" in ups[-1] and "0" in ups[-1]["activations"]

    fl = FlowIterationListener(session_id="legacy_f")
    net2 = _net()
    net2.set_listeners(fl)
    net2.fit(ds)
    static = fl.router.get_static_info("legacy_f")
    assert "configJson" in static["model"]
