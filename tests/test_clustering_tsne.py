"""Clustering (KMeans), spatial trees (VPTree/KDTree vs brute force),
t-SNE cluster preservation."""
import numpy as np
import pytest

from deeplearning4j_tpu.clustering import KDTree, KMeansClustering, VPTree
from deeplearning4j_tpu.plot import Tsne


def _blobs(n_per=40, centers=((0, 0, 0), (10, 10, 10), (-10, 5, -5)),
           seed=0):
    rng = np.random.default_rng(seed)
    xs, ys = [], []
    for ci, c in enumerate(centers):
        xs.append(rng.normal(c, 1.0, (n_per, len(c))))
        ys.extend([ci] * n_per)
    return np.concatenate(xs).astype(np.float32), np.asarray(ys)


class TestKMeans:
    def test_recovers_blobs(self):
        x, y = _blobs()
        km = KMeansClustering.setup(3, max_iterations=50).fit(x)
        labels = km.labels
        # cluster purity: each true blob maps to one dominant cluster
        for c in range(3):
            counts = np.bincount(labels[y == c], minlength=3)
            assert counts.max() / counts.sum() > 0.95
        # predict matches fit assignment
        assert np.array_equal(km.predict(x), labels)

    def test_cost_decreases_with_k(self):
        x, _ = _blobs()
        c1 = KMeansClustering.setup(1).fit(x).cost
        c3 = KMeansClustering.setup(3).fit(x).cost
        assert c3 < c1


class TestTrees:
    def test_vptree_knn_matches_bruteforce(self):
        rng = np.random.default_rng(1)
        pts = rng.random((200, 5))
        tree = VPTree(pts)
        for qi in range(5):
            q = rng.random(5)
            got = [i for _, i in tree.knn(q, 7)]
            want = np.argsort(np.linalg.norm(pts - q, axis=1))[:7]
            assert set(got) == set(want.tolist())

    def test_kdtree_nn_matches_bruteforce(self):
        rng = np.random.default_rng(2)
        pts = rng.random((150, 3))
        tree = KDTree(pts)
        for _ in range(10):
            q = rng.random(3)
            d, i = tree.nn(q)
            want = int(np.argmin(np.linalg.norm(pts - q, axis=1)))
            assert i == want
            assert abs(d - np.linalg.norm(pts[want] - q)) < 1e-9


class TestTsne:
    def test_clusters_stay_separated(self):
        x, y = _blobs(n_per=30)
        emb = (Tsne.Builder().set_max_iter(300).perplexity(10)
               .num_dimension(2).seed(3).build().fit(x))
        assert emb.shape == (90, 2)
        # mean intra-cluster distance << mean inter-cluster distance
        intra, inter = [], []
        for i in range(0, 90, 7):
            for j in range(i + 1, 90, 11):
                d = np.linalg.norm(emb[i] - emb[j])
                (intra if y[i] == y[j] else inter).append(d)
        assert np.mean(intra) * 2 < np.mean(inter)

    def test_plot_tsv_export(self, tmp_path):
        x, y = _blobs(n_per=10)
        p = tmp_path / "coords.tsv"
        Tsne(max_iter=50, perplexity=5).plot(x, labels=y, path=str(p))
        lines = p.read_text().strip().split("\n")
        assert len(lines) == 30
        assert len(lines[0].split("\t")) == 3
