"""Clustering (KMeans), spatial trees (VPTree/KDTree vs brute force),
t-SNE cluster preservation."""
import numpy as np
import pytest

from deeplearning4j_tpu.clustering import KDTree, KMeansClustering, VPTree
from deeplearning4j_tpu.plot import Tsne


def _blobs(n_per=40, centers=((0, 0, 0), (10, 10, 10), (-10, 5, -5)),
           seed=0):
    rng = np.random.default_rng(seed)
    xs, ys = [], []
    for ci, c in enumerate(centers):
        xs.append(rng.normal(c, 1.0, (n_per, len(c))))
        ys.extend([ci] * n_per)
    return np.concatenate(xs).astype(np.float32), np.asarray(ys)


class TestKMeans:
    def test_recovers_blobs(self):
        x, y = _blobs()
        km = KMeansClustering.setup(3, max_iterations=50).fit(x)
        labels = km.labels
        # cluster purity: each true blob maps to one dominant cluster
        for c in range(3):
            counts = np.bincount(labels[y == c], minlength=3)
            assert counts.max() / counts.sum() > 0.95
        # predict matches fit assignment
        assert np.array_equal(km.predict(x), labels)

    def test_cost_decreases_with_k(self):
        x, _ = _blobs()
        c1 = KMeansClustering.setup(1).fit(x).cost
        c3 = KMeansClustering.setup(3).fit(x).cost
        assert c3 < c1


class TestTrees:
    def test_vptree_knn_matches_bruteforce(self):
        rng = np.random.default_rng(1)
        pts = rng.random((200, 5))
        tree = VPTree(pts)
        for qi in range(5):
            q = rng.random(5)
            got = [i for _, i in tree.knn(q, 7)]
            want = np.argsort(np.linalg.norm(pts - q, axis=1))[:7]
            assert set(got) == set(want.tolist())

    def test_kdtree_nn_matches_bruteforce(self):
        rng = np.random.default_rng(2)
        pts = rng.random((150, 3))
        tree = KDTree(pts)
        for _ in range(10):
            q = rng.random(3)
            d, i = tree.nn(q)
            want = int(np.argmin(np.linalg.norm(pts - q, axis=1)))
            assert i == want
            assert abs(d - np.linalg.norm(pts[want] - q)) < 1e-9


class TestTsne:
    def test_clusters_stay_separated(self):
        x, y = _blobs(n_per=30)
        emb = (Tsne.Builder().set_max_iter(300).perplexity(10)
               .num_dimension(2).seed(3).build().fit(x))
        assert emb.shape == (90, 2)
        # mean intra-cluster distance << mean inter-cluster distance
        intra, inter = [], []
        for i in range(0, 90, 7):
            for j in range(i + 1, 90, 11):
                d = np.linalg.norm(emb[i] - emb[j])
                (intra if y[i] == y[j] else inter).append(d)
        assert np.mean(intra) * 2 < np.mean(inter)

    def test_sparse_p_matches_dense_p(self):
        """With k covering every neighbor, the kNN + vectorized-bisection
        P (Barnes-Hut preprocessing) equals the dense host-loop
        `_cond_probs` matrix."""
        from deeplearning4j_tpu.plot.tsne import _cond_probs, _sparse_sym_p
        rng = np.random.default_rng(4)
        x = rng.standard_normal((60, 5))
        perp = 10.0
        dense = _cond_probs(x, perp)
        row_ptr, cols, vals = _sparse_sym_p(x, perp)
        sparse = np.zeros((60, 60))
        for i in range(60):
            sparse[i, cols[row_ptr[i]:row_ptr[i + 1]]] = \
                vals[row_ptr[i]:row_ptr[i + 1]]
        np.testing.assert_allclose(sparse, dense, atol=3e-4)

    def test_bh_gradient_matches_exact_at_theta_zero(self):
        """Native quadtree forces at theta=0 == the exact O(N²) numpy
        forces (the dense kernel's gradient decomposition)."""
        from deeplearning4j_tpu.common import native_ops
        from deeplearning4j_tpu.plot.tsne import _np_repulsion
        if not native_ops.available():
            pytest.skip("native library unavailable")
        rng = np.random.default_rng(5)
        y = rng.standard_normal((400, 2)).astype(np.float32)
        rep_n, z_n = native_ops.bh_repulsion(y, theta=0.0)
        rep_e, z_e = _np_repulsion(y)
        assert abs(z_n - z_e) / z_e < 1e-5
        np.testing.assert_allclose(rep_n, rep_e, atol=1e-4)
        # theta=0.5 stays within ~1% force error
        rep_a, z_a = native_ops.bh_repulsion(y, theta=0.5)
        assert abs(z_a - z_e) / z_e < 0.02
        assert (np.abs(rep_a - rep_e).max()
                / max(np.abs(rep_e).max(), 1e-9)) < 0.05

    def test_sparse_sym_p_with_more_than_k_duplicates(self):
        """With >k exact duplicates the query's own index can be tied out
        of its top-(k+1) neighbor list; the self-removal fallback must drop
        the farthest column then, not silently discard the true nearest
        neighbor (column 0)."""
        from deeplearning4j_tpu.plot.tsne import _sparse_sym_p
        rng = np.random.default_rng(7)
        x = rng.standard_normal((40, 4)).astype(np.float32)
        # perplexity 2 -> k = 6; 10 > k+1 duplicates of one point
        x[5:15] = x[5]
        row_ptr, cols, vals = _sparse_sym_p(x, perplexity=2.0)
        n = x.shape[0]
        assert row_ptr[-1] == len(cols) == len(vals)
        for i in range(n):
            c = cols[row_ptr[i]:row_ptr[i + 1]]
            assert i not in c                        # no self pair kept
        # a duplicate row's neighbor list is dominated by its clones
        c5 = set(cols[row_ptr[6]:row_ptr[7]])
        assert len(c5 & set(range(5, 15))) >= 5
        assert np.all(vals > 0)
        """Exact duplicates merge into depth-capped leaves whose COM holds
        several points; every point's own q~1 self term must still be
        excluded from Z and the forces (r4 advisor finding: only the leaf
        RESIDENT was excluded, inflating Z by ~1 per extra duplicate)."""
        from deeplearning4j_tpu.common import native_ops
        from deeplearning4j_tpu.plot.tsne import _np_repulsion
        if not native_ops.available():
            pytest.skip("native library unavailable")
        rng = np.random.default_rng(6)
        base = rng.standard_normal((30, 2)).astype(np.float32)
        # 8 exact copies of one point + 4 of another, shuffled in
        y = np.concatenate([base, np.tile(base[3], (7, 1)),
                            np.tile(base[11], (3, 1))]).astype(np.float32)
        rep_e, z_e = _np_repulsion(y)
        for theta in (0.0, 0.5):
            rep_n, z_n = native_ops.bh_repulsion(y, theta=theta)
            assert abs(z_n - z_e) / z_e < (1e-5 if theta == 0.0 else 0.02)
            np.testing.assert_allclose(
                rep_n, rep_e,
                atol=1e-4 if theta == 0.0 else 0.05 * np.abs(rep_e).max())

    @pytest.mark.slow
    def test_barnes_hut_clusters_stay_separated(self):
        from deeplearning4j_tpu.plot.tsne import BarnesHutTsne
        x, y = _blobs(n_per=40)
        emb = BarnesHutTsne(perplexity=12, max_iter=250, seed=2).fit(x)
        assert emb.shape == (120, 2)
        intra, inter = [], []
        for i in range(0, 120, 7):
            for j in range(i + 1, 120, 11):
                d = np.linalg.norm(emb[i] - emb[j])
                (intra if y[i] == y[j] else inter).append(d)
        assert np.mean(intra) * 2 < np.mean(inter)

    def test_auto_method_selection_and_builder_theta(self):
        from deeplearning4j_tpu.plot.tsne import _DENSE_MAX, Tsne
        t = (Tsne.Builder().theta(0.3).use_barnes_hut(True)
             .perplexity(5).set_max_iter(30).build())
        assert t.theta == 0.3 and t.method == "barnes_hut"
        assert Tsne().method == "auto" and _DENSE_MAX >= 1000
        with pytest.raises(ValueError):
            Tsne(n_components=3, method="barnes_hut").fit(
                np.zeros((10, 4)))

    @pytest.mark.slow
    def test_barnes_hut_medium_scale(self):
        """8k points (past _DENSE_MAX, the auto barnes_hut regime) embeds
        in well under a minute with separated clusters — the 50k headline
        run (59 s, inter/intra 9.1) is recorded in PERF.md."""
        from deeplearning4j_tpu.plot.tsne import Tsne
        rng = np.random.default_rng(0)
        C = 5
        centers = rng.standard_normal((C, 10)) * 8
        x = (centers[np.repeat(np.arange(C), 1600)]
             + rng.standard_normal((8000, 10))).astype(np.float32)
        t = Tsne(perplexity=30, max_iter=120, seed=1)
        emb = t.fit(x)
        assert emb.shape == (8000, 2)
        lab = np.repeat(np.arange(C), 1600)
        cents = np.stack([emb[lab == i].mean(0) for i in range(C)])
        intra = np.mean([np.linalg.norm(
            emb[lab == i] - cents[i], axis=1).mean() for i in range(C)])
        inter = np.mean([np.linalg.norm(cents[i] - cents[j])
                         for i in range(C) for j in range(i + 1, C)])
        assert inter / intra > 2.5

    def test_plot_tsv_export(self, tmp_path):
        x, y = _blobs(n_per=10)
        p = tmp_path / "coords.tsv"
        Tsne(max_iter=50, perplexity=5).plot(x, labels=y, path=str(p))
        lines = p.read_text().strip().split("\n")
        assert len(lines) == 30
        assert len(lines[0].split("\t")) == 3
