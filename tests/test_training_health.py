"""Training-health watchdog (ISSUE 2 acceptance criteria).

Every numerical failure mode is driven through the REAL code path with
`common.resilience.FaultInjector`'s `corrupt` action (NaN/Inf/value-poison
a payload at a named data-path site) — no mocks:

  (a) an injected NaN gradient is SKIPPED on device: params bit-identical
      to the pre-step values for that round, counters still aligned;
  (b) an injected divergence (finite-but-huge batch) triggers ROLLBACK to
      the last good round via the ShardedCheckpointManager seam, the run
      completes, and the post-rollback stream is bit-comparable to a run
      that never saw the poisoned batch;
  (c) N consecutive faults ABORT with a TrainingDivergedError diagnostic
      naming the offending rounds;
  (d) with the watchdog disabled, the fused step's lowered HLO is
      UNCHANGED from today (pinned, like the stats-emission contract);
  (e) the iterator boundary validates batches (shape/dtype/finiteness)
      with raise/skip/count policies, through the async staging path;
  (f) watchdog events reach the StatsListener storage (UI run health).
"""
import numpy as np
import pytest

from deeplearning4j_tpu import (InputType, MultiLayerNetwork,
                                NeuralNetConfiguration)
from deeplearning4j_tpu.common.health import (TrainingDivergedError,
                                              TrainingHealthPolicy)
from deeplearning4j_tpu.common.resilience import FaultInjector
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterators import (AsyncDataSetIterator,
                                                   BatchValidationError,
                                                   DataSetValidator,
                                                   ListDataSetIterator,
                                                   ValidatingDataSetIterator)
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer


def _net(seed=7):
    conf = (NeuralNetConfiguration.Builder().seed(seed)
            .updater("adam").learning_rate(0.01).list()
            .layer(0, DenseLayer(n_out=8, activation="relu"))
            .layer(1, OutputLayer(n_out=3, activation="softmax",
                                  loss_function="mcxent"))
            .set_input_type(InputType.feed_forward(5))
            .build())
    return MultiLayerNetwork(conf).init()


def _data(n=128, seed=0):
    r = np.random.default_rng(seed)
    x = r.random((n, 5)).astype(np.float32)
    w = r.random((5, 3))
    y = np.eye(3, dtype=np.float32)[np.argmax(x @ w, axis=1)]
    return DataSet(x, y)


def _reg_net(seed=7):
    """MSE regression head: loss and gradients scale with the feature
    magnitude, so a value-poisoned batch deterministically explodes the
    gradient norm (a softmax head can saturate to near-zero gradients on
    huge inputs, which would make divergence injection data-dependent)."""
    conf = (NeuralNetConfiguration.Builder().seed(seed)
            .updater("adam").learning_rate(0.01).list()
            .layer(0, DenseLayer(n_out=8, activation="identity"))
            .layer(1, OutputLayer(n_out=3, activation="identity",
                                  loss_function="mse"))
            .set_input_type(InputType.feed_forward(5))
            .build())
    return MultiLayerNetwork(conf).init()


def _nan_batch(n=16):
    return DataSet(np.full((n, 5), np.nan, np.float32),
                   np.eye(3, dtype=np.float32)[np.zeros(n, int)])


# ---------------------------------------------------------------------------
# FaultInjector `corrupt` action
# ---------------------------------------------------------------------------

def test_fault_injector_corrupt_poisons_copy_not_original():
    inj = FaultInjector(seed=0)
    inj.plan("d", on_call=1, corrupt="nan")
    arr = np.ones((4, 3), np.float32)
    assert inj.fire("d", payload=arr) is arr        # call 0: untouched
    out = inj.fire("d", payload=arr)                # call 1: poisoned COPY
    assert np.isnan(out).all()
    assert (arr == 1.0).all()                       # original never mutated
    assert inj.fired("d") == [("d", 1)]


def test_fault_injector_corrupt_variants_and_no_raise():
    inj = FaultInjector(seed=0)
    inj.plan("a", on_call=0, corrupt="inf")
    inj.plan("b", on_call=0, corrupt=42.5)
    a = inj.fire("a", payload=np.zeros(3, np.float32))  # no raise: the
    b = inj.fire("b", payload=np.zeros(3, np.float32))  # poison IS the fault
    assert np.isinf(a).all()
    assert (b == 42.5).all()
    # call-indexed and capped exactly like drop/delay/sever
    inj2 = FaultInjector(seed=0)
    inj2.plan("c", on_calls=[0, 2], corrupt=1.0)
    hits = [i for i in range(4)
            if (inj2.fire("c", payload=np.zeros(1)) != 0).any()]
    assert hits == [0, 2]


# ---------------------------------------------------------------------------
# (d) disabled watchdog: lowered HLO unchanged (the collect_acts contract)
# ---------------------------------------------------------------------------

def _mln_lowered(net, **kwargs):
    import jax
    batch = {"features": np.zeros((4, 5), np.float32),
             "labels": np.zeros((4, 3), np.float32),
             "fmask": None, "lmask": None, "iteration": np.float32(0),
             "rng": jax.random.PRNGKey(0), "carries": None}
    return jax.jit(net.make_raw_step(**kwargs)).lower(
        net._params, net._updater_state, net._model_state, batch).as_text()


def test_disabled_watchdog_hlo_unchanged_multilayer():
    net = _net()
    t_default = _mln_lowered(net)
    t_off = _mln_lowered(net, emit_health=False)
    t_on = _mln_lowered(net, emit_health=True)
    assert t_off == t_default          # disabled path == today's program
    assert "is_finite" not in t_default  # today's program has no sentinel
    assert "is_finite" in t_on and t_on != t_default


def test_disabled_watchdog_hlo_unchanged_computation_graph():
    import jax
    from deeplearning4j_tpu import ComputationGraph
    conf = (NeuralNetConfiguration.Builder().seed(3)
            .updater("sgd").learning_rate(0.1).graph_builder()
            .add_inputs("in")
            .add_layer("d", DenseLayer(n_out=6, activation="relu"), "in")
            .add_layer("out", OutputLayer(n_out=3, activation="softmax",
                                          loss_function="mcxent"), "d")
            .set_outputs("out")
            .set_input_types(InputType.feed_forward(5))
            .build())
    net = ComputationGraph(conf).init()
    batch = {"features": {"in": np.zeros((4, 5), np.float32)},
             "labels": [np.zeros((4, 3), np.float32)],
             "fmask": None, "lmask": None, "iteration": np.float32(0),
             "rng": jax.random.PRNGKey(0), "carries": None}

    def lower(**kw):
        return jax.jit(net.make_raw_step(**kw)).lower(
            net._params, net._updater_state, net._model_state,
            batch).as_text()

    t_default, t_off, t_on = lower(), lower(emit_health=False), \
        lower(emit_health=True)
    assert t_off == t_default
    assert "is_finite" not in t_default
    assert "is_finite" in t_on and t_on != t_default


# ---------------------------------------------------------------------------
# policy classification (host side)
# ---------------------------------------------------------------------------

def _h(score, grad_norm=1.0, finite=True):
    return {"score": score, "grad_norm": grad_norm, "all_finite": finite}


def test_policy_ema_spike_classification():
    pol = TrainingHealthPolicy(spike_zscore=4.0, ema_decay=0.5,
                               warmup_steps=5, max_consecutive_bad=10)
    for i in range(8):          # stable baseline around 1.0
        assert pol.observe(_h(1.0 + 0.01 * (i % 3)), i) == "ok"
    assert pol.observe(_h(100.0), 8) == "rollback"      # massive spike
    assert pol.counts["spikes"] == 1
    # the spike never entered the EMA: the next normal step is healthy
    assert pol.observe(_h(1.0), 9) == "ok"
    assert pol.consecutive_bad == 0


def test_policy_grad_norm_limit_and_rollback_degrade():
    pol = TrainingHealthPolicy(grad_norm_limit=10.0, rollback_on_spike=False,
                               max_consecutive_bad=10)
    assert pol.observe(_h(1.0, grad_norm=50.0), 0) == "spike"
    pol2 = TrainingHealthPolicy(grad_norm_limit=10.0)
    assert pol2.observe(_h(1.0, grad_norm=50.0), 0) == "rollback"


def test_policy_abort_after_n_consecutive_names_rounds():
    pol = TrainingHealthPolicy(max_consecutive_bad=3)
    assert pol.observe(_h(np.nan, finite=False), 4) == "skip"
    assert pol.observe(_h(np.nan, finite=False), 5) == "skip"
    assert pol.observe(_h(np.nan, finite=False), 6) == "abort"
    msg = pol.diagnose()
    assert "3 consecutive" in msg
    assert "[4, 5, 6]" in msg          # the offending rounds, by name
    assert pol.counts["aborts"] == 1


# ---------------------------------------------------------------------------
# (a) NaN gradient skipped on device — params bit-identical for that round
# ---------------------------------------------------------------------------

def test_injected_nan_batch_skipped_params_bit_identical():
    inj = FaultInjector(seed=0)
    inj.plan("data.batch", on_call=2, corrupt="nan")   # poison 3rd batch
    validator = DataSetValidator(policy="count", check_finite=False,
                                 fault_injector=inj)
    batches = list(_data(64, seed=1).batch_by(16))     # 4 batches
    it = ValidatingDataSetIterator(ListDataSetIterator(batches), validator)

    pol = TrainingHealthPolicy(max_consecutive_bad=5)
    net = _net(seed=11).training_health(pol)
    snaps = []

    class Snap:
        def iteration_done(self, model, iteration):
            snaps.append(model.params())

    net.add_listener(Snap())
    net.fit(it)

    assert len(inj.fired("data.batch")) == 1
    assert pol.counts == {"ok": 3, "skips": 1, "spikes": 0, "rollbacks": 0,
                          "aborts": 0, "validation_rejects": 0}
    # the poisoned round's update was withheld ON DEVICE: params after the
    # bad step are bit-identical to the pre-step values for that round
    np.testing.assert_array_equal(snaps[2], snaps[1])
    assert not np.array_equal(snaps[3], snaps[2])      # training resumed
    assert np.isfinite(net.params()).all()
    # bookkeeping stays ALIGNED across the skip: the host counter and the
    # device-resident loop counter advanced in lockstep
    assert net.conf.iteration_count == 4
    assert float(net._loop["iteration"]) == 4.0
    assert np.isfinite(float(net.score()))   # _score kept at last good


def test_skip_keeps_score_and_epoch_bookkeeping_consistent():
    pol = TrainingHealthPolicy(max_consecutive_bad=5)
    net = _net(seed=2).training_health(pol)
    net.fit(_data(32, seed=2))
    good_score = float(net.score())
    epochs = net.conf.epoch_count
    net.fit(_nan_batch())
    assert pol.counts["skips"] == 1
    assert float(net.score()) == good_score   # NaN never became the score
    assert net.conf.epoch_count == epochs     # fit(DataSet) is epoch-free


# ---------------------------------------------------------------------------
# (b/c) rollback + abort in the single-process fit loop
#       (ShardedCheckpointManager seam)
# ---------------------------------------------------------------------------

def test_fit_loop_rollback_via_checkpoint_seam(tmp_path):
    inj = FaultInjector(seed=0)
    inj.plan("data.batch", on_call=4, corrupt=500.0)   # finite divergence
    validator = DataSetValidator(policy="count", check_finite=False,
                                 fault_injector=inj)
    batches = list(_data(128, seed=3).batch_by(16))    # 8 batches
    it = ValidatingDataSetIterator(ListDataSetIterator(batches), validator)

    pol = TrainingHealthPolicy(grad_norm_limit=50.0, max_consecutive_bad=4)
    net = _reg_net(seed=4).training_health(pol,
                                           checkpoint_dir=tmp_path / "hk",
                                           checkpoint_every=2)
    net.fit(it)

    assert pol.counts["spikes"] == 1
    assert pol.counts["rollbacks"] == 1
    rb = [e for e in pol.events if e["kind"] == "rollback"]
    assert rb and rb[0]["restoredRound"] == 4  # last even (every=2) round
    # the spiked round rolled back and its batch was abandoned: 8 batches,
    # one consumed without surviving -> 7 applied iterations
    assert net.conf.iteration_count == 7
    assert float(net._loop["iteration"]) == 7.0
    assert np.isfinite(net.params()).all()


def test_fit_loop_abort_names_offending_rounds():
    pol = TrainingHealthPolicy(max_consecutive_bad=2)
    net = _net(seed=6).training_health(pol)
    net.fit(_data(32, seed=6))
    bad = ListDataSetIterator([_nan_batch(), _nan_batch(), _nan_batch()])
    with pytest.raises(TrainingDivergedError, match="offending rounds"):
        net.fit(bad)
    assert pol.counts["aborts"] == 1


# ---------------------------------------------------------------------------
# (b) ParallelWrapper divergence rollback: completes AND the post-rollback
#     stream is bit-comparable to a run that never saw the poisoned batch
# ---------------------------------------------------------------------------

def _wrapper(net, ckpt=None, inj=None, pol=None):
    from deeplearning4j_tpu.parallel import ParallelWrapper
    b = ParallelWrapper.Builder(net).workers(4)
    if ckpt is not None:
        b = b.checkpointing(str(ckpt))
    if inj is not None:
        b = b.fault_injector(inj)
    if pol is not None:
        b = b.health_policy(pol)
    return b.build()


def test_wrapper_rollback_completes_and_is_bit_comparable(tmp_path):
    batches = list(_data(128, seed=5).batch_by(16))    # 8 batches

    inj = FaultInjector(seed=0)
    inj.plan("wrapper.batch", on_call=5, corrupt=200.0)  # finite divergence
    pol = TrainingHealthPolicy(grad_norm_limit=50.0, max_consecutive_bad=4)
    net = _reg_net(seed=5)
    pw = _wrapper(net, ckpt=tmp_path / "ck", inj=inj, pol=pol)
    pw.fit(ListDataSetIterator(batches))               # completes

    assert pol.counts["spikes"] == 1
    assert pol.counts["rollbacks"] == 1
    rb = [e for e in pol.events if e["kind"] == "rollback"][0]
    assert rb["restoredRound"] == 5      # the last good round, by name
    assert net.conf.iteration_count == 7
    assert np.isfinite(net.params()).all()

    # bit-comparability bar (the PR 1 crash-resume standard): the rollback
    # restored rng AND counters, so the run equals one whose stream simply
    # never contained the poisoned batch
    ref = _reg_net(seed=5)
    _wrapper(ref).fit(ListDataSetIterator(batches[:5] + batches[6:]))
    assert ref.conf.iteration_count == net.conf.iteration_count
    np.testing.assert_array_equal(np.asarray(net.params()),
                                  np.asarray(ref.params()))


def test_wrapper_nan_round_skipped_params_identical(tmp_path):
    batches = list(_data(64, seed=8).batch_by(16))     # 4 batches
    inj = FaultInjector(seed=0)
    inj.plan("wrapper.batch", on_call=1, corrupt="nan")
    pol = TrainingHealthPolicy(max_consecutive_bad=4)
    net = _net(seed=8)
    pw = _wrapper(net, inj=inj, pol=pol)
    snaps = []

    class Snap:
        def iteration_done(self, model, iteration):
            snaps.append(model.params())

    net.add_listener(Snap())
    pw.fit(ListDataSetIterator(batches))
    assert pol.counts["skips"] == 1
    np.testing.assert_array_equal(snaps[1], snaps[0])  # round 2 withheld
    assert not np.array_equal(snaps[2], snaps[1])
    assert np.isfinite(net.params()).all()


def test_wrapper_consecutive_faults_abort_with_diagnostic(tmp_path):
    batches = list(_data(128, seed=9).batch_by(16))
    inj = FaultInjector(seed=0)
    inj.plan("wrapper.batch", on_calls=[2, 3], corrupt="nan")
    pol = TrainingHealthPolicy(max_consecutive_bad=2)
    net = _net(seed=9)
    pw = _wrapper(net, inj=inj, pol=pol)
    with pytest.raises(TrainingDivergedError, match="offending rounds"):
        pw.fit(ListDataSetIterator(batches))
    assert pol.counts["aborts"] == 1
    # the diagnostic names the offending rounds (1-based round numbers)
    assert "[3, 4]" in pol.diagnose()


def test_wrapper_rollback_without_checkpoint_degrades_to_count(tmp_path):
    batches = list(_data(64, seed=10).batch_by(16))
    inj = FaultInjector(seed=0)
    inj.plan("wrapper.batch", on_call=1, corrupt=200.0)
    pol = TrainingHealthPolicy(grad_norm_limit=50.0, max_consecutive_bad=4)
    net = _reg_net(seed=10)
    pw = _wrapper(net, inj=inj, pol=pol)     # no checkpointing configured
    pw.fit(ListDataSetIterator(batches))     # completes anyway
    assert pol.counts["spikes"] == 1
    assert pol.counts["rollbacks"] == 0      # no seam: counted, continued
    assert net.conf.iteration_count == 4


# ---------------------------------------------------------------------------
# TrainingMaster path (k-local-steps mode: per-step device skip inside the
# scan, round-level health, rollback through the master's checkpoint seam)
# ---------------------------------------------------------------------------

def _master(ckpt=None, inj=None, pol=None):
    from deeplearning4j_tpu.parallel import ParameterAveragingTrainingMaster
    b = (ParameterAveragingTrainingMaster.Builder(batch_size_per_worker=4)
         .workers(4).averaging_frequency(2).rdd_training_approach("direct"))
    if ckpt is not None:
        b = b.checkpoint_directory(str(ckpt))
    if inj is not None:
        b = b.fault_injector(inj)
    if pol is not None:
        b = b.health_policy(pol)
    return b.build()


def test_master_kstep_nan_skip_and_divergence_rollback(tmp_path):
    ds = _data(128, seed=12)        # 8 global batches -> 4 rounds of k=2

    # round 2 (batch idx 2) gets a NaN batch: skipped on device; round 4
    # (batch idx 6) diverges: rolled back through the MASTER's checkpoints
    inj = FaultInjector(seed=0)
    inj.plan("wrapper.batch", on_call=2, corrupt="nan")
    inj.plan("wrapper.batch", on_call=6, corrupt=300.0)
    pol = TrainingHealthPolicy(grad_norm_limit=50.0, max_consecutive_bad=4)
    net = _reg_net(seed=12)
    tm = _master(ckpt=tmp_path / "ck", inj=inj, pol=pol)
    tm.execute_training(net, ds)                       # completes

    # the poisoned global batch = one skipped LOCAL step on each of the
    # 4 devices; the round is PARTIAL (4/8 steps bad): counted without
    # escalating, round score stays finite, checkpoint cadence unbroken
    assert pol.counts["skips"] == 4
    # the partial round did not escalate: only the FINAL round's spike
    # contributes to the consecutive-bad streak
    assert pol.consecutive_bad == 1
    partial = [e for e in pol.events
               if e["kind"] == "skip" and "partial" in e["reason"]]
    assert partial and partial[0]["reason"].startswith("4/8")
    assert np.isfinite(partial[0]["score"])
    assert pol.counts["spikes"] == 1
    assert pol.counts["rollbacks"] == 1
    assert np.isfinite(net.params()).all()
    rb = [e for e in pol.events if e["kind"] == "rollback"][0]
    assert rb["restoredRound"] is not None


# ---------------------------------------------------------------------------
# (e) iterator-boundary batch validation
# ---------------------------------------------------------------------------

def test_validator_raise_skip_count_policies():
    good = _data(16, seed=0)
    bad = _nan_batch()

    with pytest.raises(BatchValidationError, match="non-finite"):
        DataSetValidator(policy="raise").validate(bad)

    pol = TrainingHealthPolicy()
    v = DataSetValidator(policy="skip", health_policy=pol)
    assert v.validate(bad) is None
    assert v.validate(good) is good
    assert (v.rejected, v.passed) == (1, 1)
    assert pol.counts["validation_rejects"] == 1

    v2 = DataSetValidator(policy="count")
    assert v2.validate(bad) is bad            # passes through, counted
    assert v2.rejected == 1


def test_validator_shape_and_dtype_checks():
    ds = _data(8, seed=0)
    with pytest.raises(BatchValidationError, match="feature shape"):
        DataSetValidator(policy="raise", feature_shape=(7,)).validate(ds)
    with pytest.raises(BatchValidationError, match="label shape"):
        DataSetValidator(policy="raise", label_shape=(5,)).validate(ds)
    with pytest.raises(BatchValidationError, match="dtype"):
        DataSetValidator(policy="raise", dtypes="iu").validate(ds)
    # misaligned labels
    mis = DataSet(np.zeros((8, 5), np.float32), np.zeros((4, 3), np.float32))
    with pytest.raises(BatchValidationError, match="disagrees"):
        DataSetValidator(policy="raise").validate(mis)
    # a clean batch passes all configured checks
    ok = DataSetValidator(policy="raise", feature_shape=(5,),
                          label_shape=(3,), dtypes="f").validate(ds)
    assert ok is ds


def test_validator_skip_works_through_async_staging():
    inj = FaultInjector(seed=0)
    inj.plan("data.batch", on_call=3, corrupt="nan")
    pol = TrainingHealthPolicy()
    v = DataSetValidator(policy="skip", fault_injector=inj,
                         health_policy=pol)
    batches = list(_data(96, seed=1).batch_by(16))     # 6 batches
    it = AsyncDataSetIterator(ListDataSetIterator(batches), validator=v,
                              device_put=False)
    seen = [it.next_batch() for _ in iter(lambda: it.has_next(), False)]
    assert len(seen) == 5                    # the poisoned batch vanished
    assert v.rejected == 1
    assert pol.counts["validation_rejects"] == 1
    assert all(np.isfinite(np.asarray(b.features)).all() for b in seen)


def test_validator_raise_surfaces_through_async_not_hangs():
    inj = FaultInjector(seed=0)
    inj.plan("data.batch", on_call=1, corrupt="inf")
    v = DataSetValidator(policy="raise", fault_injector=inj)
    batches = list(_data(64, seed=2).batch_by(16))
    it = AsyncDataSetIterator(ListDataSetIterator(batches), validator=v,
                              device_put=False)
    with pytest.raises(RuntimeError) as ei:
        while it.has_next():
            it.next_batch()
    assert isinstance(ei.value.__cause__, BatchValidationError)


# ---------------------------------------------------------------------------
# (f) watchdog events reach the StatsListener storage
# ---------------------------------------------------------------------------

def test_stats_listener_reports_run_health():
    from deeplearning4j_tpu.ui.stats import StatsListener
    from deeplearning4j_tpu.ui.storage import InMemoryStatsStorage

    storage = InMemoryStatsStorage()
    pol = TrainingHealthPolicy(max_consecutive_bad=5)
    net = _net(seed=13).training_health(pol)
    net.set_listeners(StatsListener(storage, session_id="health_s"))
    net.fit(_data(32, seed=13))
    net.fit(_nan_batch())

    updates = storage.get_all_updates("health_s")
    assert updates, "no reports reached storage"
    last = updates[-1]
    assert last["health"]["counts"]["skips"] == 1
    assert last["health"]["lastEvent"]["kind"] == "skip"
