"""Worker process for the multi-host test (test_multihost.py).

Each process: jax.distributed.initialize over CPU devices -> global mesh via
parallel/distributed.py -> ParallelWrapper allreduce steps with per-process
batch slices -> prints a params checksum. The test asserts both processes
stay bit-identical and match the single-process result — proving the
DCN-path code really executes (SURVEY.md §5.8; VERDICT round-1 item 4).

Usage: python tests/multihost_worker.py <proc_id> <nproc> <coordinator>
"""
import os
import sys

proc_id, nproc, coord = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=2")

import numpy as np  # noqa: E402

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

from deeplearning4j_tpu import (InputType, MultiLayerNetwork,  # noqa: E402
                                NeuralNetConfiguration)
from deeplearning4j_tpu.datasets.dataset import DataSet  # noqa: E402
from deeplearning4j_tpu.nn.conf.layers import (DenseLayer,  # noqa: E402
                                               OutputLayer)
from deeplearning4j_tpu.parallel import distributed  # noqa: E402
from deeplearning4j_tpu.parallel.parallel_wrapper import \
    ParallelWrapper  # noqa: E402


def main():
    ok = distributed.initialize(coord, nproc, proc_id)
    assert ok, "distributed.initialize returned False"
    assert jax.process_count() == nproc
    assert jax.device_count() == 2 * nproc       # 2 cpu devices per process
    assert len(jax.local_devices()) == 2

    mesh = distributed.global_mesh()             # all devices on "data"
    assert int(mesh.shape["data"]) == 2 * nproc

    conf = (NeuralNetConfiguration.Builder().seed(7).learning_rate(0.2)
            .updater("sgd").list()
            .layer(0, DenseLayer(n_out=16, activation="relu"))
            .layer(1, OutputLayer(n_out=3, activation="softmax",
                                  loss_function="mcxent"))
            .set_input_type(InputType.feed_forward(4)).build())
    net = MultiLayerNetwork(conf).init()

    # identical global data on every process; each feeds only its slice
    rng = np.random.default_rng(0)
    centers = rng.normal(0, 3, (3, 4))
    c = rng.integers(0, 3, 64)
    gx = (centers[c] + rng.normal(0, 0.5, (64, 4))).astype(np.float32)
    gy = np.eye(3, dtype=np.float32)[c]
    sl = distributed.process_local_batch_slice(64)
    local = DataSet(gx[sl], gy[sl])

    pw = ParallelWrapper.Builder(net).mesh(mesh).averaging_frequency(1).build()
    for _ in range(3):
        pw.fit(local)

    params = np.asarray(net.params(), np.float64)
    print(f"RESULT {proc_id} sum={params.sum():.10f} "
          f"score={float(net._score):.10f}", flush=True)


if __name__ == "__main__":
    main()
