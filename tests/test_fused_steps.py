"""Fused multi-step training (ISSUE 3 acceptance criteria).

The K-batches-per-dispatch fit loops (nn/fused.py, `net.fused_steps(K)`)
are pinned against the sequential single-step loops:

  (a) fused_steps=K is BIT-IDENTICAL to K sequential dispatches —
      params, updater state, model state, rng stream, iteration
      counters, score — for the batch loop, the TBPTT loop (carries
      threaded through the scan) and the ComputationGraph twins;
  (b) fused_steps=1 compiles HLO IDENTICAL to today's step (the
      collect_acts/emit_health pin style) and never builds a scan;
  (c) a ragged tail — K not dividing the epoch, or mixed batch shapes —
      falls back to single-step dispatches with an unchanged stream;
  (d) the training-health watchdog composes: per-inner-step health comes
      out as scan ys, the on-device gate_update skip works INSIDE the
      scan (counters aligned with sequential), a rollback landing
      mid-super-batch restores and replays the remaining staged batches
      (final state bit-identical to the sequential run), and the
      checkpoint cadence is counted in OPTIMIZER STEPS (groups clip at
      checkpoint boundaries, so round checkpoints don't stretch by K);
  (e) listeners see every optimizer step (per-step scores from the
      stacked report), not every dispatch.
"""
import numpy as np
import pytest

from deeplearning4j_tpu import (ComputationGraph, InputType,
                                MultiLayerNetwork, NeuralNetConfiguration)
from deeplearning4j_tpu.common.health import TrainingHealthPolicy
from deeplearning4j_tpu.common.resilience import FaultInjector
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterators import (DataSetValidator,
                                                   ListDataSetIterator,
                                                   ValidatingDataSetIterator)
from deeplearning4j_tpu.nn.conf.layers import (DenseLayer, GravesLSTM,
                                               OutputLayer, RnnOutputLayer)


def _net(seed=7):
    conf = (NeuralNetConfiguration.Builder().seed(seed)
            .updater("adam").learning_rate(0.01).list()
            .layer(0, DenseLayer(n_out=8, activation="relu"))
            .layer(1, OutputLayer(n_out=3, activation="softmax",
                                  loss_function="mcxent"))
            .set_input_type(InputType.feed_forward(5))
            .build())
    return MultiLayerNetwork(conf).init()


def _reg_net(seed=7):
    """MSE head: a value-poisoned batch deterministically explodes the
    gradient norm (see test_training_health._reg_net)."""
    conf = (NeuralNetConfiguration.Builder().seed(seed)
            .updater("adam").learning_rate(0.01).list()
            .layer(0, DenseLayer(n_out=8, activation="identity"))
            .layer(1, OutputLayer(n_out=3, activation="identity",
                                  loss_function="mse"))
            .set_input_type(InputType.feed_forward(5))
            .build())
    return MultiLayerNetwork(conf).init()


def _rnn_net(seed=7):
    conf = (NeuralNetConfiguration.Builder().seed(seed).data_type("float32")
            .updater("sgd").learning_rate(0.05).list()
            .layer(0, GravesLSTM(n_out=12, activation="tanh"))
            .layer(1, RnnOutputLayer(n_out=4, activation="softmax",
                                     loss_function="mcxent"))
            .backprop_type("tbptt").t_bptt_forward_length(4)
            .set_input_type(InputType.recurrent(6))
            .build())
    return MultiLayerNetwork(conf).init()


def _cg(seed=3):
    conf = (NeuralNetConfiguration.Builder().seed(seed)
            .updater("adam").learning_rate(0.01).graph_builder()
            .add_inputs("in")
            .add_layer("d", DenseLayer(n_out=6, activation="relu"), "in")
            .add_layer("out", OutputLayer(n_out=3, activation="softmax",
                                          loss_function="mcxent"), "d")
            .set_outputs("out")
            .set_input_types(InputType.feed_forward(5))
            .build())
    return ComputationGraph(conf).init()


def _data(n=96, seed=0):
    r = np.random.default_rng(seed)
    x = r.random((n, 5)).astype(np.float32)
    w = r.random((5, 3))
    y = np.eye(3, dtype=np.float32)[np.argmax(x @ w, axis=1)]
    return DataSet(x, y)


def _assert_training_state_equal(a, b, iterations):
    import jax
    np.testing.assert_array_equal(a.params(), b.params())
    for x, y in zip(jax.tree.leaves(a._updater_state),
                    jax.tree.leaves(b._updater_state)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))
    assert a.conf.iteration_count == b.conf.iteration_count == iterations
    assert (float(a._loop["iteration"]) == float(b._loop["iteration"])
            == float(iterations))
    np.testing.assert_array_equal(np.asarray(a._loop["rng"]),
                                  np.asarray(b._loop["rng"]))
    assert float(a._score) == float(b._score)


def _valit(batches, call, poison):
    """Iterator that poisons the features of batch `call` via the
    injector's data.batch site (the PR 2 corruption seam)."""
    inj = FaultInjector(seed=0)
    inj.plan("data.batch", on_call=call, corrupt=poison)
    v = DataSetValidator(policy="count", check_finite=False,
                         fault_injector=inj)
    return ValidatingDataSetIterator(ListDataSetIterator(batches), v)


# ---------------------------------------------------------------------------
# (b) fused_steps=1: HLO identical to today's step; K>1 builds a scan
# ---------------------------------------------------------------------------

def _lower_fit_step(net):
    import jax
    step = net._make_step()
    loop = {"iteration": np.float32(0), "rng": jax.random.PRNGKey(0)}
    return step.lower(net._params, net._updater_state, net._model_state,
                      loop, np.zeros((4, 5), np.float32),
                      np.zeros((4, 3), np.float32), None, None).as_text()


def test_fused_steps_1_hlo_identical():
    base = _lower_fit_step(_net())
    armed = _lower_fit_step(_net().fused_steps(1))
    assert armed == base
    # the K>1 program is a genuine scan (lowers to a while loop) and the
    # single-step program is not
    import jax
    net = _net().fused_steps(4)
    from deeplearning4j_tpu.nn import fused as F
    raw = net.make_raw_step()

    def prog(params, ustate, state, loop, batch_list):
        return F.scan_batches(raw, params, ustate, state, loop, batch_list)

    batch = {"features": np.zeros((4, 5), np.float32),
             "labels": np.zeros((4, 3), np.float32),
             "fmask": None, "lmask": None}
    loop = {"iteration": np.float32(0), "rng": jax.random.PRNGKey(0)}
    fused_txt = jax.jit(prog).lower(
        net._params, net._updater_state, net._model_state, loop,
        (batch,) * 4).as_text()
    # the scan adds a while loop beyond whatever the single-step program
    # already carries (the threefry rng split lowers to one)
    assert (fused_txt.count("stablehlo.while")
            > base.count("stablehlo.while"))


# ---------------------------------------------------------------------------
# (a) bit-identical to sequential dispatches
# ---------------------------------------------------------------------------

def test_fused_batch_loop_bit_identical():
    batches = list(_data(96, seed=1).batch_by(16))     # 6 batches, K=3
    a = _net(3)
    a.fit(ListDataSetIterator(batches))
    b = _net(3).fused_steps(3)
    b.fit(ListDataSetIterator(batches))
    _assert_training_state_equal(a, b, 6)


def test_fused_multi_epoch_bit_identical():
    batches = list(_data(96, seed=2).batch_by(16))
    a = _net(5)
    a.fit(ListDataSetIterator(batches), num_epochs=2)
    b = _net(5).fused_steps(3)
    b.fit(ListDataSetIterator(batches), num_epochs=2)
    _assert_training_state_equal(a, b, 12)


# ---------------------------------------------------------------------------
# (c) ragged tails fall back to single-step dispatches
# ---------------------------------------------------------------------------

def test_fused_ragged_tail_falls_back():
    # 7 batches with K=3 -> two fused groups + 1 single; last batch is
    # also SHORTER (112 % 16 = 0, so force a short tail by slicing)
    ds = _data(104, seed=3)                  # 6x16 + one 8-row tail
    batches = list(ds.batch_by(16))
    assert batches[-1].num_examples() == 8
    a = _net(4)
    a.fit(ListDataSetIterator(batches))
    b = _net(4).fused_steps(3)
    b.fit(ListDataSetIterator(batches))
    _assert_training_state_equal(a, b, 7)


def test_fused_k_larger_than_epoch_falls_back():
    batches = list(_data(32, seed=4).batch_by(16))     # 2 batches, K=8
    a = _net(6)
    a.fit(ListDataSetIterator(batches))
    b = _net(6).fused_steps(8)
    b.fit(ListDataSetIterator(batches))
    _assert_training_state_equal(a, b, 2)


# ---------------------------------------------------------------------------
# (a) TBPTT: segments fused per dispatch, carries threaded through scan
# ---------------------------------------------------------------------------

def test_fused_tbptt_bit_identical_with_ragged_tail():
    r = np.random.default_rng(0)
    B, T, F, C = 8, 18, 6, 4       # L=4 -> 4 full segments + short tail
    x = r.random((B, T, F)).astype(np.float32)
    y = np.eye(C, dtype=np.float32)[r.integers(0, C, (B, T))]
    ds = DataSet(x, y)
    a = _rnn_net(3)
    a.fit(ds)
    b = _rnn_net(3).fused_steps(3)
    b.fit(ds)
    _assert_training_state_equal(a, b, 5)    # 4 full + 1 tail segment


# ---------------------------------------------------------------------------
# ComputationGraph twins
# ---------------------------------------------------------------------------

def _cg_rnn(seed=5):
    conf = (NeuralNetConfiguration.Builder().seed(seed)
            .updater("adam").learning_rate(0.01)
            .graph_builder()
            .add_inputs("in")
            .add_layer("lstm", GravesLSTM(n_out=8, activation="tanh"), "in")
            .add_layer("out", RnnOutputLayer(n_out=3, activation="softmax",
                                             loss_function="mcxent"), "lstm")
            .set_outputs("out")
            .set_input_types(InputType.recurrent(4))
            .backprop_type("tbptt").t_bptt_forward_length(5)
            .build())
    return ComputationGraph(conf).init()


def test_cg_fused_tbptt_bit_identical():
    from deeplearning4j_tpu.datasets.dataset import MultiDataSet
    r = np.random.default_rng(0)
    x = r.random((2, 20, 4)).astype(np.float32)     # L=5 -> 4 segments
    y = np.eye(3, dtype=np.float32)[r.integers(0, 3, (2, 20))]
    a = _cg_rnn(5)
    a.fit(MultiDataSet([x], [y]))
    b = _cg_rnn(5).fused_steps(4)
    b.fit(MultiDataSet([x], [y]))
    np.testing.assert_array_equal(a.params(), b.params())
    assert a.conf.iteration_count == b.conf.iteration_count == 4
    assert float(a._score) == float(b._score)


def test_cg_fused_batch_loop_bit_identical():
    import jax
    batches = list(_data(96, seed=5).batch_by(16))
    a = _cg(3)
    a.fit(ListDataSetIterator(batches))
    b = _cg(3).fused_steps(3)
    b.fit(ListDataSetIterator(batches))
    np.testing.assert_array_equal(a.params(), b.params())
    for x, y in zip(jax.tree.leaves(a._updater_state),
                    jax.tree.leaves(b._updater_state)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))
    assert a.conf.iteration_count == b.conf.iteration_count == 6
    assert float(a._score) == float(b._score)


# ---------------------------------------------------------------------------
# (d) health watchdog composition
# ---------------------------------------------------------------------------

def test_fused_nan_skip_inside_scan_counters_aligned():
    batches = list(_data(128, seed=6).batch_by(16))    # 8 batches
    pol_a = TrainingHealthPolicy(max_consecutive_bad=5)
    a = _net(11).training_health(pol_a)
    a.fit(_valit(batches, 2, "nan"))
    pol_b = TrainingHealthPolicy(max_consecutive_bad=5)
    b = _net(11).fused_steps(4).training_health(pol_b)
    b.fit(_valit(batches, 2, "nan"))
    # the poisoned step was skipped ON DEVICE inside the scan; host
    # counters classified the stacked report step-by-step
    assert pol_b.counts["skips"] == 1 and pol_b.counts["ok"] == 7
    assert pol_a.counts == pol_b.counts
    np.testing.assert_array_equal(a.params(), b.params())
    assert b.conf.iteration_count == 8
    assert float(b._loop["iteration"]) == 8.0


def test_fused_rollback_mid_super_batch(tmp_path):
    batches = list(_data(128, seed=7).batch_by(16))    # 8 batches
    pol_a = TrainingHealthPolicy(grad_norm_limit=50.0,
                                 max_consecutive_bad=4)
    a = _reg_net(4).training_health(pol_a, checkpoint_dir=tmp_path / "a",
                                    checkpoint_every=2)
    a.fit(_valit(batches, 4, 500.0))
    pol_b = TrainingHealthPolicy(grad_norm_limit=50.0,
                                 max_consecutive_bad=4)
    b = _reg_net(4).fused_steps(4).training_health(
        pol_b, checkpoint_dir=tmp_path / "b", checkpoint_every=2)
    b.fit(_valit(batches, 4, 500.0))
    # divergence at optimizer step 4 (inner step of a fused group):
    # restore + replay of the remaining staged batches == sequential
    assert pol_b.counts["spikes"] == 1
    assert pol_b.counts["rollbacks"] == 1
    assert pol_a.counts == pol_b.counts
    np.testing.assert_array_equal(a.params(), b.params())
    assert a.conf.iteration_count == b.conf.iteration_count == 7


def test_fused_checkpoint_cadence_in_optimizer_steps(tmp_path):
    """checkpoint_every=2 with fused_steps=8: groups clip at checkpoint
    boundaries, so the manager holds the SAME step labels as the
    sequential run — the cadence is counted in optimizer steps and never
    silently stretches by K."""
    batches = list(_data(128, seed=8).batch_by(16))    # 8 batches
    nets = {}
    for name, k in (("seq", 1), ("fused", 8)):
        pol = TrainingHealthPolicy(max_consecutive_bad=5)
        n = _net(9).fused_steps(k).training_health(
            pol, checkpoint_dir=tmp_path / name, checkpoint_every=2,
            keep_checkpoints=16)
        n.fit(ListDataSetIterator(batches))
        nets[name] = n
    seq_steps = nets["seq"]._health_ckpt.steps()
    fused_steps = nets["fused"]._health_ckpt.steps()
    assert seq_steps == fused_steps == [2, 4, 6, 8]
    np.testing.assert_array_equal(nets["seq"].params(),
                                  nets["fused"].params())


def test_fused_abort_raises_like_sequential():
    from deeplearning4j_tpu.common.health import TrainingDivergedError
    bad = DataSet(np.full((16, 5), np.nan, np.float32),
                  np.eye(3, dtype=np.float32)[np.zeros(16, int)])
    pol = TrainingHealthPolicy(max_consecutive_bad=2)
    net = _net(10).fused_steps(4).training_health(pol)
    net.fit(_data(32, seed=9))
    with pytest.raises(TrainingDivergedError, match="offending rounds"):
        net.fit(ListDataSetIterator([bad] * 4))
    assert pol.counts["aborts"] == 1


# ---------------------------------------------------------------------------
# (e) listeners see every optimizer step with its own score
# ---------------------------------------------------------------------------

def test_fused_listeners_see_every_step():
    batches = list(_data(96, seed=10).batch_by(16))

    class Recorder:
        def __init__(self):
            self.iters = []
            self.scores = []

        def iteration_done(self, model, iteration):
            self.iters.append(iteration)
            self.scores.append(float(model.score()))

    rec_a, rec_b = Recorder(), Recorder()
    a = _net(12).set_listeners(rec_a)
    a.fit(ListDataSetIterator(batches))
    b = _net(12).fused_steps(3).set_listeners(rec_b)
    b.fit(ListDataSetIterator(batches))
    assert rec_a.iters == rec_b.iters == list(range(6))
    assert rec_a.scores == rec_b.scores   # per-step, from the stacked ys
