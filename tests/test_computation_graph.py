"""ComputationGraph tests — mirrors reference test strategy (SURVEY.md §4):
gradient checks through every vertex type (GradientCheckTestsComputationGraph),
config serde round-trips, convergence, multi-input/multi-output."""
import numpy as np
import pytest

from deeplearning4j_tpu import (ComputationGraph,
                                ComputationGraphConfiguration, InputType,
                                NeuralNetConfiguration)
from deeplearning4j_tpu.datasets.dataset import DataSet, MultiDataSet
from deeplearning4j_tpu.gradientcheck.gradient_check_util import check_gradients
from deeplearning4j_tpu.nn.conf.graph_vertices import (
    DuplicateToTimeSeriesVertex, ElementWiseVertex, L2NormalizeVertex,
    L2Vertex, LastTimeStepVertex, MergeVertex, ScaleVertex, StackVertex,
    SubsetVertex, UnstackVertex)
from deeplearning4j_tpu.nn.conf.layers import (DenseLayer, GravesLSTM,
                                               OutputLayer, RnnOutputLayer)


def _rng():
    return np.random.default_rng(12345)


def _xy(n=8, nin=4, nout=3):
    r = _rng()
    x = r.random((n, nin)).astype(np.float64)
    y = np.eye(nout, dtype=np.float64)[r.integers(0, nout, n)]
    return x, y


def _gb(seed=42):
    return (NeuralNetConfiguration.Builder().seed(seed)
            .data_type("float64").updater("sgd").learning_rate(0.1)
            .graph_builder())


class TestGraphBuilding:
    def test_topological_sort_and_cycle_detection(self):
        conf = (_gb()
                .add_inputs("in")
                .add_layer("d1", DenseLayer(n_out=5, activation="tanh"), "in")
                .add_layer("d2", DenseLayer(n_out=5, activation="tanh"), "in")
                .add_vertex("merge", MergeVertex(), "d1", "d2")
                .add_layer("out", OutputLayer(n_out=3, activation="softmax",
                                              loss_function="mcxent"), "merge")
                .set_outputs("out")
                .set_input_types(InputType.feed_forward(4))
                .build())
        order = conf.topological_order
        assert order.index("merge") > order.index("d1")
        assert order.index("merge") > order.index("d2")
        assert order.index("out") > order.index("merge")
        # merge output feeds out: nIn inferred as 10
        assert conf.vertices["out"].conf.n_in == 10

    def test_cycle_raises(self):
        gb = (_gb().add_inputs("in")
              .add_layer("a", DenseLayer(n_in=4, n_out=4), "b")
              .add_layer("b", DenseLayer(n_in=4, n_out=4), "a")
              .set_outputs("a"))
        with pytest.raises(ValueError, match="[Cc]ycle"):
            gb.build()

    def test_unknown_input_raises(self):
        gb = (_gb().add_inputs("in")
              .add_layer("a", DenseLayer(n_in=4, n_out=4), "nope")
              .set_outputs("a"))
        with pytest.raises(ValueError, match="unknown input"):
            gb.build()

    def test_json_round_trip(self):
        conf = (_gb()
                .add_inputs("in")
                .add_layer("d1", DenseLayer(n_out=5, activation="relu"), "in")
                .add_vertex("scale", ScaleVertex(scale_factor=0.5), "d1")
                .add_layer("out", OutputLayer(n_out=3, activation="softmax",
                                              loss_function="mcxent"), "scale")
                .set_outputs("out")
                .set_input_types(InputType.feed_forward(4))
                .build())
        js = conf.to_json()
        conf2 = ComputationGraphConfiguration.from_json(js)
        assert conf2.topological_order == conf.topological_order
        assert conf2.vertices["scale"].conf.scale_factor == 0.5
        assert conf2.vertices["out"].conf.n_in == 5
        # weights transfer across round trip
        net = ComputationGraph(conf).init()
        net2 = ComputationGraph(conf2).init()
        net2.set_params(net.params())
        x, y = _xy()
        o1 = np.asarray(net.output(x)[0])
        o2 = np.asarray(net2.output(x)[0])
        assert np.allclose(o1, o2)


class TestGraphGradients:
    @pytest.mark.slow
    def test_gradcheck_merge_elementwise(self):
        x, y = _xy()
        for vertex in (MergeVertex(), ElementWiseVertex(op="add"),
                       ElementWiseVertex(op="subtract"),
                       ElementWiseVertex(op="product"),
                       ElementWiseVertex(op="average"),
                       ElementWiseVertex(op="max")):
            conf = (_gb()
                    .add_inputs("in")
                    .add_layer("d1", DenseLayer(n_out=5, activation="tanh"), "in")
                    .add_layer("d2", DenseLayer(n_out=5, activation="tanh"), "in")
                    .add_vertex("v", vertex, "d1", "d2")
                    .add_layer("out", OutputLayer(n_out=3, activation="softmax",
                                                  loss_function="mcxent"), "v")
                    .set_outputs("out")
                    .set_input_types(InputType.feed_forward(4))
                    .build())
            net = ComputationGraph(conf).init()
            assert check_gradients(net, x, y, max_rel_error=1e-4), vertex

    def test_gradcheck_subset_scale_l2norm(self):
        x, y = _xy()
        for vname, vertex in (("subset", SubsetVertex(from_idx=1, to_idx=3)),
                              ("scale", ScaleVertex(scale_factor=2.0)),
                              ("l2n", L2NormalizeVertex())):
            conf = (_gb()
                    .add_inputs("in")
                    .add_layer("d1", DenseLayer(n_out=5, activation="tanh"), "in")
                    .add_vertex("v", vertex, "d1")
                    .add_layer("out", OutputLayer(n_out=3, activation="softmax",
                                                  loss_function="mcxent"), "v")
                    .set_outputs("out")
                    .set_input_types(InputType.feed_forward(4))
                    .build())
            net = ComputationGraph(conf).init()
            assert check_gradients(net, x, y, max_rel_error=1e-4), vname

    def test_gradcheck_stack_unstack(self):
        x, y = _xy()
        conf = (_gb()
                .add_inputs("in")
                .add_layer("d1", DenseLayer(n_out=5, activation="tanh"), "in")
                .add_layer("d2", DenseLayer(n_out=5, activation="tanh"), "in")
                .add_vertex("stack", StackVertex(), "d1", "d2")
                .add_layer("shared", DenseLayer(n_out=5, activation="tanh"),
                           "stack")
                .add_vertex("u0", UnstackVertex(from_idx=0, stack_size=2),
                            "shared")
                .add_vertex("u1", UnstackVertex(from_idx=1, stack_size=2),
                            "shared")
                .add_vertex("sum", ElementWiseVertex(op="add"), "u0", "u1")
                .add_layer("out", OutputLayer(n_out=3, activation="softmax",
                                              loss_function="mcxent"), "sum")
                .set_outputs("out")
                .set_input_types(InputType.feed_forward(4))
                .build())
        net = ComputationGraph(conf).init()
        assert check_gradients(net, x, y, max_rel_error=1e-4)

    def test_gradcheck_l2_vertex(self):
        x, y = _xy(nout=1)
        y = _rng().random((8, 1)).astype(np.float64)
        conf = (_gb()
                .add_inputs("in")
                .add_layer("a", DenseLayer(n_out=5, activation="tanh"), "in")
                .add_layer("b", DenseLayer(n_out=5, activation="tanh"), "in")
                .add_vertex("dist", L2Vertex(), "a", "b")
                .add_layer("out", OutputLayer(n_out=1, activation="identity",
                                              loss_function="mse"), "dist")
                .set_outputs("out")
                .set_input_types(InputType.feed_forward(4))
                .build())
        net = ComputationGraph(conf).init()
        assert check_gradients(net, x, y, max_rel_error=1e-4)

    def test_gradcheck_rnn_vertices(self):
        r = _rng()
        B, T, F = 4, 5, 3
        x = r.random((B, T, F)).astype(np.float64)
        y = np.eye(2, dtype=np.float64)[r.integers(0, 2, B)]
        conf = (_gb()
                .add_inputs("in")
                .add_layer("lstm", GravesLSTM(n_out=6, activation="tanh"), "in")
                .add_vertex("last", LastTimeStepVertex(), "lstm")
                .add_layer("out", OutputLayer(n_out=2, activation="softmax",
                                              loss_function="mcxent"), "last")
                .set_outputs("out")
                .set_input_types(InputType.recurrent(F))
                .build())
        net = ComputationGraph(conf).init()
        assert check_gradients(net, x, y, max_rel_error=1e-4, subset=60)

    @pytest.mark.slow
    def test_gradcheck_duplicate_to_timeseries(self):
        r = _rng()
        B, T, F = 4, 5, 3
        x_static = r.random((B, 4)).astype(np.float64)
        x_seq = r.random((B, T, F)).astype(np.float64)
        y = np.zeros((B, T, 2), np.float64)
        y[np.arange(B)[:, None], np.arange(T)[None, :],
          r.integers(0, 2, (B, T))] = 1.0
        conf = (_gb()
                .add_inputs("stat", "seq")
                .add_layer("emb", DenseLayer(n_out=3, activation="tanh"), "stat")
                .add_vertex("dup", DuplicateToTimeSeriesVertex(), "emb", "seq")
                .add_vertex("cat", MergeVertex(), "seq", "dup")
                .add_layer("lstm", GravesLSTM(n_out=5, activation="tanh"), "cat")
                .add_layer("out", RnnOutputLayer(n_out=2, activation="softmax",
                                                 loss_function="mcxent"), "lstm")
                .set_outputs("out")
                .set_input_types(InputType.feed_forward(4),
                                 InputType.recurrent(F))
                .build())
        net = ComputationGraph(conf).init()
        assert check_gradients(net, [x_static, x_seq], y,
                               max_rel_error=1e-4, subset=60)

    def test_gradcheck_multi_output(self):
        r = _rng()
        x = r.random((8, 4)).astype(np.float64)
        y1 = np.eye(3, dtype=np.float64)[r.integers(0, 3, 8)]
        y2 = r.random((8, 2)).astype(np.float64)
        conf = (_gb()
                .add_inputs("in")
                .add_layer("trunk", DenseLayer(n_out=6, activation="tanh"), "in")
                .add_layer("cls", OutputLayer(n_out=3, activation="softmax",
                                              loss_function="mcxent"), "trunk")
                .add_layer("reg", OutputLayer(n_out=2, activation="identity",
                                              loss_function="mse"), "trunk")
                .set_outputs("cls", "reg")
                .set_input_types(InputType.feed_forward(4))
                .build())
        net = ComputationGraph(conf).init()
        assert check_gradients(net, x, [y1, y2], max_rel_error=1e-4)


class TestGraphTraining:
    def test_fit_converges_xor(self):
        x = np.array([[0, 0], [0, 1], [1, 0], [1, 1]], np.float32)
        y = np.array([[1, 0], [0, 1], [0, 1], [1, 0]], np.float32)
        conf = (NeuralNetConfiguration.Builder().seed(7)
                .updater("adam").learning_rate(0.05)
                .graph_builder()
                .add_inputs("in")
                .add_layer("h", DenseLayer(n_out=8, activation="tanh"), "in")
                .add_layer("out", OutputLayer(n_out=2, activation="softmax",
                                              loss_function="mcxent"), "h")
                .set_outputs("out")
                .set_input_types(InputType.feed_forward(2))
                .build())
        net = ComputationGraph(conf).init()
        mds = MultiDataSet([x], [y])
        for _ in range(300):
            net.fit(mds)
        ev = net.evaluate(mds)
        assert ev.accuracy() == 1.0
        assert net.score() < 0.2

    def test_fit_dataset_and_score(self):
        x, y = _xy(16)
        conf = (_gb()
                .add_inputs("in")
                .add_layer("h", DenseLayer(n_out=8, activation="relu"), "in")
                .add_layer("out", OutputLayer(n_out=3, activation="softmax",
                                              loss_function="mcxent"), "h")
                .set_outputs("out")
                .set_input_types(InputType.feed_forward(4))
                .build())
        net = ComputationGraph(conf).init()
        s0 = net.score(DataSet(x, y))
        for _ in range(50):
            net.fit(DataSet(x, y))
        assert net.score(DataSet(x, y)) < s0

    def test_multi_input_output_training(self):
        r = _rng()
        xa = r.random((16, 3)).astype(np.float32)
        xb = r.random((16, 5)).astype(np.float32)
        y1 = np.eye(2, dtype=np.float32)[r.integers(0, 2, 16)]
        y2 = r.random((16, 1)).astype(np.float32)
        conf = (NeuralNetConfiguration.Builder().seed(3)
                .updater("adam").learning_rate(0.01)
                .graph_builder()
                .add_inputs("a", "b")
                .add_layer("da", DenseLayer(n_out=4, activation="relu"), "a")
                .add_layer("db", DenseLayer(n_out=4, activation="relu"), "b")
                .add_vertex("m", MergeVertex(), "da", "db")
                .add_layer("cls", OutputLayer(n_out=2, activation="softmax",
                                              loss_function="mcxent"), "m")
                .add_layer("reg", OutputLayer(n_out=1, activation="identity",
                                              loss_function="mse"), "m")
                .set_outputs("cls", "reg")
                .set_input_types(InputType.feed_forward(3),
                                 InputType.feed_forward(5))
                .build())
        net = ComputationGraph(conf).init()
        mds = MultiDataSet([xa, xb], [y1, y2])
        s0 = net.score(mds)
        for _ in range(50):
            net.fit(mds)
        assert net.score(mds) < s0
        outs = net.output([xa, xb])
        assert np.asarray(outs[0]).shape == (16, 2)
        assert np.asarray(outs[1]).shape == (16, 1)


class TestGraphAsyncFit:
    """r5: ComputationGraph.fit auto-wraps plain iterators in async
    prefetch (reference AsyncMultiDataSetIterator role) with the bf16
    feature wire for bf16 models — including DataSetIterator
    implementations that yield MultiDataSets (per-batch dispatch)."""

    def _conf(self, dt="float32"):
        b = (NeuralNetConfiguration.Builder().seed(9)
             .updater("sgd").learning_rate(0.05))
        if dt != "float32":
            b = b.data_type(dt)
        return (b.graph_builder()
                .add_inputs("in")
                .add_layer("h", DenseLayer(n_out=8, activation="relu"), "in")
                .add_layer("out", OutputLayer(n_out=3, activation="softmax",
                                              loss_function="mcxent"), "h")
                .set_outputs("out")
                .set_input_types(InputType.feed_forward(4))
                .build())

    def test_plain_dataset_iterator_trains_and_bf16_wire_bit_identical(self):
        from deeplearning4j_tpu.datasets.iterators import (
            ArraysDataSetIterator, AsyncDataSetIterator)
        x, y = _xy(32)
        a = ComputationGraph(self._conf("bfloat16")).init()
        a.fit(ArraysDataSetIterator((x, y), batch_size=16), num_epochs=3)
        b = ComputationGraph(self._conf("bfloat16")).init()
        b.fit(AsyncDataSetIterator(                 # explicit f32 wire
            ArraysDataSetIterator((x, y), batch_size=16)), num_epochs=3)
        np.testing.assert_array_equal(np.asarray(a.params(), np.float32),
                                      np.asarray(b.params(), np.float32))

    def test_iterator_yielding_multidatasets_dispatches(self):
        from deeplearning4j_tpu.datasets.iterators import (
            ExistingDataSetIterator)
        x, y = _xy(24)
        batches = [MultiDataSet([x[i:i + 8]], [y[i:i + 8]])
                   for i in range(0, 24, 8)]
        net = ComputationGraph(self._conf()).init()
        s0 = net.score(DataSet(x, y))
        net.fit(ExistingDataSetIterator(batches), num_epochs=8)
        assert net.score(DataSet(x, y)) < s0
