"""Fleet observability plane pins (ISSUE 12 acceptance criteria).

  (a) Federation correctness: counters SUM exactly; merged histogram
      quantiles equal pooled-sample-histogram quantiles (bucket-wise
      merge IS the pooled histogram); per-instance gauges are never
      averaged into counters; merging N copies of one snapshot scales
      counters by N and leaves quantiles fixed; a Prometheus text
      scrape federates identically to the in-process kind-snapshot.
  (b) Cross-process trace stitching: a request migrated between two
      NAMED server instances yields ONE merged Perfetto-loadable trace
      with both instances' spans under the SAME trace id on distinct
      process groups, span order consistent with the clock_sync anchor
      alignment.
  (c) AutoscaleSignal: seeded two-regime synthetic traces produce
      scale_up only in the shed-accruing/service-not-rising regime,
      hold below the knee and in the queue-bound (service-rising)
      regime, scale_down only at idle-low occupancy, and hysteresis
      prevents single-window flapping.
  (d) Zero-added-dispatch: federating a serving fleet's metrics and
      propagating trace context add ZERO device dispatches (the PR 6
      dispatch-counter A/B), and obs/fleet.py never imports jax/numpy
      (structural, alongside the package-wide scan in test_obs).
"""
import json
import random
import time

import pytest

from deeplearning4j_tpu.models.zoo.transformer import TransformerLM
from deeplearning4j_tpu.obs import Tracer
from deeplearning4j_tpu.obs.fleet import (AutoscaleSignal, FleetView,
                                          merge_traces,
                                          parse_prometheus_text)
from deeplearning4j_tpu.obs.registry import (Histogram, MetricsRegistry,
                                             bucket_quantile)
from deeplearning4j_tpu.serving import (ContinuousDecodeServer,
                                        RequestMigratedError,
                                        ServingMetrics)


def _lm(seed=3):
    return TransformerLM(64, d_model=16, n_heads=2, n_layers=1,
                         max_len=64, seed=seed)


def _paged(lm, **kw):
    kw.setdefault("slots", 2)
    kw.setdefault("prompt_buckets", (8,))
    kw.setdefault("block_size", 4)
    return ContinuousDecodeServer(lm, paged=True, **kw)


def _wait_tokens(srv, n, timeout=60.0):
    """Block until the server has emitted >= n tokens total."""
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        if srv.metrics.snapshot().get("tokens_out", 0) >= n:
            return
        time.sleep(0.005)
    raise TimeoutError(f"server never reached {n} tokens")


# ---------------------------------------------------------------------------
# (a) federation correctness
# ---------------------------------------------------------------------------
class TestFederation:
    def _instances(self, seeds=(0, 1, 2)):
        out = []
        for i, seed in enumerate(seeds):
            m = ServingMetrics(name=f"i{i}", slo_target_ms=50)
            rng = random.Random(seed)
            for _ in range(20 + 10 * i):
                m.record_ttft(rng.uniform(0.1, 400.0))
                m.record_request(rng.uniform(1.0, 100.0), tokens=3)
            m.count("shed_predicted", i)
            out.append(m)
        return out

    def test_counters_sum_exactly(self):
        ms = self._instances()
        fv = FleetView()
        for m in ms:
            fv.add(m.name, m)
        assert fv.counter("completed") == sum(
            m.snapshot()["completed"] for m in ms) == 90
        assert fv.counter("shed_predicted") == 0 + 1 + 2
        assert fv.counters()["slo_total"] == sum(
            m.snapshot()["slo_total"] for m in ms)

    def test_merged_histogram_quantile_equals_pooled(self):
        """THE aggregability pin the fixed-bucket design exists for:
        bucket-wise merged counts are byte-identical to a histogram
        that observed the pooled samples, so every interpolated
        quantile is EXACTLY equal — and both sit within one bucket of
        the true pooled-sample quantile."""
        ms = self._instances()
        fv = FleetView()
        samples = []
        for m in ms:
            fv.add(m.name, m)
        pooled = ServingMetrics(name="pooled")
        for i, seed in enumerate((0, 1, 2)):
            rng = random.Random(seed)
            for _ in range(20 + 10 * i):
                v = rng.uniform(0.1, 400.0)
                pooled.record_ttft(v)
                samples.append(v)
                rng.uniform(1.0, 100.0)     # keep streams aligned
        ph = pooled.latency_histograms()["ttft_ms"]
        merged = fv.histogram("ttft_ms")
        assert merged["counts"] == ph.counts()
        assert merged["total"] == len(samples)
        samples.sort()
        for q in (10, 50, 90, 99):
            est = fv.quantile("ttft_ms", q)
            assert est == ph.quantile(q)
            # within bucket resolution of the true pooled quantile:
            # the estimate lands in the same bucket as the true value
            true = samples[min(len(samples) - 1,
                               int(q / 100.0 * (len(samples) - 1)))]
            bounds = [0.0] + list(ph.buckets)
            bi = next(j for j in range(1, len(bounds))
                      if true <= bounds[j] or j == len(bounds) - 1)
            assert bounds[bi - 1] <= est <= bounds[bi] + 1e-9, (
                f"q{q}: est {est} outside the bucket holding "
                f"true {true}")

    def test_gauges_keep_per_instance_never_sum_into_counters(self):
        a = ServingMetrics(name="a")
        b = ServingMetrics(name="b")
        a.record_request(5.0)           # materialize the counter kind
        a.record_service_rate(100.0)
        b.record_service_rate(300.0)
        fv = FleetView().add("a", a).add("b", b)
        gv = fv.gauge_view("service_rate_tokens_per_sec")
        assert gv["per_instance"] == {"a": 100.0, "b": 300.0}
        assert gv["min"] == 100.0 and gv["max"] == 300.0
        assert gv["mean"] == 200.0
        # the gauge never appears among the summed counters; summing it
        # is only available as the EXPLICIT derived verb
        assert "service_rate_tokens_per_sec" not in fv.counters()
        assert fv.gauge_sum("service_rate_tokens_per_sec") == 400.0
        with pytest.raises(ValueError):
            fv.counter("service_rate_tokens_per_sec")
        with pytest.raises(ValueError):
            fv.gauge_view("completed")

    def test_kind_conflict_across_instances_raises(self):
        fv = FleetView()
        fv.add("a", {"x": {"kind": "counter", "value": 1}})
        fv.add("b", {"x": {"kind": "gauge", "value": 2.0}})
        with pytest.raises(ValueError, match="conflicting kinds"):
            fv.counters()

    def test_n_copies_scale_counters_and_fix_quantiles(self):
        m = self._instances(seeds=(7,))[0]
        solo = FleetView().add("i0", m)
        fv = FleetView()
        for i in range(3):
            fv.add(f"c{i}", m)
        assert fv.counter("completed") == 3 * solo.counter("completed")
        for q in (50, 99):
            # 3x every bucket count: the interpolation is scale-free,
            # so the quantile is unchanged (to float round-off)
            assert fv.quantile("ttft_ms", q) == pytest.approx(
                solo.quantile("ttft_ms", q), rel=1e-12)

    def test_mismatched_histogram_grids_refused(self):
        fv = FleetView()
        fv.add("a", {"h": {"kind": "histogram", "buckets": [1, 2],
                           "counts": [1, 0, 0], "sum": 0.5,
                           "total": 1}})
        fv.add("b", {"h": {"kind": "histogram", "buckets": [1, 5],
                           "counts": [1, 0, 0], "sum": 0.5,
                           "total": 1}})
        with pytest.raises(ValueError, match="mismatched bucket grids"):
            fv.histogram("h")

    def test_mixed_instance_exposition_refused_or_filtered(self):
        """Review regression: a text carrying SEVERAL instances'
        samples (an aggregated scrape) must not silently last-win
        counters — it raises without an instance= filter, and with one
        it reads exactly that instance's samples."""
        reg0, reg1 = MetricsRegistry(), MetricsRegistry()
        ServingMetrics(registry=reg0, name="s").record_request(5.0)
        m1 = ServingMetrics(registry=reg1, name="s")
        m1.record_request(5.0)
        m1.record_request(6.0)
        agg = (reg0.prometheus_text(namespace="ns", instance="i0")
               + reg1.prometheus_text(namespace="ns", instance="i1"))
        with pytest.raises(ValueError, match="several instances"):
            parse_prometheus_text(agg)
        snap0 = parse_prometheus_text(
            agg, strip_prefix="ns_serving_s_", instance="i0")
        snap1 = parse_prometheus_text(
            agg, strip_prefix="ns_serving_s_", instance="i1")
        assert snap0["completed"]["value"] == 1
        assert snap1["completed"]["value"] == 2

    def test_prometheus_text_federates_identically(self):
        """A scraped /metrics exposition (instance label included) and
        the in-process kind-snapshot are the SAME federation input:
        counters, histogram bucket counts, and gauges all round-trip."""
        reg = MetricsRegistry()
        m = ServingMetrics(registry=reg, name="i0", slo_target_ms=50)
        rng = random.Random(5)
        for _ in range(40):
            m.record_ttft(rng.uniform(0.1, 900.0))
            m.record_request(rng.uniform(1.0, 80.0), tokens=2)
        m.record_service_rate(123.5)
        text = reg.prometheus_text(namespace="dl4j_tpu", instance="i0")
        via_text = FleetView().add(
            "i0", text, strip_prefix="dl4j_tpu_serving_i0_")
        via_obj = FleetView().add("i0", m)
        assert via_text.counter("completed") == \
            via_obj.counter("completed") == 40
        ht, ho = (v.histogram("ttft_ms")
                  for v in (via_text, via_obj))
        assert ht["counts"] == ho["counts"]
        assert ht["buckets"] == ho["buckets"]
        assert via_text.quantile("ttft_ms", 99) == \
            via_obj.quantile("ttft_ms", 99)
        gv = via_text.gauge_view("service_rate_tokens_per_sec")
        assert gv["per_instance"]["i0"] == 123.5

    def test_fleet_snapshot_derived_readouts(self):
        a = ServingMetrics(name="a", slo_target_ms=50)
        b = ServingMetrics(name="b", slo_target_ms=50)
        a.record_request(10.0, tokens=8)        # met
        b.record_request(90.0, tokens=8)        # missed
        a.count("tokens_out", 8)
        b.count("tokens_out", 8)
        a.record_service_rate(500.0)
        b.record_service_rate(300.0)
        b.count("shed_predicted", 4)
        fv = FleetView().add("a", a).add("b", b)
        snap = fv.snapshot()
        assert snap["fleet_instances"] == 2
        assert snap["fleet_slo_attainment"] == pytest.approx(0.5)
        # goodput = fleet capacity x within-SLO token fraction (8/16)
        assert snap["fleet_goodput_tokens_per_sec"] == \
            pytest.approx(800.0 * 0.5)
        assert snap["fleet_shed_predicted"] == 4
        assert snap["fleet_shed_share"] == {"a": 0.0, "b": 1.0}
        assert snap["autoscale_decision"] is None
        sig = AutoscaleSignal()
        assert FleetView(signal=sig).add("a", a).snapshot()[
            "autoscale_decision"] == "hold"


# ---------------------------------------------------------------------------
# (b) trace stitching + the migrated-request single-timeline pin
# ---------------------------------------------------------------------------
class TestTraceStitch:
    def test_merge_aligns_on_clock_anchors(self):
        t1 = Tracer(enabled=True, instance="a")
        with t1.span("first", track="lane"):
            time.sleep(0.002)
        time.sleep(0.04)
        t2 = Tracer(enabled=True, instance="b")
        with t2.span("second", track="lane"):
            time.sleep(0.002)
        merged = merge_traces([t1.chrome_trace(), t2.chrome_trace()])
        xs = [e for e in merged["traceEvents"] if e.get("ph") == "X"]
        assert sorted({e["pid"] for e in xs}) == [1, 2]
        names = {e["args"]["name"]: e["pid"]
                 for e in merged["traceEvents"]
                 if e.get("ph") == "M" and e["name"] == "process_name"}
        assert names == {"a": 1, "b": 2}
        first = next(e for e in xs if e["name"] == "first")
        second = next(e for e in xs if e["name"] == "second")
        # wall-anchor alignment: the later trace's span lands LATER on
        # the merged timeline by ~the real elapsed gap (>= 30ms here)
        assert second["ts"] - first["ts"] >= 30e3
        json.dumps(merged)      # JSON-serializable = Perfetto-loadable

    def test_anchorless_trace_merges_unshifted(self):
        t = Tracer(enabled=True)
        with t.span("x"):
            pass
        bare = {"traceEvents": [
            {"name": "y", "cat": "c", "ph": "X", "ts": 1.0, "dur": 1.0,
             "pid": 0, "tid": 0, "args": {}}]}
        merged = merge_traces([t.chrome_trace(), bare])
        xs = [e for e in merged["traceEvents"] if e.get("ph") == "X"]
        assert {e["name"] for e in xs} == {"x", "y"}
        assert sorted({e["pid"] for e in xs}) == [1, 2]

    def test_migrated_request_is_one_timeline(self):
        """THE acceptance pin: a request moved between two NAMED server
        instances via migrate_out/migrate_in yields ONE merged
        Perfetto-loadable trace with both instances' spans under the
        SAME trace id on distinct process groups, and the destination's
        resume spans sit AFTER the origin's spill marker on the merged
        timeline (the clock_sync alignment is the in-process wall==mono
        delta, pinned in test_obs)."""
        lm = _lm()
        ta = Tracer(enabled=True, instance="a")
        tb = Tracer(enabled=True, instance="b")
        a = _paged(lm, instance="a", tracer=ta).start()
        b = _paged(lm, instance="b", tracer=tb).start()
        try:
            with _paged(lm) as solo:
                ref = solo.generate([5, 9, 2, 7, 1, 3], 20, timeout=120)
            fut = a.submit([5, 9, 2, 7, 1, 3], 20)
            _wait_tokens(a, 4)
            art = a.migrate_out(fut)
            # the trace baton rides the artifact manifest
            assert art.trace["origin"] == "a"
            tid = art.trace["trace_id"]
            assert str(tid).startswith("a-")
            with pytest.raises(RequestMigratedError):
                fut.result(10)
            out = b.migrate_in(art).result(120)
            assert out == ref       # stream survives, bit-identical
        finally:
            a.stop(timeout=120)
            b.stop(timeout=120)
        merged = merge_traces([ta.chrome_trace(), tb.chrome_trace()],
                              names=["a", "b"])
        evs = [e for e in merged["traceEvents"]
               if (e.get("args") or {}).get("trace_id") == tid]
        by_pid = {}
        for e in evs:
            by_pid.setdefault(e["pid"], []).append(e)
        # both instances' spans, same trace id, distinct process groups
        assert set(by_pid) == {1, 2}
        a_names = {e["name"] for e in by_pid[1]}
        b_names = {e["name"] for e in by_pid[2]}
        assert "serve.migrate_out" in a_names
        assert "serve.migrate_in" in b_names
        assert "decode.restore" in b_names
        assert "serve.request" in b_names       # the completed lane
        # order across the process boundary: every destination event
        # sits at/after the origin's spill marker on the merged clock
        spill = next(e for e in by_pid[1]
                     if e["name"] == "serve.migrate_out")
        assert all(e["ts"] >= spill["ts"] - 1e3 for e in by_pid[2]), (
            "destination spans precede the origin's spill marker")
        # the continued lane name is the origin's req-<id> lane on BOTH
        lanes = {e["args"]["name"] for e in merged["traceEvents"]
                 if e.get("ph") == "M" and e["name"] == "thread_name"
                 and e["args"]["name"] == f"req-{tid}"}
        assert lanes == {f"req-{tid}"}
        json.dumps(merged)

    def test_artifact_trace_context_survives_disk(self, tmp_path):
        """The manifest carries the baton through the wire format."""
        lm = _lm()
        a = _paged(lm, instance="a").start()
        try:
            # long budget: the export must land while the request is
            # still decoding (a finished request has no state to move)
            fut = a.submit([1, 2, 3], 48)
            _wait_tokens(a, 2)
            art = a.migrate_out(fut)
        finally:
            a.stop(timeout=120)
        from deeplearning4j_tpu.serving.kvstate import RequestArtifact
        p = str(tmp_path / "art")
        art.save(p)
        loaded = RequestArtifact.load(p)
        assert loaded.trace == art.trace
        assert loaded.trace["origin"] == "a"

    def test_unnamed_server_keeps_integer_ids(self):
        """Default (no instance=): request ids stay plain ints — the
        single-server trace format is unchanged."""
        lm = _lm()
        with ContinuousDecodeServer(lm, slots=2,
                                    prompt_buckets=(8,)) as srv:
            srv.generate([1, 2, 3], 2, timeout=120)
            assert srv.instance == srv.metrics.name

    def test_unnamed_origin_id_not_adopted(self):
        """Review regression: an UNNAMED origin's integer trace id
        could collide with the destination's own counter (both count
        from 0) — the destination mints a fresh LOCAL id instead;
        lane continuity is a named-fleet feature."""
        lm = _lm()
        a = _paged(lm).start()      # unnamed: integer ids
        tb = Tracer(enabled=True)
        b = _paged(lm, tracer=tb).start()
        try:
            a.generate([9, 9], 2, timeout=120)      # burn ids 0..
            a.generate([8, 8], 2, timeout=120)
            fut = a.submit([5, 9, 2, 7], 48)        # origin id >= 2
            _wait_tokens(a, 7)      # 4 warm-up tokens + a few of its own
            art = a.migrate_out(fut)
            origin_id = art.trace["trace_id"]
            assert isinstance(origin_id, int) and origin_id >= 2
            b.migrate_in(art).result(120)
        finally:
            a.stop(timeout=120)
            b.stop(timeout=120)
        (mi,) = [s for s in tb.spans() if s.name == "serve.migrate_in"]
        assert mi.args["trace_id"] == 0     # b's OWN fresh counter
        assert mi.args["trace_id"] != origin_id

    def test_decompose_partitions_within_each_process_group(self):
        """Review regression: decomposing a MERGED multi-instance
        trace must attribute each request against its OWN instance's
        busy windows — pooling pids charged every request with the
        other replicas' concurrent dispatches (decode_ms > total_ms,
        sched_gap clamped to 0)."""
        from deeplearning4j_tpu.obs.decompose import decompose_requests

        def trace(pid_free, tid):
            # one request [0, 100]ms with 10ms queue wait and a 40ms
            # dispatch window; a SECOND 40ms dispatch on the same
            # timeline belongs to the other instance's trace
            return {"traceEvents": [
                {"name": "serve.request", "ph": "X", "ts": 0.0,
                 "dur": 100e3, "pid": pid_free, "tid": 1,
                 "args": {"trace_id": tid}},
                {"name": "serve.queue_wait", "ph": "X", "ts": 0.0,
                 "dur": 10e3, "pid": pid_free, "tid": 1,
                 "args": {"trace_id": tid}},
                {"name": "decode.dispatch", "ph": "X", "ts": 20e3,
                 "dur": 40e3, "pid": pid_free, "tid": 0, "args": {}},
            ]}
        merged = merge_traces([trace(0, "a-0"), trace(0, "b-0")])
        rows = decompose_requests(merged)
        assert len(rows) == 2
        for r in rows:
            # own 40ms dispatch only — NOT the other instance's too
            # (decompose rows are in ms; the trace's ts/dur are us)
            assert r["decode_ms"] == pytest.approx(40.0, rel=1e-6)
            assert r["queue_wait_ms"] == pytest.approx(10.0, rel=1e-6)
            total = (r["queue_wait_ms"] + r["prefill_ms"]
                     + r["decode_ms"] + r["sched_gap_ms"])
            assert total == pytest.approx(r["total_ms"], rel=1e-6)


# ---------------------------------------------------------------------------
# (c) the autoscaling signal
# ---------------------------------------------------------------------------
def _run_regimes(sig, regimes, rng):
    """Feed seeded synthetic observations; returns [(regime_idx,
    decision)] per observation. Each regime dict: n windows,
    shed_rate (cumulative deltas drawn 0.8x-1.2x), service (drawn
    +/- jitter), occupancy."""
    out, cum = [], 0.0
    for ri, r in enumerate(regimes):
        for _ in range(r["n"]):
            cum += r["shed_rate"] * rng.uniform(0.8, 1.2) \
                if r["shed_rate"] else 0.0
            svc = r["service"] * (1 + rng.uniform(-r.get("jitter", .03),
                                                  r.get("jitter", .03)))
            out.append((ri, sig.observe(sheds=cum, service_rate=svc,
                                        occupancy=r.get("occ", 0.6))))
    return out


class TestAutoscaleSignal:
    def test_two_regime_scale_up_only_past_knee(self):
        """Seeded two-regime trace: below the knee (zero sheds, flat
        service) the decision never leaves hold; past it (sheds
        accruing, service flat at capacity) scale_up fires and LATCHES
        for the rest of the regime."""
        for seed in range(8):
            sig = AutoscaleSignal(window=6, hysteresis=2)
            rng = random.Random(f"fleet:{seed}")
            hist = _run_regimes(sig, [
                {"n": 12, "shed_rate": 0.0, "service": 1000.0},
                {"n": 12, "shed_rate": 8.0, "service": 1000.0},
            ], rng)
            below = [d for ri, d in hist if ri == 0]
            past = [d for ri, d in hist if ri == 1]
            assert set(below) == {"hold"}, f"seed {seed}: {below}"
            assert past[-1] == "scale_up", f"seed {seed}: {past}"
            # once capacity-bound, it stays scale_up (no flapping back)
            first = past.index("scale_up")
            assert set(past[first:]) == {"scale_up"}

    def test_queue_bound_regime_holds(self):
        """Sheds accruing while service rate is STILL RISING = queue /
        ramp, not capacity — the detector must hold."""
        for seed in range(8):
            sig = AutoscaleSignal(window=6, hysteresis=2, flat_tol=0.1)
            rng = random.Random(f"queue:{seed}")
            svc, cum, decs = 400.0, 0.0, []
            for _ in range(14):
                svc *= 1.18             # capacity ramping hard
                cum += 5 * rng.uniform(0.8, 1.2)
                decs.append(sig.observe(sheds=cum, service_rate=svc,
                                        occupancy=0.9))
            assert set(decs) == {"hold"}, f"seed {seed}: {decs}"

    def test_scale_down_only_at_idle_low_occupancy(self):
        sig = AutoscaleSignal(window=6, hysteresis=2,
                              low_occupancy=0.25)
        decs = [sig.observe(sheds=0, service_rate=1000.0,
                            occupancy=0.1) for _ in range(10)]
        assert decs[-1] == "scale_down"
        # moderate occupancy: never scale_down
        sig2 = AutoscaleSignal(window=6, hysteresis=2,
                               low_occupancy=0.25)
        decs2 = [sig2.observe(sheds=0, service_rate=1000.0,
                              occupancy=0.5) for _ in range(10)]
        assert set(decs2) == {"hold"}
        # unknown occupancy disables scale_down entirely
        sig3 = AutoscaleSignal(window=6, hysteresis=2)
        decs3 = [sig3.observe(sheds=0, service_rate=1000.0)
                 for _ in range(10)]
        assert set(decs3) == {"hold"}

    def test_single_window_burst_never_flaps(self):
        """One anomalous shed burst (a single observation window) must
        not flip the decision, for any window size: the lower-median
        delta statistic rejects the lone outlier outright, and the
        hysteresis bound guards whatever residual raw flip remains."""
        for window in (4, 5, 6, 8):
            sig = AutoscaleSignal(window=window, hysteresis=2,
                                  min_shed_rate=2.0)
            cum = 0.0
            for _ in range(2 * window):
                sig.observe(sheds=cum, service_rate=100.0,
                            occupancy=0.6)
            cum += 50.0                 # one burst window
            decs = [sig.observe(sheds=cum, service_rate=100.0,
                                occupancy=0.6)]
            for _ in range(2 * window):     # quiet again
                decs.append(sig.observe(sheds=cum, service_rate=100.0,
                                        occupancy=0.6))
            assert set(decs) == {"hold"}, f"window {window}: {decs}"
            assert sig.transitions == []

    def test_hysteresis_delays_a_real_transition(self):
        """The decision changes only after `hysteresis` consecutive
        identical raw verdicts: under a sustained shed regime the
        scale_up lands at least one observation AFTER the first raw
        flip could have occurred."""
        sig = AutoscaleSignal(window=6, hysteresis=3)
        cum, decs = 0.0, []
        for i in range(20):
            if i >= 8:
                cum += 10.0             # sustained overload from obs 8
            decs.append(sig.observe(sheds=cum, service_rate=100.0,
                                    occupancy=0.8))
        assert decs[-1] == "scale_up"
        (first_idx, first) = sig.transitions[0]
        assert first == "scale_up"
        # at least `hysteresis` observations of the regime passed
        # before the decision moved
        assert first_idx >= 8 + 3

    def test_warmup_never_acts(self):
        sig = AutoscaleSignal(window=6, hysteresis=1)
        for _ in range(5):
            assert sig.observe(sheds=1000, service_rate=1.0,
                               occupancy=0.0) == "hold"

    def test_deterministic_same_inputs_same_decisions(self):
        def run():
            sig = AutoscaleSignal()
            rng = random.Random("det")
            return [d for _, d in _run_regimes(sig, [
                {"n": 10, "shed_rate": 0.0, "service": 500.0},
                {"n": 10, "shed_rate": 4.0, "service": 500.0},
            ], rng)]
        assert run() == run()

    def test_counter_reset_reads_as_quiet_not_negative(self):
        sig = AutoscaleSignal(window=4, hysteresis=1)
        cum = 100.0
        for _ in range(6):
            sig.observe(sheds=cum, service_rate=100.0, occupancy=0.6)
        # an instance restarted: the merged counter drops — one quiet
        # window, never a negative spike / crash
        assert sig.observe(sheds=10.0, service_rate=100.0,
                           occupancy=0.6) == "hold"

    def test_snapshot_input_form(self):
        sig = AutoscaleSignal(window=4, hysteresis=1)
        snap = {"fleet_shed_predicted": 3,
                "fleet_service_rate_tokens_per_sec": 100.0,
                "fleet_occupancy_mean": 0.5}
        assert sig.observe(snap) == "hold"

    def test_config_validation(self):
        with pytest.raises(ValueError):
            AutoscaleSignal(window=2)
        with pytest.raises(ValueError):
            AutoscaleSignal(hysteresis=0)


# ---------------------------------------------------------------------------
# (d) zero-added-dispatch + structural pins
# ---------------------------------------------------------------------------
class TestFleetCostPins:
    def test_fleet_module_never_imports_device_code(self):
        """Thin wrapper over the graftlint layering pass since ISSUE
        15: layers.toml's 'obs-stdlib-only' rule (which covers
        obs/fleet.py) is the single source of truth — the pass
        resolves relative and function-local imports the old regex
        pin could only approximate. The module-lives-in-obs assert
        stays: the rule matches by path, so moving the file out of
        obs/ would silently drop it from the layer."""
        import os
        import deeplearning4j_tpu.obs.fleet as fleet_mod
        from tools.analyze import check_layer_rules
        assert os.path.dirname(fleet_mod.__file__).endswith("obs")
        findings = check_layer_rules(["obs-stdlib-only"])
        assert not findings, \
            "\n".join(f"{f.path}:{f.line}: {f.message}"
                      for f in findings)

    def test_federation_adds_zero_device_dispatches(self):
        """Same sequential workload twice: bare server vs a server
        whose metrics are federated into a FleetView + AutoscaleSignal
        observation after EVERY request, with tracing on. Dispatch
        counters must be IDENTICAL — the fleet plane observes the
        schedule, never alters it."""
        counts = {}
        for arm in ("bare", "federated"):
            lm = _lm()
            tracer = Tracer(enabled=(arm == "federated"),
                            instance=arm)
            sig = AutoscaleSignal(window=4, hysteresis=1)
            with ContinuousDecodeServer(lm, slots=2, prompt_buckets=(8,),
                                        tracer=tracer,
                                        instance=arm) as srv:
                for i in range(4):
                    srv.generate([1 + i, 2, 3], 5, timeout=120)
                    if arm == "federated":
                        fv = FleetView(signal=sig).add(
                            arm, srv.metrics)
                        sig.observe(fv.snapshot())
                        fv.snapshot()
            snap = srv.metrics.snapshot()
            counts[arm] = (snap["dispatches"], snap["tokens_out"])
        assert counts["federated"] == counts["bare"]

    def test_named_instance_request_ids_are_fleet_unique(self):
        lm = _lm()
        with ContinuousDecodeServer(lm, slots=2, prompt_buckets=(8,),
                                    instance="i0") as a:
            with ContinuousDecodeServer(lm, slots=2,
                                        prompt_buckets=(8,),
                                        instance="i1") as b:
                a.generate([1, 2, 3], 2, timeout=120)
                b.generate([1, 2, 3], 2, timeout=120)
                assert a.metrics.name == "i0"
                assert b.metrics.name == "i1"
