"""ModelSerializer zip round-trips + early stopping behavior + normalizers.
Mirrors reference test strategy §4: serialization round-trips and
early-stopping suites (deeplearning4j-core earlystopping tests)."""
import numpy as np
import pytest

from deeplearning4j_tpu import (ComputationGraph, InputType,
                                MultiLayerNetwork, NeuralNetConfiguration)
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterators import ListDataSetIterator
from deeplearning4j_tpu.datasets.normalizers import (ImagePreProcessingScaler,
                                                     Normalizer,
                                                     NormalizerMinMaxScaler,
                                                     NormalizerStandardize)
from deeplearning4j_tpu.earlystopping import (
    DataSetLossCalculator, EarlyStoppingConfiguration, EarlyStoppingTrainer,
    InvalidScoreIterationTerminationCondition, LocalFileModelSaver,
    MaxEpochsTerminationCondition, MaxTimeIterationTerminationCondition,
    ScoreImprovementEpochTerminationCondition)
from deeplearning4j_tpu.nn.conf.graph_vertices import MergeVertex
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.util import model_serializer as MS


def _mln(seed=7):
    conf = (NeuralNetConfiguration.Builder().seed(seed)
            .updater("adam").learning_rate(0.01).list()
            .layer(0, DenseLayer(n_out=8, activation="relu"))
            .layer(1, OutputLayer(n_out=3, activation="softmax",
                                  loss_function="mcxent"))
            .set_input_type(InputType.feed_forward(4))
            .build())
    return MultiLayerNetwork(conf).init()


def _data(n=32, seed=0):
    r = np.random.default_rng(seed)
    x = r.random((n, 4)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[r.integers(0, 3, n)]
    return DataSet(x, y)


class TestModelSerializer:
    def test_mln_round_trip_exact(self, tmp_path):
        net = _mln()
        ds = _data()
        net.fit(ds)
        path = str(tmp_path / "model.zip")
        MS.write_model(net, path)
        net2 = MS.restore_multi_layer_network(path)
        assert np.allclose(net.params(), net2.params())
        assert net2.conf.iteration_count == net.conf.iteration_count
        out1 = np.asarray(net.output(ds.features))
        out2 = np.asarray(net2.output(ds.features))
        assert np.allclose(out1, out2)

    def test_updater_state_resume(self, tmp_path):
        """Exact resume: continuing training after restore must equal
        continuous training (params + Adam moments round-trip)."""
        ds = _data()
        net_a = _mln()
        net_b = _mln()
        net_b.set_params(net_a.params())
        net_a.fit(ds)
        path = str(tmp_path / "ckpt.zip")
        MS.write_model(net_a, path)
        restored = MS.restore_multi_layer_network(path)
        net_a.fit(ds)
        restored.fit(ds)
        assert np.allclose(net_a.params(), restored.params(), atol=1e-6)

    def test_cg_round_trip(self, tmp_path):
        conf = (NeuralNetConfiguration.Builder().seed(5)
                .updater("sgd").learning_rate(0.1)
                .graph_builder()
                .add_inputs("in")
                .add_layer("a", DenseLayer(n_out=5, activation="tanh"), "in")
                .add_layer("b", DenseLayer(n_out=5, activation="tanh"), "in")
                .add_vertex("m", MergeVertex(), "a", "b")
                .add_layer("out", OutputLayer(n_out=3, activation="softmax",
                                              loss_function="mcxent"), "m")
                .set_outputs("out")
                .set_input_types(InputType.feed_forward(4))
                .build())
        net = ComputationGraph(conf).init()
        ds = _data()
        net.fit(ds)
        path = str(tmp_path / "cg.zip")
        MS.write_model(net, path)
        net2 = MS.restore_model(path)  # ModelGuesser path
        assert isinstance(net2, ComputationGraph)
        o1 = np.asarray(net.output(ds.features)[0])
        o2 = np.asarray(net2.output(ds.features)[0])
        assert np.allclose(o1, o2)

    def test_mln_yaml_round_trip(self):
        """YAML serde — reference MultiLayerConfiguration.toYaml/fromYaml."""
        from deeplearning4j_tpu.nn.conf.layers import (ConvolutionLayer,
                                                       SubsamplingLayer)
        from deeplearning4j_tpu.nn.conf.neural_net_configuration import \
            MultiLayerConfiguration
        conf = (NeuralNetConfiguration.Builder().seed(11)
                .updater("nesterovs").momentum(0.9).learning_rate(0.05)
                .list()
                .layer(0, ConvolutionLayer(n_out=4, kernel_size=(3, 3),
                                           stride=(1, 1), activation="relu"))
                .layer(1, SubsamplingLayer(pooling_type="max",
                                           kernel_size=(2, 2)))
                .layer(2, OutputLayer(n_out=3, activation="softmax",
                                      loss_function="mcxent"))
                .set_input_type(InputType.convolutional(8, 8, 1))
                .build())
        y = conf.to_yaml()
        conf2 = MultiLayerConfiguration.from_yaml(y)
        assert conf2.to_json() == conf.to_json()
        # round-tripped config trains/infers identically
        net = MultiLayerNetwork(conf).init()
        net2 = MultiLayerNetwork(conf2).init()
        net2.set_params(net.params())
        x = np.random.default_rng(0).random((2, 8, 8, 1)).astype(np.float32)
        assert np.allclose(np.asarray(net.output(x)),
                           np.asarray(net2.output(x)))

    def test_cg_yaml_round_trip(self):
        """reference ComputationGraphConfiguration toYaml/fromYaml."""
        from deeplearning4j_tpu.nn.conf.computation_graph_configuration import \
            ComputationGraphConfiguration
        conf = (NeuralNetConfiguration.Builder().seed(5)
                .updater("sgd").learning_rate(0.1)
                .graph_builder()
                .add_inputs("in")
                .add_layer("a", DenseLayer(n_out=5, activation="tanh"), "in")
                .add_layer("b", DenseLayer(n_out=5, activation="tanh"), "in")
                .add_vertex("m", MergeVertex(), "a", "b")
                .add_layer("out", OutputLayer(n_out=3, activation="softmax",
                                              loss_function="mcxent"), "m")
                .set_outputs("out")
                .set_input_types(InputType.feed_forward(4))
                .build())
        y = conf.to_yaml()
        conf2 = ComputationGraphConfiguration.from_yaml(y)
        assert conf2.to_json() == conf.to_json()

    def test_normalizer_round_trip(self, tmp_path):
        net = _mln()
        ds = _data()
        norm = NormalizerStandardize().fit(ds)
        path = str(tmp_path / "m.zip")
        MS.write_model(net, path, normalizer=norm)
        norm2 = MS.restore_normalizer(path)
        assert isinstance(norm2, NormalizerStandardize)
        assert np.allclose(norm.mean, norm2.mean)
        assert np.allclose(norm.std, norm2.std)


class TestNormalizers:
    def test_standardize(self):
        ds = _data(100)
        norm = NormalizerStandardize().fit(ds)
        norm.transform(ds)
        assert np.allclose(ds.features.mean(axis=0), 0, atol=1e-5)
        assert np.allclose(ds.features.std(axis=0), 1, atol=1e-2)

    def test_minmax(self):
        ds = _data(50)
        ds.features = ds.features * 10 - 3
        norm = NormalizerMinMaxScaler().fit(ds)
        norm.transform(ds)
        assert ds.features.min() >= -1e-6
        assert ds.features.max() <= 1 + 1e-6

    def test_image_scaler_serde(self):
        s = ImagePreProcessingScaler()
        ds = DataSet(np.full((2, 4), 255.0, np.float32),
                     np.zeros((2, 3), np.float32))
        s.transform(ds)
        assert np.allclose(ds.features, 1.0)
        s2 = Normalizer.from_dict(s.to_dict())
        assert isinstance(s2, ImagePreProcessingScaler)


class TestEarlyStopping:
    def _iters(self):
        train = ListDataSetIterator(list(_data(64, 1).batch_by(16)))
        val = ListDataSetIterator(list(_data(32, 2).batch_by(16)))
        return train, val

    def test_max_epochs_termination(self):
        train, val = self._iters()
        es = (EarlyStoppingConfiguration.Builder()
              .score_calculator(DataSetLossCalculator(val))
              .epoch_termination_conditions(MaxEpochsTerminationCondition(3))
              .build())
        result = EarlyStoppingTrainer(es, _mln(), train).fit()
        assert result.termination_reason == \
            "EpochTerminationCondition"
        assert "MaxEpochs" in result.termination_details
        assert result.total_epochs == 3
        assert result.get_best_model() is not None
        assert len(result.score_vs_epoch) == 3

    def test_score_improvement_termination(self):
        train, val = self._iters()
        es = (EarlyStoppingConfiguration.Builder()
              .score_calculator(DataSetLossCalculator(val))
              .epoch_termination_conditions(
                  ScoreImprovementEpochTerminationCondition(2),
                  MaxEpochsTerminationCondition(100))
              .build())
        net = _mln()
        # zero LR -> no improvement -> stops after 2 stagnant epochs
        for l in net.layers:
            l.learning_rate = 0.0
        result = EarlyStoppingTrainer(es, net, train).fit()
        assert result.termination_reason == "EpochTerminationCondition"
        assert "ScoreImprovement" in result.termination_details

    def test_invalid_score_termination(self):
        train, val = self._iters()
        es = (EarlyStoppingConfiguration.Builder()
              .score_calculator(DataSetLossCalculator(val))
              .iteration_termination_conditions(
                  InvalidScoreIterationTerminationCondition())
              .epoch_termination_conditions(MaxEpochsTerminationCondition(5))
              .build())
        net = _mln()
        for l in net.layers:
            l.learning_rate = 1e9  # diverge -> NaN
        result = EarlyStoppingTrainer(es, net, train).fit()
        # either NaN hit (iteration condition) or epochs exhausted
        assert result.termination_reason in (
            "IterationTerminationCondition", "EpochTerminationCondition")

    def test_local_file_saver(self, tmp_path):
        train, val = self._iters()
        es = (EarlyStoppingConfiguration.Builder()
              .score_calculator(DataSetLossCalculator(val))
              .epoch_termination_conditions(MaxEpochsTerminationCondition(2))
              .model_saver(LocalFileModelSaver(str(tmp_path)))
              .build())
        result = EarlyStoppingTrainer(es, _mln(), train).fit()
        best = result.get_best_model()
        assert best is not None
        assert (tmp_path / "bestModel.bin").exists()
        ds = _data()
        assert np.asarray(best.output(ds.features)).shape == (32, 3)

    def test_max_time_termination(self):
        train, val = self._iters()
        es = (EarlyStoppingConfiguration.Builder()
              .score_calculator(DataSetLossCalculator(val))
              .iteration_termination_conditions(
                  MaxTimeIterationTerminationCondition(0.0))
              .epoch_termination_conditions(MaxEpochsTerminationCondition(50))
              .build())
        result = EarlyStoppingTrainer(es, _mln(), train).fit()
        assert result.termination_reason == "IterationTerminationCondition"
        assert "MaxTime" in result.termination_details
