"""Fault-tolerant distributed training (ISSUE 1 acceptance criteria).

Every failure mode is driven deterministically through
`common.resilience.FaultInjector` against the REAL code paths — no mocks:

  (a) a severed PSClient connection reconnects with backoff and training
      converges to the same applied-gradient count, with a retried PUSH
      applied exactly once (server-side (worker, seq) dedup);
  (b) a killed worker is reaped via heartbeat timeout and the remaining
      workers finish the run (graceful degradation, counted in stats);
  (c) a TrainingMaster run killed mid-epoch resumes from the last
      checkpoint and completes with a matching final averaging-round
      count (and bit-matching parameters vs. an uninterrupted run);
  (d) a mid-stream producer exception in the multi-worker async staging
      pipeline surfaces as a raised error under a full queue, not a hang.

Tiering: the deterministic fast tests run in tier-1; the timing-heavy
random-churn stress run is @pytest.mark.slow.
"""
import threading
import time

import numpy as np
import pytest

from deeplearning4j_tpu import (InputType, MultiLayerNetwork,
                                NeuralNetConfiguration)
from deeplearning4j_tpu.common.resilience import (FaultInjected,
                                                  FaultInjector,
                                                  NonRetryableError,
                                                  RetryPolicy)
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterators import ListDataSetIterator
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer


def _net(seed=7):
    conf = (NeuralNetConfiguration.Builder().seed(seed)
            .updater("adam").learning_rate(0.01).list()
            .layer(0, DenseLayer(n_out=16, activation="relu"))
            .layer(1, OutputLayer(n_out=3, activation="softmax",
                                  loss_function="mcxent"))
            .set_input_type(InputType.feed_forward(5))
            .build())
    return MultiLayerNetwork(conf).init()


def _data(n=256, seed=0):
    r = np.random.default_rng(seed)
    x = r.random((n, 5)).astype(np.float32)
    w = r.random((5, 3))
    y = np.eye(3, dtype=np.float32)[np.argmax(x @ w, axis=1)]
    return DataSet(x, y)


# ---------------------------------------------------------------------------
# RetryPolicy
# ---------------------------------------------------------------------------

def test_retry_policy_backoff_deterministic_and_bounded():
    a = RetryPolicy(seed=3, sleep=lambda d: None)
    b = RetryPolicy(seed=3, sleep=lambda d: None)
    assert [a.delay(i) for i in range(6)] == [b.delay(i) for i in range(6)]
    # bounded: never negative, never beyond max_delay * (1 + jitter)
    c = RetryPolicy(seed=9, base_delay=0.05, max_delay=2.0, jitter=0.25)
    for i in range(30):
        d = c.delay(i)
        assert 0.0 <= d <= 2.0 * 1.25


def test_retry_policy_retries_then_succeeds():
    sleeps = []
    pol = RetryPolicy(max_retries=5, base_delay=0.0, jitter=0.0,
                      sleep=sleeps.append, seed=0)
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise ConnectionError("transient")
        return "ok"

    assert pol.call(flaky) == "ok"
    assert calls["n"] == 3
    assert len(sleeps) == 2


def test_retry_policy_classification():
    pol = RetryPolicy(max_retries=5, base_delay=0.0, jitter=0.0,
                      sleep=lambda d: None)

    # a non-retryable marker wins even when the type matches `retryable`
    class Refused(ConnectionError, NonRetryableError):
        pass

    n = {"v": 0}

    def refused():
        n["v"] += 1
        raise Refused("terminal")

    with pytest.raises(Refused):
        pol.call(refused)
    assert n["v"] == 1         # no retries

    # an unclassified exception is never retried
    m = {"v": 0}

    def broken():
        m["v"] += 1
        raise ValueError("bug, not weather")

    with pytest.raises(ValueError):
        pol.call(broken)
    assert m["v"] == 1


def test_retry_policy_deadline_and_exhaustion():
    t = {"now": 0.0}
    sleeps = []

    def fake_sleep(d):
        sleeps.append(d)
        t["now"] += d

    pol = RetryPolicy(max_retries=100, base_delay=1.0, max_delay=1.0,
                      jitter=0.0, deadline=3.5,
                      sleep=fake_sleep, clock=lambda: t["now"])
    n = {"v": 0}

    def always():
        n["v"] += 1
        raise ConnectionError("down")

    with pytest.raises(ConnectionError):
        pol.call(always)
    # attempts at t=0,1,2,3 then a FINAL one at the deadline edge: the
    # last backoff is capped to the remaining 0.5s instead of either
    # sleeping past the deadline or forfeiting the remainder
    assert n["v"] == 5
    assert sleeps == [1.0, 1.0, 1.0, 0.5]
    assert t["now"] == 3.5          # never slept past the deadline

    pol2 = RetryPolicy(max_retries=2, base_delay=0.0, jitter=0.0,
                       sleep=lambda d: None)
    m = {"v": 0}

    def always2():
        m["v"] += 1
        raise TimeoutError("down")

    with pytest.raises(TimeoutError):
        pol2.call(always2)
    assert m["v"] == 3          # initial + 2 retries


# ---------------------------------------------------------------------------
# FaultInjector
# ---------------------------------------------------------------------------

def test_fault_injector_explicit_schedule():
    inj = FaultInjector(seed=0)
    inj.plan("op", on_calls=[2, 5])
    hits = []
    for i in range(8):
        try:
            inj.fire("op")
        except FaultInjected:
            hits.append(i)
    assert hits == [2, 5]
    assert inj.calls("op") == 8
    assert inj.fired("op") == [("op", 2), ("op", 5)]


def test_fault_injector_prob_schedule_is_seed_deterministic():
    def run(seed):
        inj = FaultInjector(seed=seed)
        inj.plan("op", prob=0.4, times=5)
        hits = []
        for i in range(25):
            try:
                inj.fire("op")
            except FaultInjected:
                hits.append(i)
        return hits

    assert run(11) == run(11)       # reproducible
    assert len(run(11)) == 5        # capped by times
    assert run(11) != run(12)       # seed actually matters


def test_fault_injector_sever_callback_and_custom_exc():
    inj = FaultInjector()
    inj.plan("op", on_call=0, sever=True, exc=RuntimeError("boom"))
    severed = []
    with pytest.raises(RuntimeError, match="boom"):
        inj.fire("op", on_sever=lambda: severed.append(1))
    assert severed == [1]
    # exc=None: fault (sever/delay) without raising
    inj.plan("quiet", on_call=0, sever=True, exc=None)
    inj.fire("quiet", on_sever=lambda: severed.append(2))
    assert severed == [1, 2]


# ---------------------------------------------------------------------------
# (a) severed connection: reconnect + at-most-once push
# ---------------------------------------------------------------------------

def test_severed_connection_reconnects_and_push_applies_once():
    import jax
    from deeplearning4j_tpu.parallel.parameter_server import (_jitted_ps_fns,
                                                              ps_batch)
    from deeplearning4j_tpu.parallel.ps_transport import PSClient, PSServer

    net = _net()
    ds = _data(96)
    s0 = float(net.score(ds))
    srv = PSServer(net, queue_size=4, n_workers=1)
    client = None
    try:
        inj = FaultInjector(seed=0)
        # sever right AFTER push #2's bytes hit the wire: the server
        # applies the gradient, the client never sees the ack and must
        # reconnect + resend the SAME seq — applied at most once
        inj.plan("client.push.sent", on_call=1, sever=True)
        # and a pull severed mid-flight is retried (idempotent read)
        inj.plan("client.pull.sent", on_call=3, sever=True)
        pol = RetryPolicy(max_retries=8, base_delay=0.01, max_delay=0.1,
                          seed=1)
        client = PSClient("127.0.0.1", srv.port, retry_policy=pol,
                          fault_injector=inj)

        worker = _net(seed=9)          # architecture donor only
        worker._ensure_init()
        grad_fn = _jitted_ps_fns(worker)[0]
        treedef = jax.tree_util.tree_structure(worker._params)
        rng = jax.random.PRNGKey(0)
        batches = list(ds.batch_by(16))          # 6 logical pushes
        for j, b in enumerate(batches):
            pleaves, _sleaves, version = client.pull()
            params = jax.tree_util.tree_unflatten(treedef, pleaves)
            batch = ps_batch(b, jax.random.fold_in(rng, j))
            grads, score, _state, _ = grad_fn(params, worker._model_state,
                                              batch)
            client.push(
                [np.asarray(l) for l in jax.tree_util.tree_leaves(grads)],
                float(score), version)
        client.done()
        final = srv.wait(timeout=120)
    finally:
        srv.stop()
        if client is not None:
            client.close()
    assert len(inj.fired()) == 2                 # both faults fired
    assert client.reconnects >= 2                # both paths re-dialed
    assert final["dup_pushes"] >= 1              # the retry was detected
    # the retried push was applied EXACTLY once: every logical push
    # counted, none double-applied
    assert final["applied"] == len(batches)
    assert float(net.score(ds)) < s0             # and training trained


# ---------------------------------------------------------------------------
# (b) heartbeat reaping: a crashed worker doesn't deadlock the survivors
# ---------------------------------------------------------------------------

def test_dead_worker_is_reaped_and_survivors_finish():
    from deeplearning4j_tpu.parallel.ps_transport import PSClient, PSServer

    net = _net()
    srv = PSServer(net, queue_size=4, n_workers=2, heartbeat_timeout=1.0)
    alive = dead = None
    try:
        alive = PSClient("127.0.0.1", srv.port, heartbeat_interval=0.1)
        dead = PSClient("127.0.0.1", srv.port, heartbeat_interval=0.1)
        assert alive.worker_id != dead.worker_id

        def zero_push(c):
            pleaves, _s, version = c.pull()
            c.push([np.zeros_like(np.asarray(l)) for l in pleaves],
                   1.0, version)

        zero_push(dead)
        dead.kill()                # crash: no DONE, heartbeats stop
        for _ in range(3):
            zero_push(alive)       # survivor keeps training
        alive.done()
        t0 = time.monotonic()
        stats = srv.wait(timeout=60)
        waited = time.monotonic() - t0
    finally:
        srv.stop()
        for c in (alive, dead):
            if c is not None:
                c.close()
    assert stats["workers_reaped"] == 1
    assert stats["workers_done"] == 1
    assert stats["applied"] == 4       # dead's 1 + alive's 3 all landed
    # wait() returned via the reaper, not a lucky race: the barrier held
    # until the heartbeat timeout had passed, then released
    assert waited < 30


def test_restarted_worker_reusing_id_resumes_seq_numbering():
    """A restarted worker PROCESS that proposes its old worker_id must not
    have its fresh pushes (seq restarting from 1) dedup'd against its
    previous life's seqs — the HELLO reply carries the last applied seq
    and the client resumes above it."""
    from deeplearning4j_tpu.parallel.ps_transport import PSClient, PSServer

    net = _net()
    srv = PSServer(net, queue_size=4, n_workers=1)
    try:
        def zero_push(c):
            pleaves, _s, version = c.pull()
            c.push([np.zeros_like(np.asarray(l)) for l in pleaves],
                   1.0, version)

        first = PSClient("127.0.0.1", srv.port, worker_id=3)
        for _ in range(3):
            zero_push(first)
        first.kill()                      # process dies, no DONE

        # "restart": fresh client, same identity, fresh seq counter
        second = PSClient("127.0.0.1", srv.port, worker_id=3)
        assert second._push_seq == 3      # resumed above the applied seqs
        for _ in range(2):
            zero_push(second)
        second.done()
        final = srv.wait(timeout=60)
    finally:
        srv.stop()
    assert final["dup_pushes"] == 0       # nothing silently discarded
    assert final["applied"] == 5          # all 3 + 2 gradients landed


def test_worker_that_never_connects_is_reaped():
    """n_workers promises a worker that crashes before HELLO: the server
    must still release wait() instead of blocking forever."""
    from deeplearning4j_tpu.parallel.ps_transport import PSClient, PSServer

    net = _net()
    srv = PSServer(net, queue_size=4, n_workers=2, heartbeat_timeout=0.6)
    c = None
    try:
        c = PSClient("127.0.0.1", srv.port, heartbeat_interval=0.1)
        c.done()
        stats = srv.wait(timeout=60)
    finally:
        srv.stop()
        if c is not None:
            c.close()
    assert stats["workers_done"] == 1
    assert stats["workers_reaped"] == 1


# ---------------------------------------------------------------------------
# (c) TrainingMaster / ParallelWrapper crash-resume
# ---------------------------------------------------------------------------

def _master(ckpt_dir=None, inj=None):
    from deeplearning4j_tpu.parallel import ParameterAveragingTrainingMaster
    b = (ParameterAveragingTrainingMaster.Builder(batch_size_per_worker=8)
         .workers(4).averaging_frequency(2).rdd_training_approach("direct"))
    if ckpt_dir is not None:
        b = b.checkpoint_directory(str(ckpt_dir))
    if inj is not None:
        b = b.fault_injector(inj)
    return b.build()


def test_training_master_crash_resume_matches_clean_run(tmp_path):
    ds = _data(256, seed=3)        # 8 global batches -> 4 rounds per pass

    # clean reference: two passes (epochs), 8 averaging rounds total
    ref = _net(seed=11)
    tm_ref = _master()
    tm_ref.execute_training(ref, ds)
    tm_ref.execute_training(ref, ds)
    assert tm_ref._round == 8

    # crashing run: checkpoint every round, die at round index 5
    # (mid-second-epoch)
    inj = FaultInjector()
    inj.plan("master.round", on_call=5, exc=RuntimeError("injected crash"))
    net1 = _net(seed=11)
    tm1 = _master(tmp_path / "ck", inj)
    tm1.execute_training(net1, ds)                 # first pass: rounds 0-3
    with pytest.raises(RuntimeError, match="injected crash"):
        tm1.execute_training(net1, ds)             # dies entering round 5

    # resume: FRESH net + FRESH master on the same checkpoint dir re-runs
    # the same two passes; rounds 0-4 fast-forward from the restored
    # checkpoint, rounds 5-7 train
    net2 = _net(seed=11)
    tm2 = _master(tmp_path / "ck")
    tm2.execute_training(net2, ds)
    tm2.execute_training(net2, ds)
    assert tm2._round == 8                         # matching round count
    assert tm2._resume_round == 5
    assert net2.conf.iteration_count == ref.conf.iteration_count
    np.testing.assert_allclose(np.asarray(net2.params()),
                               np.asarray(ref.params()), atol=1e-6)


def test_parallel_wrapper_crash_resume_matches_clean_run(tmp_path):
    from deeplearning4j_tpu.parallel import ParallelWrapper

    batches = list(_data(128, seed=5).batch_by(16))     # 8 batches

    def wrapper(net, ckpt=None, inj=None):
        b = (ParallelWrapper.Builder(net).workers(4)
             .averaging_frequency(2))
        if ckpt is not None:
            b = b.checkpointing(str(ckpt))
        if inj is not None:
            b = b.fault_injector(inj)
        return b.build()

    ref = _net(seed=5)
    wrapper(ref).fit(ListDataSetIterator(batches), num_epochs=2)

    inj = FaultInjector()
    inj.plan("wrapper.round", on_call=5, exc=RuntimeError("injected crash"))
    net1 = _net(seed=5)
    with pytest.raises(RuntimeError, match="injected crash"):
        wrapper(net1, tmp_path / "ck", inj).fit(
            ListDataSetIterator(batches), num_epochs=2)

    net2 = _net(seed=5)
    pw2 = wrapper(net2, tmp_path / "ck")
    pw2.fit(ListDataSetIterator(batches), num_epochs=2)
    assert pw2._round == 8
    assert pw2._resume_round == 5
    assert net2.conf.iteration_count == ref.conf.iteration_count
    np.testing.assert_allclose(np.asarray(net2.params()),
                               np.asarray(ref.params()), atol=1e-6)


def test_warm_net_is_not_clobbered_by_resume(tmp_path):
    """A model that already trained IN THIS PROCESS is a continuation,
    not a crash restart: pointing it at a populated checkpoint dir must
    not roll it back."""
    ds = _data(128, seed=1)
    net = _net(seed=2)
    tm = _master(tmp_path / "ck")
    tm.execute_training(net, ds)
    it_after = net.conf.iteration_count
    assert it_after > 0
    # same net, new master over the SAME populated dir: no rollback
    tm2 = _master(tmp_path / "ck")
    tm2.execute_training(net, ds)
    assert net.conf.iteration_count > it_after


# ---------------------------------------------------------------------------
# (d) mid-stream producer error surfaces under a full queue
# ---------------------------------------------------------------------------

def test_producer_error_surfaces_not_hangs_under_full_queue():
    from deeplearning4j_tpu.datasets.iterators import (AsyncDataSetIterator,
                                                       DataSetIterator)

    class MidStreamCorruption(DataSetIterator):
        """10 good batches, then the source blows up (a corrupt file in
        FileDataSetIterator, a flaky decoder...)."""

        def __init__(self):
            self._i = 0

        def reset(self):
            self._i = 0

        def has_next(self):
            return True            # the source still PROMISES more

        def next_batch(self):
            if self._i >= 10:
                raise ValueError("corrupt record mid-stream")
            self._i += 1
            return DataSet(np.zeros((2, 3), np.float32),
                           np.zeros((2, 1), np.float32))

    result = {}

    def consume():
        try:
            # tiny queues + a consumer slower than staging keep the
            # bounded futs queue FULL when the producer hits the error —
            # exactly the state that used to drop the exception and the
            # sentinel and hang the consumer forever (ADVICE r5)
            it = AsyncDataSetIterator(MidStreamCorruption(), queue_size=1,
                                      num_workers=2, device_put=False)
            n = 0
            while it.has_next():
                time.sleep(0.05)
                it.next_batch()
                n += 1
            result["consumed"] = n
        except BaseException as e:  # noqa: BLE001 — recorded for asserts
            result["err"] = e

    t = threading.Thread(target=consume, daemon=True)
    t.start()
    t.join(timeout=60)
    assert not t.is_alive(), \
        "consumer hung: the producer's mid-stream error was dropped"
    err = result.get("err")
    assert isinstance(err, RuntimeError)
    assert isinstance(err.__cause__, ValueError)
    assert "corrupt record" in str(err.__cause__)


# ---------------------------------------------------------------------------
# random-churn stress (timing-heavy -> slow tier)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_worker_fit_survives_random_severs_with_exact_accounting():
    """A full ps_worker_fit run with seeded random connection severs on
    both pull and push: the run completes, no worker is reaped (heartbeats
    ride a separate socket), and the applied count is EXACT — dedup keeps
    every retried push at-most-once even under churn."""
    from deeplearning4j_tpu.parallel.ps_transport import (PSServer,
                                                          ps_worker_fit)

    net = _net()
    ds = _data(256, seed=4)
    srv = PSServer(net, queue_size=4, n_workers=1, heartbeat_timeout=5.0)
    try:
        inj = FaultInjector(seed=7)
        inj.plan("client.push.sent", prob=0.25, times=4, sever=True)
        inj.plan("client.pull", prob=0.2, times=3, sever=True)
        pol = RetryPolicy(max_retries=10, base_delay=0.01, max_delay=0.05,
                          seed=2)
        worker = _net(seed=3)
        ps_worker_fit(worker, "127.0.0.1", srv.port,
                      ListDataSetIterator(list(ds.batch_by(32))),
                      num_epochs=2, retry_policy=pol,
                      heartbeat_interval=0.2, fault_injector=inj)
        final = srv.wait(timeout=240)
    finally:
        srv.stop()
    assert final["applied"] + final["stale_dropped"] == 16  # 8 x 2 epochs
    assert final["workers_reaped"] == 0
    assert len(inj.fired()) >= 1
