"""Overload-robustness pins (ISSUE 9 acceptance criteria).

  (a) Chunked prefill determinism: the chunked stream is BIT-IDENTICAL
      to the one-shot-prefill stream — fixed-slot and paged layouts,
      solo and joining a running batch, composed with the prefix cache
      (where chunking additionally SAVES prompt compute: fewer chunk
      dispatches on a hit) and with speculative decoding.
  (b) Deadline-aware admission: the service-rate estimator warms before
      it may shed, sheds predicted deadline misses at ENQUEUE
      (`shed_predicted`), never sheds a request solo execution would
      have completed within deadline (the conservatism invariant,
      property-tested), and publishes its signed prediction error
      (`admission_error_ms`) + live capacity on snapshot/Prometheus.
  (c) Brownout policy: accept/defer/shed per class is an explicit unit-
      testable object; deferred requests park, yield to the primary
      queue, still decode bit-identically, and fail promptly on
      fail-fast stop (the PR 8 memory-waiter livelock pin, extended).
  (d) Overload drain: stop(drain=True) under a saturated queue with
      parked memory-waiters drains bounded by the remaining work —
      expired-deadline backlog sheds at admission instead of decoding.
"""
import time

import numpy as np
import pytest

from deeplearning4j_tpu.models.zoo.transformer import TransformerLM
from deeplearning4j_tpu.serving import (AdmissionController,
                                        BrownoutPolicy,
                                        ContinuousDecodeServer,
                                        NGramDraft, ServerClosedError,
                                        ServerOverloadedError,
                                        ServiceRateEstimator, Speculator)
from deeplearning4j_tpu.serving.admission import ACCEPT, DEFER, SHED


def _lm(seed=3):
    return TransformerLM(64, d_model=16, n_heads=2, n_layers=1,
                         max_len=48, seed=seed)


# ---------------------------------------------------------------------------
# (a) chunked prefill determinism
# ---------------------------------------------------------------------------
class TestChunkedPrefill:
    def test_chunk_size_guards(self):
        lm = _lm()
        # 1-row chunks take XLA:CPU's gemv path (different accumulation
        # order) — the same floor every padding bucket enforces
        with pytest.raises(ValueError, match="gemv|>= 2"):
            ContinuousDecodeServer(lm, chunked_prefill=1)
        with pytest.raises(ValueError, match="max_len"):
            ContinuousDecodeServer(lm, chunked_prefill=1000)

    def test_chunked_equals_one_shot_fixed(self):
        """Prompt lengths spanning below/at/above the chunk size (and a
        single-token prompt) through ONE chunked server: every stream
        bit-identical to the pinned generate() reference."""
        lm = _lm()
        rng = np.random.default_rng(4)
        cases = [rng.integers(1, 64, n).tolist()
                 for n in (1, 3, 4, 5, 11, 16)]
        with ContinuousDecodeServer(lm, slots=2, prompt_buckets=(8, 16),
                                    chunked_prefill=4) as srv:
            for p in cases:
                assert srv.generate(p, 7, timeout=120) == \
                    lm.generate(p, max_new_tokens=7)
            snap = srv.metrics.snapshot()
        # the chunk SIZING RULE: prompts longer than one chunk run
        # ceil(plen/C) chunk dispatches; prompts that fit in one chunk
        # take the cheaper one-shot bucket program (zero chunks)
        assert snap["chunk_dispatches"] == sum(
            -(-len(p) // 4) for p in cases if len(p) > 4)

    def test_chunked_equals_one_shot_paged(self):
        lm = _lm()
        rng = np.random.default_rng(5)
        cases = [rng.integers(1, 64, n).tolist() for n in (1, 4, 9, 14)]
        with ContinuousDecodeServer(lm, slots=2, prompt_buckets=(16,),
                                    paged=True, block_size=4,
                                    n_blocks=40,
                                    chunked_prefill=4) as srv:
            for p in cases:
                assert srv.generate(p, 7, timeout=120) == \
                    lm.generate(p, max_new_tokens=7)
            assert srv._pool.blocks_in_use == 0

    def test_chunked_join_equals_solo(self):
        """The join==solo pin EXTENDED: a long-prompt joiner prefilling
        in chunks beside live decoders changes nobody's bits — neither
        its own nor its co-residents'."""
        lm = _lm()
        rng = np.random.default_rng(6)
        pa = rng.integers(1, 64, 4).tolist()
        pb = rng.integers(1, 64, 15).tolist()     # the long joiner
        pc = rng.integers(1, 64, 3).tolist()
        with ContinuousDecodeServer(lm, slots=3, prompt_buckets=(8, 16),
                                    chunked_prefill=4) as srv:
            solo = {k: srv.generate(p, n, timeout=120)
                    for k, (p, n) in {"a": (pa, 12), "b": (pb, 10),
                                      "c": (pc, 8)}.items()}
            fa = srv.submit(pa, 12)
            time.sleep(0.03)                      # a is decoding...
            fb = srv.submit(pb, 10)               # ...b chunks in beside
            fc = srv.submit(pc, 8)
            assert fa.result(120) == solo["a"]
            assert fb.result(120) == solo["b"]
            assert fc.result(120) == solo["c"]

    def test_chunked_prefix_hit_saves_chunk_dispatches(self):
        """Chunked prefill COMPOSES with the prefix cache — and closes
        the PR 8 compute-reuse seam: a full-prefix hit re-runs ONE
        chunk (the final row, for its logits) instead of the whole
        prompt, streams bit-identical and hit counters live."""
        lm = _lm()
        rng = np.random.default_rng(7)
        p = rng.integers(1, 64, 11).tolist()      # 2 full blocks + tail
        with ContinuousDecodeServer(lm, slots=2, prompt_buckets=(16,),
                                    paged=True, block_size=4,
                                    n_blocks=40, prefix_cache=False,
                                    chunked_prefill=4) as srv:
            unshared = srv.generate(p, 8, timeout=120)
        with ContinuousDecodeServer(lm, slots=2, prompt_buckets=(16,),
                                    paged=True, block_size=4,
                                    n_blocks=40,
                                    chunked_prefill=4) as srv:
            first = srv.generate(p, 8, timeout=120)
            c1 = srv.metrics.snapshot()["chunk_dispatches"]
            again = srv.generate(p, 8, timeout=120)
            snap = srv.metrics.snapshot()
        assert first == unshared and again == unshared
        assert c1 == 3                  # ceil(11/4) chunks, no hit
        assert snap["chunk_dispatches"] - c1 == 1   # full hit: 1 chunk
        assert snap["prefix_rows_hit"] >= 8

    def test_chunked_shared_prefix_streams_unperturbed(self):
        """Two concurrent streams behind one system prefix, chunked +
        paged: shared leading blocks + write-window gating change WHERE
        rows live and WHAT gets recomputed, never any stream's bits."""
        lm = _lm()
        rng = np.random.default_rng(8)
        sysp = rng.integers(1, 64, 8).tolist()
        pa = sysp + rng.integers(1, 64, 3).tolist()
        pb = sysp + rng.integers(1, 64, 2).tolist()
        with ContinuousDecodeServer(lm, slots=2, prompt_buckets=(16,),
                                    paged=True, block_size=4,
                                    n_blocks=40, prefix_cache=False,
                                    chunked_prefill=4) as srv:
            ra0 = srv.generate(pa, 24, timeout=120)
            rb0 = srv.generate(pb, 8, timeout=120)
        with ContinuousDecodeServer(lm, slots=2, prompt_buckets=(16,),
                                    paged=True, block_size=4,
                                    n_blocks=40,
                                    chunked_prefill=4) as srv:
            fa = srv.submit(pa, 24)
            # chunked commit is DEFERRED to the final chunk (a failed
            # chunk must never leave garbage blocks matchable): wait for
            # a's blocks to become matchable, then join b while a still
            # DECODES — co-resident prefix reuse, not sequential
            deadline = time.monotonic() + 30
            while srv.metrics.snapshot()["prefix_rows_total"] < len(pa) \
                    and time.monotonic() < deadline:
                time.sleep(0.002)
            fb = srv.submit(pb, 8)
            ra, rb = fa.result(120), fb.result(120)
            snap = srv.metrics.snapshot()
            assert srv._pool.blocks_in_use == 0
        assert ra == ra0 and rb == rb0
        assert snap["prefix_rows_hit"] >= 8

    def test_chunked_composes_with_speculate(self):
        """Chunked prefill + K-wide speculative decode: still the plain
        greedy stream, bit for bit."""
        lm = _lm()
        rng = np.random.default_rng(9)
        pat = rng.integers(1, 64, 3).tolist()
        p = (pat * 5)[:9]
        expect = lm.generate(p, max_new_tokens=10)
        with ContinuousDecodeServer(
                lm, slots=2, prompt_buckets=(16,), chunked_prefill=4,
                speculate=Speculator(NGramDraft(n=3), k=4)) as srv:
            assert srv.generate(p, 10, timeout=120) == expect

    def test_chunked_one_token_request_releases_at_prefill(self):
        lm = _lm()
        p = [5, 9, 2, 7, 1]
        expect = lm.generate(p, max_new_tokens=1)
        with ContinuousDecodeServer(lm, slots=2, prompt_buckets=(8,),
                                    paged=True, block_size=4,
                                    n_blocks=20,
                                    chunked_prefill=2) as srv:
            assert srv.generate(p, 1, timeout=120) == expect
            assert srv._pool.blocks_in_use == 0

    def test_mid_prefill_deadline_eviction_releases_blocks(self):
        """A deadline expiring DURING chunked prefill evicts the slot
        between iterations: future fails, blocks release, the server
        keeps serving."""
        from deeplearning4j_tpu.common.resilience import FaultInjector
        from deeplearning4j_tpu.serving import DeadlineExceededError
        lm = _lm()
        rng = np.random.default_rng(10)
        p = rng.integers(1, 64, 16).tolist()
        inj = FaultInjector(seed=3).plan(
            "serve.batch", on_calls=range(0, 200), times=200,
            delay=0.03, exc=None)
        with ContinuousDecodeServer(lm, slots=2, prompt_buckets=(16,),
                                    paged=True, block_size=4,
                                    n_blocks=40, chunked_prefill=2,
                                    fault_injector=inj) as srv:
            # warm the compile OFF the doomed request's clock (delay
            # plan paces every dispatch; compile only the first)
            srv.generate([1, 2], 2, deadline_ms=600_000, timeout=120)
            doomed = srv.submit(p, 8, deadline_ms=60)   # 8 chunks x30ms
            with pytest.raises(DeadlineExceededError):
                doomed.result(120)
            deadline = time.monotonic() + 10
            while srv._pool.blocks_in_use and time.monotonic() < deadline:
                time.sleep(0.01)
            assert srv._pool.blocks_in_use == 0
            assert srv.metrics.snapshot()["shed_deadline"] == 1


# ---------------------------------------------------------------------------
# (b) deadline-aware admission
# ---------------------------------------------------------------------------
class TestServiceRateEstimator:
    def test_warm_up_guard_and_prediction(self):
        est = ServiceRateEstimator(slots=4, min_samples=4)
        assert not est.ready
        assert est.predict_seconds(100, 10) is None
        for _ in range(4):
            est.observe(4, 0.01, active=4)      # 4 slots, 10ms/iter
        assert est.ready
        assert est.seconds_per_iteration == pytest.approx(0.01)
        assert est.tokens_per_second == pytest.approx(400.0)
        # 100 backlog tokens at 400 tok/s + 10 own iterations
        assert est.predict_seconds(100, 10) == pytest.approx(0.35)

    def test_median_absorbs_compile_outlier(self):
        """One compile-sized sample (1000x an iteration) must not move
        predictions — the rolling median, unlike an EWMA, shrugs it
        off."""
        est = ServiceRateEstimator(slots=2, min_samples=2)
        for _ in range(9):
            est.observe(2, 0.002, active=2)
        est.observe(2, 2.0, active=2)           # the compile spike
        assert est.seconds_per_iteration == pytest.approx(0.002)

    def test_zero_token_iterations_lengthen_but_never_ready(self):
        est = ServiceRateEstimator(slots=2, min_samples=2)
        for _ in range(50):
            est.observe(0, 0.005)               # chunk-only passes
        assert not est.ready                    # no token-bearing iters

    def test_controller_guards(self):
        with pytest.raises(ValueError, match="conservatism"):
            AdmissionController(conservatism=0.5)
        ac = AdmissionController(min_samples=1, slots=2)
        assert not ac.should_shed(10_000, 100, 0.001)   # cold: never
        ac.estimator.observe(2, 0.01, active=2)
        assert ac.should_shed(10_000, 100, 0.001)
        assert not ac.should_shed(10_000, 100, None)    # no deadline

    def test_variance_margin_widens_predictions(self):
        """High-variance acceptance (the speculative regime: per-slot
        rate swinging 1..K) must make predictions MORE conservative
        than the mean rate implies — the margined rate sits below the
        EWMA mean, never below the structural 1.0 floor, and a steady
        stream (plain decode: every sample exactly 1.0) pays nothing."""
        est = ServiceRateEstimator(slots=2, min_samples=4, margin=1.0)
        for t in (4, 1, 4, 1, 4, 1, 4, 1):      # thrash-shaped stream
            est.observe(t, 0.01, active=1)
        cons = est.tokens_per_slot_conservative
        assert 1.0 <= cons < est._tok_slot
        # zero-variance stream: margin is free, any margin value
        steady = ServiceRateEstimator(slots=2, min_samples=4, margin=5.0)
        for _ in range(10):
            steady.observe(2, 0.01, active=2)
        assert steady.tokens_per_slot_conservative \
            == steady._tok_slot == 1.0
        # wider margin => longer (or equal) predictions, same samples
        wide = ServiceRateEstimator(slots=2, min_samples=4, margin=3.0)
        for t in (4, 1, 4, 1, 4, 1, 4, 1):
            wide.observe(t, 0.01, active=1)
        assert wide.predict_seconds(100, 10) \
            >= est.predict_seconds(100, 10)
        with pytest.raises(ValueError, match="margin"):
            ServiceRateEstimator(margin=-1.0)

    def test_variance_margin_never_sheds_feasible_solo_property(self):
        """The never-sheds-feasible-solo invariant survives ANY margin:
        whatever high-variance sample stream the estimator saw, a
        request whose deadline covers its WORST-CASE solo run
        (own_units x s_iter — one token per iteration, the speculative
        floor: every round lands at least its bonus token) is never
        shed on an idle server. Structural, because the margined rate
        is floored at 1.0 token/slot/iteration — property-tested over
        random streams and margins."""
        rng = np.random.default_rng(12)
        for trial in range(20):
            margin = float(rng.uniform(0.0, 4.0))
            ac = AdmissionController(conservatism=1.0, min_samples=4,
                                     slots=int(rng.integers(1, 8)),
                                     margin=margin)
            k = int(rng.integers(2, 9))
            for _ in range(int(rng.integers(8, 40))):
                # per-slot rates in [1, K]: the speculative envelope
                active = int(rng.integers(1, ac.estimator.slots + 1))
                per_slot = int(rng.integers(1, k + 1))
                ac.estimator.observe(per_slot * active,
                                     float(rng.uniform(0.002, 0.05)),
                                     active=active)
            own = int(rng.integers(1, 50))
            worst_solo = own * ac.estimator.seconds_per_iteration
            assert not ac.should_shed(0, own, worst_solo), (
                f"trial {trial}: margin {margin} shed a feasible solo "
                f"request")


class TestDeadlineAwareAdmission:
    def test_sheds_predicted_at_submit(self):
        lm = _lm()
        ac = AdmissionController(conservatism=1.0, min_samples=2)
        with ContinuousDecodeServer(lm, slots=2, prompt_buckets=(8,),
                                    admission=ac) as srv:
            for _ in range(3):                  # warm the estimator
                srv.generate([1, 2, 3], 6, timeout=120)
            assert ac.estimator.ready
            with pytest.raises(ServerOverloadedError,
                               match="predicted"):
                srv.submit([1, 2, 3], 40, deadline_ms=1)
            snap = srv.metrics.snapshot()
        assert snap["shed_predicted"] == 1
        assert snap["service_rate_tokens_per_sec"] is not None

    def test_conservatism_invariant_property(self):
        """The predictor never sheds a request that solo execution
        would have completed within deadline: random feasible requests
        against an IDLE warmed server (deadline = 3x measured solo
        time, floored well above scheduler jitter) must all admit and
        complete in time. The margin is weather, not semantics: the
        shared-CPU host runs back-to-back identical work >2x apart
        (measured, PERF r12), so a 2x budget flakes on the
        COMPLETION half of the assertion while the shedding half —
        the property under test — was never in doubt."""
        lm = _lm()
        rng = np.random.default_rng(11)
        with ContinuousDecodeServer(lm, slots=2, prompt_buckets=(8,),
                                    admission=AdmissionController(
                                        min_samples=4)) as srv:
            for _ in range(3):                  # warm compile+estimator
                srv.generate([1, 2, 3], 8, timeout=120)
            for _ in range(8):
                p = rng.integers(1, 64, int(rng.integers(1, 8))).tolist()
                n = int(rng.integers(2, 14))
                t0 = time.monotonic()
                solo = srv.generate(p, n, timeout=120)  # idle => solo
                solo_ms = (time.monotonic() - t0) * 1e3
                got = srv.generate(p, n,
                                   deadline_ms=max(3 * solo_ms, 250),
                                   timeout=120)
                assert got == solo
            snap = srv.metrics.snapshot()
        assert snap["shed_predicted"] == 0
        assert snap.get("evicted_mid_decode", 0) == 0

    def test_admission_error_histogram_and_exposition(self):
        from deeplearning4j_tpu.obs import MetricsRegistry
        from deeplearning4j_tpu.serving import ServingMetrics
        lm = _lm()
        reg = MetricsRegistry()
        metrics = ServingMetrics(registry=reg, name="adm")
        with ContinuousDecodeServer(lm, slots=2, prompt_buckets=(8,),
                                    metrics=metrics,
                                    admission=AdmissionController(
                                        min_samples=2)) as srv:
            for _ in range(4):
                srv.generate([1, 2, 3], 6, timeout=120)
        snap = metrics.snapshot()
        # estimator warmed after request 1-2: later completions carry a
        # prediction, so the signed error histogram has mass
        assert snap["admission_error_ms_count"] >= 1
        assert snap["admission_error_ms_p50"] is not None
        text = reg.prometheus_text()
        assert "# TYPE serving_adm_admission_error_ms histogram" in text
        assert "serving_adm_service_rate_tokens_per_sec" in text


# ---------------------------------------------------------------------------
# (c) brownout policy
class TestPrefixPriorityAdmission:
    """Prefix-hit priority admission (ISSUE 10 satellite / ROADMAP
    overload seam 2): a full-prefix hit costs ONE chunk of prefill, so
    it overtakes queued cold prompts when both fit."""

    def _srv(self, lm, **kw):
        kw.setdefault("slots", 1)
        kw.setdefault("prompt_buckets", (8, 16))
        kw.setdefault("block_size", 4)
        kw.setdefault("n_blocks", 40)
        kw.setdefault("chunked_prefill", 4)
        return ContinuousDecodeServer(lm, paged=True, **kw)

    def test_prefix_hit_overtakes_cold_prompt(self):
        """slots=1, the slot held by a long request: a cold prompt
        queued FIRST is overtaken by a later full-prefix-hit request —
        completion order flips, `admitted_prefix_priority` counts the
        reorder, and BOTH streams stay bit-identical to solo."""
        lm = _lm()
        sysp = list(range(1, 9))                 # 2 full blocks
        order = []
        with self._srv(lm) as srv:
            srv.generate(sysp + [9], 4, timeout=120)   # prime the index
            fa = srv.submit(list(range(20, 28)), 30)   # holds the slot
            cold = list(range(30, 42))
            hit = sysp + [13]
            fb = srv.submit(cold, 6)
            fb.add_done_callback(lambda f: order.append("cold"))
            fc = srv.submit(hit, 6)
            fc.add_done_callback(lambda f: order.append("hit"))
            fa.result(120)
            rb, rc = fb.result(120), fc.result(120)
            snap = srv.metrics.snapshot()
        assert rb == lm.generate(cold, max_new_tokens=6)
        assert rc == lm.generate(hit, max_new_tokens=6)
        assert order == ["hit", "cold"]
        assert snap["admitted_prefix_priority"] == 1

    def test_priority_off_keeps_fifo(self):
        """prefix_priority=False: the same workload admits in FIFO
        order and the counter never moves."""
        lm = _lm()
        sysp = list(range(1, 9))
        order = []
        with self._srv(lm, prefix_priority=False) as srv:
            srv.generate(sysp + [9], 4, timeout=120)
            fa = srv.submit(list(range(20, 28)), 30)
            fb = srv.submit(list(range(30, 42)), 6)
            fb.add_done_callback(lambda f: order.append("cold"))
            fc = srv.submit(sysp + [13], 6)
            fc.add_done_callback(lambda f: order.append("hit"))
            fa.result(120), fb.result(120), fc.result(120)
            snap = srv.metrics.snapshot()
        assert order == ["cold", "hit"]
        assert snap["admitted_prefix_priority"] == 0

    def test_cold_prompt_never_takes_priority(self):
        """A prompt with NO resident prefix stays in the FIFO queue
        even with priority armed (the line is for hits only)."""
        lm = _lm()
        with self._srv(lm) as srv:
            got = srv.generate(list(range(30, 42)), 4, timeout=120)
            snap = srv.metrics.snapshot()
        assert got == lm.generate(list(range(30, 42)), max_new_tokens=4)
        assert snap["admitted_prefix_priority"] == 0

    def test_priority_burst_cannot_starve_cold_prompts(self):
        """After _PRIO_BURST consecutive overtakes the primary head
        gets one turn: 6 parked hits + 1 parked cold on a slots=1
        server admit as hit x4, cold, hit x2 — sustained hit traffic
        degrades a cold prompt's position, never parks it forever."""
        lm = _lm()
        sysp = list(range(1, 9))
        order = []
        with self._srv(lm, max_queue=16) as srv:
            srv.generate(sysp + [9], 4, timeout=120)   # prime the index
            fa = srv.submit(list(range(20, 28)), 30)   # holds the slot
            deadline = time.monotonic() + 20
            while not any(srv._slot_req) and time.monotonic() < deadline:
                time.sleep(0.002)
            cold = list(range(30, 42))
            fc = srv.submit(cold, 4)
            fc.add_done_callback(lambda f: order.append("cold"))
            hits = []
            for i in range(6):
                f = srv.submit(sysp + [10 + i], 4)
                f.add_done_callback(
                    lambda _f, j=i: order.append(f"hit{j}"))
                hits.append(f)
            fa.result(120)
            fc.result(120)
            for f in hits:
                f.result(120)
            snap = srv.metrics.snapshot()
        assert order == ["hit0", "hit1", "hit2", "hit3", "cold",
                         "hit4", "hit5"]
        # hits 4-5 popped against an EMPTY primary queue (the cold
        # request was already served): no overtake, not counted
        assert snap["admitted_prefix_priority"] == 4

    def test_idle_server_serves_priority_submit(self):
        """A prefix-hit submit landing on an IDLE server rides the
        priority line through the idle wait (the blocking get watches
        only the primary queue — the poll must see the parked line)
        and decodes bit-identically."""
        lm = _lm()
        sysp = list(range(1, 9))
        with self._srv(lm) as srv:
            srv.generate(sysp + [9], 4, timeout=120)   # prime the index
            time.sleep(0.12)    # let the loop settle into its idle wait
            got = srv.generate(sysp + [13], 5, timeout=120)
        assert got == lm.generate(sysp + [13], max_new_tokens=5)

    def test_priority_line_shares_queue_budget(self):
        """max_queue bounds the SUM of the primary queue and the
        priority line, both ways: parked hits consume the backpressure
        budget cold submits see, and vice versa — two lines must not
        stack 2x the operator's bound."""
        lm = _lm()
        sysp = list(range(1, 9))
        with self._srv(lm, max_queue=2) as srv:
            srv.generate(sysp + [9], 4, timeout=120)   # prime the index
            fa = srv.submit(list(range(20, 28)), 30)   # holds the slot
            deadline = time.monotonic() + 20
            while not any(srv._slot_req) and time.monotonic() < deadline:
                time.sleep(0.002)
            f1 = srv.submit(sysp + [13], 4)            # parks: prio 1/2
            f2 = srv.submit(sysp + [14], 4)            # parks: prio 2/2
            with pytest.raises(ServerOverloadedError, match="queue full"):
                srv.submit(list(range(30, 38)), 4)     # cold: budget gone
            with pytest.raises(ServerOverloadedError, match="queue full"):
                srv.submit(sysp + [15], 4)             # hit: budget gone
            fa.result(120)
            r1, r2 = f1.result(120), f2.result(120)
            snap = srv.metrics.snapshot()
        assert r1 == lm.generate(sysp + [13], max_new_tokens=4)
        assert r2 == lm.generate(sysp + [14], max_new_tokens=4)
        assert snap["shed_queue_full"] == 2

    def test_deadline_expires_in_priority_line(self):
        """Priority-line wait is queue wait: the deadline sweep fails a
        parked priority request and counts the shed."""
        from deeplearning4j_tpu.common.resilience import FaultInjector
        from deeplearning4j_tpu.serving import DeadlineExceededError
        lm = _lm()
        sysp = list(range(1, 9))
        inj = FaultInjector(seed=9).plan(
            "serve.batch", on_calls=range(0, 300), times=300,
            delay=0.02, exc=None)
        with self._srv(lm, fault_injector=inj) as srv:
            srv.generate(sysp + [9], 4, deadline_ms=600_000,
                         timeout=120)                  # prime + compile
            fa = srv.submit(list(range(20, 28)), 40)   # slot held long
            # wait until fa actually OWNS the slot: a priority submit
            # racing fa's queue pop would legitimately overtake it and
            # win the slot instead of parking
            deadline = time.monotonic() + 20
            while not any(srv._slot_req) and time.monotonic() < deadline:
                time.sleep(0.002)
            doomed = srv.submit(sysp + [13], 6, deadline_ms=100)
            with pytest.raises(DeadlineExceededError,
                               match="priority|before prefill"):
                doomed.result(120)
            fa.result(120)
            snap = srv.metrics.snapshot()
        assert snap["shed_deadline"] == 1
        # the doomed request never ADMITTED: an expired pop must not
        # count as a reordered admission
        assert snap["admitted_prefix_priority"] == 0


# ---------------------------------------------------------------------------
class TestBrownoutPolicy:
    def test_decide_thresholds(self):
        bp = BrownoutPolicy(classes={"batch": (0.5, 0.9)})
        assert bp.decide("batch", 0.1) == ACCEPT
        assert bp.decide("batch", 0.5) == DEFER
        assert bp.decide("batch", 0.95) == SHED
        # unlisted classes use the never-defer default
        assert bp.decide("interactive", 0.95) == ACCEPT

    def test_attainment_brownout(self):
        bp = BrownoutPolicy(classes={"batch": (0.5, 0.9)},
                            min_attainment=0.8)
        assert bp.decide("batch", 0.0, attainment=0.9) == ACCEPT
        assert bp.decide("batch", 0.0, attainment=0.5) == DEFER
        assert bp.decide("batch", 0.0, attainment=None) == ACCEPT

    def test_shed_below_defer_raises(self):
        with pytest.raises(ValueError, match="defer"):
            BrownoutPolicy(classes={"x": (0.9, 0.5)})

    def test_deferred_class_parks_and_still_decodes_identically(self):
        """batch-class requests defer (counter moves), interactive
        requests do not, and deferred work still produces the pinned
        bit-identical stream once pressure allows."""
        lm = _lm()
        bp = BrownoutPolicy(classes={"batch": (0.0, 1.01)})
        rng = np.random.default_rng(12)
        pi = rng.integers(1, 64, 4).tolist()
        pb = rng.integers(1, 64, 5).tolist()
        expect_i = lm.generate(pi, max_new_tokens=8)
        expect_b = lm.generate(pb, max_new_tokens=6)
        with ContinuousDecodeServer(lm, slots=2, prompt_buckets=(8,),
                                    brownout=bp) as srv:
            fb = srv.submit(pb, 6, klass="batch")   # defers (>= 0.0)
            fi = srv.submit(pi, 8)                  # default: accepted
            assert fi.result(120) == expect_i
            assert fb.result(120) == expect_b
            snap = srv.metrics.snapshot()
        assert snap["deferred"] == 1
        assert snap["shed_brownout"] == 0

    def test_brownout_shed_class(self):
        lm = _lm()
        bp = BrownoutPolicy(classes={"batch": (0.0, 0.0)})
        with ContinuousDecodeServer(lm, slots=2, prompt_buckets=(8,),
                                    brownout=bp) as srv:
            with pytest.raises(ServerOverloadedError, match="brownout"):
                srv.submit([1, 2, 3], 4, klass="batch")
            got = srv.generate([1, 2, 3], 4, timeout=120)  # default ok
            snap = srv.metrics.snapshot()
        assert snap["shed_brownout"] == 1
        assert got == lm.generate([1, 2, 3], max_new_tokens=4)

    def test_fail_fast_stop_fails_deferred(self):
        """stop(drain=False) with requests parked in the deferred line:
        the parked futures fail with ServerClosedError and the loop
        exits promptly — deferred requests count as _busy(), so leaving
        them parked would spin the serve thread forever (the PR 8
        memory-waiter livelock, extended)."""
        from deeplearning4j_tpu.common.resilience import FaultInjector
        lm = _lm()
        bp = BrownoutPolicy(classes={"batch": (0.0, 1.01)})
        inj = FaultInjector(seed=4).plan(
            "serve.batch", on_calls=range(0, 200), times=200,
            delay=0.02, exc=None)
        srv = ContinuousDecodeServer(lm, slots=1, prompt_buckets=(8,),
                                     brownout=bp,
                                     fault_injector=inj).start()
        try:
            fa = srv.submit([1, 2, 3], 12)      # occupies the one slot
            time.sleep(0.1)
            fbs = [srv.submit([4, 5], 6, klass="batch")
                   for _ in range(3)]           # all park deferred
            assert srv.metrics.snapshot()["deferred"] == 3
        finally:
            srv.stop(drain=False, timeout=60)
        assert srv._thread is None              # loop actually exited
        assert fa.result(1)                     # busy slot finished
        for f in fbs:
            with pytest.raises(ServerClosedError):
                f.result(1)


# ---------------------------------------------------------------------------
# (d) overload drain
# ---------------------------------------------------------------------------
class TestOverloadDrain:
    def test_drain_stop_bounded_under_saturation(self):
        """stop(drain=True) on a SATURATED paged server — slow decode,
        deep deadline-carrying backlog, a request parked on the memory
        gate — must drain bounded by the remaining work: expired
        backlog sheds at admission instead of decoding, parked waiters
        admit as blocks free, and EVERY future resolves."""
        from deeplearning4j_tpu.common.resilience import FaultInjector
        lm = _lm()
        rng = np.random.default_rng(14)
        inj = FaultInjector(seed=5).plan(
            "serve.batch", on_calls=range(1, 400), times=400,
            delay=0.01, exc=None)
        srv = ContinuousDecodeServer(lm, slots=2, prompt_buckets=(8,),
                                     paged=True, block_size=4,
                                     n_blocks=8, max_queue=64,
                                     fault_injector=inj).start()
        futs = []
        try:
            p1 = rng.integers(1, 64, 7).tolist()
            futs.append(srv.submit(p1, 16))     # 6 of 8 blocks
            time.sleep(0.05)
            futs.append(srv.submit(p1, 16))     # parks on the mem gate
            # deep deadline-carrying backlog: most of it EXPIRES in the
            # queue while the head decodes — drain must shed it at
            # admission, not decode it
            for _ in range(24):
                futs.append(srv.submit(
                    rng.integers(1, 64, 3).tolist(), 8,
                    deadline_ms=100))
        finally:
            t0 = time.monotonic()
            srv.stop(drain=True, timeout=90)
            drain_s = time.monotonic() - t0
        assert srv._thread is None, "drain did not complete"
        assert drain_s < 60
        resolved = 0
        for f in futs:
            try:
                f.result(1)
                resolved += 1
            except Exception:       # noqa: BLE001 — shed/expired: fine
                resolved += 1
        assert resolved == len(futs)
        assert srv._pool.blocks_in_use == 0
