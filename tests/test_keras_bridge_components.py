"""Keras backend bridge (HTTP gateway) + UI component library.
reference: deeplearning4j-keras Server.java/DeepLearning4jEntryPoint.java
and deeplearning4j-ui-components."""
import json
import urllib.request

import h5py
import numpy as np
import pytest

from deeplearning4j_tpu.keras import KerasBridgeServer
from deeplearning4j_tpu.ui import (ChartHistogram, ChartLine, ChartTimeline,
                                   Component, ComponentDiv, ComponentTable,
                                   ComponentText, render_html)


def _write_keras_model(path):
    """Tiny Keras-1 sequential MLP in the HDF5 layout keras_import reads."""
    rng = np.random.default_rng(0)
    W1 = rng.standard_normal((4, 8)).astype(np.float32) * 0.3
    b1 = np.zeros(8, np.float32)
    W2 = rng.standard_normal((8, 2)).astype(np.float32) * 0.3
    b2 = np.zeros(2, np.float32)
    cfg = {"class_name": "Sequential", "config": [
        {"class_name": "Dense",
         "config": {"name": "d1", "output_dim": 8, "activation": "relu",
                    "batch_input_shape": [None, 4]}},
        {"class_name": "Dense",
         "config": {"name": "d2", "output_dim": 2,
                    "activation": "softmax"}}]}
    with h5py.File(path, "w") as f:
        f.attrs["model_config"] = json.dumps(cfg).encode("utf-8")
        mw = f.create_group("model_weights")
        for lname, arrs in [("d1", [("W", W1), ("b", b1)]),
                            ("d2", [("W", W2), ("b", b2)])]:
            g = mw.create_group(lname)
            names = []
            for suffix, arr in arrs:
                n = f"{lname}_{suffix}"
                g.create_dataset(n, data=arr)
                names.append(n.encode())
            g.attrs["weight_names"] = names


class TestKerasBridge:
    def test_fit_and_predict_over_http(self, tmp_path):
        model_path = str(tmp_path / "model.h5")
        _write_keras_model(model_path)
        rng = np.random.default_rng(1)
        x = rng.random((64, 4)).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[(x.sum(1) > 2).astype(int)]
        fpath, lpath = str(tmp_path / "x.h5"), str(tmp_path / "y.h5")
        with h5py.File(fpath, "w") as f:
            f.create_dataset("features", data=x)
        with h5py.File(lpath, "w") as f:
            f.create_dataset("labels", data=y)

        server = KerasBridgeServer().start()
        try:
            base = f"http://127.0.0.1:{server.port}"
            with urllib.request.urlopen(base + "/health") as r:
                assert json.load(r)["ok"]

            def post(path, payload):
                req = urllib.request.Request(
                    base + path, data=json.dumps(payload).encode(),
                    headers={"Content-Type": "application/json"})
                with urllib.request.urlopen(req) as r:
                    return json.load(r)

            scores = [post("/fit", {"model_path": model_path,
                                    "features_path": fpath,
                                    "labels_path": lpath,
                                    "nb_epoch": 3, "batch_size": 16})
                      ["score"] for _ in range(4)]
            assert scores[-1] < scores[0]     # repeated fits keep learning
            preds = np.asarray(post("/predict",
                                    {"model_path": model_path,
                                     "features_path": fpath})
                               ["predictions"])
            assert preds.shape == (64, 2)
            assert np.allclose(preds.sum(1), 1.0, atol=1e-3)
            # errors surface as HTTP codes, not hung connections
            req = urllib.request.Request(
                base + "/fit", data=b'{"model_path": "missing.h5"}',
                headers={"Content-Type": "application/json"})
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(req)
            assert ei.value.code in (400, 500)
        finally:
            server.stop()


class TestUIComponents:
    def test_component_json_round_trips(self):
        line = (ChartLine(title="loss", x_label="iter", y_label="score")
                .add_series("train", [0, 1, 2], [1.0, 0.5, 0.2])
                .add_series("val", [0, 1, 2], [1.1, 0.7, 0.4]))
        hist = ChartHistogram(title="weights").add_bin(-1, 0, 5).add_bin(
            0, 1, 7)
        tl = ChartTimeline(title="phases").add_lane(
            "worker0", [(0, 10, "fit"), (10, 12, "avg")])
        table = ComponentTable(["k", "v"], [["a", "1"], ["b", "2"]],
                               title="stats")
        text = ComponentText("hello world")
        div = ComponentDiv(line, hist, tl, table, text)
        for comp in (line, hist, tl, table, text, div):
            back = Component.from_json(comp.to_json())
            assert back.to_dict() == comp.to_dict()

    def test_unknown_component_raises(self):
        with pytest.raises(ValueError, match="Unknown component"):
            Component.from_dict({"componentType": "Nope"})

    def test_render_html_embeds_data(self):
        line = ChartLine(title="curve").add_series("s", [0, 1], [2.0, 3.0])
        html = render_html([line, ComponentText("note")], title="Report")
        assert "<title>Report</title>" in html
        assert "ChartLine" in html and "note" in html
        # data is embedded as a JSON island the renderer parses
        assert 'type=\'application/json\'' in html

    def test_training_stats_to_components(self):
        """TrainingMasterStats timeline -> ChartTimeline (the HTML export
        path the reference builds from SparkTrainingStats)."""
        from deeplearning4j_tpu.parallel import TrainingMasterStats
        stats = TrainingMasterStats()
        stats.record("fit", 1.0, 0.5)
        stats.record("split", 1.5, 0.1)
        tl = ChartTimeline(title="phases")
        entries = [(e["startMs"], e["startMs"] + e["durationMs"],
                    e["phase"]) for e in stats.events]
        tl.add_lane("master", entries)
        d = tl.to_dict()
        assert len(d["lanes"][0]["entries"]) == 2
        assert d["lanes"][0]["entries"][0]["label"] == "fit"
