"""Pipeline parallelism tests on the virtual 8-device CPU mesh.

The reference has no pipeline parallelism (SURVEY.md §2.5); these tests pin
the TPU-first extension: GPipe schedule == sequential execution exactly
(forward AND gradients — the transpose-of-rotation backward), and a
pipelined transformer LM trains.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.models.zoo.transformer import (
    TransformerLM, embed_fn, init_lm, lm_loss, make_block_fn)
from deeplearning4j_tpu.parallel.pipeline import (
    PipelineParallel, gpipe, make_pipeline_mesh, microbatch,
    stack_stage_params)

pytestmark = pytest.mark.skipif(len(jax.devices()) < 8,
                                reason="needs 8 virtual devices")


def _mlp_stages(n, d, seed=0):
    rng = np.random.default_rng(seed)
    return [{"W": jnp.asarray(rng.standard_normal((d, d)) * 0.3,
                              jnp.float32),
             "b": jnp.zeros(d, jnp.float32)} for _ in range(n)]


def _mlp_stage_fn(p, x):
    return jnp.tanh(x @ p["W"] + p["b"])


class TestGPipeSchedule:
    def test_forward_matches_sequential(self):
        mesh = make_pipeline_mesh(n_pipe=4, n_data=2)
        params = _mlp_stages(4, 16)
        x = jnp.asarray(np.random.default_rng(1).standard_normal(
            (8, 4, 16)), jnp.float32)          # [M, B, D]
        pipe = gpipe(_mlp_stage_fn, mesh, data_axis="data")
        out = jax.jit(pipe)(stack_stage_params(params), x)
        ref = x
        for p in params:
            ref = _mlp_stage_fn(p, ref)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-6)

    def test_grads_match_sequential(self):
        mesh = make_pipeline_mesh(n_pipe=8, n_data=1)
        params = _mlp_stages(8, 16, seed=3)
        x = jnp.asarray(np.random.default_rng(2).standard_normal(
            (16, 2, 16)), jnp.float32)
        pipe = gpipe(_mlp_stage_fn, mesh)

        def loss_p(stk):
            return jnp.mean(pipe(stk, x) ** 2)

        def loss_s(plist):
            h = x
            for p in plist:
                h = _mlp_stage_fn(p, h)
            return jnp.mean(h ** 2)

        g_pipe = jax.jit(jax.grad(loss_p))(stack_stage_params(params))
        g_seq = stack_stage_params(jax.grad(loss_s)(params))
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=1e-6),
            g_pipe, g_seq)

    def test_microbatch_shape_guard(self):
        with pytest.raises(ValueError):
            microbatch(jnp.zeros((10, 3)), 4)


def _char_data(B=16, T=16, V=11, seed=0):
    """Deterministic next-token task: y[t] = (x[t] + 1) mod V."""
    rng = np.random.default_rng(seed)
    x = rng.integers(0, V, (B, T)).astype(np.int32)
    y = (x + 1) % V
    return x, y


class TestPipelinedTransformer:
    @pytest.mark.slow
    def test_pipeline_loss_matches_single_chip(self):
        """Pipelined forward loss == stacking the blocks sequentially."""
        V, D = 11, 32
        mesh = make_pipeline_mesh(n_pipe=4, n_data=2)
        aux, blocks = init_lm(V, d_model=D, n_heads=4, n_layers=4,
                              max_len=16, seed=5)
        block_fn = make_block_fn(4)
        pp = PipelineParallel(
            block_fn, blocks, mesh, loss_fn=lm_loss, aux_params=aux,
            pre_fn=embed_fn, n_micro=4, data_axis="data",
            learning_rate=0.0)
        x, y = _char_data()
        xs, ys = microbatch(jnp.asarray(x), 4), microbatch(jnp.asarray(y), 4)
        loss_pipe = float(jax.jit(pp._loss)(pp.stacked, pp.aux, xs, ys))
        h = embed_fn(aux, jnp.asarray(x))
        for p in blocks:
            h = block_fn(p, h)
        loss_seq = float(lm_loss(aux, h, jnp.asarray(y)))
        assert abs(loss_pipe - loss_seq) < 1e-5

    @pytest.mark.slow
    def test_dp_pp_training_learns(self):
        """dp=2 x pp=4 mesh: the pipelined LM learns the shift task."""
        V, D = 11, 32
        mesh = make_pipeline_mesh(n_pipe=4, n_data=2)
        aux, blocks = init_lm(V, d_model=D, n_heads=4, n_layers=4,
                              max_len=16, seed=7)
        pp = PipelineParallel(
            make_block_fn(4), blocks, mesh, loss_fn=lm_loss,
            aux_params=aux, pre_fn=embed_fn, n_micro=4, data_axis="data",
            learning_rate=0.5, momentum=0.9)
        x, y = _char_data(B=32)
        first = pp.fit_batch(x, y)
        for _ in range(30):
            last = pp.fit_batch(x, y)
        assert last < first * 0.5, (first, last)

    @pytest.mark.slow
    def test_stage_params_sharded_over_pipe(self):
        V, D = 11, 32
        mesh = make_pipeline_mesh(n_pipe=4, n_data=2)
        aux, blocks = init_lm(V, d_model=D, n_heads=4, n_layers=4,
                              max_len=16)
        pp = PipelineParallel(
            make_block_fn(4), blocks, mesh, loss_fn=lm_loss,
            aux_params=aux, pre_fn=embed_fn, n_micro=4, data_axis="data")
        w = pp.stacked["attn"]["wqkv"]          # [S, D, 3D]
        assert tuple(w.sharding.spec)[0] == "pipe"

    @pytest.mark.slow
    def test_single_chip_reference_model_learns(self):
        lm = TransformerLM(11, d_model=32, n_heads=4, n_layers=2,
                           max_len=16, learning_rate=0.2, momentum=0.9)
        x, y = _char_data()
        first = lm.fit_batch(x, y)
        for _ in range(80):
            last = lm.fit_batch(x, y)
        assert last < first * 0.5
        # greedy argmax solves the shift task after training
        pred = np.asarray(jnp.argmax(lm.logits(x), -1))
        assert (pred == y).mean() > 0.8

    def test_decode_paths_token_identical_untrained(self):
        """Core-tier pin of every decode path with NO training loop (path
        equality doesn't need learned weights): per-token KV-cache decode
        and the one-program generate_batch both reproduce the recompute
        generate() tokens on a freshly-initialized model."""
        lm = TransformerLM(11, d_model=16, n_heads=2, n_layers=2,
                           max_len=12)
        out = lm.generate([2, 3, 4], max_new_tokens=4)
        assert lm.generate([2, 3, 4], max_new_tokens=4,
                           use_cache=True) == out
        batched = lm.generate_batch(np.array([[2, 3, 4]], np.int32),
                                    max_new_tokens=4)
        assert list(batched[0]) == out

    @pytest.mark.slow
    def test_generate_continues_learned_pattern(self):
        """After learning the +1 shift task, greedy generate() continues
        the arithmetic sequence."""
        lm = TransformerLM(11, d_model=32, n_heads=4, n_layers=2,
                           max_len=16, learning_rate=0.2, momentum=0.9)
        x, y = _char_data()
        for _ in range(80):
            lm.fit_batch(x, y)
        out = lm.generate([2, 3, 4], max_new_tokens=5)
        assert out == [2, 3, 4, 5, 6, 7, 8, 9]
        sampled = lm.generate([0], max_new_tokens=4, temperature=0.5,
                              seed=1)
        assert len(sampled) == 5 and all(0 <= t < 11 for t in sampled)
        # the jitted KV-cache decode path produces IDENTICAL tokens
        cached = lm.generate([2, 3, 4], max_new_tokens=5, use_cache=True)
        assert cached == out
        assert lm.generate([0], max_new_tokens=4, temperature=0.5, seed=1,
                           use_cache=True) == sampled
        with pytest.raises(ValueError):
            lm.generate([1] * 10, max_new_tokens=10, use_cache=True)

    @pytest.mark.slow
    def test_generate_batch_matches_cached_decode(self):
        """generate_batch (one on-device prefill+decode scan program) is
        token-identical, row by row, to the per-token KV-cache decode —
        the same greedy outputs with one host round trip per call.
        Full tier: core still pins greedy==cached per-token decode and the
        generate_batch LRU/shape contract; this is the cross-path sweep."""
        lm = TransformerLM(11, d_model=32, n_heads=4, n_layers=2,
                           max_len=16, learning_rate=0.2, momentum=0.9)
        x, y = _char_data()
        for _ in range(40):
            lm.fit_batch(x, y)
        prompts = np.array([[2, 3, 4], [0, 1, 2], [7, 8, 9]], np.int32)
        out = lm.generate_batch(prompts, max_new_tokens=5)
        assert out.shape == (3, 8)
        for b in range(3):
            ref = lm.generate(prompts[b], max_new_tokens=5, use_cache=True)
            assert list(out[b]) == ref
        # n_new=1 edge (decode scan has zero iterations)
        one = lm.generate_batch(prompts, max_new_tokens=1)
        assert one.shape == (3, 4)
        assert [list(r[:4]) for r in out] == [list(r) for r in one]
        with pytest.raises(ValueError):
            lm.generate_batch(np.zeros((2, 10), np.int32),
                              max_new_tokens=10)

    @pytest.mark.slow
    def test_generate_batch_sampling(self):
        """temperature>0: on-device categorical sampling in the decode
        scan — deterministic per seed, varies across seeds, near-greedy
        as temperature -> 0."""
        lm = TransformerLM(11, d_model=32, n_heads=4, n_layers=2,
                           max_len=16, learning_rate=0.2, momentum=0.9)
        x, y = _char_data()
        for _ in range(40):
            lm.fit_batch(x, y)
        prompts = np.array([[2, 3, 4], [7, 8, 9]], np.int32)
        a = lm.generate_batch(prompts, 5, temperature=1.0, seed=1)
        b = lm.generate_batch(prompts, 5, temperature=1.0, seed=1)
        c = lm.generate_batch(prompts, 5, temperature=1.0, seed=2)
        np.testing.assert_array_equal(a, b)        # same seed = same toks
        assert a.shape == (2, 8) and (a[:, 3:] < 11).all()
        assert not np.array_equal(a, c)            # seeds diverge
        # temperature -> 0 converges to the greedy program's output
        greedy = lm.generate_batch(prompts, 5)
        near = lm.generate_batch(prompts, 5, temperature=1e-4, seed=3)
        np.testing.assert_array_equal(greedy, near)

    def test_generate_batch_jit_cache_is_bounded_lru(self, monkeypatch):
        """A serving workload with varied (B, P, n_new) shapes must not
        accumulate compiled programs without bound; re-use must not
        re-trace (the hot key stays resident under eviction pressure).
        Cache cap patched to 2 so the eviction path is exercised with a
        handful of compiles (cap + 2 extra shapes) instead of the real
        GEN_JIT_CACHE_SIZE's worth."""
        from deeplearning4j_tpu.models.zoo import transformer as tr
        monkeypatch.setattr(tr, "GEN_JIT_CACHE_SIZE", 2)
        lm = TransformerLM(11, d_model=16, n_heads=2, n_layers=1,
                           max_len=32)
        hot = np.zeros((1, 2), np.int32)
        lm.generate_batch(hot, max_new_tokens=1)
        hot_fn = lm._jit_gen_cache[(1, 2, 1, False)]
        for p in range(3, 3 + tr.GEN_JIT_CACHE_SIZE + 2):
            lm.generate_batch(np.zeros((1, p), np.int32),
                              max_new_tokens=1)
            lm.generate_batch(hot, max_new_tokens=1)   # LRU touch
        assert len(lm._jit_gen_cache) <= tr.GEN_JIT_CACHE_SIZE
        assert lm._jit_gen_cache[(1, 2, 1, False)] is hot_fn
