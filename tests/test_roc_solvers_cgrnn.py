"""ROC/AUC metrics, second-order solvers (LBFGS/CG/line search),
ComputationGraph TBPTT + rnnTimeStep."""
import numpy as np
import pytest

from deeplearning4j_tpu import (ComputationGraph, InputType,
                                MultiLayerNetwork, NeuralNetConfiguration)
from deeplearning4j_tpu.datasets.dataset import DataSet, MultiDataSet
from deeplearning4j_tpu.eval.roc import ROC, ROCMultiClass
from deeplearning4j_tpu.nn.conf.layers import (DenseLayer, GravesLSTM,
                                               OutputLayer, RnnOutputLayer)
from deeplearning4j_tpu.optimize.solvers import Solver


class TestROC:
    def test_perfect_classifier_auc_1(self):
        roc = ROC(threshold_steps=50)
        labels = np.array([0, 0, 1, 1, 1])
        probs = np.array([0.1, 0.2, 0.8, 0.9, 0.95])
        roc.eval(labels, probs)
        assert roc.calculate_auc() > 0.99

    def test_random_classifier_auc_half(self):
        rng = np.random.default_rng(0)
        roc = ROC(threshold_steps=100)
        labels = rng.integers(0, 2, 4000)
        probs = rng.random(4000)
        roc.eval(labels, probs)
        assert abs(roc.calculate_auc() - 0.5) < 0.05

    def test_merge_equals_single_pass(self):
        rng = np.random.default_rng(1)
        labels = rng.integers(0, 2, 1000)
        probs = np.clip(labels * 0.4 + rng.random(1000) * 0.6, 0, 1)
        whole = ROC().eval(labels, probs)
        a = ROC().eval(labels[:500], probs[:500])
        b = ROC().eval(labels[500:], probs[500:])
        a.merge(b)
        assert abs(whole.calculate_auc() - a.calculate_auc()) < 1e-12

    def test_one_hot_and_curve_monotone(self):
        labels = np.eye(2)[[0, 1, 1, 0]]
        probs = np.array([[0.9, 0.1], [0.2, 0.8], [0.3, 0.7], [0.6, 0.4]])
        roc = ROC().eval(labels, probs)
        curve = roc.get_roc_curve()
        assert curve[0][1:] == (1.0, 1.0)    # threshold 0: everything positive
        assert roc.calculate_auc() > 0.99

    def test_multiclass(self):
        rng = np.random.default_rng(2)
        y = np.eye(3)[rng.integers(0, 3, 300)]
        # good predictions with noise
        probs = np.clip(y + rng.normal(0, 0.3, y.shape), 0, 1)
        probs /= probs.sum(1, keepdims=True)
        mroc = ROCMultiClass().eval(y, probs)
        for c in range(3):
            assert mroc.calculate_auc(c) > 0.8
        assert mroc.calculate_average_auc() > 0.8


class TestSolvers:
    def _net(self, algo):
        conf = (NeuralNetConfiguration.Builder().seed(11)
                .optimization_algo(algo).data_type("float64").list()
                .layer(0, DenseLayer(n_out=8, activation="tanh"))
                .layer(1, OutputLayer(n_out=2, activation="softmax",
                                      loss_function="mcxent"))
                .set_input_type(InputType.feed_forward(4))
                .build())
        return MultiLayerNetwork(conf).init()

    def _xor_ish(self):
        r = np.random.default_rng(3)
        x = r.random((32, 4)).astype(np.float64)
        y = np.eye(2, dtype=np.float64)[
            ((x[:, 0] > 0.5) ^ (x[:, 1] > 0.5)).astype(int)]
        return x, y

    @pytest.mark.parametrize("algo", ["lbfgs", "conjugate_gradient",
                                      "line_gradient_descent"])
    def test_solver_reduces_score(self, algo):
        net = self._net(algo)
        x, y = self._xor_ish()
        s0 = net.score(DataSet(x, y))
        final = Solver(net, max_iterations=60).optimize(x, y)
        assert final < s0 * 0.7, (algo, s0, final)

    def test_lbfgs_beats_few_sgd_steps(self):
        x, y = self._xor_ish()
        net = self._net("lbfgs")
        Solver(net, max_iterations=100).optimize(x, y)
        assert net.score(DataSet(x, y)) < 0.3


class TestCGRecurrent:
    def _conf(self, tbptt=False):
        gb = (NeuralNetConfiguration.Builder().seed(5)
              .updater("adam").learning_rate(0.01)
              .graph_builder()
              .add_inputs("in")
              .add_layer("lstm", GravesLSTM(n_out=8, activation="tanh"), "in")
              .add_layer("out", RnnOutputLayer(n_out=3, activation="softmax",
                                               loss_function="mcxent"),
                         "lstm")
              .set_outputs("out")
              .set_input_types(InputType.recurrent(4)))
        if tbptt:
            gb.backprop_type("tbptt").t_bptt_forward_length(5)
        return gb.build()

    def test_cg_tbptt_iteration_count(self):
        net = ComputationGraph(self._conf(tbptt=True)).init()
        r = np.random.default_rng(0)
        x = r.random((2, 20, 4)).astype(np.float32)
        y = np.zeros((2, 20, 3), np.float32)
        y[:, :, 0] = 1.0
        net.fit(MultiDataSet([x], [y]))
        assert net.conf.iteration_count == 4   # 20 / 5 segments
        assert np.isfinite(float(net._score))

    def test_cg_rnn_time_step_matches_full_output(self):
        net = ComputationGraph(self._conf()).init()
        r = np.random.default_rng(1)
        x = r.random((2, 6, 4)).astype(np.float32)
        full = np.asarray(net.output(x)[0])
        net.rnn_clear_previous_state()
        steps = [np.asarray(net.rnn_time_step(x[:, t])[0])
                 for t in range(6)]
        chained = np.stack(steps, axis=1)
        assert np.allclose(full, chained, atol=1e-5), \
            np.abs(full - chained).max()
