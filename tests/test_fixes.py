"""Regression tests for review findings: BN activation, poly LR, async
iterator error propagation, masked output/eval, ParallelWrapper ragged tail."""
import numpy as np
import pytest

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterators import (AsyncDataSetIterator,
                                                   ListDataSetIterator)
from deeplearning4j_tpu.eval.evaluation import Evaluation


def test_batchnorm_applies_no_activation():
    """BN output must be gamma*xhat+beta, not sigmoid(...) from the global
    default (reference BatchNormalization.java:227 applies none)."""
    import jax.numpy as jnp
    from deeplearning4j_tpu.nn.conf.layers.normalization import BatchNormalization
    bn = BatchNormalization(n_out=4)
    bn = bn.apply_global_defaults({"activation": "sigmoid"})
    x = jnp.asarray(np.random.default_rng(0).normal(size=(16, 4)) * 3 + 1)
    out, _ = bn.forward_with_state(bn.init_params(None), x, bn.init_state(),
                                   train=True)
    out = np.asarray(out)
    # sigmoid output would be in (0,1); normalized output must have
    # negative values and ~unit variance
    assert out.min() < -0.5
    assert abs(out.std() - 1.0) < 0.2


def test_poly_lr_requires_horizon():
    from deeplearning4j_tpu.nn.updater import updaters as U
    with pytest.raises(ValueError, match="poly"):
        U.schedule_lr(0.1, "poly", 3, power=2.0)
    lr = U.schedule_lr(0.1, "poly", 50, power=1.0, max_iterations=100)
    assert abs(float(lr) - 0.05) < 1e-9


def test_async_iterator_propagates_worker_error():
    class FailingIterator(ListDataSetIterator):
        def __init__(self):
            ds = DataSet(np.zeros((4, 3), np.float32), np.zeros((4, 2), np.float32))
            super().__init__([ds, ds, ds])
            self._n = 0

        def next_batch(self):
            self._n += 1
            if self._n >= 2:
                raise RuntimeError("corrupt record")
            return super().next_batch()

    it = AsyncDataSetIterator(FailingIterator(), queue_size=1, device_put=False)
    with pytest.raises(RuntimeError, match="prefetch worker failed"):
        while it.has_next():
            it.next_batch()


def test_caller_supplied_async_iterator_resets_on_epoch0():
    """ADVICE r5: fit() skips the epoch-0 reset only for the async wrapper
    it CREATED (freshly prefetching from position 0). A caller-supplied
    async iterator may be mid-stream and must be reset, or the first
    epoch silently trains truncated."""
    from deeplearning4j_tpu import (InputType, MultiLayerNetwork,
                                    NeuralNetConfiguration)
    from deeplearning4j_tpu.datasets.iterators import next_processed
    from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer

    def mk():
        conf = (NeuralNetConfiguration.Builder()
                .seed(4).updater("sgd").learning_rate(0.1).list()
                .layer(0, DenseLayer(n_out=8, activation="relu"))
                .layer(1, OutputLayer(n_out=2, activation="softmax",
                                      loss_function="mcxent"))
                .set_input_type(InputType.feed_forward(3))
                .build())
        return MultiLayerNetwork(conf).init()

    net = mk()
    rng = np.random.default_rng(0)
    batches = [DataSet(rng.random((4, 3)).astype(np.float32),
                       np.eye(2, dtype=np.float32)[rng.integers(0, 2, 4)])
               for _ in range(4)]
    ait = AsyncDataSetIterator(ListDataSetIterator(batches), queue_size=2,
                               device_put=False)
    next_processed(ait)          # caller consumed 2 of 4 batches...
    next_processed(ait)
    net.fit(ait, num_epochs=1)   # ...fit must still train the FULL epoch
    assert net.conf.iteration_count == 4

    # same for a caller-supplied PLAIN iterator mid-stream: fit() resets
    # the underlying before wrapping it
    net3 = mk()
    plain = ListDataSetIterator(batches)
    next_processed(plain)
    next_processed(plain)
    net3.fit(plain, num_epochs=1)
    assert net3.conf.iteration_count == 4

    # the wrapper fit() itself creates still avoids the double-drain:
    # a plain iterator trains exactly one pass per epoch
    net2 = mk()
    net2.fit(ListDataSetIterator(batches), num_epochs=2)
    assert net2.conf.iteration_count == 8


def test_evaluation_2d_mask():
    ev = Evaluation()
    labels = np.eye(3, dtype=np.float32)[[0, 1, 2, 0]]
    preds = np.eye(3, dtype=np.float32)[[0, 1, 0, 1]]  # last two wrong
    mask = np.array([1, 1, 0, 0], np.float32)  # mask out the wrong ones
    ev.eval(labels, preds, mask=mask)
    assert ev.num_examples == 2
    assert ev.accuracy() == 1.0


def test_output_accepts_features_mask():
    from deeplearning4j_tpu import (InputType, MultiLayerNetwork,
                                    NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.conf.layers import GravesLSTM, RnnOutputLayer

    conf = (NeuralNetConfiguration.Builder()
            .seed(3).list()
            .layer(0, GravesLSTM(n_out=6, activation="tanh"))
            .layer(1, RnnOutputLayer(n_out=3, activation="softmax",
                                     loss_function="mcxent"))
            .set_input_type(InputType.recurrent(4))
            .build())
    net = MultiLayerNetwork(conf).init()
    rng = np.random.default_rng(0)
    x = rng.random((2, 5, 4)).astype(np.float32)
    fmask = np.array([[1, 1, 1, 0, 0], [1, 1, 1, 1, 1]], np.float32)
    out_masked = np.asarray(net.output(x, features_mask=fmask))
    out_unmasked = np.asarray(net.output(x))
    assert out_masked.shape == (2, 5, 3)
    # masking must change the padded-region computation for example 0
    assert not np.allclose(out_masked[0], out_unmasked[0])


def test_parallel_wrapper_ragged_tail_no_duplicate_steps():
    import jax
    from deeplearning4j_tpu import (InputType, MultiLayerNetwork,
                                    NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.parallel import ParallelWrapper, make_mesh

    conf = (NeuralNetConfiguration.Builder()
            .seed(1).updater("sgd").learning_rate(0.1).list()
            .layer(0, DenseLayer(n_out=8, activation="relu"))
            .layer(1, OutputLayer(n_out=2, activation="softmax",
                                  loss_function="mcxent"))
            .set_input_type(InputType.feed_forward(5))
            .build())
    net = MultiLayerNetwork(conf).init()
    mesh = make_mesh(n_data=2, n_model=1, devices=jax.devices()[:2])
    pw = (ParallelWrapper.Builder(net).mesh(mesh)
          .averaging_frequency(4).build())
    rng = np.random.default_rng(0)
    batches = [DataSet(rng.random((4, 5)).astype(np.float32),
                       np.eye(2, dtype=np.float32)[rng.integers(0, 2, 4)])
               for _ in range(6)]

    class SixIterator(ListDataSetIterator):
        pass

    start = net.conf.iteration_count
    pw.fit(ListDataSetIterator(batches))
    # 6 batches -> exactly 6 optimizer iterations (4 + ragged tail of 2)
    assert net.conf.iteration_count - start == 6
