"""Trained-model-driven POS annotation (VERDICT r4 missing item 3):
serialized perceptron model + committed trained fixture, loaded by
annotators the way the reference's UIMA PoStagger loads OpenNLP maxent
models (deeplearning4j-nlp-uima .../annotator/PoStagger.java,
treeparser/TreeParser.java). Fixture trained by
tools/train_pos_fixture.py (94% held-out on its tiny corpus)."""
import gzip
import json
import os

import pytest

from deeplearning4j_tpu.text.annotation import standard_pipeline
from deeplearning4j_tpu.text.pos_model import (PerceptronPosTagger,
                                               TrainedPosAnnotator)
from deeplearning4j_tpu.text.treeparser import TreeParser

FIXTURE = os.path.join(os.path.dirname(__file__), "fixtures",
                       "pos_model.json.gz")


class TestModelFormat:
    def test_fixture_loads_and_tags(self):
        m = PerceptronPosTagger.load(FIXTURE)
        tags = dict(m.tag("the quick dog chased a ball .".split()))
        assert tags["the"] == "DT" and tags["quick"] == "JJ"
        assert tags["chased"] == "VBD" and tags["dog"] == "NN"

    def test_generalization_beyond_training_vocab(self):
        """The trained features (affixes, shape, tag history) generalize
        to unseen words — the property a lookup table cannot have."""
        m = PerceptronPosTagger.load(FIXTURE)
        # 'sprinted' never occurs in the training corpus
        tags = dict(m.tag("the tired runner sprinted home .".split()))
        assert tags["sprinted"] == "VBD"
        # unseen capitalized mid-sentence token -> proper-noun-ish/noun
        tags2 = dict(m.tag("she visited Kyoto yesterday .".split()))
        assert tags2["visited"] == "VBD"
        assert tags2["Kyoto"] in ("NNP", "NN")

    def test_round_trip_identical_tagging(self, tmp_path):
        m = PerceptronPosTagger.load(FIXTURE)
        p = str(tmp_path / "m.json.gz")
        m.save(p)
        m2 = PerceptronPosTagger.load(p)
        sent = "two small boys watched the old train .".split()
        assert m.tag(sent) == m2.tag(sent)

    def test_rejects_wrong_format_and_future_version(self, tmp_path):
        bad = tmp_path / "bad.json.gz"
        with gzip.open(bad, "wt") as f:
            json.dump({"format": "something-else"}, f)
        with pytest.raises(ValueError):
            PerceptronPosTagger.load(str(bad))
        fut = tmp_path / "fut.json.gz"
        with gzip.open(fut, "wt") as f:
            json.dump({"format": "dl4j-tpu-pos-perceptron", "version": 99,
                       "tags": [], "weights": {}}, f)
        with pytest.raises(ValueError):
            PerceptronPosTagger.load(str(fut))


class TestAnnotatorIntegration:
    def test_pipeline_with_trained_model(self):
        """standard_pipeline(pos_model=path): the annotator loads the
        serialized model itself (the PoStagger mechanism)."""
        doc = standard_pipeline(pos_model=FIXTURE).process(
            "The hungry dog chased the ball")
        tags = {t.features["text"]: t.features["pos"]
                for t in doc.select("token")}
        assert tags["chased"] == "VBD" and tags["dog"] == "NN"
        assert tags["hungry"] == "JJ"

    def test_trained_model_beats_heuristic_on_adjectives(self):
        """'green' has no heuristic suffix rule (falls to NN); the trained
        model learned it is an adjective — the concrete value of the
        trained path over the heuristic one."""
        text = "green leaves covered the wet ground ."
        heur = standard_pipeline().process(text)
        trained = standard_pipeline(pos_model=FIXTURE).process(text)
        h = {t.features["text"]: t.features["pos"] for t in
             heur.select("token")}
        m = {t.features["text"]: t.features["pos"] for t in
             trained.select("token")}
        assert h["green"] == "NN"            # heuristic limitation
        assert m["green"] == "JJ"            # trained model gets it

    def test_tree_parser_with_trained_model(self):
        parser = TreeParser(pos_model=FIXTURE)
        trees = parser.get_trees("The quick dog chased a small cat.")
        assert len(trees) == 1
        s = trees[0].to_string()
        assert "(NP" in s and "(VP" in s
        leaf_tags = {l.value: l.label for l in trees[0].leaves()}
        assert leaf_tags["chased"] == "VBD"

    def test_annotator_accepts_model_instance(self):
        m = PerceptronPosTagger.load(FIXTURE)
        ann = TrainedPosAnnotator(m)
        assert ann.model is m


CHUNK_FIXTURE = os.path.join(os.path.dirname(__file__), "fixtures",
                             "chunk_model.json.gz")


class TestTrainedChunker:
    def test_fixture_loads_and_tags_bio(self):
        from deeplearning4j_tpu.text.pos_model import PerceptronChunker
        m = PerceptronChunker.load(CHUNK_FIXTURE)
        pairs = [("the", "DT"), ("old", "JJ"), ("farmer", "NN"),
                 ("watered", "VBD"), ("his", "PRP$"), ("fields", "NNS"),
                 (".", ".")]
        tags = [t for _, t in m.tag(pairs)]
        assert tags[:3] == ["B-NP", "I-NP", "I-NP"]
        assert tags[3] == "B-VP" and tags[-1] == "O"

    def test_format_guard_rejects_pos_model(self):
        from deeplearning4j_tpu.text.pos_model import PerceptronChunker
        with pytest.raises(ValueError):
            PerceptronChunker.load(FIXTURE)     # pos format != chunk

    def test_tree_parser_with_trained_chunker(self):
        parser = TreeParser(pos_model=FIXTURE, chunk_model=CHUNK_FIXTURE)
        trees = parser.get_trees("Two large ships arrived at the port")
        s = trees[0].to_string()
        assert "(NP" in s and "(VP" in s and "(PP" in s
        np_words = next(n for n in trees[0] if n.label == "NP")
        assert np_words.yield_words() == ["Two", "large", "ships"]

    def test_bio_repair_orphan_inside_tag(self):
        """An I-X with no open X phrase opens one (standard BIO repair)."""
        from deeplearning4j_tpu.text.treeparser import _chunks_from_bio
        toks = [("ships", "NNS", 0, 5), ("sail", "VBP", 6, 10)]
        tagged = [(("ships", "NNS"), "I-NP"), (("sail", "VBP"), "B-VP")]
        out = _chunks_from_bio(toks, tagged)
        assert [n.label for n in out] == ["NP", "VP"]
        assert out[0].yield_words() == ["ships"]
