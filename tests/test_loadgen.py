"""Production-traffic harness pins (ISSUE 7 acceptance criteria).

  (a) Determinism: same seed => byte-identical arrival schedule
      (arrivals + payloads + sha256 digest) for all three arrival
      processes, and identical admitted/shed/SLO accounting across two
      fault-free replays of the same schedule on a real server.
  (b) No coordinated omission: open-loop arrivals are honored by
      SUBMISSION time, never completion time — pinned against a fake
      server that stalls every completion (a coordinated generator
      would crawl; ours keeps to the schedule).
  (c) Zero extra device dispatches: driving a server through the
      loadgen with tracing + histograms + decomposition enabled
      dispatches exactly what the tracing-off arm and a bare sequential
      generate() loop dispatch (the PR 6 dispatch-counter A/B
      protocol).
  (d) TTFT + inter-token histograms: recorded by the decode server
      (TTFT closed at prefill, one inter-token sample per decode
      iteration per slot), exposed in snapshot() and the Prometheus
      text exposition as cumulative `_bucket`/`_sum`/`_count`.
  (e) Smoke sweep: a fast tools/load_sweep.py run producing the
      combined obs_report (sweep curve + knee + latency decomposition)
      — tier1.yml uploads its JSON as a CI artifact.
"""
import concurrent.futures as cf
import importlib
import json
import os
import sys
import tempfile
import threading
import time

import pytest

from deeplearning4j_tpu.models.zoo.transformer import TransformerLM
from deeplearning4j_tpu.obs import MetricsRegistry, Tracer, decompose
from deeplearning4j_tpu.serving import (ClosedLoop, ContinuousDecodeServer,
                                        DecodeSizeMix, OnOffProcess,
                                        PoissonProcess, ServingMetrics,
                                        SharedPrefixMix, build_schedule,
                                        run_load)


def _lm(seed=3):
    return TransformerLM(64, d_model=16, n_heads=2, n_layers=1,
                         max_len=48, seed=seed)


def _mix():
    return DecodeSizeMix(((0.7, (2, 6), (3, 8)),
                          (0.3, (4, 8), (6, 12))), vocab=64)


_PROCESSES = {
    "poisson": lambda: PoissonProcess(80.0),
    "onoff": lambda: OnOffProcess(160.0, on_s=0.25, off_s=0.25),
    "closed": lambda: ClosedLoop(4),
}


# ---------------------------------------------------------------------------
# (a) schedule determinism
# ---------------------------------------------------------------------------
class TestScheduleDeterminism:
    @pytest.mark.parametrize("name", sorted(_PROCESSES))
    def test_same_seed_byte_identical(self, name):
        make = _PROCESSES[name]
        s1 = build_schedule(make(), _mix(), 32, seed=11)
        s2 = build_schedule(make(), _mix(), 32, seed=11)
        # byte-identical, not approximately equal: repr of the full
        # float arrival tuple and every payload tuple must match
        assert repr(s1.arrivals) == repr(s2.arrivals)
        assert repr(s1.items) == repr(s2.items)
        assert s1.digest() == s2.digest()
        assert s1.digest() != build_schedule(make(), _mix(), 32,
                                             seed=12).digest()

    def test_open_loop_arrivals_sorted(self):
        for name in ("poisson", "onoff"):
            s = build_schedule(_PROCESSES[name](), _mix(), 64, seed=1)
            assert list(s.arrivals) == sorted(s.arrivals)
            assert all(t >= 0 for t in s.arrivals)

    def test_onoff_has_silence_gaps(self):
        """Bursty means bursty: with bursts much shorter than the
        request budget, consecutive arrivals must straddle at least one
        full off period."""
        s = build_schedule(OnOffProcess(200.0, on_s=0.1, off_s=0.4),
                           _mix(), 64, seed=2)
        gaps = [b - a for a, b in zip(s.arrivals, s.arrivals[1:])]
        assert any(g >= 0.4 for g in gaps)

    def test_arrival_and_size_streams_independent(self):
        """Changing the mix must not perturb the arrival pattern."""
        other = DecodeSizeMix(((1.0, (10, 14), (20, 30)),), vocab=64)
        s1 = build_schedule(PoissonProcess(50.0), _mix(), 16, seed=7)
        s2 = build_schedule(PoissonProcess(50.0), other, 16, seed=7)
        assert s1.arrivals == s2.arrivals
        assert s1.items != s2.items

    def test_shared_prefix_mix_digest_byte_identical(self):
        """ISSUE 20 satellite: the shared-system-prompt mix is as
        deterministic as the size mixes — same seed, byte-identical
        schedule (prefix population + suffixes + digest)."""
        s1 = build_schedule(PoissonProcess(80.0),
                            SharedPrefixMix(n_prefixes=3, seed=5),
                            32, seed=11)
        s2 = build_schedule(PoissonProcess(80.0),
                            SharedPrefixMix(n_prefixes=3, seed=5),
                            32, seed=11)
        assert repr(s1.items) == repr(s2.items)
        assert s1.digest() == s2.digest()
        assert s1.digest() != build_schedule(
            PoissonProcess(80.0), SharedPrefixMix(n_prefixes=3, seed=6),
            32, seed=11).digest()

    def test_shared_prefix_population_stable_across_seeds(self):
        """The prefixes are drawn ONCE on their own string-seeded
        stream: different SCHEDULE seeds keep the identical (block-
        aligned) prompt population — every prompt opens with one of
        the mix's system prompts."""
        mix = SharedPrefixMix(n_prefixes=3, block_size=8, seed=5)
        for p in mix.prefixes:
            assert len(p) >= 8 and len(p) % 8 == 0
        for seed in (1, 2):
            s = build_schedule(PoissonProcess(80.0), mix, 24, seed=seed)
            for item in s.items:
                prompt = item["prompt"]
                assert any(prompt[:len(p)] == p for p in mix.prefixes)


# ---------------------------------------------------------------------------
# (b) open loop honors submission time (no coordinated omission)
# ---------------------------------------------------------------------------
class _StallSink:
    """Fake server that completes every request `delay_s` AFTER submit —
    slow enough that a completion-coordinated generator would crawl."""

    metrics = None

    def __init__(self, delay_s):
        self.delay_s = float(delay_s)
        self.t_submit = []

    def submit(self, prompt, max_new):
        self.t_submit.append(time.monotonic())
        f = cf.Future()
        t = threading.Timer(self.delay_s, f.set_result, args=([0],))
        t.daemon = True
        t.start()
        return f


class TestOpenLoopNoCoordination:
    def test_submissions_track_schedule_not_completions(self):
        """12 arrivals over ~0.15s against a server that takes 0.4s per
        request: a closed/coordinated generator would need ~4.8s of
        submission time; the open loop must keep submit lateness tiny
        and finish submissions before the FIRST completion lands."""
        sched = build_schedule(PoissonProcess(100.0), _mix(), 12, seed=0)
        sink = _StallSink(delay_s=0.4)
        out = run_load(sink, sched, result_timeout=30.0)
        assert out["admitted"] == 12 and out["completed"] == 12
        assert out["submit_lateness_ms_max"] < 250.0
        # every submission happened before the first completion could
        # have landed — the structural no-coordination pin
        span = sink.t_submit[-1] - sink.t_submit[0]
        assert span < sink.delay_s

    def test_closed_loop_respects_concurrency(self):
        class _CountingSink:
            metrics = None

            def __init__(self, delay_s):
                self.delay_s = delay_s
                self.outstanding = 0
                self.max_outstanding = 0
                self.lock = threading.Lock()

            def submit(self, prompt, max_new):
                with self.lock:
                    self.outstanding += 1
                    self.max_outstanding = max(self.max_outstanding,
                                               self.outstanding)
                f = cf.Future()

                def done():
                    with self.lock:
                        self.outstanding -= 1
                    f.set_result([0])
                t = threading.Timer(self.delay_s, done)
                t.daemon = True
                t.start()
                return f

        sched = build_schedule(ClosedLoop(3), _mix(), 12, seed=4)
        sink = _CountingSink(delay_s=0.02)
        out = run_load(sink, sched, result_timeout=30.0)
        assert out["completed"] == 12
        assert sink.max_outstanding <= 3


# ---------------------------------------------------------------------------
# (a cont.) identical accounting across replays on a real server
# ---------------------------------------------------------------------------
class TestAccountingDeterminism:
    @pytest.mark.parametrize("name", sorted(_PROCESSES))
    def test_same_seed_same_accounting(self, name):
        """Fault-free, under-capacity replay of one schedule twice on
        the SAME server: admitted/shed/completed/failed/tokens and the
        SLO deltas must be identical (the generous SLO keeps wall-clock
        jitter out of attainment)."""
        lm = _lm()
        metrics = ServingMetrics(slo_target_ms=60_000)
        sched = build_schedule(_PROCESSES[name](), _mix(), 10, seed=5)
        keys = ("submitted", "admitted", "shed_at_submit", "completed",
                "failed", "tokens_out", "ttft_ms_count")
        with ContinuousDecodeServer(lm, slots=2, prompt_buckets=(8,),
                                    max_queue=64,
                                    metrics=metrics) as srv:
            srv.generate([1, 2, 3], 3, timeout=120)     # warm compile
            r1 = run_load(srv, sched)
            r2 = run_load(srv, sched)
        assert r1["schedule"]["digest"] == r2["schedule"]["digest"]
        for k in keys:
            assert r1[k] == r2[k], f"{k}: {r1[k]} != {r2[k]}"
        assert r1["shed_at_submit"] == 0 and r1["failed"] == 0
        assert r1["completed"] == 10
        # per-run SLO deltas: all 10 admitted, all met, both runs
        for r in (r1, r2):
            assert r["slo"]["slo_total"] == 10
            assert r["slo"]["slo_met"] == 10
            assert r["slo"]["attainment"] == 1.0


# ---------------------------------------------------------------------------
# (c) zero extra device dispatches (PR 6 dispatch-counter A/B protocol)
# ---------------------------------------------------------------------------
class TestZeroExtraDispatches:
    def test_loadgen_histograms_decomposition_add_zero_dispatches(self):
        """The SAME closed-loop(1) schedule — deterministic co-residency,
        so dispatch counts are exactly comparable — through three arms:
        loadgen with tracing ON (+ decomposition computed over the
        spans), loadgen with tracing OFF, and a bare sequential
        generate() loop (the pre-harness protocol). The decode dispatch
        and token counters must be IDENTICAL: load generation, histogram
        recording, and span analysis are host-side observers, never
        schedulers."""
        sched = build_schedule(ClosedLoop(1),
                               DecodeSizeMix(((1.0, (2, 6), (3, 7)),),
                                             vocab=64), 6, seed=9)
        counts = {}
        for name, tracer in (("on", Tracer(enabled=True)),
                             ("off", Tracer(enabled=False))):
            metrics = ServingMetrics(slo_target_ms=60_000)
            with ContinuousDecodeServer(_lm(), slots=2,
                                        prompt_buckets=(8,),
                                        tracer=tracer,
                                        metrics=metrics) as srv:
                srv.generate([1, 2, 3], 2, timeout=120)   # warm compile
                base = metrics.snapshot()
                out = run_load(srv, sched)
                snap = metrics.snapshot()
            assert out["completed"] == 6
            counts[name] = (snap["dispatches"] - base["dispatches"],
                            snap["tokens_out"] - base["tokens_out"])
            if name == "on":
                # the analyzer consumes what the run recorded (6 loadgen
                # requests + the traced warm-up request)
                dec = decompose(tracer)
                assert dec["n_requests"] == 7
        metrics = ServingMetrics()
        with ContinuousDecodeServer(_lm(), slots=2, prompt_buckets=(8,),
                                    metrics=metrics) as srv:
            srv.generate([1, 2, 3], 2, timeout=120)       # warm compile
            base = metrics.snapshot()
            for item in sched.items:
                srv.generate(list(item["prompt"]), item["max_new"],
                             timeout=120)
            snap = metrics.snapshot()
        counts["direct"] = (snap["dispatches"] - base["dispatches"],
                            snap["tokens_out"] - base["tokens_out"])
        assert counts["on"] == counts["off"] == counts["direct"]


# ---------------------------------------------------------------------------
# (d) TTFT + inter-token histograms through the real decode server
# ---------------------------------------------------------------------------
class TestTTFTInterToken:
    def test_recorded_and_exposed(self):
        reg = MetricsRegistry()
        metrics = ServingMetrics(registry=reg, name="t1")
        with ContinuousDecodeServer(_lm(), slots=2, prompt_buckets=(8,),
                                    metrics=metrics) as srv:
            srv.generate([1, 2, 3], 6, timeout=120)
            snap_mid = metrics.snapshot()
            # a one-token request closes TTFT at prefill and never
            # records an inter-token sample (no decode iteration)
            srv.generate([4, 5, 6], 1, timeout=120)
        snap = metrics.snapshot()
        assert snap_mid["ttft_ms_count"] == 1
        # 6 tokens: 1 from prefill + 5 decode iterations
        assert snap_mid["inter_token_ms_count"] == 5
        assert snap_mid["ttft_ms_p50"] is not None
        assert snap_mid["inter_token_ms_p99"] is not None
        assert snap["ttft_ms_count"] == 2
        assert snap["inter_token_ms_count"] == 5
        text = reg.prometheus_text()
        assert "# TYPE serving_t1_ttft_ms histogram" in text
        assert 'serving_t1_ttft_ms_bucket{le="+Inf"} 2' in text
        assert "serving_t1_inter_token_ms_count 5" in text
        assert "serving_t1_inter_token_ms_sum" in text


# ---------------------------------------------------------------------------
# decomposition over a real traced run
# ---------------------------------------------------------------------------
class TestDecomposition:
    def test_phases_partition_request_latency(self):
        tracer = Tracer(enabled=True)
        with ContinuousDecodeServer(_lm(), slots=2, prompt_buckets=(8,),
                                    tracer=tracer) as srv:
            srv.generate([1, 2, 3], 4, timeout=120)       # warm compile
            futs = [srv.submit([2 + i, 3, 4], 6) for i in range(3)]
            for f in futs:
                f.result(120)
        dec = decompose(tracer)
        assert dec["n_requests"] == 4
        for row in dec["requests"]:
            for ph in ("queue_wait_ms", "prefill_ms", "decode_ms",
                       "sched_gap_ms"):
                assert row[ph] >= 0.0
            # the server lane is single-threaded, every term is clipped
            # to the request window: the four phases PARTITION the total
            parts = (row["queue_wait_ms"] + row["prefill_ms"]
                     + row["decode_ms"] + row["sched_gap_ms"])
            assert parts == pytest.approx(row["total_ms"], abs=1e-6)
        assert sum(dec["fractions"].values()) == pytest.approx(1.0,
                                                               abs=0.01)
        # a decode request spends real time in prefill and decode
        assert dec["phases"]["prefill_ms"]["total_ms"] > 0
        assert dec["phases"]["decode_ms"]["total_ms"] > 0


# ---------------------------------------------------------------------------
# (e) smoke sweep: the tier-1 artifact CI uploads
# ---------------------------------------------------------------------------
class TestSmokeSweep:
    def test_smoke_sweep_writes_report(self):
        """Fast (<10s) end-to-end tools/load_sweep.py run: 2-rate curve
        over the real decode server, knee identified, combined
        obs_report written. tier1.yml uploads the JSON next to the
        junit/log artifacts, so every CI run ships a machine-readable
        throughput-latency record."""
        tools = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "tools")
        if tools not in sys.path:
            sys.path.insert(0, tools)
        mod = importlib.import_module("load_sweep")
        # tier1.yml sets SMOKE_REPORT_DIR so its artifact-upload paths
        # and this test agree even on runners with a custom TMPDIR
        out = os.path.join(
            os.environ.get("SMOKE_REPORT_DIR") or tempfile.gettempdir(),
            "load_sweep_smoke")
        res = mod.run_sweep(server="decode", rates=(40.0, 400.0),
                            n_req=8, slo_ms=250.0, seed=0, trace=True,
                            report_path=out)
        (decode,) = res
        assert decode["server"] == "decode"
        assert len(decode["curve"]) == 2
        for pt in decode["curve"]:
            assert pt["completed"] == 8
            assert pt["tokens_per_sec"] > 0
            assert pt["latency_ms"]["p99"] is not None
            assert pt["ttft_ms_p99"] is not None
            assert "sustained_ratio" in pt
        assert decode["knee"]["criterion"].startswith("achieved >=")
        with open(out + ".json") as fh:
            rep = json.load(fh)
        assert rep["sweep"][0]["server"] == "decode"
        assert rep["decomposition"]["n_requests"] >= 16
        assert set(rep["decomposition"]["fractions"]) == {
            "queue_wait_ms", "prefill_ms", "decode_ms", "sched_gap_ms"}
        assert os.path.exists(out + ".txt")
        assert os.path.exists(out + ".trace.json")

    def test_smoke_sweep_overload_goodput_monotone(self):
        """The ISSUE 9 monotonicity pin at smoke scale: one at-knee-ish
        rate and one FAR-past-knee rate through the overload-controlled
        decode server (chunked prefill + deadline-aware admission,
        deadline = SLO). Goodput at the past-knee rate must be >= the
        knee-rate goodput — the baseline curve's pinned behavior at the
        same point is a COLLAPSE (PR 7: 2,515 -> 635 tok/s), which is
        exactly what overload control exists to prevent. The margin is
        structural, not statistical: the low-rate point's goodput is
        bounded by its tiny offered rate while the past-knee point runs
        at machine capacity. Report uploads next to the other smoke
        sweeps (tier1.yml)."""
        tools = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "tools")
        if tools not in sys.path:
            sys.path.insert(0, tools)
        mod = importlib.import_module("load_sweep")
        out = os.path.join(
            os.environ.get("SMOKE_REPORT_DIR") or tempfile.gettempdir(),
            "load_sweep_smoke_overload")
        res = mod.run_sweep(server="decode", rates=(40.0, 2000.0),
                            n_req=8, slo_ms=500.0, seed=0, trace=False,
                            report_path=out, chunked_prefill=4,
                            admission=True)
        (decode,) = res
        assert decode["overload_control"] is True
        knee_pt, past_pt = decode["curve"]
        g_knee = (knee_pt.get("slo") or {}).get(
            "goodput_tokens_per_sec") or 0.0
        g_past = (past_pt.get("slo") or {}).get(
            "goodput_tokens_per_sec") or 0.0
        assert g_knee > 0
        assert g_past >= g_knee, (
            f"goodput collapsed past the knee: {g_past} < {g_knee}")
        # the shed-reason breakdown columns ride every sweep point
        assert set(past_pt["sheds"]) == {
            "shed_queue", "shed_deadline", "shed_blocks",
            "shed_predicted", "shed_brownout", "evicted_mid_decode"}

    def test_smoke_sweep_paged_mode(self):
        """One PAGED-mode sweep rate in tier-1: the same loadgen
        arrivals through `ContinuousDecodeServer(paged=True)`, so every
        CI run exercises the block-gated admission path (kvpool admit/
        release under real traffic, not just the unit pins). Its report
        uploads next to the fixed-slot one (tier1.yml)."""
        tools = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "tools")
        if tools not in sys.path:
            sys.path.insert(0, tools)
        mod = importlib.import_module("load_sweep")
        out = os.path.join(
            os.environ.get("SMOKE_REPORT_DIR") or tempfile.gettempdir(),
            "load_sweep_smoke_paged")
        res = mod.run_sweep(server="decode", rates=(40.0,), n_req=8,
                            slo_ms=250.0, seed=0, trace=False,
                            report_path=out, paged=True)
        (decode,) = res
        assert decode["paged"] is True
        (pt,) = decode["curve"]
        assert pt["completed"] == 8
        assert pt["tokens_per_sec"] > 0
        # the paged pool really carried the traffic
        snap = json.load(open(out + ".json"))["metrics"]["decode"]
        assert snap["pool_blocks"] > 0
        assert snap["blocks_in_use_max"] > 0

    def test_smoke_sweep_paged_speculative(self):
        """One PAGED + SPECULATIVE sweep rate in tier-1 (ISSUE 10): the
        same loadgen arrivals through `ContinuousDecodeServer(
        paged=True, speculate=...)`, so every CI run exercises the
        block-table verify program under real traffic — block-gated
        admission, K-wide verify dispatches, and the pool accounting
        all in one pass. Its report uploads next to the paged one
        (tier1.yml)."""
        tools = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "tools")
        if tools not in sys.path:
            sys.path.insert(0, tools)
        mod = importlib.import_module("load_sweep")
        out = os.path.join(
            os.environ.get("SMOKE_REPORT_DIR") or tempfile.gettempdir(),
            "load_sweep_smoke_paged_spec")
        res = mod.run_sweep(server="decode", rates=(40.0,), n_req=8,
                            slo_ms=250.0, seed=0, trace=False,
                            report_path=out, paged=True, speculate_k=4)
        (decode,) = res
        assert decode["paged"] is True
        assert decode["speculate_k"] == 4
        (pt,) = decode["curve"]
        assert pt["completed"] == 8
        assert pt["tokens_per_sec"] > 0
        snap = json.load(open(out + ".json"))["metrics"]["decode"]
        # the paged pool carried the traffic AND the verify program
        # produced the tokens (every emitted token is a spec token in
        # speculative mode; dispatches/token <= 1 — the bonus floor)
        assert snap["pool_blocks"] > 0
        assert snap["blocks_in_use_max"] > 0
        assert snap["spec_tokens"] == snap["tokens_out"] > 0
        assert snap["dispatches_per_token"] <= 1.0

    def test_smoke_sweep_fused_serve(self):
        """One FUSED-WINDOW sweep rate in tier-1 (ISSUE 18): the same
        loadgen arrivals through `ContinuousDecodeServer(fused_serve=4)`
        so every CI run exercises the scanned K-iteration decode
        program under real traffic — window dispatch, boundary
        admission, and the per-iteration estimator fan-out all in one
        pass. Its report uploads next to the other smoke sweeps
        (tier1.yml)."""
        tools = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "tools")
        if tools not in sys.path:
            sys.path.insert(0, tools)
        mod = importlib.import_module("load_sweep")
        out = os.path.join(
            os.environ.get("SMOKE_REPORT_DIR") or tempfile.gettempdir(),
            "load_sweep_smoke_fused")
        res = mod.run_sweep(server="decode", rates=(40.0,), n_req=8,
                            slo_ms=250.0, seed=0, trace=False,
                            report_path=out, fused_serve=4)
        (decode,) = res
        assert decode["fused_serve"] == 4
        (pt,) = decode["curve"]
        assert pt["completed"] == 8
        assert pt["tokens_per_sec"] > 0
        snap = json.load(open(out + ".json"))["metrics"]["decode"]
        # the fused program really carried the decode traffic: windows
        # were dispatched and each one retired >1 iteration on average
        assert snap["fused_windows"] > 0
        assert snap["iterations_per_dispatch"] > 1.0

    def test_smoke_sweep_preempt_mode(self):
        """One PREEMPTION-enabled sweep rate in tier-1 (ISSUE 11:
        durable KV state): the same loadgen arrivals through
        `ContinuousDecodeServer(paged=True, preempt=True)` with the
        mix's long tail submitted as the spillable batch class — every
        CI run exercises the preempt/spill/resume machinery (and its
        always-present snapshot keys) under real arrivals, not just
        the unit pins. Its report uploads next to the paged one
        (tier1.yml)."""
        tools = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "tools")
        if tools not in sys.path:
            sys.path.insert(0, tools)
        mod = importlib.import_module("load_sweep")
        out = os.path.join(
            os.environ.get("SMOKE_REPORT_DIR") or tempfile.gettempdir(),
            "load_sweep_smoke_preempt")
        res = mod.run_sweep(server="decode", rates=(40.0,), n_req=8,
                            slo_ms=250.0, seed=0, trace=False,
                            report_path=out, preempt=True)
        (decode,) = res
        assert decode["preempt"] is True
        assert decode["paged"] is True      # implied by --preempt
        (pt,) = decode["curve"]
        assert pt["completed"] == 8
        assert pt["tokens_per_sec"] > 0
        snap = json.load(open(out + ".json"))["metrics"]["decode"]
        assert snap["pool_blocks"] > 0
        # the durable-KV keys ride every snapshot (zero when the smoke
        # rate never saturated the pool — presence is the pin; the
        # preemption BEHAVIOR pins live in tests/test_kvstate.py)
        for key in ("preempted", "resumed", "migrated", "spill_bytes",
                    "prefix_restore_hits"):
            assert key in snap

    def test_smoke_sweep_fleet_autoscale(self):
        """The 2-replica fleet mini-sweep in tier-1 (ISSUE 12): a
        below-knee and a far-past-knee rate through TWO named
        round-robin decode replicas with deadline-aware admission, the
        merged fleet snapshot fed to ONE AutoscaleSignal per schedule
        slice. Pins the e2e acceptance: the detector fires `scale_up`
        past the knee (sheds accruing while the fleet service-rate
        estimate is not rising) and stays `hold` below it — plus the
        merged multi-instance trace artifact CI uploads (tier1.yml)."""
        tools = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "tools")
        if tools not in sys.path:
            sys.path.insert(0, tools)
        mod = importlib.import_module("load_sweep")
        out = os.path.join(
            os.environ.get("SMOKE_REPORT_DIR") or tempfile.gettempdir(),
            "load_sweep_smoke_fleet")
        res = mod.run_sweep(server="decode", rates=(30.0, 1500.0),
                            n_req=24, slo_ms=400.0, seed=0, trace=True,
                            report_path=out, fleet=2,
                            fleet_obs_per_rate=6, fleet_slice_s=0.2)
        (body,) = res
        assert body["server"] == "fleet"
        assert body["n_replicas"] == 2
        below, past = body["curve"]
        # below the knee: zero predicted sheds, the detector holds
        assert set(below["autoscale_decisions"]) == {"hold"}
        # far past the knee: sheds accrue every slice while the fleet
        # capacity estimate stays flat/sagging -> scale_up fires and
        # ends the rung latched
        assert "scale_up" in past["autoscale_decisions"]
        assert past["autoscale_decision"] == "scale_up"
        assert past["fleet_shed_predicted"] > 0
        assert body["fleet"]["fleet_instances"] == 2
        # artifacts: report + the clock-anchor-MERGED trace with both
        # replicas as distinct process groups
        rep = json.load(open(out + ".json"))
        assert rep["sweep"][0]["server"] == "fleet"
        assert os.path.exists(out + ".txt")
        merged = json.load(open(out + ".trace.merged.json"))
        xs = [e for e in merged["traceEvents"] if e.get("ph") == "X"]
        assert sorted({e["pid"] for e in xs}) == [1, 2]
        pnames = {e["args"]["name"] for e in merged["traceEvents"]
                  if e.get("ph") == "M"
                  and e.get("name") == "process_name"}
        assert pnames == {"i0", "i1"}

    def test_smoke_sweep_fleet_procs(self):
        """The CROSS-PROCESS fleet smoke (ISSUE 14: the serving wire):
        `load_sweep --fleet-procs 2` — two REAL replica child
        processes behind `serving/wire.py` RemoteReplicas, routed by
        the FleetManager, with ONE injected socket sever mid-stream.
        Pins the acceptance: zero lost requests (every admitted future
        resolves), the faulted batch's streams BIT-IDENTICAL to the
        quiet fleet's (dedup re-delivery / failover replay are
        indistinguishable from an undisturbed run), the sever visibly
        exercised the reconnect path (wire counters moved), and the
        merged trace covers BOTH replica pids as distinct Perfetto
        process groups. Artifacts upload next to the in-process fleet
        smokes (tier1.yml)."""
        tools = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "tools")
        if tools not in sys.path:
            sys.path.insert(0, tools)
        mod = importlib.import_module("load_sweep")
        out = os.path.join(
            os.environ.get("SMOKE_REPORT_DIR") or tempfile.gettempdir(),
            "load_sweep_smoke_fleet_procs")
        res = mod.run_sweep(server="decode", rates=(40.0,), n_req=16,
                            slo_ms=400.0, seed=0, trace=True,
                            report_path=out, fleet_procs=2,
                            fleet_obs_per_rate=3, fleet_slice_s=0.15)
        (body,) = res
        assert body["server"] == "fleet_procs"
        assert len(body["replica_pids"]) == 2
        assert len(set(body["replica_pids"].values())) == 2  # real procs
        # zero lost under real arrivals: every admitted future resolved
        for pt in body["curve"]:
            assert pt["admitted"] == pt["completed"] + pt["failed"]
        # the injected sever: fired once, nothing lost, bits identical
        fault = body["wire_fault"]
        assert fault["severed"] == 1
        assert fault["all_futures_resolved"] is True
        assert fault["streams_bit_identical"] is True
        assert fault["wire_reconnects"] >= 1    # the wire really died
        assert fault["wire_retries"] >= 1       # and really resent
        # the wire counters ride the federated fleet snapshot
        assert body["fleet"]["fleet_wire_reconnects"] >= 1
        # artifacts: report + the merged trace covering BOTH pids
        rep = json.load(open(out + ".json"))
        assert rep["sweep"][0]["server"] == "fleet_procs"
        assert os.path.exists(out + ".txt")
        merged = json.load(open(out + ".trace.merged.json"))
        xs = [e for e in merged["traceEvents"] if e.get("ph") == "X"]
        assert sorted({e["pid"] for e in xs}) == [1, 2]
        pnames = {e["args"]["name"] for e in merged["traceEvents"]
                  if e.get("ph") == "M"
                  and e.get("name") == "process_name"}
        assert pnames == {"i0", "i1"}

    def test_smoke_sweep_fleet_control(self):
        """The CLOSED-LOOP fleet smoke (ISSUE 13): 2 -> 3 -> 2
        replicas with one injected replica death, driven end to end by
        the FleetManager — scale_up past the knee actually ADDS a
        replica (and goodput does not collapse across the spawn),
        a mid-sweep `fleet.replica` sever kills one replica with zero
        lost requests (every admitted future resolves), and the quiet
        tail drains back to min_replicas. Artifacts upload next to the
        observe-only fleet smoke (tier1.yml)."""
        from deeplearning4j_tpu.common.resilience import FaultInjector
        tools = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "tools")
        if tools not in sys.path:
            sys.path.insert(0, tools)
        mod = importlib.import_module("load_sweep")
        out = os.path.join(
            os.environ.get("SMOKE_REPORT_DIR") or tempfile.gettempdir(),
            "load_sweep_smoke_fleet_control")
        inj = FaultInjector()
        # fleet.replica fires once per alive replica per control tick:
        # 2/tick through rung 1 (6 ticks = calls 0-11), so call 13 is
        # rung 2's FIRST tick — the death lands before any scale_up
        # (the signal's window is still warming into the overload
        # regime), the same tick's floor check backfills to min=2, and
        # the later scale_up takes the fleet to 3 so the quiet tail
        # has a replica to DRAIN back down
        inj.plan("fleet.replica", on_call=13, sever=True, exc=None)
        # the overload rung uses the observe-only fleet smoke's proven
        # far-past-knee rate: at 800 req/s a fast freshly-warm box can
        # absorb most of the offered load within the SLO (observed —
        # ~100 predicted sheds over the whole rung) and the detector
        # CORRECTLY holds; 1500 req/s saturates any machine weather
        res = mod.run_sweep(server="decode",
                            rates=(30.0, 1500.0, 10.0, 10.0),
                            n_req=24, slo_ms=400.0, seed=0, trace=True,
                            report_path=out, fleet=2,
                            fleet_control=True, fleet_injector=inj,
                            fleet_max=3, fleet_obs_per_rate=6,
                            fleet_slice_s=0.15)
        (body,) = res
        assert body["server"] == "fleet_control"
        ctl = body["fleet_control"]
        # scale_up past the knee really added a replica (on a slow,
        # noisy host the below-knee rung can shed enough to scale
        # early — the pin is that the fleet REACHED 3 via an acted
        # scale_up, wherever the window crossed)
        assert ctl["scale_up_at"] is not None
        assert any("scale_up" in pt["autoscale_acted"]
                   for pt in body["curve"])
        assert max(max(pt["n_replicas"]) for pt in body["curve"]) == 3
        # the injected death: exactly one, and nothing was lost —
        # every admitted request completed or failed LOUDLY (run_load
        # resolves every future; a hung future would time it out)
        assert ctl["replica_dead"] == 1
        for pt in body["curve"]:
            assert pt["admitted"] == pt["completed"] + pt["failed"]
        # goodput across the spawn: the official criterion is 0.8x
        # (recorded in the artifact); the CI assert uses the sweep's
        # documented machine-weather slack (MONOTONE_SLACK — identical
        # baseline runs vary >2x on shared-CPU hosts). A spawn landing
        # on a rung's FINAL slice has no post-spawn slices to measure
        # (recovery None) — the scale-up pin above still holds
        rec = ctl["goodput_recovery_x"]
        if rec is not None:
            assert rec >= mod.MONOTONE_SLACK
        # quiet tail: drained back to the floor
        assert ctl["n_replicas_final"] == 2
        assert ctl["returned_to_min"] is True
        assert ctl["replica_drained"] >= 1
        # artifacts: report + merged multi-instance trace (every
        # replica that ever lived gets a process group)
        rep = json.load(open(out + ".json"))
        assert rep["sweep"][0]["server"] == "fleet_control"
        assert os.path.exists(out + ".txt")
        merged = json.load(open(out + ".trace.merged.json"))
        pnames = {e["args"]["name"] for e in merged["traceEvents"]
                  if e.get("ph") == "M"
                  and e.get("name") == "process_name"}
        assert {"i0", "i1"} <= pnames and len(pnames) >= 3

    def test_smoke_sweep_affinity(self):
        """The PREFIX-AFFINITY fleet smoke (ISSUE 20): `load_sweep
        --fleet-procs 2 --affinity` — solo vs affinity vs least-
        backlog on one seeded shared-system-prompt workload, the two
        fleet arms as REAL replica processes (block pulls travel as
        PREFIX_PULL/PREFIX_PUSH artifact frames). Pins the
        acceptance: fleet hit rate retained at >= 0.9x the solo
        ceiling (the prefix-blind baseline recorded alongside), ZERO
        lost requests in every arm, the no-pull affinity path at ZERO
        added device dispatches per token (dispatch-counter A/B), and
        the ring-churn phase really pulling blocks over the wire
        after a scale_up remaps keys. Artifacts upload next to the
        other fleet smokes (tier1.yml)."""
        tools = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "tools")
        if tools not in sys.path:
            sys.path.insert(0, tools)
        mod = importlib.import_module("load_sweep")
        out = os.path.join(
            os.environ.get("SMOKE_REPORT_DIR") or tempfile.gettempdir(),
            "load_sweep_smoke_affinity")
        res = mod.run_sweep(server="decode", rates=(30.0,), n_req=12,
                            slo_ms=400.0, seed=0, trace=False,
                            report_path=out, affinity=True,
                            fleet_procs=2, fleet_obs_per_rate=2,
                            fleet_slice_s=0.2)
        (body,) = res
        assert body["server"] == "fleet_affinity"
        assert body["procs"] == 2
        # the acceptance pin: affinity keeps the solo hit-rate ceiling
        # while the prefix-blind baseline is recorded alongside
        assert body["solo"]["hit_rate"] > 0
        assert body["least_backlog"]["hit_rate"] is not None
        assert body["hit_rate_ratio_vs_solo"] >= 0.9
        assert body["hit_rate_retained_09"] is True
        # zero lost requests: every admitted future resolved, all arms
        for arm in ("solo", "affinity", "least_backlog"):
            rec = body[arm]
            assert rec["lost"] == 0
            for pt in rec["curve"]:
                assert pt["admitted"] == pt["completed"] + pt["failed"]
        assert body["affinity"]["routed_affinity"] > 0
        # the dispatch A/B: consistent-hash routing is host-side work —
        # the same fixed request list through a fleet-of-one under each
        # policy dispatches IDENTICALLY (zero added per token)
        dab = body["dispatch_ab"]
        assert dab["zero_added_dispatches"] is True
        assert dab["affinity_dispatches"] \
            == dab["least_backlog_dispatches"]
        assert dab["affinity_tokens"] == dab["least_backlog_tokens"]
        # ring churn: scale_up remapped >= 1 prefix and the prefetch
        # pulled its blocks over the REAL wire into the newcomer; the
        # re-routed requests then hit the adopted rows
        churn = body["affinity"]["ring_churn"]
        assert churn is not None
        assert churn["keys_moved"] >= 1
        assert churn["pulled_blocks"] >= 1
        assert churn["prefix_pull_hits"] >= 1
        assert churn["prefix_pull_bytes"] > 0
        assert churn["rehit_rows_after_pull"] > 0
        # artifacts for tier1.yml
        rep = json.load(open(out + ".json"))
        assert rep["sweep"][0]["server"] == "fleet_affinity"
        assert os.path.exists(out + ".txt")
