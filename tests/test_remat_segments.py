"""Segment gradient checkpointing (ComputationGraph remat_segments) —
the structural bytes/step lever for HBM-bound CNN training (PERF.md r4).
Numerics must be IDENTICAL to the default path: remat changes what the
backward stores, never what it computes."""
import numpy as np
import pytest

jax = __import__("jax")
jnp = jax.numpy

from deeplearning4j_tpu import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.nn.conf.graph_vertices import ElementWiseVertex
from deeplearning4j_tpu.nn.conf.layers import (ActivationLayer,
                                               BatchNormalization,
                                               ConvolutionLayer,
                                               GlobalPoolingLayer,
                                               OutputLayer)
from deeplearning4j_tpu.nn.graph import ComputationGraph


def _residual_conf(seed=7):
    """Two residual blocks: conv->BN->relu chains + adds (the ResNet
    shape at toy scale)."""
    gb = (NeuralNetConfiguration.Builder().seed(seed).updater("sgd")
          .learning_rate(0.1).weight_init("relu").graph_builder()
          .add_inputs("input"))
    gb.add_layer("c0", ConvolutionLayer(n_out=8, kernel_size=(3, 3),
                                        convolution_mode="same"), "input")
    x = "c0"
    for b in range(2):
        gb.add_layer(f"b{b}_c1", ConvolutionLayer(
            n_out=8, kernel_size=(3, 3), convolution_mode="same"), x)
        gb.add_layer(f"b{b}_bn", BatchNormalization(), f"b{b}_c1")
        gb.add_layer(f"b{b}_r", ActivationLayer(activation="relu"),
                     f"b{b}_bn")
        gb.add_layer(f"b{b}_c2", ConvolutionLayer(
            n_out=8, kernel_size=(3, 3), convolution_mode="same"),
            f"b{b}_r")
        gb.add_vertex(f"b{b}_add", ElementWiseVertex(op="add"),
                      f"b{b}_c2", x)
        gb.add_layer(f"b{b}_out", ActivationLayer(activation="relu"),
                     f"b{b}_add")
        x = f"b{b}_out"
    gb.add_layer("pool", GlobalPoolingLayer(pooling_type="avg"), x)
    gb.add_layer("fc", OutputLayer(n_out=3, activation="softmax",
                                   loss_function="mcxent"), "pool")
    return (gb.set_outputs("fc")
            .set_input_types(InputType.convolutional(8, 8, 2)).build())


def _data(seed=0):
    rng = np.random.default_rng(seed)
    x = rng.random((8, 8, 8, 2)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 8)]
    return x, y


class TestRematSegments:
    def test_plan_segments_at_adds(self):
        net = ComputationGraph(_residual_conf(), remat_segments=True).init()
        seg_of, n_seg = net._remat_plan()
        assert n_seg == 3                       # two adds -> three segments
        assert seg_of["b0_c1"] == 0
        assert seg_of["b0_out"] == 1            # first vertex after add 0
        assert seg_of["fc"] == 2

    def test_training_identical_to_default(self):
        """Same seed, same data: per-step scores and final params match
        the non-remat path bit-for-bit-ish (fp tolerance)."""
        x, y = _data()
        nets = [ComputationGraph(_residual_conf(), remat_segments=r).init()
                for r in (False, True)]
        scores = [[], []]
        for i, net in enumerate(nets):
            for _ in range(4):
                net.fit(DataSet(x, y))
                scores[i].append(float(net._score))
        np.testing.assert_allclose(scores[0], scores[1], rtol=1e-5)
        for a, b in zip(jax.tree.leaves(nets[0]._params),
                        jax.tree.leaves(nets[1]._params)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=2e-5)

    def test_bn_running_stats_still_update(self):
        x, y = _data()
        net = ComputationGraph(_residual_conf(), remat_segments=True).init()
        before = np.asarray(net._model_state["b0_bn"]["mean"]).copy()
        net.fit(DataSet(x, y))
        after = np.asarray(net._model_state["b0_bn"]["mean"])
        assert not np.allclose(before, after)

    def test_inference_output_matches(self):
        x, _ = _data()
        n0 = ComputationGraph(_residual_conf(), remat_segments=False).init()
        n1 = ComputationGraph(_residual_conf(), remat_segments=True).init()
        np.testing.assert_allclose(np.asarray(n0.output(x)),
                                   np.asarray(n1.output(x)), atol=1e-6)

    def test_resnet50_factory_flag(self):
        from deeplearning4j_tpu.models.zoo.resnet import resnet50_conf
        conf = resnet50_conf(height=32, width=32, num_classes=4,
                             data_type="float32")
        net = ComputationGraph(conf, remat_segments=True)
        _, n_seg = net._remat_plan()
        assert n_seg == 17                      # 16 bottleneck adds + head
