"""Mesh-sharded checkpointing (orbax): exact resume + sharding-preserving
restore — the TPU-scale path the reference's single-JVM ModelSerializer
zip (util/ModelSerializer.java) cannot express. The zip format keeps its
own golden tests (test_regression_golden.py); these pin the sharded one."""
import jax
import numpy as np
import pytest

from deeplearning4j_tpu import (InputType, MultiLayerNetwork,
                                NeuralNetConfiguration)
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.util.sharded_checkpoint import (load_checkpoint,
                                                        save_checkpoint)


def _net(seed=11):
    conf = (NeuralNetConfiguration.Builder().seed(seed)
            .updater("adam").learning_rate(0.05).list()
            .layer(0, DenseLayer(n_out=16, activation="relu"))
            .layer(1, OutputLayer(n_out=3, activation="softmax",
                                  loss_function="mcxent"))
            .set_input_type(InputType.feed_forward(5))
            .build())
    return MultiLayerNetwork(conf).init()


def _data(n=64, seed=0):
    r = np.random.default_rng(seed)
    x = r.random((n, 5)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[r.integers(0, 3, n)]
    return DataSet(x, y)


def test_exact_resume_round_trip(tmp_path):
    """Save mid-training; a fresh net restored from the checkpoint
    continues EXACTLY like the original (params, updater moments, rng,
    iteration counter and the device loop state all round-trip)."""
    ds = _data()
    a = _net()
    for _ in range(5):
        a.fit(ds)
    save_checkpoint(a, tmp_path / "ck")
    b = load_checkpoint(_net(seed=99), tmp_path / "ck")
    assert b.conf.iteration_count == a.conf.iteration_count
    np.testing.assert_array_equal(np.asarray(a.params()),
                                  np.asarray(b.params()))
    # identical continuation: 3 more steps on each, scores match exactly
    for _ in range(3):
        a.fit(ds)
        b.fit(ds)
        assert float(a._score) == float(b._score)


def test_fixed_path_periodic_resave(tmp_path):
    """The periodic-save pattern: re-saving to the same path overwrites
    (ModelSerializer semantics); overwrite=False raises instead."""
    ds = _data()
    a = _net()
    a.fit(ds)
    save_checkpoint(a, tmp_path / "latest")
    a.fit(ds)
    save_checkpoint(a, tmp_path / "latest")      # overwrite, no raise
    b = load_checkpoint(_net(seed=2), tmp_path / "latest")
    assert b.conf.iteration_count == a.conf.iteration_count
    with pytest.raises(ValueError):
        save_checkpoint(a, tmp_path / "latest", overwrite=False)


def test_unfitted_net_round_trip(tmp_path):
    """No loop state yet (never fitted): the placeholder keeps the pytree
    structure fixed and restore leaves the loop unset."""
    a = _net()
    save_checkpoint(a, tmp_path / "ck")
    b = load_checkpoint(_net(seed=5), tmp_path / "ck")
    assert b._loop is None
    np.testing.assert_array_equal(np.asarray(a.params()),
                                  np.asarray(b.params()))


@pytest.mark.slow
def test_computation_graph_round_trip(tmp_path):
    """Same module serves ComputationGraph (dict-keyed pytrees)."""
    from deeplearning4j_tpu import ComputationGraph
    from deeplearning4j_tpu.nn.conf.graph_vertices import MergeVertex

    def build(seed=3):
        conf = (NeuralNetConfiguration.Builder().seed(seed)
                .updater("adam").learning_rate(0.05)
                .graph_builder()
                .add_inputs("in")
                .add_layer("d1", DenseLayer(n_out=8, activation="tanh"),
                           "in")
                .add_layer("d2", DenseLayer(n_out=8, activation="relu"),
                           "in")
                .add_vertex("m", MergeVertex(), "d1", "d2")
                .add_layer("out", OutputLayer(
                    n_out=3, activation="softmax",
                    loss_function="mcxent"), "m")
                .set_outputs("out")
                .set_input_types(InputType.feed_forward(5))
                .build())
        return ComputationGraph(conf).init()

    ds = _data()
    a = build()
    for _ in range(4):
        a.fit(ds)
    save_checkpoint(a, tmp_path / "ck")
    b = load_checkpoint(build(seed=77), tmp_path / "ck")
    for _ in range(2):
        a.fit(ds)
        b.fit(ds)
        assert float(a._score) == float(b._score)


def test_checkpoint_manager_retention(tmp_path):
    """keep-last-k + keep-best retention (the CheckpointListener role):
    the best-scoring checkpoint survives pruning even when old."""
    import os
    from deeplearning4j_tpu.util.sharded_checkpoint import \
        ShardedCheckpointManager
    ds = _data()
    net = _net()
    mgr = ShardedCheckpointManager(tmp_path / "ckpts", keep_last=2,
                                   mode="min")
    # step 1 gets the BEST (lowest) score; later steps score worse
    scores = {1: 0.10, 2: 0.50, 3: 0.40, 4: 0.60, 5: 0.70}
    for step, score in scores.items():
        net.fit(ds)
        mgr.save(net, step, score=score)
    assert mgr.steps() == [1, 4, 5]          # last 2 + best
    assert mgr.best_step() == 1
    kept = sorted(d for d in os.listdir(tmp_path / "ckpts")
                  if d.startswith("ckpt_"))
    assert kept == ["ckpt_1", "ckpt_4", "ckpt_5"]
    # restores: latest continues exactly; best differs from latest
    b = mgr.restore_latest(_net(seed=2))
    assert b.conf.iteration_count == net.conf.iteration_count
    best = mgr.restore_best(_net(seed=3))
    assert best.conf.iteration_count < b.conf.iteration_count
    # a fresh manager over the same dir reloads the metadata
    mgr2 = ShardedCheckpointManager(tmp_path / "ckpts", keep_last=2)
    assert mgr2.steps() == [1, 4, 5] and mgr2.best_step() == 1
    # a mismatched retention policy on resume fails loudly (a silent
    # mode flip would invert best_step and prune the true best)
    with pytest.raises(ValueError):
        ShardedCheckpointManager(tmp_path / "ckpts", keep_last=2,
                                 mode="max")
    # a score-less re-save of a scored step keeps the recorded score
    net.fit(ds)
    mgr2.save(net, 1)
    assert mgr2.best_step() == 1
    # orphan sweep: a dir left by a crash (metadata written, delete
    # missed) disappears on the next save
    os.makedirs(tmp_path / "ckpts" / "ckpt_99")
    net.fit(ds)
    mgr2.save(net, 6, score=0.8)
    assert not (tmp_path / "ckpts" / "ckpt_99").exists()


@pytest.mark.slow
def test_sharded_saver_in_early_stopping(tmp_path):
    """ShardedModelSaver drives the early-stopping trainer the way
    LocalFileModelSaver does (reference saver SPI), restoring the best
    model from the sharded format via the architecture factory."""
    from deeplearning4j_tpu.earlystopping.early_stopping import (
        DataSetLossCalculator, EarlyStoppingConfiguration,
        EarlyStoppingTrainer, MaxEpochsTerminationCondition)
    from deeplearning4j_tpu.datasets.iterators import ListDataSetIterator
    from deeplearning4j_tpu.util.sharded_checkpoint import \
        ShardedModelSaver
    train = ListDataSetIterator(list(_data(64, 1).batch_by(16)))
    val = ListDataSetIterator(list(_data(32, 2).batch_by(16)))
    es = (EarlyStoppingConfiguration.Builder()
          .score_calculator(DataSetLossCalculator(val))
          .epoch_termination_conditions(MaxEpochsTerminationCondition(2))
          .model_saver(ShardedModelSaver(str(tmp_path), _net))
          .build())
    result = EarlyStoppingTrainer(es, _net(), train).fit()
    best = result.get_best_model()
    assert best is not None
    assert (tmp_path / "bestModel").exists()
    assert np.asarray(best.output(_data(32).features)).shape == (32, 3)


@pytest.mark.multiprocess
def test_two_process_sharded_save_restore(tmp_path):
    """2 real processes x 2 devices: every process writes only its own
    shards on save (orbax multihost commit over the jax.distributed
    coordinator), restore lands ZeRO-partitioned, continuation identical
    across processes."""
    import os
    import socket
    import subprocess
    import sys
    REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    coord = f"127.0.0.1:{s.getsockname()[1]}"
    s.close()
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    env["PYTHONPATH"] = REPO
    script = os.path.join(REPO, "tests", "multihost_worker_ckpt.py")
    procs = [subprocess.Popen(
        [sys.executable, script, str(i), "2", coord,
         str(tmp_path / "ck")],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True, env=env) for i in range(2)]
    outs = [p.communicate(timeout=280)[0] for p in procs]
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {i} failed:\n{out}"
    results = {}
    for out in outs:
        for line in out.splitlines():
            if line.startswith("RESULT"):
                _, pid, ssum, sc = line.split()
                results[int(pid)] = (ssum, sc)
    assert set(results) == {0, 1}, outs
    assert results[0] == results[1]              # bit-identical across procs


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 devices")
def test_restore_into_zero1_sharded_layout(tmp_path):
    """Restore places shards onto the CURRENT sharding of the target: a
    fresh net sharded by ParallelWrapper (ZeRO-1 optimizer partitioning)
    restores with the Adam moments landing partitioned over 'data' — no
    host ever holds the replicated whole."""
    from deeplearning4j_tpu.parallel import ParallelWrapper, make_mesh
    ds = _data()
    a = _net()
    pw_a = (ParallelWrapper.Builder(a)
            .mesh(make_mesh(n_data=8, n_model=1))
            .sharded_updater_state(True).averaging_frequency(1).build())
    pw_a.fit(ds)
    save_checkpoint(a, tmp_path / "ck")

    b = _net(seed=42)
    pw_b = (ParallelWrapper.Builder(b)
            .mesh(make_mesh(n_data=8, n_model=1))
            .sharded_updater_state(True).averaging_frequency(1).build())
    pw_b._ensure_sharded()
    load_checkpoint(b, tmp_path / "ck")
    np.testing.assert_array_equal(np.asarray(a.params()),
                                  np.asarray(b.params()))
    # the restored Adam moment landed ZeRO-partitioned, not replicated
    m = b._updater_state[0]["W"]["m"]
    assert "data" in jax.tree_util.tree_leaves(
        [tuple(m.sharding.spec)])  # spec mentions the data axis
    # and training continues identically to the original sharded run
    pw_a.fit(ds)
    pw_b.fit(ds)
    assert float(a._score) == float(b._score)


def test_wrong_architecture_restore_fails_loudly(tmp_path):
    """Restoring into a mismatched architecture raises (orbax shape
    check) — never silently truncates or pads."""
    ds = _data()
    a = _net()
    a.fit(ds)
    save_checkpoint(a, tmp_path / "ck")
    from deeplearning4j_tpu import (InputType, MultiLayerNetwork,
                                    NeuralNetConfiguration)
    other = MultiLayerNetwork(
        (NeuralNetConfiguration.Builder().seed(1).updater("adam")
         .learning_rate(0.05).list()
         .layer(0, DenseLayer(n_out=99, activation="relu"))
         .layer(1, OutputLayer(n_out=3, activation="softmax",
                               loss_function="mcxent"))
         .set_input_type(InputType.feed_forward(5)).build())).init()
    with pytest.raises(Exception):
        load_checkpoint(other, tmp_path / "ck")
