"""Japanese lattice (trie + Viterbi) tokenizer — dictionary-based
morphological segmentation the script-transition baseline cannot do
(reference: deeplearning4j-nlp-japanese Kuromoji ViterbiSearcher.java /
PatriciaTrie.java), plus Korean particle-stripping behavior
(deeplearning4j-nlp-korean KoreanTokenizer.java)."""
import pytest

from deeplearning4j_tpu.text.cjk_tokenization import (JapaneseTokenizer,
                                                      KoreanTokenizer)
from deeplearning4j_tpu.text.ja_lattice import (
    JapaneseLatticeTokenizer, JapaneseLatticeTokenizerFactory,
    viterbi_segment)
from deeplearning4j_tpu.text.ja_lexicon import build_entries


class TestLexicon:
    def test_conjugation_expansion_scale(self):
        """A few hundred lemmas expand to thousands of surface forms —
        the Kuromoji dictionary shape at small scale (r5: ~4.9k
        surfaces after the everyday-vocabulary expansion)."""
        entries = build_entries()
        assert len(entries) > 4500
        surfaces = {s for s, _, _ in entries}
        # expanded godan forms (never written in the lexicon literally)
        for form in ("行きました", "書いて", "読んだ", "買った", "話して",
                     "飲みません", "待って", "遊んで", "泳いだ"):
            assert form in surfaces, form
        # expanded i-adjective forms
        for form in ("高かった", "新しくない", "暑くて"):
            assert form in surfaces, form


class TestLatticeSegmentation:
    def test_all_hiragana_classic(self):
        """The classic: one unbroken hiragana run — script-transition
        splitting yields a single token; the lattice segments the words."""
        text = "すもももももももものうち"
        assert JapaneseTokenizer(text)._tokens == [text]  # baseline fails
        assert JapaneseLatticeTokenizer(text)._tokens == \
            ["すもも", "も", "もも", "も", "もも", "の", "うち"]

    def test_mixed_script_sentence(self):
        """は after 私 is a particle boundary the script splitter merges
        (私は is one hiragana-adjacent run boundary, but 行きました is
        split mid-verb by the han->hiragana transition)."""
        got = JapaneseLatticeTokenizer("私は学校に行きました")._tokens
        assert got == ["私", "は", "学校", "に", "行きました"]
        # the baseline splits the verb 行きました after the kanji stem
        base = JapaneseTokenizer("私は学校に行きました")._tokens
        assert "行きました" not in base

    def test_katakana_unknown_word_grouped(self):
        got = JapaneseLatticeTokenizer("東京でラーメンを食べた")._tokens
        assert got == ["東京", "で", "ラーメン", "を", "食べた"]

    def test_adjective_and_final_particles(self):
        got = JapaneseLatticeTokenizer("今日はとても暑いですね")._tokens
        assert got == ["今日", "は", "とても", "暑い", "です", "ね"]

    def test_te_iru_progressive(self):
        got = JapaneseLatticeTokenizer("彼女は新しい本を読んでいます")._tokens
        assert got == ["彼女", "は", "新しい", "本", "を", "読んでいます"]

    def test_expanded_everyday_vocabulary(self):
        """r5 lexicon expansion: everyday sentences over the new nouns/
        verbs (weekdays, facilities, loanword nouns, expanded godan and
        ichidan conjugations) segment correctly."""
        cases = {
            "昨日友達と映画館で面白い映画を見ました":
                ["昨日", "友達", "と", "映画館", "で", "面白い", "映画",
                 "を", "見ました"],
            "来週の日曜日に家族と動物園へ行く予定です":
                ["来週", "の", "日曜日", "に", "家族", "と", "動物園",
                 "へ", "行く", "予定", "です"],
            "冷蔵庫に牛乳とチーズが残っています":
                ["冷蔵庫", "に", "牛乳", "と", "チーズ", "が",
                 "残っています"],
        }
        for text, want in cases.items():
            assert JapaneseLatticeTokenizer(text)._tokens == want, text

    def test_punctuation_splits_chunks(self):
        got = JapaneseLatticeTokenizer("今日は雨です。明日は晴れます。")._tokens
        assert got == ["今日", "は", "雨", "です", "明日", "は", "晴れます"]

    def test_pos_tags_exposed(self):
        t = JapaneseLatticeTokenizer("私は学校に行きました")
        assert t.pos_tags == ["pron", "particle", "noun", "particle",
                              "verb"]

    def test_unknown_model_always_connects(self):
        # out-of-vocabulary everything still yields a segmentation
        toks = JapaneseLatticeTokenizer("燚燚燚がヘンテコだ")._tokens
        assert toks and "".join(toks) == "燚燚燚がヘンテコだ"

    def test_viterbi_segment_empty(self):
        assert viterbi_segment("") == []

    def test_factory_spi(self):
        f = JapaneseLatticeTokenizerFactory()
        t = f.create("水を飲みたいです")
        out = []
        while t.has_more_tokens():
            out.append(t.next_token())
        assert out == ["水", "を", "飲みたい", "です"]


class TestKoreanMorphology:
    """Eojeol decomposition (reference deeplearning4j-nlp-korean vendored
    KoreanText analyzer; closed-class + jamo-aware rules here)."""

    def test_stem_josa_eomi_stream(self):
        from deeplearning4j_tpu.text.ko_morph import KoreanMorphTokenizer
        got = KoreanMorphTokenizer("학교에서 공부를 했다")._tokens
        assert got == ["학교", "에서", "공부", "를", "하", "였다"]

    def test_batchim_agreement_selects_particle(self):
        from deeplearning4j_tpu.text.ko_morph import split_josa
        # 은/는, 이/가, 을/를 alternate on the final consonant
        assert split_josa("책은") == ("책", "은")
        assert split_josa("저는") == ("저", "는")
        assert split_josa("책이") == ("책", "이")
        assert split_josa("친구가") == ("친구", "가")
        # (으)로: 로 after vowel OR ㄹ-final (서울로), 으로 otherwise
        assert split_josa("서울로") == ("서울", "로")
        assert split_josa("집으로") == ("집", "으로")

    def test_ha_and_bieup_contractions(self):
        from deeplearning4j_tpu.text.ko_morph import split_eomi
        assert split_eomi("했다") == ("하", "였다")
        assert split_eomi("갑니다") == ("가", "ㅂ니다")      # 가 + ㅂ니다
        assert split_eomi("마십니다") == ("마시", "ㅂ니다")
        # regular polite after consonant stem stays table-matched — the
        # 습 syllable also ends in ㅂ, so this pins the tie-break (먹+습니다,
        # never the bogus 먹스+ㅂ니다)
        assert split_eomi("읽었습니다") == ("읽", "었습니다")
        assert split_eomi("먹습니다") == ("먹", "습니다")
        assert split_eomi("좋습니다") == ("좋", "습니다")

    def test_stems_only_mode_and_factory(self):
        from deeplearning4j_tpu.text.ko_morph import \
            KoreanMorphTokenizerFactory
        f = KoreanMorphTokenizerFactory(emit_affixes=False)
        t = f.create("학교에서 공부를 했다")
        assert t.get_tokens() == ["학교", "공부", "하"]

    def test_bare_nouns_pass_through(self):
        from deeplearning4j_tpu.text.ko_morph import KoreanMorphTokenizer
        assert KoreanMorphTokenizer("서울 김치")._tokens == ["서울", "김치"]


class TestKoreanParticles:
    def test_strips_common_particles(self):
        got = KoreanTokenizer("학교에서 공부를 했다")._tokens
        assert got == ["학교", "공부", "했다"]

    def test_longest_particle_wins(self):
        # 에서 must strip before 에 (longest-match ordering)
        assert KoreanTokenizer("도서관에서")._tokens == ["도서관"]
        assert KoreanTokenizer("도서관에")._tokens == ["도서관"]

    def test_no_strip_mode(self):
        got = KoreanTokenizer("학교에서 공부를 했다",
                              strip_particles=False)._tokens
        assert got == ["학교에서", "공부를", "했다"]

    def test_single_char_words_kept(self):
        # a word that IS a particle-like single char must not vanish
        assert KoreanTokenizer("물 좀 주세요")._tokens == ["물", "좀",
                                                           "주세요"]
