"""Loss-function tail (MAPE, MSLE) + VAE reconstruction-distribution set
(Exponential, Composite, LossFunctionWrapper) — closes the reference's
ILossFunction surface (nd4j LossMAPE/LossMSLE) and
nn/conf/layers/variational/ (ExponentialReconstructionDistribution.java,
CompositeReconstructionDistribution.java, LossFunctionWrapper.java).
Each new term is gradient-checked numerically, the reference's
VaeGradientCheckTests / LossFunctionGradientCheck pattern."""
import numpy as np
import pytest

jax = __import__("jax")
jnp = jax.numpy

from deeplearning4j_tpu.nn import losses
from deeplearning4j_tpu.nn.conf.layers.variational import (
    BernoulliReconstructionDistribution,
    CompositeReconstructionDistribution,
    ExponentialReconstructionDistribution,
    GaussianReconstructionDistribution, LossFunctionWrapper,
    VariationalAutoencoder, _dist_from_dict)


def _numeric_grad_check(f, x0, n_probe=25, eps=1e-6, tol=1e-4, seed=0):
    """Central-difference check of jax.grad(f) at flat vector x0."""
    g = np.asarray(jax.grad(f)(jnp.asarray(x0)))
    rs = np.random.default_rng(seed)
    idx = rs.choice(x0.size, min(n_probe, x0.size), replace=False)
    for i in idx:
        v = x0.copy()
        v[i] += eps
        sp = float(f(jnp.asarray(v)))
        v[i] -= 2 * eps
        sm = float(f(jnp.asarray(v)))
        num = (sp - sm) / (2 * eps)
        denom = abs(g[i]) + abs(num)
        assert denom == 0 or abs(g[i] - num) / denom < tol, (i, g[i], num)


class TestLossTail:
    def test_mape_value_and_grad(self):
        r = np.random.default_rng(0)
        y = r.random((6, 4)) + 0.5            # bounded away from zero
        p = r.standard_normal((6, 4))
        got = np.asarray(losses.mape(jnp.asarray(y), jnp.asarray(p)))
        want = (100.0 * np.abs(p - y) / np.abs(y)).sum(1) / 4
        np.testing.assert_allclose(got, want, rtol=1e-9)
        _numeric_grad_check(
            lambda v: jnp.mean(losses.mape(jnp.asarray(y),
                                           v.reshape(6, 4))),
            p.ravel().copy())

    def test_msle_value_and_grad(self):
        r = np.random.default_rng(1)
        y = r.random((5, 3)) * 4
        p = r.random((5, 3)) * 4
        got = np.asarray(losses.msle(jnp.asarray(y), jnp.asarray(p)))
        want = ((np.log1p(p) - np.log1p(y)) ** 2).sum(1) / 3
        np.testing.assert_allclose(got, want, rtol=1e-9)
        _numeric_grad_check(
            lambda v: jnp.mean(losses.msle(jnp.asarray(y),
                                           v.reshape(5, 3))),
            p.ravel().copy())

    def test_registry_exposes_new_losses(self):
        assert losses.get("mape") is losses.mape
        assert losses.get("MSLE") is losses.msle

    def test_mask_zeroes_contributions(self):
        y = jnp.ones((2, 3)) * 2.0
        p = jnp.ones((2, 3)) * 3.0
        m = jnp.asarray([[1.0, 1.0, 0.0], [1.0, 1.0, 1.0]])
        full = np.asarray(losses.mape(y, p, "identity", None))
        masked = np.asarray(losses.mape(y, p, "identity", m))
        assert masked[0] == pytest.approx(full[0] * 2 / 3)
        assert masked[1] == pytest.approx(full[1])


def _vae(dist):
    return VariationalAutoencoder(
        n_in=8, n_out=3, encoder_layer_sizes=(10,),
        decoder_layer_sizes=(10,), activation="tanh",
        reconstruction_distribution=dist,
    ).apply_global_defaults({"weight_init": "xavier"})


def _flat_elbo(vae, x, seed=0):
    params = vae.init_params(jax.random.PRNGKey(seed), jnp.float64)
    leaves, treedef = jax.tree_util.tree_flatten(params)
    flat = np.concatenate([np.asarray(l).ravel() for l in leaves])
    rng = jax.random.PRNGKey(3)

    def unflatten(v):
        out, off = [], 0
        for l in leaves:
            n = l.size
            out.append(jnp.asarray(v[off:off + n]).reshape(l.shape))
            off += n
        return jax.tree_util.tree_unflatten(treedef, out)

    return params, flat, (lambda v: vae.pretrain_loss(unflatten(v), x,
                                                      rng=rng))


class TestReconstructionDistributionTail:
    @pytest.mark.slow
    def test_exponential_elbo_gradcheck(self):
        r = np.random.default_rng(2)
        x = jnp.asarray(r.exponential(1.0, (12, 8)))
        vae = _vae({"type": "exponential"})
        _, flat, f = _flat_elbo(vae, x)
        _numeric_grad_check(f, flat, n_probe=12)

    def test_exponential_mean_is_inverse_rate(self):
        d = ExponentialReconstructionDistribution()
        gamma = jnp.asarray([[0.0, 1.0, -1.0]])
        mean = np.asarray(d.sample_mean(gamma, 3))
        np.testing.assert_allclose(mean, np.exp([[0.0, -1.0, 1.0]]),
                                   rtol=1e-6)
        # analytic check: -log p for λ=1 (γ=0) is x
        x = jnp.asarray([[0.5, 2.0, 1.0]])
        nlp = float(d.neg_log_prob(x, jnp.zeros((1, 3)))[0])
        assert nlp == pytest.approx(3.5)

    def test_composite_slices_and_sums(self):
        """Composite(gaussian 5, bernoulli 3) == gaussian on x[:, :5] +
        bernoulli on x[:, 5:] with the matching param slices."""
        g = GaussianReconstructionDistribution()
        b = BernoulliReconstructionDistribution()
        comp = CompositeReconstructionDistribution([(5, g), (3, b)])
        assert comp.total_params(8) == 5 * 2 + 3
        r = np.random.default_rng(3)
        x = jnp.asarray(r.random((6, 8)))
        params = jnp.asarray(r.standard_normal((6, 13)))
        got = np.asarray(comp.neg_log_prob(x, params))
        want = (np.asarray(g.neg_log_prob(x[:, :5], params[:, :10]))
                + np.asarray(b.neg_log_prob(x[:, 5:], params[:, 10:])))
        np.testing.assert_allclose(got, want, rtol=1e-9)
        mean = np.asarray(comp.sample_mean(params, 8))
        assert mean.shape == (6, 8)
        with pytest.raises(ValueError):
            comp.total_params(9)   # components cover 8 features

    @pytest.mark.slow
    def test_composite_elbo_gradcheck_and_serde(self):
        dist = {"type": "composite", "components": [
            [5, {"type": "gaussian", "activation": "identity"}],
            [3, {"type": "bernoulli"}]]}
        r = np.random.default_rng(4)
        x = np.asarray(r.random((10, 8)))
        x[:, 5:] = (x[:, 5:] > 0.5).astype(np.float64)
        vae = _vae(dist)
        _, flat, f = _flat_elbo(vae, jnp.asarray(x))
        _numeric_grad_check(f, flat, n_probe=12)
        # serde round-trip through the dict form
        d2 = _dist_from_dict(vae._dist().to_dict())
        assert isinstance(d2, CompositeReconstructionDistribution)
        assert d2.total_params(8) == 13

    @pytest.mark.slow
    def test_loss_wrapper_trains_plain_autoencoder(self):
        vae = _vae({"type": "loss_wrapper", "loss": "mse",
                    "activation": "sigmoid"})
        r = np.random.default_rng(5)
        x = jnp.asarray(r.random((12, 8)))
        _, flat, f = _flat_elbo(vae, x)
        _numeric_grad_check(f, flat, n_probe=12)
        # distribution-object construction path also accepted + normalized
        vae2 = _vae(LossFunctionWrapper("mse", "sigmoid"))
        assert vae2.reconstruction_distribution["type"] == "loss_wrapper"
        assert isinstance(vae2._dist(), LossFunctionWrapper)
        # not a normalized density: log p(x) is undefined (reference throws)
        params = vae2.init_params(jax.random.PRNGKey(0), jnp.float64)
        with pytest.raises(ValueError):
            vae2.reconstruction_probability(params, x, num_samples=2)
