"""Durable, migratable KV state pins (ISSUE 11 acceptance criteria).

  (a) Artifact layer (host-only, no device): RequestArtifact /
      PrefixCacheArtifact round-trip byte-exactly through the
      manifest+bin directory format, refuse malformed panels, refuse
      foreign format versions, and `require_tag` fails loudly on a
      param-version mismatch. A crash-shaped directory (payload
      without manifest) reads as ABSENT, never half-loaded.
  (b) BlockPool.adopt: restored blocks join the cached/LRU tier with
      full accounting invariants (check() after every operation), are
      matchable, evictable, and tracked for `prefix_restore_hits`.
  (c) PREEMPTION: at full block occupancy an interactive-class request
      takes a batch-class slot's blocks (brownout preempt verb); the
      preempted stream resumes BIT-IDENTICALLY (== an uninterrupted
      solo run), the pool survives churn with zero leaks, the
      memory-gate scan admits a claimant parked BEHIND a blocked
      lower-class request (the head-of-line inversion regression), and
      the NON-preempting path adds zero device dispatches per token
      (counter A/B). Composes with chunked prefill and speculation.
  (d) MIGRATION: a live request exported from server A and imported
      into server B resumes bit-identical to a solo run on B; the
      local future fails RequestMigratedError; cross-params migration
      refuses loudly (KVStateVersionError).
  (e) PERSISTENT PREFIX CACHE: stop() saves, a restarted server
      warm-starts (prefix_restore_hits > 0) with a stream bit-identical
      to a cold server's, and a snapshot saved under params v1 restored
      into v2 refuses the blocks loudly with ZERO silent reuse.
"""
import os
import time

import numpy as np
import pytest

from deeplearning4j_tpu.models.zoo.transformer import TransformerLM
from deeplearning4j_tpu.serving import (BlockPool, BrownoutPolicy,
                                        ContinuousDecodeServer,
                                        KVStateError,
                                        KVStateVersionError, NGramDraft,
                                        PrefixCacheArtifact,
                                        RequestArtifact,
                                        RequestMigratedError, Speculator)
from deeplearning4j_tpu.serving.kvstate import artifact_kind


def _lm(seed=3):
    return TransformerLM(64, d_model=32, n_heads=2, n_layers=2,
                         max_len=64, seed=seed)


def _paged(lm, **kw):
    kw.setdefault("slots", 4)
    kw.setdefault("prompt_buckets", (8, 16))
    kw.setdefault("block_size", 4)
    kw.setdefault("n_blocks", 40)
    return ContinuousDecodeServer(lm, paged=True, **kw)


def _wait_tokens(srv, n, timeout=30.0):
    """Block until the server has emitted >= n tokens total."""
    t0 = time.monotonic()
    while srv.metrics.snapshot().get("tokens_out", 0) < n:
        if time.monotonic() - t0 > timeout:
            raise TimeoutError(f"no {n} tokens after {timeout}s")
        time.sleep(0.003)


def _panels(rows=6, layers=2, h=2, hd=16, seed=0):
    r = np.random.default_rng(seed)
    return [(r.standard_normal((rows, h, hd)).astype(np.float32),
             r.standard_normal((rows, h, hd)).astype(np.float32))
            for _ in range(layers)]


# ---------------------------------------------------------------------------
# (a) artifact layer: host-only serialization pins
# ---------------------------------------------------------------------------
class TestArtifacts:
    def test_request_artifact_round_trip_byte_exact(self, tmp_path):
        art = RequestArtifact([1, 2, 3], [9, 8, 7, 6], 10, "tagA", 4,
                              _panels(rows=6), klass="batch")
        p = art.save(str(tmp_path / "req"))
        assert artifact_kind(p) == "request"
        back = RequestArtifact.load(p)
        assert back.prompt == (1, 2, 3)
        assert back.generated == (9, 8, 7, 6)
        assert back.max_new == 10 and back.tag == "tagA"
        assert back.block_size == 4 and back.klass == "batch"
        assert back.pos == 6 and back.remaining == 6
        assert back.nbytes == art.nbytes
        for (k0, v0), (k1, v1) in zip(art.panels, back.panels):
            np.testing.assert_array_equal(k0, k1)
            np.testing.assert_array_equal(v0, v1)

    def test_prefix_artifact_round_trip_parent_first(self, tmp_path):
        # entries handed in child-first are stored parent-first
        e_child = (tuple(range(8)), _panels(rows=4, seed=1))
        e_parent = (tuple(range(4)), _panels(rows=4, seed=2))
        art = PrefixCacheArtifact("tagB", 4, [e_child, e_parent])
        assert [len(p) for p, _ in art.entries] == [4, 8]
        p = art.save(str(tmp_path / "pc"))
        assert artifact_kind(p) == "prefix_cache"
        back = PrefixCacheArtifact.load(p)
        assert [len(pp) for pp, _ in back.entries] == [4, 8]
        np.testing.assert_array_equal(back.entries[0][1][0][0],
                                      e_parent[1][0][0])

    def test_byte_round_trip_and_disk_wire_layout_shared(self, tmp_path):
        """The ISSUE 14 serialization satellite: `to_bytes()` carries
        the manifest+panels layout as ONE buffer (the serving wire's
        MIGRATE payload), round-trips byte-exactly, and its payload
        section is BYTE-IDENTICAL to the on-disk panels.bin — the two
        serializers share `_serialize_arrays`, so they structurally
        cannot drift."""
        import struct

        from deeplearning4j_tpu.serving.kvstate import artifact_from_bytes
        art = RequestArtifact([1, 2, 3], [9, 8, 7, 6], 10, "tagA", 4,
                              _panels(rows=6), klass="batch",
                              trace={"trace_id": "i0-3", "origin": "i0"})
        buf = art.to_bytes()
        back = RequestArtifact.from_bytes(buf)
        assert back.prompt == art.prompt
        assert back.generated == art.generated
        assert back.max_new == art.max_new and back.tag == art.tag
        assert back.block_size == art.block_size
        assert back.klass == "batch" and back.trace == art.trace
        for (k0, v0), (k1, v1) in zip(art.panels, back.panels):
            np.testing.assert_array_equal(k0, k1)
            np.testing.assert_array_equal(v0, v1)
        # payload section == panels.bin, byte for byte
        p = art.save(str(tmp_path / "req"))
        raw = open(os.path.join(p, "panels.bin"), "rb").read()
        (hlen,) = struct.unpack_from("<I", buf, 0)
        assert buf[4 + hlen:] == raw
        # the kind probe dispatches either artifact kind
        assert isinstance(artifact_from_bytes(buf), RequestArtifact)
        pc = PrefixCacheArtifact("tagB", 4,
                                 [(tuple(range(4)), _panels(rows=4))])
        pc2 = PrefixCacheArtifact.from_bytes(pc.to_bytes())
        assert pc2.entries[0][0] == pc.entries[0][0]
        np.testing.assert_array_equal(pc2.entries[0][1][0][0],
                                      pc.entries[0][1][0][0])
        assert isinstance(artifact_from_bytes(pc.to_bytes()),
                          PrefixCacheArtifact)

    def test_byte_layer_refuses_corruption_loudly(self):
        """Truncation, kind mismatch, and format-version drift on the
        ONE-buffer layer fail with the same loud KVStateError family
        the disk loader uses."""
        from deeplearning4j_tpu.serving.kvstate import artifact_from_bytes
        art = RequestArtifact([1], [2], 4, "t", 4, _panels(rows=1))
        buf = art.to_bytes()
        with pytest.raises(KVStateError):
            RequestArtifact.from_bytes(buf[:3])        # no header
        with pytest.raises(KVStateError):
            artifact_from_bytes(buf[:3])     # same guards on dispatch
        with pytest.raises(KVStateError):
            artifact_from_bytes(buf[:12])    # header cut off
        with pytest.raises(KVStateError, match="request"):
            PrefixCacheArtifact.from_bytes(buf)        # wrong kind
        import json
        import struct
        (hlen,) = struct.unpack_from("<I", buf, 0)
        m = json.loads(buf[4:4 + hlen].decode())
        m["format_version"] = 999
        h = json.dumps(m).encode()
        bad = struct.pack("<I", len(h)) + h + buf[4 + hlen:]
        with pytest.raises(KVStateError, match="format_version"):
            RequestArtifact.from_bytes(bad)

    def test_require_tag_fails_loudly(self):
        art = RequestArtifact([1], [2], 4, "v1-fingerprint", 4,
                              _panels(rows=1))
        art.require_tag("v1-fingerprint")     # no raise
        with pytest.raises(KVStateVersionError, match="v2-fingerprint"):
            art.require_tag("v2-fingerprint")

    def test_malformed_panels_and_wrong_kind_refused(self, tmp_path):
        with pytest.raises(KVStateError):
            RequestArtifact([1], [2], 4, "t", 4,
                            _panels(rows=3))      # rows != pos (1)
        with pytest.raises(KVStateError):
            RequestArtifact([1], [], 4, "t", 4, _panels(rows=0))
        with pytest.raises(KVStateError):
            PrefixCacheArtifact("t", 4, [((1, 2, 3), _panels(rows=4))])
        with pytest.raises(KVStateError, match="uniform"):
            # layer 1 shorter than layer 0: must refuse loudly, never
            # zero-fill at install
            RequestArtifact([1, 2], [3], 4, "t", 4,
                            [_panels(rows=2, seed=1)[0],
                             _panels(rows=1, seed=2)[0]])
        p = RequestArtifact([1], [2], 4, "t", 4,
                            _panels(rows=1)).save(str(tmp_path / "a"))
        with pytest.raises(KVStateError, match="request"):
            PrefixCacheArtifact.load(p)

    def test_crash_shaped_directory_reads_as_absent(self, tmp_path):
        d = tmp_path / "half"
        d.mkdir()
        (d / "panels.bin").write_bytes(b"\x00" * 64)   # no manifest
        assert artifact_kind(str(d)) is None
        with pytest.raises(FileNotFoundError):
            RequestArtifact.load(str(d))

    def test_format_version_refused(self, tmp_path):
        import json
        p = RequestArtifact([1], [2], 4, "t", 4,
                            _panels(rows=1)).save(str(tmp_path / "a"))
        m = json.load(open(os.path.join(p, "manifest.json")))
        m["format_version"] = 999
        json.dump(m, open(os.path.join(p, "manifest.json"), "w"))
        with pytest.raises(KVStateError, match="format_version"):
            RequestArtifact.load(p)


# ---------------------------------------------------------------------------
# (b) BlockPool.adopt: restored blocks, full invariants
# ---------------------------------------------------------------------------
class TestPoolAdopt:
    def test_adopt_indexes_and_lru_evicts(self):
        pool = BlockPool(4, 4)
        b0 = pool.adopt((0, tuple(range(4))))
        b1 = pool.adopt((0, tuple(range(8))))
        assert b0 is not None and b1 is not None
        assert pool.restored == {b0, b1}
        pool.check()
        assert pool.match_prefix(list(range(8)), tag=0)[1] == 8
        assert pool.adopt((0, tuple(range(4)))) is None   # already there
        # a full pool evicts adopted blocks LRU like any cached block
        a = pool.admit(list(range(20, 36)), 16)
        assert a is not None
        pool.check()
        assert pool.restored == set()     # both evicted and unmarked
        pool.release(a)
        pool.check()

    def test_adopted_block_shared_by_admission(self):
        pool = BlockPool(8, 4)
        b0 = pool.adopt((0, tuple(range(1, 5))))
        a = pool.admit(list(range(1, 9)), 10, tag=0)
        assert a.shared_rows == 4 and a.ids[0] == b0
        pool.check()
        pool.release(a)
        pool.check()


# ---------------------------------------------------------------------------
# (c) preemption
# ---------------------------------------------------------------------------
class TestPreemption:
    def _brownout(self):
        return BrownoutPolicy(classes={"batch": (0.9, 1.01)})

    def test_preempt_verb_ranking(self):
        pol = self._brownout()
        assert pol.may_preempt("batch", "interactive")
        assert pol.may_preempt("batch", "default")
        assert not pol.may_preempt("interactive", "batch")
        assert not pol.may_preempt("batch", "batch")
        assert not pol.may_preempt("default", "default")

    def test_preempt_requires_paged_and_brownout(self):
        lm = _lm()
        with pytest.raises(ValueError, match="paged"):
            ContinuousDecodeServer(lm, preempt=True,
                                   brownout=self._brownout())
        with pytest.raises(ValueError, match="brownout"):
            _paged(lm, preempt=True)

    def test_preempted_stream_bit_identical_and_pool_clean(self):
        lm = _lm()
        srv = _paged(lm, slots=2, prompt_buckets=(8,), n_blocks=10,
                     brownout=self._brownout(), preempt=True).start()
        try:
            # batch reserves 8 of 10 blocks; after the second
            # interactive (3 blocks) only preemption can admit it
            bfut = srv.submit([1, 2, 3, 4, 5, 6], 26, klass="batch")
            _wait_tokens(srv, 2)
            i1 = srv.submit([7, 8, 9], 6, klass="interactive")
            i2 = srv.submit([9, 8, 7, 6], 8, klass="interactive")
            r2, r1, rb = i2.result(120), i1.result(120), bfut.result(240)
            snap = srv.metrics.snapshot()
            assert snap["preempted"] >= 1
            assert snap["resumed"] >= 1
            assert snap["spill_bytes"] > 0
        finally:
            srv.stop(timeout=120)
        srv._pool.check()
        assert srv._pool.blocks_in_use == 0
        with _paged(lm, slots=2, prompt_buckets=(8,)) as solo:
            assert rb == solo.generate([1, 2, 3, 4, 5, 6], 26,
                                       timeout=120)
            assert r1 == solo.generate([7, 8, 9], 6, timeout=120)
            assert r2 == solo.generate([9, 8, 7, 6], 8, timeout=120)

    def test_claimant_behind_blocked_batch_still_preempts(self):
        """Head-of-line inversion regression: a second BATCH request
        parks blocked on the memory gate; an interactive request
        arriving behind it must still reach its preemption chance (the
        preempting gate scans past blocked requests instead of walling
        the line)."""
        lm = _lm()
        srv = _paged(lm, slots=3, prompt_buckets=(8,), n_blocks=8,
                     brownout=self._brownout(), preempt=True).start()
        try:
            b1 = srv.submit([1, 2, 3, 4], 28, klass="batch")  # 8 blocks
            _wait_tokens(srv, 2)
            b2 = srv.submit([4, 3, 2, 1], 28, klass="batch")  # blocked
            time.sleep(0.02)
            i1 = srv.submit([5, 6, 7], 5, klass="interactive")
            r1 = i1.result(60)      # would hang without the gate scan
            snap = srv.metrics.snapshot()
            assert snap["preempted"] >= 1
            rb1, rb2 = b1.result(240), b2.result(240)
        finally:
            srv.stop(timeout=120)
        srv._pool.check()
        assert srv._pool.blocks_in_use == 0
        with _paged(lm, slots=2, prompt_buckets=(8,)) as solo:
            assert rb1 == solo.generate([1, 2, 3, 4], 28, timeout=120)
            assert rb2 == solo.generate([4, 3, 2, 1], 28, timeout=120)
            assert r1 == solo.generate([5, 6, 7], 5, timeout=120)

    def test_composes_with_chunked_prefill_and_speculation(self):
        lm = _lm()
        spec = Speculator(NGramDraft(n=3), k=4)
        srv = _paged(lm, slots=2, prompt_buckets=(16,), n_blocks=12,
                     chunked_prefill=4, speculate=spec,
                     brownout=self._brownout(), preempt=True).start()
        try:
            bfut = srv.submit([1, 2, 3, 1, 2, 3, 1, 2], 32,
                              klass="batch")   # 39 rows -> 10 blocks
            _wait_tokens(srv, 2)
            ifut = srv.submit([5, 6, 5, 6, 5], 8, klass="interactive")
            ri, rb = ifut.result(120), bfut.result(240)
            snap = srv.metrics.snapshot()
            assert snap["preempted"] >= 1 and snap["resumed"] >= 1
        finally:
            srv.stop(timeout=120)
        srv._pool.check()
        assert srv._pool.blocks_in_use == 0
        # spec + chunked preempted streams == plain greedy solo
        with _paged(lm, slots=2, prompt_buckets=(16,)) as solo:
            assert rb == solo.generate([1, 2, 3, 1, 2, 3, 1, 2], 32,
                                       timeout=120)
            assert ri == solo.generate([5, 6, 5, 6, 5], 8, timeout=120)

    def test_property_churn_admit_preempt_resume_release(self):
        """Satellite pin: random interleaving of admissions (both
        classes), preemptions (forced by interactive pressure),
        resumes, and releases — the pool's invariants hold at drain
        with ZERO leaked blocks and an empty pool."""
        lm = _lm()
        rng = np.random.default_rng(7)
        srv = _paged(lm, slots=3, prompt_buckets=(8,), n_blocks=14,
                     brownout=self._brownout(), preempt=True).start()
        futs = []
        try:
            for i in range(40):
                if rng.random() < 0.35:
                    p = rng.integers(1, 60, 4).tolist()
                    futs.append(srv.submit(p, int(rng.integers(16, 30)),
                                           klass="batch"))
                else:
                    p = rng.integers(1, 60, int(rng.integers(2, 6)))
                    futs.append(srv.submit(p.tolist(),
                                           int(rng.integers(2, 9)),
                                           klass="interactive"))
                if rng.random() < 0.3:
                    time.sleep(0.004)
            for f in futs:
                f.result(300)
            snap = srv.metrics.snapshot()
        finally:
            srv.stop(timeout=180)
        srv._pool.check()
        assert srv._pool.blocks_in_use == 0
        assert srv._pool.blocks_free == srv._pool.capacity
        assert snap["completed"] == len(futs)

    def test_preempted_request_survives_hot_swap(self):
        """A request preempted BEFORE a hot swap resumes under the
        params it started with (its version is pinned while parked —
        the artifact's rows are only valid there), bit-identical to a
        solo run on the OLD params, while post-swap requests get the
        new params."""
        lm, lm2 = _lm(seed=3), _lm(seed=11)
        srv = _paged(lm, slots=2, prompt_buckets=(8,), n_blocks=10,
                     brownout=self._brownout(), preempt=True).start()
        try:
            b = srv.submit([1, 2, 3, 4, 5, 6], 26, klass="batch")
            _wait_tokens(srv, 2)
            i = srv.submit([9, 8, 7, 6], 8, klass="interactive")
            i.result(120)
            assert srv.metrics.snapshot()["preempted"] >= 1
            srv.swap(lm2)
            post = srv.submit([7, 7, 7], 5)
            rb, rp = b.result(240), post.result(120)
        finally:
            srv.stop(timeout=120)
        srv._pool.check()
        with _paged(lm, slots=2, prompt_buckets=(8,)) as solo_old:
            assert rb == solo_old.generate([1, 2, 3, 4, 5, 6], 26,
                                           timeout=120)
        with _paged(lm2, slots=2, prompt_buckets=(8,)) as solo_new:
            assert rp == solo_new.generate([7, 7, 7], 5, timeout=120)

    def test_non_preempting_path_zero_added_dispatches(self):
        """Dispatch-counter A/B (acceptance pin): with preemption
        ENABLED but never triggered (ample blocks), the dispatch count
        for an identical workload equals the preempt=False server's —
        durable KV state costs zero device dispatches per token until
        a spill actually happens."""
        lm = _lm()
        work = [([1, 2, 3, 4], 6), ([5, 6, 7], 9), ([8, 9], 5)]
        counts = {}
        for name, kw in (("preempt_on",
                          dict(brownout=self._brownout(), preempt=True)),
                         ("preempt_off", {})):
            srv = _paged(lm, slots=4, n_blocks=40, **kw).start()
            try:
                srv.generate([1, 2], 2, timeout=120)    # warm compile
                base = srv.metrics.snapshot()["dispatches"]
                futs = [srv.submit(p, n, klass="interactive")
                        for p, n in work]
                for f in futs:
                    f.result(120)
                snap = srv.metrics.snapshot()
                counts[name] = snap["dispatches"] - base
                assert snap["preempted"] == 0
            finally:
                srv.stop(timeout=120)
            srv._pool.check()
        assert counts["preempt_on"] == counts["preempt_off"]

    def test_preempted_request_deadline_enforced(self):
        """A preempted request's deadline stays enforced: whether it
        expires while PARKED on the resume line (the resume-line sweep)
        or right after resuming (mid-decode eviction), the future fails
        loudly with DeadlineExceededError and every block is back in
        the pool. The interactive claimant reserves the WHOLE pool, so
        the batch request is guaranteed parked for the interactive's
        full runtime — far past its budget on any machine."""
        from deeplearning4j_tpu.serving import DeadlineExceededError
        lm = _lm()
        srv = _paged(lm, slots=2, prompt_buckets=(8,), n_blocks=13,
                     brownout=self._brownout(), preempt=True).start()
        try:
            srv.generate([9, 9], 2, timeout=120)    # compile off clock
            b = srv.submit([1, 2, 3, 4, 5, 6], 26, klass="batch",
                           deadline_ms=60.0)
            _wait_tokens(srv, 2)
            # whole-pool interactive: 4 + 49 - 1 = 52 rows = 13 blocks
            i = srv.submit([7, 8, 9, 1], 49, klass="interactive")
            i.result(120)
            with pytest.raises(DeadlineExceededError):
                b.result(120)
            snap = srv.metrics.snapshot()
            assert snap["preempted"] >= 1
        finally:
            srv.stop(timeout=120)
        srv._pool.check()
        assert srv._pool.blocks_in_use == 0


# ---------------------------------------------------------------------------
# (d) migration
# ---------------------------------------------------------------------------
class TestMigration:
    def test_migrated_stream_bit_identical_to_solo(self):
        lm = _lm()
        a = _paged(lm).start()
        b = _paged(lm).start()
        try:
            with _paged(lm) as solo:
                ref = solo.generate([5, 9, 2, 7, 1, 3], 20, timeout=120)
            fut = a.submit([5, 9, 2, 7, 1, 3], 20)
            _wait_tokens(a, 4)
            art = a.migrate_out(fut)
            assert len(art.generated) >= 1
            with pytest.raises(RequestMigratedError):
                fut.result(10)
            out = b.migrate_in(art).result(120)
            assert out == ref
            assert b.metrics.snapshot()["migrated"] == 1
            assert a.metrics.snapshot()["spill_bytes"] > 0
        finally:
            a.stop(timeout=120)
            b.stop(timeout=120)
        a._pool.check()
        b._pool.check()
        assert a._pool.blocks_in_use == 0
        assert b._pool.blocks_in_use == 0

    def test_migration_composes_with_speculation_and_chunking(self):
        lm = _lm()
        kw = dict(slots=2, prompt_buckets=(16,), chunked_prefill=4,
                  speculate=Speculator(NGramDraft(n=3), k=4))
        a = _paged(lm, **kw).start()
        b = _paged(lm, **kw).start()
        try:
            prompt = [1, 2, 3, 1, 2, 3, 1, 2, 3]
            with _paged(lm, slots=2, prompt_buckets=(16,)) as solo:
                ref = solo.generate(prompt, 24, timeout=120)
            fut = a.submit(prompt, 24)
            _wait_tokens(a, 4)
            art = a.migrate_out(fut)
            out = b.migrate_in(art).result(120)
            assert out == ref
        finally:
            a.stop(timeout=120)
            b.stop(timeout=120)
        a._pool.check()
        b._pool.check()

    def test_cross_params_migration_refused_loudly(self):
        a = _paged(_lm(seed=3)).start()
        b = _paged(_lm(seed=9)).start()
        try:
            fut = a.submit([5, 9, 2, 7], 12)
            _wait_tokens(a, 2)
            art = a.migrate_out(fut)
            with pytest.raises(KVStateVersionError):
                b.migrate_in(art)
            assert b.metrics.snapshot()["migrated"] == 0
        finally:
            a.stop(timeout=120)
            b.stop(timeout=120)
        b._pool.check()
        assert b._pool.blocks_in_use == 0

    def test_unknown_request_export_fails_loudly(self):
        import concurrent.futures as cf
        srv = _paged(_lm()).start()
        try:
            with pytest.raises(KVStateError, match="not found"):
                srv.migrate_out(cf.Future())
        finally:
            srv.stop(timeout=120)

    def test_restore_onto_partial_block_ride_never_corrupts_owner(self):
        """Regression: a restored request whose prefix match rides the
        FIRST PART of a shared partial block must not install its rows
        into that block — rows [shared, pos) would overwrite the cached
        owner's tail (E,F,G,H of a block another prompt still matches).
        The restore materializes the reserved CoW spare FIRST and
        installs the whole block from the artifact. Detector: a later
        full-prefix hit on the owner's prompt must still be
        bit-identical to a cold run."""
        lm = _lm()
        P8 = [1, 2, 3, 4, 5, 6, 7, 8]       # 2 full blocks at bs=4
        P6 = P8[:6]                         # full block + 2-row partial
        with _paged(lm, slots=2, prompt_buckets=(8,)) as solo:
            ref8 = solo.generate(P8, 6, timeout=120)
            ref6 = solo.generate(P6, 24, timeout=120)
        srv = _paged(lm, slots=2, prompt_buckets=(8,)).start()
        try:
            assert srv.generate(P8, 6, timeout=120) == ref8   # indexed
            f2 = srv.submit(P6, 24)         # partial ride + CoW
            # wait on the SLOT STATE, not the shared token counter: a
            # counter threshold can be crossed arbitrarily close to
            # the request's own completion on a slow box, and a
            # completed request is (correctly) no longer exportable —
            # observed as a rare machine-weather flake. Decode-phase
            # occupancy plus a 24-token budget leaves ~20 tokens of
            # runway for the export command to land.
            t0 = time.monotonic()
            while not any(r is not None and r.future is f2
                          and r.pf_next is None
                          for r in srv._slot_req):
                assert time.monotonic() - t0 < 60, "never reached decode"
                time.sleep(0.002)
            art = srv.migrate_out(f2)
            out2 = srv.migrate_in(art).result(120)
            assert out2 == ref6
            # the owner's blocks must be intact: full-prefix re-hit
            assert srv.generate(P8, 6, timeout=120) == ref8
        finally:
            srv.stop(timeout=120)
        srv._pool.check()
        assert srv._pool.blocks_in_use == 0

    def test_artifact_survives_disk_round_trip(self, tmp_path):
        """The migration seam IS the serialization seam: an artifact
        saved to disk and re-loaded imports identically (the
        prefill/decode-disaggregation wire path)."""
        lm = _lm()
        a = _paged(lm).start()
        b = _paged(lm).start()
        try:
            with _paged(lm) as solo:
                ref = solo.generate([3, 1, 4, 1, 5], 16, timeout=120)
            fut = a.submit([3, 1, 4, 1, 5], 16)
            _wait_tokens(a, 3)
            art = a.migrate_out(fut)
            p = art.save(str(tmp_path / "wire"))
            out = b.migrate_in(RequestArtifact.load(p)).result(120)
            assert out == ref
        finally:
            a.stop(timeout=120)
            b.stop(timeout=120)
        a._pool.check()
        b._pool.check()


# ---------------------------------------------------------------------------
# (e) persistent prefix cache
# ---------------------------------------------------------------------------
class TestPersistentPrefixCache:
    SYS = list(range(1, 13))    # 3 full blocks at block_size 4

    def test_restart_warm_start_bit_identical(self, tmp_path):
        lm = _lm()
        pdir = str(tmp_path / "prefix")
        s1 = _paged(lm, slots=2, prompt_buckets=(16,),
                    prefix_cache_dir=pdir).start()
        cold = s1.generate(self.SYS + [20, 21], 8, timeout=120)
        s1.stop(timeout=120)
        assert artifact_kind(pdir) == "prefix_cache"
        s2 = _paged(lm, slots=2, prompt_buckets=(16,),
                    prefix_cache_dir=pdir).start()
        try:
            s2._pool.check()
            warm = s2.generate(self.SYS + [20, 21], 8, timeout=120)
            snap = s2.metrics.snapshot()
        finally:
            s2.stop(timeout=120)
        assert warm == cold
        assert snap["prefix_restore_hits"] > 0
        s2._pool.check()
        assert s2._pool.blocks_in_use == 0

    def test_version_mismatch_refused_loudly_zero_reuse(self, tmp_path):
        """Satellite pin: a snapshot saved under params v1 restored
        into a server running v2 refuses the blocks loudly — the
        constructor raises, and a direct restore attempt adopts ZERO
        blocks (the in-process hot-swap invalidation rule, across
        restarts)."""
        pdir = str(tmp_path / "prefix")
        s1 = _paged(_lm(seed=3), slots=2, prompt_buckets=(16,),
                    prefix_cache_dir=pdir).start()
        s1.generate(self.SYS + [20, 21], 8, timeout=120)
        s1.stop(timeout=120)
        with pytest.raises(KVStateVersionError):
            _paged(_lm(seed=9), slots=2, prompt_buckets=(16,),
                   prefix_cache_dir=pdir)
        # direct restore into a v2 server without the dir wiring: same
        # loud refusal, zero adopted blocks
        s2 = _paged(_lm(seed=9), slots=2, prompt_buckets=(16,))
        with pytest.raises(KVStateVersionError):
            s2.restore_prefix_cache(pdir)
        assert s2._pool.restored == set()
        assert s2._pool.blocks_free == s2._pool.capacity
        s2._pool.check()

    def test_small_pool_restores_prefix_of_snapshot(self, tmp_path):
        """A pool smaller than the snapshot adopts what fits (parent-
        first, so what it adopts is matchable) and never fails the
        server."""
        lm = _lm()
        pdir = str(tmp_path / "prefix")
        s1 = _paged(lm, slots=2, prompt_buckets=(16,), n_blocks=40,
                    prefix_cache_dir=pdir).start()
        s1.generate(self.SYS + [20, 21], 8, timeout=120)
        s1.generate(list(range(30, 42)) + [1], 8, timeout=120)
        s1.stop(timeout=120)
        art = PrefixCacheArtifact.load(pdir)
        assert len(art.entries) >= 4
        s2 = _paged(lm, slots=1, prompt_buckets=(16,), n_blocks=3,
                    max_blocks_per_slot=16)
        n = s2.restore_prefix_cache(pdir)
        assert 0 < n <= 3
        s2._pool.check()

    def test_stale_snapshot_removed_when_nothing_saveable(self, tmp_path):
        """Regression: a server that hot-swaps and then stops with no
        prefix entries under the NEWEST version must not leave the
        previous version's snapshot behind — a stale artifact would
        strand the next constructor on a version refusal the server's
        own lifecycle caused. The save removes it; the next start is a
        clean cold start."""
        lm, lm2 = _lm(seed=3), _lm(seed=11)
        pdir = str(tmp_path / "prefix")
        s1 = _paged(lm, slots=2, prompt_buckets=(16,),
                    prefix_cache_dir=pdir).start()
        s1.generate(self.SYS + [20, 21], 8, timeout=120)
        s1.stop(timeout=120)
        assert artifact_kind(pdir) == "prefix_cache"
        s2 = _paged(lm, slots=2, prompt_buckets=(16,),
                    prefix_cache_dir=pdir).start()     # warm restore OK
        s2.swap(lm2)            # newest version now has no entries
        s2.stop(timeout=120)    # save finds nothing: stale dir removed
        assert artifact_kind(pdir) is None
        # the new-params server boots cold instead of raising
        s3 = _paged(lm2, slots=2, prompt_buckets=(16,),
                    prefix_cache_dir=pdir).start()
        try:
            s3.generate(self.SYS + [20, 21], 8, timeout=120)
        finally:
            s3.stop(timeout=120)
        assert artifact_kind(pdir) == "prefix_cache"

    def test_explicit_foreign_path_never_deleted(self, tmp_path):
        """save_prefix_cache with nothing saveable removes only the
        server's OWN stale prefix_cache_dir; an explicitly passed path
        may be another server's valid snapshot and must survive."""
        lm = _lm()
        pdir = str(tmp_path / "prefix")
        s1 = _paged(lm, slots=2, prompt_buckets=(16,),
                    prefix_cache_dir=pdir).start()
        s1.generate(self.SYS + [20, 21], 8, timeout=120)
        s1.stop(timeout=120)
        assert artifact_kind(pdir) == "prefix_cache"
        s2 = _paged(lm)             # never started: nothing saveable
        assert s2.save_prefix_cache(pdir) is None
        assert artifact_kind(pdir) == "prefix_cache"    # intact

    def test_save_without_dir_and_on_running_server_refused(self):
        srv = _paged(_lm()).start()
        try:
            with pytest.raises(KVStateError, match="stopped"):
                srv.save_prefix_cache("/tmp/nope")
        finally:
            srv.stop(timeout=120)
        with pytest.raises(ValueError, match="path"):
            srv.save_prefix_cache()

    def test_prefix_dir_requires_paged_prefix_cache(self, tmp_path):
        with pytest.raises(ValueError, match="prefix_cache_dir"):
            ContinuousDecodeServer(_lm(),
                                   prefix_cache_dir=str(tmp_path / "x"))


# ---------------------------------------------------------------------------
# snapshot keys
# ---------------------------------------------------------------------------
class TestDurableMetricsKeys:
    def test_keys_always_present_and_zero_when_idle(self):
        from deeplearning4j_tpu.serving import ServingMetrics
        snap = ServingMetrics().snapshot()
        for key in ("preempted", "resumed", "migrated", "migrated_out",
                    "spill_bytes", "prefix_restore_hits"):
            assert snap[key] == 0
