"""Durable control plane pins (ISSUE 16 acceptance criteria).

  (a) Recovery + re-adoption: kill a journaled manager mid-fleet
      (journal handle gone, replica servers untouched) and
      `FleetManager.recover` rebuilds the successor from the journal —
      every live listed replica re-adopted over an identity-verified
      HELLO (`replicas_adopted` counted), streams across the restart
      bit-identical to the pre-kill references, federated counters
      monotone.
  (b) Epoch fencing: the successor's epoch announcement fences the
      predecessor out — its next control-plane op is refused with a
      TYPED `StaleEpochError` (`fenced_ops` counted on the replica AND
      the stale client) while the predecessor's in-flight data-plane
      work still resolves: zero requests lost to the fence.
  (c) Reconcile rules: an absent/empty journal is an empty fleet
      (backfill respawns, nothing adopted); a replica journaled
      mid-drain is never re-adopted; a half-finished canary rolls back
      deterministically (`canary_rollbacks` counted); a recycled port
      answering with the WRONG identity is refused
      (`adopt_identity_mismatch`) with local-only teardown — the
      unrelated process is never sent a control frame.
  (d) Zero-added-dispatch A/B: journaling + epoch plumbing on the
      wire fleet dispatches exactly what the journal-less PR 14 fleet
      dispatches, streams bit-identical (host-side durability must
      never buy a token with a device dispatch).
  (e) Chaos smoke: the seeded `load_sweep --chaos` arm (replica
      PROCESSES, one manager kill+recover inside the schedule) —
      tier1.yml uploads its report as the CI artifact.
"""
import importlib
import os
import sys
import tempfile

import pytest

from deeplearning4j_tpu.models.zoo.transformer import TransformerLM
from deeplearning4j_tpu.serving import (ContinuousDecodeServer,
                                        FleetJournal, FleetManager,
                                        RemoteReplica, ReplicaServer,
                                        ServingMetrics, StaleEpochError,
                                        replay_journal)


def _lm(seed=3):
    return TransformerLM(64, d_model=16, n_heads=2, n_layers=1,
                         max_len=64, seed=seed)


class _JournaledFleet:
    """N in-thread ReplicaServers behind RemoteReplicas with the
    manager JOURNALING — the test_wire `_WireFleet` idiom plus the
    durable control plane. `abandon()` simulates the manager process
    dying (journal handle vanishes with it; replica servers and the
    zombie's sockets stay up); `recover()` builds the successor from
    the journal through the same factory."""

    def __init__(self, lm, jpath, **mgr_kw):
        self.wrappers = {}
        self.stales = []
        self._lm = lm
        self.jpath = jpath
        self.mgr = FleetManager(self._factory, journal=jpath, **mgr_kw)

    def _factory(self, name):
        srv = ContinuousDecodeServer(
            self._lm, slots=2, prompt_buckets=(8, 16),
            metrics=ServingMetrics(name=name), instance=name)
        rs = ReplicaServer(srv)
        self.wrappers[name] = rs
        return RemoteReplica("127.0.0.1", rs.port, name=name,
                             heartbeat_interval=0.05)

    def start(self):
        self.mgr.start()
        for n in self.mgr.replicas:     # compile off the clock
            self.mgr.replica(n).generate([1, 2, 3], 2, timeout=120)
        return self.mgr

    def abandon(self):
        """The manager 'dies': drop its journal handle the way process
        death would, keep the object as the zombie predecessor."""
        stale = self.mgr
        j, stale._journal = stale._journal, None
        if j is not None:
            j.close()
        self.stales.append(stale)
        return stale

    def recover(self, **kw):
        self.mgr = FleetManager.recover(self._factory, self.jpath, **kw)
        return self.mgr

    def close(self):
        from deeplearning4j_tpu.serving import ServerClosedError
        try:
            self.mgr.stop(timeout=60)
        finally:
            for stale in self.stales:
                for n in list(stale.replicas):
                    try:
                        stale.replica(n)._shutdown_local(
                            ServerClosedError("test teardown"),
                            dead=False)
                    except Exception:   # noqa: BLE001
                        pass
                stale._running = False
            for rs in self.wrappers.values():
                rs.close(stop_server=False)


@pytest.fixture
def jpath(tmp_path):
    return str(tmp_path / "fleet.journal")


# ---------------------------------------------------------------------------
# (a) recovery + re-adoption, (b) epoch fencing
# ---------------------------------------------------------------------------
class TestRecovery:
    def test_kill_recover_readopts_and_fences(self, jpath):
        lm = _lm()
        prompts = [[1 + i, 2, 3] for i in range(4)]
        fleet = _JournaledFleet(lm, jpath, n_replicas=2,
                                policy="round_robin")
        try:
            mgr = fleet.start()
            assert mgr.epoch == 1
            refs = [list(mgr.generate(p, 6, timeout=120))
                    for p in prompts]
            fv = mgr.fleet_view()
            pre_done = {n: fv.flat(n).get("completed") or 0
                        for n in fv.instances}
            listed = set(mgr.replicas)
            stale = fleet.abandon()
            # the predecessor still has DATA-PLANE work in flight when
            # the successor takes over — the fence must not touch it
            inflight = [stale.submit(p, 6, deadline_ms=600_000)
                        for p in prompts]
            mgr2 = fleet.recover(n_replicas=2, policy="round_robin")
            assert mgr2.epoch == 2
            assert set(mgr2.replicas) == listed     # re-adopted, not
            assert mgr2.metrics.count_value(        # respawned
                "replicas_adopted") == 2
            assert mgr2.fleet_snapshot()["fleet_replica_spawned"] == 0
            # streams across the restart: bit-identical to pre-kill
            assert [list(mgr2.generate(p, 6, timeout=120))
                    for p in prompts] == refs
            # federated counters monotone across the manager restart
            fv2 = mgr2.fleet_view()
            for n in fv2.instances:
                assert (fv2.flat(n).get("completed") or 0) \
                    >= pre_done.get(n, 0)
            # FENCING: the zombie's next control op gets the typed
            # refusal, counted replica-side AND on the stale client
            victim = next(iter(listed))
            with pytest.raises(StaleEpochError):
                stale.replica(victim).drain(timeout=10.0)
            assert mgr2.fleet_snapshot()["fleet_fenced_ops"] >= 1
            assert stale.metrics.count_value("fenced_ops") >= 1
            # zero requests lost: the zombie's in-flight futures all
            # resolved bit-identically through the fence
            assert [list(f.result(120)) for f in inflight] == refs
        finally:
            fleet.close()

    def test_empty_journal_recovers_empty_then_backfills(self, jpath):
        lm = _lm()
        fleet = _JournaledFleet(lm, jpath, n_replicas=2)
        try:
            # never started: the journal on disk holds only this
            # manager's epoch record — no roster to adopt
            mgr = fleet.recover(n_replicas=2)
            assert mgr.metrics.count_value("replicas_adopted") == 0
            assert mgr.n_alive() == 2           # backfilled, fresh
        finally:
            fleet.close()

    def test_empty_journal_no_backfill_is_empty_fleet(self, tmp_path):
        mgr = FleetManager.recover(
            lambda name: (_ for _ in ()).throw(AssertionError(
                "no spawn may happen with backfill=False")),
            str(tmp_path / "absent.journal"), backfill=False,
            n_replicas=2)
        assert mgr.n_alive() == 0
        assert mgr.metrics.count_value("replicas_adopted") == 0
        mgr._running = False

    def test_recovered_manager_still_gets_control_thread(self, tmp_path):
        # recover() marks the manager running; the public
        # start(control_interval_s=...) must still attach the control
        # thread — and never a second one
        mgr = FleetManager.recover(
            lambda name: (_ for _ in ()).throw(AssertionError(
                "no spawn may happen with backfill=False")),
            str(tmp_path / "absent.journal"), backfill=False,
            n_replicas=2)
        try:
            assert mgr._ctl_thread is None
            mgr.start(control_interval_s=30.0)
            t = mgr._ctl_thread
            assert t is not None and t.is_alive()
            mgr.start(control_interval_s=30.0)
            assert mgr._ctl_thread is t
        finally:
            mgr.stop(timeout=10)

    def test_recover_accepts_control_interval(self, tmp_path):
        mgr = FleetManager.recover(
            lambda name: (_ for _ in ()).throw(AssertionError(
                "no spawn may happen with backfill=False")),
            str(tmp_path / "absent.journal"), backfill=False,
            n_replicas=2, control_interval_s=30.0)
        try:
            assert mgr._ctl_thread is not None
            assert mgr._ctl_thread.is_alive()
        finally:
            mgr.stop(timeout=10)


# ---------------------------------------------------------------------------
# (c) reconcile rules
# ---------------------------------------------------------------------------
class TestReconcile:
    def test_mid_drain_replica_never_readopted(self, jpath):
        lm = _lm()
        fleet = _JournaledFleet(lm, jpath, n_replicas=2)
        try:
            mgr = fleet.start()
            doomed = mgr.replicas[0]
            # the predecessor journaled drain INTENT and died before
            # the completion record — resurrection would route new
            # work at a replica mid-goodbye
            mgr._journal_append("drain_begin", name=doomed)
            fleet.abandon()
            mgr2 = fleet.recover(n_replicas=2)
            assert doomed not in mgr2.replicas
            assert mgr2.n_alive() == 2          # backfilled past it
        finally:
            fleet.close()

    def test_half_finished_canary_rolls_back(self, jpath):
        lm = _lm()
        fleet = _JournaledFleet(lm, jpath, n_replicas=2)
        try:
            mgr = fleet.start()
            canary = mgr.replicas[0]
            mgr._journal_append("canary_begin", name=canary, version=1)
            fleet.abandon()
            mgr2 = fleet.recover(n_replicas=2)
            # the canary alone held unvetted params: deterministic
            # rollback by crash, backfill rebuilt on factory params
            assert mgr2.metrics.count_value("canary_rollbacks") == 1
            assert canary not in mgr2.replicas
            assert mgr2.n_alive() == 2
        finally:
            fleet.close()

    def test_recycled_port_identity_mismatch_refused(self, tmp_path):
        lm = _lm()
        jp = str(tmp_path / "fleet.journal")
        # an UNRELATED server now owns the journaled port: its HELLO
        # claims a different instance (and pid/start-time would also
        # miss) — adoption must refuse without sending it a control
        # frame
        srv = ContinuousDecodeServer(
            lm, slots=2, prompt_buckets=(8, 16),
            metrics=ServingMetrics(name="imposter"),
            instance="imposter")
        rs = ReplicaServer(srv)
        try:
            with FleetJournal(jp) as j:
                j.append("epoch", epoch=1)
                j.append("spawn", name="i0", seq=0, host="127.0.0.1",
                         port=rs.port, pid=999999, start_time=1.0)
            mgr = FleetManager.recover(
                lambda name: (_ for _ in ()).throw(AssertionError(
                    "mismatch must refuse, not respawn here")),
                jp, backfill=False, n_replicas=1)
            assert mgr.metrics.count_value(
                "adopt_identity_mismatch") == 1
            assert mgr.n_alive() == 0
            mgr._running = False
            # local-only teardown: the imposter was NEVER stopped — it
            # still serves its own clients
            rr = RemoteReplica("127.0.0.1", rs.port, name="imposter",
                               heartbeat_interval=0.05)
            try:
                assert list(rr.generate([1, 2, 3], 4, timeout=120)) \
                    == list(lm.generate([1, 2, 3], 4))
            finally:
                rr.stop(drain=True)
        finally:
            rs.close(stop_server=False)

    def test_clean_exit_identity_file_skips_dial(self, tmp_path):
        jp = str(tmp_path / "fleet.journal")
        with FleetJournal(jp) as j:
            j.append("epoch", epoch=1)
            # journaled at a port nobody listens on; identity_dir has
            # no i0.json -> clean exit, skipped WITHOUT a dial (a dial
            # would raise/yield replica_dead, not replica_drained)
            j.append("spawn", name="i0", seq=0, host="127.0.0.1",
                     port=1, pid=1, start_time=1.0)
        mgr = FleetManager.recover(
            lambda name: (_ for _ in ()).throw(AssertionError(
                "backfill off: no spawn")),
            jp, backfill=False, identity_dir=str(tmp_path),
            n_replicas=1)
        assert mgr.n_alive() == 0
        assert mgr.metrics.count_value("replicas_adopted") == 0
        recs = [r for r in replay_journal(jp)
                if r.get("name") == "i0" and r["kind"] != "spawn"]
        assert [r["kind"] for r in recs] == ["replica_drained"]
        mgr._running = False


# ---------------------------------------------------------------------------
# (d) zero-added-dispatch A/B
# ---------------------------------------------------------------------------
class TestDispatchAB:
    def test_journal_and_epoch_add_zero_dispatches(self, jpath):
        """THE no-fault A/B: the SAME sequential workload through the
        journaled+epoch-fenced wire fleet and the journal-less PR 14
        wire fleet — per-replica (dispatches, tokens_out) IDENTICAL,
        streams bit-identical. Journal appends and epoch HELLOs are
        host-side; they must never buy a token with a dispatch."""
        lm = _lm()
        prompts = [[1 + i, 2, 3] for i in range(6)]
        counts, outs = {}, {}
        fleet = _JournaledFleet(lm, jpath, n_replicas=2,
                                policy="round_robin")
        try:
            mgr = fleet.start()
            assert mgr.epoch == 1       # epoch plumbing really on
            outs["journaled"] = [mgr.generate(p, 5, timeout=120)
                                 for p in prompts]
            counts["journaled"] = [
                (mgr.replica(n).metrics.count_value("dispatches"),
                 mgr.replica(n).metrics.count_value("tokens_out"))
                for n in mgr.replicas]
        finally:
            fleet.close()

        wrappers = {}

        def plain_factory(name):
            srv = ContinuousDecodeServer(
                lm, slots=2, prompt_buckets=(8, 16),
                metrics=ServingMetrics(name=name), instance=name)
            rs = ReplicaServer(srv)
            wrappers[name] = rs
            return RemoteReplica("127.0.0.1", rs.port, name=name,
                                 heartbeat_interval=0.05)
        try:
            with FleetManager(plain_factory, n_replicas=2,
                              policy="round_robin") as mgr:
                for n in mgr.replicas:
                    mgr.replica(n).generate([1, 2, 3], 2, timeout=120)
                assert mgr.epoch == 0   # no journal -> no epoch
                outs["plain"] = [mgr.generate(p, 5, timeout=120)
                                 for p in prompts]
                counts["plain"] = [
                    (mgr.replica(n).metrics.count_value("dispatches"),
                     mgr.replica(n).metrics.count_value("tokens_out"))
                    for n in mgr.replicas]
        finally:
            for rs in wrappers.values():
                rs.close(stop_server=False)
        assert counts["journaled"] == counts["plain"]
        assert [list(r) for r in outs["journaled"]] == \
            [list(r) for r in outs["plain"]]


# ---------------------------------------------------------------------------
# (e) chaos smoke — the CI artifact producer
# ---------------------------------------------------------------------------
class TestSmokeChaos:
    def test_smoke_chaos_sweep(self):
        """`load_sweep --chaos --fleet-procs 2` at smoke scale: one
        seeded schedule (3 events, one guaranteed manager kill) over 2
        replica PROCESSES. Pins: recovery re-adopted both replicas,
        the stale manager was epoch-fenced with the typed refusal,
        admitted == completed + failed, every future resolved, every
        disturbed replay bit-identical. tier1.yml uploads the report
        (`load_sweep_smoke_chaos.json`/`.txt`)."""
        out = os.path.join(
            os.environ.get("SMOKE_REPORT_DIR") or tempfile.gettempdir(),
            "load_sweep_smoke_chaos")
        tools = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "tools")
        if tools not in sys.path:
            sys.path.insert(0, tools)
        mod = importlib.import_module("load_sweep")
        results = mod.run_sweep(
            server="decode", rates=(40.0,), n_req=12, slo_ms=400.0,
            seed=0, trace=False, report_path=out, fleet_procs=2,
            chaos=True, chaos_events=3)
        body = next(r for r in results if r["server"] == "fleet_chaos")
        rec = body["recovery"]
        assert rec["replicas_adopted"] == 2
        assert rec["fenced_op_refused"] is True
        assert rec["fenced_ops_counted"] >= 1
        assert rec["counters_monotone_across_restart"] is True
        assert body["accounting"]["balanced"] is True
        for entry in body["chaos"]["log"]:
            assert entry["all_resolved"] is True
            assert entry["bit_identical"] is True
        # the digest pins the schedule: seed 0 must replay THIS run
        from deeplearning4j_tpu.serving import build_chaos_schedule
        again = build_chaos_schedule(
            duration_s=3.0, n_events=3, seed=0,
            actions=("sever_submit", "sever_stream", "sever_heartbeat",
                     "replica_crash", "manager_kill"))
        assert body["chaos"]["digest"] == again.digest()
        assert os.path.exists(out + ".json")
        assert os.path.exists(out + ".txt")

    def test_smoke_cascade_sweep(self):
        """`load_sweep --chaos --cascade --fleet-procs 3` at smoke
        scale: the blast-radius-containment arm (ISSUE 17). One seeded
        schedule whose required actions (poison + spawn_fail +
        manager_kill) compose with the wire severs. Pins: the poison
        pill is convicted after EXACTLY two replica deaths and its
        re-submission sheds at the door; the spawn_fail window costs
        at most K spawn attempts (breaker opens, fleet serves
        degraded, then heals to full strength); the recovered manager
        inherits the quarantine; accounting balances; every disturbed
        replay is bit-identical. tier1.yml uploads the report
        (`load_sweep_smoke_cascade.json`/`.txt`)."""
        out = os.path.join(
            os.environ.get("SMOKE_REPORT_DIR") or tempfile.gettempdir(),
            "load_sweep_smoke_cascade")
        tools = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "tools")
        if tools not in sys.path:
            sys.path.insert(0, tools)
        mod = importlib.import_module("load_sweep")
        results = mod.run_sweep(
            server="decode", rates=(40.0,), n_req=12, slo_ms=400.0,
            seed=0, trace=False, report_path=out, fleet_procs=3,
            chaos=True, cascade=True, chaos_events=4)
        body = next(r for r in results if r["server"] == "fleet_chaos")
        # poison: convicted on its second death, never a third
        poison = next(e["poison"] for e in body["chaos"]["log"]
                      if e.get("action") == "poison")
        assert poison["verdict"] == "poison_pill"
        assert poison["deaths"] <= 2
        assert poison["resubmission_shed"] is True
        assert poison["quarantined_counter"] >= 2
        # spawn_fail window: bounded attempts, breaker opened, healed
        breaker = next(e["breaker"] for e in body["chaos"]["log"]
                       if e.get("action") == "spawn_fail")
        assert breaker["state_after_window"] == "open"
        assert breaker["bounded"] is True
        assert breaker["recovered_state"] == "closed"
        assert breaker["n_alive_after"] == 3
        assert breaker["degraded_mode_ticks"] >= 1
        # the successor inherits the journaled quarantine
        rec = body["recovery"]
        if rec.get("quarantine_inherited") is not None:
            assert rec["quarantine_inherited"] is True
        assert body["accounting"]["balanced"] is True
        for entry in body["chaos"]["log"]:
            assert entry["all_resolved"] is True
            assert entry["bit_identical"] is True
        # the retry budget held: a bounded-chaos run never drains it
        assert body["cascade"]["retry_budget"]["tokens_remaining"] > 0
        # compaction fired mid-run (the threshold is set to guarantee
        # it) and the rotated journal replays from its snapshot record
        assert body["cascade"]["journal_compacted"] is True
        # the digest pins the cascade schedule: builder args alone
        # (require= rewrite included) replay THIS timeline
        from deeplearning4j_tpu.serving import build_chaos_schedule
        again = build_chaos_schedule(
            duration_s=4.0, n_events=4, seed=0,
            actions=("sever_submit", "sever_stream", "poison",
                     "spawn_fail", "manager_kill"),
            require=("poison", "spawn_fail", "manager_kill"))
        assert body["chaos"]["digest"] == again.digest()
        # seed 0 ordering: the poison fires BEFORE the manager kill,
        # so the quarantine-inheritance pin above was exercised
        acts = again.actions()
        assert acts.index("poison") < acts.index("manager_kill")
        assert os.path.exists(out + ".json")
        assert os.path.exists(out + ".txt")
