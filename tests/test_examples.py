"""Examples bitrot guard: every example must at least byte-compile; the
fast ones run end-to-end as subprocesses (the full set is exercised
manually — each prints a success line; see examples/README.md)."""
import os
import py_compile
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
EXAMPLES = os.path.join(REPO, "examples")


def _example_files():
    return sorted(f for f in os.listdir(EXAMPLES)
                  if f.endswith(".py") and not f.startswith("_"))


def test_all_examples_compile():
    files = _example_files()
    assert len(files) >= 10
    for f in files:
        py_compile.compile(os.path.join(EXAMPLES, f), doraise=True)


@pytest.mark.slow
@pytest.mark.parametrize("name", ["ring_attention_long_context.py",
                                  "moe_expert_parallel.py",
                                  "cjk_dictionary_tokenization.py",
                                  "ps_cross_process.py"])
def test_fast_examples_run(name):
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    # 420s: must exceed the largest internal budget any example carries
    # (ps_cross_process.py: 240s worker + 60s server wait + scoring)
    p = subprocess.run([sys.executable, name], cwd=EXAMPLES, env=env,
                       capture_output=True, text=True, timeout=420)
    assert p.returncode == 0, p.stderr[-800:]
    assert "True" in p.stdout or "==" in p.stdout
