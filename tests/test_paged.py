"""Paged KV-cache subsystem pins (ISSUE 8 acceptance criteria).

  (a) BlockPool invariants: free-list/refcount accounting survives
      property-style churn with zero leaks; prefix matching, LRU
      eviction of cached blocks, and the CoW spare reservation behave.
  (b) Determinism: the paged decode server's streams are BIT-IDENTICAL
      to solo decode, to the fixed-slot server, across a mid-stream
      join, and with prefix sharing on vs off (shared leading blocks +
      copy-on-write change WHERE rows live, never what any stream
      reads).
  (c) Scheduling: admission gates on free blocks (blocked_on_memory,
      deadline enforcement while blocked, out-of-blocks shed at
      submit), hot swap drains dual-version over paged slots, and the
      dispatch-counter A/B pins that paging adds ZERO device dispatches
      per token.
  (d) Paged SPECULATION (ISSUE 10): the K-wide verify program
      re-addressed through the block table (`make_paged_verify_fn`) —
      paged speculative streams bit-identical to plain greedy AND to
      fixed-layout speculation (solo, join==solo, across a hot swap,
      K in {2,4,8}, both draft sources); CoW-shared prefix + divergent
      K-wide verify write yields exactly one copy with both streams
      intact; verify-round block accounting leaves the pool empty
      after churn; mid-round deadline eviction releases blocks; and
      the dispatch-counter A/B pins that the PAGED verify costs the
      identical dispatch count as the fixed verify (paging adds zero,
      under speculation too).
"""
import time

import numpy as np
import pytest

from deeplearning4j_tpu.models.zoo.transformer import TransformerLM
from deeplearning4j_tpu.serving import (BlockPool, ContinuousDecodeServer,
                                        DeadlineExceededError, ModelDraft,
                                        NGramDraft, ServerOverloadedError,
                                        Speculator)


def _lm(seed=3):
    return TransformerLM(64, d_model=32, n_heads=2, n_layers=2,
                         max_len=64, seed=seed)


def _paged(lm, **kw):
    kw.setdefault("slots", 4)
    kw.setdefault("prompt_buckets", (8, 16))
    kw.setdefault("block_size", 4)
    kw.setdefault("n_blocks", 40)
    return ContinuousDecodeServer(lm, paged=True, **kw)


# ---------------------------------------------------------------------------
# (a) BlockPool: host-side invariants, no device needed
# ---------------------------------------------------------------------------
class TestBlockPool:
    def test_churn_no_leak_property(self):
        """Random admit/release churn (shared prefixes included): the
        accounting invariants hold after EVERY operation and the pool
        returns to fully-reusable when the last request releases."""
        rng = np.random.default_rng(0)
        pool = BlockPool(24, 4)
        live = []
        prefixes = [tuple(rng.integers(1, 9, 8)),
                    tuple(rng.integers(1, 9, 8))]
        for _ in range(300):
            if live and (rng.random() < 0.45 or len(live) >= 8):
                alloc = live.pop(int(rng.integers(0, len(live))))
                if alloc.cow is not None and rng.random() < 0.5:
                    pool.cow(alloc)     # sometimes materialize first
                pool.release(alloc)
            else:
                base = list(prefixes[int(rng.integers(0, 2))])
                prompt = base[:int(rng.integers(1, 9))] + \
                    list(rng.integers(1, 9, int(rng.integers(0, 4))))
                rows = len(prompt) + int(rng.integers(1, 12))
                alloc = pool.admit(prompt, rows)
                if alloc is not None:
                    pool.commit(alloc)   # as the scheduler does on
                    live.append(alloc)   # prefill success
            pool.check()
        for alloc in live:
            pool.release(alloc)
        pool.check()
        assert pool.blocks_in_use == 0
        assert pool.blocks_free == pool.capacity

    def test_prefix_reuse_and_lru_eviction(self):
        pool = BlockPool(6, 4)
        p = list(range(1, 9))                   # 2 full blocks
        a = pool.admit(p, len(p))
        assert a is not None and a.shared_rows == 0
        pool.commit(a)                           # "prefill succeeded"
        pool.release(a)                          # retire to prefix cache
        assert pool.blocks_in_use == 0 and pool.blocks_free == 6
        b = pool.admit(p, len(p) + 3)            # same prompt: full hit
        assert b.shared_rows == 8 and b.n_shared == 2
        # demand exceeding the free list evicts cached blocks LRU
        pool.release(b)
        c = pool.admit(list(range(20, 44)), 24)  # needs all 6 blocks
        assert c is not None
        pool.release(c)
        # the old prefix was evicted to make room: no hit anymore
        d = pool.admit(p, len(p))
        assert d.shared_rows == 0
        pool.release(d)
        pool.check()

    def test_prefix_index_namespaced_by_tag(self):
        """Blocks indexed under one tag never match another tag's
        lookups: the server tags by param version, so k/v rows computed
        under swapped-out weights are structurally unreachable."""
        pool = BlockPool(8, 4)
        p = list(range(1, 9))
        a = pool.admit(p, len(p), tag=0)
        pool.commit(a)
        pool.release(a)
        assert pool.match_prefix(p, tag=0)[1] == 8
        assert pool.match_prefix(p, tag=1) == ([], 0, None)
        b = pool.admit(p, len(p) + 3, tag=1)     # no cross-tag hit
        assert b.shared_rows == 0
        pool.commit(b)
        pool.release(b)
        # both versions' blocks now cached, each under its own tag
        assert pool.match_prefix(p, tag=0)[1] == 8
        assert pool.match_prefix(p, tag=1)[1] == 8
        pool.check()

    def test_partial_match_reserves_cow_spare(self):
        pool = BlockPool(12, 4)
        long = list(range(1, 9))                # blocks [1..4][5..8]
        a = pool.admit(long, len(long) + 4)
        pool.commit(a)
        short = long[:6]                        # rides block 2 partially
        b = pool.admit(short, len(short) + 4, will_append=True)
        assert b.shared_rows == 6 and b.cow is not None
        idx, spare = b.cow
        assert b.ids[idx] == a.ids[1]           # shared physical block
        src, dst = pool.cow(b)
        assert (src, dst) == (a.ids[1], spare) and b.cow is None
        assert b.ids[idx] == spare
        # a prefill-only rider shares with NO spare and no copy
        c = pool.admit(short, len(short), will_append=False)
        assert c.shared_rows == 6 and c.cow is None
        for alloc in (a, b, c):
            pool.release(alloc)
        pool.check()
        assert pool.blocks_in_use == 0

    def test_capacity_sized_table_forgoes_cow_ride(self):
        """A capacity-sized block table plus its CoW spare can NEVER be
        satisfied — admit() must forgo the partial-tail ride (prefill
        recomputes those rows) instead of returning None forever, which
        would park the request at the head of the memory queue and
        deadlock every later admission behind it."""
        pool = BlockPool(4, 4)
        a = pool.admit(list(range(1, 9)), 8)     # 2 blocks
        pool.commit(a)
        pool.release(a)                          # both cached + indexed
        # 6-token prompt rides a's partial tail; 15 total rows -> a
        # 4-block table == capacity, so the spare would be block 5
        b = pool.admit(list(range(1, 7)), 15)
        assert b is not None                     # not parked forever
        assert b.cow is None and len(b.ids) == 4
        assert b.shared_rows == 4                # full-block hit kept
        pool.release(b)
        pool.check()

    def test_admit_blocks_when_pool_short(self):
        pool = BlockPool(4, 4)
        a = pool.admit(list(range(1, 7)), 12)   # 3 blocks
        assert pool.admit(list(range(30, 36)), 12) is None  # 3 > 1 free
        pool.release(a)
        assert pool.admit(list(range(30, 36)), 12) is not None


# ---------------------------------------------------------------------------
# (b) determinism pins
# ---------------------------------------------------------------------------
class TestPagedDeterminism:
    def test_join_running_batch_equals_solo(self):
        """The continuous-decode determinism pin, over the block table:
        a request joining mid-flight emits the same tokens as alone."""
        lm = _lm()
        rng = np.random.default_rng(4)
        pa = rng.integers(1, 64, 5).tolist()
        pb = rng.integers(1, 64, 8).tolist()
        pc = rng.integers(1, 64, 3).tolist()
        with _paged(lm) as srv:
            solo = srv.generate(pa, 10, timeout=60)
            flong = srv.submit(pb, 30)
            time.sleep(0.05)
            fa = srv.submit(pa, 10)
            fc = srv.submit(pc, 6)
            joined = fa.result(60)
            flong.result(60)
            fc.result(60)
        assert joined == solo

    def test_paged_equals_fixed_slot_and_generate(self):
        """Same request through the paged server, the fixed-slot server,
        and the pinned generate(use_cache=True) reference: one stream."""
        lm = _lm()
        rng = np.random.default_rng(5)
        p = rng.integers(1, 64, 6).tolist()
        expect = lm.generate(p, max_new_tokens=9)
        with ContinuousDecodeServer(lm, slots=2,
                                    prompt_buckets=(8,)) as srv:
            fixed = srv.generate(p, 9, timeout=60)
        with _paged(lm) as srv:
            paged = srv.generate(p, 9, timeout=60)
        assert fixed == expect
        assert paged == expect

    def test_prefix_shared_equals_unshared(self):
        """Two requests behind one system prefix decode bit-identically
        with sharing on (leading blocks one physical copy) and off —
        and the shared run actually hits the prefix cache."""
        lm = _lm()
        rng = np.random.default_rng(6)
        sysp = rng.integers(1, 64, 8).tolist()      # 2 full blocks
        pa = sysp + rng.integers(1, 64, 3).tolist()
        pb = sysp + rng.integers(1, 64, 2).tolist()
        with _paged(lm, prefix_cache=False) as srv:
            ra0 = srv.generate(pa, 8, timeout=60)
            rb0 = srv.generate(pb, 8, timeout=60)
            assert srv.metrics.snapshot()["prefix_rows_hit"] == 0
        with _paged(lm) as srv:
            fa = srv.submit(pa, 8)
            time.sleep(0.05)
            fb = srv.submit(pb, 8)
            ra, rb = fa.result(60), fb.result(60)
            snap = srv.metrics.snapshot()
        assert ra == ra0 and rb == rb0
        # B's two leading blocks were resident from A
        assert snap["prefix_rows_hit"] >= 8
        assert snap["prefix_hit_rate"] > 0

    def test_copy_on_write_correctness(self):
        """A shorter prompt rides a longer prompt's final block; its
        first divergent append triggers exactly one CoW, and BOTH
        streams stay bit-identical to their unshared runs."""
        lm = _lm()
        rng = np.random.default_rng(7)
        p8 = rng.integers(1, 64, 8).tolist()
        p6 = p8[:6]
        with _paged(lm, prefix_cache=False) as srv:
            a0 = srv.generate(p8, 10, timeout=60)
            b0 = srv.generate(p6, 10, timeout=60)
        with _paged(lm) as srv:
            fa = srv.submit(p8, 10)
            time.sleep(0.05)
            fb = srv.submit(p6, 10)     # shares [p8[0:4]] + part of blk 2
            a1, b1 = fa.result(60), fb.result(60)
            snap = srv.metrics.snapshot()
        assert a1 == a0          # owner's rows never clobbered
        assert b1 == b0          # sharer diverges onto its private copy
        assert snap["cow_copies"] == 1
        assert snap["prefix_rows_hit"] >= 6


# ---------------------------------------------------------------------------
# (c) scheduling: memory gate, shed accounting, swap, dispatch A/B
# ---------------------------------------------------------------------------
class TestPagedScheduling:
    def test_blocked_on_memory_admits_when_blocks_free(self):
        """Admission is gated by FREE BLOCKS: a request that cannot get
        its reservation waits (counted once), then serves correctly
        when the resident request completes — no deadlock, no drop."""
        lm = _lm()
        rng = np.random.default_rng(8)
        p1 = rng.integers(1, 64, 8).tolist()
        p2 = rng.integers(1, 64, 6).tolist()
        expect = lm.generate(p2, max_new_tokens=16)
        with _paged(lm, slots=4, n_blocks=8) as srv:
            f1 = srv.submit(p1, 16)          # 6 of 8 blocks
            time.sleep(0.05)
            f2 = srv.submit(p2, 16)          # needs 6 > 2 free: waits
            r1, r2 = f1.result(60), f2.result(60)
            snap = srv.metrics.snapshot()
            assert srv._pool.blocks_in_use == 0     # all returned
        assert len(r1) == 8 + 16
        assert r2 == expect
        assert snap["blocked_on_memory"] == 1
        assert snap.get("failed", 0) == 0

    def test_never_fits_shed_at_submit(self):
        lm = _lm()
        with _paged(lm, n_blocks=4) as srv:
            with pytest.raises(ServerOverloadedError, match="KV blocks"):
                srv.submit([1, 2, 3, 4], 30)     # needs 9 > 4 blocks
            assert srv.metrics.snapshot()["shed_blocks"] == 1

    def test_deadline_expires_while_blocked_on_memory(self):
        """Blocked-on-blocks is queue wait: the deadline still fires,
        the shed is counted, and the blocks it never got stay free.
        Delay-only faults pace the decode iterations (the
        test_serving.py eviction pattern) so the block-holder reliably
        outlives the blocked request's deadline."""
        from deeplearning4j_tpu.common.resilience import FaultInjector
        lm = _lm()
        rng = np.random.default_rng(9)
        p1 = rng.integers(1, 64, 8).tolist()
        inj = FaultInjector(seed=6).plan(
            "serve.batch", on_calls=range(1, 120), times=120,
            delay=0.02, exc=None)
        with _paged(lm, slots=4, n_blocks=8,
                    fault_injector=inj) as srv:
            f1 = srv.submit(p1, 24)          # holds 31 rows -> all 8
            # wait past prefill + the first (compile-bearing) decode
            # iterations, so admission examines the doomed request
            # BEFORE its deadline can expire
            t0 = time.monotonic()
            while srv.metrics.count_value("dispatches") < 3 and \
                    time.monotonic() - t0 < 30:
                time.sleep(0.01)
            doomed = srv.submit(p1, 16, deadline_ms=150)
            # the shed fires from whichever sweep sees the expiry first:
            # the mem-wait sweep ("KV blocks") or the admission re-check
            # ("before prefill") — both count it identically
            with pytest.raises(DeadlineExceededError,
                               match="KV blocks|before prefill"):
                doomed.result(60)
            f1.result(60)
        snap = srv.metrics.snapshot()
        assert snap["shed_deadline"] == 1
        assert snap["blocked_on_memory"] == 1

    def test_no_leak_after_request_churn(self):
        """N mixed requests (shared prefixes, mixed lengths) through a
        small arena: every future resolves, the pool ends empty, and
        the invariants hold — the serving-level refcount/free-list
        pin."""
        lm = _lm()
        rng = np.random.default_rng(10)
        sysp = rng.integers(1, 64, 4).tolist()
        with _paged(lm, slots=3, n_blocks=16) as srv:
            futs = []
            for i in range(12):
                own = rng.integers(1, 64, int(rng.integers(1, 5))).tolist()
                p = (sysp + own) if i % 2 else own
                futs.append(srv.submit(p, int(rng.integers(2, 8))))
            for f in futs:
                assert f.result(120)
            assert srv._pool.blocks_in_use == 0
            assert srv._pool.check()
            assert srv.metrics.snapshot().get("failed", 0) == 0

    def test_dispatch_counter_ab_zero_extra_per_token(self):
        """Paging must be free in DISPATCHES: the same workload through
        fixed-slot and paged servers costs the identical number of
        decode dispatches (the per-token device cost), and the paged
        arm pays no CoW copies on an unshared workload."""
        lm = _lm()
        rng = np.random.default_rng(11)
        work = [(rng.integers(1, 64, int(rng.integers(3, 8))).tolist(),
                 int(rng.integers(3, 9))) for _ in range(6)]
        counts = {}
        for name, srv in (
                ("fixed", ContinuousDecodeServer(
                    lm, slots=2, prompt_buckets=(8,))),
                ("paged", _paged(lm, slots=2))):
            with srv:
                for p, n in work:       # sequential: same iteration count
                    srv.generate(p, n, timeout=60)
                snap = srv.metrics.snapshot()
            counts[name] = (snap["dispatches"], snap["tokens_out"],
                            snap.get("cow_copies", 0))
        assert counts["fixed"][:2] == counts["paged"][:2]
        assert counts["paged"][2] == 0

    def test_hot_swap_drain_with_paged_slots(self):
        """Dual-version drain over the block table: in-flight requests
        finish on pre-swap params, a post-swap request gets the new —
        zero failures, blocks all returned."""
        lm1, lm2 = _lm(3), _lm(11)
        rng = np.random.default_rng(12)
        pa = rng.integers(1, 64, 4).tolist()
        pb = rng.integers(1, 64, 4).tolist()
        with _paged(lm1, slots=2) as srv:
            solo_old = srv.generate(pa, 14, timeout=60)
            fa = srv.submit(pa, 14)
            time.sleep(0.03)
            srv.swap(lm2)
            fb = srv.submit(pb, 5)
            ra, rb = fa.result(60), fb.result(60)
            assert srv._pool.blocks_in_use == 0
        assert ra == solo_old
        expect_new = lm2.generate_batch(np.asarray([pb], np.int32),
                                        max_new_tokens=5)
        assert rb == expect_new[0].tolist()
        assert srv.metrics.snapshot().get("failed", 0) == 0

    def test_fail_fast_stop_fails_memory_waiters(self):
        """stop(drain=False) with a request parked on the memory gate:
        the parked future fails with ServerClosedError and the loop
        exits promptly. Parked requests count as _busy(), so leaving
        them parked would keep the serve thread spinning (and the
        caller blocked on the future) forever once the slots drain."""
        from deeplearning4j_tpu.serving import ServerClosedError
        lm = _lm()
        rng = np.random.default_rng(15)
        pa = rng.integers(1, 64, 4).tolist()
        pb = rng.integers(1, 64, 4).tolist()
        srv = _paged(lm, slots=2, n_blocks=4).start()
        try:
            fa = srv.submit(pa, 9)          # 12 rows -> 3 of 4 blocks
            time.sleep(0.05)                # let A occupy its slot
            fb = srv.submit(pb, 9)          # needs 3, 1 free: parks
            deadline = time.monotonic() + 5
            while (srv.metrics.snapshot().get("blocked_on_memory", 0)
                   < 1 and time.monotonic() < deadline):
                time.sleep(0.005)
            assert srv.metrics.snapshot()["blocked_on_memory"] == 1
        finally:
            srv.stop(drain=False, timeout=30)
        assert srv._thread is None          # loop actually exited
        assert fa.result(1) == lm.generate(pa, max_new_tokens=9)
        with pytest.raises(ServerClosedError):
            fb.result(1)

    def test_swap_invalidates_prefix_reuse(self):
        """A post-swap request with a prompt already in the prefix cache
        must NOT share the old version's blocks — those k/v rows were
        computed under the old params. Pinned two ways: the post-swap
        result is bit-identical to the new params' solo decode, and the
        prefix-hit counter does not move across the swap."""
        lm1, lm2 = _lm(3), _lm(11)
        p = list(range(1, 10))                   # 2 full blocks + tail
        with _paged(lm1, slots=2) as srv:
            srv.generate(p, 4, timeout=60)       # populates the index
            srv.generate(p, 4, timeout=60)       # proves it hits
            hits_before = srv.metrics.snapshot()["prefix_rows_hit"]
            assert hits_before >= 8
            srv.swap(lm2)
            got = srv.generate(p, 4, timeout=60)
            assert srv.metrics.snapshot()["prefix_rows_hit"] \
                == hits_before                   # no cross-version hit
        expect = lm2.generate_batch(np.asarray([p], np.int32),
                                    max_new_tokens=4)
        assert got == expect[0].tolist()

    def test_paged_thread_survives_terminal_dispatch_fault(self):
        """A terminal decode-dispatch fault fails the occupied requests
        LOUDLY and rebuilds arena + pool + tables together (a pool that
        outlived its arena would hand out rows in dead buffers); the
        server keeps serving."""
        from deeplearning4j_tpu.common.resilience import (FaultInjected,
                                                          FaultInjector)
        lm = _lm()
        inj = FaultInjector(seed=5).plan("serve.batch", on_call=1,
                                         exc=FaultInjected)  # 0 = prefill
        rng = np.random.default_rng(13)
        p = rng.integers(1, 64, 4).tolist()
        with _paged(lm, slots=2, fault_injector=inj) as srv:
            f = srv.submit(p, 6)
            with pytest.raises(FaultInjected):
                f.result(60)
            got = srv.generate(p, 6, timeout=60)
            assert srv._pool.blocks_in_use == 0
        assert got == lm.generate(p, max_new_tokens=6)
        assert srv.metrics.snapshot().get("failed") == 1

    def test_paged_prefill_fault_fails_only_that_request(self):
        """The paged prefill does NOT donate the arena precisely so a
        prefill-time failure stays per-request: the arena survives, the
        failed request's reserved blocks release, the next request
        serves bit-identically."""
        from deeplearning4j_tpu.common.resilience import (FaultInjected,
                                                          FaultInjector)
        lm = _lm()
        inj = FaultInjector(seed=5).plan("serve.batch", on_call=0,
                                         exc=FaultInjected)
        rng = np.random.default_rng(14)
        p = rng.integers(1, 64, 4).tolist()
        with _paged(lm, slots=2, fault_injector=inj) as srv:
            f = srv.submit(p, 6)
            with pytest.raises(FaultInjected):
                f.result(60)
            assert srv._pool.blocks_in_use == 0
            got = srv.generate(p, 6, timeout=60)
        assert got == lm.generate(p, max_new_tokens=6)
        assert srv.metrics.snapshot().get("failed") == 1

    def test_one_token_request_releases_blocks_at_prefill(self):
        lm = _lm()
        p = [5, 9, 2]
        expect = lm.generate(p, max_new_tokens=1)
        with _paged(lm) as srv:
            got = srv.generate(p, 1, timeout=60)
            assert srv._pool.blocks_in_use == 0
        assert got == expect


# ---------------------------------------------------------------------------
# (d) paged speculation: the block-table verify program (ISSUE 10)
# ---------------------------------------------------------------------------
def _spec(k=4, draft=None):
    return Speculator(draft if draft is not None else NGramDraft(n=3),
                      k=k)


class TestPagedSpeculative:
    def test_constructs_and_serves(self):
        """The PR 8 refusal is gone: paged=True + speculate= builds the
        block-table verify program and serves — the production
        configuration (paged memory + speculation) exists."""
        lm = _lm()
        p = [5, 9, 2, 7]
        with _paged(lm, speculate=_spec()) as srv:
            got = srv.generate(p, 6, timeout=60)
            assert srv._pool.blocks_in_use == 0
        assert got == lm.generate(p, max_new_tokens=6)

    def test_solo_join_fixed_bit_identical_across_k(self):
        """For K in {2,4,8}: the paged speculative stream == plain
        greedy == fixed-layout speculation — solo, and joining a
        running speculative batch (the continuous-decode pin under
        ragged multi-token advance, over the block table)."""
        lm = _lm()
        rng = np.random.default_rng(21)
        pa = rng.integers(1, 64, 5).tolist()
        pb = rng.integers(1, 64, 8).tolist()
        plain = lm.generate(pa, 10, use_cache=True)
        for k in (2, 4, 8):
            with ContinuousDecodeServer(
                    lm, slots=4, prompt_buckets=(8, 16),
                    speculate=_spec(k)) as srv:
                fixed = srv.generate(pa, 10, timeout=60)
            with _paged(lm, speculate=_spec(k)) as srv:
                solo = srv.generate(pa, 10, timeout=60)
                flong = srv.submit(pb, 24)      # running batch
                time.sleep(0.05)
                joined = srv.submit(pa, 10).result(60)
                flong.result(60)
                assert srv._pool.blocks_in_use == 0
            assert fixed == plain
            assert solo == plain
            assert joined == plain

    def test_model_draft_bit_identical(self):
        """The small-model draft source over the paged layout — and
        the self-draft amortization ceiling: the target drafting for
        itself accepts exactly K per dispatch, dispatches/token = 1/K,
        unchanged by paging."""
        lm = _lm()
        draft_lm = TransformerLM(64, d_model=16, n_heads=2, n_layers=1,
                                 max_len=80, seed=21)
        rng = np.random.default_rng(22)
        p = rng.integers(1, 64, 5).tolist()
        plain = lm.generate(p, 16, use_cache=True)
        with _paged(lm, slots=2, speculate=_spec(4, ModelDraft(
                draft_lm))) as srv:
            assert srv.generate(p, 16, timeout=60) == plain
        k = 4
        with _paged(lm, slots=2, speculate=_spec(k, ModelDraft(
                lm))) as srv:
            got = srv.generate(p, 21, timeout=60)
            snap = srv.metrics.snapshot()
        assert got == lm.generate(p, 21, use_cache=True)
        assert snap["spec_accepted_per_dispatch_mean"] == pytest.approx(k)
        assert snap["dispatches_per_token"] == pytest.approx(1.0 / k)

    def test_cow_divergent_verify_write(self):
        """A shorter prompt riding a longer prompt's final block under
        SPECULATION: the first K-wide verify write starts inside the
        shared block, so the CoW must materialize first — exactly one
        copy, both streams bit-identical to their unshared runs."""
        lm = _lm()
        rng = np.random.default_rng(23)
        p8 = rng.integers(1, 64, 8).tolist()
        p6 = p8[:6]
        with _paged(lm, prefix_cache=False, speculate=_spec()) as srv:
            a0 = srv.generate(p8, 10, timeout=60)
            b0 = srv.generate(p6, 10, timeout=60)
        with _paged(lm, speculate=_spec()) as srv:
            fa = srv.submit(p8, 10)
            time.sleep(0.05)
            fb = srv.submit(p6, 10)
            a1, b1 = fa.result(60), fb.result(60)
            snap = srv.metrics.snapshot()
            assert srv._pool.blocks_in_use == 0
        assert a1 == a0          # owner's rows never clobbered
        assert b1 == b0          # sharer diverges onto its private copy
        assert snap["cow_copies"] == 1
        assert snap["prefix_rows_hit"] >= 6

    def test_no_leak_after_spec_request_churn(self):
        """Mixed speculative requests (shared prefixes, mixed lengths,
        block-boundary-crossing verify rounds) through a small arena:
        every future resolves, the pool ends empty, invariants hold —
        the verify-round block-accounting pin."""
        lm = _lm()
        rng = np.random.default_rng(24)
        sysp = rng.integers(1, 64, 4).tolist()
        with _paged(lm, slots=3, n_blocks=16, speculate=_spec(8)) as srv:
            futs = []
            for i in range(12):
                own = rng.integers(1, 64, int(rng.integers(1, 5))).tolist()
                p = (sysp + own) if i % 2 else own
                futs.append(srv.submit(p, int(rng.integers(2, 10))))
            for f in futs:
                assert f.result(120)
            assert srv._pool.blocks_in_use == 0
            assert srv._pool.check()
            assert srv.metrics.snapshot().get("failed", 0) == 0

    def test_mid_round_deadline_eviction_releases_blocks(self):
        """A deadline expiring between verify rounds evicts the slot:
        future fails, its blocks release, the server keeps serving.
        Delay-only faults pace the verify dispatches so the doomed
        request reliably outlives its budget mid-decode."""
        from deeplearning4j_tpu.common.resilience import FaultInjector
        lm = _lm()
        rng = np.random.default_rng(25)
        p = rng.integers(1, 64, 4).tolist()
        inj = FaultInjector(seed=7).plan(
            "serve.batch", on_calls=range(0, 200), times=200,
            delay=0.03, exc=None)
        with _paged(lm, slots=2, fault_injector=inj,
                    speculate=_spec()) as srv:
            # warm the compile OFF the doomed request's clock
            srv.generate([1, 2], 2, deadline_ms=600_000, timeout=120)
            doomed = srv.submit(p, 40, deadline_ms=120)
            with pytest.raises(DeadlineExceededError):
                doomed.result(120)
            deadline = time.monotonic() + 10
            while srv._pool.blocks_in_use and time.monotonic() < deadline:
                time.sleep(0.01)
            assert srv._pool.blocks_in_use == 0
            snap = srv.metrics.snapshot()
        assert snap["shed_deadline"] == 1
        assert snap["evicted_mid_decode"] == 1

    def test_dispatch_counter_ab_paged_spec_equals_fixed_spec(self):
        """Paging must stay free in DISPATCHES under speculation: the
        same sequential speculative workload through fixed and paged
        servers costs the identical verify-dispatch count per token
        (the PR 5 amortization carries over unchanged), with zero CoW
        copies on an unshared workload."""
        lm = _lm()
        rng = np.random.default_rng(26)
        # repetitive prompts so the n-gram draft really accepts (the
        # amortization regime, not just the bonus-token floor)
        work = []
        for _ in range(6):
            pat = rng.integers(1, 64, 3).tolist()
            work.append(((pat * 3)[:int(rng.integers(4, 8))],
                         int(rng.integers(6, 12))))
        counts = {}
        for name, srv in (
                ("fixed", ContinuousDecodeServer(
                    lm, slots=2, prompt_buckets=(8,),
                    speculate=_spec())),
                ("paged", _paged(lm, slots=2, speculate=_spec()))):
            with srv:
                for p, n in work:       # sequential: same round count
                    srv.generate(p, n, timeout=60)
                snap = srv.metrics.snapshot()
            counts[name] = (snap["dispatches"], snap["tokens_out"],
                            snap.get("cow_copies", 0))
        assert counts["fixed"][:2] == counts["paged"][:2]
        assert counts["paged"][2] == 0

    def test_hot_swap_drain_paged_speculative(self):
        """Dual-version drain under paged speculation: the in-flight
        stream finishes on pre-swap params (verify pinned to the slot's
        version over the block table) while a post-swap request gets
        the new params; blocks all returned."""
        lm1, lm2 = _lm(3), _lm(11)
        rng = np.random.default_rng(27)
        pa = rng.integers(1, 64, 4).tolist()
        pb = rng.integers(1, 64, 4).tolist()
        with _paged(lm1, slots=2, speculate=_spec()) as srv:
            solo_old = srv.generate(pa, 14, timeout=60)
            fa = srv.submit(pa, 14)
            time.sleep(0.03)
            srv.swap(lm2)
            fb = srv.submit(pb, 5)
            ra, rb = fa.result(60), fb.result(60)
            assert srv._pool.blocks_in_use == 0
        assert ra == solo_old
        expect_new = lm2.generate_batch(np.asarray([pb], np.int32),
                                        max_new_tokens=5)
        assert rb == expect_new[0].tolist()
        assert srv.metrics.snapshot().get("failed", 0) == 0


# ---------------------------------------------------------------------------
# guards that remain
# ---------------------------------------------------------------------------
class TestPagedGuards:
    def test_oversize_for_slot_table_shed_at_submit(self):
        """A caller-tuned max_blocks_per_slot below ceil(max_len/bs) is
        a hard per-request ceiling too: an oversize request sheds
        loudly at submit instead of crashing the admission thread on
        the block-table write."""
        lm = _lm()
        with _paged(lm, max_blocks_per_slot=2) as srv:
            with pytest.raises(ServerOverloadedError, match="table"):
                srv.submit(list(range(1, 10)), 5)
            got = srv.generate([5, 1], 4, timeout=60)
            assert srv.metrics.snapshot()["shed_blocks"] == 1
        assert got == lm.generate([5, 1], max_new_tokens=4)
