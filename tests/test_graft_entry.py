"""Driver entry-point contract: `entry()` must return a traceable forward
(the driver compile-checks it single-chip every round — r5 caught it broken
by an `_apply_graph` arity change, so this pins the contract in the core
tier). `dryrun_multichip` has its own driver run + the parallel test
suite; tracing the flagship forward here is the cheap guard."""
import os
import sys

import jax

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_entry_traces_flagship_forward():
    sys.path.insert(0, REPO)
    try:
        import __graft_entry__ as g
    finally:
        sys.path.pop(0)
    fn, args = g.entry()
    # eval_shape = full trace without XLA compilation (seconds, not
    # minutes) — exactly what catches signature/arity/shape breakage
    out = jax.eval_shape(fn, *args)
    assert out.shape == (8, 1000)
