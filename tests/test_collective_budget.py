"""Per-mode collective-cost budgets on the virtual 8-device mesh
(VERDICT r4 item 9; SURVEY §4.6 simulated-pod pattern extended to cost).

Each parallelism mode's training/step program is lowered (never executed),
its compiled HLO parsed for cross-device collectives, and the totals pinned
against the committed budget in tests/fixtures/collective_budgets.json —
a >2x bytes (or count) regression fails, catching e.g. a lost sharding
constraint that re-replicates the ZeRO-partitioned optimizer state with a
per-step all-gather. Regenerate the budgets after an INTENTIONAL sharding
change with:

    UPDATE_COLLECTIVE_BUDGETS=1 python -m pytest \
        tests/test_collective_budget.py -q
"""
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu import (InputType, MultiLayerNetwork,
                                NeuralNetConfiguration)
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.nn.conf.layers import (ConvolutionLayer, DenseLayer,
                                               OutputLayer, SubsamplingLayer)
from deeplearning4j_tpu.parallel import ParallelWrapper, make_mesh
from deeplearning4j_tpu.parallel.mesh_cost import (footprint_totals,
                                                   lowered_footprint)

BUDGET_PATH = os.path.join(os.path.dirname(__file__), "fixtures",
                           "collective_budgets.json")
N = 8


def _conf():
    return (NeuralNetConfiguration.Builder()
            .seed(7).updater("adam").learning_rate(1e-3).list()
            .layer(0, ConvolutionLayer(n_out=8, kernel_size=(3, 3),
                                       activation="relu"))
            .layer(1, SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
            .layer(2, DenseLayer(n_out=32, activation="relu"))
            .layer(3, OutputLayer(n_out=4, activation="softmax",
                                  loss_function="mcxent"))
            .set_input_type(InputType.convolutional(8, 8, 2))
            .build())


def _mode_lowerings():
    """name -> jax lowering for one step of each parallelism mode, the same
    constructions dryrun_multichip exercises."""
    devices = jax.devices()[:N]
    rng = np.random.default_rng(0)
    out = {}

    # dp x tp with ZeRO-1 sharded optimizer state
    net = MultiLayerNetwork(_conf()).init()
    mesh = make_mesh(n_data=N // 2, n_model=2, devices=devices)
    pw = (ParallelWrapper.Builder(net).mesh(mesh).tensor_parallel(True)
          .sharded_updater_state(True).averaging_frequency(1).build())
    x = rng.random((16, 8, 8, 2)).astype(np.float32)
    y = np.eye(4, dtype=np.float32)[rng.integers(0, 4, 16)]
    out["dp_tp_zero1"] = pw.lower_step(DataSet(x, y))

    # k-local-steps parameter averaging (averaging_frequency=2: lax.scan
    # of 2 local steps inside shard_map, then pmean over "data")
    net2 = MultiLayerNetwork(_conf()).init()
    pw2 = (ParallelWrapper.Builder(net2)
           .mesh(make_mesh(n_data=N, n_model=1, devices=devices))
           .averaging_frequency(2).build())
    out["param_averaging"] = pw2.lower_kstep(
        [DataSet(x[:8], y[:8]), DataSet(x[8:], y[8:])])

    # GPipe pipeline transformer (pipe=4 x data=2)
    from deeplearning4j_tpu.models.zoo.transformer import (embed_fn, init_lm,
                                                           lm_loss,
                                                           make_block_fn)
    from deeplearning4j_tpu.parallel.pipeline import (PipelineParallel,
                                                      make_pipeline_mesh)
    pp_mesh = make_pipeline_mesh(n_pipe=4, n_data=2, devices=devices)
    aux, blocks = init_lm(11, d_model=16, n_heads=2, n_layers=4,
                          max_len=8, seed=3)
    pp = PipelineParallel(make_block_fn(2), blocks, pp_mesh, loss_fn=lm_loss,
                          aux_params=aux, pre_fn=embed_fn, n_micro=2,
                          data_axis="data", learning_rate=0.1)
    xt = rng.integers(0, 11, (8, 8)).astype(np.int32)
    out["gpipe_pp"] = pp.lower_step(xt, (xt + 1) % 11)

    # 3-axis dp x tp x pp: Megatron tensor-parallel blocks inside the
    # GPipe rotation (pipe=4 x model=2 x data=1 on 8 devices)
    from deeplearning4j_tpu.models.zoo.transformer import (
        init_tp_block, make_tp_block_fn, tp_block_specs)
    mesh3 = make_pipeline_mesh(n_pipe=4, n_data=1, n_model=2,
                               devices=devices)
    blocks3 = [init_tp_block(jax.random.fold_in(jax.random.PRNGKey(9), i),
                             16, 4, 32) for i in range(4)]
    aux3, _ = init_lm(11, d_model=16, n_heads=4, n_layers=1, max_len=8,
                      seed=9)
    pp3 = PipelineParallel(
        make_tp_block_fn(2, "model"), blocks3, mesh3, loss_fn=lm_loss,
        aux_params=aux3, pre_fn=embed_fn, n_micro=2, data_axis="data",
        learning_rate=0.1, param_specs=tp_block_specs("pipe", "model"))
    x3 = rng.integers(0, 11, (4, 8)).astype(np.int32)
    out["dp_tp_pp_3axis"] = pp3.lower_step(x3, (x3 + 1) % 11)

    # ring-attention sequence parallelism
    from jax.sharding import Mesh
    from deeplearning4j_tpu.parallel.ring_attention import ring_self_attention
    seq_mesh = Mesh(np.array(devices), ("seq",))
    q = jnp.asarray(rng.standard_normal((2, 4 * N, 2, 8)), jnp.float32)
    out["ring_attention_sp"] = jax.jit(
        lambda q, k, v: ring_self_attention(q, k, v, seq_mesh, axis="seq",
                                            causal=True)).lower(q, q, q)

    # Switch-MoE expert parallelism (all_to_all dispatch)
    from deeplearning4j_tpu.parallel.moe import (init_moe, make_expert_mesh,
                                                 moe_mlp_sharded,
                                                 shard_moe_params)
    ep_mesh = make_expert_mesh(N, devices=devices)
    moe_p = shard_moe_params(init_moe(jax.random.PRNGKey(0), 16, N, 32),
                             ep_mesh)
    xm = jnp.asarray(rng.standard_normal((8 * N, 16)), jnp.float32)
    out["moe_ep"] = jax.jit(moe_mlp_sharded(ep_mesh)).lower(moe_p, xm)

    # dp x ep top-2 MoE: batch over (data, expert) jointly, per-data-slice
    # all_to_all rings, top-2 combine
    from jax.sharding import Mesh as _Mesh
    de_mesh = _Mesh(np.array(devices).reshape(2, N // 2),
                    ("data", "expert"))
    moe_p2 = shard_moe_params(init_moe(jax.random.PRNGKey(1), 16, N // 2,
                                       32), de_mesh)
    out["dp_ep_moe_top2"] = jax.jit(
        moe_mlp_sharded(de_mesh, k=2, data_axis="data")).lower(moe_p2, xm)

    # model-sharded word2vec: syn0/syn1 column-shard over "model", the
    # flush step's logit psum is the only collective
    from deeplearning4j_tpu.models.embeddings.learning import SkipGram
    from deeplearning4j_tpu.models.embeddings.lookup_table import \
        InMemoryLookupTable
    from deeplearning4j_tpu.models.word2vec.vocab import VocabCache
    vocab = VocabCache()
    for i in range(50):
        vocab.add_token(f"w{i}", count=5)
    vocab.finish()
    table = InMemoryLookupTable(vocab, vector_length=8 * N, seed=1,
                                negative=3, use_hs=False).reset_weights()
    sg = SkipGram(batch_pairs=256)
    sg.configure(vocab, table, window=3, negative=3, use_hs=False, seed=1,
                 mesh=make_mesh(n_data=1, n_model=N, devices=devices))
    out["w2v_model_sharded"] = sg.lower_step()
    return out


@pytest.mark.slow
def test_collective_bytes_within_budget():
    if len(jax.devices()) < N:
        pytest.skip(f"needs {N} virtual devices")
    measured = {}
    for name, lowered in _mode_lowerings().items():
        fp, _ = lowered_footprint(lowered)
        measured[name] = {**footprint_totals(fp), "ops": fp}
    if os.environ.get("UPDATE_COLLECTIVE_BUDGETS"):
        with open(BUDGET_PATH, "w") as f:
            json.dump(measured, f, indent=1, sort_keys=True)
        pytest.skip(f"budgets regenerated at {BUDGET_PATH}")
    with open(BUDGET_PATH) as f:
        budget = json.load(f)
    assert set(measured) == set(budget), (
        "parallelism modes changed — regenerate the budget fixture")
    for name, got in measured.items():
        want = budget[name]
        assert got["bytes"] <= 2 * max(want["bytes"], 1), (
            f"{name}: per-step collective bytes regressed "
            f"{want['bytes']} -> {got['bytes']} (>2x budget); if the "
            f"sharding change is intentional, regenerate the fixture")
        assert got["count"] <= 2 * max(want["count"], 1), (
            f"{name}: collective op count regressed "
            f"{want['count']} -> {got['count']} (>2x budget)")
    # mode-shape sanity: the ring rides collective-permute, MoE all_to_all
    assert "collective-permute" in measured["ring_attention_sp"]["ops"]
    assert any(op.startswith("all-to-all")
               for op in measured["moe_ep"]["ops"])


def test_async_variadic_collective_accounting():
    """ADVICE r5 (mesh_cost.py): a VARIADIC async -start tuple aliases ALL
    its operands as the leading components, not just the first — the
    accounting must subtract the first half (after stripping trailing
    context scalars) so committed collective-bytes budgets don't shift on
    a sync<->async backend flip. Pure HLO-text parsing, no lowering."""
    from deeplearning4j_tpu.parallel.mesh_cost import (
        hlo_collective_footprint, shape_bytes)

    sync = ("  %ar = (f32[128,4]{1,0}, f32[64]{0}) "
            "all-reduce(f32[128,4] %a, f32[64] %b), replica_groups={}")
    sync_bytes = hlo_collective_footprint(sync)["all-reduce"]["bytes"]
    assert sync_bytes == 128 * 4 * 4 + 64 * 4

    # variadic async: 2 operand aliases + 2 results — must equal sync
    async_ = ("  %ars = (f32[128,4]{1,0}, f32[64]{0}, f32[128,4]{1,0}, "
              "f32[64]{0}) all-reduce-start(f32[128,4] %a, f32[64] %b), "
              "replica_groups={}")
    fp = hlo_collective_footprint(async_)["all-reduce"]
    assert fp["count"] == 1
    assert fp["bytes"] == sync_bytes

    # trailing context scalars (some lowerings) are stripped before the
    # half-split and stay counted, exactly as in the single-operand case
    async_ctx = ("  %ars = (f32[128,4]{1,0}, f32[64]{0}, f32[128,4]{1,0}, "
                 "f32[64]{0}, u32[], u32[]) all-reduce-start("
                 "f32[128,4] %a, f32[64] %b), replica_groups={}")
    fp_ctx = hlo_collective_footprint(async_ctx)["all-reduce"]
    assert fp_ctx["bytes"] == sync_bytes + 2 * shape_bytes("u32[]")

    # single-operand behavior unchanged: (operand, result) subtracts the
    # operand alias, matching the sync lowering
    s1 = "  %r = f32[32]{0} all-reduce(f32[32] %x), replica_groups={}"
    a1 = ("  %rs = (f32[32]{0}, f32[32]{0}) all-reduce-start(f32[32] %x), "
          "replica_groups={}")
    assert (hlo_collective_footprint(a1)["all-reduce"]["bytes"]
            == hlo_collective_footprint(s1)["all-reduce"]["bytes"])
