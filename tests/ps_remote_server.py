"""Parameter-server master process for test_ps_transport.py.

Owns the master network + GradientsAccumulator behind a PSServer socket,
waits for every worker's DONE, then prints the final score and accumulator
stats. Usage: python tests/ps_remote_server.py <port_file> <n_workers>
"""
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

from deeplearning4j_tpu import (InputType, MultiLayerNetwork,  # noqa: E402
                                NeuralNetConfiguration)
from deeplearning4j_tpu.datasets.dataset import DataSet  # noqa: E402
from deeplearning4j_tpu.nn.conf.layers import (DenseLayer,  # noqa: E402
                                               OutputLayer)
from deeplearning4j_tpu.parallel.ps_transport import PSServer  # noqa: E402


def build_net(seed=7):
    conf = (NeuralNetConfiguration.Builder().seed(seed)
            .updater("adam").learning_rate(0.01).list()
            .layer(0, DenseLayer(n_out=16, activation="relu"))
            .layer(1, OutputLayer(n_out=3, activation="softmax",
                                  loss_function="mcxent"))
            .set_input_type(InputType.feed_forward(5))
            .build())
    return MultiLayerNetwork(conf).init()


def build_data(n=256, seed=0):
    r = np.random.default_rng(seed)
    x = r.random((n, 5)).astype(np.float32)
    w = r.random((5, 3))
    y = np.eye(3, dtype=np.float32)[np.argmax(x @ w, axis=1)]
    return DataSet(x, y)


def main():
    # argv parsed here, not at module scope: the worker script and the
    # pytest process both import build_net/build_data from this module
    port_file, n_workers = sys.argv[1], int(sys.argv[2])
    net = build_net()
    ds = build_data()
    s0 = float(net.score(ds))
    srv = PSServer(net, queue_size=4, n_workers=n_workers)
    with open(port_file, "w") as f:
        f.write(str(srv.port))
    stats = srv.wait(timeout=240)
    print("RESULT", f"s0={s0}", f"score={float(net.score(ds))}",
          f"applied={stats['applied']}",
          f"stale_dropped={stats['stale_dropped']}",
          f"max_staleness={stats['max_staleness_seen']}", flush=True)


if __name__ == "__main__":
    main()
