"""Worker for the HIERARCHICAL multi-host test (test_multihost.py).

4 real processes x 2 virtual CPU devices each = 8 global devices, one
3-axis mesh (data=2, model=2, pipe=2) laid out so the axes mix fabrics the
way a real pod slice does: with jax.devices() ordered process-major, the
reshape puts "pipe" INSIDE a process (the ICI role) while "data" and
"model" SPAN process boundaries (the DCN role). One dp x tp x pp training
step (Megatron TP blocks inside the GPipe rotation) then exercises
psum/ppermute over both fabrics in a single jitted program — SURVEY.md
§5.8's north star (ICI within the pod, DCN across).

Usage: python tests/multihost_worker_hier.py <proc_id> <nproc> <coord>
"""
import os
import sys

proc_id, nproc, coord = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=2")

import numpy as np  # noqa: E402

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

from deeplearning4j_tpu.models.zoo.transformer import (  # noqa: E402
    embed_fn, init_lm, init_tp_block, lm_loss, make_tp_block_fn,
    tp_block_specs)
from deeplearning4j_tpu.parallel import distributed  # noqa: E402
from deeplearning4j_tpu.parallel.pipeline import (  # noqa: E402
    PipelineParallel, make_pipeline_mesh)


def main():
    ok = distributed.initialize(coord, nproc, proc_id)
    assert ok, "distributed.initialize returned False"
    assert jax.process_count() == nproc and jax.device_count() == 8

    # process-major device order -> (data=2, model=2, pipe=2): pipe pairs
    # are intra-process (ICI), data/model boundaries are cross-process (DCN)
    mesh = make_pipeline_mesh(n_pipe=2, n_data=2, n_model=2)
    assert mesh.axis_names == ("data", "model", "pipe")
    dev_grid = np.asarray(mesh.devices)
    # pipe neighbours share a process; model neighbours do not
    assert dev_grid[0, 0, 0].process_index == dev_grid[0, 0, 1].process_index
    assert dev_grid[0, 0, 0].process_index != dev_grid[0, 1, 0].process_index

    D, H, F = 16, 4, 32
    rng = jax.random.PRNGKey(3)
    blocks = [init_tp_block(jax.random.fold_in(rng, i), D, H, F)
              for i in range(2)]
    aux, _ = init_lm(11, d_model=D, n_heads=H, n_layers=1, max_len=8,
                     seed=5)
    pp = PipelineParallel(
        make_tp_block_fn(H // 2, "model"), blocks, mesh, loss_fn=lm_loss,
        aux_params=aux, pre_fn=embed_fn, n_micro=2, data_axis="data",
        learning_rate=0.1, param_specs=tp_block_specs("pipe", "model"))

    r = np.random.default_rng(0)
    x = r.integers(0, 11, (8, 8)).astype(np.int32)
    y = (x + 1) % 11
    losses = [pp.fit_batch(x, y) for _ in range(3)]
    assert all(np.isfinite(l) for l in losses)

    # gather the full (replicated-view) stacked params for the checksum
    total = 0.0
    for leaf in jax.tree.leaves(pp.stacked):
        total += float(jax.jit(lambda a: jax.numpy.sum(
            a.astype(jax.numpy.float64)), out_shardings=None)(leaf))
    print(f"RESULT {proc_id} sum={total:.10f} loss={losses[-1]:.10f}",
          flush=True)


if __name__ == "__main__":
    main()
