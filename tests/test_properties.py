"""Property-based invariants (hypothesis) across cross-cutting seams.

The reference's unit tests pin examples; these pin LAWS the examples are
instances of — the SURVEY §4 strategy deepened one level. Each property is
cheap (numpy-level or tiny nets, bounded example counts) so the module
stays in the core tier.
"""
import numpy as np
import pytest

hypothesis = pytest.importorskip("hypothesis")   # optional dependency
from hypothesis import HealthCheck, given, settings  # noqa: E402
from hypothesis import strategies as st  # noqa: E402

SET = settings(max_examples=25, deadline=None,
               suppress_health_check=[HealthCheck.too_slow])


# --------------------------------------------------------------------------
# DataSet algebra: merge(batch_by(ds)) == ds, shuffle is a permutation
# --------------------------------------------------------------------------
@SET
@given(n=st.integers(1, 40), f=st.integers(1, 8), bs=st.integers(1, 17),
       seed=st.integers(0, 2**31 - 1))
def test_dataset_batch_by_merge_round_trip(n, f, bs, seed):
    from deeplearning4j_tpu.datasets.dataset import DataSet
    rng = np.random.default_rng(seed)
    x = rng.random((n, f)).astype(np.float32)
    y = rng.random((n, 3)).astype(np.float32)
    ds = DataSet(x, y)
    batches = list(ds.batch_by(bs))
    assert sum(b.num_examples() for b in batches) == n
    assert all(b.num_examples() <= bs for b in batches)
    back = DataSet.merge(batches)
    np.testing.assert_array_equal(np.asarray(back.features), x)
    np.testing.assert_array_equal(np.asarray(back.labels), y)


@SET
@given(n=st.integers(2, 40), seed=st.integers(0, 2**31 - 1))
def test_dataset_shuffle_is_a_permutation(n, seed):
    from deeplearning4j_tpu.datasets.dataset import DataSet
    x = np.arange(n, dtype=np.float32).reshape(n, 1)
    y = np.arange(n, dtype=np.float32).reshape(n, 1) * 10
    ds = DataSet(x.copy(), y.copy())
    ds.shuffle(seed=seed)
    xs = np.asarray(ds.features).ravel()
    ys = np.asarray(ds.labels).ravel()
    assert sorted(xs.tolist()) == list(range(n))
    # feature/label alignment survives the shuffle
    np.testing.assert_array_equal(ys, xs * 10)


# --------------------------------------------------------------------------
# Wire caster: floats shrink, ints/bools/None pass through, values survive
# --------------------------------------------------------------------------
@SET
@given(dt=st.sampled_from(["float32", "float64", "uint8", "uint16",
                           "int32", "bool"]),
       shape=st.lists(st.integers(1, 6), min_size=1, max_size=3),
       seed=st.integers(0, 2**31 - 1))
def test_wire_caster_laws(dt, shape, seed):
    import jax.numpy as jnp

    from deeplearning4j_tpu.datasets.iterators import _wire_caster
    rng = np.random.default_rng(seed)
    a = (rng.random(shape) * 100).astype(dt)
    cast = _wire_caster("bfloat16")
    out = cast(a)
    assert cast(None) is None
    if np.dtype(dt).kind == "f":
        assert out.dtype == jnp.bfloat16
        # bf16 has an 8-bit mantissa: relative error bounded by 2^-8
        np.testing.assert_allclose(np.asarray(out, np.float64),
                                   a.astype(np.float64),
                                   rtol=2.0 ** -8, atol=2.0 ** -8)
    else:
        assert out.dtype == a.dtype
        np.testing.assert_array_equal(out, a)


# --------------------------------------------------------------------------
# Normalizers: transform laws + device/host agreement on any input dtype
# --------------------------------------------------------------------------
@SET
@given(n=st.integers(4, 60), f=st.integers(1, 6),
       seed=st.integers(0, 2**31 - 1))
def test_standardize_yields_zero_mean_unit_var(n, f, seed):
    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.datasets.normalizers import NormalizerStandardize
    rng = np.random.default_rng(seed)
    x = (rng.random((n, f)) * 50 - 10).astype(np.float32)
    norm = NormalizerStandardize().fit(DataSet(x.copy(), None))
    out = np.asarray(norm.transform(DataSet(x.copy(), None)).features,
                     np.float64)
    np.testing.assert_allclose(out.mean(0), 0, atol=1e-3)
    # constant columns keep std 0 (epsilon floor), others normalize to 1
    live = x.std(0) > 1e-4
    np.testing.assert_allclose(out.std(0)[live], 1, atol=1e-2)


@SET
@given(dt=st.sampled_from(["uint8", "uint16", "float32"]),
       lo=st.floats(-2, 0), hi=st.floats(0.5, 3),
       seed=st.integers(0, 2**31 - 1))
def test_minmax_output_bounded_and_device_matches_host(dt, lo, hi, seed):
    import jax.numpy as jnp

    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.datasets.normalizers import NormalizerMinMaxScaler
    rng = np.random.default_rng(seed)
    x = (rng.random((12, 4)) * 200).astype(dt)
    norm = NormalizerMinMaxScaler(lo, hi).fit(
        DataSet(x.astype(np.float32), None))
    host = np.asarray(
        norm.transform(DataSet(x.astype(np.float32), None)).features,
        np.float64)
    assert host.min() >= lo - 1e-4 and host.max() <= hi + 1e-4
    dev = np.asarray(norm.device_apply(jnp.asarray(x)), np.float64)
    np.testing.assert_allclose(dev, host, rtol=1e-4, atol=1e-4)


# --------------------------------------------------------------------------
# Evaluation.merge: splitting a prediction stream changes nothing
# --------------------------------------------------------------------------
@SET
@given(n=st.integers(2, 60), c=st.integers(2, 5), cut=st.floats(0.1, 0.9),
       seed=st.integers(0, 2**31 - 1))
def test_evaluation_merge_equals_whole(n, c, cut, seed):
    from deeplearning4j_tpu.eval.evaluation import Evaluation
    rng = np.random.default_rng(seed)
    labels = np.eye(c, dtype=np.float32)[rng.integers(0, c, n)]
    preds = rng.random((n, c)).astype(np.float32)
    preds /= preds.sum(1, keepdims=True)
    whole = Evaluation()
    whole.eval(labels, preds)
    k = max(1, min(n - 1, int(n * cut)))
    a, b = Evaluation(), Evaluation()
    a.eval(labels[:k], preds[:k])
    b.eval(labels[k:], preds[k:])
    a.merge(b)
    assert a.accuracy() == pytest.approx(whole.accuracy())
    assert a.f1() == pytest.approx(whole.f1())


# --------------------------------------------------------------------------
# Huffman: prefix-free codes, shorter codes for more frequent words
# --------------------------------------------------------------------------
@SET
@given(counts=st.lists(st.integers(1, 10_000), min_size=2, max_size=40))
def test_huffman_codes_prefix_free_and_ordered(counts):
    from deeplearning4j_tpu.models.word2vec.vocab import (VocabCache,
                                                          build_huffman)
    vocab = VocabCache()
    for i, cnt in enumerate(counts):
        vocab.add_token(f"w{i}", cnt)
    vocab.finish()
    build_huffman(vocab)
    words = list(vocab.vocab_words())
    codes = ["".join(str(b) for b in w.codes) for w in words]
    assert len(set(codes)) == len(codes)
    for i, ci in enumerate(codes):          # prefix-freeness
        for j, cj in enumerate(codes):
            if i != j:
                assert not cj.startswith(ci)
    # optimality consequence, tie-tolerant pairwise form: a STRICTLY more
    # frequent word never gets a strictly longer code
    for wi in words:
        for wj in words:
            if wi.count > wj.count:
                assert len(wi.codes) <= len(wj.codes), (wi, wj)


# --------------------------------------------------------------------------
# Japanese lattice tokenizer: lossless segmentation (offsets partition)
# --------------------------------------------------------------------------
_JA = st.text(
    alphabet=st.sampled_from(
        "すもももものうち私は学生でカタナひらが混在漢字山川水日本語食べる高い"),
    min_size=1, max_size=20)


@SET
@given(s=_JA)
def test_japanese_lattice_segmentation_is_lossless(s):
    from deeplearning4j_tpu.text.ja_lattice import JapaneseLatticeTokenizer
    toks = JapaneseLatticeTokenizer(s).get_tokens()
    assert "".join(toks) == s


def _mln(widths, act, updater, lr, seed):
    from deeplearning4j_tpu import (InputType, MultiLayerNetwork,
                                    NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
    b = (NeuralNetConfiguration.Builder().seed(seed)
         .updater(updater).learning_rate(lr).list())
    for i, w in enumerate(widths):
        b = b.layer(i, DenseLayer(n_out=w, activation=act))
    b = b.layer(len(widths), OutputLayer(n_out=2, activation="softmax",
                                         loss_function="mcxent"))
    conf = b.set_input_type(InputType.feed_forward(3)).build()
    from deeplearning4j_tpu import MultiLayerNetwork as _M
    return _M(conf).init(), conf


# --------------------------------------------------------------------------
# Flat-params contract: params()/set_params round-trips exactly for random
# layer stacks (the reference's single-flat-vector law)
# --------------------------------------------------------------------------
@settings(max_examples=8, deadline=None,
          suppress_health_check=[HealthCheck.too_slow])
@given(widths=st.lists(st.integers(1, 9), min_size=1, max_size=3),
       act=st.sampled_from(["relu", "tanh", "sigmoid"]),
       seed=st.integers(0, 2**31 - 1))
def test_flat_params_round_trip_random_stacks(widths, act, seed):
    from deeplearning4j_tpu import MultiLayerNetwork
    net, conf = _mln(widths, act, "sgd", 0.1, seed)
    flat = np.asarray(net.params())
    assert flat.ndim == 1 and flat.size == net.num_params()
    net2 = MultiLayerNetwork(conf).init()
    net2.set_params(flat)
    np.testing.assert_array_equal(np.asarray(net2.params()), flat)
    # config serde: json -> rebuild -> identical json
    from deeplearning4j_tpu.nn.conf.neural_net_configuration import (
        MultiLayerConfiguration)
    j = conf.to_json()
    assert MultiLayerConfiguration.from_json(j).to_json() == j


# --------------------------------------------------------------------------
# Serialization format laws: word-vector text/binary round-trips, model
# zip save/restore identity, ROC bounds
# --------------------------------------------------------------------------
_WORD = st.text(alphabet=st.characters(min_codepoint=33, max_codepoint=126,
                                       exclude_characters=" "),
                min_size=1, max_size=10)


class _VecModel:
    def __init__(self, vocab, lookup):
        self.vocab, self.lookup = vocab, lookup


def _random_vec_model(words, dim, seed):
    from deeplearning4j_tpu.models.embeddings.lookup_table import (
        InMemoryLookupTable)
    from deeplearning4j_tpu.models.word2vec.vocab import VocabCache
    rng = np.random.default_rng(seed)
    vocab = VocabCache()
    # descending counts keep rank order stable through (de)serialization
    for i, w in enumerate(words):
        vocab.add_token(w, len(words) + 1 - i)
    vocab.finish()
    lookup = InMemoryLookupTable(vocab, dim)
    lookup.syn0 = rng.standard_normal((len(words), dim)).astype(np.float32)
    return _VecModel(vocab, lookup)


@SET
@given(words=st.lists(_WORD, min_size=1, max_size=12, unique=True),
       dim=st.integers(1, 16), seed=st.integers(0, 2**31 - 1),
       binary=st.booleans())
def test_word_vector_serialization_round_trip(tmp_path_factory, words, dim,
                                              seed, binary):
    from deeplearning4j_tpu.models.embeddings.serializer import (
        read_word2vec_binary, read_word2vec_text, write_word2vec_binary,
        write_word2vec_text)
    model = _random_vec_model(words, dim, seed)
    path = str(tmp_path_factory.mktemp("wv") / ("m.bin" if binary else "m.txt"))
    if binary:
        write_word2vec_binary(model, path)
        back = read_word2vec_binary(path)
    else:
        write_word2vec_text(model, path)
        back = read_word2vec_text(path)
    assert [w.word for w in back.vocab.vocab_words()] == list(words)
    tol = 0 if binary else 5e-6          # text format prints %.6f
    np.testing.assert_allclose(back.lookup.syn0, model.lookup.syn0,
                               atol=tol)


@settings(max_examples=6, deadline=None,
          suppress_health_check=[HealthCheck.too_slow])
@given(widths=st.lists(st.integers(1, 8), min_size=1, max_size=2),
       seed=st.integers(0, 2**31 - 1))
def test_model_zip_save_restore_identity(tmp_path_factory, widths, seed):
    import jax

    from deeplearning4j_tpu.util.model_serializer import (restore_model,
                                                          write_model)
    net, conf = _mln(widths, "relu", "adam", 0.05, seed)
    rng = np.random.default_rng(seed)
    x = rng.random((8, 3)).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 8)]
    net.fit(x, y)                         # non-trivial updater state
    path = str(tmp_path_factory.mktemp("mz") / "model.zip")
    write_model(net, path)
    back = restore_model(path)
    np.testing.assert_array_equal(np.asarray(back.params()),
                                  np.asarray(net.params()))
    np.testing.assert_allclose(np.asarray(back.output(x), np.float64),
                               np.asarray(net.output(x), np.float64),
                               rtol=1e-6)
    # the Adam moments themselves round-trip (not just params/outputs)
    for a, b2 in zip(jax.tree.leaves(net._updater_state),
                     jax.tree.leaves(back._updater_state)):
        np.testing.assert_allclose(np.asarray(a, np.float64),
                                   np.asarray(b2, np.float64), rtol=1e-7)


@SET
@given(n=st.integers(4, 80), seed=st.integers(0, 2**31 - 1))
def test_roc_auc_laws(n, seed):
    from deeplearning4j_tpu.eval.roc import ROC
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, 2, n)
    if labels.min() == labels.max():      # need both classes
        labels[0] = 1 - labels[0]
    probs = rng.random(n)
    roc = ROC(threshold_steps=200)
    roc.eval(labels, probs)
    auc = roc.calculate_auc()
    assert 0.0 <= auc <= 1.0
    # perfectly separated scores give AUC ~ 1
    perfect = ROC(threshold_steps=200)
    perfect.eval(labels, labels * 0.8 + 0.1)
    assert perfect.calculate_auc() >= 0.99


# --------------------------------------------------------------------------
# Spatial trees: exact agreement with brute force on random point sets
# --------------------------------------------------------------------------
@SET
@given(n=st.integers(2, 60), d=st.integers(1, 5), k=st.integers(1, 5),
       seed=st.integers(0, 2**31 - 1))
def test_vptree_knn_matches_brute_force(n, d, k, seed):
    from deeplearning4j_tpu.clustering.trees import VPTree
    rng = np.random.default_rng(seed)
    pts = rng.random((n, d))
    q = rng.random(d)
    k = min(k, n)
    got = VPTree(pts).knn(q, k)
    dists = np.linalg.norm(pts - q, axis=1)
    want = np.sort(dists)[:k]
    np.testing.assert_allclose(sorted(dd for dd, _ in got), want,
                               rtol=1e-9, atol=1e-12)
    for dd, idx in got:                     # returned indices are genuine
        assert dd == pytest.approx(dists[idx])


@SET
@given(n=st.integers(1, 60), d=st.integers(1, 5),
       seed=st.integers(0, 2**31 - 1))
def test_kdtree_nn_matches_brute_force(n, d, seed):
    from deeplearning4j_tpu.clustering.trees import KDTree
    rng = np.random.default_rng(seed)
    pts = rng.random((n, d))
    q = rng.random(d)
    dist, idx = KDTree(pts).nn(q)
    dists = np.linalg.norm(pts - q, axis=1)
    assert dist == pytest.approx(dists.min())
    assert dists[idx] == pytest.approx(dists.min())


# --------------------------------------------------------------------------
# CSV record reader: numeric matrices survive a write/read round-trip
# --------------------------------------------------------------------------
@SET
@given(n=st.integers(1, 20), f=st.integers(1, 6),
       seed=st.integers(0, 2**31 - 1))
def test_csv_record_reader_round_trip(tmp_path_factory, n, f, seed):
    from deeplearning4j_tpu.datasets.records import CSVRecordReader
    rng = np.random.default_rng(seed)
    raw = rng.standard_normal((n, f)) * 100
    text = "\n".join(",".join(f"{v:.6f}" for v in row) for row in raw)
    # ground truth = exactly what the file says
    m = np.asarray([[float(v) for v in line.split(",")]
                    for line in text.splitlines()])
    p = tmp_path_factory.mktemp("csv") / "m.csv"
    p.write_text(text + "\n")
    rows = [[float(v) for v in rec] for rec in CSVRecordReader(str(p))]
    # the reader parses to float32 (DataSet feature dtype) — exact to f32
    np.testing.assert_allclose(np.asarray(rows), m, rtol=2e-7, atol=1e-7)


# --------------------------------------------------------------------------
# Graph walks, k-means, and text vectorizer laws
# --------------------------------------------------------------------------
@SET
@given(n=st.integers(2, 15), extra=st.integers(0, 20), wl=st.integers(1, 8),
       seed=st.integers(0, 2**31 - 1))
def test_random_walks_stay_on_edges(n, extra, wl, seed):
    from deeplearning4j_tpu.graph.graph import Graph
    from deeplearning4j_tpu.graph.walks import RandomWalkIterator
    rng = np.random.default_rng(seed)
    g = Graph(n)
    edges = set()
    for i in range(n):                     # ring keeps it connected
        g.add_edge(i, (i + 1) % n)
        edges |= {(i, (i + 1) % n), ((i + 1) % n, i)}
    for _ in range(extra):
        a, b = rng.integers(0, n, 2)
        g.add_edge(int(a), int(b))
        edges |= {(int(a), int(b)), (int(b), int(a))}
    it = RandomWalkIterator(g, wl, seed=seed)
    starts = []
    while it.has_next():
        walk = list(it.next())
        starts.append(walk[0])
        # walk_length counts NODES (reference RandomWalkIterator semantics)
        assert len(walk) == max(1, wl)
        for a, b in zip(walk, walk[1:]):
            assert (a, b) in edges or a == b   # self-loop fallback
    assert sorted(starts) == list(range(n))    # one walk per vertex


@SET
@given(n=st.integers(6, 60), d=st.integers(1, 4), k=st.integers(1, 4),
       seed=st.integers(0, 2**31 - 1))
def test_kmeans_assignments_are_nearest_center(n, d, k, seed):
    from deeplearning4j_tpu.clustering.kmeans import KMeansClustering
    rng = np.random.default_rng(seed)
    x = rng.random((n, d)).astype(np.float32)   # the impl computes in f32
    k = min(k, n)
    km = KMeansClustering(k, seed=seed)
    km.fit(x)
    assign = np.asarray(km.predict(x))
    centers = np.asarray(km.centers)
    d2 = ((x[:, None, :] - centers[None]) ** 2).sum(-1)
    np.testing.assert_allclose(d2[np.arange(n), assign], d2.min(1),
                               rtol=1e-5, atol=1e-7)
    # cost is the total squared distance to assigned centers (f32 math)
    assert km.cost == pytest.approx(float(d2.min(1).sum()), rel=1e-3) or \
        km.cost == pytest.approx(float(d2.min(1).mean()), rel=1e-3)


_DOC = st.lists(st.sampled_from("cat dog fish bird tree sun moon".split()),
                min_size=1, max_size=12).map(" ".join)


@SET
@given(docs=st.lists(_DOC, min_size=1, max_size=8))
def test_bow_counts_match_manual(docs):
    from deeplearning4j_tpu.text.vectorizers import BagOfWordsVectorizer
    bow = BagOfWordsVectorizer().fit(docs)
    for doc in docs:
        vec = np.asarray(bow.transform(doc))
        assert vec.sum() == len(doc.split())
        for w in set(doc.split()):
            if bow.vocab.contains_word(w) if hasattr(bow.vocab, "contains_word") else True:
                idx = bow.vocab.word_for(w).index if hasattr(bow.vocab, "word_for") else None
                if idx is not None:
                    assert vec[idx] == doc.split().count(w)


import functools


@functools.lru_cache(maxsize=1)
def _fixture_dictionary():
    import os

    from deeplearning4j_tpu.text.ja_dictionary import compile_dictionary
    return compile_dictionary(
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "fixtures", "ja_dict"))


@SET
@given(s=_JA)
def test_compiled_dictionary_segmentation_is_lossless(s):
    """Same losslessness law over the mecab-format COMPILED dictionary
    path (tests/fixtures/ja_dict) as over the builtin lexicon — the
    ingestion pipeline must never drop or duplicate characters either."""
    from deeplearning4j_tpu.text.ja_lattice import JapaneseLatticeTokenizer
    toks = JapaneseLatticeTokenizer(
        s, dictionary=_fixture_dictionary()).get_tokens()
    assert "".join(toks) == s
