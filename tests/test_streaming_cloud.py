"""Streaming pipelines, NTP time source, cloud provisioning/object store
(VERDICT r2 missing item 8 + NTP row). Mirrors reference test patterns:
embedded broker in-process (EmbeddedKafkaCluster role), fake NTP server,
provisioning exercised through the local command runner."""
import socket
import struct
import threading
import time

import numpy as np
import pytest

from deeplearning4j_tpu import (InputType, MultiLayerNetwork,
                                NeuralNetConfiguration)
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.streaming import (InMemoryBroker,
                                          StreamingInferencePipeline,
                                          StreamingTrainingPipeline, serde)


def _net():
    conf = (NeuralNetConfiguration.Builder().seed(3)
            .updater("adam").learning_rate(0.02).list()
            .layer(0, DenseLayer(n_out=8, activation="relu"))
            .layer(1, OutputLayer(n_out=2, activation="softmax",
                                  loss_function="mcxent"))
            .set_input_type(InputType.feed_forward(4))
            .build())
    return MultiLayerNetwork(conf).init()


class TestSerde:
    def test_array_round_trip(self):
        a = np.random.default_rng(0).random((3, 4)).astype(np.float32)
        assert np.array_equal(serde.decode_array(serde.encode_array(a)), a)

    def test_dataset_round_trip_with_masks(self):
        r = np.random.default_rng(0)
        ds = DataSet(r.random((4, 3)).astype(np.float32),
                     r.random((4, 2)).astype(np.float32),
                     np.ones((4, 3), np.float32), None)
        ds2 = serde.decode_dataset(serde.encode_dataset(ds))
        assert np.array_equal(ds2.features, ds.features)
        assert np.array_equal(ds2.labels, ds.labels)
        assert np.array_equal(ds2.features_mask, ds.features_mask)
        assert ds2.labels_mask is None

    def test_record_round_trip(self):
        vals = [1.5, -2.0, 3.25]
        assert serde.decode_record(serde.encode_record(vals)) == vals


class TestStreamingPipelines:
    def test_inference_pipeline_end_to_end(self):
        net = _net()
        broker = InMemoryBroker()
        out_sub = broker.subscribe("predictions")
        pipe = StreamingInferencePipeline(net, broker).start()
        try:
            rng = np.random.default_rng(0)
            batches = [rng.random((5, 4)).astype(np.float32)
                       for _ in range(3)]
            for b in batches:
                broker.publish("features", serde.encode_array(b))
            preds = []
            deadline = time.time() + 30
            while len(preds) < 3 and time.time() < deadline:
                p = out_sub.get(timeout=0.2)
                if p is not None:
                    preds.append(serde.decode_array(p))
            assert len(preds) == 3
            for b, p in zip(batches, preds):
                expect = np.asarray(net.output(b))
                assert p.shape == (5, 2)
                assert np.allclose(p, expect, atol=1e-5)
        finally:
            pipe.stop()

    def test_training_pipeline_fits_online(self):
        net = _net()
        broker = InMemoryBroker()
        pipe = StreamingTrainingPipeline(net, broker, score_topic="scores")
        score_sub = broker.subscribe("scores")
        pipe.start()
        try:
            rng = np.random.default_rng(1)
            x = rng.random((64, 4)).astype(np.float32)
            y = np.eye(2, dtype=np.float32)[(x.sum(1) > 2).astype(int)]
            for _ in range(10):
                broker.publish("train", serde.encode_dataset(DataSet(x, y)))
            deadline = time.time() + 60
            scores = []
            while len(scores) < 10 and time.time() < deadline:
                p = score_sub.get(timeout=0.2)
                if p is not None:
                    scores.append(np.frombuffer(p, np.float64)[0])
            assert pipe.batches_fit == 10
            assert scores[-1] < scores[0]   # online training reduced loss
        finally:
            pipe.stop()

    def test_kafka_broker_gated(self):
        from deeplearning4j_tpu.streaming import KafkaBroker
        with pytest.raises(ImportError, match="kafka-python"):
            KafkaBroker()

    def test_kafka_broker_protocol_contract(self, monkeypatch):
        """Contract test against a kafka-python API stub (the reference
        tests against EmbeddedKafkaCluster — dl4j-streaming
        src/test/.../embedded/; no Kafka client ships in this image, so
        the stub pins every interaction KafkaBroker makes with the
        client API: producer construction args, async send(topic, bytes),
        flush-on-close, consumer construction with earliest offset, and
        the pump thread delivering msg.value)."""
        import sys
        import time
        import types

        sent, flushed, closed = [], [], []

        class FakeProducer:
            def __init__(self, bootstrap_servers=None):
                sent.append(("init", bootstrap_servers))

            def send(self, topic, payload):
                sent.append((topic, payload))

            def flush(self):
                flushed.append(True)

            def close(self):
                closed.append(True)

        class FakeMsg:
            def __init__(self, value):
                self.value = value

        class FakeConsumer:
            created = []

            def __init__(self, topic, bootstrap_servers=None,
                         auto_offset_reset=None):
                FakeConsumer.created.append(
                    (topic, bootstrap_servers, auto_offset_reset))
                self._msgs = [FakeMsg(b"m1"), FakeMsg(b"m2")]

            def __iter__(self):
                return iter(self._msgs)

        fake = types.ModuleType("kafka")
        fake.KafkaProducer = FakeProducer
        fake.KafkaConsumer = FakeConsumer
        monkeypatch.setitem(sys.modules, "kafka", fake)

        from deeplearning4j_tpu.streaming import KafkaBroker
        b = KafkaBroker(bootstrap_servers="broker:9092")
        assert sent == [("init", "broker:9092")]
        b.publish("ndarray-topic", b"payload")
        assert sent[-1] == ("ndarray-topic", b"payload")
        assert not flushed            # publish is async (batched)
        b.flush()
        assert flushed == [True]
        sub = b.subscribe("ndarray-topic")
        assert FakeConsumer.created == [
            ("ndarray-topic", "broker:9092", "earliest")]
        got = {sub.get(timeout=2), sub.get(timeout=2)}
        assert got == {b"m1", b"m2"}
        b.close()
        assert closed == [True] and len(flushed) == 2  # flush-on-close


class TestNTPTimeSource:
    def _fake_ntp_server(self, offset_s):
        """Minimal SNTP responder applying a fixed clock offset."""
        sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        sock.bind(("127.0.0.1", 0))
        port = sock.getsockname()[1]

        def serve():
            try:
                data, addr = sock.recvfrom(512)
                now = time.time() + offset_s + 2208988800
                sec = int(now)
                frac = int((now - sec) * 2**32)
                resp = bytearray(48)
                resp[0] = 0x1C            # LI=0 VN=3 Mode=4 (server)
                struct.pack_into("!II", resp, 32, sec, frac)
                struct.pack_into("!II", resp, 40, sec, frac)
                sock.sendto(bytes(resp), addr)
            finally:
                sock.close()

        threading.Thread(target=serve, daemon=True).start()
        return port

    def test_offset_measured_from_server(self):
        from deeplearning4j_tpu.parallel import NTPTimeSource
        port = self._fake_ntp_server(offset_s=120.0)
        ts = NTPTimeSource(server="127.0.0.1", port=port,
                           update_frequency_ms=10 ** 9)
        assert abs(ts.offset_millis() - 120_000) < 2_000
        assert abs(ts.current_time_millis()
                   - (time.time() + 120.0) * 1000) < 2_000

    def test_unreachable_server_falls_back_to_system_clock(self):
        from deeplearning4j_tpu.parallel import (NTPTimeSource,
                                                 SystemClockTimeSource)
        ts = NTPTimeSource(server="127.0.0.1", port=1, timeout=0.3,
                           update_frequency_ms=10 ** 9)
        assert ts.offset_millis() == 0.0
        sys_ts = SystemClockTimeSource()
        assert abs(ts.current_time_millis()
                   - sys_ts.current_time_millis()) < 1_000


class TestCloud:
    def test_local_object_store_round_trip(self, tmp_path):
        from deeplearning4j_tpu.cloud import LocalFSObjectStore
        store = LocalFSObjectStore(tmp_path / "store")
        store.put("data/a.bin", b"hello")
        store.put("data/b.bin", b"world")
        store.put("other/c.bin", b"!")
        assert store.get("data/a.bin") == b"hello"
        assert store.list_keys("data/") == ["data/a.bin", "data/b.bin"]
        store.delete("data/a.bin")
        assert store.list_keys("data/") == ["data/b.bin"]
        with pytest.raises(ValueError, match="escapes"):
            store.put("../evil", b"x")

    def test_object_store_dataset_iterator(self, tmp_path):
        from deeplearning4j_tpu.cloud import (LocalFSObjectStore,
                                              ObjectStoreDataSetIterator)
        store = LocalFSObjectStore(tmp_path / "store")
        rng = np.random.default_rng(0)
        for i in range(3):
            ds = DataSet(rng.random((4, 3)).astype(np.float32),
                         rng.random((4, 2)).astype(np.float32))
            store.put(f"ds/batch_{i}.npz", serde.encode_dataset(ds))
        it = ObjectStoreDataSetIterator(store, "ds/")
        batches = list(it)
        assert len(batches) == 3
        assert batches[0].features.shape == (4, 3)
        it.reset()
        assert it.has_next()

    def test_provisioner_local_runner_and_launch_commands(self, tmp_path):
        from deeplearning4j_tpu.cloud import (ClusterProvisioner, ClusterSpec,
                                              LocalCommandRunner)
        marker = tmp_path / "provisioned.txt"
        spec = ClusterSpec(["hostA", "hostB"],
                           setup_commands=[f"echo ok >> {marker}"],
                           env={"EXTRA": "1"})
        prov = ClusterProvisioner(
            spec, runner_factory=lambda host: LocalCommandRunner())
        results = prov.provision()
        assert set(results) == {"hostA", "hostB"}
        assert marker.read_text().count("ok") == 2
        launches = prov.launch_commands("python worker.py")
        assert len(launches) == 2
        host0, cmd0 = launches[0]
        assert host0 == "hostA"
        assert "DL4J_TPU_COORDINATOR=hostA:8476" in cmd0
        assert "DL4J_TPU_PROCESS_ID=0" in cmd0
        assert "DL4J_TPU_NUM_PROCESSES=2" in cmd0
        assert "EXTRA=1" in cmd0
        assert cmd0.endswith("python worker.py")

    def test_provisioner_fails_fast(self):
        from deeplearning4j_tpu.cloud import (ClusterProvisioner, ClusterSpec,
                                              LocalCommandRunner)
        spec = ClusterSpec(["h"], setup_commands=["false"])
        prov = ClusterProvisioner(
            spec, runner_factory=lambda host: LocalCommandRunner())
        with pytest.raises(RuntimeError, match="provisioning h failed"):
            prov.provision()

    def test_s3_backend_with_injected_client(self):
        from deeplearning4j_tpu.cloud import S3ObjectStore

        class FakeS3:
            def __init__(self):
                self.objs = {}

            def put_object(self, Bucket, Key, Body):
                self.objs[(Bucket, Key)] = Body

            def get_object(self, Bucket, Key):
                import io
                return {"Body": io.BytesIO(self.objs[(Bucket, Key)])}

            def list_objects_v2(self, Bucket, Prefix):
                return {"Contents": [
                    {"Key": k} for (b, k) in self.objs
                    if b == Bucket and k.startswith(Prefix)]}

            def delete_object(self, Bucket, Key):
                del self.objs[(Bucket, Key)]

        store = S3ObjectStore("bkt", client=FakeS3())
        store.put("p/x", b"data")
        assert store.get("p/x") == b"data"
        assert store.list_keys("p/") == ["p/x"]
        store.delete("p/x")
        assert store.list_keys("p/") == []

    def test_create_instances_command_rendered(self):
        from deeplearning4j_tpu.cloud import create_instances_command
        cmds = create_instances_command("trainer", "us-central2-b",
                                        accelerator_type="v5e-8", count=2)
        assert len(cmds) == 2
        assert "tpu-vm create trainer-0" in cmds[0]
        assert "--accelerator-type=v5e-8" in cmds[0]


def test_inference_pipeline_surfaces_bad_payload_error():
    net = _net()
    broker = InMemoryBroker()
    pipe = StreamingInferencePipeline(net, broker).start()
    broker.publish("features", b"definitely not npz")
    deadline = time.time() + 20
    while pipe.error() is None and time.time() < deadline:
        time.sleep(0.05)
    assert pipe.error() is not None
    with pytest.raises(Exception):
        pipe.stop()
