"""Golden-model regression tests: restore COMMITTED checkpoint zips and
assert config/params/updater identity and identical outputs — so the
checkpoint format cannot silently drift between rounds.

reference: deeplearning4j-core regressiontest/RegressionTest050.java (restores
zips produced by released versions and asserts config+params+updater
identity). Fixture generator: tests/fixtures/make_golden_models.py.
"""
import json
import os
import zipfile

import numpy as np
import pytest

from deeplearning4j_tpu.util import model_serializer as ms

GOLDEN = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "fixtures", "golden")

with open(os.path.join(GOLDEN, "manifest.json")) as _fh:
    MANIFEST = json.load(_fh)


def _restore(name):
    path = os.path.join(GOLDEN, f"{name}.zip")
    if MANIFEST[name]["type"] == "ComputationGraph":
        return ms.restore_computation_graph(path)
    return ms.restore_multi_layer_network(path)


@pytest.mark.parametrize("name", ["mlp", "lenet", "lstm", "cg"])
def test_golden_restore_params_and_output(name):
    net = _restore(name)
    io = np.load(os.path.join(GOLDEN, f"{name}_io.npz"))
    # exact param identity with the committed flat vector
    np.testing.assert_array_equal(np.asarray(net.params(), np.float32),
                                  io["params"].astype(np.float32))
    # counters restored through the config JSON
    assert net.conf.iteration_count == MANIFEST[name]["iteration_count"]
    assert int(net.num_params()) == MANIFEST[name]["num_params"]
    # identical inference output (same platform/dtype as generation: cpu f32)
    out = net.output(io["x"])
    if MANIFEST[name]["type"] == "ComputationGraph":
        out = out[0]
    np.testing.assert_allclose(np.asarray(out), io["y"], rtol=1e-6,
                               atol=1e-6)


@pytest.mark.parametrize("name", ["mlp", "cg"])
def test_golden_updater_state_restored(name):
    net = _restore(name)
    leaves = [np.asarray(l) for l in
              __import__("jax").tree_util.tree_leaves(net._updater_state)]
    # trained nets must restore non-trivial updater state (adam/nesterovs
    # moments are nonzero after 3 steps)
    assert any(np.abs(l).sum() > 0 for l in leaves if l.size)


@pytest.mark.parametrize("name", ["mlp", "lenet", "lstm", "cg"])
def test_golden_zip_layout_stable(name):
    """The reference zip entry names are the wire format — keep them."""
    with zipfile.ZipFile(os.path.join(GOLDEN, f"{name}.zip")) as zf:
        names = set(zf.namelist())
    assert "configuration.json" in names
    assert "coefficients.bin" in names
    assert "updaterState.bin" in names


@pytest.mark.parametrize("name", ["mlp", "cg"])
def test_golden_restore_resumes_training(name):
    """A restored model must keep training (params+updater are a complete
    resume state)."""
    net = _restore(name)
    io = np.load(os.path.join(GOLDEN, f"{name}_io.npz"))
    x = io["x"]
    rng = np.random.default_rng(0)
    if MANIFEST[name]["type"] == "ComputationGraph":
        from deeplearning4j_tpu.datasets.dataset import MultiDataSet
        y = np.eye(4, dtype=np.float32)[rng.integers(0, 4, x.shape[0])]
        data = MultiDataSet([x], [y])
    else:
        from deeplearning4j_tpu.datasets.dataset import DataSet
        y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, x.shape[0])]
        data = DataSet(x, y)
    it0 = net.conf.iteration_count
    net.fit(data)
    assert net.conf.iteration_count == it0 + 1
