"""Speculative decoding pins (ISSUE 5 acceptance criteria).

  (a) Bit-identity: the speculative greedy token stream is IDENTICAL to
      plain greedy decode — solo generate(), batched generate_batch(),
      solo and co-batched through ContinuousDecodeServer (BOTH cache
      layouts: fixed-slot and paged block-table — the paged-specific
      pins live in tests/test_paged.py), for K in {2, 4, 8}, for BOTH
      draft sources (NGramDraft prompt-lookup and ModelDraft
      small-model), and across a mid-stream hot swap.
      Acceptance-by-exact-argmax-match makes the stream the verify
      program's own argmax chain by construction — a draft only changes
      the dispatch count — and these pins hold it to the plain decode
      programs' chains across dispatch widths.
  (b) Amortization: a perfectly-aligned draft (the target model drafting
      for itself) accepts K tokens per dispatch — dispatches/token
      = 1/K; a garbage draft still advances >= 1 token per dispatch.
  (c) Speculation x faults: FaultInjector at `serve.batch` during a
      verify dispatch — a retried transient keeps the stream
      bit-identical; a terminal fault fails the slot LOUDLY and resets
      state (the PR 4 plain-decode pin, re-proven under speculation).
  (d) Speculation metrics (acceptance rate, tokens/dispatch) ride the
      existing ServingMetrics -> ui/stats storage path.
"""
import time

import numpy as np
import pytest

from deeplearning4j_tpu.common.resilience import (FaultInjected,
                                                  FaultInjector,
                                                  RetryPolicy)
from deeplearning4j_tpu.models.zoo.transformer import TransformerLM
from deeplearning4j_tpu.serving import (ContinuousDecodeServer, ModelDraft,
                                        NGramDraft, Speculator)


def _lm(seed=3, max_len=64):
    return TransformerLM(64, d_model=32, n_heads=2, n_layers=2,
                         max_len=max_len, seed=seed)


def _draft_lm(seed=21):
    """A genuinely SMALLER draft model (the Leviathan setting); max_len
    covers the target's plus the speculative overhang."""
    return TransformerLM(64, d_model=16, n_heads=2, n_layers=1,
                         max_len=80, seed=seed)


def _prompt(seed=4, n=5):
    return np.random.default_rng(seed).integers(1, 64, n).tolist()


# ---------------------------------------------------------------------------
# draft sources (host-side behavior)
# ---------------------------------------------------------------------------
class TestNGramDraft:
    def test_prompt_lookup_proposes_continuation(self):
        d = NGramDraft(n=3)
        d.start("r", [1, 2, 3, 4, 5, 1, 2, 3])
        # suffix [1,2,3] occurred at the start; continuation is [4,5,...]
        assert d.propose("r", 3) == [4, 5, 1]
        d.stop("r")

    def test_most_recent_match_wins(self):
        d = NGramDraft(n=2)
        d.start("r", [7, 8, 1, 7, 8, 2, 7, 8])
        assert d.propose("r", 1) == [2]     # recency, not first occurrence

    def test_no_match_returns_empty(self):
        d = NGramDraft(n=3, min_match=2)
        d.start("r", [1, 2, 3, 4])
        assert d.propose("r", 4) == []

    def test_observe_extends_history(self):
        d = NGramDraft(n=2)
        d.start("r", [5, 6])
        d.observe("r", [9, 5, 6])
        assert d.propose("r", 1) == [9]

    def test_stop_is_idempotent(self):
        d = NGramDraft()
        d.start("r", [1])
        d.stop("r")
        d.stop("r")                          # no KeyError


# ---------------------------------------------------------------------------
# (a) bit-identity: generate / generate_batch
# ---------------------------------------------------------------------------
class TestGenerateSpeculative:
    def test_ngram_bit_identical_across_k(self):
        lm = _lm()
        p = _prompt()
        plain = lm.generate(p, 20, use_cache=True)
        for k in (2, 4, 8):
            assert lm.generate(p, 20, draft=NGramDraft(),
                               speculate_k=k) == plain

    def test_k1_degenerates_to_plain_decode(self):
        lm = _lm()
        p = _prompt()
        assert lm.generate(p, 12, draft=NGramDraft(),
                           speculate_k=1) == lm.generate(p, 12,
                                                         use_cache=True)

    def test_model_draft_bit_identical(self):
        lm = _lm()
        p = _prompt()
        plain = lm.generate(p, 16, use_cache=True)
        assert lm.generate(p, 16, draft=ModelDraft(_draft_lm()),
                           speculate_k=4) == plain

    def test_speculator_bundle_accepted(self):
        lm = _lm()
        p = _prompt()
        spec = Speculator(NGramDraft(), k=4)
        assert lm.generate(p, 10, draft=spec) == lm.generate(
            p, 10, use_cache=True)

    def test_generate_batch_both_sources(self):
        lm = _lm()
        prompts = np.random.default_rng(5).integers(
            1, 64, (3, 4)).astype(np.int32)
        plain = lm.generate_batch(prompts, max_new_tokens=12)
        for draft in (NGramDraft(), ModelDraft(_draft_lm())):
            got = lm.generate_batch(prompts, max_new_tokens=12,
                                    draft=draft, speculate_k=4)
            assert np.array_equal(got, plain)

    def test_greedy_only(self):
        lm = _lm()
        with pytest.raises(ValueError, match="greedy-only"):
            lm.generate(_prompt(), 8, temperature=0.7, draft=NGramDraft())
        with pytest.raises(ValueError, match="greedy-only"):
            lm.generate_batch(np.asarray([[1, 2]], np.int32), 8,
                              temperature=0.7, draft=NGramDraft())

    def test_max_len_guard(self):
        lm = _lm()
        with pytest.raises(ValueError, match="max_len"):
            lm.generate([1] * 10, 60, draft=NGramDraft())


# ---------------------------------------------------------------------------
# (a)+(b) serving: solo, co-batched, swap, amortization
# ---------------------------------------------------------------------------
class TestServerSpeculative:
    def test_solo_and_join_bit_identical_across_k(self):
        """For K in {2,4,8}: a speculative solo stream matches plain
        decode, and a request JOINING a running speculative batch matches
        its own solo stream (the continuous-decode pin, under ragged
        multi-token slot advance)."""
        lm = _lm()
        rng = np.random.default_rng(4)
        pa = rng.integers(1, 64, 5).tolist()
        pb = rng.integers(1, 64, 8).tolist()
        plain = lm.generate(pa, 10, use_cache=True)
        for k in (2, 4, 8):
            with ContinuousDecodeServer(
                    lm, slots=4, prompt_buckets=(4, 8),
                    speculate=Speculator(NGramDraft(), k=k)) as srv:
                solo = srv.generate(pa, 10, timeout=60)
                flong = srv.submit(pb, 24)      # running batch
                time.sleep(0.05)
                fa = srv.submit(pa, 10)         # joins mid-flight
                joined = fa.result(60)
                flong.result(60)
            assert solo == plain
            assert joined == solo

    def test_model_draft_server_bit_identical(self):
        lm = _lm()
        p = _prompt()
        with ContinuousDecodeServer(
                lm, slots=2, prompt_buckets=(8,),
                speculate=Speculator(ModelDraft(_draft_lm()), k=4)) as srv:
            got = srv.generate(p, 14, timeout=60)
        assert got == lm.generate(p, 14, use_cache=True)

    def test_paged_server_bit_identical_both_sources(self):
        """Speculation over the PAGED cache (ISSUE 10 — the block-table
        verify twin; the heavy pins live in tests/test_paged.py): same
        stream as plain greedy for both draft sources through
        `ContinuousDecodeServer(paged=True, speculate=...)`."""
        lm = _lm()
        p = _prompt()
        plain = lm.generate(p, 14, use_cache=True)
        for draft in (NGramDraft(), ModelDraft(_draft_lm())):
            with ContinuousDecodeServer(
                    lm, slots=2, prompt_buckets=(8,), paged=True,
                    block_size=4, n_blocks=40,
                    speculate=Speculator(draft, k=4)) as srv:
                got = srv.generate(p, 14, timeout=60)
                assert srv._pool.blocks_in_use == 0
            assert got == plain

    def test_equal_arrival_matches_generate_batch(self):
        lm = _lm()
        prompts = np.random.default_rng(5).integers(
            1, 64, (4, 4)).astype(np.int32)
        expect = lm.generate_batch(prompts, max_new_tokens=8)
        with ContinuousDecodeServer(
                lm, slots=4, prompt_buckets=(4,),
                speculate=Speculator(NGramDraft(), k=4)) as srv:
            futs = [srv.submit(prompts[i], 8) for i in range(4)]
            rows = [f.result(60) for f in futs]
        for i in range(4):
            assert rows[i] == expect[i].tolist()

    def test_self_draft_accepts_k_per_dispatch(self):
        """The target drafting for itself = every draft matches: exactly
        K accepted tokens per dispatch, dispatches/token == 1/K — the
        amortization ceiling the dispatch-cost model predicts."""
        lm = _lm()
        k = 4
        with ContinuousDecodeServer(
                lm, slots=2, prompt_buckets=(8,),
                speculate=Speculator(ModelDraft(lm), k=k)) as srv:
            got = srv.generate(_prompt(), 21, timeout=60)
        assert got == lm.generate(_prompt(), 21, use_cache=True)
        snap = srv.metrics.snapshot()
        # 21 tokens: 1 at prefill, then 20 = 5 full-acceptance dispatches
        assert snap["spec_accepted_per_dispatch_mean"] == pytest.approx(k)
        assert snap["dispatches_per_token"] == pytest.approx(1.0 / k)
        assert snap["spec_acceptance_rate_mean"] == pytest.approx(1.0)
        # honesty: a MODEL draft pays its own device dispatches (~K-1 per
        # round + context ingestion) — the folded-in metric must show the
        # round-trip cost a host-side draft would not pay
        assert snap["draft_dispatches"] > 0
        assert snap["device_dispatches_per_token"] > \
            3 * snap["dispatches_per_token"]

    def test_garbage_draft_still_advances(self):
        """A draft that never matches still advances one (bonus) token
        per dispatch — speculation can degrade to plain-decode cost but
        never stall or corrupt."""

        class WorstDraft(NGramDraft):
            def propose(self, key, k):
                hist = self._hist[key]
                return [(hist[-1] + 1) % 3 for _ in range(k)]

        lm = _lm()
        p = _prompt()
        with ContinuousDecodeServer(
                lm, slots=2, prompt_buckets=(8,),
                speculate=Speculator(WorstDraft(), k=4)) as srv:
            got = srv.generate(p, 10, timeout=60)
        assert got == lm.generate(p, 10, use_cache=True)
        snap = srv.metrics.snapshot()
        assert snap["spec_accepted_per_dispatch_mean"] < 2.0
        assert snap["dispatches_per_token"] <= 1.0

    def test_swap_drain_speculative(self):
        """Dual-version drain under speculation: the in-flight stream
        finishes on pre-swap params bit-identical to a pre-swap solo run
        while a post-swap request gets the new params — draft + verify
        both evaluated under the slot's pinned version."""
        lm1, lm2 = _lm(3), _lm(11)
        rng = np.random.default_rng(10)
        pa = rng.integers(1, 64, 4).tolist()
        pb = rng.integers(1, 64, 4).tolist()
        with ContinuousDecodeServer(
                lm1, slots=2, prompt_buckets=(4,),
                speculate=Speculator(NGramDraft(), k=4)) as srv:
            solo_old = srv.generate(pa, 14, timeout=60)
            fa = srv.submit(pa, 14)
            time.sleep(0.03)                  # pa decoding on v0
            srv.swap(lm2)
            fb = srv.submit(pb, 5)            # admitted on v1
            ra, rb = fa.result(60), fb.result(60)
        assert ra == solo_old
        expect_new = lm2.generate_batch(np.asarray([pb], np.int32),
                                        max_new_tokens=5)
        assert rb == expect_new[0].tolist()
        assert srv.metrics.snapshot().get("failed", 0) == 0


# ---------------------------------------------------------------------------
# (c) speculation x faults
# ---------------------------------------------------------------------------
class TestSpeculationFaults:
    def test_retry_keeps_stream_bit_identical(self):
        """Transient fault at serve.batch on the FIRST verify dispatch
        (call 0 is the admission prefill): the retry re-runs the verify
        and the stream is unchanged."""
        lm = _lm()
        p = _prompt()
        inj = FaultInjector(seed=1).plan("serve.batch", on_call=1,
                                         exc=FaultInjected)
        rp = RetryPolicy(max_retries=3, base_delay=0.001,
                         retryable=(ConnectionError,))
        with ContinuousDecodeServer(
                lm, slots=2, prompt_buckets=(8,), fault_injector=inj,
                retry_policy=rp,
                speculate=Speculator(NGramDraft(), k=4)) as srv:
            got = srv.generate(p, 10, timeout=60)
        snap = srv.metrics.snapshot()
        assert got == lm.generate(p, 10, use_cache=True)
        assert snap.get("retries") == 1 and snap.get("failed", 0) == 0

    def test_terminal_fault_fails_loudly_and_recovers(self):
        lm = _lm()
        p = _prompt()
        inj = FaultInjector(seed=2).plan("serve.batch", on_call=1,
                                         exc=FaultInjected)
        with ContinuousDecodeServer(
                lm, slots=2, prompt_buckets=(8,), fault_injector=inj,
                speculate=Speculator(NGramDraft(), k=4)) as srv:
            f = srv.submit(p, 6)
            with pytest.raises(FaultInjected):
                f.result(60)
            # slot state reset (incl. the draft stream): serves again
            got = srv.generate(p, 6, timeout=60)
        assert got == lm.generate(p, 6, use_cache=True)
        assert srv.metrics.snapshot().get("failed") == 1


# ---------------------------------------------------------------------------
# (d) metrics through the UI storage path
# ---------------------------------------------------------------------------
class TestSpeculationMetrics:
    def test_spec_metrics_reach_ui_storage(self):
        from deeplearning4j_tpu.ui.stats import ServingStatsReporter
        from deeplearning4j_tpu.ui.storage import InMemoryStatsStorage
        lm = _lm()
        storage = InMemoryStatsStorage()
        rep = ServingStatsReporter(storage, session_id="spec_serve",
                                   model_info={"model": "lm-spec"})
        with ContinuousDecodeServer(
                lm, slots=2, prompt_buckets=(8,), stats_reporter=rep,
                report_every=1,
                speculate=Speculator(NGramDraft(), k=4)) as srv:
            srv.generate(_prompt(), 12, timeout=60)
        serving = storage.get_latest_update("spec_serve")["serving"]
        assert serving["spec_accepted_per_dispatch_mean"] >= 1.0
        assert 0.0 <= serving["spec_acceptance_rate_mean"] <= 1.0
        assert 0.0 < serving["dispatches_per_token"] <= 1.0
        assert serving["spec_tokens"] == serving["tokens_out"]

    def test_metrics_record_speculation_shape(self):
        from deeplearning4j_tpu.serving import ServingMetrics
        m = ServingMetrics(window=8)
        m.count("dispatches", 2)
        m.count("tokens_out", 6)
        m.record_speculation(4, 3, 3)
        m.record_speculation(2, 3, 1)
        snap = m.snapshot()
        assert snap["spec_accepted_per_dispatch_mean"] == 3.0
        assert snap["spec_acceptance_rate_mean"] == pytest.approx(2 / 3)
        assert snap["dispatches_per_token"] == pytest.approx(1 / 3)
        assert snap["spec_tokens"] == 6 and snap["spec_matched"] == 4
