"""Observability-layer pins (ISSUE 6 acceptance criteria).

  (a) Export schemas: Chrome trace-event JSON carries name/cat/ph/ts/dur
      on every complete event (loads in Perfetto), and the Prometheus
      text route on ui/server.py serves registry counters/summaries.
  (b) Correct nesting: a served request's queue-wait span sits inside
      its request span; a fused training dispatch sits inside its
      fused-group span.
  (c) Cost pins: a DISABLED tracer's span() is nanosecond-scale per
      call, and tracing (on or off) adds ZERO device dispatches — the
      obs package never imports jax/numpy (structural pin) and a traced
      serve run's dispatch counter equals an untraced one's.
  (d) MetricsRegistry storage keys through ui.stats.ServingStatsReporter
      are pinned so renames fail a test; SLO counters (deadline
      attainment, goodput) and the queue-depth-at-enqueue staleness fix
      are pinned through the real servers.
  (e) Flight recorder: rolling-p99 threshold arms the tracer for the
      next N spans and stores the capture.
"""
import contextlib
import json
import os
import time
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu import (InputType, MultiLayerNetwork,
                                NeuralNetConfiguration)
from deeplearning4j_tpu import obs
from deeplearning4j_tpu.models.zoo.transformer import TransformerLM
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.obs import FlightRecorder, MetricsRegistry, Tracer
from deeplearning4j_tpu.obs.registry import (default_registry, fmt,
                                             reset_default_registry,
                                             sanitize)
from deeplearning4j_tpu.serving import (ContinuousDecodeServer,
                                        InferenceServer, ServingMetrics)


def _mln(seed=7, n_in=6, n_out=4):
    conf = (NeuralNetConfiguration.Builder().seed(seed)
            .updater("adam").learning_rate(0.01).list()
            .layer(0, DenseLayer(n_out=16, activation="relu"))
            .layer(1, OutputLayer(n_out=n_out, activation="softmax",
                                  loss_function="mcxent"))
            .set_input_type(InputType.feed_forward(n_in))
            .build())
    return MultiLayerNetwork(conf).init()


def _lm(seed=3):
    return TransformerLM(64, d_model=16, n_heads=2, n_layers=1,
                         max_len=48, seed=seed)


@contextlib.contextmanager
def _global_tracer(tracer):
    """Swap the process-wide tracer (the one the fit loops record on)."""
    old = obs.TRACER
    obs.TRACER = tracer
    try:
        yield tracer
    finally:
        obs.TRACER = old


def _events(tracer, name=None, ph="X"):
    evs = [e for e in tracer.chrome_trace()["traceEvents"]
           if e.get("ph") == ph]
    return evs if name is None else [e for e in evs if e["name"] == name]


def _contains(outer, inner, slack_us=1.0):
    return (inner["ts"] >= outer["ts"] - slack_us
            and inner["ts"] + inner["dur"]
            <= outer["ts"] + outer["dur"] + slack_us)


# ---------------------------------------------------------------------------
# (a) export schemas
# ---------------------------------------------------------------------------
class TestTraceSchema:
    def test_chrome_trace_event_schema(self):
        """The pinned trace-event contract: complete events carry
        name/cat/ph/ts/dur (+pid/tid), metadata events name the tracks —
        exactly what Perfetto/chrome://tracing load."""
        t = Tracer(enabled=True)
        with t.span("outer", cat="test", track="lane", k=2):
            with t.span("inner", cat="test", track="lane"):
                pass
        t.instant("marker", cat="test")
        ct = t.chrome_trace(process_name="proc")
        assert set(ct) == {"traceEvents", "displayTimeUnit"}
        xs = [e for e in ct["traceEvents"] if e.get("ph") == "X"]
        assert len(xs) == 3          # outer, inner, marker(dur 0)
        for e in xs:
            for key in ("name", "cat", "ph", "ts", "dur", "pid", "tid",
                        "args"):
                assert key in e, f"missing {key} in {e}"
            assert isinstance(e["ts"], (int, float))
            assert isinstance(e["dur"], (int, float)) and e["dur"] >= 0
        metas = [e for e in ct["traceEvents"] if e.get("ph") == "M"]
        assert {m["name"] for m in metas} >= {"process_name",
                                              "thread_name"}
        # inner nests inside outer on the same tid
        outer = next(e for e in xs if e["name"] == "outer")
        inner = next(e for e in xs if e["name"] == "inner")
        assert outer["tid"] == inner["tid"]
        assert _contains(outer, inner)
        assert outer["args"]["k"] == 2

    def test_save_round_trips_as_json(self, tmp_path):
        t = Tracer(enabled=True)
        with t.span("a"):
            pass
        path = t.save(str(tmp_path / "t.trace.json"))
        with open(path) as fh:
            data = json.load(fh)
        assert any(e.get("ph") == "X" and e["name"] == "a"
                   for e in data["traceEvents"])

    def test_ring_is_bounded(self):
        t = Tracer(capacity=16, enabled=True)
        for i in range(100):
            t.emit(f"s{i}", i, 1)
        spans = t.spans()
        assert len(spans) == 16
        assert spans[0].name == "s84"       # oldest fell off the far end

    def test_registry_prometheus_text(self):
        reg = MetricsRegistry()
        reg.counter("serve.requests").inc(5)
        reg.gauge("queue.depth").set(3)
        res = reg.reservoir("latency_ms", window=16)
        for v in (1.0, 2.0, 3.0, 4.0):
            res.record(v)
        text = reg.prometheus_text(namespace="dl4j_tpu")
        assert "# TYPE dl4j_tpu_serve_requests counter" in text
        assert "dl4j_tpu_serve_requests 5" in text
        assert "# TYPE dl4j_tpu_queue_depth gauge" in text
        assert "dl4j_tpu_queue_depth 3.0" in text
        assert "# TYPE dl4j_tpu_latency_ms summary" in text
        assert 'dl4j_tpu_latency_ms{quantile="0.5"}' in text
        assert 'dl4j_tpu_latency_ms{quantile="0.99"}' in text
        assert "dl4j_tpu_latency_ms_count 4" in text

    def test_registry_kind_conflict_raises(self):
        reg = MetricsRegistry()
        reg.counter("x")
        with pytest.raises(TypeError):
            reg.gauge("x")
        reg.histogram("h")
        with pytest.raises(TypeError):
            reg.reservoir("h")

    def test_histogram_buckets_and_quantiles(self):
        """The fixed-bucket histogram kind (ISSUE 7): cumulative
        `_bucket{le=}`/`_sum`/`_count` exposition, interpolated
        quantile estimates, overflow clamped to the largest bound."""
        from deeplearning4j_tpu.obs import Histogram
        h = Histogram("lat", buckets=(1, 2, 5, 10))
        assert h.quantile(50) is None           # empty: no data
        for v in (0.5, 1.5, 3.0, 4.0, 7.0, 50.0):
            h.observe(v)
        assert h.counts() == [1, 1, 2, 1, 1]    # last = +Inf overflow
        assert h.total == 6 and h.sum == 66.0
        # interpolated within the (2, 5] bucket holding the median
        assert 2.0 < h.quantile(50) <= 5.0
        assert h.quantile(99) == 10.0           # overflow clamps
        assert h.mean() == pytest.approx(11.0)

    def test_histogram_prometheus_exposition(self):
        reg = MetricsRegistry()
        h = reg.histogram("req.ttft_ms", buckets=(1, 10, 100))
        h.observe(5.0)
        h.observe(500.0)
        text = reg.prometheus_text(namespace="dl4j_tpu")
        assert "# TYPE dl4j_tpu_req_ttft_ms histogram" in text
        assert 'dl4j_tpu_req_ttft_ms_bucket{le="1"} 0' in text
        assert 'dl4j_tpu_req_ttft_ms_bucket{le="10"} 1' in text
        assert 'dl4j_tpu_req_ttft_ms_bucket{le="100"} 1' in text
        assert 'dl4j_tpu_req_ttft_ms_bucket{le="+Inf"} 2' in text
        assert "dl4j_tpu_req_ttft_ms_sum 505.0" in text
        assert "dl4j_tpu_req_ttft_ms_count 2" in text
        snap = reg.snapshot()
        assert snap["req.ttft_ms_count"] == 2
        assert snap["req.ttft_ms_p50"] is not None

    def test_clock_sync_anchors_traces_for_alignment(self):
        """Trace-alignment fix (ISSUE 7): spans are timed on the bare
        monotonic clock, so two saved traces were un-alignable. Every
        chrome_trace() now carries a `clock_sync` metadata event whose
        `wallclock_ns_at_ts0` anchors ts=0 to the wall clock; two
        traces align by shifting one by the anchor difference."""
        t1 = Tracer(enabled=True)
        with t1.span("a"):
            pass
        time.sleep(0.05)
        t2 = Tracer(enabled=True)
        with t2.span("b"):
            pass

        def anchor(t):
            (cs,) = [e for e in t.chrome_trace()["traceEvents"]
                     if e.get("name") == "clock_sync"]
            assert cs["ph"] == "M"
            assert "wallclock_iso" in cs["args"]
            return (cs["args"]["wallclock_ns_at_ts0"],
                    cs["args"]["monotonic_ns_at_ts0"])
        w1, m1 = anchor(t1)
        w2, m2 = anchor(t2)
        # the anchors agree with the real elapsed time: wall-clock
        # difference == monotonic difference (same process, so the two
        # clocks tick together; 10ms slack for clock-read jitter)
        assert w2 > w1 and m2 > m1
        assert abs((w2 - w1) - (m2 - m1)) < 10e6
        # and the anchor is an actual recent wallclock time
        assert abs(time.time_ns() - w2) < 60e9

    def test_sanitize_and_fmt(self):
        assert sanitize("a.b-c d") == "a_b_c_d"
        assert sanitize("9lives")[0] == "_"
        assert fmt(None) is None
        assert fmt(1.23456) == 1.235
        assert fmt(1.23456, 1) == 1.2

    def test_histogram_bucketwise_merge_is_pooled(self):
        """The aggregability contract federation depends on (ISSUE 12):
        element-wise summing two histograms' bucket counts gives
        `bucket_quantile` results EQUAL to a single histogram that
        observed the pooled samples — merged counts ARE the pooled
        histogram's counts, so the invariant is exact, not
        approximate."""
        import random

        from deeplearning4j_tpu.obs import Histogram
        from deeplearning4j_tpu.obs.registry import bucket_quantile
        grid = (1, 5, 25, 100, 500)
        h1, h2, pooled = (Histogram(n, buckets=grid)
                          for n in ("a", "b", "p"))
        rng = random.Random("agg-pin")
        for _ in range(300):
            v = rng.uniform(0.0, 700.0)
            (h1 if rng.random() < 0.4 else h2).observe(v)
            pooled.observe(v)
        merged = [a + b for a, b in zip(h1.counts(), h2.counts())]
        assert merged == pooled.counts()
        assert sum(merged) == 300
        for q in (1, 25, 50, 75, 99):
            assert bucket_quantile(grid, merged, q) == \
                pooled.quantile(q)

    def test_chrome_trace_pid_and_instance_metadata(self):
        """Satellite pin (ISSUE 12): every event carries an explicit
        pid (settable, default 0) and process_name defaults to the
        tracer's instance name — the hooks merged multi-server traces
        need — while the default export stays schema-compatible with
        every existing consumer."""
        t = Tracer(enabled=True)
        with t.span("x"):
            pass
        ct = t.chrome_trace()
        assert all(e["pid"] == 0 for e in ct["traceEvents"])
        (pn,) = [e for e in ct["traceEvents"]
                 if e.get("ph") == "M" and e["name"] == "process_name"]
        assert pn["args"]["name"] == "deeplearning4j_tpu"

        ti = Tracer(enabled=True, instance="i3")
        with ti.span("y"):
            pass
        ct3 = ti.chrome_trace(pid=7)
        assert all(e["pid"] == 7 for e in ct3["traceEvents"])
        (pn3,) = [e for e in ct3["traceEvents"]
                  if e.get("ph") == "M" and e["name"] == "process_name"]
        assert pn3["args"]["name"] == "i3"
        (cs,) = [e for e in ct3["traceEvents"]
                 if e.get("ph") == "M" and e["name"] == "clock_sync"]
        assert cs["args"]["instance"] == "i3"

    def test_prometheus_instance_label(self):
        """instance= labels EVERY exposition sample (counter, gauge,
        histogram buckets incl. +Inf, summary quantiles) and composes
        with existing labels; default output is label-free."""
        reg = MetricsRegistry()
        reg.counter("c").inc(2)
        reg.gauge("g").set(1.5)
        reg.histogram("h", buckets=(1, 10)).observe(5.0)
        res = reg.reservoir("r", window=8)
        res.record(3.0)
        text = reg.prometheus_text(namespace="ns", instance="i0")
        assert 'ns_c{instance="i0"} 2' in text
        assert 'ns_g{instance="i0"} 1.5' in text
        assert 'ns_h_bucket{le="1",instance="i0"} 0' in text
        assert 'ns_h_bucket{le="+Inf",instance="i0"} 1' in text
        assert 'ns_h_count{instance="i0"} 1' in text
        assert 'ns_r{quantile="0.5",instance="i0"} 3.0' in text
        assert 'ns_r_count{instance="i0"} 1' in text
        plain = reg.prometheus_text(namespace="ns")
        assert "instance=" not in plain


class TestPrometheusRoute:
    def test_metrics_route_serves_registry(self):
        from deeplearning4j_tpu.ui import UIServer
        reg = MetricsRegistry()
        reg.counter("train.health.ok").inc(7)
        m = ServingMetrics(registry=reg, name="s1", slo_target_ms=50)
        m.record_request(10.0, tokens=4)
        server = UIServer(port=0).attach_metrics(reg).start()
        try:
            url = f"http://127.0.0.1:{server.port}/metrics"
            with urllib.request.urlopen(url) as r:
                assert r.headers["Content-Type"].startswith("text/plain")
                text = r.read().decode()
            assert "dl4j_tpu_train_health_ok 7" in text
            # ServingMetrics built over a shared registry exports its
            # counters on the same route, namespaced by endpoint name
            assert "dl4j_tpu_serving_s1_completed 1" in text
            assert "dl4j_tpu_serving_s1_slo_met 1" in text
            assert 'dl4j_tpu_serving_s1_latency_ms{quantile="0.5"} 10.0' \
                in text
        finally:
            server.stop()

    def test_metrics_route_with_instance_label(self):
        """attach_metrics(..., instance=) labels every sample — the
        federation-friendly exposition a fleet's per-replica routes
        serve, round-trippable by obs.fleet.parse_prometheus_text."""
        from deeplearning4j_tpu.obs.fleet import FleetView
        from deeplearning4j_tpu.ui import UIServer
        reg = MetricsRegistry()
        m = ServingMetrics(registry=reg, name="r0", slo_target_ms=50)
        m.record_request(10.0, tokens=4)
        server = UIServer(port=0).attach_metrics(
            reg, instance="replica-0").start()
        try:
            url = f"http://127.0.0.1:{server.port}/metrics"
            with urllib.request.urlopen(url) as r:
                text = r.read().decode()
            assert 'instance="replica-0"' in text
            assert 'dl4j_tpu_serving_r0_completed{instance="replica-0"}'\
                ' 1' in text
            fv = FleetView().add(
                "replica-0", text,
                strip_prefix="dl4j_tpu_serving_r0_")
            assert fv.counter("completed") == 1
        finally:
            server.stop()


# ---------------------------------------------------------------------------
# (b) correct nesting through the real servers / fit loops
# ---------------------------------------------------------------------------
class TestServedRequestTrace:
    def test_decode_request_spans_nest(self, tmp_path):
        t = Tracer(enabled=True)
        lm = _lm()
        with ContinuousDecodeServer(lm, slots=2, prompt_buckets=(8,),
                                    tracer=t) as srv:
            srv.generate([1, 2, 3], 6, timeout=120)
        req = _events(t, "serve.request")
        qw = _events(t, "serve.queue_wait")
        assert len(req) == 1 and len(qw) == 1
        assert req[0]["tid"] == qw[0]["tid"]    # same req-<id> lane
        assert _contains(req[0], qw[0])
        assert req[0]["args"]["tokens"] == 6
        # one span per decode iteration, tagged with occupancy and
        # accepted-token count (5 iterations: token 1 came from prefill)
        iters = _events(t, "decode.iteration")
        assert len(iters) == 5
        for e in iters:
            assert 0.0 < e["args"]["slot_occupancy"] <= 1.0
            assert e["args"]["accepted"] >= 1
        assert len(_events(t, "decode.prefill")) == 1
        assert len(_events(t, "decode.dispatch")) == 5
        # and the whole thing round-trips to a Perfetto-loadable file
        with open(t.save(str(tmp_path / "serve.trace.json"))) as fh:
            assert json.load(fh)["traceEvents"]

    def test_microbatch_request_spans_nest(self):
        t = Tracer(enabled=True)
        net = _mln()
        rng = np.random.default_rng(0)
        with InferenceServer(net, max_batch=4, max_wait_ms=1.0,
                             tracer=t) as srv:
            for _ in range(3):
                srv.predict(rng.standard_normal(6).astype(np.float32),
                            timeout=60)
        reqs = _events(t, "serve.request")
        qws = _events(t, "serve.queue_wait")
        assert len(reqs) == 3 and len(qws) == 3
        by_tid = {e["tid"]: e for e in reqs}
        for q in qws:
            assert _contains(by_tid[q["tid"]], q)
        # dispatch nests inside its batch span on the server lane
        batch = _events(t, "serve.batch")
        disp = _events(t, "serve.dispatch")
        assert batch and disp
        assert _contains(batch[0], disp[0])


class TestTrainingTrace:
    def test_fused_fit_spans_nest(self, tmp_path):
        from deeplearning4j_tpu.datasets.dataset import DataSet
        from deeplearning4j_tpu.datasets.iterators import \
            ListDataSetIterator
        rng = np.random.default_rng(1)
        x = rng.standard_normal((32, 6)).astype(np.float32)
        y = np.eye(4, dtype=np.float32)[rng.integers(0, 4, 32)]
        it = ListDataSetIterator(list(DataSet(x, y).batch_by(4)), 4)
        net = _mln().fused_steps(4)
        with _global_tracer(Tracer(enabled=True)) as t:
            net.fit(it, num_epochs=1)
        groups = _events(t, "train.fused_group")
        disp = _events(t, "train.dispatch")
        stage = _events(t, "train.stage")
        assert len(groups) == 2          # 8 batches / K=4
        assert len(disp) == 2 and len(stage) == 2
        for g in groups:
            assert g["args"]["k"] == 4
            assert any(_contains(g, d) for d in disp)
        # staging and dispatch never overlap: the staged group is handed
        # to exactly one dispatch
        assert all(not _contains(g, s) for g in groups for s in stage)
        assert _events(t, "train.compile")  # first build of the program
        with open(t.save(str(tmp_path / "train.trace.json"))) as fh:
            assert json.load(fh)["traceEvents"]

    def test_single_step_fit_emits_dispatch_spans(self):
        from deeplearning4j_tpu.datasets.dataset import DataSet
        rng = np.random.default_rng(2)
        x = rng.standard_normal((8, 6)).astype(np.float32)
        y = np.eye(4, dtype=np.float32)[rng.integers(0, 4, 8)]
        net = _mln()
        with _global_tracer(Tracer(enabled=True)) as t:
            net.fit(DataSet(x, y))
        assert len(_events(t, "train.dispatch")) == 1


# ---------------------------------------------------------------------------
# (c) cost pins: disabled overhead + zero device work
# ---------------------------------------------------------------------------
class TestCostPins:
    def test_disabled_span_is_nanosecond_scale(self):
        """The tentpole claim: a disabled tracer's span() is ONE
        attribute check returning a shared no-op. Pin the per-call cost
        well under 2 microseconds (measured ~0.1-0.2 us; min over trials
        rejects scheduler noise)."""
        t = Tracer(enabled=False)
        n = 50_000
        best = float("inf")
        for _ in range(5):
            t0 = time.perf_counter()
            for _ in range(n):
                t.span("x")
            best = min(best, (time.perf_counter() - t0) / n)
        assert best < 2e-6, f"disabled span() cost {best * 1e9:.0f}ns"
        assert len(t) == 0                    # nothing recorded
        # the with-statement path stays no-op too
        with t.span("x", k=1):
            pass
        assert len(t) == 0

    def test_obs_package_never_imports_device_code(self):
        """Structural zero-device-dispatch pin: recording a span or a
        metric can never touch jax/numpy because the obs package does
        not import them. Since ISSUE 15 this is a thin wrapper over
        the graftlint layering pass — tools/analyze/layers.toml's
        'obs-stdlib-only' rule is the single source of truth (the
        pass resolves relative AND function-local imports, which the
        old regex pin could only approximate); check_layer_rules
        raises if the rule is renamed away, so this cannot pass
        vacuously."""
        from tools.analyze import check_layer_rules
        findings = check_layer_rules(["obs-stdlib-only",
                                      "obs-below-serving"])
        assert not findings, \
            "\n".join(f"{f.path}:{f.line}: {f.message}"
                      for f in findings)

    def test_tracing_adds_zero_device_dispatches(self):
        """Same sequential workload through a traced and an untraced
        decode server: the dispatch counters must be IDENTICAL — spans
        observe the schedule, never alter it."""
        counts = {}
        for name, tracer in (("off", Tracer(enabled=False)),
                             ("on", Tracer(enabled=True))):
            lm = _lm()
            with ContinuousDecodeServer(lm, slots=2, prompt_buckets=(8,),
                                        tracer=tracer) as srv:
                for i in range(3):
                    srv.generate([1 + i, 2, 3], 5, timeout=120)
            snap = srv.metrics.snapshot()
            counts[name] = (snap["dispatches"], snap["tokens_out"])
        assert counts["on"] == counts["off"]


# ---------------------------------------------------------------------------
# (d) metrics: storage keys, SLO counters, queue-depth staleness fix
# ---------------------------------------------------------------------------
class TestMetricsPins:
    # the ONE export surface: every consumer (UI storage, bench.py,
    # tools/serve_ab.py, tools/obs_report.py) reads these names — a
    # rename must fail here before it silently breaks a dashboard
    PINNED_KEYS = (
        "completed", "latency_ms_p50", "latency_ms_p99",
        "queue_wait_ms_p50", "queue_wait_ms_p99",
        "queue_depth_last", "queue_depth_max",
        "batch_occupancy_mean", "batch_size_mean",
        "spec_accepted_per_dispatch_mean", "spec_acceptance_rate_mean",
        "dispatches_per_token", "device_dispatches_per_token",
        # fused decode windows (serving/decode.py fused_serve=K,
        # ISSUE 18): window count, realized decode iterations, and the
        # amortization ratio (~1.0 unfused, ~K fused) — consumed by
        # tools/serve_ab.py's fused_serve_vs_plain arm, bench.py's
        # fused_decode config, and the Prometheus route
        "fused_windows", "decode_iterations", "iterations_per_dispatch",
        # paged KV-cache pool view (serving/kvpool.py): arena pressure,
        # measured concurrency, prefix-cache hit rate, CoW and
        # memory-gate accounting — consumed by tools/serve_ab.py's
        # paged_vs_fixed arm and bench.py's paged_decode config
        "pool_blocks", "blocks_in_use_last", "blocks_in_use_max",
        "live_streams_max", "prefix_rows_hit", "prefix_rows_total",
        "prefix_hit_rate", "cow_copies", "blocked_on_memory",
        "shed_blocks",
        # overload-control view (serving/admission.py): shed-by-cause
        # counters, brownout deferral, chunk dispatches, the live
        # service-rate gauge, and the admission estimator's signed
        # (predicted - actual) error histogram — consumed by the
        # load_sweep/serve_ab overload A/Bs and the Prometheus route
        "shed_predicted", "shed_brownout", "deferred",
        "chunk_dispatches", "service_rate_tokens_per_sec",
        # prefix-hit priority admission (serving/decode.py, PR 10):
        # always-present since then but never pinned — surfaced by
        # the graftlint metrics-keys reverse check (ISSUE 15)
        "admitted_prefix_priority",
        # durable KV state (serving/kvstate.py): preempt/resume/migrate
        # event counts, host bytes spilled, restored-prefix hits —
        # consumed by tools/serve_ab.py's preempt_vs_shed arm and the
        # Prometheus route (eagerly created, so a server that never
        # preempted scrapes zero, not absence)
        "preempted", "resumed", "migrated", "migrated_out",
        "spill_bytes", "prefix_restore_hits",
        # fleet-control events (serving/fleet.py FleetManager):
        # spawn/drain/death, failover replays, canary rollbacks —
        # consumed by tools/fleet_report.py and the load_sweep
        # --fleet-control record (eagerly created: a fleet that never
        # failed over scrapes zero, not absence)
        "replica_spawned", "replica_drained", "replica_dead",
        "replica_degraded", "failover_resubmitted", "canary_rollbacks",
        # serving-wire transport (serving/wire.py RemoteReplica via the
        # fleet manager's metrics): reconnects, at-most-once resends,
        # refused migrations — consumed by tools/fleet_report.py and
        # the load_sweep --fleet-procs record (eagerly created: a fleet
        # that never lost a connection scrapes zero, not absence)
        "wire_reconnects", "wire_retries", "migrate_refused",
        # durable control plane (serving/fleetjournal.py + recovery
        # and epoch fencing in serving/fleet.py / serving/wire.py):
        # manager generation, recovery re-adoptions, fenced stale-
        # manager control ops, journal records — consumed by
        # tools/fleet_report.py's control section and the load_sweep
        # --chaos record (eagerly created: a fleet whose manager never
        # restarted scrapes zero, not absence)
        "manager_epoch", "replicas_adopted", "fenced_ops",
        "journal_records",
        # blast-radius containment (serving/fleet.py, ISSUE 17):
        # poison-pill quarantine verdicts, the spawn circuit breaker
        # (open events + live state gauge), fleet retry-budget denials,
        # degraded-mode time, infant deaths — consumed by
        # tools/fleet_report.py's containment section and the
        # load_sweep --cascade record (eagerly created: a fleet that
        # never contained anything scrapes zero, not absence)
        "requests_quarantined", "breaker_open_total", "breaker_state",
        "retry_budget_exhausted", "degraded_mode_ticks",
        "infant_deaths",
        # prefix-affinity routing + fleet prefix tier (serving/fleet.py
        # affinity policy, serving/decode.py prefix_export/prefix_adopt,
        # serving/wire.py PREFIX ops, ISSUE 20): routing verdicts and
        # cross-replica block traffic — consumed by
        # tools/fleet_report.py's control section and the load_sweep
        # --affinity record (eagerly created: a fleet that never
        # spilled or pulled scrapes zero, not absence)
        "routed_affinity", "routed_spill", "prefix_pull_hits",
        "prefix_pull_refused", "prefix_pull_bytes",
        "admission_error_ms_p50", "admission_error_ms_p99",
        "admission_error_ms_mean", "admission_error_ms_count",
        "slo_total", "slo_met", "slo_tokens_met", "slo_attainment",
        "ttft_ms_p50", "ttft_ms_p99", "ttft_ms_mean", "ttft_ms_count",
        "inter_token_ms_p50", "inter_token_ms_p99",
        "inter_token_ms_mean", "inter_token_ms_count",
    )

    # fleet federation read-outs (obs/fleet.py): ALWAYS-PRESENT keys on
    # FleetView.snapshot() — the tools/fleet_report.py surface and the
    # AutoscaleSignal's inputs; a rename must fail here before it
    # silently breaks the fleet report or the detector
    FLEET_PINNED_KEYS = (
        "fleet_instances", "fleet_slo_attainment",
        "fleet_goodput_tokens_per_sec", "autoscale_decision",
        "fleet_service_rate_tokens_per_sec", "fleet_shed_predicted",
        "fleet_sheds_total", "fleet_shed_share",
        "fleet_occupancy_mean", "fleet_tokens_out",
        # fleet-control event counters (serving/fleet.py): summed like
        # any counter; FleetManager.fleet_snapshot() overlays its own
        "fleet_replica_spawned", "fleet_replica_drained",
        "fleet_replica_dead", "fleet_failover_resubmitted",
        "fleet_canary_rollbacks",
        # serving-wire transport counters (serving/wire.py): summed the
        # same way, overlaid live by FleetManager.fleet_snapshot()
        "fleet_wire_reconnects", "fleet_wire_retries",
        "fleet_migrate_refused",
        # durable-control-plane counters (serving/fleetjournal.py and
        # the recovery/fencing paths): summed the same way, overlaid
        # live by FleetManager.fleet_snapshot()
        "fleet_manager_epoch", "fleet_replicas_adopted",
        "fleet_fenced_ops", "fleet_journal_records",
        # blast-radius containment counters (serving/fleet.py): summed
        # the same way; fleet_breaker_state is the per-instance MAX of
        # the breaker gauge (any open breaker reads open) until
        # FleetManager.fleet_snapshot() overlays its live state
        "fleet_requests_quarantined", "fleet_breaker_open_total",
        "fleet_retry_budget_exhausted", "fleet_degraded_mode_ticks",
        "fleet_infant_deaths", "fleet_breaker_state",
        # fused decode windows (serving/decode.py fused_serve=K):
        # window/iteration counters summed like any counter; the
        # amortization ratio is re-derived from the MERGED counters so
        # it weights instances by dispatch volume
        "fleet_fused_windows", "fleet_decode_iterations",
        "fleet_iterations_per_dispatch",
        # prefix-affinity routing + fleet prefix tier (ISSUE 20):
        # routed_* summed then overlaid live by the manager (its own
        # verbs); prefix_pull_* stay federated — the ADOPTING replica
        # counts hits/bytes/refusals
        "fleet_routed_affinity", "fleet_routed_spill",
        "fleet_prefix_pull_hits", "fleet_prefix_pull_refused",
        "fleet_prefix_pull_bytes",
    )

    def test_fleet_snapshot_keys_pinned(self):
        from deeplearning4j_tpu.obs.fleet import FleetView
        # empty fleet AND a populated one: the keys never depend on
        # what traffic happened to flow
        for fv in (FleetView(),
                   FleetView().add("i0", ServingMetrics(
                       name="i0", slo_target_ms=50))):
            snap = fv.snapshot()
            for key in self.FLEET_PINNED_KEYS:
                assert key in snap, f"missing fleet snapshot key {key}"

    def test_registry_storage_keys_via_stats_reporter(self):
        from deeplearning4j_tpu.ui.stats import ServingStatsReporter
        from deeplearning4j_tpu.ui.storage import InMemoryStatsStorage
        m = ServingMetrics(slo_target_ms=100)
        m.record_request(12.0, queue_wait_ms=3.0, tokens=5)
        m.record_batch(3, 4, 1)
        storage = InMemoryStatsStorage()
        rep = ServingStatsReporter(storage, session_id="obs_pin")
        rep.report(m.snapshot())
        serving = storage.get_latest_update("obs_pin")["serving"]
        for key in self.PINNED_KEYS:
            assert key in serving, f"renamed/missing snapshot key {key}"
        assert serving["completed"] == 1
        assert serving["slo_total"] == 1 and serving["slo_met"] == 1
        assert serving["slo_tokens_met"] == 5
        assert serving["slo_attainment"] == 1.0

    def test_slo_counters_from_latency_target(self):
        m = ServingMetrics(slo_target_ms=50)
        m.record_request(10.0, tokens=4)     # met
        m.record_request(80.0, tokens=4)     # missed
        m.record_slo_miss()                  # shed deadline-carrying req
        snap = m.snapshot()
        assert snap["slo_total"] == 3
        assert snap["slo_met"] == 1
        assert snap["slo_tokens_met"] == 4
        assert snap["slo_attainment"] == pytest.approx(1 / 3)

    def test_explicit_deadline_overrides_latency_target(self):
        m = ServingMetrics(slo_target_ms=1.0)
        # the server KNOWS the request's deadline was met — the latency
        # target must not re-classify it
        m.record_request(500.0, tokens=2, deadline_met=True)
        snap = m.snapshot()
        assert snap["slo_met"] == 1 and snap["slo_total"] == 1

    def test_no_slo_configured_reports_none(self):
        m = ServingMetrics()
        m.record_request(10.0)
        snap = m.snapshot()
        assert snap["slo_total"] == 0
        assert snap["slo_attainment"] is None

    def test_deadline_eviction_counts_slo_miss(self):
        from deeplearning4j_tpu.serving import DeadlineExceededError
        lm = _lm()
        with ContinuousDecodeServer(lm, slots=2,
                                    prompt_buckets=(8,)) as srv:
            srv.generate([1, 2, 3], 4, timeout=120)   # warm compile
            # 40 tokens cannot finish in 2ms: shed at admission or
            # evicted mid-decode — either way an SLO miss is counted
            fut = srv.submit([4, 5, 6], 40, deadline_ms=2)
            with pytest.raises(DeadlineExceededError):
                fut.result(60)
        snap = srv.metrics.snapshot()
        assert snap["slo_total"] >= 1
        assert snap["slo_met"] <= snap["slo_total"] - 1

    def test_queue_depth_sampled_at_enqueue(self):
        """The staleness fix: depth must be observable BEFORE any batch
        forms. A burst into a long-max-wait server shows non-zero depth
        immediately; the old batch-formation-only sampling reported 0
        until the first dispatch."""
        net = _mln()
        srv = InferenceServer(net, max_batch=32, max_wait_ms=400.0,
                              max_queue=64).start()
        try:
            rng = np.random.default_rng(3)
            futs = [srv.submit(rng.standard_normal(6).astype(np.float32))
                    for _ in range(4)]
            snap = srv.metrics.snapshot()
            assert snap.get("batches", 0) == 0      # no batch formed yet
            assert snap["queue_depth_max"] >= 1     # ...but depth seen
            for f in futs:
                f.result(60)
        finally:
            srv.stop()

    def test_queue_full_shed_records_depth(self):
        """Queue-full backpressure on a busy decode server (one long
        request holds the only slot, so the queue really fills) records
        the full depth — the shed IS a depth observation."""
        from deeplearning4j_tpu.serving import ServerOverloadedError
        lm = _lm()
        srv = ContinuousDecodeServer(lm, slots=1, prompt_buckets=(8,),
                                     max_queue=2).start()
        try:
            srv.generate([1, 2, 3], 2, timeout=120)   # warm compile
            hog = srv.submit([4, 5, 6], 40)           # occupies the slot
            time.sleep(0.05)                          # let it be admitted
            with pytest.raises(ServerOverloadedError):
                for i in range(4):
                    srv.submit([7 + i, 8, 9], 40)
            assert srv.metrics.snapshot()["queue_depth_max"] >= 2
            hog.result(120)
        finally:
            srv.stop(timeout=60)

    def test_health_counters_reach_default_registry(self):
        from deeplearning4j_tpu.common.health import TrainingHealthPolicy
        reg = reset_default_registry()
        try:
            pol = TrainingHealthPolicy(warmup_steps=1)
            pol.observe({"score": 1.0, "grad_norm": 1.0,
                         "all_finite": True})
            pol.observe({"score": float("nan"), "grad_norm": 1.0,
                         "all_finite": False})
            assert reg.counter("train.health.ok").value == 1
            assert reg.counter("train.health.skips").value == 1
        finally:
            reset_default_registry()

    def test_retry_publishes_to_default_registry(self):
        from deeplearning4j_tpu.common.resilience import RetryPolicy
        reg = reset_default_registry()
        try:
            calls = [0]

            def flaky():
                calls[0] += 1
                if calls[0] < 3:
                    raise ConnectionError("transient")
                return "ok"

            pol = RetryPolicy(max_retries=5, base_delay=0.0, jitter=0.0,
                              metric="unit_test")
            assert pol.call(flaky) == "ok"
            assert reg.counter("resilience.retries").value == 2
            assert reg.counter(
                "resilience.retries.unit_test").value == 2
        finally:
            reset_default_registry()


# ---------------------------------------------------------------------------
# (e) flight recorder
# ---------------------------------------------------------------------------
class TestFlightRecorder:
    def test_p99_threshold_arms_capture(self):
        t = Tracer(enabled=False)
        rec = FlightRecorder(t, threshold_ms=50, window=32, min_samples=8,
                             capture_spans=3, cooldown_s=0.0)
        for _ in range(10):
            rec.observe(10.0)               # healthy: below threshold
        assert rec.triggers == 0 and not t.enabled
        for _ in range(10):
            rec.observe(120.0)              # SLO violation
            if rec.triggers:
                break
        assert rec.triggers == 1
        assert t.enabled                     # armed for the next N spans
        for i in range(3):
            t.emit(f"cap{i}", i, 1)
        assert not t.enabled                 # auto-disarmed after N
        assert len(rec.captures) == 1
        cap = rec.captures[0]
        names = [s.name for s in cap["spans"]]
        assert "flight.trigger" in names
        assert {"cap0", "cap1", "cap2"} <= set(names)
        assert cap["p99_ms"] >= 50

    def test_spike_before_min_samples_still_triggers(self):
        """Regression: the O(1) pre-filter must not suppress a capture
        when the samples that pushed the window p99 over threshold
        arrived during warmup — later all-fast traffic still triggers,
        because the spike IS the window's p99 until it ages out."""
        t = Tracer(enabled=False)
        rec = FlightRecorder(t, threshold_ms=50, window=64,
                             min_samples=32, capture_spans=2,
                             cooldown_s=0.0)
        for _ in range(5):
            rec.observe(500.0)          # spikes land before min_samples
        for _ in range(40):
            rec.observe(10.0)           # then only fast requests
        assert rec.triggers == 1        # p99 is still the 500ms spike

    def test_already_enabled_tracer_stays_enabled(self):
        t = Tracer(enabled=True)
        rec = FlightRecorder(t, threshold_ms=10, window=8, min_samples=2,
                             capture_spans=2, cooldown_s=0.0)
        rec.observe(100.0)
        rec.observe(100.0)
        assert rec.triggers == 1
        t.emit("a", 0, 1)
        t.emit("b", 1, 1)
        assert t.enabled                     # restored to previous state

    def test_flight_recorder_on_live_server(self):
        """Slow real requests (tiny deadline-free decode on CPU) trip a
        sub-ms threshold: the recorder arms the server's OWN tracer and
        the capture self-documents with real serve spans."""
        t = Tracer(enabled=False)
        rec = FlightRecorder(t, threshold_ms=0.5, window=16,
                             min_samples=2, capture_spans=8,
                             cooldown_s=0.0)
        lm = _lm()
        with ContinuousDecodeServer(lm, slots=2, prompt_buckets=(8,),
                                    tracer=t, flight_recorder=rec) as srv:
            for i in range(4):
                srv.generate([1 + i, 2, 3], 6, timeout=120)
        assert rec.triggers >= 1
        assert rec.captures or t.enabled     # capture done or still armed


# ---------------------------------------------------------------------------
# combined report (tools/obs_report.py)
# ---------------------------------------------------------------------------
class TestObsReport:
    def _mod(self):
        import importlib
        import sys
        tools = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "tools")
        if tools not in sys.path:
            sys.path.insert(0, tools)
        return importlib.import_module("obs_report")

    def test_build_and_format(self):
        mod = self._mod()
        t = Tracer(enabled=True)
        for _ in range(3):
            with t.span("serve.dispatch"):
                pass
        m = ServingMetrics(slo_target_ms=100)
        m.record_request(5.0, tokens=2)
        report = mod.build_report(spans=t,
                                  metrics={"arm": m.snapshot()})
        row = next(r for r in report["spans"]
                   if r["name"] == "serve.dispatch")
        assert row["count"] == 3
        assert row["total_ms"] is not None
        assert report["metrics"]["arm"]["completed"] == 1
        text = mod.format_report(report)
        assert "serve.dispatch" in text and "completed" in text

    def test_report_survives_missing_profile(self, tmp_path):
        mod = self._mod()
        report = mod.build_report(spans=[], metrics=None,
                                  profile_logdir=str(tmp_path / "nope"))
        assert report["device_ops"] is None
        assert "device_ops_error" in report
        assert isinstance(mod.format_report(report), str)

    def test_chrome_trace_input(self):
        mod = self._mod()
        t = Tracer(enabled=True)
        with t.span("x"):
            pass
        rows = mod.span_summary(t.chrome_trace())
        assert rows[0]["name"] == "x" and rows[0]["count"] == 1

    def test_multi_trace_merge_plumbing(self, tmp_path):
        """Satellite pin (ISSUE 12): obs_report accepts MULTIPLE trace
        files — merge_trace_files stitches them on the clock anchors
        and the merged dict feeds build_report like any single trace."""
        mod = self._mod()
        t1 = Tracer(enabled=True, instance="a")
        with t1.span("serve.dispatch"):
            pass
        time.sleep(0.02)
        t2 = Tracer(enabled=True, instance="b")
        with t2.span("serve.dispatch"):
            pass
        p1 = t1.save(str(tmp_path / "a.trace.json"))
        p2 = t2.save(str(tmp_path / "b.trace.json"))
        merged = mod.merge_trace_files([p1, p2])
        xs = [e for e in merged["traceEvents"] if e.get("ph") == "X"]
        assert sorted({e["pid"] for e in xs}) == [1, 2]
        report = mod.build_report(spans=merged)
        row = next(r for r in report["spans"]
                   if r["name"] == "serve.dispatch")
        assert row["count"] == 2
