"""Blast-radius containment pins (ISSUE 17 acceptance criteria).

  (a) Poison-pill quarantine: a request aboard TWO distinct
      spontaneous replica deaths is convicted — its outer future fails
      with the typed `PoisonPillError`, it is NEVER replayed a second
      time, its fingerprint sheds re-submissions at the door, and the
      cascade stops at exactly two deaths while innocent co-victims
      fail over normally and complete. Operator kills are
      administrative and never convict (regression for the
      all-replicas-killed path).
  (b) Quarantine durability: the conviction is journaled; a successor
      manager folding the same journal keeps shedding the fingerprint
      without the request ever touching a fresh replica.
  (c) Spawn circuit breaker: K consecutive spawn-path strikes OPEN
      the breaker — ONE control tick against an always-failing
      factory costs exactly K factory calls, not one per tick; while
      open the fleet serves degraded (brownout sheds the configured
      classes, `degraded_mode_ticks` counts) and half-open probes
      retry on exponential backoff until a probe survives infancy.
      A recovered manager INHERITS the open breaker and bounds its
      backfill loop instead of resuming the crash-loop.
  (d) Fleet-wide retry budget: failover replays spend from one token
      bucket; exhaustion fails LOUDLY (`RetryBudgetExhaustedError` +
      `retry_budget_exhausted`) instead of amplifying load; successes
      refill a fraction per completion; and the no-fault A/B shows
      zero behavior change — same dispatch count, bit-identical
      streams, zero tokens spent.
"""
import concurrent.futures as cf
import threading
import time

import pytest

from deeplearning4j_tpu.common.resilience import (RetryBudget,
                                                  RetryBudgetExhaustedError,
                                                  RetryPolicy)
from deeplearning4j_tpu.serving import (FleetManager, PoisonPillError,
                                        ReplicaDeadError, ServingMetrics,
                                        fold_records, replay_journal)
from deeplearning4j_tpu.serving.admission import BrownoutPolicy
from deeplearning4j_tpu.serving.fleet import (BREAKER_CLOSED,
                                              BREAKER_OPEN)
from deeplearning4j_tpu.serving.server import ServerOverloadedError


class _HoldReplica:
    """Fake replica whose submits stay IN FLIGHT until the test says
    otherwise: `kill()` fails the held futures with ReplicaDeadError
    (the real server contract), `resolve_all()` completes them with
    the deterministic greedy stream. The conviction/failover paths
    only engage against requests that are genuinely aboard."""

    def __init__(self, name):
        self.name = name
        self.instance = name
        self.metrics = ServingMetrics(name=name)
        self._running = True
        self.paged = False
        self.killed = False
        self._lock = threading.Lock()
        self.held = []          # (future, prompt, max_new)
        self.n_submits = 0

    @property
    def alive(self):
        return not self.killed

    def start(self):
        self._running = True
        return self

    def submit(self, prompt, max_new, **kw):
        fut = cf.Future()
        with self._lock:
            self.n_submits += 1
            self.held.append((fut, list(prompt), int(max_new)))
        return fut

    def resolve_all(self):
        with self._lock:
            held, self.held = self.held, []
        for fut, prompt, max_new in held:
            if not fut.done():
                fut.set_result(prompt + [0] * max_new)

    def kill(self):
        self.killed = True
        self._running = False
        with self._lock:
            held, self.held = self.held, []
        for fut, _, _ in held:
            if not fut.done():
                fut.set_exception(ReplicaDeadError(
                    f"replica {self.name} killed"))

    def stop(self, drain=True, timeout=None):
        self._running = False

    def drain(self, migrate=None, timeout=60.0):
        self._running = False
        return [], []


class _InstantReplica(_HoldReplica):
    """Fake replica that completes every submit synchronously — the
    no-fault / refill arms, where nothing is ever in flight."""

    def submit(self, prompt, max_new, **kw):
        fut = super().submit(prompt, max_new, **kw)
        fut.set_result(list(prompt) + [0] * int(max_new))
        return fut


def _factory(cls, made=None):
    def make(name):
        r = cls(name)
        if made is not None:
            made[name] = r
        return r
    return make


POISON = [13, 13, 13]


def _poison_hook(prompt, replica_name):
    return list(prompt) == POISON


# ---------------------------------------------------------------------------
# (a) poison-pill quarantine
# ---------------------------------------------------------------------------
class TestQuarantine:
    def test_poison_convicted_after_exactly_two_deaths(self):
        made = {}
        with FleetManager(_factory(_HoldReplica, made), n_replicas=3,
                          kill_hook=_poison_hook) as mgr:
            pre_alive = mgr.n_alive()
            fut = mgr.submit(POISON, 4)
            with pytest.raises(PoisonPillError):
                fut.result(10)
            # the cascade stopped at the conviction threshold: two
            # replicas died, the third never saw the poison
            assert mgr.metrics.count_value("replica_dead") == 2
            assert mgr.n_alive() == pre_alive - 2
            assert mgr.metrics.count_value(
                "requests_quarantined") == 1

    def test_resubmission_shed_at_the_door(self):
        made = {}
        with FleetManager(_factory(_HoldReplica, made), n_replicas=3,
                          kill_hook=_poison_hook) as mgr:
            with pytest.raises(PoisonPillError):
                mgr.submit(POISON, 4).result(10)
            dead_before = mgr.metrics.count_value("replica_dead")
            submits_before = sum(r.n_submits for r in made.values())
            with pytest.raises(PoisonPillError):
                mgr.submit(POISON, 4)       # raises AT submit
            # the shed never reached a replica, let alone killed one
            assert sum(r.n_submits
                       for r in made.values()) == submits_before
            assert mgr.metrics.count_value(
                "replica_dead") == dead_before
            assert mgr.metrics.count_value(
                "requests_quarantined") == 2

    def test_innocent_co_victims_fail_over_and_complete(self):
        made = {}
        with FleetManager(_factory(_HoldReplica, made), n_replicas=3,
                          kill_hook=_poison_hook) as mgr:
            # innocents land on i0 and i1 (least backlog), so the
            # poison takes i2 first, then replays onto a loaded
            # survivor and kills it too — one innocent rides a death
            inn_a = mgr.submit([1, 2, 3], 2)
            inn_b = mgr.submit([4, 5, 6], 2)
            poison = mgr.submit(POISON, 4)
            with pytest.raises(PoisonPillError):
                poison.result(10)
            assert mgr.metrics.count_value("replica_dead") == 2
            assert mgr.n_alive() == 1
            # the survivor serves everything that failed over onto it
            for r in made.values():
                r.resolve_all()
            assert inn_a.result(10) == [1, 2, 3, 0, 0]
            assert inn_b.result(10) == [4, 5, 6, 0, 0]
            # exactly the poison was lost
            assert mgr.metrics.count_value("completed") == 2
            assert mgr.metrics.count_value("failed") == 1

    def test_operator_kill_never_convicts(self):
        made = {}
        with FleetManager(_factory(_HoldReplica, made),
                          n_replicas=2) as mgr:
            f1 = mgr.submit([1, 2, 3], 2)
            f2 = mgr.submit([1, 2, 3], 2)
            for name in list(mgr.replicas):
                mgr.kill_replica(name)
            # an administrative kill of every replica is an outage,
            # not evidence: both requests fail with the infrastructure
            # error, neither is branded a poison pill
            for fut in (f1, f2):
                with pytest.raises(ReplicaDeadError):
                    fut.result(10)
            assert mgr.metrics.count_value(
                "requests_quarantined") == 0


# ---------------------------------------------------------------------------
# (b) quarantine durability across manager generations
# ---------------------------------------------------------------------------
class TestQuarantineDurability:
    def test_conviction_is_journaled(self, tmp_path):
        jpath = str(tmp_path / "fleet.journal")
        with FleetManager(_factory(_HoldReplica), n_replicas=3,
                          kill_hook=_poison_hook,
                          journal=jpath) as mgr:
            with pytest.raises(PoisonPillError):
                mgr.submit(POISON, 4).result(10)
        folded = fold_records(replay_journal(jpath))
        assert len(folded["quarantine"]) == 1

    def test_successor_keeps_shedding(self, tmp_path):
        jpath = str(tmp_path / "fleet.journal")
        with FleetManager(_factory(_HoldReplica), n_replicas=3,
                          kill_hook=_poison_hook,
                          journal=jpath) as mgr:
            with pytest.raises(PoisonPillError):
                mgr.submit(POISON, 4).result(10)
        made = {}
        with FleetManager(_factory(_HoldReplica, made), n_replicas=2,
                          journal=jpath) as mgr2:
            # no kill_hook on the successor: only the inherited
            # quarantine set stands between the poison and the fleet
            with pytest.raises(PoisonPillError):
                mgr2.submit(POISON, 4)
            assert sum(r.n_submits for r in made.values()) == 0
            assert mgr2.metrics.count_value(
                "requests_quarantined") == 1
            # innocents still flow
            ok = mgr2.submit([7, 8, 9], 2)
            for r in made.values():
                r.resolve_all()
            assert ok.result(10) == [7, 8, 9, 0, 0]


# ---------------------------------------------------------------------------
# (c) spawn circuit breaker + degraded mode
# ---------------------------------------------------------------------------
def _flaky_factory(made, arm):
    """Factory that refuses to spawn while `arm["on"]` (counting every
    attempt) — the spawn_fail chaos window in unit form."""
    calls = {"n": 0}

    def make(name):
        calls["n"] += 1
        if arm["on"]:
            raise RuntimeError("spawn_fail window: factory refused")
        r = _InstantReplica(name)
        made[name] = r
        return r
    return make, calls


class TestSpawnBreaker:
    def _mgr(self, **kw):
        made, arm = {}, {"on": False}
        factory, calls = _flaky_factory(made, arm)
        mgr = FleetManager(factory, n_replicas=2, breaker_strikes=3,
                           breaker_backoff_s=0.2,
                           infant_mortality_s=0.1, **kw).start()
        return mgr, made, arm, calls

    def test_one_tick_costs_exactly_k_strikes(self):
        mgr, made, arm, calls = self._mgr()
        try:
            mgr.kill_replica(mgr.replicas[0])
            arm["on"] = True
            base = calls["n"]
            mgr.control_tick()
            # the backfill loop stopped AT the breaker, not at the
            # tick boundary: exactly K attempts, then OPEN
            assert calls["n"] - base == mgr.breaker_strikes
            assert mgr.breaker_state == BREAKER_OPEN
            assert mgr.metrics.count_value("breaker_open_total") == 1
            assert mgr.metrics.count_value("degraded_mode_ticks") == 1
            # ticks inside the backoff window spawn NOTHING
            mgr.control_tick()
            assert calls["n"] - base == mgr.breaker_strikes
            assert mgr.metrics.count_value("degraded_mode_ticks") == 2
        finally:
            arm["on"] = False
            mgr.stop()

    def test_half_open_probe_backoff_doubles_then_heals(self):
        mgr, made, arm, calls = self._mgr()
        try:
            mgr.kill_replica(mgr.replicas[0])
            arm["on"] = True
            mgr.control_tick()
            base = calls["n"]
            time.sleep(0.25)            # past the first backoff
            mgr.control_tick()
            # ONE half-open probe, it failed, the breaker re-opened
            # with doubled backoff
            assert calls["n"] - base == 1
            assert mgr.breaker_state == BREAKER_OPEN
            assert mgr.metrics.count_value("breaker_open_total") == 2
            arm["on"] = False
            time.sleep(0.45)            # past the doubled backoff
            mgr.control_tick()          # probe spawn succeeds
            assert mgr.n_alive() == 2
            time.sleep(0.15)            # probe survives infancy
            mgr.control_tick()
            assert mgr.breaker_state == BREAKER_CLOSED
        finally:
            arm["on"] = False
            mgr.stop()

    def test_degraded_mode_brownout_sheds_low_classes(self):
        mgr, made, arm, calls = self._mgr(
            brownout=BrownoutPolicy(classes={"batch": (0.0, 0.0)}))
        try:
            mgr.kill_replica(mgr.replicas[0])
            arm["on"] = True
            mgr.control_tick()          # opens the breaker
            with pytest.raises(ServerOverloadedError):
                mgr.submit([1, 2, 3], 2, klass="batch")
            assert mgr.metrics.count_value("shed_brownout") == 1
            # the default class still serves on what is alive
            assert mgr.submit([1, 2, 3], 2).result(10) == \
                [1, 2, 3, 0, 0]
        finally:
            arm["on"] = False
            mgr.stop()

    def test_infant_death_strikes_the_breaker(self):
        made = {}
        with FleetManager(_factory(_InstantReplica, made),
                          n_replicas=1, breaker_strikes=1,
                          infant_mortality_s=30.0) as mgr:
            name = mgr.replicas[0]
            # dies well inside infant_mortality_s of its spawn
            mgr._crash(name, reason="died at startup")
            assert mgr.metrics.count_value("infant_deaths") == 1
            assert mgr.breaker_state == BREAKER_OPEN


class TestBreakerRecovery:
    def _crashloop_journal(self, tmp_path):
        """A journal left by a manager that died with the breaker
        OPEN (its roster has no wire identity, so a successor cannot
        re-adopt anything)."""
        jpath = str(tmp_path / "fleet.journal")
        made, arm = {}, {"on": False}
        factory, calls = _flaky_factory(made, arm)
        mgr = FleetManager(factory, n_replicas=2, breaker_strikes=3,
                           breaker_backoff_s=0.2,
                           infant_mortality_s=0.1,
                           journal=jpath).start()
        mgr.kill_replica(mgr.replicas[0])
        arm["on"] = True
        mgr.control_tick()
        assert mgr.breaker_state == BREAKER_OPEN
        # abandon WITHOUT stop(): the manager "crashed" mid-outage
        return jpath

    def test_recovered_manager_inherits_open_breaker(self, tmp_path):
        jpath = self._crashloop_journal(tmp_path)
        made2, arm2 = {}, {"on": False}
        factory2, calls2 = _flaky_factory(made2, arm2)
        mgr2 = FleetManager.recover(factory2, jpath, n_replicas=2,
                                    breaker_strikes=3,
                                    breaker_backoff_s=0.2,
                                    infant_mortality_s=0.05)
        try:
            # the successor did NOT resume the spawn crash-loop: the
            # inherited open breaker held the backfill to zero spawns
            assert mgr2.breaker_state == BREAKER_OPEN
            assert calls2["n"] == 0
            assert mgr2.n_alive() == 0
            # after the inherited backoff it probes and heals
            time.sleep(0.3)
            mgr2.control_tick()
            assert mgr2.n_alive() >= 1
        finally:
            mgr2.stop()

    def test_recovery_backfill_is_bounded(self, tmp_path):
        # a CLOSED-breaker journal + an infant-death factory: the
        # recovery backfill must strike out and fall through to
        # degraded mode, not loop forever
        jpath = str(tmp_path / "fleet.journal")
        mgr = FleetManager(_factory(_InstantReplica), n_replicas=2,
                           journal=jpath).start()
        mgr._journal.close()            # abandon mid-flight
        made2, arm2 = {}, {"on": True}
        factory2, calls2 = _flaky_factory(made2, arm2)
        mgr2 = FleetManager.recover(factory2, jpath, n_replicas=2,
                                    breaker_strikes=3)
        try:
            assert calls2["n"] == mgr2.breaker_strikes
            assert calls2["n"] <= mgr2.min_replicas \
                + mgr2.breaker_strikes
            assert mgr2.breaker_state == BREAKER_OPEN
            assert mgr2.n_alive() == 0
        finally:
            mgr2.stop()
        mgr._running = False


# ---------------------------------------------------------------------------
# (d) fleet-wide retry budget
# ---------------------------------------------------------------------------
class TestRetryBudget:
    def test_replays_bounded_by_budget(self):
        budget = RetryBudget(capacity=8, initial=2)
        made = {}
        with FleetManager(_factory(_HoldReplica, made), n_replicas=2,
                          retry_budget=budget,
                          retry_policy=RetryPolicy(
                              max_retries=10, base_delay=0.0,
                              jitter=0.0)) as mgr:
            futs = [mgr.submit([1, 2, 3], 2) for _ in range(4)]
            for name in list(mgr.replicas):
                mgr.kill_replica(name)
            for fut in futs:
                with pytest.raises((ReplicaDeadError,
                                    RetryBudgetExhaustedError)):
                    fut.result(10)
            # total replays never exceeded the two tokens the bucket
            # held; everything past them failed LOUDLY, typed + counted
            assert mgr.metrics.count_value(
                "failover_resubmitted") <= 2
            assert mgr.metrics.count_value(
                "retry_budget_exhausted") >= 1
            assert budget.denied >= 1
            assert budget.tokens == 0.0

    def test_exhaustion_is_typed_and_counted(self):
        budget = RetryBudget(capacity=4, initial=0)
        made = {}
        with FleetManager(_factory(_HoldReplica, made), n_replicas=2,
                          retry_budget=budget) as mgr:
            fut = mgr.submit([1, 2, 3], 2)
            victim = next(r.name for r in made.values() if r.held)
            mgr.kill_replica(victim)
            with pytest.raises(RetryBudgetExhaustedError):
                fut.result(10)
            assert mgr.metrics.count_value(
                "retry_budget_exhausted") == 1
            assert mgr.metrics.count_value("failed") == 1

    def test_successes_refill_the_bucket(self):
        budget = RetryBudget(capacity=8, initial=0,
                             refill_fraction=0.5)
        with FleetManager(_factory(_InstantReplica), n_replicas=2,
                          retry_budget=budget) as mgr:
            for _ in range(4):
                assert mgr.submit([1, 2, 3], 2).result(10) == \
                    [1, 2, 3, 0, 0]
            # four completions at 0.5 token each
            assert budget.tokens == 2.0
            assert budget.take()
            assert budget.take()
            assert not budget.take()

    def test_no_fault_ab_zero_behavior_change(self):
        prompts = [[1, 2, 3], [4, 5, 6], [7, 8, 9]]

        def run(retry_budget):
            made = {}
            with FleetManager(_factory(_InstantReplica, made),
                              n_replicas=2,
                              retry_budget=retry_budget) as mgr:
                out = [mgr.submit(p, 3).result(10) for p in prompts]
            return out, sum(r.n_submits for r in made.values())

        budget = RetryBudget(capacity=64)
        with_budget, dispatches_b = run(budget)
        without, dispatches = run(None)
        # bit-identical streams, ZERO added dispatches, zero spend
        assert with_budget == without
        assert dispatches_b == dispatches == len(prompts)
        assert budget.tokens == float(budget.capacity)
        assert budget.denied == 0

    def test_policy_without_budget_always_grants(self):
        pol = RetryPolicy(max_retries=3, base_delay=0.0, jitter=0.0)
        assert pol.grant_retry()
        pol.budget = RetryBudget(capacity=1, initial=1)
        assert pol.grant_retry()
        assert not pol.grant_retry()
