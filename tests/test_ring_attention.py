"""Ring attention (sequence parallelism) + SelfAttentionLayer:
- ring kernel over the virtual 8-device mesh == single-device attention
- causal + key-mask correctness
- layer gradient check, training, and mesh-parallel layer path
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from deeplearning4j_tpu.parallel.ring_attention import (blockwise_attention,
                                                        ring_self_attention)


def _seq_mesh(n=4):
    return Mesh(np.array(jax.devices()[:n]), ("seq",))


def _qkv(B=2, T=16, H=2, D=8, seed=0):
    r = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(r.standard_normal((B, T, H, D)), jnp.float32)
    return mk(), mk(), mk()


class TestRingAttention:
    def test_matches_full_attention(self):
        q, k, v = _qkv()
        mesh = _seq_mesh(4)
        full = blockwise_attention(q, k, v)
        ring = ring_self_attention(q, k, v, mesh, axis="seq")
        assert np.allclose(np.asarray(full), np.asarray(ring), atol=1e-5), \
            np.abs(np.asarray(full) - np.asarray(ring)).max()

    @pytest.mark.slow
    def test_causal_matches(self):
        q, k, v = _qkv(T=24, seed=1)
        mesh = _seq_mesh(4)
        full = blockwise_attention(q, k, v, causal=True)
        ring = ring_self_attention(q, k, v, mesh, axis="seq", causal=True)
        assert np.allclose(np.asarray(full), np.asarray(ring), atol=1e-5)

    @pytest.mark.slow
    def test_causality_actually_holds(self):
        """Changing future keys must not change past outputs."""
        q, k, v = _qkv(T=16, seed=2)
        mesh = _seq_mesh(4)
        out1 = np.asarray(ring_self_attention(q, k, v, mesh, axis="seq",
                                              causal=True))
        k2 = k.at[:, 12:].set(99.0)
        v2 = v.at[:, 12:].set(-99.0)
        out2 = np.asarray(ring_self_attention(q, k2, v2, mesh, axis="seq",
                                              causal=True))
        assert np.allclose(out1[:, :12], out2[:, :12], atol=1e-5)
        assert not np.allclose(out1[:, 12:], out2[:, 12:])

    @pytest.mark.slow
    def test_key_mask(self):
        q, k, v = _qkv(T=16, seed=3)
        mesh = _seq_mesh(4)
        kv_mask = jnp.asarray(
            np.repeat([[1] * 10 + [0] * 6], 2, axis=0), jnp.float32)
        full = blockwise_attention(q, k, v, kv_mask=kv_mask)
        ring = ring_self_attention(q, k, v, mesh, axis="seq",
                                   kv_mask=kv_mask)
        assert np.allclose(np.asarray(full), np.asarray(ring), atol=1e-5)
        # masked keys are ignored: result equals attention over first 10 only
        trunc = blockwise_attention(q, k[:, :10], v[:, :10])
        assert np.allclose(np.asarray(full), np.asarray(trunc), atol=1e-5)

    @pytest.mark.slow
    def test_gradients_flow_through_ring(self):
        q, k, v = _qkv(T=8, seed=4)
        mesh = _seq_mesh(4)

        def loss_ring(q, k, v):
            return jnp.sum(ring_self_attention(q, k, v, mesh, axis="seq") ** 2)

        def loss_full(q, k, v):
            return jnp.sum(blockwise_attention(q, k, v) ** 2)

        g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
        g_full = jax.grad(loss_full, argnums=(0, 1, 2))(q, k, v)
        for gr, gf in zip(g_ring, g_full):
            assert np.allclose(np.asarray(gr), np.asarray(gf), atol=1e-4)


class TestSelfAttentionLayer:
    def _conf(self, causal=False):
        from deeplearning4j_tpu import InputType, NeuralNetConfiguration
        from deeplearning4j_tpu.nn.conf.layers import (RnnOutputLayer,
                                                       SelfAttentionLayer)
        return (NeuralNetConfiguration.Builder().seed(5)
                .data_type("float64").updater("sgd").learning_rate(0.05)
                .list()
                .layer(0, SelfAttentionLayer(n_heads=2, causal=causal,
                                             activation="identity"))
                .layer(1, RnnOutputLayer(n_out=3, activation="softmax",
                                         loss_function="mcxent"))
                .set_input_type(InputType.recurrent(6))
                .build())

    @pytest.mark.slow
    def test_gradient_check(self):
        from deeplearning4j_tpu import MultiLayerNetwork
        from deeplearning4j_tpu.gradientcheck.gradient_check_util import \
            check_gradients
        net = MultiLayerNetwork(self._conf()).init()
        r = np.random.default_rng(0)
        x = r.random((3, 5, 6)).astype(np.float64)
        y = np.zeros((3, 5, 3))
        y[np.arange(3)[:, None], np.arange(5)[None, :],
          r.integers(0, 3, (3, 5))] = 1.0
        assert check_gradients(net, x, y, max_rel_error=1e-4, subset=60)

    def test_trains(self):
        from deeplearning4j_tpu import MultiLayerNetwork
        from deeplearning4j_tpu.datasets.dataset import DataSet
        net = MultiLayerNetwork(self._conf(causal=True)).init()
        r = np.random.default_rng(1)
        x = r.random((4, 6, 6)).astype(np.float64)
        y = np.zeros((4, 6, 3))
        y[np.arange(4)[:, None], np.arange(6)[None, :],
          r.integers(0, 3, (4, 6))] = 1.0
        ds = DataSet(x, y)
        s0 = net.score(ds)
        for _ in range(30):
            net.fit(ds)
        assert net.score(ds) < s0

    @pytest.mark.slow
    def test_sequence_parallel_layer_matches_local(self):
        from deeplearning4j_tpu.nn.conf.layers import SelfAttentionLayer
        layer = SelfAttentionLayer(n_in=8, n_out=8, n_heads=2,
                                   activation="identity")
        layer = layer.apply_global_defaults({})
        params = layer.init_params(jax.random.PRNGKey(0), jnp.float32)
        r = np.random.default_rng(2)
        x = jnp.asarray(r.standard_normal((2, 16, 8)), jnp.float32)
        out_local = np.asarray(layer.forward(params, x))
        layer_sp = SelfAttentionLayer(n_in=8, n_out=8, n_heads=2,
                                      activation="identity")
        layer_sp = layer_sp.apply_global_defaults({})
        layer_sp.with_sequence_parallel(_seq_mesh(4), "seq")
        out_sp = np.asarray(layer_sp.forward(params, x))
        assert np.allclose(out_local, out_sp, atol=1e-5)


class TestRingFlashPath:
    """use_flash=True: per-hop compute via the Pallas partial kernel
    (interpreter on CPU, Mosaic on TPU) — the full long-context stack
    (sequence parallelism x flash attention)."""

    @pytest.mark.slow
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_full_attention(self, causal):
        q, k, v = _qkv(T=32, seed=3)
        mesh = _seq_mesh(4)
        full = blockwise_attention(q, k, v, causal=causal)
        ring = ring_self_attention(q, k, v, mesh, axis="seq",
                                   causal=causal, use_flash=True)
        assert np.allclose(np.asarray(full), np.asarray(ring), atol=1e-5), \
            np.abs(np.asarray(full) - np.asarray(ring)).max()

    @pytest.mark.slow
    def test_eight_device_ring(self):
        q, k, v = _qkv(T=64, seed=4)
        mesh = _seq_mesh(8)
        full = blockwise_attention(q, k, v, causal=True)
        ring = ring_self_attention(q, k, v, mesh, axis="seq", causal=True,
                                   use_flash=True)
        assert np.allclose(np.asarray(full), np.asarray(ring), atol=1e-5)

    def test_kv_mask_rejected(self):
        q, k, v = _qkv(seed=5)
        mesh = _seq_mesh(4)
        with pytest.raises(ValueError):
            ring_self_attention(q, k, v, mesh, axis="seq", use_flash=True,
                                kv_mask=jnp.ones(q.shape[:2]))

    @pytest.mark.slow
    @pytest.mark.parametrize("causal", [False, True])
    def test_flash_path_differentiable(self, causal):
        """use_flash trains with the FUSED ring backward (r4: reverse
        ring feeding the Pallas dQ/dK+dV grid passes per hop, dK/dV
        partials rotating home with their blocks; global lse saved by the
        forward makes each hop's probabilities exact) — grads match the
        einsum ring's autodiff."""
        q, k, v = _qkv(T=32, seed=6)
        mesh = _seq_mesh(4)

        def loss_flash(q, k, v):
            return jnp.mean(ring_self_attention(
                q, k, v, mesh, axis="seq", causal=causal,
                use_flash=True) ** 2)

        def loss_ref(q, k, v):
            return jnp.mean(ring_self_attention(
                q, k, v, mesh, axis="seq", causal=causal) ** 2)

        gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gf, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-5)

    @pytest.mark.slow
    def test_fused_ring_backward_bf16(self):
        """bf16 chunks: per-hop partials come back f32 and are rounded
        ONCE after the ring, tracking the f32 reference within bf16
        resolution (scaled tolerance)."""
        r = np.random.default_rng(11)
        mk = lambda: jnp.asarray(r.standard_normal((2, 32, 2, 8)),
                                 jnp.bfloat16)
        q, k, v = mk(), mk(), mk()
        mesh = _seq_mesh(4)

        def loss_flash(q, k, v):
            return jnp.mean(ring_self_attention(
                q, k, v, mesh, axis="seq", causal=True,
                use_flash=True).astype(jnp.float32) ** 2)

        gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        qf, kf, vf = (a.astype(jnp.float32) for a in (q, k, v))

        def loss_ref(q, k, v):
            return jnp.mean(blockwise_attention(q, k, v,
                                                causal=True) ** 2)

        gr = jax.grad(loss_ref, argnums=(0, 1, 2))(qf, kf, vf)
        for a, b in zip(gf, gr):
            assert a.dtype == jnp.bfloat16
            scale = np.abs(np.asarray(b)).max()
            assert scale > 0
            np.testing.assert_allclose(
                np.asarray(a, np.float32) / scale,
                np.asarray(b) / scale, atol=0.03)

    @pytest.mark.slow
    def test_fused_ring_backward_eight_devices(self):
        """The rotating dK/dV accumulators come home correctly over a
        longer ring (8 hops) — grads match the single-device reference."""
        q, k, v = _qkv(T=64, seed=7)
        mesh = _seq_mesh(8)

        def loss_flash(q, k, v):
            return jnp.mean(ring_self_attention(
                q, k, v, mesh, axis="seq", causal=True,
                use_flash=True) ** 2)

        def loss_single(q, k, v):
            return jnp.mean(blockwise_attention(q, k, v,
                                                causal=True) ** 2)

        gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(loss_single, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gf, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-5)
