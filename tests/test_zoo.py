"""Model zoo: ResNet-50 topology/training smoke, char-RNN TBPTT training."""
import numpy as np
import pytest

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.models.zoo import char_rnn_conf, lenet_conf, resnet50_conf
from deeplearning4j_tpu.nn.graph import ComputationGraph
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork


@pytest.mark.slow
def test_resnet50_full_param_count():
    conf = resnet50_conf(num_classes=1000, data_type="float32")
    net = ComputationGraph(conf).init()
    # canonical ResNet-50 parameter count ~25.6M (fc 1000 head);
    # BN gamma/beta included, running stats are model state not params
    n = net.num_params()
    assert 25.4e6 < n < 25.8e6, n


@pytest.mark.slow
def test_resnet_tiny_trains():
    conf = resnet50_conf(height=32, width=32, channels=3, num_classes=10,
                         data_type="float32", learning_rate=1e-3,
                         updater="sgd")
    net = ComputationGraph(conf).init()
    r = np.random.default_rng(0)
    x = r.random((4, 32, 32, 3)).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[r.integers(0, 10, 4)]
    ds = DataSet(x, y)
    # train-mode score: BN batch statistics (running stats are cold at init)
    s0 = net.score(ds, training=True)
    for _ in range(5):
        net.fit(ds)
    assert net.score(ds, training=True) < s0
    out = np.asarray(net.output(x)[0])
    assert out.shape == (4, 10)
    assert np.allclose(out.sum(axis=1), 1.0, atol=1e-4)


@pytest.mark.slow
def test_char_rnn_tbptt_trains():
    vocab, T, B = 12, 20, 4
    conf = char_rnn_conf(vocab_size=vocab, hidden=16, layers=2,
                         tbptt_length=5, learning_rate=0.05)
    net = MultiLayerNetwork(conf).init()
    r = np.random.default_rng(0)
    ids = r.integers(0, vocab, (B, T + 1))
    x = np.eye(vocab, dtype=np.float32)[ids[:, :-1]]
    y = np.eye(vocab, dtype=np.float32)[ids[:, 1:]]
    ds = DataSet(x, y)
    net.fit(ds)
    # 20 timesteps / tbptt 5 -> 4 optimizer iterations per fit
    assert net.conf.iteration_count == 4
    out = np.asarray(net.output(x))
    assert out.shape == (B, T, vocab)


def test_lenet_conf_shapes():
    net = MultiLayerNetwork(lenet_conf()).init()
    x = np.random.default_rng(0).random((2, 784)).astype(np.float32)
    out = np.asarray(net.output(x))
    assert out.shape == (2, 10)


class TestClassicCNNs:
    """AlexNet / VGG-16 zoo configs (reference-era model zoo members)."""

    @pytest.mark.slow
    def test_alexnet_trains_small(self):
        from deeplearning4j_tpu.datasets.dataset import DataSet
        from deeplearning4j_tpu.models.zoo import alexnet_conf
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        conf = alexnet_conf(height=64, width=64, channels=3, num_classes=4,
                            data_type="float32")
        net = MultiLayerNetwork(conf).init()
        rng = np.random.default_rng(0)
        x = rng.random((4, 64, 64, 3)).astype(np.float32)
        y = np.eye(4, dtype=np.float32)[rng.integers(0, 4, 4)]
        net.fit(DataSet(x, y))
        assert np.isfinite(float(net.score()))
        out = np.asarray(net.output(x))
        assert out.shape == (4, 4)
        assert np.allclose(out.sum(1), 1.0, atol=1e-3)

    @pytest.mark.slow
    def test_googlenet_inception_modules_train(self):
        """Inception-v1: nine 4-branch modules merged on the channel axis
        (the era's classic multi-branch ComputationGraph)."""
        from deeplearning4j_tpu.datasets.dataset import DataSet
        from deeplearning4j_tpu.models.zoo import googlenet, googlenet_conf
        conf = googlenet_conf(height=64, width=64, num_classes=4,
                              data_type="float32")
        # nine inception merge vertices in the DAG
        merges = [n for n in conf.vertices if n.endswith("_out")
                  and not conf.vertices[n].is_layer]
        assert len(merges) == 9
        net = googlenet(height=64, width=64, num_classes=4,
                        data_type="float32", learning_rate=0.005)
        rng = np.random.default_rng(0)
        x = rng.random((4, 64, 64, 3)).astype(np.float32)
        y = np.eye(4, dtype=np.float32)[rng.integers(0, 4, 4)]
        for _ in range(2):
            net.fit(DataSet(x, y))
        assert np.isfinite(float(net._score))
        out = np.asarray(net.output(x)[0])
        assert out.shape == (4, 4)
        assert np.allclose(out.sum(1), 1.0, atol=1e-3)

    @pytest.mark.slow
    def test_vgg16_structure_and_forward(self):
        from deeplearning4j_tpu.models.zoo import vgg16_conf
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        conf = vgg16_conf(height=32, width=32, channels=3, num_classes=5,
                          data_type="float32")
        conv_layers = [l for l in conf.layers
                       if type(l).__name__ == "ConvolutionLayer"]
        assert len(conv_layers) == 13            # VGG-16 = 13 convs
        net = MultiLayerNetwork(conf).init()
        rng = np.random.default_rng(1)
        out = np.asarray(net.output(
            rng.random((2, 32, 32, 3)).astype(np.float32)))
        assert out.shape == (2, 5)
