"""Native C++ runtime library: build, IDX/CSV parser equivalence vs python,
staging-buffer pool reuse. The toolchain exists in CI images; tests skip
gracefully when it does not (the library itself always has python fallbacks).
"""
import os
import struct

import numpy as np
import pytest

from deeplearning4j_tpu.common import native_ops


def _require_native():
    if not native_ops.available():
        pytest.skip("native toolchain unavailable")


def test_idx_parser_matches_python(tmp_path):
    _require_native()
    rng = np.random.default_rng(0)
    data = rng.integers(0, 256, (5, 4, 3), dtype=np.uint8)
    p = tmp_path / "test-idx3-ubyte"
    with open(p, "wb") as f:
        f.write(struct.pack(">HBB", 0, 0x08, 3))
        f.write(struct.pack(">III", 5, 4, 3))
        f.write(data.tobytes())
    native = native_ops.read_idx_u8(str(p))
    assert native is not None
    assert native.shape == (5, 4, 3)
    assert np.array_equal(native, data.astype(np.float32))
    # and through the public read_idx (uses native path)
    from deeplearning4j_tpu.datasets.mnist import read_idx
    assert np.array_equal(np.asarray(read_idx(str(p)), np.float32),
                          data.astype(np.float32))


def test_csv_parser_matches_python(tmp_path):
    _require_native()
    p = tmp_path / "m.csv"
    p.write_text("hdr1,hdr2,hdr3\n1.5,2,3\n-4,5e-2,6\n7,8,9.25\n")
    mat = native_ops.parse_csv(str(p), ",", skip_lines=1)
    assert mat is not None
    want = np.array([[1.5, 2, 3], [-4, 0.05, 6], [7, 8, 9.25]], np.float32)
    assert np.allclose(mat, want)
    # non-numeric -> None (callers fall back to python csv)
    p2 = tmp_path / "s.csv"
    p2.write_text("a,b\nc,d\n")
    assert native_ops.parse_csv(str(p2), ",") is None


def test_csv_record_reader_uses_native(tmp_path):
    p = tmp_path / "data.csv"
    p.write_text("1,2,0\n3,4,1\n")
    from deeplearning4j_tpu.datasets import (CSVRecordReader,
                                             RecordReaderDataSetIterator)
    rr = CSVRecordReader(str(p))
    it = RecordReaderDataSetIterator(rr, 2, label_index=2, num_classes=2)
    ds = it.next_batch()
    assert np.array_equal(ds.features, [[1, 2], [3, 4]])
    assert np.array_equal(ds.labels, [[1, 0], [0, 1]])


def test_staging_pool_reuse():
    _require_native()
    pool = native_ops.StagingBufferPool()
    p1 = pool.acquire(1 << 16)
    arr = pool.as_array(p1, (128, 128), np.float32)
    arr[:] = 7.0
    assert arr.sum() == 7.0 * 128 * 128
    pool.release(p1, 1 << 16)
    p2 = pool.acquire(1 << 14)   # smaller request reuses the freed buffer
    assert p2 == p1
    stats = pool.stats()
    assert stats["allocated"] == 1
    assert stats["reused"] == 1
    pool.release(p2, 1 << 16)
    pool.close()


class TestSkipgramPairs:
    def test_window1_exact_adjacency(self):
        """window=1 forces b=1: the pair set is exactly the adjacency
        pairs of each sequence, in order."""
        from deeplearning4j_tpu.common import native_ops
        if not native_ops.available():
            pytest.skip("native library unavailable")
        ids = np.array([10, 11, 12, 20, 21], np.int32)
        offs = np.array([0, 3, 5], np.int64)
        c, o = native_ops.skipgram_pairs(ids, offs, window=1, seed=1)
        expect = [(10, 11), (11, 10), (11, 12), (12, 11), (20, 21),
                  (21, 20)]
        assert list(zip(c.tolist(), o.tolist())) == expect

    def test_pairs_stay_within_sequence(self):
        from deeplearning4j_tpu.common import native_ops
        if not native_ops.available():
            pytest.skip("native library unavailable")
        rng = np.random.default_rng(0)
        seqs = [rng.integers(0, 50, rng.integers(2, 12)).astype(np.int32)
                for _ in range(30)]
        # tag each sequence's tokens with a distinct hundreds-block so a
        # cross-sequence pair is detectable from values alone
        tagged = [s + 100 * i for i, s in enumerate(seqs)]
        ids = np.concatenate(tagged)
        offs = np.zeros(len(tagged) + 1, np.int64)
        np.cumsum([len(s) for s in tagged], out=offs[1:])
        c, o = native_ops.skipgram_pairs(ids, offs, window=5, seed=7)
        assert len(c) > 0
        assert (c // 100 == o // 100).all()          # same sequence
        # count bound: per position at most 2w neighbors
        assert len(c) <= ids.shape[0] * 2 * 5
        # deterministic for a fixed seed
        c2, o2 = native_ops.skipgram_pairs(ids, offs, window=5, seed=7)
        assert (c == c2).all() and (o == o2).all()

    def test_batch_path_trains_to_cluster_quality(self):
        """The NATIVE pair stream trains embeddings to the same
        topic-cluster structure the per-sequence path reaches — a
        behavioral check on the generated pairs, not just their counts
        (wrong-but-in-vocab pairs would destroy the cluster signal)."""
        from deeplearning4j_tpu.models.embeddings.learning import SkipGram
        from deeplearning4j_tpu.models.embeddings.lookup_table import \
            InMemoryLookupTable
        from deeplearning4j_tpu.models.embeddings.model_utils import \
            cosine_sim
        from deeplearning4j_tpu.models.word2vec.vocab import VocabCache
        if not native_ops.available():
            pytest.skip("native library unavailable")
        rng = np.random.default_rng(0)
        # two topic clusters, ids 0-19 and 20-39: co-occurrence only
        # within a cluster
        vocab = VocabCache()
        for i in range(40):
            vocab.add_token(f"w{i}", count=5)
        vocab.finish()
        idx = {f"w{i}": vocab.index_of(f"w{i}") for i in range(40)}
        seqs = []
        for _ in range(300):
            seqs.append([idx[f"w{i}"] for i in rng.choice(20, 8,
                                                          replace=False)])
            seqs.append([idx[f"w{i + 20}"] for i in rng.choice(
                20, 8, replace=False)])
        table = InMemoryLookupTable(vocab, vector_length=24, seed=1,
                                    negative=5,
                                    use_hs=False).reset_weights()
        sg = SkipGram(batch_pairs=4096)
        sg.configure(vocab, table, window=3, negative=5, use_hs=False,
                     seed=1)
        for _ in range(4):
            for i in range(0, len(seqs), 128):
                sg.learn_sequences_batch(seqs[i:i + 128], 0.05)
        sg.finish()
        v = lambda w: table.syn0[idx[w]]
        intra = cosine_sim(v("w0"), v("w1"))
        inter = cosine_sim(v("w0"), v("w20"))
        assert intra > inter + 0.2, (intra, inter)


class TestPrefetchCsvLoader:
    def _write_files(self, tmp_path, n=10):
        rng = np.random.default_rng(0)
        paths, mats = [], []
        for i in range(n):
            m = rng.random((15 + i, 4)).astype(np.float32).round(4)
            p = str(tmp_path / f"f{i:02d}.csv")
            np.savetxt(p, m, delimiter=",", fmt="%.4f")
            paths.append(p)
            mats.append(m)
        return paths, mats

    def test_order_and_values(self, tmp_path):
        if not native_ops.available():
            pytest.skip("native library unavailable")
        paths, mats = self._write_files(tmp_path)
        with native_ops.PrefetchCsvLoader(paths, n_threads=3,
                                          capacity=3) as ld:
            outs = list(ld)
        assert len(outs) == len(mats)
        for a, b in zip(outs, mats):
            np.testing.assert_allclose(a, b, atol=1e-4)

    def test_more_threads_than_files(self, tmp_path):
        if not native_ops.available():
            pytest.skip("native library unavailable")
        paths, mats = self._write_files(tmp_path, n=2)
        with native_ops.PrefetchCsvLoader(paths, n_threads=8) as ld:
            outs = list(ld)
        assert len(outs) == 2

    def test_parse_failure_raises(self, tmp_path):
        if not native_ops.available():
            pytest.skip("native library unavailable")
        bad = str(tmp_path / "bad.csv")
        with open(bad, "w") as fh:
            fh.write("1,2,3\nnot,numbers,here_x\n4\n")
        with native_ops.PrefetchCsvLoader([bad]) as ld:
            with pytest.raises(IOError):
                ld.next()

    def test_sequence_reader_prefetch_matches_python(self, tmp_path):
        """CSVSequenceRecordReader(prefetch=N) yields the same sequences
        as the python csv path, in the same order."""
        from deeplearning4j_tpu.datasets.records import \
            CSVSequenceRecordReader
        if not native_ops.available():
            pytest.skip("native library unavailable")
        paths, _ = self._write_files(tmp_path, n=6)
        plain = CSVSequenceRecordReader(files=paths)
        fast = CSVSequenceRecordReader(files=paths, prefetch=3)
        for _ in range(2):      # includes a reset cycle
            while plain.has_next():
                a = np.asarray(plain.next_sequence(), np.float32)
                b = np.asarray(fast.next_sequence(), np.float32)
                np.testing.assert_allclose(a, b, atol=1e-4)
            assert not fast.has_next()
            plain.reset()
            fast.reset()

    def test_empty_file_matches_python_path(self, tmp_path):
        """A zero-row file yields [] on BOTH the prefetch and python
        paths (the native parser's empty sentinel, not a parse error)."""
        from deeplearning4j_tpu.datasets.records import \
            CSVSequenceRecordReader
        if not native_ops.available():
            pytest.skip("native library unavailable")
        good = str(tmp_path / "a.csv")
        np.savetxt(good, np.ones((3, 2)), delimiter=",", fmt="%.1f")
        empty = str(tmp_path / "b.csv")
        open(empty, "w").close()
        plain = CSVSequenceRecordReader(files=[good, empty])
        fast = CSVSequenceRecordReader(files=[good, empty], prefetch=2)
        assert len(plain.next_sequence()) == len(fast.next_sequence()) == 3
        assert plain.next_sequence() == fast.next_sequence() == []


class TestCbowContexts:
    def test_window1_rows(self):
        if not native_ops.available():
            pytest.skip("native library unavailable")
        ids = np.array([5, 6, 7], np.int32)
        offs = np.array([0, 3], np.int64)
        ctx, tgt = native_ops.cbow_contexts(ids, offs, window=1, seed=1)
        assert tgt.tolist() == [5, 6, 7]
        assert ctx.shape == (3, 2)
        assert ctx[0].tolist() == [6, -1]         # only right neighbor
        assert sorted(ctx[1].tolist()) == [5, 7]  # both neighbors
        assert ctx[2].tolist() == [6, -1]

    def test_cbow_batch_trains_to_cluster_quality(self):
        """Native context rows train CBOW embeddings to the same
        topic-cluster structure as the per-sequence path."""
        from deeplearning4j_tpu.models.embeddings.learning import CBOW
        from deeplearning4j_tpu.models.embeddings.lookup_table import \
            InMemoryLookupTable
        from deeplearning4j_tpu.models.embeddings.model_utils import \
            cosine_sim
        from deeplearning4j_tpu.models.word2vec.vocab import VocabCache
        if not native_ops.available():
            pytest.skip("native library unavailable")
        rng = np.random.default_rng(0)
        vocab = VocabCache()
        for i in range(40):
            vocab.add_token(f"w{i}", count=5)
        vocab.finish()
        idx = {f"w{i}": vocab.index_of(f"w{i}") for i in range(40)}
        seqs = []
        for _ in range(300):
            seqs.append([idx[f"w{i}"] for i in rng.choice(
                20, 8, replace=False)])
            seqs.append([idx[f"w{i + 20}"] for i in rng.choice(
                20, 8, replace=False)])
        table = InMemoryLookupTable(vocab, vector_length=24, seed=1,
                                    negative=5,
                                    use_hs=False).reset_weights()
        cb = CBOW(batch_pairs=2048)
        cb.configure(vocab, table, window=3, negative=5, use_hs=False,
                     seed=1)
        for _ in range(6):
            for i in range(0, len(seqs), 128):
                cb.learn_sequences_batch(seqs[i:i + 128], 0.05)
        cb.finish()
        v = lambda w: table.syn0[idx[w]]
        intra = cosine_sim(v("w0"), v("w1"))
        inter = cosine_sim(v("w0"), v("w20"))
        assert intra > inter + 0.2, (intra, inter)


class TestGloveCooc:
    def test_matches_python_counts(self):
        """Native co-occurrence counting == the python dict loop exactly
        (same windowed 1/distance weights, symmetric counting)."""
        if not native_ops.available():
            pytest.skip("native library unavailable")
        rng = np.random.default_rng(0)
        seqs = [rng.integers(0, 30, rng.integers(2, 15)).astype(np.int32)
                for _ in range(40)]
        ids = np.concatenate(seqs)
        offs = np.zeros(len(seqs) + 1, np.int64)
        np.cumsum([len(s) for s in seqs], out=offs[1:])
        for symmetric in (True, False):
            ci, cj, cx = native_ops.glove_cooc(ids, offs, window=4,
                                               symmetric=symmetric)
            native = {(int(a), int(b)): float(x)
                      for a, b, x in zip(ci, cj, cx)}
            python = {}
            for s in seqs:
                n = len(s)
                for i in range(n):
                    for off in range(1, 5):
                        j = i + off
                        if j >= n:
                            break
                        w = 1.0 / off
                        python[(int(s[i]), int(s[j]))] = python.get(
                            (int(s[i]), int(s[j])), 0.0) + w
                        if symmetric:
                            python[(int(s[j]), int(s[i]))] = python.get(
                                (int(s[j]), int(s[i])), 0.0) + w
            assert set(native) == set(python)
            for k in python:
                assert abs(native[k] - python[k]) < 1e-4, k
