"""Native C++ runtime library: build, IDX/CSV parser equivalence vs python,
staging-buffer pool reuse. The toolchain exists in CI images; tests skip
gracefully when it does not (the library itself always has python fallbacks).
"""
import os
import struct

import numpy as np
import pytest

from deeplearning4j_tpu.common import native_ops


def _require_native():
    if not native_ops.available():
        pytest.skip("native toolchain unavailable")


def test_idx_parser_matches_python(tmp_path):
    _require_native()
    rng = np.random.default_rng(0)
    data = rng.integers(0, 256, (5, 4, 3), dtype=np.uint8)
    p = tmp_path / "test-idx3-ubyte"
    with open(p, "wb") as f:
        f.write(struct.pack(">HBB", 0, 0x08, 3))
        f.write(struct.pack(">III", 5, 4, 3))
        f.write(data.tobytes())
    native = native_ops.read_idx_u8(str(p))
    assert native is not None
    assert native.shape == (5, 4, 3)
    assert np.array_equal(native, data.astype(np.float32))
    # and through the public read_idx (uses native path)
    from deeplearning4j_tpu.datasets.mnist import read_idx
    assert np.array_equal(np.asarray(read_idx(str(p)), np.float32),
                          data.astype(np.float32))


def test_csv_parser_matches_python(tmp_path):
    _require_native()
    p = tmp_path / "m.csv"
    p.write_text("hdr1,hdr2,hdr3\n1.5,2,3\n-4,5e-2,6\n7,8,9.25\n")
    mat = native_ops.parse_csv(str(p), ",", skip_lines=1)
    assert mat is not None
    want = np.array([[1.5, 2, 3], [-4, 0.05, 6], [7, 8, 9.25]], np.float32)
    assert np.allclose(mat, want)
    # non-numeric -> None (callers fall back to python csv)
    p2 = tmp_path / "s.csv"
    p2.write_text("a,b\nc,d\n")
    assert native_ops.parse_csv(str(p2), ",") is None


def test_csv_record_reader_uses_native(tmp_path):
    p = tmp_path / "data.csv"
    p.write_text("1,2,0\n3,4,1\n")
    from deeplearning4j_tpu.datasets import (CSVRecordReader,
                                             RecordReaderDataSetIterator)
    rr = CSVRecordReader(str(p))
    it = RecordReaderDataSetIterator(rr, 2, label_index=2, num_classes=2)
    ds = it.next_batch()
    assert np.array_equal(ds.features, [[1, 2], [3, 4]])
    assert np.array_equal(ds.labels, [[1, 0], [0, 1]])


def test_staging_pool_reuse():
    _require_native()
    pool = native_ops.StagingBufferPool()
    p1 = pool.acquire(1 << 16)
    arr = pool.as_array(p1, (128, 128), np.float32)
    arr[:] = 7.0
    assert arr.sum() == 7.0 * 128 * 128
    pool.release(p1, 1 << 16)
    p2 = pool.acquire(1 << 14)   # smaller request reuses the freed buffer
    assert p2 == p1
    stats = pool.stats()
    assert stats["allocated"] == 1
    assert stats["reused"] == 1
    pool.release(p2, 1 << 16)
    pool.close()
