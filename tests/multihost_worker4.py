"""Worker for the scaled multi-host test (test_multihost.py, 4 processes).

Proves six things beyond the 2-process minimum (VERDICT r2 item 9):
  A. a mesh whose MODEL axis spans process boundaries (2 local devices per
     process, mesh data=2 x model=4: each model row covers 2 processes)
     trains with tensor parallelism over the cross-process axis;
  B. a TrainingMaster run on the multi-host mesh with per-process input
     slices (each process feeds its local fraction of every global batch);
  C. MagicQueue stages per-device shards onto this process's local devices
     (the per-process input-pipeline role);
  D. GPipe pipeline parallelism with the PIPE axis spanning processes —
     the stage-to-stage ppermute (and its autodiff transpose) rides the
     DCN boundary, and the pipelined transformer LM trains;
  E. Switch-MoE expert parallelism with 8 experts over the 8 global
     devices — the token-dispatch all_to_all crosses processes, and the
     output checksum matches the dense single-host reference;
  F. ring-attention sequence parallelism with the seq axis spanning
     processes — K/V ppermute hops ride DCN, output == the single-device
     reference.

Usage: python tests/multihost_worker4.py <proc_id> <nproc> <coordinator>
"""
import os
import sys

proc_id, nproc, coord = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=2")

import numpy as np  # noqa: E402

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

from jax.sharding import Mesh  # noqa: E402

from deeplearning4j_tpu import (InputType, MultiLayerNetwork,  # noqa: E402
                                NeuralNetConfiguration)
from deeplearning4j_tpu.datasets.dataset import DataSet  # noqa: E402
from deeplearning4j_tpu.datasets.iterators import \
    ListDataSetIterator  # noqa: E402
from deeplearning4j_tpu.nn.conf.layers import (DenseLayer,  # noqa: E402
                                               OutputLayer)
from deeplearning4j_tpu.parallel import (MagicQueue,  # noqa: E402
                                         ParameterAveragingTrainingMaster,
                                         distributed)
from deeplearning4j_tpu.parallel.parallel_wrapper import \
    ParallelWrapper  # noqa: E402


def _net(seed=7):
    conf = (NeuralNetConfiguration.Builder().seed(seed).learning_rate(0.2)
            .updater("sgd").list()
            .layer(0, DenseLayer(n_out=16, activation="relu"))
            .layer(1, OutputLayer(n_out=3, activation="softmax",
                                  loss_function="mcxent"))
            .set_input_type(InputType.feed_forward(4)).build())
    return MultiLayerNetwork(conf).init()


def _global_data(n=128):
    rng = np.random.default_rng(0)
    centers = rng.normal(0, 3, (3, 4))
    c = rng.integers(0, 3, n)
    gx = (centers[c] + rng.normal(0, 0.5, (n, 4))).astype(np.float32)
    gy = np.eye(3, dtype=np.float32)[c]
    return gx, gy


def main():
    ok = distributed.initialize(coord, nproc, proc_id)
    assert ok
    assert jax.process_count() == nproc
    n_dev = jax.device_count()
    assert n_dev == 2 * nproc and len(jax.local_devices()) == 2

    # --- A: model axis spanning processes -----------------------------
    devices = np.array(jax.devices()).reshape(2, n_dev // 2)
    mesh_tp = Mesh(devices, ("data", "model"))
    # each model row covers n_dev//2 = 4 devices = 2 processes
    row_procs = {d.process_index for d in devices[0]}
    assert len(row_procs) > 1, "model axis must span processes"

    net_a = _net()
    gx, gy = _global_data(64)
    sl = distributed.process_local_batch_slice(64)
    pw = (ParallelWrapper.Builder(net_a).mesh(mesh_tp)
          .tensor_parallel(True).averaging_frequency(1).build())
    for _ in range(3):
        pw.fit(DataSet(gx[sl], gy[sl]))

    def _checksum(net):
        # on-device reduction -> replicated scalar (raw fetch of a
        # model-sharded param would touch non-addressable shards)
        import jax.numpy as jnp
        total = 0.0
        for layer in net._params:
            for v in layer.values():
                total = total + jnp.sum(v)
        return float(total)

    sum_a = _checksum(net_a)

    # --- B: TrainingMaster over the multi-host data mesh --------------
    net_b = _net()
    mesh_dp = distributed.global_mesh()          # all devices on "data"
    tm = (ParameterAveragingTrainingMaster.Builder(batch_size_per_worker=4)
          .workers(n_dev).averaging_frequency(2)
          .rdd_training_approach("direct").mesh(mesh_dp).build())
    gx2, gy2 = _global_data(128)
    sl2 = distributed.process_local_batch_slice(128)
    tm.execute_training(net_b, DataSet(gx2[sl2], gy2[sl2]))
    sum_b = _checksum(net_b)

    # --- C: MagicQueue staging onto this process's local devices ------
    local = DataSet(gx2[sl2], gy2[sl2])
    mq = MagicQueue(devices=jax.local_devices(), capacity=2)
    mq.feed(ListDataSetIterator(list(local.batch_by(8))))
    rows = 0
    devs_seen = set()
    while True:
        shard0 = mq.next_for(0)
        shard1 = mq.next_for(1)
        if shard0 is None and shard1 is None:
            break
        for shard in (shard0, shard1):
            if shard is not None and shard.num_examples():
                rows += shard.num_examples()
                devs_seen |= set(shard.features.devices())
    mq.shutdown()
    assert rows == local.num_examples()
    assert devs_seen == set(jax.local_devices())

    # --- D: pipeline parallelism with the pipe axis spanning processes -
    # mesh (data=2, pipe=4): every pipe row covers 2 processes, so the
    # GPipe ppermute hops (and the autodiff backward rotation) cross the
    # DCN boundary
    from deeplearning4j_tpu.models.zoo.transformer import (
        embed_fn, init_lm, lm_loss, make_block_fn)
    from deeplearning4j_tpu.parallel.pipeline import PipelineParallel
    mesh_pp = Mesh(devices, ("data", "pipe"))
    pipe_procs = {d.process_index for d in devices[0]}
    assert len(pipe_procs) > 1, "pipe axis must span processes"
    aux, blocks = init_lm(11, d_model=16, n_heads=2, n_layers=4,
                          max_len=8, seed=3)
    pp = PipelineParallel(make_block_fn(2), blocks, mesh_pp,
                          loss_fn=lm_loss, aux_params=aux,
                          pre_fn=embed_fn, n_micro=2, data_axis="data",
                          learning_rate=0.3, momentum=0.9)
    rng_pp = np.random.default_rng(0)
    xt_global = rng_pp.integers(0, 11, (8, 8)).astype(np.int32)
    yt_global = (xt_global + 1) % 11
    # the batch dim shards over "data" (2 rows), each row spanning 2
    # processes: this process feeds its DATA ROW's slice (row-mates feed
    # identical copies — make_array_from_process_local_data semantics)
    my_rows = [r for r in range(devices.shape[0])
               if any(d.process_index == proc_id for d in devices[r])]
    assert len(my_rows) == 1
    per_row = 8 // devices.shape[0]
    sl_pp = slice(my_rows[0] * per_row, (my_rows[0] + 1) * per_row)
    first_pp = pp.fit_batch(xt_global[sl_pp], yt_global[sl_pp])
    for _ in range(14):
        last_pp = pp.fit_batch(xt_global[sl_pp], yt_global[sl_pp])
    assert np.isfinite(last_pp) and last_pp < first_pp, (first_pp, last_pp)

    # --- E: expert parallelism with all_to_all crossing processes ------
    # 8 experts over 8 global devices (2 per process): the token dispatch
    # all_to_all and the return hop both ride the DCN boundary
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    from deeplearning4j_tpu.parallel.moe import (init_moe,
                                                 make_expert_mesh,
                                                 moe_mlp_dense,
                                                 moe_mlp_sharded,
                                                 shard_moe_params)
    from deeplearning4j_tpu.parallel.sharding import put_sharded
    ep_mesh = make_expert_mesh(n_dev)
    moe_p = init_moe(jax.random.PRNGKey(0), 8, n_dev, 16)
    moe_ps = shard_moe_params(moe_p, ep_mesh)
    rng_ep = np.random.default_rng(3)
    x_glob = rng_ep.standard_normal((8 * n_dev, 8)).astype(np.float32)
    sl_ep = distributed.process_local_batch_slice(8 * n_dev)
    x_sh = put_sharded(x_glob[sl_ep], NamedSharding(ep_mesh, P("expert")))
    apply_ep = moe_mlp_sharded(ep_mesh)

    @jax.jit
    def ep_checksum(ps, x):
        y, aux = apply_ep(ps, x)
        return jnp.sum(y), aux

    cs_ep, _ = ep_checksum(moe_ps, x_sh)
    y_ref, _ = moe_mlp_dense(moe_p, jnp.asarray(x_glob))
    assert abs(float(cs_ep) - float(jnp.sum(y_ref))) < 1e-2, \
        (float(cs_ep), float(jnp.sum(y_ref)))

    # --- F: ring attention with the sequence axis spanning processes ---
    # K/V blocks rotate over the DCN boundary via ppermute; the folded
    # output must equal the single-device reference on the global batch
    from deeplearning4j_tpu.parallel.ring_attention import (
        blockwise_attention, ring_self_attention)
    seq_mesh = Mesh(np.array(jax.devices()), ("seq",))
    rng_sp = np.random.default_rng(4)
    T_glob = 4 * n_dev
    q_glob = rng_sp.standard_normal((2, T_glob, 2, 8)).astype(np.float32)
    t_sl = distributed.process_local_batch_slice(T_glob)
    q_sh = put_sharded(q_glob[:, t_sl],
                       NamedSharding(seq_mesh, P(None, "seq")))
    mask_sh = put_sharded(np.ones((2, T_glob // nproc), np.float32),
                          NamedSharding(seq_mesh, P(None, "seq")))
    ring = ring_self_attention(q_sh, q_sh, q_sh, seq_mesh, axis="seq",
                               causal=True, kv_mask=mask_sh)
    cs_ring = float(jax.jit(jnp.sum)(ring))
    full = blockwise_attention(jnp.asarray(q_glob), jnp.asarray(q_glob),
                               jnp.asarray(q_glob), causal=True)
    assert abs(cs_ring - float(jnp.sum(full))) < 1e-2, \
        (cs_ring, float(jnp.sum(full)))

    print(f"RESULT {proc_id} tp={sum_a:.10f} tm={sum_b:.10f} "
          f"score={float(net_b._score):.10f} pp={last_pp:.10f} "
          f"ep={float(cs_ep):.6f} sp={cs_ring:.6f}", flush=True)


if __name__ == "__main__":
    main()
