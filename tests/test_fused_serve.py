"""Fused decode windows (ISSUE 18 acceptance criteria).

  (a) Bit-identity: a `fused_serve=K` server's greedy token stream is
      IDENTICAL to K host-scheduled iterations — solo, co-batched
      (joining a running window'd batch), both cache layouts
      (fixed-slot and paged block-table), across a mid-stream hot
      swap, for K in {2, 4, 8}. fused_serve=1 is the plain path
      exactly: no window program is even built.
  (b) Amortization: exactly ceil(iterations / K) decode dispatches on
      a solo stream, and the `iterations_per_dispatch` /
      `fused_windows` snapshot keys record the win.
  (c) Window boundaries: admissions land between windows and still
      produce the solo stream; the mid-window deadline clamp falls
      back to the plain per-iteration path whenever the tightest live
      deadline lacks K iterations of headroom, so a tight-deadline
      request is evicted at the K=1 sweep cadence (+ one iteration of
      slack), never K-1 iterations late.
  (d) Composition: speculate= is refused LOUDLY at the constructor
      (the PR 8 precedent — no silent mode pick); chunked prefill
      composes (transitions happen at window boundaries).
  (e) Faults: a terminal fault at `serve.batch` mid-window fails the
      occupied slots LOUDLY and resets device state (the server keeps
      serving); a retried transient keeps the stream bit-identical.
  (f) Estimator fan-out: a fused window feeds the admission estimator
      K per-iteration samples (window wall / K), not one K-sized
      sample — the rolling median stays per-iteration instead of
      inflating ~K-fold and shedding feasible work.
"""
import math
import time

import numpy as np
import pytest

from deeplearning4j_tpu.common.resilience import (FaultInjected,
                                                  FaultInjector,
                                                  RetryPolicy)
from deeplearning4j_tpu.models.zoo.transformer import TransformerLM
from deeplearning4j_tpu.serving import (AdmissionController,
                                        ContinuousDecodeServer,
                                        DeadlineExceededError, NGramDraft,
                                        ServiceRateEstimator, Speculator)


def _lm(seed=3, max_len=64):
    return TransformerLM(64, d_model=32, n_heads=2, n_layers=2,
                         max_len=max_len, seed=seed)


def _prompt(seed=4, n=5):
    return np.random.default_rng(seed).integers(1, 64, n).tolist()


# ---------------------------------------------------------------------------
# device programs
# ---------------------------------------------------------------------------
class TestFusedPrograms:
    def test_window_k_floor(self):
        """k=1 is the plain decode program — the factories refuse it
        (scan overhead for nothing), mirroring the chunk-size floor's
        loud-constructor style."""
        from deeplearning4j_tpu.models.zoo.transformer import (
            make_fused_decode_fn, make_paged_fused_decode_fn)
        with pytest.raises(ValueError, match=">= 2"):
            make_fused_decode_fn(2, 1)
        with pytest.raises(ValueError, match=">= 2"):
            make_paged_fused_decode_fn(2, 8, 1)

    def test_server_flag_validation(self):
        with pytest.raises(ValueError, match="fused_serve"):
            ContinuousDecodeServer(_lm(), slots=2, prompt_buckets=(8,),
                                   fused_serve=0)


# ---------------------------------------------------------------------------
# (a) bit-identity
# ---------------------------------------------------------------------------
class TestFusedBitIdentity:
    def test_solo_and_join_bit_identical_across_k(self):
        """For K in {2,4,8}: a fused solo stream matches plain decode,
        and a request JOINING a running fused batch (admitted at a
        window boundary) matches its own solo stream — the
        continuous-decode pin under windowed advance."""
        lm = _lm()
        rng = np.random.default_rng(4)
        pa = rng.integers(1, 64, 5).tolist()
        pb = rng.integers(1, 64, 8).tolist()
        plain = lm.generate(pa, 10, use_cache=True)
        for k in (2, 4, 8):
            with ContinuousDecodeServer(
                    lm, slots=4, prompt_buckets=(4, 8),
                    fused_serve=k) as srv:
                solo = srv.generate(pa, 10, timeout=60)
                flong = srv.submit(pb, 24)      # running fused batch
                time.sleep(0.05)
                fa = srv.submit(pa, 10)         # joins at a boundary
                joined = fa.result(60)
                flong.result(60)
            assert solo == plain
            assert joined == solo

    def test_paged_bit_identical_across_k(self):
        """Same pin over the PAGED layout: the scanned window threads
        the block-table frontier through the carry and never crosses
        the reservation (pool fully drains after)."""
        lm = _lm()
        p = _prompt()
        plain = lm.generate(p, 14, use_cache=True)
        for k in (2, 4, 8):
            with ContinuousDecodeServer(
                    lm, slots=2, prompt_buckets=(8,), paged=True,
                    block_size=4, n_blocks=40, fused_serve=k) as srv:
                got = srv.generate(p, 14, timeout=60)
                flong = srv.submit(_prompt(9, 6), 18)
                fa = srv.submit(p, 14)
                joined = fa.result(60)
                flong.result(60)
                assert srv._pool.blocks_in_use == 0
            assert got == plain
            assert joined == plain

    def test_swap_drain_fused(self):
        """Dual-version drain under fused windows: one fused window
        per live version per pass — the in-flight stream finishes on
        pre-swap params bit-identical to a pre-swap solo run while a
        post-swap request decodes the new params."""
        lm1, lm2 = _lm(3), _lm(11)
        rng = np.random.default_rng(10)
        pa = rng.integers(1, 64, 4).tolist()
        pb = rng.integers(1, 64, 4).tolist()
        with ContinuousDecodeServer(
                lm1, slots=2, prompt_buckets=(4,),
                fused_serve=4) as srv:
            solo_old = srv.generate(pa, 14, timeout=60)
            fa = srv.submit(pa, 14)
            time.sleep(0.03)                  # pa decoding on v0
            srv.swap(lm2)
            fb = srv.submit(pb, 5)            # admitted on v1
            ra, rb = fa.result(60), fb.result(60)
        assert ra == solo_old
        expect_new = lm2.generate_batch(np.asarray([pb], np.int32),
                                        max_new_tokens=5)
        assert rb == expect_new[0].tolist()
        assert srv.metrics.snapshot().get("failed", 0) == 0

    def test_k1_is_zero_behavior_change(self):
        """fused_serve=1 (and the default None) build NO window
        program and count NO windows — the plain path, untouched."""
        lm = _lm()
        p = _prompt()
        with ContinuousDecodeServer(lm, slots=2, prompt_buckets=(8,),
                                    fused_serve=1) as srv:
            assert srv._window_step is None
            got = srv.generate(p, 10, timeout=60)
        snap = srv.metrics.snapshot()
        assert got == lm.generate(p, 10, use_cache=True)
        assert snap["fused_windows"] == 0
        assert snap["iterations_per_dispatch"] == pytest.approx(1.0)


# ---------------------------------------------------------------------------
# (b) amortization accounting
# ---------------------------------------------------------------------------
class TestFusedDispatchCount:
    @pytest.mark.parametrize("max_new,k", [(13, 4), (12, 4), (17, 8)])
    def test_exactly_ceil_iters_over_k_dispatches(self, max_new, k):
        """Solo stream: max_new-1 decode iterations (the first token
        comes from prefill) in exactly ceil((max_new-1)/K) decode
        dispatches — the A/B the amortization claim rests on."""
        lm = _lm()
        with ContinuousDecodeServer(lm, slots=2, prompt_buckets=(8,),
                                    fused_serve=k) as srv:
            got = srv.generate(_prompt(), max_new, timeout=60)
        assert got == lm.generate(_prompt(), max_new, use_cache=True)
        snap = srv.metrics.snapshot()
        iters = max_new - 1
        assert snap["decode_iterations"] == iters
        assert snap["dispatches"] == math.ceil(iters / k)
        assert snap["fused_windows"] == snap["dispatches"]
        assert snap["iterations_per_dispatch"] == pytest.approx(
            iters / math.ceil(iters / k))


# ---------------------------------------------------------------------------
# (c) window boundaries: deadline clamp
# ---------------------------------------------------------------------------
class TestFusedDeadlines:
    def test_window_ok_gate(self):
        """The clamp's decision table, directly: no deadlines -> fused;
        any deadline + cold EWMA -> plain (conservative warm-up); ample
        headroom -> fused; headroom under K iterations -> plain."""

        class R:
            def __init__(self, deadline):
                self.deadline = deadline

        srv = ContinuousDecodeServer(_lm(), slots=2, prompt_buckets=(8,),
                                     fused_serve=4)
        try:
            now = time.monotonic()
            assert srv._fused_window_ok([(0, R(None))])
            assert not srv._fused_window_ok([(0, R(now + 60.0))])  # cold
            srv._iter_ewma = 0.01
            assert srv._fused_window_ok([(0, R(now + 60.0))])
            assert not srv._fused_window_ok([(0, R(now + 0.02))])
            # the TIGHTEST deadline governs a mixed batch
            assert not srv._fused_window_ok(
                [(0, R(now + 60.0)), (1, R(now + 0.02))])
        finally:
            srv.stop()

    def test_tight_horizon_falls_back_to_plain(self):
        """With the EWMA seeded at 10 s/iteration, a 4 s-deadline
        request can never afford an 8-iteration window (the EWMA
        decays by at most 0.8^11 over the stream's 11 iterations, so
        the horizon stays above the headroom throughout): every round
        takes the plain path (fused_windows stays 0) and the stream
        still completes bit-identical."""
        lm = _lm()
        p = _prompt()
        with ContinuousDecodeServer(lm, slots=2, prompt_buckets=(8,),
                                    fused_serve=8) as srv:
            srv._iter_ewma = 10.0
            got = srv.generate(p, 12, deadline_ms=4_000, timeout=60)
        assert got == lm.generate(p, 12, use_cache=True)
        assert srv.metrics.snapshot()["fused_windows"] == 0

    def test_tight_deadline_evicted_at_plain_cadence(self):
        """A request whose token budget outlives its latency budget
        under fused_serve=8 is evicted by the boundary sweep no later
        than the K=1 cadence + one iteration of slack — the clamp
        forces plain rounds (per-iteration sweeps) once headroom drops
        below the window horizon, so eviction lateness is iteration
        granularity, not K-1 iterations of overshoot. Delay-only
        faults pace every dispatch at 20 ms so the cadences are
        distinguishable on wall clock: a mis-clamped window would
        overshoot by ~8 x 20 ms; the clamp keeps lateness under half
        a window."""
        lm = _lm()
        inj = FaultInjector(seed=6).plan(
            "serve.batch", on_calls=range(1, 300), times=300,
            delay=0.02, exc=None)
        with ContinuousDecodeServer(lm, slots=1, prompt_buckets=(8,),
                                    fault_injector=inj,
                                    fused_serve=8) as srv:
            # warm-up compiles BOTH decode programs off-clock (a loose
            # deadline starts plain while the EWMA is cold, then fuses
            # once it warms), so the doomed request's lateness measures
            # cadence, not first-dispatch compilation
            srv.generate(_prompt(), 12, deadline_ms=60_000, timeout=60)
            t0 = time.monotonic()
            f = srv.submit(_prompt(), 40, deadline_ms=100)
            with pytest.raises(DeadlineExceededError,
                               match="mid-decode"):
                f.result(60)
            late = (time.monotonic() - t0) - 0.1
        snap = srv.metrics.snapshot()
        assert snap["evicted_mid_decode"] == 1
        assert late < 0.1


# ---------------------------------------------------------------------------
# (d) composition
# ---------------------------------------------------------------------------
class TestFusedComposition:
    def test_speculate_refused_loudly(self):
        """fused_serve > 1 + speculate= is a constructor ValueError
        (the PR 8 precedent): a window cannot take fresh host drafts
        mid-scan, and silently picking one mode would lie about the
        other."""
        with pytest.raises(ValueError, match="speculate"):
            ContinuousDecodeServer(
                _lm(), slots=2, prompt_buckets=(8,), fused_serve=4,
                speculate=Speculator(NGramDraft(), k=4))
        # fused_serve=1 (the plain path) composes fine
        srv = ContinuousDecodeServer(
            _lm(), slots=2, prompt_buckets=(8,), fused_serve=1,
            speculate=Speculator(NGramDraft(), k=4))
        srv.stop()

    def test_chunked_prefill_composes(self):
        """Chunk transitions land at window boundaries: a long prompt
        prefills chunk-at-a-time while a co-resident stream decodes in
        fused windows, and both streams stay bit-identical."""
        lm = _lm()
        rng = np.random.default_rng(7)
        long_p = rng.integers(1, 64, 24).tolist()
        short_p = rng.integers(1, 64, 4).tolist()
        with ContinuousDecodeServer(
                lm, slots=2, prompt_buckets=(4, 8, 32),
                chunked_prefill=8, fused_serve=4) as srv:
            fs = srv.submit(short_p, 16)
            time.sleep(0.03)                  # decoding mid-window
            fl = srv.submit(long_p, 8)        # chunked joiner
            rs, rl = fs.result(60), fl.result(60)
        assert rs == lm.generate(short_p, 16, use_cache=True)
        assert rl == lm.generate(long_p, 8, use_cache=True)


# ---------------------------------------------------------------------------
# (e) faults
# ---------------------------------------------------------------------------
class TestFusedFaults:
    def test_terminal_fault_mid_window_fails_loudly_and_recovers(self):
        """Terminal fault at `serve.batch` on the first WINDOW dispatch
        (call 0 is the admission prefill): the occupied slot fails
        LOUDLY, device state resets, and the server serves the next
        request bit-identically — the PR 4 contract under windows."""
        lm = _lm()
        p = _prompt()
        inj = FaultInjector(seed=2).plan("serve.batch", on_call=1,
                                         exc=FaultInjected)
        with ContinuousDecodeServer(lm, slots=2, prompt_buckets=(8,),
                                    fault_injector=inj,
                                    fused_serve=4) as srv:
            f = srv.submit(p, 6)
            with pytest.raises(FaultInjected):
                f.result(60)
            got = srv.generate(p, 6, timeout=60)
        assert got == lm.generate(p, 6, use_cache=True)
        assert srv.metrics.snapshot().get("failed") == 1

    def test_retry_keeps_stream_bit_identical(self):
        """Transient fault before the first window dispatch: the retry
        re-runs the whole window (the injector site sits before the
        compiled call — donated buffers are untouched) and the stream
        is unchanged."""
        lm = _lm()
        p = _prompt()
        inj = FaultInjector(seed=1).plan("serve.batch", on_call=1,
                                         exc=FaultInjected)
        rp = RetryPolicy(max_retries=3, base_delay=0.001,
                         retryable=(ConnectionError,))
        with ContinuousDecodeServer(lm, slots=2, prompt_buckets=(8,),
                                    fault_injector=inj, retry_policy=rp,
                                    fused_serve=4) as srv:
            got = srv.generate(p, 10, timeout=60)
        snap = srv.metrics.snapshot()
        assert got == lm.generate(p, 10, use_cache=True)
        assert snap.get("retries") == 1 and snap.get("failed", 0) == 0


# ---------------------------------------------------------------------------
# (f) estimator fan-out
# ---------------------------------------------------------------------------
class TestFusedEstimator:
    def test_window_feeds_k_per_iteration_samples(self):
        """The fan-out contract, deterministically: a K=8 window of
        0.8 s with 2 slots at full budget feeds 8 samples of
        (2 tokens, 0.1 s) — the median reads the PER-ITERATION time
        and readiness arrives after one window. One K-sized sample
        (the bug this satellite fixes) would leave the estimator cold
        for 8x longer AND inflate its median ~K-fold, shedding
        feasible work."""
        window_dt, k = 0.8, 8
        steps = np.asarray([8, 8, 0, 0])
        est = ServiceRateEstimator(slots=4)
        for i in range(k):
            t_i = int(np.sum(steps > i))
            est.observe(t_i, window_dt / k, t_i)
        assert est.samples == 8 and est.ready
        assert est.seconds_per_iteration == pytest.approx(0.1)
        assert est.tokens_per_slot_conservative == pytest.approx(1.0)
        bad = ServiceRateEstimator(slots=4)
        bad.observe(16, window_dt, 2)        # the one-sample mistake
        assert bad.samples == 1 and not bad.ready
        assert bad._s_iter == pytest.approx(0.8)   # ~K-fold inflation

    def test_ragged_window_tail_feeds_partial_samples(self):
        """A slot that exhausts its budget mid-window stops counting
        toward later per-iteration samples — token totals across the
        fan-out equal the window's realized tokens exactly."""
        steps = np.asarray([4, 2, 0])
        est = ServiceRateEstimator(slots=3, min_samples=1)
        for i in range(4):
            t_i = int(np.sum(steps > i))
            est.observe(t_i, 0.05, t_i)
        # samples only count token-bearing iterations: steps 0..3 all
        # carry tokens here (2, 2, 1, 1)
        assert est.samples == 4
        tok = sum(t for t, _ in est._win)
        assert tok == int(steps.sum())

    def test_server_estimator_stays_per_iteration_under_fused(self):
        """Integration: a fused K=8 server's admission estimator reads
        a per-iteration median comparable to a plain server's on the
        same workload — not ~8x it."""
        lm = _lm()
        p = _prompt()

        def run(**kw):
            adm = AdmissionController()
            with ContinuousDecodeServer(lm, slots=2, prompt_buckets=(8,),
                                        admission=adm, **kw) as srv:
                srv.generate(p, 20, timeout=60)     # warm-up/compile
                srv.generate(p, 20, timeout=60)
            return adm.estimator

        plain = run()
        fused = run(fused_serve=8)
        assert fused.ready
        assert fused.seconds_per_iteration < \
            4 * max(plain.seconds_per_iteration, 1e-4)
