"""Prediction metadata + ModelGuesser + MagicQueue (VERDICT r2 item 10 +
missing item 7). Mirrors reference eval/meta/Prediction.java,
util/ModelGuesser.java, parallelism/MagicQueue.java tests."""
import numpy as np
import pytest

from deeplearning4j_tpu import (InputType, MultiLayerNetwork,
                                NeuralNetConfiguration)
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterators import ListDataSetIterator
from deeplearning4j_tpu.eval.evaluation import Evaluation, Prediction
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer


def _mln():
    conf = (NeuralNetConfiguration.Builder().seed(7)
            .updater("adam").learning_rate(0.01).list()
            .layer(0, DenseLayer(n_out=8, activation="relu"))
            .layer(1, OutputLayer(n_out=3, activation="softmax",
                                  loss_function="mcxent"))
            .set_input_type(InputType.feed_forward(4))
            .build())
    return MultiLayerNetwork(conf).init()


class TestPredictionMetadata:
    def test_eval_with_meta_records_predictions(self):
        ev = Evaluation()
        labels = np.eye(3, dtype=np.float32)[[0, 1, 2, 0]]
        preds = np.eye(3, dtype=np.float32)[[0, 2, 2, 1]]  # errors at 1, 3
        ev.eval(labels, preds, meta=["r0", "r1", "r2", "r3"])
        errs = ev.get_prediction_errors()
        assert errs == [Prediction(0, 1, "r3"), Prediction(1, 2, "r1")]
        assert ev.get_predictions(1, 2) == [Prediction(1, 2, "r1")]
        assert ev.get_predictions_by_actual_class(0) == [
            Prediction(0, 0, "r0"), Prediction(0, 1, "r3")]
        assert ev.get_predictions_by_predicted_class(2) == [
            Prediction(1, 2, "r1"), Prediction(2, 2, "r2")]

    def test_no_meta_returns_none(self):
        ev = Evaluation()
        ev.eval(np.eye(2, dtype=np.float32)[[0, 1]],
                np.eye(2, dtype=np.float32)[[1, 0]])
        assert ev.get_prediction_errors() is None   # reference returns null

    def test_meta_survives_merge_and_masks(self):
        a, b = Evaluation(), Evaluation()
        labels = np.eye(2, dtype=np.float32)[[0, 1, 1]]
        preds = np.eye(2, dtype=np.float32)[[1, 1, 0]]
        a.eval(labels, preds, mask=np.asarray([1, 0, 1]),
               meta=["x", "y", "z"])   # "y" masked out
        b.eval(np.eye(2, dtype=np.float32)[[1]],
               np.eye(2, dtype=np.float32)[[0]], meta=["w"])
        a.merge(b)
        assert a.get_prediction_errors() == [
            Prediction(0, 1, "x"), Prediction(1, 0, "z"),
            Prediction(1, 0, "w")]

    def test_meta_length_mismatch_raises(self):
        with pytest.raises(ValueError, match="meta length"):
            Evaluation().eval(np.eye(2, dtype=np.float32)[[0]],
                              np.eye(2, dtype=np.float32)[[0]],
                              meta=["a", "b"])

    def test_evaluate_with_meta_through_network(self):
        net = _mln()
        rng = np.random.default_rng(0)
        x = rng.random((12, 4)).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 12)]
        it = ListDataSetIterator(list(DataSet(x, y).batch_by(5)))
        meta = [f"row{i}" for i in range(12)]
        ev = net.evaluate(it, meta=meta)
        errs = ev.get_prediction_errors()
        assert errs is not None
        total = sum(len(v) for v in ev._meta_confusion.values())
        assert total == 12
        # every recorded meta is one of ours
        assert {p.record_meta_data for p in errs} <= set(meta)

    def test_collect_meta_data_from_record_reader(self, tmp_path):
        """reference RecordReaderDataSetIterator.setCollectMetaData path."""
        from deeplearning4j_tpu.datasets import (CSVRecordReader,
                                                 RecordReaderDataSetIterator)
        p = tmp_path / "d.csv"
        p.write_text("1,2,1,2,0\n3,4,3,4,1\n5,6,5,6,2\n7,8,7,8,0\n"
                     "9,1,9,1,1\n")
        it = RecordReaderDataSetIterator(CSVRecordReader(str(p)),
                                         batch_size=2, label_index=4,
                                         num_classes=3,
                                         collect_meta_data=True)
        ds = it.next_batch()
        assert ds.example_metas == [(str(p), 0), (str(p), 1)]
        ds2 = it.next_batch()
        assert ds2.example_metas == [(str(p), 2), (str(p), 3)]
        it.reset()
        assert it.next_batch().example_metas[0] == (str(p), 0)
        net = _mln()
        it.reset()
        ev = net.evaluate(it)
        assert sum(len(v) for v in ev._meta_confusion.values()) == 5


class TestModelGuesser:
    def test_guess_zip_mln(self, tmp_path):
        from deeplearning4j_tpu.util import load_model_guess, write_model
        net = _mln()
        p = str(tmp_path / "m.zip")
        write_model(net, p)
        restored = load_model_guess(p)
        assert isinstance(restored, MultiLayerNetwork)
        assert np.allclose(net.params(), restored.params())

    def test_guess_json_and_yaml_configs(self, tmp_path):
        from deeplearning4j_tpu import ComputationGraph
        from deeplearning4j_tpu.nn.conf.graph_vertices import MergeVertex
        from deeplearning4j_tpu.util import (load_config_guess,
                                             load_model_guess)
        mconf = _mln().conf
        pj = tmp_path / "c.json"
        pj.write_text(mconf.to_json())
        py = tmp_path / "c.yaml"
        py.write_text(mconf.to_yaml())
        for p in (pj, py):
            m = load_model_guess(str(p))
            assert isinstance(m, MultiLayerNetwork)
            assert len(m.conf.layers) == 2
        gconf = (NeuralNetConfiguration.Builder().graph_builder()
                 .add_inputs("in")
                 .add_layer("a", DenseLayer(n_out=4, activation="tanh"), "in")
                 .add_layer("b", DenseLayer(n_out=4, activation="tanh"), "in")
                 .add_vertex("m", MergeVertex(), "a", "b")
                 .add_layer("o", OutputLayer(n_out=2, activation="softmax",
                                             loss_function="mcxent"), "m")
                 .set_outputs("o")
                 .set_input_types(InputType.feed_forward(3))
                 .build())
        pg = tmp_path / "g.yaml"
        pg.write_text(gconf.to_yaml())
        g = load_model_guess(str(pg))
        assert isinstance(g, ComputationGraph)
        # raw strings parse too
        conf2 = load_config_guess(gconf.to_yaml())
        assert conf2.to_json() == gconf.to_json()

    def test_guess_garbage_raises(self, tmp_path):
        from deeplearning4j_tpu.util import load_model_guess
        p = tmp_path / "x.txt"
        p.write_text("definitely: not a [model")
        with pytest.raises(ValueError, match="guess"):
            load_model_guess(str(p))


class TestMagicQueue:
    def test_per_device_bucketing_and_residency(self):
        import jax

        from deeplearning4j_tpu.parallel import MagicQueue
        devices = jax.devices()[:2] if len(jax.devices()) >= 2 \
            else jax.devices()
        n = len(devices)
        rng = np.random.default_rng(0)
        batches = [DataSet(rng.random((8, 3)).astype(np.float32),
                           rng.random((8, 2)).astype(np.float32))
                   for _ in range(3)]
        mq = MagicQueue(devices=devices, capacity=2)
        mq.feed(ListDataSetIterator(batches))
        seen = [0] * n
        for bi in range(3):
            for di in range(n):
                shard = mq.next_for(di)
                assert shard is not None
                assert shard.features.shape[0] == 8 // n
                assert list(shard.features.devices())[0] == devices[di]
                np.testing.assert_array_equal(
                    np.asarray(shard.features),
                    batches[bi].features[di * (8 // n):(di + 1) * (8 // n)])
                seen[di] += 1
        for di in range(n):
            assert mq.next_for(di) is None     # end of stream
        assert seen == [3] * n
        mq.shutdown()

    def test_masks_and_ragged_tail(self):
        import jax

        from deeplearning4j_tpu.parallel import MagicQueue
        devices = jax.devices()[:2] if len(jax.devices()) >= 2 \
            else jax.devices()
        n = len(devices)
        x = np.arange(5 * 3, dtype=np.float32).reshape(5, 3)
        fm = np.ones((5, 3), np.float32)
        ds = DataSet(x, x.copy(), fm, None)
        mq = MagicQueue(devices=devices, capacity=2)
        mq.feed(ListDataSetIterator([ds]))
        rows = 0
        for di in range(n):
            shard = mq.next_for(di)
            if shard is not None:
                rows += shard.features.shape[0]
                assert shard.features_mask is not None
        assert rows == 5
        mq.shutdown()
