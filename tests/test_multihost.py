"""Multi-host DCN-path test: two REAL processes, jax.distributed, a global
mesh, ParallelWrapper steps with per-process batch slices.

The reference has no multi-process test at all (SURVEY.md §4.6 — everything
distributed is simulated in one JVM); this goes beyond that pattern because
the jax.distributed path cannot be exercised in-process.
"""
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

pytestmark = pytest.mark.multiprocess

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_two_process_parallel_wrapper_allreduce():
    coord = f"127.0.0.1:{_free_port()}"
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    env["PYTHONPATH"] = REPO
    script = os.path.join(REPO, "tests", "multihost_worker.py")
    procs = [subprocess.Popen(
        [sys.executable, script, str(i), "2", coord],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True, env=env) for i in range(2)]
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=280)
        outs.append(out)
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {i} failed:\n{out}"
    results = {}
    for out in outs:
        for line in out.splitlines():
            if line.startswith("RESULT"):
                _, pid, s, sc = line.split()
                results[int(pid)] = (float(s.split("=")[1]),
                                     float(sc.split("=")[1]))
    assert set(results) == {0, 1}, f"missing results: {outs}"
    # both processes hold identical averaged params and scores
    assert results[0] == results[1]
    assert np.isfinite(results[0][0]) and np.isfinite(results[0][1])


def test_hierarchical_three_axis_mesh_across_processes():
    """4 processes x 2 virtual devices: one (data=2, model=2, pipe=2)
    mesh whose pipe axis is intra-process (ICI role) while data/model span
    processes (DCN role) — a dp x tp x pp step with Megatron TP blocks
    inside the GPipe rotation, collectives riding both fabrics in one
    program (VERDICT r3 item 9; SURVEY §5.8 north star)."""
    coord = f"127.0.0.1:{_free_port()}"
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    env["PYTHONPATH"] = REPO
    script = os.path.join(REPO, "tests", "multihost_worker_hier.py")
    procs = [subprocess.Popen(
        [sys.executable, script, str(i), "4", coord],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True, env=env) for i in range(4)]
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=280)
        outs.append(out)
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {i} failed:\n{out}"
    results = {}
    for out in outs:
        for line in out.splitlines():
            if line.startswith("RESULT"):
                _, pid, s, l = line.split()
                results[int(pid)] = (s, l)
    assert set(results) == {0, 1, 2, 3}, f"missing results: {outs}"
    assert len(set(results.values())) == 1       # bit-identical params
    assert np.isfinite(float(results[0][0].split("=")[1]))


def test_four_process_model_axis_and_training_master():
    """Scaled multi-host proof (VERDICT r2 item 9): 4 real processes, a
    mesh whose model axis spans process boundaries (tensor parallelism over
    DCN), a TrainingMaster run on the multi-host mesh with per-process
    input slices, and MagicQueue staging per local device."""
    coord = f"127.0.0.1:{_free_port()}"
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    env["PYTHONPATH"] = REPO
    script = os.path.join(REPO, "tests", "multihost_worker4.py")
    procs = [subprocess.Popen(
        [sys.executable, script, str(i), "4", coord],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True, env=env) for i in range(4)]
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=280)
        outs.append(out)
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {i} failed:\n{out}"
    results = {}
    for out in outs:
        for line in out.splitlines():
            if line.startswith("RESULT"):
                _, pid, tp, tm, sc, pp, ep, sp = line.split()
                results[int(pid)] = (tp, tm, sc, pp, ep, sp)
    assert set(results) == {0, 1, 2, 3}, f"missing results: {outs}"
    # every process holds identical parameters after all paths (incl. the
    # cross-process GPipe loss, replicated by the pipeline's masked psum)
    assert len({r for r in results.values()}) == 1
    vals = [float(v.split("=")[1]) for v in results[0]]
    assert all(np.isfinite(v) for v in vals)
