"""Fleet journal + chaos schedule pins (ISSUE 16, stdlib-only half).

  (a) Round trip: append N records -> replay returns them in order,
      every field JSON-faithful, `journal_records` counted per append.
  (b) Torn tail is a CRASH ARTIFACT: a final record cut anywhere (mid
      header, mid payload, corrupted checksum extending to EOF) drops
      SILENTLY — the kvstate discipline for a write that died with the
      process.
  (c) Mid-file damage is CORRUPTION: the same byte-flip with intact
      records after it refuses LOUDLY with `JournalCorruptError` (a
      `KVStateError` — same family every durable-artifact refusal in
      the repo raises).
  (d) Empty/absent journal -> empty record list -> empty fold (a new
      fleet, not an error).
  (e) fold_records: epoch is the max seen, spawn/adopt build the
      roster, drain_begin poisons a replica (mid-drain at recovery is
      never re-adopted), replica_dead/_drained remove, canary_begin
      with no verdict survives the fold (the recovery rollback
      trigger), params tracks the rolled-forward version, minted name
      ordinals resume past the journal's max, unknown kinds are
      ignored (forward compatibility).
  (f) build_chaos_schedule: string-seeded determinism (same seed ==
      same events AND same sha256 digest; different seed differs),
      `require_manager_kill` guarantees at least one manager kill,
      offsets stay inside the middle 80% of the duration.
"""
import os
import struct
import threading

import pytest

from deeplearning4j_tpu.serving import (ChaosSchedule, FleetJournal,
                                        JournalBrokenError,
                                        JournalCorruptError, KVStateError,
                                        ServingMetrics,
                                        build_chaos_schedule,
                                        fold_records, replay_journal)


@pytest.fixture
def jpath(tmp_path):
    return str(tmp_path / "fleet.journal")


def _write(jpath, *recs):
    with FleetJournal(jpath) as j:
        for kind, fields in recs:
            j.append(kind, **fields)
    return open(jpath, "rb").read()


class TestRoundTrip:
    def test_append_replay_order_and_fields(self, jpath):
        _write(jpath,
               ("epoch", {"epoch": 1}),
               ("spawn", {"name": "i0", "seq": 0, "host": "127.0.0.1",
                          "port": 4242, "pid": 77,
                          "start_time": 1723.456789}),
               ("drain_begin", {"name": "i0"}))
        recs = replay_journal(jpath)
        assert [r["kind"] for r in recs] == ["epoch", "spawn",
                                             "drain_begin"]
        # floats survive the JSON round trip EXACTLY — the identity
        # check at re-adoption compares start_time by equality
        assert recs[1]["start_time"] == 1723.456789
        assert recs[1]["port"] == 4242

    def test_journal_records_counted_per_append(self, jpath):
        m = ServingMetrics()
        with FleetJournal(jpath, counters=m) as j:
            for k in range(3):
                j.append("epoch", epoch=k)
        assert m.count_value("journal_records") == 3

    def test_append_survives_reopen(self, jpath):
        _write(jpath, ("epoch", {"epoch": 1}))
        with FleetJournal(jpath) as j:
            j.append("epoch", epoch=2)
        assert [r["epoch"] for r in replay_journal(jpath)] == [1, 2]

    def test_concurrent_appends_never_interleave(self, jpath):
        # crash/drain paths journal from done-callback and heartbeat
        # threads while the control thread journals spawns: records
        # written from many threads must each land contiguous, or
        # replay refuses the whole file exactly when recovery needs it
        n_threads, per_thread = 8, 50
        with FleetJournal(jpath) as j:
            def hammer(tid):
                for k in range(per_thread):
                    j.append("spawn", name=f"t{tid}", seq=k,
                             pad="x" * (17 * (k % 7)))
            threads = [threading.Thread(target=hammer, args=(t,))
                       for t in range(n_threads)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        recs = replay_journal(jpath)    # would raise on interleaving
        assert len(recs) == n_threads * per_thread
        # per-thread order preserved, no record lost or duplicated
        for tid in range(n_threads):
            seqs = [r["seq"] for r in recs if r["name"] == f"t{tid}"]
            assert seqs == list(range(per_thread))


class TestAppendFailure:
    """A failed append must leave the file replayable: truncate back
    to the last good record boundary, or refuse all further writes."""

    def test_failed_append_truncates_to_good_boundary(self, jpath,
                                                      monkeypatch):
        import deeplearning4j_tpu.serving.fleetjournal as fj

        def boom(fd):
            raise OSError("disk full")
        with FleetJournal(jpath) as j:
            j.append("epoch", epoch=1)
            good = j._good
            monkeypatch.setattr(fj.os, "fsync", boom)
            with pytest.raises(OSError):
                j.append("spawn", name="i0", seq=0)
            monkeypatch.undo()
            # the unsynced record was truncated away: the journal is
            # NOT broken and the next append lands at the good boundary
            assert j._good == good
            j.append("spawn", name="i1", seq=1)
        recs = replay_journal(jpath)
        assert [r["kind"] for r in recs] == ["epoch", "spawn"]
        assert recs[1]["name"] == "i1"

    def test_broken_journal_refuses_further_appends(self, jpath,
                                                    monkeypatch):
        import deeplearning4j_tpu.serving.fleetjournal as fj
        j = FleetJournal(jpath)
        j.append("epoch", epoch=1)
        real_fh = j._fh

        class TornFile:         # dies 5 bytes into the record
            def write(self, mv):
                real_fh.write(bytes(mv[:5]))
                raise OSError("disk full mid-record")

            def fileno(self):
                return real_fh.fileno()

            def close(self):
                real_fh.close()

        def boom(*a):
            raise OSError("disk gone")
        monkeypatch.setattr(fj.os, "ftruncate", boom)
        j._fh = TornFile()
        with pytest.raises(OSError):
            j.append("spawn", name="i0", seq=0)
        monkeypatch.undo()
        j._fh = real_fh
        # the write tore mid-record AND the truncate failed: writing
        # after the torn bytes would corrupt the file mid-stream, so
        # every further append refuses
        with pytest.raises(JournalBrokenError):
            j.append("spawn", name="i1", seq=1)
        j.close()
        # the tear stayed at EOF: replay still recovers the prefix
        assert [r["kind"] for r in replay_journal(jpath)] == ["epoch"]

    def test_broken_error_is_kvstate_family(self, jpath):
        with FleetJournal(jpath) as j:
            j._broken = True
            with pytest.raises(KVStateError):
                j.append("epoch", epoch=1)


class TestTornTail:
    """A damaged FINAL record is the signature of dying mid-write:
    every cut point must drop it silently and keep the prefix."""

    def _cut(self, jpath, data, keep):
        with open(jpath, "wb") as fh:
            fh.write(data[:keep])

    @pytest.mark.parametrize("cut_from_end", [1, 3, 7])
    def test_truncated_payload_dropped(self, jpath, cut_from_end):
        data = _write(jpath, ("epoch", {"epoch": 1}),
                      ("spawn", {"name": "i0", "seq": 0}))
        self._cut(jpath, data, len(data) - cut_from_end)
        recs = replay_journal(jpath)
        assert [r["kind"] for r in recs] == ["epoch"]

    def test_truncated_header_dropped(self, jpath):
        data = _write(jpath, ("epoch", {"epoch": 1}),
                      ("spawn", {"name": "i0", "seq": 0}))
        hdr = struct.Struct("<II")
        first_end = hdr.size + hdr.unpack_from(data, 0)[0]
        self._cut(jpath, data, first_end + 4)   # half the next header
        assert [r["kind"] for r in replay_journal(jpath)] == ["epoch"]

    def test_corrupt_final_record_dropped(self, jpath):
        data = bytearray(_write(jpath, ("epoch", {"epoch": 1}),
                                ("spawn", {"name": "i0", "seq": 0})))
        data[-2] ^= 0xFF                        # CRC mismatch at EOF
        with open(jpath, "wb") as fh:
            fh.write(bytes(data))
        assert [r["kind"] for r in replay_journal(jpath)] == ["epoch"]


class TestCorruption:
    def test_mid_file_flip_refuses_loudly(self, jpath):
        data = bytearray(_write(jpath, ("epoch", {"epoch": 1}),
                                ("spawn", {"name": "i0", "seq": 0})))
        hdr = struct.Struct("<II")
        first_len = hdr.unpack_from(bytes(data), 0)[0]
        data[hdr.size + 2] ^= 0xFF      # inside record 0's payload,
        assert first_len > 2            # records after it intact
        with open(jpath, "wb") as fh:
            fh.write(bytes(data))
        with pytest.raises(JournalCorruptError):
            replay_journal(jpath)

    def test_corrupt_error_is_kvstate_family(self, jpath):
        data = bytearray(_write(jpath, ("epoch", {"epoch": 1}),
                                ("spawn", {"name": "i0", "seq": 0})))
        data[10] ^= 0xFF
        with open(jpath, "wb") as fh:
            fh.write(bytes(data))
        with pytest.raises(KVStateError):
            replay_journal(jpath)

    def test_oversized_length_with_intact_tail_is_torn(self, jpath):
        # a header whose length runs past EOF IS a torn write — the
        # length prefix itself never got its payload
        data = _write(jpath, ("epoch", {"epoch": 1}))
        with open(jpath, "ab") as fh:
            fh.write(struct.pack("<II", 1 << 20, 0))
        assert [r["kind"] for r in replay_journal(jpath)] == ["epoch"]


class TestEmpty:
    def test_absent_file_is_empty_fleet(self, tmp_path):
        recs = replay_journal(str(tmp_path / "never_written"))
        assert recs == []
        intent = fold_records(recs)
        assert intent["roster"] == {} and intent["epoch"] == 0

    def test_empty_file_is_empty_fleet(self, jpath):
        open(jpath, "wb").close()
        assert replay_journal(jpath) == []


class TestFold:
    def test_roster_lifecycle(self):
        recs = [
            {"kind": "epoch", "epoch": 2},
            {"kind": "spawn", "name": "i0", "seq": 0, "port": 1},
            {"kind": "spawn", "name": "i1", "seq": 1, "port": 2},
            {"kind": "spawn", "name": "i2", "seq": 2, "port": 3},
            {"kind": "drain_begin", "name": "i1"},
            {"kind": "replica_dead", "name": "i2"},
            {"kind": "autoscale", "action": "hold", "tick": 9},
            {"kind": "wholly_unknown_kind", "x": 1},
        ]
        intent = fold_records(recs)
        assert intent["epoch"] == 2
        assert set(intent["roster"]) == {"i0", "i1"}
        assert intent["roster"]["i1"]["draining"] is True
        assert intent["roster"]["i0"]["draining"] is False
        assert intent["max_id"] == 2    # minted names resume past i2

    def test_drained_removes_and_adopt_rebuilds(self):
        recs = [
            {"kind": "spawn", "name": "i0", "seq": 0},
            {"kind": "drain_begin", "name": "i0"},
            {"kind": "replica_drained", "name": "i0"},
            {"kind": "adopt", "name": "i0", "seq": 5, "port": 9},
        ]
        roster = fold_records(recs)["roster"]
        assert roster["i0"]["draining"] is False
        assert roster["i0"]["seq"] == 5

    def test_canary_verdict_clears(self):
        begin = {"kind": "canary_begin", "name": "i1", "version": 2}
        assert fold_records([begin])["canary"] is not None
        for verdict in ("canary_rolled_forward", "canary_rolled_back"):
            recs = [begin, {"kind": verdict, "name": "i1"}]
            assert fold_records(recs)["canary"] is None

    def test_params_version_tracked(self):
        recs = [{"kind": "params", "version": 3}]
        assert fold_records(recs)["params_version"] == 3


class TestCompaction:
    """compact() changes the file's SIZE, never its MEANING: one
    snapshot record replaces the whole history, and a crash at any
    point leaves exactly one authoritative file."""

    RECS = (
        ("epoch", {"epoch": 1}),
        ("spawn", {"name": "i0", "seq": 0, "host": "h", "port": 7,
                   "pid": 11, "start_time": 1.5}),
        ("spawn", {"name": "i1", "seq": 1}),
        ("spawn", {"name": "i2", "seq": 2}),
        ("drain_begin", {"name": "i1"}),
        ("replica_dead", {"name": "i2"}),
        ("params", {"version": 4}),
        ("canary_begin", {"name": "i0", "version": 5}),
        ("quarantine", {"fingerprint": "aa" * 32}),
        ("breaker", {"state": "open", "strikes": 3,
                     "backoff_s": 0.8}),
    )

    def test_fold_identical_before_and_after(self, jpath):
        with FleetJournal(jpath) as j:
            for kind, fields in self.RECS:
                j.append(kind, **fields)
            before = fold_records(replay_journal(jpath))
            size_before = j.size()
            j.compact()
            after = fold_records(replay_journal(jpath))
            assert after == before
            # the file really shrank to one record, and size() tracks
            # the rotated file
            recs = replay_journal(jpath)
            assert [r["kind"] for r in recs] == ["snapshot"]
            assert j.size() < size_before

    def test_appends_after_compaction_fold_on_top(self, jpath):
        with FleetJournal(jpath) as j:
            for kind, fields in self.RECS:
                j.append(kind, **fields)
            j.compact()
            j.append("replica_dead", name="i0")
            j.append("spawn", name="i3", seq=3)
        intent = fold_records(replay_journal(jpath))
        assert set(intent["roster"]) == {"i1", "i3"}
        assert intent["max_id"] == 3
        assert intent["quarantine"] == ["aa" * 32]
        assert intent["breaker"]["state"] == "open"

    def test_crash_before_commit_keeps_old_file(self, jpath):
        with FleetJournal(jpath) as j:
            for kind, fields in self.RECS:
                j.append(kind, **fields)
        before = fold_records(replay_journal(jpath))
        # a compaction that died before its os.replace commit point:
        # the half-written snapshot sits in the .compacting sibling
        with open(jpath + ".compacting", "wb") as fh:
            fh.write(b"half a snapshot reco")
        assert fold_records(replay_journal(jpath)) == before
        # the next open removes the stale sibling and appends continue
        # on the intact original
        with FleetJournal(jpath) as j:
            j.append("epoch", epoch=2)
        assert not os.path.exists(jpath + ".compacting")
        assert fold_records(replay_journal(jpath))["epoch"] == 2

    def test_counts_into_sink(self, jpath):
        m = ServingMetrics()
        with FleetJournal(jpath, counters=m) as j:
            j.append("epoch", epoch=1)
            j.compact()
        assert m.count_value("journal_records") == 2

    def test_refuses_after_close_and_broken(self, jpath):
        j = FleetJournal(jpath)
        j.append("epoch", epoch=1)
        j.close()
        with pytest.raises(JournalBrokenError):
            j.compact()
        j2 = FleetJournal(jpath)
        j2._broken = True
        with pytest.raises(JournalBrokenError):
            j2.compact()
        j2.close()


class TestChaosSchedule:
    ACTIONS = ("sever_submit", "sever_stream", "replica_crash",
               "manager_kill")

    def test_seed_determinism_and_digest(self):
        a = build_chaos_schedule(10.0, 6, seed=42, actions=self.ACTIONS)
        b = build_chaos_schedule(10.0, 6, seed=42, actions=self.ACTIONS)
        c = build_chaos_schedule(10.0, 6, seed=43, actions=self.ACTIONS)
        assert a.events == b.events
        assert a.digest() == b.digest()
        assert a.digest() != c.digest()

    def test_manager_kill_guaranteed(self):
        for seed in range(20):
            sched = build_chaos_schedule(5.0, 3, seed=seed,
                                         actions=self.ACTIONS)
            assert "manager_kill" in sched.actions()

    def test_offsets_inside_middle_band(self):
        sched = build_chaos_schedule(10.0, 16, seed=0,
                                     actions=self.ACTIONS)
        assert sched.n == 16
        ts = [e["t"] for e in sched.events]
        assert ts == sorted(ts)
        assert all(1.0 <= t <= 9.0 for t in ts)

    def test_rejects_empty_schedule(self):
        with pytest.raises(ValueError):
            build_chaos_schedule(5.0, 0)

    def test_schedule_validates_events(self):
        with pytest.raises(ValueError):
            ChaosSchedule([{"t": 1.0}], duration_s=5.0)

    def test_schedule_validates_missing_t_before_sorting(self):
        # validation must run before the time sort reads e["t"], or a
        # missing offset surfaces as a KeyError from the sort key
        with pytest.raises(ValueError):
            ChaosSchedule([{"action": "manager_kill"}], duration_s=5.0)


class TestChaosScheduleEdges:
    """ISSUE 17 satellite: the hand-built-schedule corners the seeded
    builder never produces."""

    def test_t_zero_event_is_valid(self):
        sched = ChaosSchedule([{"t": 0.0, "action": "manager_kill"}],
                              duration_s=5.0)
        assert sched.events[0]["t"] == 0.0
        assert sched.actions() == ("manager_kill",)

    def test_duplicate_timestamps_keep_stable_order(self):
        events = [{"t": 1.0, "action": "sever_submit"},
                  {"t": 1.0, "action": "manager_kill"},
                  {"t": 1.0, "action": "sever_stream"}]
        a = ChaosSchedule(events, duration_s=5.0)
        b = ChaosSchedule(list(events), duration_s=5.0)
        # the sort is STABLE: insertion order among equal offsets is
        # part of the timeline, and the digest pins it
        assert a.actions() == ("sever_submit", "manager_kill",
                               "sever_stream")
        assert a.digest() == b.digest()
        flipped = ChaosSchedule([events[1], events[0], events[2]],
                                duration_s=5.0)
        assert flipped.digest() != a.digest()

    def test_unknown_action_names_the_action(self):
        with pytest.raises(ValueError, match="reboot_rack"):
            ChaosSchedule([{"t": 1.0, "action": "reboot_rack"}],
                          duration_s=5.0)

    def test_empty_schedule_is_a_valid_no_op(self):
        sched = ChaosSchedule([], duration_s=5.0)
        assert sched.n == 0
        assert sched.actions() == ()
        assert sched.digest() == ChaosSchedule([], 5.0).digest()

    def test_require_fills_missing_actions_deterministically(self):
        req = ("poison", "spawn_fail", "manager_kill")
        a = build_chaos_schedule(8.0, 6, seed=1,
                                 actions=("sever_submit",
                                          "sever_stream"),
                                 require=req)
        b = build_chaos_schedule(8.0, 6, seed=1,
                                 actions=("sever_submit",
                                          "sever_stream"),
                                 require=req)
        assert a.digest() == b.digest()
        for action in req:
            assert action in a.actions()

    def test_require_legacy_digest_unchanged(self):
        # require=("manager_kill",) IS the legacy
        # require_manager_kill rewrite — byte-identical timelines
        pool = ("sever_submit", "sever_stream", "manager_kill")
        legacy = build_chaos_schedule(10.0, 5, seed=3, actions=pool)
        explicit = build_chaos_schedule(10.0, 5, seed=3, actions=pool,
                                        require=("manager_kill",))
        assert legacy.digest() == explicit.digest()

    def test_require_overflow_refuses(self):
        with pytest.raises(ValueError):
            build_chaos_schedule(5.0, 2, seed=0,
                                 require=("poison", "spawn_fail",
                                          "manager_kill"))
