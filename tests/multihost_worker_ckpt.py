"""Worker for the cross-process sharded-checkpoint test
(test_sharded_checkpoint.py): 2 processes x 2 devices, ZeRO-sharded
optimizer state over the global mesh, orbax save (every process writes its
own shards), restore into a FRESH sharded net, identical continuation.

Usage: python tests/multihost_worker_ckpt.py <proc_id> <nproc> <coord> <dir>
"""
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=2")

import numpy as np  # noqa: E402

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

from deeplearning4j_tpu import (InputType, MultiLayerNetwork,  # noqa: E402
                                NeuralNetConfiguration)
from deeplearning4j_tpu.datasets.dataset import DataSet  # noqa: E402
from deeplearning4j_tpu.nn.conf.layers import (DenseLayer,  # noqa: E402
                                               OutputLayer)
from deeplearning4j_tpu.parallel import (ParallelWrapper,  # noqa: E402
                                         distributed)
from deeplearning4j_tpu.util.sharded_checkpoint import (  # noqa: E402
    load_checkpoint, save_checkpoint)


def build_net(seed):
    conf = (NeuralNetConfiguration.Builder().seed(seed).learning_rate(0.1)
            .updater("adam").list()
            .layer(0, DenseLayer(n_out=16, activation="relu"))
            .layer(1, OutputLayer(n_out=3, activation="softmax",
                                  loss_function="mcxent"))
            .set_input_type(InputType.feed_forward(4)).build())
    return MultiLayerNetwork(conf).init()


def main():
    proc_id, nproc, coord, ckdir = (int(sys.argv[1]), int(sys.argv[2]),
                                    sys.argv[3], sys.argv[4])
    assert distributed.initialize(coord, nproc, proc_id)
    mesh = distributed.global_mesh()

    rng = np.random.default_rng(0)
    gx = rng.random((64, 4)).astype(np.float32)
    gy = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 64)]
    sl = distributed.process_local_batch_slice(64)
    local = DataSet(gx[sl], gy[sl])

    def wrap(net):
        return (ParallelWrapper.Builder(net).mesh(mesh)
                .sharded_updater_state(True).averaging_frequency(1).build())

    a = build_net(seed=7)
    pw_a = wrap(a)
    for _ in range(3):
        pw_a.fit(local)
    save_checkpoint(a, ckdir)                    # every process: own shards

    b = build_net(seed=99)
    pw_b = wrap(b)
    pw_b._ensure_sharded()                       # restore INTO ZeRO layout
    load_checkpoint(b, ckdir)
    spec = tuple(b._updater_state[0]["W"]["m"].sharding.spec)
    assert "data" in str(spec), spec             # moments landed sharded

    # identical continuation on both the original and the restored net.
    # Comparison happens ON DEVICE (global sharded arrays spanning
    # processes cannot be fetched host-side) — every process runs the same
    # global computation and reads the replicated result.
    import jax.numpy as jnp
    pw_a.fit(local)
    pw_b.fit(local)
    la = jax.tree_util.tree_leaves(a._params)
    lb = jax.tree_util.tree_leaves(b._params)
    assert all(bool(jnp.all(x == y)) for x, y in zip(la, lb))
    chk = float(sum(jnp.sum(x.astype(jnp.float64)) for x in lb))
    print(f"RESULT {proc_id} sum={chk:.10f} "
          f"score={float(b._score):.10f}", flush=True)


if __name__ == "__main__":
    main()
