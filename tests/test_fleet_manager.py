"""Fleet manager pins (ISSUE 13 acceptance criteria).

  (a) Router: least-backlog dispatch over alive replicas; the no-fault
      fleet path adds ZERO device dispatches per token vs N bare
      servers (dispatch-counter A/B); a shed at the chosen replica is
      a fleet shed (propagates).
  (b) Crash survival: a fault-injected replica death under load loses
      ZERO requests — every admitted future resolves (failover replay
      on survivors, streams bit-identical to solo runs) or fails
      loudly with a named error; `kill()` itself fails in-flight
      futures with ReplicaDeadError; the control loop backfills to
      min_replicas with a NEVER-reused instance id.
  (c) Drain seam: `drain(migrate=True)` moves ALL decode-phase
      requests out as artifacts in one verb while queued + PREFILLING
      requests come back as replay specs (half-written panels are
      never artifacts — the durable-KV victim rule at the drain seam);
      a manager scale_down resumes the migrated streams bit-identical
      on survivors.
  (d) Closed autoscale loop: control_tick ACTS on the signal's
      decisions (scale_up spawns, scale_down drains), resets the
      signal after acting, and federation stays monotone across
      replica churn (tombstoned counters, unique ids).
  (e) Canary rollout: poisoned params (rowwise_finite screen) roll
      back before ANY replica serves them; a failing canary rolls
      back with zero lost requests; healthy params roll forward with
      zero dropped in-flight requests and spawns inherit them.
"""
import concurrent.futures as cf
import os
import sys
import time

import jax.numpy as jnp
import pytest

from deeplearning4j_tpu.common.resilience import FaultInjector
from deeplearning4j_tpu.models.zoo.transformer import TransformerLM
from deeplearning4j_tpu.obs.fleet import AutoscaleSignal, FleetView
from deeplearning4j_tpu.serving import (ContinuousDecodeServer,
                                        FleetManager, ReplicaDeadError,
                                        RequestDrainedError,
                                        RequestMigratedError,
                                        ServingMetrics)

TOOLS = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tools")


def _lm(seed=3):
    return TransformerLM(64, d_model=16, n_heads=2, n_layers=1,
                         max_len=64, seed=seed)


def _factory(lm, **kw):
    def make(name):
        return ContinuousDecodeServer(
            lm, slots=2, prompt_buckets=(8, 16),
            metrics=ServingMetrics(name=name), instance=name, **kw)
    return make


def _warm(mgr, prompt=(1, 2, 3)):
    """Compile every replica's programs off the measurement clock."""
    for name in mgr.replicas:
        mgr.replica(name).generate(list(prompt), 2, timeout=120)


def _wait(pred, timeout=30.0, msg="condition"):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        if pred():
            return
        time.sleep(0.002)
    raise TimeoutError(f"never reached: {msg}")


# ---------------------------------------------------------------------------
# (a) router
# ---------------------------------------------------------------------------
class TestRouter:
    def test_least_backlog_prefers_idle_replica(self):
        lm = _lm()
        with FleetManager(_factory(lm), n_replicas=2) as mgr:
            _warm(mgr)
            # two long streams pin i0 then i1; the third breaks the
            # tie by spawn order back onto i0
            futs = [mgr.submit([1, 2, 3], 24) for _ in range(3)]
            names = mgr.replicas
            [f.result(120) for f in futs]
            recv = {n: mgr.replica(n).metrics.count_value("received")
                    for n in names}
            # warm-up added 1 to each; the routed split is 2 / 1
            assert recv[names[0]] == 3 and recv[names[1]] == 2

    def test_round_robin_fleet_adds_zero_dispatches_vs_bare_servers(self):
        """The acceptance A/B: the same sequential workload through
        the managed fleet (round-robin policy, federation after every
        request) and through N bare servers — per-replica dispatch and
        token counters IDENTICAL, results bit-identical. The control
        plane observes the schedule, never alters it."""
        prompts = [[1 + i, 2, 3] for i in range(6)]
        counts = {}
        outs = {}
        lm = _lm()
        with FleetManager(_factory(lm), n_replicas=2,
                          policy="round_robin") as mgr:
            res = []
            for p in prompts:
                res.append(mgr.generate(p, 5, timeout=120))
                mgr.fleet_snapshot()        # federate every request
                mgr.control_tick()          # health probe every request
            names = mgr.replicas
            counts["fleet"] = [
                (mgr.replica(n).metrics.count_value("dispatches"),
                 mgr.replica(n).metrics.count_value("tokens_out"))
                for n in names]
            outs["fleet"] = res
        bare = [ContinuousDecodeServer(lm, slots=2,
                                       prompt_buckets=(8, 16)).start()
                for _ in range(2)]
        try:
            res = [bare[i % 2].generate(p, 5, timeout=120)
                   for i, p in enumerate(prompts)]
            counts["bare"] = [
                (s.metrics.count_value("dispatches"),
                 s.metrics.count_value("tokens_out")) for s in bare]
            outs["bare"] = res
        finally:
            for s in bare:
                s.stop(timeout=120)
        assert counts["fleet"] == counts["bare"]
        assert [list(r) for r in outs["fleet"]] == \
            [list(r) for r in outs["bare"]]

    def test_replica_shed_propagates_to_caller(self):
        """A shed at the chosen replica is a fleet shed: the manager
        owns failover, the caller owns overload retry policy."""
        from deeplearning4j_tpu.serving import ServerOverloadedError
        lm = _lm()
        with FleetManager(_factory(lm, max_queue=1), n_replicas=2) as mgr:
            _warm(mgr)
            futs, sheds = [], 0
            for _ in range(64):         # tiny queues fill fast
                try:
                    futs.append(mgr.submit([1, 2, 3], 30))
                except ServerOverloadedError:
                    sheds += 1
            assert sheds > 0            # the shed reached the caller
            for f in futs:              # admitted work all completes
                f.result(120)


# ---------------------------------------------------------------------------
# (b) crash survival
# ---------------------------------------------------------------------------
class TestCrashSurvival:
    def test_kill_fails_inflight_loudly_and_refuses_restart(self):
        lm = _lm()
        srv = ContinuousDecodeServer(lm, slots=2,
                                     prompt_buckets=(8,)).start()
        srv.generate([1, 2, 3], 2, timeout=120)     # warm
        futs = [srv.submit([1, 2, 3], 40) for _ in range(4)]
        srv.kill()
        for f in futs:
            with pytest.raises(ReplicaDeadError):
                f.result(30)
        assert not srv.alive
        from deeplearning4j_tpu.serving import ServerClosedError
        with pytest.raises(ServerClosedError):
            srv.start()

    def test_injected_replica_death_under_load_zero_lost(self):
        """THE crash acceptance pin: a fault-injected replica death
        mid-stream loses zero requests — every admitted future
        resolves, and every resolved stream is bit-identical to a solo
        run (failover replays the prompt; deterministic greedy decode
        reproduces the exact stream)."""
        lm = _lm()
        prompts = [[1, 2, 3], [4, 5], [6, 7, 8, 9]]
        refs = {tuple(p): list(lm.generate(p, 32)) for p in prompts}
        inj = FaultInjector()
        with FleetManager(_factory(lm), n_replicas=2,
                          fault_injector=inj) as mgr:
            _warm(mgr)
            futs = [mgr.submit(prompts[i % len(prompts)], 32)
                    for i in range(10)]
            # sever at the NEXT fleet.replica probe = death mid-stream
            inj.plan("fleet.replica", on_call=0, sever=True, exc=None)
            time.sleep(0.05)
            tick = mgr.control_tick()
            # the sever fires inside this tick's own probe pass, so
            # the SAME tick's floor check already backfills to min=2
            # (the autoscale loop backfilling capacity) — with a fresh
            # never-reused id
            assert tick["backfilled"] == 1
            assert tick["n_replicas"] == 2
            for i, f in enumerate(futs):
                out = f.result(120)
                assert list(out) == refs[tuple(prompts[i % len(prompts)])]
            snap = mgr.fleet_snapshot()
            assert snap["fleet_replica_dead"] == 1
            assert snap["fleet_failover_resubmitted"] >= 1

    def test_backfilled_replica_never_reuses_a_dead_name(self):
        lm = _lm()
        with FleetManager(_factory(lm), n_replicas=2) as mgr:
            first = list(mgr.replicas)
            mgr.kill_replica(first[0])
            mgr.control_tick()                  # backfill to min=2
            assert mgr.n_alive() == 2
            fresh = set(mgr.replicas) - set(first)
            assert fresh and not (fresh & set(first))
            assert mgr.states()[first[0]] == "dead"

    def test_no_survivors_fails_loudly(self):
        lm = _lm()
        mgr = FleetManager(_factory(lm), n_replicas=2, min_replicas=1)
        mgr.start()
        try:
            _warm(mgr)
            futs = [mgr.submit([1, 2, 3], 40) for _ in range(3)]
            for n in list(mgr.replicas):
                mgr.kill_replica(n)
            for f in futs:
                with pytest.raises(Exception) as ei:
                    f.result(30)
                assert isinstance(ei.value, ReplicaDeadError)
            with pytest.raises(ReplicaDeadError):
                mgr.submit([1, 2, 3], 4)
        finally:
            mgr.stop()


# ---------------------------------------------------------------------------
# (c) the drain seam
# ---------------------------------------------------------------------------
class TestDrainSeam:
    def test_drain_migrates_decode_replays_prefill_and_queued(self):
        """ONE drain verb: decode-phase slots leave as artifacts,
        PREFILLING slots and queued requests come back as replay
        specs (a half-written panel is never an artifact — the victim
        rule at the drain seam), and both re-land bit-identically on
        a second server."""
        lm = _lm()
        inj = FaultInjector()
        a = ContinuousDecodeServer(
            lm, slots=2, prompt_buckets=(8, 16), paged=True,
            block_size=4, chunked_prefill=2, fault_injector=inj,
            metrics=ServingMetrics(name="a"), instance="a").start()
        b = ContinuousDecodeServer(
            lm, slots=2, prompt_buckets=(8, 16), paged=True,
            block_size=4, chunked_prefill=2,
            metrics=ServingMetrics(name="b"), instance="b").start()
        try:
            a.generate([1, 2], 2, timeout=120)      # warm (one-shot)
            # decode-phase occupant: short prompt, one-shot prefill
            fa = a.submit([1, 2], 12)
            _wait(lambda: any(r is not None and r.pf_next is None
                              and r.future is fa
                              for r in a._slot_req),
                  msg="request A decoding")
            # slow every subsequent dispatch so B stays mid-prefill
            inj.plan("serve.batch", prob=1.0, times=500, delay=0.05,
                     exc=None)
            long_prompt = list(range(1, 15))        # 14 rows, C=2
            fb = a.submit(long_prompt, 6)
            _wait(lambda: any(r is not None and r.pf_next is not None
                              for r in a._slot_req),
                  msg="request B prefilling")
            fc = a.submit([3, 4, 5], 8)             # queued: slots full
            migrated, replayed = a.drain(migrate=True)
            assert not a.alive
            assert [f for f, _ in migrated] == [fa]
            assert {f for f, _ in replayed} == {fb, fc}
            assert isinstance(fa.exception(), RequestMigratedError)
            assert isinstance(fb.exception(), RequestDrainedError)
            assert isinstance(fc.exception(), RequestDrainedError)
            # re-land on B: migrate_in the artifact, resubmit the specs
            (_, art), = migrated
            out_a = b.migrate_in(art).result(120)
            assert list(out_a) == list(lm.generate([1, 2], 12))
            for _, spec in replayed:
                out = b.submit(spec["prompt"], spec["max_new"],
                               klass=spec["klass"]).result(120)
                ref = lm.generate(spec["prompt"], spec["max_new"])
                assert list(out) == list(ref)
            assert b.metrics.count_value("migrated") == 1
        finally:
            for s in (a, b):
                try:
                    s.stop(timeout=120)
                except Exception:   # noqa: BLE001 — already drained
                    pass

    def test_drain_nonpaged_replays_everything(self):
        lm = _lm()
        srv = ContinuousDecodeServer(lm, slots=2,
                                     prompt_buckets=(8,)).start()
        srv.generate([1, 2, 3], 2, timeout=120)
        futs = [srv.submit([1, 2, 3], 16) for _ in range(3)]
        migrated, replayed = srv.drain()
        assert migrated == []
        assert {f for f, _ in replayed} <= set(futs)
        for _, spec in replayed:
            assert spec["prompt"] == [1, 2, 3] and spec["max_new"] == 16

    def test_drain_migrate_true_refused_on_fixed_slot(self):
        lm = _lm()
        srv = ContinuousDecodeServer(lm, slots=2,
                                     prompt_buckets=(8,)).start()
        try:
            with pytest.raises(ValueError):
                srv.drain(migrate=True)
        finally:
            srv.stop(timeout=60)

    def test_scale_down_migrates_live_requests_bit_identical(self):
        """Manager-level drain: the drained replica's live
        decode-phase requests RESUME on survivors (the durable-KV
        bit-identity pin, exercised across the router)."""
        lm = _lm()
        with FleetManager(_factory(lm, paged=True, block_size=4),
                          n_replicas=2, min_replicas=1) as mgr:
            _warm(mgr)
            futs = [mgr.submit([1, 2, 3], 28) for _ in range(4)]
            names = mgr.replicas
            _wait(lambda: mgr.replica(names[1])
                  .metrics.count_value("tokens_out") > 2,
                  msg="second replica decoding")
            mgr.scale_down(names[1])
            ref = list(lm.generate([1, 2, 3], 28))
            for f in futs:
                assert list(f.result(120)) == ref
            snap = mgr.fleet_snapshot()
            assert snap["fleet_replica_drained"] == 1
            assert mgr.n_alive() == 1
            # at least one stream actually MIGRATED (vs replayed):
            # the survivor adopted its artifact
            survivor = mgr.replica(names[0])
            assert survivor.metrics.count_value("migrated") >= 1


# ---------------------------------------------------------------------------
# (d) the closed autoscale loop
# ---------------------------------------------------------------------------
class _ScriptedSignal:
    """Duck-typed AutoscaleSignal: scripted decisions, so actuation
    tests are timing-free."""

    def __init__(self, seq):
        self.seq = list(seq)
        self.decision = "hold"
        self.transitions = []
        self.resets = 0

    def observe(self, snapshot=None, **kw):
        self.decision = self.seq.pop(0) if self.seq else "hold"
        return self.decision

    def reset(self):
        self.resets += 1


class TestAutoscaleLoop:
    def test_acts_on_decisions_and_resets_signal(self):
        lm = _lm()
        sig = _ScriptedSignal(["scale_up", "hold", "scale_down"])
        with FleetManager(_factory(lm), n_replicas=2, signal=sig,
                          max_replicas=3) as mgr:
            t1 = mgr.control_tick()
            assert t1["acted"] == "scale_up" and t1["n_replicas"] == 3
            t2 = mgr.control_tick()
            assert t2["acted"] is None and t2["n_replicas"] == 3
            t3 = mgr.control_tick()
            assert t3["acted"] == "scale_down" and t3["n_replicas"] == 2
            assert sig.resets == 2          # one per ACTION, not per tick
            snap = mgr.fleet_snapshot()
            assert snap["fleet_replica_spawned"] == 3   # 2 initial + 1
            assert snap["fleet_replica_drained"] == 1

    def test_scale_capped_at_min_and_max(self):
        lm = _lm()
        sig = _ScriptedSignal(["scale_up", "scale_down"])
        with FleetManager(_factory(lm), n_replicas=2, min_replicas=2,
                          max_replicas=2, signal=sig) as mgr:
            assert mgr.control_tick()["acted"] is None
            assert mgr.control_tick()["acted"] is None
            assert mgr.n_alive() == 2

    def test_federation_monotone_across_churn(self):
        """One instance dies mid-window, another spawns: fleet
        counters stay MONOTONE (the dead replica's final counters
        tombstone into every later federation) and the fresh replica
        never aliases the dead one's name."""
        lm = _lm()
        with FleetManager(_factory(lm), n_replicas=2) as mgr:
            _warm(mgr)
            for i in range(4):
                mgr.generate([1 + i, 2, 3], 4, timeout=120)
            snap1 = mgr.fleet_snapshot()
            victim = mgr.replicas[0]
            mgr.kill_replica(victim)
            mgr.control_tick()              # backfill spawns a fresh id
            for i in range(2):
                mgr.generate([1 + i, 2, 3], 4, timeout=120)
            snap2 = mgr.fleet_snapshot()
            assert snap2["fleet_tokens_out"] >= snap1["fleet_tokens_out"]
            assert snap2["fleet_sheds_total"] >= snap1["fleet_sheds_total"]
            assert victim in snap2["instances"]     # tombstoned, not
            #                                         vanished
            assert len(set(snap2["instances"])) == \
                len(snap2["instances"])             # no aliasing
            # the tombstone carries counters ONLY: its stale gauges
            # must not haunt the live capacity estimate
            fv = mgr.fleet_view()
            assert fv.gauge_view("service_rate_tokens_per_sec")[
                "per_instance"].get(victim) is None

    def test_autoscale_signal_reset_reenters_warmup(self):
        sig = AutoscaleSignal(window=4, hysteresis=1, min_shed_rate=1)
        sheds = 0
        for i in range(6):
            sheds += 10
            sig.observe(sheds=sheds, service_rate=100.0, occupancy=0.9)
        assert sig.decision == AutoscaleSignal.SCALE_UP
        sig.reset()
        assert sig.decision == AutoscaleSignal.HOLD
        for i in range(3):                  # part-window: never acts
            sheds += 10
            assert sig.observe(sheds=sheds, service_rate=100.0,
                               occupancy=0.9) == AutoscaleSignal.HOLD


# ---------------------------------------------------------------------------
# (e) canary rollout
# ---------------------------------------------------------------------------
class TestCanaryRollout:
    def test_poisoned_params_roll_back_before_any_request(self):
        lm = _lm()
        bad = _lm(seed=9)
        bad.aux = dict(bad.aux)
        bad.aux["tok"] = bad.aux["tok"].at[0, 0].set(jnp.nan)
        with FleetManager(_factory(lm), n_replicas=2) as mgr:
            _warm(mgr)
            r = mgr.rollout(bad)
            assert r["status"] == "rolled_back"
            assert r["reason"] == "nan_screen"
            assert mgr.metrics.count_value("canary_rollbacks") == 1
            # zero requests served wrong bits: the fleet still speaks
            # the OLD params everywhere
            out = mgr.generate([1, 2, 3], 6, timeout=120)
            assert list(out) == list(lm.generate([1, 2, 3], 6))

    def test_failing_canary_rolls_back_zero_lost(self):
        lm = _lm()
        new = _lm(seed=9)
        inj = FaultInjector()

        def factory(name):
            # only the FIRST replica (the rollout's canary pick)
            # carries the injector
            return ContinuousDecodeServer(
                lm, slots=2, prompt_buckets=(8, 16),
                fault_injector=inj if name == "i0" else None,
                metrics=ServingMetrics(name=name), instance=name)

        with FleetManager(factory, n_replicas=2) as mgr:
            _warm(mgr)

            def traffic():
                futs = [mgr.submit([2, 3, 4], 6) for _ in range(4)]
                for f in futs:
                    f.result(120)       # failover keeps them whole

            # arm AFTER warm-up: the canary's decode dispatches fail
            inj.plan("serve.batch", prob=1.0, times=2,
                     exc=RuntimeError("canary dispatch fault"))
            r = mgr.rollout(new, watch_ticks=1, traffic=traffic)
            assert r["status"] == "rolled_back"
            assert r["reason"].startswith("failures")
            assert mgr.metrics.count_value("canary_rollbacks") == 1
            out = mgr.generate([1, 2, 3], 6, timeout=120)
            assert list(out) == list(lm.generate([1, 2, 3], 6))

    def test_healthy_rollout_rolls_forward_zero_dropped(self):
        lm = _lm()
        new = _lm(seed=9)
        with FleetManager(_factory(lm), n_replicas=2) as mgr:
            _warm(mgr)
            base = {n: mgr.replica(n).metrics.count_value("tokens_out")
                    for n in mgr.replicas}
            inflight = [mgr.submit([4, 5, 6], 24) for _ in range(3)]
            # ALL three must be decoding before the swap lands — a
            # still-queued request legitimately picks up the NEW
            # version at admission (single-server swap semantics); the
            # dual-version pin is about requests already in slots
            _wait(lambda: all(
                mgr.replica(n).metrics.count_value("tokens_out")
                - base[n] >= 4 for n in mgr.replicas),
                msg="in-flight requests decoding")

            def traffic():
                for _ in range(3):
                    mgr.generate([7, 8], 4, timeout=120)

            r = mgr.rollout(new, watch_ticks=1, traffic=traffic)
            assert r["status"] == "rolled_forward"
            # in-flight requests drained dual-version on their OLD
            # params — zero dropped, old bits (the PR 4 pin per
            # replica)
            old_ref = list(lm.generate([4, 5, 6], 24))
            for f in inflight:
                assert list(f.result(120)) == old_ref
            # new traffic speaks the new params on EVERY replica
            new_ref = list(new.generate([4, 5, 6], 8))
            for name in mgr.replicas:
                out = mgr.replica(name).generate([4, 5, 6], 8,
                                                 timeout=120)
                assert list(out) == new_ref
            # and a post-rollout spawn inherits them
            spawned = mgr.scale_up()
            out = mgr.replica(spawned).generate([4, 5, 6], 8,
                                                timeout=120)
            assert list(out) == new_ref


# ---------------------------------------------------------------------------
# report surface
# ---------------------------------------------------------------------------
class TestFleetReportSurface:
    def test_fleet_report_renders_control_counters(self):
        if TOOLS not in sys.path:
            sys.path.insert(0, TOOLS)
        from fleet_report import build_fleet_report, format_fleet_report
        m = ServingMetrics(name="i0", slo_target_ms=50)
        mgr_m = ServingMetrics(name="fleet")
        mgr_m.count("replica_spawned", 2)
        mgr_m.count("replica_dead", 1)
        report, merged = build_fleet_report({"i0": m, "fleet": mgr_m})
        assert merged is None
        fleet = report["fleet"]
        assert fleet["fleet_replica_spawned"] == 2
        assert fleet["fleet_replica_dead"] == 1
        assert fleet["fleet_canary_rollbacks"] == 0
        text = format_fleet_report(report)
        assert "fleet_replica_dead" in text
        assert "fleet_failover_resubmitted" in text

    def test_fleet_view_snapshot_counts_events_from_members(self):
        m = ServingMetrics(name="i0")
        m.count("failover_resubmitted", 3)
        snap = FleetView().add("i0", m).snapshot()
        assert snap["fleet_failover_resubmitted"] == 3
        assert snap["fleet_replica_drained"] == 0


# ---------------------------------------------------------------------------
# (f) graftlint regression: tombstone fetch runs OUTSIDE the manager
#     lock (ISSUE 15 — a REMOTE replica's kind_snapshot is a wire
#     round-trip; holding _lock through it stalled every router/
#     probe/federation path on one dead replica's socket)
# ---------------------------------------------------------------------------
class _LockProbeMetrics:
    """ServingMetrics-shaped probe: kind_snapshot() records whether
    the calling thread holds the manager lock at fetch time — the
    crash/drain paths fetch on the caller's thread, so RLock's
    _is_owned() is exactly the question."""

    def __init__(self, name):
        self.name = name
        self.instance = name
        self.mgr = None
        self.lock_held_at_fetch = []

    def kind_snapshot(self):
        if self.mgr is not None:
            self.lock_held_at_fetch.append(
                self.mgr._lock._is_owned())
        return {"completed": {"kind": "counter", "value": 3}}

    def count_value(self, key):
        return 0


class _FakeReplica:
    """The minimal FleetManager-pluggable surface (no device work)."""

    def __init__(self, name):
        self.name = name
        self.instance = name
        self.metrics = _LockProbeMetrics(name)
        self._running = True
        self.paged = False
        self.killed = False

    @property
    def alive(self):
        return not self.killed

    def start(self):
        self._running = True
        return self

    def submit(self, prompt, max_new, **kw):
        fut = cf.Future()
        fut.set_result(list(prompt) + [0] * int(max_new))
        return fut

    def kill(self):
        self.killed = True
        self._running = False

    def stop(self, drain=True, timeout=None):
        self._running = False

    def drain(self, migrate=None, timeout=60.0):
        self._running = False
        return [], []


class TestTombstoneLockDiscipline:
    def _mgr(self):
        replicas = {}

        def factory(name):
            r = _FakeReplica(name)
            replicas[name] = r
            return r

        mgr = FleetManager(factory, n_replicas=2).start()
        for r in replicas.values():
            r.metrics.mgr = mgr
        return mgr, replicas

    def test_crash_fetches_tombstone_outside_manager_lock(self):
        mgr, replicas = self._mgr()
        try:
            victim = mgr.replicas[0]
            mgr.kill_replica(victim)
            probe = replicas[victim].metrics
            # both fetches happened (pre-removal + post-kill refresh)
            # and NEITHER ran while this thread held the manager lock
            assert len(probe.lock_held_at_fetch) >= 2
            assert not any(probe.lock_held_at_fetch)
            # the tombstone still landed atomically with the removal:
            # counters survive the instance, state reads dead
            assert mgr.states()[victim] == "dead"
            with mgr._lock:
                assert mgr._tombstones[victim]["completed"][
                    "value"] == 3
        finally:
            mgr.stop()

    def test_scale_down_fetches_tombstone_outside_manager_lock(self):
        mgr, replicas = self._mgr()
        try:
            victim = mgr.scale_down(timeout=10.0)
            probe = replicas[victim].metrics
            assert probe.lock_held_at_fetch
            assert not any(probe.lock_held_at_fetch)
            assert mgr.states()[victim] == "dead"
            with mgr._lock:
                assert victim in mgr._tombstones
        finally:
            mgr.stop()
