"""Recurrent layer tests: GravesLSTM / bidirectional / masking / TBPTT /
rnnTimeStep.

Mirrors reference suites GradientCheckTests (LSTM), GradientCheckTestsMasking,
nn/layers/recurrent tests, and MultiLayerNetwork TBPTT tests (SURVEY.md §4).
"""
import numpy as np
import pytest

from deeplearning4j_tpu import (InputType, MultiLayerNetwork,
                                NeuralNetConfiguration)
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.gradientcheck.gradient_check_util import check_gradients
from deeplearning4j_tpu.nn.conf.layers import (GravesBidirectionalLSTM,
                                               GravesLSTM, RnnOutputLayer,
                                               SimpleRnn)


def rnn_conf(layer, n_in=3, n_classes=3, data_type="float64", **kwargs):
    b = (NeuralNetConfiguration.Builder().seed(12345).data_type(data_type)
         .learning_rate(0.1).weight_init("xavier"))
    lb = b.list().layer(0, layer).layer(
        1, RnnOutputLayer(n_out=n_classes, activation="softmax",
                          loss_function="mcxent"))
    for k, v in kwargs.items():
        getattr(lb, k)(v)
    return lb.set_input_type(InputType.recurrent(n_in)).build()


def seq_data(n=4, t=6, f=3, n_classes=3, seed=0, dtype=np.float64):
    rng = np.random.default_rng(seed)
    x = rng.normal(0, 1, (n, t, f)).astype(dtype)
    y = np.eye(n_classes, dtype=dtype)[rng.integers(0, n_classes, (n, t))]
    return x, y


class TestLSTMShapes:
    def test_lstm_output_shape(self):
        net = MultiLayerNetwork(rnn_conf(GravesLSTM(n_out=5),
                                         data_type="float32")).init()
        x, _ = seq_data(dtype=np.float32)
        out = np.asarray(net.output(x))
        assert out.shape == (4, 6, 3)
        np.testing.assert_allclose(out.sum(-1), 1.0, atol=1e-4)

    def test_lstm_param_count(self):
        net = MultiLayerNetwork(rnn_conf(GravesLSTM(n_out=5))).init()
        # W 3*20 + RW 5*20 + b 20 + peep 15 = 60+100+20+15 = 195; out 5*3+3=18
        assert net.num_params() == 195 + 18

    @pytest.mark.slow
    def test_scan_unroll_equivalent_numerics(self):
        """scan_unroll is a scheduling knob (lax.scan unroll=N): the same
        math with different XLA fusion, so forward and a masked training
        step match unroll=1 to float-reassociation tolerance — the bench
        A/B `char_rnn_lstm_unroll` measures speed only. Full tier: the
        knob is off by default and only the bench A/B sets it."""
        x, y = seq_data(dtype=np.float32)
        mask = np.ones((4, 6), np.float32)
        mask[2, 4:] = 0.0
        outs, scores = [], []
        for unroll in (1, 4):
            net = MultiLayerNetwork(rnn_conf(
                GravesLSTM(n_out=5, scan_unroll=unroll),
                data_type="float32")).init()
            outs.append(np.asarray(net.output(x, features_mask=mask)))
            net.fit(DataSet(x, y, features_mask=mask))
            scores.append(float(net._score))
        np.testing.assert_allclose(outs[0], outs[1], atol=1e-6)
        assert abs(scores[0] - scores[1]) < 1e-5

    def test_bidirectional_shape(self):
        net = MultiLayerNetwork(rnn_conf(GravesBidirectionalLSTM(n_out=5),
                                         data_type="float32")).init()
        x, _ = seq_data(dtype=np.float32)
        assert np.asarray(net.output(x)).shape == (4, 6, 3)


class TestLSTMGradients:
    def test_gradcheck_lstm(self):
        x, y = seq_data()
        net = MultiLayerNetwork(rnn_conf(GravesLSTM(n_out=4))).init()
        assert check_gradients(net, x, y, max_rel_error=1e-4, subset=60)

    def test_gradcheck_simple_rnn(self):
        x, y = seq_data()
        net = MultiLayerNetwork(rnn_conf(SimpleRnn(n_out=4))).init()
        assert check_gradients(net, x, y, max_rel_error=1e-4, subset=40)

    @pytest.mark.slow
    def test_gradcheck_bidirectional(self):
        x, y = seq_data()
        net = MultiLayerNetwork(
            rnn_conf(GravesBidirectionalLSTM(n_out=3))).init()
        assert check_gradients(net, x, y, max_rel_error=1e-4, subset=60)

    def test_gradcheck_lstm_masked(self):
        x, y = seq_data()
        lmask = np.ones((4, 6))
        lmask[2, 3:] = 0
        lmask[3, 1:] = 0
        fmask = lmask.copy()
        net = MultiLayerNetwork(rnn_conf(GravesLSTM(n_out=4))).init()
        assert check_gradients(net, x, y, fmask=fmask, lmask=lmask,
                               max_rel_error=1e-4, subset=50)


class TestMaskingSemantics:
    def test_masked_steps_zero_output(self):
        layer = GravesLSTM(n_in=3, n_out=4)
        layer = layer.apply_global_defaults({"activation": "tanh"})
        import jax
        params = layer.init_params(jax.random.PRNGKey(0))
        x = np.random.default_rng(0).normal(size=(2, 5, 3)).astype(np.float32)
        mask = np.ones((2, 5), np.float32)
        mask[1, 2:] = 0
        out, carry = layer.forward_with_carry(
            params, x, layer.init_carry(2), mask=mask)
        out = np.asarray(out)
        assert np.all(out[1, 2:] == 0.0)
        assert np.any(out[1, :2] != 0.0)

    def test_masked_state_carried(self):
        """State at masked steps must hold the last unmasked value."""
        import jax
        layer = GravesLSTM(n_in=3, n_out=4).apply_global_defaults(
            {"activation": "tanh"})
        params = layer.init_params(jax.random.PRNGKey(0))
        x = np.random.default_rng(0).normal(size=(1, 5, 3)).astype(np.float32)
        mask = np.array([[1, 1, 0, 0, 0]], np.float32)
        _, carry_masked = layer.forward_with_carry(
            params, x, layer.init_carry(1), mask=mask)
        _, carry_short = layer.forward_with_carry(
            params, x[:, :2], layer.init_carry(1))
        np.testing.assert_allclose(np.asarray(carry_masked["h"]),
                                   np.asarray(carry_short["h"]), rtol=1e-5)


class TestRnnTimeStep:
    def test_time_step_matches_full_forward(self):
        net = MultiLayerNetwork(rnn_conf(GravesLSTM(n_out=4),
                                         data_type="float32")).init()
        x, _ = seq_data(n=2, t=5, dtype=np.float32)
        full = np.asarray(net.output(x))
        net.rnn_clear_previous_state()
        step_outs = []
        for t in range(5):
            step_outs.append(np.asarray(net.rnn_time_step(x[:, t])))
        stepped = np.stack(step_outs, axis=1)
        np.testing.assert_allclose(full, stepped, rtol=1e-4, atol=1e-5)

    def test_clear_state_resets(self):
        net = MultiLayerNetwork(rnn_conf(GravesLSTM(n_out=4),
                                         data_type="float32")).init()
        x, _ = seq_data(n=2, t=3, dtype=np.float32)
        o1 = np.asarray(net.rnn_time_step(x[:, 0]))
        net.rnn_clear_previous_state()
        o2 = np.asarray(net.rnn_time_step(x[:, 0]))
        np.testing.assert_allclose(o1, o2, rtol=1e-5)


class TestTBPTT:
    def test_tbptt_runs_and_learns(self):
        x, y = seq_data(n=8, t=12, dtype=np.float32)
        conf = rnn_conf(GravesLSTM(n_out=8), data_type="float32",
                        backprop_type="tbptt", t_bptt_forward_length=4)
        net = MultiLayerNetwork(conf).init()
        ds = DataSet(x, y)
        s0 = net.score(ds)
        for _ in range(10):
            net.fit(ds)
        # 3 segments per fit * 10 fits
        assert net.conf.iteration_count == 30
        assert net.score(ds) < s0
