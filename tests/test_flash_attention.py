"""Pallas flash-attention kernel tests (interpreter mode on the CPU mesh;
the same kernel compiles via Mosaic on TPU — PERF.md records the on-chip
numbers).

Pinned against `blockwise_attention` (the ring-attention single-device
reference): forward exact in f32, causal masking, block-size obliviousness,
and the FUSED Pallas backward (dQ / dK+dV kernels) == autodiff of the
reference — both masks, any divisor tiling, uneven T, bf16 inputs.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.ops import flash_attention
from deeplearning4j_tpu.parallel.ring_attention import blockwise_attention

B, T, H, D = 2, 256, 4, 64


def _qkv(seed=0, t=T, dtype=jnp.float32):
    rng = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(rng.standard_normal((B, t, H, D)), dtype)
    return mk(), mk(), mk()


class TestFlashForward:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_reference(self, causal):
        q, k, v = _qkv()
        out = flash_attention(q, k, v, causal, None, 128, 128, True)
        ref = blockwise_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-6)

    def test_block_size_oblivious(self):
        """Any divisor block size gives the same numbers (online softmax
        is associative over blocks)."""
        q, k, v = _qkv(1)
        outs = [flash_attention(q, k, v, True, None, bq, bk, True)
                for bq, bk in ((256, 256), (64, 128), (128, 32))]
        for o in outs[1:]:
            np.testing.assert_allclose(np.asarray(o), np.asarray(outs[0]),
                                       atol=2e-6)

    def test_non_divisible_seq_auto_picks_divisor_block(self):
        # T=96 with requested block 64 -> largest divisor 48 is used; the
        # values still match the reference exactly
        q, k, v = _qkv(2, t=96)
        out = flash_attention(q, k, v, True, None, 64, 64, True)
        ref = blockwise_attention(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-6)

    def test_scale_override(self):
        q, k, v = _qkv(3)
        out = flash_attention(q, k, v, False, 0.5, 128, 128, True)
        ref = blockwise_attention(q, k, v, causal=False, scale=0.5)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=5e-6)


class TestFlashBackward:
    @pytest.mark.parametrize("causal", [False, True])
    def test_grads_match_reference_autodiff_both_masks(self, causal):
        """Fused Pallas dQ/dK/dV == autodiff of the XLA reference, causal
        and full attention."""
        q, k, v = _qkv(7)

        def loss_f(q, k, v):
            return jnp.mean(
                flash_attention(q, k, v, causal, None, 128, 128, True) ** 2)

        def loss_r(q, k, v):
            return jnp.mean(
                blockwise_attention(q, k, v, causal=causal) ** 2)

        gf = jax.grad(loss_f, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(loss_r, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gf, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-5)

    @pytest.mark.slow
    def test_backward_block_size_oblivious(self):
        """Backward accumulation is associative over (bq, bk) tilings —
        any divisor blocks give the same gradients."""
        q, k, v = _qkv(8)

        def g(bq, bk):
            def loss(q, k, v):
                return jnp.mean(
                    flash_attention(q, k, v, True, None, bq, bk, True) ** 2)
            return jax.grad(loss, argnums=(0, 1, 2))(q, k, v)

        ref = g(256, 256)
        for bq, bk in ((64, 128), (128, 32)):
            for a, b in zip(g(bq, bk), ref):
                np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                           atol=2e-5)

    @pytest.mark.slow
    def test_backward_non_divisible_seq(self):
        q, k, v = _qkv(9, t=96)

        def loss_f(q, k, v):
            return jnp.mean(
                flash_attention(q, k, v, True, None, 64, 64, True) ** 2)

        def loss_r(q, k, v):
            return jnp.mean(blockwise_attention(q, k, v, causal=True) ** 2)

        gf = jax.grad(loss_f, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(loss_r, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gf, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-5)

    def test_backward_bf16_inputs(self):
        """bf16 q/k/v (the training dtype on chip): grads keep the input
        dtype and track the f32 reference within bf16 resolution."""
        q, k, v = _qkv(10, dtype=jnp.bfloat16)

        def loss_f(q, k, v):
            return jnp.mean(flash_attention(
                q, k, v, True, None, 128, 128, True).astype(jnp.float32)
                ** 2)

        gf = jax.grad(loss_f, argnums=(0, 1, 2))(q, k, v)
        qf, kf, vf = (a.astype(jnp.float32) for a in (q, k, v))

        def loss_r(q, k, v):
            return jnp.mean(blockwise_attention(q, k, v, causal=True) ** 2)

        gr = jax.grad(loss_r, argnums=(0, 1, 2))(qf, kf, vf)
        for a, b in zip(gf, gr):
            assert a.dtype == jnp.bfloat16
            # tolerance SCALED to the gradient magnitude (grads here are
            # ~1e-4; an absolute atol would be vacuous): every entry must
            # land within 3% of the largest reference gradient
            scale = np.abs(np.asarray(b)).max()
            assert scale > 0
            np.testing.assert_allclose(
                np.asarray(a, np.float32) / scale,
                np.asarray(b) / scale, atol=0.03)

    @pytest.mark.slow
    def test_trains_in_transformer_block(self):
        """flash attention drops into the zoo transformer block and the LM
        still learns (attention='flash' path)."""
        from deeplearning4j_tpu.models.zoo.transformer import TransformerLM
        lm = TransformerLM(11, d_model=32, n_heads=4, n_layers=2,
                           max_len=16, learning_rate=0.2, momentum=0.9,
                           attention="flash")
        rng = np.random.default_rng(0)
        x = rng.integers(0, 11, (16, 16)).astype(np.int32)
        y = (x + 1) % 11
        first = lm.fit_batch(x, y)
        for _ in range(60):
            last = lm.fit_batch(x, y)
        assert last < first * 0.5
