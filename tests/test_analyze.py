"""graftlint pins (ISSUE 15 acceptance criteria).

  (a) THE GATE: `tools.analyze.run()` over the real package reports
      ZERO unsuppressed findings — the four invariant families
      (lock-discipline, future-hygiene, layering, metrics-keys) are
      enforced structurally on every tier-1 run, and every inline
      suppression in the tree carries its one-line justification
      (a bare disable is itself a finding, so the policy is part of
      the gate).
  (b) Fixture goldens: each pass catches its seeded known-bad snippet
      (tests/fixtures/graftlint/) — blocking-under-lock (direct AND
      transitive), a lock-order cycle, a leaked Future (fall-through
      and return-path), a swallowed-exception pending future, a layer
      violation, an unregistered pinned metrics key — and reports
      NOTHING for the clean controls next to them.
  (c) Suppression/baseline round-trip: an inline justified disable
      suppresses exactly its pass at its line; a justification-less
      disable is an error; write_baseline -> load -> re-run turns
      every active finding into a baselined one and back.

The analyzer is stdlib-only and never IMPORTS the fixtures — parsing
a file full of deliberate deadlocks must not require executing it.
"""
import json
import os

from tools.analyze import core, load_config, run
from tools.analyze import futures as futures_pass
from tools.analyze import layering as layering_pass
from tools.analyze import lockcheck as lock_pass
from tools.analyze import metrics_keys as metrics_pass

REPO = core.repo_root()
FIXTURES = os.path.join("tests", "fixtures", "graftlint")


def _sources(*names):
    return core.collect_sources(
        REPO, paths=[os.path.join(FIXTURES, n) for n in names])


def _keys(findings):
    return sorted(f.key for f in findings)


# ---------------------------------------------------------------------------
# (b) fixture goldens, pass by pass
# ---------------------------------------------------------------------------
class TestLockDisciplineFixtures:
    def test_blocking_under_lock_goldens(self):
        files = _sources("bad_blocking_under_lock.py")
        findings = lock_pass.check(load_config(), files)
        keys = _keys(findings)
        # the three seeded direct primitives, each an error
        for frag, label in (("bad_send_under_lock", "socket.sendall"),
                            ("bad_sleep_under_lock", "time.sleep"),
                            ("bad_join_under_lock", "queue.join")):
            key = (f"blocking-under-lock:BlockingUnderLock.{frag}"
                   f":{label}")
            assert key in keys, (key, keys)
        sev = {f.key: f.severity for f in findings}
        assert sev["blocking-under-lock:BlockingUnderLock."
                   "bad_send_under_lock:socket.sendall"] == "error"
        # the transitive case (helper blocks via queue.get) — warning
        assert ("blocking-under-lock:BlockingUnderLock."
                "bad_transitive_under_lock:blocking_helper") in keys
        # clean controls: the outside-the-lock send and the lambda
        # body never fire
        assert not any("ok_send_outside_lock" in k for k in keys)
        assert not any("ok_callback_not_scanned" in k for k in keys)

    def test_suppression_scoping(self):
        """The justified disable silences ITS line; the seeded
        findings on other lines stay; the bare disable adds a
        suppression-policy error."""
        report = run(paths=[os.path.join(
            FIXTURES, "bad_blocking_under_lock.py")], baseline={})
        sup_keys = _keys(report.suppressed)
        assert any("suppressed_send" in k for k in sup_keys)
        assert any("suppressed_without_reason" in k for k in sup_keys)
        act_keys = _keys(report.active)
        assert any("bad_send_under_lock" in k for k in act_keys)
        assert any(k.startswith("missing-justification")
                   for k in act_keys)

    def test_lock_cycle_golden(self):
        files = _sources("bad_lock_cycle.py")
        findings = lock_pass.check(load_config(), files)
        cyc = [f for f in findings
               if f.key.startswith("lock-order-cycle")]
        assert len(cyc) == 1, _keys(findings)
        assert "LockCycle._a" in cyc[0].key
        assert "LockCycle._b" in cyc[0].key
        # the consistently-ordered pair is NOT a cycle
        assert not any("NoCycle" in f.key for f in findings)


class TestFutureHygieneFixtures:
    def test_future_leak_goldens(self):
        files = _sources("bad_future_leak.py")
        findings = futures_pass.check(load_config(), files)
        keys = _keys(findings)
        assert "future-leak:leaky_branch:fut" in keys
        assert "future-leak:leaky_return:fut" in keys
        assert "future-swallowed-exception:swallowed:fut" in keys
        # clean controls: resolved-on-every-path, escape-at-birth,
        # and the raise-before-escape path are all fine
        assert not any("clean_" in k for k in keys)
        assert len(keys) == 3, keys


class TestLayeringFixtures:
    def _config(self):
        return core.Config({
            "meta": {"package": FIXTURES},
            "layer": [{
                "name": "fixture-stdlib-only",
                "modules": ["layered/*.py"],
                "deny": ["jax", "numpy"],
                "reason": "fixture layer",
            }],
        }, REPO)

    def test_layer_violation_golden(self):
        files = _sources("layered")
        findings = layering_pass.check(self._config(), files)
        assert _keys(findings) == ["layer:fixture-stdlib-only:jax"]
        assert findings[0].severity == "error"
        # threading (stdlib) did not trip the rule
        assert "threading" not in findings[0].message

    def test_wrapper_hook_raises_on_unknown_rule(self):
        """The test_obs/test_fleet wrappers must fail loudly if a
        rule is renamed away — never pass vacuously."""
        import pytest
        with pytest.raises(KeyError):
            layering_pass.check_rules(["no-such-rule"])

    def test_relative_import_resolution(self):
        """`from ..parallel import x` in pkg/serving/mod.py resolves
        to pkg.parallel.x — the deny-prefix match the old regex pins
        could not do."""
        src = core.SourceFile(
            "pkg/serving/mod.py",
            "from ..parallel.ps import pack\nfrom . import util\n")
        mods = {m for _, m in layering_pass.resolve_imports(
            src.relpath, src.tree)}
        assert "pkg.parallel.ps" in mods
        assert "pkg.parallel.ps.pack" in mods
        assert "pkg.serving.util" in mods


class TestMetricsKeysFixtures:
    def test_unregistered_pin_and_reverse_drift(self):
        srcs = _sources("bad_metrics_src.py")
        pins = _sources("bad_metrics_pins.py")[0]
        findings = metrics_pass.check_extracted(srcs, pins,
                                                ["PINNED_KEYS"])
        keys = _keys(findings)
        assert "unregistered-pin:ghost_key" in keys
        # registered keys (eager loop, subscript, setdefault) all
        # satisfied their pins
        assert not any(k.startswith("unregistered-pin:")
                       and "ghost" not in k for k in keys)
        # the reverse check: an always-present setdefault key the pin
        # tuple never grew
        assert "unpinned-stable-key:epsilon" in keys

    def test_missing_pin_tuple_is_a_finding(self):
        srcs = _sources("bad_metrics_src.py")
        pins = _sources("bad_metrics_pins.py")[0]
        findings = metrics_pass.check_extracted(srcs, pins,
                                                ["NO_SUCH_PINS"])
        assert "missing-pin-tuple:NO_SUCH_PINS" in _keys(findings)


# ---------------------------------------------------------------------------
# (c) baseline round-trip
# ---------------------------------------------------------------------------
class TestBaselineRoundTrip:
    def test_write_load_rerun(self, tmp_path):
        paths = [os.path.join(FIXTURES, "bad_future_leak.py")]
        before = run(paths=paths, baseline={})
        assert before.active
        bl_path = str(tmp_path / "baseline.json")
        core.write_baseline(before.active, bl_path)
        baseline = core.load_baseline(bl_path)
        assert set(baseline) == {f.fingerprint
                                 for f in before.active}
        after = run(paths=paths, baseline=baseline)
        assert not after.active
        assert _keys(after.baselined) == _keys(before.active)
        # fingerprints are line-free: the file moving lines around
        # must not invalidate the baseline (stable identity)
        data = json.load(open(bl_path))
        assert all(":" in e["fingerprint"] and not any(
            part.isdigit() for part in
            e["fingerprint"].split(":")[-1].split("-"))
            for e in data["findings"])


# ---------------------------------------------------------------------------
# (a) THE GATE: the real repo is clean
# ---------------------------------------------------------------------------
class TestRepoGate:
    def test_repo_has_zero_unsuppressed_findings(self):
        """The tier-1 enforcement point: every future PR inherits the
        four passes. A finding here means either fix the code or add
        a JUSTIFIED suppression / baseline entry — never ignore."""
        report = run()
        assert not report.active, "\n".join(
            f"{f.path}:{f.line}: [{f.severity}] {f.pass_name}: "
            f"{f.message}" for f in report.active)
        # the suppression mechanism is live (the wire/ps deliberate
        # sites) and every suppression carried its justification —
        # a bare one would have surfaced in report.active above
        assert report.suppressed, \
            "expected the documented deliberate sites to be " \
            "inline-suppressed"

    def test_cli_json_shape(self, capsys):
        """The CI artifact contract: --json emits counts + findings
        with fingerprints."""
        from tools.analyze.__main__ import main
        rc = main(["--json"])
        assert rc == 0
        data = json.loads(capsys.readouterr().out)
        assert data["counts"]["active"] == 0
        assert data["counts"]["suppressed"] >= 1
        assert data["files_checked"] > 100
        for entry in data["suppressed"]:
            assert entry["fingerprint"].startswith(entry["pass"])
