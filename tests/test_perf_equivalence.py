"""Accelerated-path vs reference-path equivalence (SURVEY §4 pattern 5:
'same test, two backends, assert numerical agreement'). Pins the
hand-written perf lowerings to their autodiff references so a silent edit
cannot corrupt gradients:

- fused closed-form BN backward (_bn_train_fused) vs XLA autodiff
- argmax-gather maxpool VJP (_maxpool_gather) vs select-and-scatter
- bf16 updater state vs f32 state (loose tolerance: storage rounding only)
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.nn.conf.layers.convolution import SubsamplingLayer
from deeplearning4j_tpu.nn.conf.layers.normalization import BatchNormalization


class TestFusedBNBackward:
    def _grads(self, fused, fast_var, x, params, st):
        layer = BatchNormalization(n_out=x.shape[-1],
                                   use_fast_variance=fast_var,
                                   fused_backward=fused)

        def loss(p, xx):
            y, ns = layer.forward_with_state(p, xx, st, train=True)
            return jnp.sum(jnp.sin(y) * jnp.cos(xx)), ns

        (v, ns), g = jax.value_and_grad(loss, argnums=(0, 1),
                                        has_aux=True)(params, x)
        return v, ns, g

    def test_fused_equals_autodiff_f64(self):
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.standard_normal((8, 5, 6, 4)))
        params = {"gamma": jnp.asarray(rng.standard_normal(4) * 0.5 + 1.0),
                  "beta": jnp.asarray(rng.standard_normal(4) * 0.1)}
        st = BatchNormalization(n_out=4).init_state()
        for fast in (True, False):
            vf, nsf, gf = self._grads(True, fast, x, params, st)
            va, nsa, ga = self._grads(False, fast, x, params, st)
            assert abs(float(vf) - float(va)) < 1e-9
            for a, b in zip(jax.tree.leaves(gf), jax.tree.leaves(ga)):
                assert float(jnp.max(jnp.abs(a - b))) < 1e-9
            for a, b in zip(jax.tree.leaves(nsf), jax.tree.leaves(nsa)):
                assert float(jnp.max(jnp.abs(a - b))) < 1e-9

    @pytest.mark.slow
    def test_fused_numeric_gradient(self):
        rng = np.random.default_rng(1)
        x = jnp.asarray(rng.standard_normal((4, 3, 3, 2)))
        params = {"gamma": jnp.asarray(rng.standard_normal(2) + 1.0),
                  "beta": jnp.asarray(rng.standard_normal(2) * 0.1)}
        layer = BatchNormalization(n_out=2, fused_backward=True)
        st = layer.init_state()

        def loss(xx):
            y, _ = layer.forward_with_state(params, xx, st, train=True)
            return jnp.sum(jnp.sin(y))

        g = jax.grad(loss)(x)
        eps = 1e-6
        for idx in [(0, 0, 0, 0), (3, 2, 2, 1), (1, 1, 0, 1)]:
            num = (loss(x.at[idx].add(eps)) - loss(x.at[idx].add(-eps))) \
                / (2 * eps)
            assert abs(float(num) - float(g[idx])) < 1e-5


class TestMaxpoolGatherVJP:
    @pytest.mark.slow
    def test_gather_equals_select_scatter(self):
        rng = np.random.default_rng(0)
        for kern, stride, mode, pad in [((2, 2), (2, 2), "truncate", (0, 0)),
                                        ((3, 3), (2, 2), "same", (0, 0)),
                                        ((3, 3), (1, 1), "truncate", (1, 1)),
                                        ((3, 2), (2, 3), "same", (0, 0))]:
            x = jnp.asarray(
                rng.standard_normal((2, 13, 11, 5)).astype(np.float32))
            variants = {}
            for bp in ("argmax_gather", "select_scatter"):
                layer = SubsamplingLayer(
                    pooling_type="max", kernel_size=kern, stride=stride,
                    convolution_mode=mode, padding=pad, pool_backprop=bp)
                y = layer.forward({}, x)
                g = jax.grad(
                    lambda xx: jnp.sum(jnp.sin(layer.forward({}, xx))))(x)
                variants[bp] = (y, g)
            yg, gg = variants["argmax_gather"]
            ys, gs = variants["select_scatter"]
            assert jnp.array_equal(yg, ys)
            assert float(jnp.max(jnp.abs(gg - gs))) < 1e-6


class TestBf16UpdaterState:
    def test_state_dtype_and_training_agreement(self):
        from deeplearning4j_tpu import (InputType, MultiLayerNetwork,
                                        NeuralNetConfiguration)
        from deeplearning4j_tpu.datasets.dataset import DataSet
        from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer

        def build(state_dtype):
            b = (NeuralNetConfiguration.Builder().seed(3)
                 .updater("nesterovs").momentum(0.9).learning_rate(0.05)
                 .data_type("float32"))
            if state_dtype:
                b = b.updater_state_dtype(state_dtype)
            conf = (b.list()
                    .layer(0, DenseLayer(n_out=8, activation="tanh"))
                    .layer(1, OutputLayer(n_out=2, activation="softmax",
                                          loss_function="mcxent"))
                    .set_input_type(InputType.feed_forward(4))
                    .build())
            return MultiLayerNetwork(conf).init()

        rng = np.random.default_rng(0)
        x = rng.random((32, 4)).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 32)]
        f32 = build(None)
        b16 = build("bfloat16")
        b16.set_params(f32.params())
        # state leaves stored bf16, scalar counters untouched
        leaves = jax.tree.leaves(b16._updater_state)
        assert all(l.dtype == jnp.bfloat16 for l in leaves if l.ndim > 0)
        for _ in range(10):
            f32.fit(DataSet(x, y))
            b16.fit(DataSet(x, y))
        # bf16 state stays bf16 across steps; trajectories agree loosely
        leaves = jax.tree.leaves(b16._updater_state)
        assert all(l.dtype == jnp.bfloat16 for l in leaves if l.ndim > 0)
        assert np.allclose(f32.params(), b16.params(), atol=5e-3)
