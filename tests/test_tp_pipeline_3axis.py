"""dp x tp x pp composition in ONE program (8-device CPU mesh).

The reference's distributed story is data-parallel only (SURVEY.md §2.5);
r3 proved each extra strategy separately. These tests pin the 3-axis
composition: Megatron tensor-parallel blocks (`make_tp_block_fn`, head-
and hidden-sharded with two psums) INSIDE the GPipe rotation
(`gpipe(param_specs=...)`), batch sharded over "data" — all in a single
shard_map program, the scaling-book mesh recipe."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from deeplearning4j_tpu.common.jax_compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from deeplearning4j_tpu.models.zoo.transformer import (
    embed_fn, init_lm, init_tp_block, lm_loss, make_block_fn,
    make_tp_block_fn, tp_block_specs)
from deeplearning4j_tpu.parallel.pipeline import (
    PipelineParallel, make_pipeline_mesh, microbatch, stack_stage_params)

pytestmark = pytest.mark.skipif(len(jax.devices()) < 8,
                                reason="needs 8 virtual devices")

D_MODEL, HEADS, D_FF = 32, 4, 64


def _dense_params_from_tp(tp):
    """Reassemble `init_tp_block` storage into `init_block` layout."""
    H, D, three_hd = tp["attn"]["wqkv"].shape
    hd = three_hd // 3
    w = tp["attn"]["wqkv"]
    dense_wqkv = jnp.concatenate(
        [jnp.concatenate([w[h, :, i * hd:(i + 1) * hd] for h in range(H)],
                         axis=1) for i in range(3)], axis=1)
    dense_wo = tp["attn"]["wo"].reshape(H * hd, D)
    return {"ln1": tp["ln1"], "ln2": tp["ln2"],
            "attn": {"wqkv": dense_wqkv, "wo": dense_wo},
            "mlp": tp["mlp"]}


class TestTensorParallelBlock:
    @pytest.mark.slow
    def test_tp_block_matches_dense_block(self):
        """Head/hidden-sharded block over a 4-way model axis == the dense
        single-device block, to float tolerance."""
        rng = jax.random.PRNGKey(0)
        tp = init_tp_block(rng, D_MODEL, HEADS, D_FF)
        dense = _dense_params_from_tp(tp)
        x = jnp.asarray(np.random.default_rng(1).standard_normal(
            (2, 8, D_MODEL)), jnp.float32)
        ref = make_block_fn(HEADS)(dense, x)

        mesh = Mesh(np.asarray(jax.devices()[:4]), ("model",))
        block = make_tp_block_fn(HEADS // 4, "model")
        specs = {
            "ln1": {"g": P(), "b": P()},
            "attn": {"wqkv": P("model"), "wo": P("model")},
            "ln2": {"g": P(), "b": P()},
            "mlp": {"w1": P(None, "model"), "b1": P("model"),
                    "w2": P("model", None), "b2": P()},
        }
        fn = shard_map(block, mesh=mesh, in_specs=(specs, P()),
                       out_specs=P(), check_vma=False)
        got = jax.jit(fn)(tp, x)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   atol=2e-5)


class TestThreeAxisPipeline:
    def _build(self, n_data, n_model, n_pipe, lr=0.0):
        mesh = make_pipeline_mesh(n_pipe=n_pipe, n_data=n_data,
                                  n_model=n_model)
        assert mesh.axis_names == ("data", "model", "pipe")
        rng = jax.random.PRNGKey(3)
        blocks = [init_tp_block(jax.random.fold_in(rng, i), D_MODEL,
                                HEADS, D_FF) for i in range(n_pipe)]
        aux, _ = init_lm(11, d_model=D_MODEL, n_heads=HEADS,
                         n_layers=1, max_len=16, seed=5)
        pp = PipelineParallel(
            make_tp_block_fn(HEADS // n_model, "model"), blocks, mesh,
            loss_fn=lm_loss, aux_params=aux, pre_fn=embed_fn, n_micro=2,
            data_axis="data", learning_rate=lr, momentum=0.9,
            param_specs=tp_block_specs("pipe", "model"))
        return pp, aux, blocks

    @pytest.mark.slow
    def test_loss_matches_sequential(self):
        """(data=2, model=2, pipe=2) pipelined+TP loss == running the
        dense-layout blocks sequentially on one device."""
        pp, aux, blocks = self._build(2, 2, 2)
        rng = np.random.default_rng(0)
        x = rng.integers(0, 11, (8, 16)).astype(np.int32)
        y = (x + 1) % 11
        xs = microbatch(jnp.asarray(x), 2)
        ys = microbatch(jnp.asarray(y), 2)
        loss_pipe = float(jax.jit(pp._loss)(pp.stacked, pp.aux, xs, ys))
        h = embed_fn(aux, jnp.asarray(x))
        dense_fn = make_block_fn(HEADS)
        for b in blocks:
            h = dense_fn(_dense_params_from_tp(b), h)
        loss_seq = float(lm_loss(aux, h, jnp.asarray(y)))
        assert abs(loss_pipe - loss_seq) < 1e-4, (loss_pipe, loss_seq)

    def test_param_shardings_cover_three_axes(self):
        pp, _, _ = self._build(2, 2, 2)
        wqkv = pp.stacked["attn"]["wqkv"]         # [S, H, D, 3hd]
        spec = tuple(wqkv.sharding.spec)
        assert spec[0] == "pipe" and spec[1] == "model"
        w1 = pp.stacked["mlp"]["w1"]
        assert tuple(w1.sharding.spec)[2] == "model"

    @pytest.mark.slow
    def test_three_axis_training_learns(self):
        pp, _, _ = self._build(2, 2, 2, lr=0.5)
        rng = np.random.default_rng(0)
        x = rng.integers(0, 11, (16, 16)).astype(np.int32)
        y = (x + 1) % 11
        first = pp.fit_batch(x, y)
        for _ in range(30):
            last = pp.fit_batch(x, y)
        assert last < first * 0.6, (first, last)
