"""Updater state-equation tests (reference: deeplearning4j-core
TestUpdaters.java, 1,668 LoC asserting Adam/Adadelta/RMSProp/Nesterov math
directly — SURVEY.md §4.2)."""
import numpy as np
import jax.numpy as jnp
import pytest

from deeplearning4j_tpu.nn.updater import updaters as U


def arr(*v):
    return jnp.asarray(np.array(v, np.float64))


class TestUpdaterEquations:
    def test_sgd(self):
        init, apply = U.get("sgd")
        upd, _ = apply(init(arr(1.0)), arr(2.0), 0.5, {})
        assert float(upd[0]) == pytest.approx(1.0)

    def test_nesterovs_matches_reference_equations(self):
        # reference TestUpdaters.java:231-234: vPrev=v; v=mu*v-lr*g;
        # grad_expected = mu*vPrev - (1+mu)*v ; params -= grad_expected
        init, apply = U.get("nesterovs")
        mu, lr = 0.9, 0.1
        g = arr(0.5, -1.0)
        state = init(g)
        upd1, state = apply(state, g, lr, {"momentum": mu})
        v1 = mu * 0.0 - lr * np.array([0.5, -1.0])
        exp1 = mu * 0.0 - (1 + mu) * v1
        np.testing.assert_allclose(np.asarray(upd1), exp1, rtol=1e-12)
        # descent direction at mu anything: p - upd1 moves against gradient
        assert float(upd1[0]) > 0 and float(upd1[1]) < 0
        upd2, state = apply(state, g, lr, {"momentum": mu})
        v2 = mu * v1 - lr * np.array([0.5, -1.0])
        exp2 = mu * v1 - (1 + mu) * v2
        np.testing.assert_allclose(np.asarray(upd2), exp2, rtol=1e-12)

    def test_adam_bias_correction(self):
        init, apply = U.get("adam")
        b1, b2, eps, lr = 0.9, 0.999, 1e-8, 0.01
        g = arr(0.3)
        upd, st = apply(init(g), g, lr, {"adamMeanDecay": b1,
                                         "adamVarDecay": b2, "epsilon": eps})
        m = (1 - b1) * 0.3
        v = (1 - b2) * 0.09
        alpha = lr * np.sqrt(1 - b2) / (1 - b1)
        np.testing.assert_allclose(float(upd[0]), alpha * m / (np.sqrt(v) + eps),
                                   rtol=1e-10)

    def test_rmsprop(self):
        init, apply = U.get("rmsprop")
        d, eps, lr = 0.95, 1e-8, 0.1
        g = arr(2.0)
        upd, st = apply(init(g), g, lr, {"rmsDecay": d, "epsilon": eps})
        g2 = (1 - d) * 4.0
        np.testing.assert_allclose(float(upd[0]), lr * 2.0 / np.sqrt(g2 + eps),
                                   rtol=1e-10)

    def test_adagrad(self):
        init, apply = U.get("adagrad")
        upd, st = apply(init(arr(3.0)), arr(3.0), 0.1, {"epsilon": 1e-6})
        np.testing.assert_allclose(float(upd[0]), 0.1 * 3.0 / (3.0 + 1e-6),
                                   rtol=1e-8)

    def test_adadelta_ignores_lr(self):
        init, apply = U.get("adadelta")
        u1, _ = apply(init(arr(1.0)), arr(1.0), 0.1, {"rho": 0.95})
        u2, _ = apply(init(arr(1.0)), arr(1.0), 99.0, {"rho": 0.95})
        np.testing.assert_allclose(np.asarray(u1), np.asarray(u2))

    def test_none_updater(self):
        init, apply = U.get("none")
        upd, _ = apply(init(arr(5.0)), arr(5.0), 0.1, {})
        assert float(upd[0]) == 0.0


class TestSchedules:
    def test_step_policy(self):
        lr = U.schedule_lr(1.0, "step", jnp.asarray(10.0), decay_rate=0.5,
                           steps=5.0)
        assert float(lr) == pytest.approx(0.25)

    def test_exponential_policy(self):
        lr = U.schedule_lr(1.0, "exponential", jnp.asarray(3.0), decay_rate=0.9)
        assert float(lr) == pytest.approx(0.9 ** 3)

    def test_poly_policy(self):
        lr = U.schedule_lr(1.0, "poly", jnp.asarray(50.0), power=2.0,
                           max_iterations=100)
        assert float(lr) == pytest.approx(0.25)

    def test_schedule_map(self):
        lr = U.schedule_lr(0.1, "schedule", jnp.asarray(7.0),
                           schedule_map={5: 0.01, 10: 0.001})
        assert float(lr) == pytest.approx(0.01)


class TestGradientNormalization:
    def test_clip_elementwise(self):
        g = {"W": arr(5.0, -3.0), "b": arr(0.5)}
        out = U.normalize_gradients(g, "ClipElementWiseAbsoluteValue", 1.0)
        np.testing.assert_allclose(np.asarray(out["W"]), [1.0, -1.0])
        np.testing.assert_allclose(np.asarray(out["b"]), [0.5])

    def test_renormalize_l2_per_layer(self):
        g = {"W": arr(3.0), "b": arr(4.0)}
        out = U.normalize_gradients(g, "RenormalizeL2PerLayer")
        total = np.sqrt(sum(float(jnp.sum(v * v)) for v in out.values()))
        assert total == pytest.approx(1.0, rel=1e-4)

    def test_clip_l2_noop_below_threshold(self):
        g = {"W": arr(0.1)}
        out = U.normalize_gradients(g, "ClipL2PerLayer", 1.0)
        np.testing.assert_allclose(np.asarray(out["W"]), [0.1], rtol=1e-6)
