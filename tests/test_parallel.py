"""ParallelWrapper tests on a virtual 8-device CPU mesh.

Mirrors the reference's in-one-JVM distributed testing strategy (SURVEY.md §4.6:
ParallelWrapperTest runs multi-threaded single-process; here a virtual device
mesh stands in for a pod).
"""
import jax
import numpy as np
import pytest

from deeplearning4j_tpu import (InputType, MultiLayerNetwork,
                                NeuralNetConfiguration)
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterators import ListDataSetIterator
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.parallel import ParallelWrapper, make_mesh

pytestmark = pytest.mark.skipif(len(jax.devices()) < 8,
                                reason="needs 8 virtual devices")


def make_net(seed=42, lr=0.2):
    conf = (NeuralNetConfiguration.Builder()
            .seed(seed).learning_rate(lr).updater("sgd")
            .list()
            .layer(0, DenseLayer(n_out=16, activation="relu"))
            .layer(1, OutputLayer(n_out=3, activation="softmax",
                                  loss_function="mcxent"))
            .set_input_type(InputType.feed_forward(4))
            .build())
    return MultiLayerNetwork(conf).init()


def blob_data(n=160, seed=0):
    rng = np.random.default_rng(seed)
    centers = rng.normal(0, 3, (3, 4))
    c = rng.integers(0, 3, n)
    x = (centers[c] + rng.normal(0, 0.5, (n, 4))).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[c]
    return x, y


class TestMesh:
    def test_make_mesh(self):
        mesh = make_mesh()
        assert mesh.shape["data"] * mesh.shape["model"] == len(jax.devices())

    def test_make_mesh_2d(self):
        mesh = make_mesh(n_data=4, n_model=2)
        assert mesh.shape["data"] == 4 and mesh.shape["model"] == 2


class TestAllReduceMode:
    def test_fit_and_learn(self):
        net = make_net()
        pw = ParallelWrapper.Builder(net).workers(8).averaging_frequency(1).build()
        x, y = blob_data()
        ds = DataSet(x, y)
        s0 = net.score(ds)
        pw.fit(ListDataSetIterator(ds, 40), num_epochs=15)
        assert net.score(ds) < s0 * 0.6

    def test_matches_single_device(self):
        """Sharded-step result == single-device result for the same batches
        (the reference's cuDNN-vs-builtin two-backend equality pattern,
        SURVEY.md §4.5, applied to sharding)."""
        x, y = blob_data(n=64)
        ds = DataSet(x, y)
        net_a = make_net(seed=7)
        net_b = make_net(seed=7)
        # identical init
        net_b.set_params(net_a.params())
        pw = ParallelWrapper.Builder(net_a).workers(8).averaging_frequency(1).build()
        pw.fit(ListDataSetIterator(ds, 64), num_epochs=3)
        for _ in range(3):
            net_b.fit(ds)
        np.testing.assert_allclose(net_a.params(), net_b.params(),
                                   rtol=2e-4, atol=2e-5)


class TestLocalStepsMode:
    @pytest.mark.slow
    def test_param_averaging_mode(self):
        net = make_net()
        pw = (ParallelWrapper.Builder(net).workers(8)
              .averaging_frequency(4).build())
        x, y = blob_data(n=320)
        ds = DataSet(x, y)
        s0 = net.score(ds)
        pw.fit(ListDataSetIterator(ds, 40), num_epochs=12)
        assert net.score(ds) < s0 * 0.6
        assert net.conf.iteration_count == 12 * 8


def make_cg_net(seed=42, lr=0.2):
    from deeplearning4j_tpu.nn.graph import ComputationGraph
    conf = (NeuralNetConfiguration.Builder()
            .seed(seed).learning_rate(lr).updater("sgd")
            .graph_builder()
            .add_inputs("in")
            .add_layer("h", DenseLayer(n_out=16, activation="relu"), "in")
            .add_layer("out", OutputLayer(n_out=3, activation="softmax",
                                          loss_function="mcxent"), "h")
            .set_outputs("out")
            .set_input_types(InputType.feed_forward(4))
            .build())
    return ComputationGraph(conf).init()


class TestComputationGraphParallel:
    def test_cg_allreduce_fit(self):
        net = make_cg_net()
        pw = (ParallelWrapper.Builder(net).workers(8)
              .averaging_frequency(1).build())
        x, y = blob_data()
        ds = DataSet(x, y)
        s0 = net.score(ds)
        pw.fit(ListDataSetIterator(ds, 40), num_epochs=15)
        assert net.score(ds) < s0 * 0.6

    def test_cg_param_averaging_mode(self):
        net = make_cg_net()
        pw = (ParallelWrapper.Builder(net).workers(8)
              .averaging_frequency(4).build())
        x, y = blob_data(n=320)
        ds = DataSet(x, y)
        s0 = net.score(ds)
        pw.fit(ListDataSetIterator(ds, 40), num_epochs=12)
        assert net.score(ds) < s0 * 0.6


class TestTensorParallel:
    def test_tp_fit(self):
        net = make_net()
        pw = (ParallelWrapper.Builder(net).workers(8)
              .tensor_parallel(True).build())
        assert pw.mesh.shape["model"] == 2
        x, y = blob_data()
        ds = DataSet(x, y)
        s0 = net.score(ds)
        pw.fit(ListDataSetIterator(ds, 40), num_epochs=10)
        assert net.score(ds) < s0


class TestZeroShardedUpdaterState:
    """ZeRO-1 analog: optimizer state partitioned over the data axis.

    Numerics must match the replicated-state run exactly (sharding a pure
    elementwise optimizer update changes layout, not math); the state leaves
    must actually live sharded on the mesh."""

    @staticmethod
    def _adam_net(seed=11):
        conf = (NeuralNetConfiguration.Builder()
                .seed(seed).learning_rate(0.05).updater("adam")
                .list()
                .layer(0, DenseLayer(n_out=16, activation="relu"))
                .layer(1, OutputLayer(n_out=3, activation="softmax",
                                      loss_function="mcxent"))
                .set_input_type(InputType.feed_forward(4))
                .build())
        return MultiLayerNetwork(conf).init()

    @pytest.mark.slow
    def test_matches_replicated(self):
        x, y = blob_data(n=64)
        ds = DataSet(x, y)
        net_a, net_b = self._adam_net(), self._adam_net()
        net_b.set_params(net_a.params())
        pw_a = (ParallelWrapper.Builder(net_a).workers(8)
                .sharded_updater_state(True).build())
        pw_b = ParallelWrapper.Builder(net_b).workers(8).build()
        pw_a.fit(ListDataSetIterator(ds, 64), num_epochs=4)
        pw_b.fit(ListDataSetIterator(ds, 64), num_epochs=4)
        np.testing.assert_allclose(net_a.params(), net_b.params(),
                                   rtol=1e-5, atol=1e-6)

    def test_state_actually_sharded(self):
        x, y = blob_data(n=64)
        net = self._adam_net()
        pw = (ParallelWrapper.Builder(net).workers(8)
              .sharded_updater_state(True).build())
        pw.fit(ListDataSetIterator(DataSet(x, y), 64), num_epochs=2)
        # layer-0 Adam moment m has shape (4, 16): dim 1 divides 8 devices
        m = net._updater_state[0]["W"]["m"]
        spec = m.sharding.spec
        assert "data" in tuple(spec), spec
        # a leaf no axis of which divides the mesh stays replicated
        b_out = net._updater_state[1]["b"]["m"]   # shape (3,)
        assert all(s is None for s in tuple(b_out.sharding.spec))

    def test_rejects_local_steps_mode(self):
        net = self._adam_net()
        with pytest.raises(ValueError):
            (ParallelWrapper.Builder(net).workers(8)
             .sharded_updater_state(True).averaging_frequency(4).build())


def _cli_iterator():
    """Factory target for the ParallelWrapperMain CLI test."""
    x, y = blob_data(n=64, seed=3)
    return ListDataSetIterator(DataSet(x, y), 32)


class TestEarlyStoppingParallelTrainer:
    def test_early_stops_over_parallel_wrapper(self, tmp_path):
        from deeplearning4j_tpu.earlystopping.early_stopping import (
            DataSetLossCalculator, EarlyStoppingConfiguration,
            LocalFileModelSaver, MaxEpochsTerminationCondition)
        from deeplearning4j_tpu.parallel.early_stopping import \
            EarlyStoppingParallelTrainer
        net = make_net(seed=3)
        x, y = blob_data(n=128, seed=1)
        train_it = ListDataSetIterator(DataSet(x, y), 32)
        es = (EarlyStoppingConfiguration.Builder()
              .model_saver(LocalFileModelSaver(str(tmp_path)))
              .score_calculator(DataSetLossCalculator(
                  ListDataSetIterator(DataSet(x, y), 64)))
              .epoch_termination_conditions(
                  MaxEpochsTerminationCondition(4))
              .build())
        trainer = EarlyStoppingParallelTrainer(es, net, train_it, workers=8)
        result = trainer.fit()
        assert result.total_epochs <= 5
        assert result.get_best_model() is not None
        assert np.isfinite(result.best_model_score)


class TestParallelWrapperMain:
    def test_cli_trains_and_saves(self, tmp_path):
        """Full CLI path in-process: guessed model load -> ParallelWrapper
        training via an iterator factory -> serialized output model."""
        from deeplearning4j_tpu.parallel.main import run
        from deeplearning4j_tpu.util.model_serializer import (
            restore_multi_layer_network, write_model)
        net = make_net(seed=9)
        src = str(tmp_path / "in.zip")
        dst = str(tmp_path / "out.zip")
        write_model(net, src, save_updater=True)
        x, y = blob_data(n=64, seed=3)
        s0 = make_net(seed=9).score(DataSet(x, y))
        trained = run([
            "--model-path", src,
            "--iterator-factory", "tests.test_parallel:_cli_iterator",
            "--workers", "8", "--epochs", "6", "--report-score",
            "--model-output-path", dst,
        ])
        assert trained.score(DataSet(x, y)) < s0
        restored = restore_multi_layer_network(dst)
        np.testing.assert_allclose(restored.params(), trained.params(),
                                   rtol=1e-6)

    def test_kstep_averaging_mode_forms_groups(self, tmp_path):
        """averaging_frequency>1 must route the WHOLE epoch iterator
        through ParallelWrapper so k-batch groups actually form."""
        from deeplearning4j_tpu.earlystopping.early_stopping import (
            DataSetLossCalculator, EarlyStoppingConfiguration,
            LocalFileModelSaver, MaxEpochsTerminationCondition)
        from deeplearning4j_tpu.parallel.early_stopping import \
            EarlyStoppingParallelTrainer
        net = make_net(seed=5)
        x, y = blob_data(n=128, seed=2)
        train_it = ListDataSetIterator(DataSet(x, y), 16)  # 8 batches
        es = (EarlyStoppingConfiguration.Builder()
              .model_saver(LocalFileModelSaver(str(tmp_path)))
              .score_calculator(DataSetLossCalculator(
                  ListDataSetIterator(DataSet(x, y), 64)))
              .epoch_termination_conditions(
                  MaxEpochsTerminationCondition(3))
              .build())
        trainer = EarlyStoppingParallelTrainer(
            es, net, train_it, workers=8, averaging_frequency=4)
        result = trainer.fit()
        assert result.get_best_model() is not None
        # 3 epochs x 8 batches in k=4 groups -> iteration_count advanced
        # by k per group: 8 per epoch
        assert net.conf.iteration_count == 3 * 8
