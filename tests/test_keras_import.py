"""Keras import: synthetic HDF5 models verified against manual numpy
forward passes (the reference's pattern: import then assert output equality,
modelimport ModelConfigurationTest/KerasLayerTest), plus a committed
real-Keras functional-model fixture (dl4j-test-resources pattern)."""
import json
import os

import h5py
import numpy as np
import pytest

from deeplearning4j_tpu.keras import (import_keras_model_and_weights,
                                      import_keras_model_configuration,
                                      import_keras_sequential_model_and_weights)

FIXTURES = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "fixtures")


def _write_model(path, layer_cfgs, weights):
    """weights: dict layer_name -> list[(suffix, array)]."""
    cfg = {"class_name": "Sequential",
           "config": [{"class_name": c, "config": k}
                      for c, k in layer_cfgs]}
    with h5py.File(path, "w") as f:
        f.attrs["model_config"] = json.dumps(cfg).encode("utf-8")
        mw = f.create_group("model_weights")
        for lname, arrs in weights.items():
            g = mw.create_group(lname)
            names = []
            for suffix, arr in arrs:
                n = f"{lname}_{suffix}"
                g.create_dataset(n, data=np.asarray(arr, np.float32))
                names.append(n.encode())
            g.attrs["weight_names"] = names


def test_dense_mlp_output_matches_numpy(tmp_path):
    rng = np.random.default_rng(0)
    W1 = rng.standard_normal((4, 8)).astype(np.float32)
    b1 = rng.standard_normal(8).astype(np.float32)
    W2 = rng.standard_normal((8, 3)).astype(np.float32)
    b2 = rng.standard_normal(3).astype(np.float32)
    p = str(tmp_path / "mlp.h5")
    _write_model(
        p,
        [("Dense", {"name": "d1", "output_dim": 8, "activation": "relu",
                    "batch_input_shape": [None, 4]}),
         ("Dense", {"name": "d2", "output_dim": 3,
                    "activation": "softmax"})],
        {"d1": [("W", W1), ("b", b1)], "d2": [("W", W2), ("b", b2)]})
    net = import_keras_sequential_model_and_weights(p)
    x = rng.standard_normal((5, 4)).astype(np.float32)
    got = np.asarray(net.output(x))
    h = np.maximum(x @ W1 + b1, 0)
    z = h @ W2 + b2
    want = np.exp(z - z.max(1, keepdims=True))
    want /= want.sum(1, keepdims=True)
    assert np.allclose(got, want, atol=1e-5)


def test_conv_th_ordering_matches_numpy(tmp_path):
    rng = np.random.default_rng(1)
    C, H, W = 2, 8, 8
    F, KH, KW = 3, 3, 3
    Wc = rng.standard_normal((F, C, KH, KW)).astype(np.float32)  # OIHW (th)
    bc = rng.standard_normal(F).astype(np.float32)
    OH, OW = H - KH + 1, W - KW + 1
    PH, PW = OH // 2, OW // 2
    Wd = rng.standard_normal((F * PH * PW, 4)).astype(np.float32)  # CHW rows
    bd = rng.standard_normal(4).astype(np.float32)
    p = str(tmp_path / "conv.h5")
    _write_model(
        p,
        [("Convolution2D", {"name": "c1", "nb_filter": F, "nb_row": KH,
                            "nb_col": KW, "activation": "relu",
                            "dim_ordering": "th", "border_mode": "valid",
                            "batch_input_shape": [None, C, H, W]}),
         ("MaxPooling2D", {"name": "p1", "pool_size": [2, 2],
                           "strides": [2, 2], "dim_ordering": "th"}),
         ("Flatten", {"name": "f1"}),
         ("Dense", {"name": "d1", "output_dim": 4,
                    "activation": "identity" if False else "linear"})],
        {"c1": [("W", Wc), ("b", bc)], "d1": [("W", Wd), ("b", bd)]})
    net = import_keras_sequential_model_and_weights(p)

    x_nchw = rng.standard_normal((2, C, H, W)).astype(np.float32)
    # manual NCHW forward
    conv = np.zeros((2, F, OH, OW), np.float32)
    for n in range(2):
        for f in range(F):
            for i in range(OH):
                for j in range(OW):
                    conv[n, f, i, j] = (
                        x_nchw[n, :, i:i + KH, j:j + KW] * Wc[f]).sum() + bc[f]
    conv = np.maximum(conv, 0)
    pool = conv[:, :, :PH * 2, :PW * 2].reshape(2, F, PH, 2, PW, 2).max((3, 5))
    flat = pool.reshape(2, -1)        # CHW order
    want = flat @ Wd + bd

    x_nhwc = x_nchw.transpose(0, 2, 3, 1)
    got = np.asarray(net.output(x_nhwc))
    assert np.allclose(got, want, atol=1e-3), np.abs(got - want).max()


def test_lstm_matches_numpy(tmp_path):
    rng = np.random.default_rng(2)
    nin, H = 3, 5
    mk = lambda *s: rng.standard_normal(s).astype(np.float32) * 0.5
    Wi, Ui, bi = mk(nin, H), mk(H, H), mk(H)
    Wc, Uc, bc = mk(nin, H), mk(H, H), mk(H)
    Wf, Uf, bf = mk(nin, H), mk(H, H), mk(H)
    Wo, Uo, bo = mk(nin, H), mk(H, H), mk(H)
    p = str(tmp_path / "lstm.h5")
    _write_model(
        p,
        [("LSTM", {"name": "l1", "output_dim": H, "activation": "tanh",
                   "inner_activation": "hard_sigmoid",
                   "batch_input_shape": [None, 6, nin]}),
         ("Dense", {"name": "d1", "output_dim": 2, "activation": "linear"})],
        {"l1": [("W_i", Wi), ("U_i", Ui), ("b_i", bi),
                ("W_c", Wc), ("U_c", Uc), ("b_c", bc),
                ("W_f", Wf), ("U_f", Uf), ("b_f", bf),
                ("W_o", Wo), ("U_o", Uo), ("b_o", bo)],
         "d1": [("W", mk(H, 2)), ("b", mk(2))]})
    net = import_keras_sequential_model_and_weights(p)

    x = rng.standard_normal((2, 6, nin)).astype(np.float32)
    hs = lambda v: np.clip(0.2 * v + 0.5, 0, 1)
    h = np.zeros((2, H), np.float32)
    c = np.zeros((2, H), np.float32)
    for t in range(6):
        xt = x[:, t]
        i = hs(xt @ Wi + h @ Ui + bi)
        f = hs(xt @ Wf + h @ Uf + bf)
        a = np.tanh(xt @ Wc + h @ Uc + bc)
        c = f * c + i * a
        o = hs(xt @ Wo + h @ Uo + bo)
        h = o * np.tanh(c)
    Wd = net._params[1]["W"]
    bd = net._params[1]["b"]
    want = h @ np.asarray(Wd) + np.asarray(bd)
    got = np.asarray(net.output(x))[:, -1]
    assert np.allclose(got, want, atol=1e-4), np.abs(got - want).max()


def test_batchnorm_inference_stats(tmp_path):
    rng = np.random.default_rng(3)
    gamma = rng.standard_normal(4).astype(np.float32)
    beta = rng.standard_normal(4).astype(np.float32)
    mean = rng.standard_normal(4).astype(np.float32)
    var = np.abs(rng.standard_normal(4)).astype(np.float32) + 0.5
    p = str(tmp_path / "bn.h5")
    _write_model(
        p,
        [("BatchNormalization", {"name": "bn", "epsilon": 1e-5,
                                 "batch_input_shape": [None, 4]})],
        {"bn": [("gamma", gamma), ("beta", beta),
                ("running_mean", mean), ("running_std", var)]})
    net = import_keras_sequential_model_and_weights(p)
    x = rng.standard_normal((6, 4)).astype(np.float32)
    want = gamma * (x - mean) / np.sqrt(var + 1e-5) + beta
    got = np.asarray(net.output(x))
    assert np.allclose(got, want, atol=1e-5)


def test_config_only_import():
    cfg = {"class_name": "Sequential", "config": [
        {"class_name": "Dense",
         "config": {"name": "d", "output_dim": 7, "activation": "tanh",
                    "batch_input_shape": [None, 3]}}]}
    conf = import_keras_model_configuration(json.dumps(cfg))
    assert conf.layers[0].n_out == 7
    assert conf.layers[0].n_in == 3
    assert conf.layers[0].activation == "tanh"


def test_dense_linear_plus_activation_becomes_trainable_head():
    """Keras-1 classic: Dense(linear) + separate Activation('softmax') —
    must import with a loss head so fit()/score() work (keras bridge)."""
    import numpy as np

    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.nn.conf.layers.feedforward import LossLayer
    cfg = {"class_name": "Sequential", "config": [
        {"class_name": "Dense",
         "config": {"name": "d", "output_dim": 3, "activation": "linear",
                    "batch_input_shape": [None, 4]}},
        {"class_name": "Activation",
         "config": {"name": "a", "activation": "softmax"}}]}
    conf = import_keras_model_configuration(json.dumps(cfg))
    assert isinstance(conf.layers[-1], LossLayer)
    assert conf.layers[-1].loss_function == "mcxent"
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    net = MultiLayerNetwork(conf).init()
    rng = np.random.default_rng(0)
    x = rng.random((16, 4)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 16)]
    net.fit(DataSet(x, y))
    assert np.isfinite(float(net.score()))


def test_asymmetric_zero_padding_raises():
    cfg = {"class_name": "Sequential", "config": [
        {"class_name": "ZeroPadding2D",
         "config": {"name": "zp", "padding": [[1, 2], [1, 1]],
                    "batch_input_shape": [None, 8, 8, 3]}}]}
    with pytest.raises(ValueError, match="Asymmetric ZeroPadding2D"):
        import_keras_model_configuration(json.dumps(cfg))


def test_symmetric_nested_zero_padding_imports():
    cfg = {"class_name": "Sequential", "config": [
        {"class_name": "ZeroPadding2D",
         "config": {"name": "zp", "padding": [[2, 2], [3, 3]],
                    "batch_input_shape": [None, 8, 8, 3]}},
        {"class_name": "Flatten", "config": {"name": "f"}},
        {"class_name": "Dense",
         "config": {"name": "d", "output_dim": 4, "activation": "tanh"}}]}
    conf = import_keras_model_configuration(json.dumps(cfg))
    assert conf.layers[0].pad == (2, 3)


def test_unsupported_layer_raises():
    cfg = {"class_name": "Sequential", "config": [
        {"class_name": "Lambda",
         "config": {"name": "x", "batch_input_shape": [None, 3]}}]}
    with pytest.raises(ValueError, match="Unsupported Keras layer"):
        import_keras_model_configuration(json.dumps(cfg))


# ---------------------------------------------------------------------------
# Functional Model -> ComputationGraph (reference KerasModel.java:57)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_functional_import_real_keras_fixture():
    """Committed h5 written by an actual Keras installation (generator:
    tests/fixtures/make_keras_fixture.py): Conv branches + Add + Concatenate
    + BN + Flatten + softmax Dense. Outputs must match Keras's own
    predictions."""
    net = import_keras_model_and_weights(
        os.path.join(FIXTURES, "keras_toy_residual.h5"))
    from deeplearning4j_tpu.nn.graph import ComputationGraph
    assert isinstance(net, ComputationGraph)
    io = np.load(os.path.join(FIXTURES, "keras_toy_residual_io.npz"))
    got = np.asarray(net.output(io["x"])[0])
    assert got.shape == io["y"].shape
    assert np.abs(got - io["y"]).max() < 1e-5


def test_functional_import_keras1_dialect_matches_numpy(tmp_path):
    """Keras 1.x 'Model' JSON dialect: classic inbound_nodes triples, Merge
    with mode=sum, th dim-ordering convs, Dense-after-Flatten row permute.
    Verified against a manual numpy forward."""
    rng = np.random.default_rng(9)
    C, H, W = 2, 6, 6
    F = 3
    Wa = rng.standard_normal((F, C, 1, 1)).astype(np.float32)   # OIHW
    ba = rng.standard_normal(F).astype(np.float32)
    Wb = rng.standard_normal((F, C, 1, 1)).astype(np.float32)
    bb = rng.standard_normal(F).astype(np.float32)
    Wd = rng.standard_normal((F * H * W, 4)).astype(np.float32)  # CHW rows
    bd = rng.standard_normal(4).astype(np.float32)

    layers = [
        {"class_name": "InputLayer", "name": "in1",
         "config": {"name": "in1", "batch_input_shape": [None, C, H, W]},
         "inbound_nodes": []},
        {"class_name": "Convolution2D", "name": "ca",
         "config": {"name": "ca", "nb_filter": F, "nb_row": 1, "nb_col": 1,
                    "activation": "relu", "dim_ordering": "th",
                    "border_mode": "valid"},
         "inbound_nodes": [[["in1", 0, 0]]]},
        {"class_name": "Convolution2D", "name": "cb",
         "config": {"name": "cb", "nb_filter": F, "nb_row": 1, "nb_col": 1,
                    "activation": "linear", "dim_ordering": "th",
                    "border_mode": "valid"},
         "inbound_nodes": [[["in1", 0, 0]]]},
        {"class_name": "Merge", "name": "m1",
         "config": {"name": "m1", "mode": "sum"},
         "inbound_nodes": [[["ca", 0, 0], ["cb", 0, 0]]]},
        {"class_name": "Flatten", "name": "f1",
         "config": {"name": "f1"}, "inbound_nodes": [[["m1", 0, 0]]]},
        {"class_name": "Dense", "name": "d1",
         "config": {"name": "d1", "output_dim": 4, "activation": "linear"},
         "inbound_nodes": [[["f1", 0, 0]]]},
    ]
    cfg = {"class_name": "Model", "config": {
        "name": "toy", "layers": layers,
        "input_layers": [["in1", 0, 0]],
        "output_layers": [["d1", 0, 0]]}}
    p = str(tmp_path / "func1.h5")
    with h5py.File(p, "w") as f:
        f.attrs["model_config"] = json.dumps(cfg).encode("utf-8")
        mw = f.create_group("model_weights")
        for lname, arrs in {"ca": [("W", Wa), ("b", ba)],
                            "cb": [("W", Wb), ("b", bb)],
                            "d1": [("W", Wd), ("b", bd)]}.items():
            g = mw.create_group(lname)
            names = []
            for suffix, arr in arrs:
                n = f"{lname}_{suffix}"
                g.create_dataset(n, data=np.asarray(arr, np.float32))
                names.append(n.encode())
            g.attrs["weight_names"] = names
    net = import_keras_model_and_weights(p)

    x_nchw = rng.standard_normal((3, C, H, W)).astype(np.float32)
    # numpy forward in NCHW (1x1 convs are einsums)
    a = np.maximum(np.einsum("nchw,fcij->nfhw", x_nchw, Wa)
                   + ba[None, :, None, None], 0)
    b = (np.einsum("nchw,fcij->nfhw", x_nchw, Wb)
         + bb[None, :, None, None])
    m = a + b
    want = m.reshape(3, -1) @ Wd + bd   # CHW flatten

    got = np.asarray(net.output(x_nchw.transpose(0, 2, 3, 1))[0])
    assert np.allclose(got, want, atol=1e-4), np.abs(got - want).max()


def test_functional_output_dense_becomes_trainable_output_layer():
    net = import_keras_model_and_weights(
        os.path.join(FIXTURES, "keras_toy_residual.h5"))
    from deeplearning4j_tpu.nn.conf.layers import OutputLayer
    assert isinstance(net.conf.vertices["dense_out"].conf, OutputLayer)
    # and the imported graph trains
    io = np.load(os.path.join(FIXTURES, "keras_toy_residual_io.npz"))
    y = np.eye(10, dtype=np.float32)[np.arange(5)]
    from deeplearning4j_tpu.datasets.dataset import MultiDataSet
    mds = MultiDataSet([io["x"]], [y])
    s0 = net.score(mds)
    for _ in range(5):
        net.fit(mds)
    assert net.score(mds) < s0


def test_conv_use_bias_false_imports(tmp_path):
    """Conv2D(use_bias=False) — kernel-only weight group (standard for
    conv+BN models) must import without a bias param."""
    import numpy as np

    rng = np.random.default_rng(0)
    W = rng.standard_normal((3, 3, 1, 4)).astype(np.float32)
    path = str(tmp_path / "nb.h5")
    cfg = {"class_name": "Sequential", "config": [
        {"class_name": "Conv2D",
         "config": {"name": "c", "filters": 4, "kernel_size": [3, 3],
                    "use_bias": False, "activation": "relu",
                    "batch_input_shape": [None, 8, 8, 1]}},
        {"class_name": "Flatten", "config": {"name": "f"}},
        {"class_name": "Dense",
         "config": {"name": "d", "units": 2, "activation": "softmax"}}]}
    Wd = rng.standard_normal((144, 2)).astype(np.float32)
    bd = np.zeros(2, np.float32)
    with h5py.File(path, "w") as f:
        f.attrs["model_config"] = json.dumps(cfg).encode("utf-8")
        mw = f.create_group("model_weights")
        g = mw.create_group("c")
        g.create_dataset("c_W", data=W)
        g.attrs["weight_names"] = [b"c_W"]
        g2 = mw.create_group("d")
        g2.create_dataset("d_W", data=Wd)
        g2.create_dataset("d_b", data=bd)
        g2.attrs["weight_names"] = [b"d_W", b"d_b"]
    net = import_keras_sequential_model_and_weights(path)
    assert "b" not in net._params[0]
    assert np.allclose(np.asarray(net._params[0]["W"]), W)
    out = np.asarray(net.output(rng.random((2, 8, 8, 1)).astype(np.float32)))
    assert out.shape == (2, 2)
