"""Parameter-server worker process for test_ps_transport.py.

Builds the SAME architecture as the master (its own params are never used),
shards the dataset by worker id, and runs the pull->grad->push loop against
the remote master. Usage:
python tests/ps_remote_worker.py <worker_id> <n_workers> <port>
"""
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

from deeplearning4j_tpu.datasets.iterators import \
    ListDataSetIterator  # noqa: E402
from deeplearning4j_tpu.parallel.ps_transport import \
    ps_worker_fit  # noqa: E402
from ps_remote_server import build_data, build_net  # noqa: E402


def main():
    worker_id, n_workers, port = (int(sys.argv[1]), int(sys.argv[2]),
                                  int(sys.argv[3]))
    net = build_net()
    batches = list(build_data().batch_by(32))
    shard = batches[worker_id::n_workers]
    stats = ps_worker_fit(net, "127.0.0.1", port,
                          ListDataSetIterator(shard), num_epochs=3,
                          seed=worker_id)
    print("WORKER", worker_id, "pushed", len(shard) * 3,
          "applied_seen", stats["applied"], flush=True)


if __name__ == "__main__":
    main()
