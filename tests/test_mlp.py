"""End-to-end tests for the minimum slice: config DSL -> MultiLayerNetwork ->
fit/output/evaluate on synthetic data, plus gradient checks.

Mirrors the reference's backbone test strategy (SURVEY.md §4): gradient checks
+ convergence tests (deeplearning4j-core/src/test/.../gradientcheck/,
nn/multilayer/).
"""
import numpy as np
import pytest

from deeplearning4j_tpu import (InputType, MultiLayerConfiguration,
                                MultiLayerNetwork, NeuralNetConfiguration)
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterators import ListDataSetIterator
from deeplearning4j_tpu.gradientcheck.gradient_check_util import check_gradients
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer


def make_blobs(n=200, n_features=4, n_classes=3, seed=0):
    rng = np.random.default_rng(seed)
    centers = rng.normal(0, 3, (n_classes, n_features))
    X, Y = [], []
    for i in range(n):
        c = i % n_classes
        X.append(centers[c] + rng.normal(0, 0.5, n_features))
        y = np.zeros(n_classes)
        y[c] = 1.0
        Y.append(y)
    return np.array(X, np.float32), np.array(Y, np.float32)


def mlp_conf(lr=0.1, updater="sgd", seed=42, n_in=4, n_hidden=16, n_classes=3,
             **g):
    b = (NeuralNetConfiguration.Builder()
         .seed(seed)
         .updater(updater)
         .learning_rate(lr))
    for k, v in g.items():
        getattr(b, k)(v)
    return (b.list()
            .layer(0, DenseLayer(n_out=n_hidden, activation="relu",
                                 weight_init="xavier"))
            .layer(1, OutputLayer(n_out=n_classes, activation="softmax",
                                  loss_function="mcxent"))
            .set_input_type(InputType.feed_forward(n_in))
            .build())


class TestMLP:
    def test_nin_inference(self):
        conf = mlp_conf()
        assert conf.layers[0].n_in == 4
        assert conf.layers[1].n_in == 16

    def test_param_counts(self):
        net = MultiLayerNetwork(mlp_conf()).init()
        # 4*16+16 + 16*3+3 = 80 + 51 = 131
        assert net.num_params() == 131
        assert net.params().shape == (131,)

    def test_set_get_params_roundtrip(self):
        net = MultiLayerNetwork(mlp_conf()).init()
        p = net.params()
        p2 = np.arange(p.size, dtype=np.float32) / p.size
        net.set_params(p2)
        np.testing.assert_allclose(net.params(), p2, rtol=1e-6)

    def test_output_shape(self):
        net = MultiLayerNetwork(mlp_conf()).init()
        X, _ = make_blobs(10)
        out = np.asarray(net.output(X))
        assert out.shape == (10, 3)
        np.testing.assert_allclose(out.sum(axis=1), 1.0, atol=1e-5)

    def test_fit_reduces_score(self):
        X, Y = make_blobs(120)
        net = MultiLayerNetwork(mlp_conf(lr=0.5)).init()
        ds = DataSet(X, Y)
        s0 = net.score(ds)
        net.fit(ListDataSetIterator(ds, 32), num_epochs=20)
        s1 = net.score(ds)
        assert s1 < s0 * 0.5, f"score did not drop: {s0} -> {s1}"

    def test_fit_accuracy(self):
        X, Y = make_blobs(300)
        net = MultiLayerNetwork(mlp_conf(lr=0.3, updater="adam",
                                         learning_rate=0.01)).init()
        net.fit(ListDataSetIterator(DataSet(X, Y), 50), num_epochs=30)
        ev = net.evaluate(ListDataSetIterator(DataSet(X, Y), 100))
        assert ev.accuracy() > 0.95, ev.stats()

    def test_feed_forward_activations(self):
        net = MultiLayerNetwork(mlp_conf()).init()
        X, _ = make_blobs(5)
        acts = net.feed_forward(X)
        assert len(acts) == 3  # input + 2 layers
        assert acts[1].shape == (5, 16)
        assert acts[2].shape == (5, 3)

    def test_iteration_count_increments(self):
        X, Y = make_blobs(64)
        net = MultiLayerNetwork(mlp_conf()).init()
        net.fit(ListDataSetIterator(DataSet(X, Y), 16), num_epochs=2)
        assert net.conf.iteration_count == 8


class TestSerde:
    def test_json_roundtrip(self):
        conf = mlp_conf(updater="adam", l2=1e-4)
        s = conf.to_json()
        conf2 = MultiLayerConfiguration.from_json(s)
        assert conf2.to_json() == s
        assert conf2.layers[0].n_out == 16
        assert conf2.layers[1].loss_function == "mcxent"

    def test_network_from_deserialized_conf(self):
        conf = MultiLayerConfiguration.from_json(mlp_conf().to_json())
        net = MultiLayerNetwork(conf).init()
        assert net.num_params() == 131


class TestGradients:
    def _check(self, **kwargs):
        X, Y = make_blobs(8)
        conf = mlp_conf(data_type="float64", **kwargs)
        net = MultiLayerNetwork(conf).init()
        assert check_gradients(net, X, Y, epsilon=1e-6, max_rel_error=1e-4)

    def test_gradcheck_mlp_softmax(self):
        self._check()

    def test_gradcheck_l1_l2(self):
        self._check(l1=0.01, l2=0.02)

    def test_gradcheck_tanh_mse(self):
        X, Y = make_blobs(8)
        conf = (NeuralNetConfiguration.Builder()
                .seed(7).data_type("float64").learning_rate(0.1)
                .list()
                .layer(0, DenseLayer(n_out=8, activation="tanh"))
                .layer(1, OutputLayer(n_out=3, activation="identity",
                                      loss_function="mse"))
                .set_input_type(InputType.feed_forward(4))
                .build())
        net = MultiLayerNetwork(conf).init()
        assert check_gradients(net, X, Y, max_rel_error=1e-4)

    def test_gradcheck_sigmoid_xent(self):
        X, Y = make_blobs(8)
        conf = (NeuralNetConfiguration.Builder()
                .seed(7).data_type("float64").learning_rate(0.1)
                .list()
                .layer(0, DenseLayer(n_out=8, activation="elu"))
                .layer(1, OutputLayer(n_out=3, activation="sigmoid",
                                      loss_function="xent"))
                .set_input_type(InputType.feed_forward(4))
                .build())
        net = MultiLayerNetwork(conf).init()
        assert check_gradients(net, X, Y, max_rel_error=1e-4)


class TestEvaluation:
    def test_eval_counts(self):
        from deeplearning4j_tpu.eval.evaluation import Evaluation
        ev = Evaluation()
        labels = np.eye(3)[[0, 1, 2, 0, 1]]
        preds = np.eye(3)[[0, 1, 1, 0, 1]]
        ev.eval(labels, preds)
        assert ev.accuracy() == pytest.approx(0.8)
        assert ev.precision(1) == pytest.approx(2 / 3)
        assert ev.recall(2) == pytest.approx(0.0)

    def test_eval_merge(self):
        from deeplearning4j_tpu.eval.evaluation import Evaluation
        labels = np.eye(3)[[0, 1, 2, 0]]
        preds = np.eye(3)[[0, 1, 2, 1]]
        e1 = Evaluation().eval(labels[:2], preds[:2])
        e2 = Evaluation().eval(labels[2:], preds[2:])
        e1.merge(e2)
        full = Evaluation().eval(labels, preds)
        assert e1.accuracy() == full.accuracy()
