"""Cross-process asynchronous parameter server (VERDICT r4 item 5).

The reference PS is inherently cross-process — ParameterServerParallelWrapper
launches an Aeron MediaDriver and workers talk to it over UDP
(ParameterServerParallelWrapper.java:159-160). These tests put a REAL
process/network boundary under the same semantics: one master process owning
the accumulator, two worker processes pushing gradients over TCP, and the
convergence compared against the in-process PS on identical data.
"""
import os
import subprocess
import sys
import time

import numpy as np
import pytest

pytestmark = pytest.mark.multiprocess

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _clean_env():
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    env["PYTHONPATH"] = REPO + os.pathsep + os.path.join(REPO, "tests")
    return env


def test_two_process_ps_converges_like_in_process(tmp_path):
    port_file = str(tmp_path / "port")
    env = _clean_env()
    server = subprocess.Popen(
        [sys.executable, os.path.join(REPO, "tests", "ps_remote_server.py"),
         port_file, "2"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True, env=env)
    try:
        for _ in range(600):                      # wait for the bound port
            if os.path.exists(port_file) and open(port_file).read().strip():
                break
            if server.poll() is not None:
                raise AssertionError(
                    f"server died early:\n{server.stdout.read()}")
            time.sleep(0.1)
        port = open(port_file).read().strip()
        workers = [subprocess.Popen(
            [sys.executable,
             os.path.join(REPO, "tests", "ps_remote_worker.py"),
             str(i), "2", port],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env) for i in range(2)]
        wouts = [p.communicate(timeout=240)[0] for p in workers]
        for i, (p, out) in enumerate(zip(workers, wouts)):
            assert p.returncode == 0, f"worker {i} failed:\n{out}"
        sout, _ = server.communicate(timeout=120)
        assert server.returncode == 0, f"server failed:\n{sout}"
    finally:
        if server.poll() is None:
            server.kill()
    result = next(l for l in sout.splitlines() if l.startswith("RESULT"))
    fields = dict(kv.split("=") for kv in result.split()[1:])
    s0, score = float(fields["s0"]), float(fields["score"])
    # every push from both workers was applied or counted as dropped:
    # 8 batches x 3 epochs = 24 total
    assert int(fields["applied"]) + int(fields["stale_dropped"]) == 24
    assert np.isfinite(score) and score < s0

    # convergence ~ the in-process PS on the SAME data/arch/hyperparams
    # (the network boundary must not change the training semantics)
    from deeplearning4j_tpu.datasets.iterators import ListDataSetIterator
    from deeplearning4j_tpu.parallel import ParameterServerParallelWrapper
    sys.path.insert(0, os.path.join(REPO, "tests"))
    try:
        from ps_remote_server import build_data, build_net
    finally:
        sys.path.pop(0)
    net = build_net()
    ds = build_data()
    psw = (ParameterServerParallelWrapper.Builder(net)
           .workers(2).queue_size(4).build())
    psw.fit(ListDataSetIterator(list(ds.batch_by(32))), num_epochs=3)
    in_proc = float(net.score(ds))
    assert score < s0 - 0.5 * (s0 - in_proc), (
        f"remote PS converged too little: remote {score}, "
        f"in-process {in_proc}, start {s0}")


def test_ps_leaf_serialization_round_trip():
    """Wire format: every dtype/shape the params and BN state use survives
    pack->unpack bit-exactly, including 0-d scalars and empty arrays."""
    from deeplearning4j_tpu.parallel.ps_transport import (pack_leaves,
                                                          unpack_leaves)
    rng = np.random.default_rng(0)
    leaves = [rng.standard_normal((4, 7)).astype(np.float32),
              np.float32(3.25).reshape(()),
              rng.integers(0, 9, (3,), dtype=np.int64),
              np.empty((0, 5), np.float32),
              rng.standard_normal((2, 3, 4)).astype(np.float64)]
    buf = pack_leaves(leaves) + b"trailing"
    out, off = unpack_leaves(buf)
    assert off == len(buf) - len(b"trailing")
    assert len(out) == len(leaves)
    for a, b in zip(leaves, out):
        assert a.dtype == b.dtype and a.shape == b.shape
        np.testing.assert_array_equal(a, b)


def test_leaf_serialization_bfloat16_round_trip():
    """Regression: dtype was serialized as numpy dtype.str, which for
    ml_dtypes types is raw void ('<V2') — a bf16 model's params/grads
    came back as opaque void arrays on the peer."""
    import ml_dtypes

    from deeplearning4j_tpu.parallel.ps_transport import (pack_leaves,
                                                          unpack_leaves)
    leaves = [np.linspace(-2, 2, 8).astype(ml_dtypes.bfloat16),
              np.float32(1.5),
              np.arange(6, dtype=np.int32).reshape(2, 3)]
    out, _ = unpack_leaves(pack_leaves(leaves))
    assert out[0].dtype == ml_dtypes.bfloat16
    np.testing.assert_array_equal(
        np.asarray(out[0], np.float32), np.asarray(leaves[0], np.float32))
    assert out[1].dtype == np.float32 and float(out[1]) == 1.5
    np.testing.assert_array_equal(out[2], leaves[2])


def test_client_errors_are_loud():
    """A dead server is a ConnectionError at connect; a half-open server
    that closes mid-protocol raises instead of hanging or mis-parsing.
    Since the resilience round every connection opens with a HELLO
    handshake, so the mid-protocol close surfaces at construction."""
    import socket
    import threading
    from deeplearning4j_tpu.parallel.ps_transport import PSClient
    with pytest.raises(OSError):
        PSClient("127.0.0.1", 1, connect_timeout=1)
    # server that accepts then immediately closes: the HELLO handshake
    # must raise a ConnectionError (peer closed), not return garbage
    srv = socket.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(1)
    port = srv.getsockname()[1]

    def accept_close():
        conn, _ = srv.accept()
        conn.close()

    t = threading.Thread(target=accept_close, daemon=True)
    t.start()
    with pytest.raises(ConnectionError):
        PSClient("127.0.0.1", port, connect_timeout=5)
    t.join(timeout=5)
    srv.close()


def test_server_on_fresh_net_accepts_push():
    """Regression: PSServer built around a NEVER-initialized net captured
    the treedef before GradientsAccumulator ran _ensure_init, freezing the
    empty None-pytree and making every PUSH unflatten blow up."""
    from deeplearning4j_tpu import (InputType, MultiLayerNetwork,
                                    NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.parallel.ps_transport import PSClient, PSServer

    conf = (NeuralNetConfiguration.Builder().seed(3)
            .updater("adam").learning_rate(0.01).list()
            .layer(0, DenseLayer(n_out=8, activation="relu"))
            .layer(1, OutputLayer(n_out=2, activation="softmax",
                                  loss_function="mcxent"))
            .set_input_type(InputType.feed_forward(4))
            .build())
    net = MultiLayerNetwork(conf)    # NOT .init()'ed by the caller
    assert net._params is None       # precondition: genuinely uninitialized
    srv = PSServer(net, n_workers=1)   # serving starts in __init__
    try:
        c = PSClient("127.0.0.1", srv.port)
        leaves, _state, version = c.pull()
        assert len(leaves) > 0
        grads = [np.zeros_like(np.asarray(l)) for l in leaves]
        c.push(grads, 1.0, version)    # raised before the fix
        c.done()
    finally:
        srv.stop()
