"""CJK dictionary ingestion (VERDICT r4 item 6): mecab-format dictionary
compile for the Japanese lattice (reference: Kuromoji
ipadic/compile/DictionaryCompiler.java + dict/UserDictionary.java +
util/DictionaryEntryLineParser.java) and KoreanText-layout wordlist loading
for the Korean analyzer (reference: deeplearning4j-nlp-korean). The
committed fixtures under tests/fixtures/{ja_dict,ko_dict} are format-exact:
IPADIC 13-field token CSVs, full matrix.def, char.def/unk.def, and a
Kuromoji user dictionary."""
import os
import shutil

import pytest

from deeplearning4j_tpu.text.ja_dictionary import (compile_dictionary,
                                                   MecabDictionary,
                                                   parse_entry_line,
                                                   parse_user_dictionary,
                                                   viterbi_segment_dict)
from deeplearning4j_tpu.text.ja_lattice import (
    JapaneseLatticeTokenizer, JapaneseLatticeTokenizerFactory)
from deeplearning4j_tpu.text.ko_dictionary import load_dictionary
from deeplearning4j_tpu.text.ko_morph import (KoreanMorphTokenizer,
                                              KoreanMorphTokenizerFactory)

FIX = os.path.join(os.path.dirname(__file__), "fixtures")
JA = os.path.join(FIX, "ja_dict")
KO = os.path.join(FIX, "ko_dict")


class TestEntryLineParser:
    def test_plain_and_quoted_fields(self):
        assert parse_entry_line("a,b,c") == ["a", "b", "c"]
        # a quoted field keeps its commas (DictionaryEntryLineParser)
        assert parse_entry_line('"3,4-x",1,2') == ["3,4-x", "1", "2"]
        # "" inside a quoted field is a literal quote
        assert parse_entry_line('"say ""hi""",9') == ['say "hi"', "9"]

    def test_unmatched_quote_raises(self):
        with pytest.raises(ValueError):
            parse_entry_line('"unterminated,1,2')


class TestMecabCompile:
    def test_compile_reads_all_components(self):
        dic = compile_dictionary(JA)
        surfaces = {e[0] for e in dic.entries}
        assert {"東京", "東京都", "に", "住む",
                "3,4-ジヒドロキシ安息香酸"} <= surfaces
        # matrix.def header sizes honored
        assert dic.conn.forward_size == dic.conn.backward_size == 7
        assert dic.conn.cost(1, 2) == 0          # noun -> particle
        assert dic.conn.cost(2, 1) == 100        # particle -> noun
        # char.def categories + ranges
        assert dic.char_defs.categories["KATAKANA"] == (1, 1, 0)
        assert dic.char_defs.lookup("ラ") == "KATAKANA"
        assert dic.char_defs.lookup("住") == "KANJI"
        # unk.def templates keyed by category
        assert "KATAKANA" in dic.unk_entries

    def test_lattice_prefers_low_cost_path(self):
        dic = compile_dictionary(JA)
        out = [s for s, _, _ in viterbi_segment_dict("東京都に住む", dic)]
        # 東京都 (5500) beats 東京+都 (3000 + conn 800 + 4000)
        assert out == ["東京都", "に", "住む"]

    def test_matrix_def_drives_segmentation(self, tmp_path):
        """Same CSVs, one matrix.def line changed: the noun->noun-suffix
        join becomes strongly negative and the SPLIT path must win — the
        connection matrix is really consulted, format-exactly."""
        d = tmp_path / "dict"
        shutil.copytree(JA, d)
        lines = (d / "matrix.def").read_text().splitlines()
        patched = ["4 1 -9000" if l == "4 1 800" else l for l in lines]
        assert patched != lines
        (d / "matrix.def").write_text("\n".join(patched) + "\n")
        dic = compile_dictionary(str(d))
        out = [s for s, _, _ in viterbi_segment_dict("東京都に住む", dic)]
        assert out == ["東京", "都", "に", "住む"]

    def test_quoted_surface_matches_in_lattice(self):
        dic = compile_dictionary(JA)
        out = viterbi_segment_dict("3,4-ジヒドロキシ安息香酸です", dic)
        assert [s for s, _, _ in out] == ["3,4-ジヒドロキシ安息香酸",
                                          "です"]

    def test_unknown_words_char_def_semantics(self):
        dic = compile_dictionary(JA)
        # katakana: group=1 -> whole run as one unknown noun
        out = viterbi_segment_dict("コンピュータに住む", dic)
        assert [s for s, _, _ in out] == ["コンピュータ", "に", "住む"]
        assert out[0][1][0] == "名詞"            # unk.def KATAKANA features
        # numeric grouping
        out2 = viterbi_segment_dict("2026に住む", dic)
        assert [s for s, _, _ in out2] == ["2026", "に", "住む"]

    def test_unk_def_without_char_def_still_honored(self, tmp_path):
        """A dictionary shipping unk.def but no char.def: the builtin
        script classes map to the standard uppercase category names, so
        the user's unknown templates apply (not the hardcoded default)."""
        d = tmp_path / "dict"
        shutil.copytree(JA, d)
        os.remove(d / "char.def")
        dic = compile_dictionary(str(d))
        assert dic.char_defs is None and "KATAKANA" in dic.unk_entries
        out = viterbi_segment_dict("コンピュータに住む", dic)
        assert [s for s, _, _ in out] == ["コンピュータ", "に", "住む"]
        assert out[0][1][0] == "名詞"            # unk.def template features

    def test_compiled_artifact_round_trip(self, tmp_path):
        dic = compile_dictionary(JA, user_dict_path=os.path.join(
            JA, "userdict.txt"))
        p = str(tmp_path / "compiled.json")
        dic.save_compiled(p)
        dic2 = MecabDictionary.load_compiled(p)
        for text in ("東京都に住む", "関西国際空港に行った",
                     "コンピュータです"):
            a = viterbi_segment_dict(text, dic)
            b = viterbi_segment_dict(text, dic2)
            assert a == b


class TestUserDictionary:
    def test_user_entry_expands_to_segments(self):
        """関西国際空港 matches as ONE lattice entry but is reported as its
        three segments — UserDictionary.java's match shape."""
        fac = JapaneseLatticeTokenizerFactory(
            dict_path=JA, user_dict_path=os.path.join(JA, "userdict.txt"))
        toks = fac.create("関西国際空港に行った")
        assert toks.get_tokens() == ["関西", "国際", "空港", "に", "行った"]
        assert toks.pos_tags[:3] == ["カスタム名詞"] * 3

    def test_without_user_dict_base_segmentation_differs(self):
        fac = JapaneseLatticeTokenizerFactory(dict_path=JA)
        toks = fac.create("関西国際空港に行った")
        # base dictionary: 関西 + 国際 + 空港 as separate lexical entries
        # with noun->noun connection costs (not the single user entry)
        assert toks.get_tokens()[:3] == ["関西", "国際", "空港"]
        assert toks.pos_tags[0] == "noun"        # not カスタム名詞

    def test_segment_concatenation_validated(self):
        with pytest.raises(ValueError):
            parse_user_dictionary("東京都,東京 京都,トウキョウ キョウト,"
                                  "カスタム名詞")

    def test_user_dict_requires_base_dict(self):
        with pytest.raises(ValueError):
            JapaneseLatticeTokenizerFactory(
                user_dict_path=os.path.join(JA, "userdict.txt"))


class TestDictPathChangesSegmentation:
    def test_builtin_vs_fixture_dictionary(self):
        """The VERDICT acceptance: JapaneseTokenizer(dict_path=...) loads a
        mecab-format CSV and segmentation changes accordingly."""
        text = "東京都に住む"
        builtin = JapaneseLatticeTokenizer(text).get_tokens()
        withdict = JapaneseLatticeTokenizer(
            text, dictionary=compile_dictionary(JA)).get_tokens()
        # both segment, but the fixture's single 東京都 entry wins there
        assert withdict == ["東京都", "に", "住む"]
        assert builtin != withdict


class TestKoreanDictionary:
    def test_load_layout_and_stems(self):
        dic = load_dictionary(KO)
        assert "바다" in dic.nouns and "서울" in dic.nouns
        # verb.txt dictionary forms are stemmed (먹다 -> 먹)
        assert "먹" in dic.verbs and "가" in dic.verbs
        assert "바다" in dic.words("noun")

    def test_known_noun_suppresses_eomi_split(self):
        """바다 ends in 다, which the heuristic strips as a verb ending;
        the dictionary must keep the noun whole — including under a
        particle (바다는 -> 바다|는)."""
        assert KoreanMorphTokenizer("바다").get_tokens() == ["바", "다"]
        dic = load_dictionary(KO)
        assert KoreanMorphTokenizer(
            "바다", dictionary=dic).get_tokens() == ["바다"]
        assert KoreanMorphTokenizer(
            "바다는 넓다", dictionary=dic).get_tokens() == \
            ["바다", "는", "넓", "다"]

    def test_factory_dict_path(self):
        fac = KoreanMorphTokenizerFactory(dict_path=KO)
        assert fac.create("바다는").get_tokens() == ["바다", "는"]

    def test_runtime_word_addition(self):
        """addNounsToDictionary parity: user words extend a category at
        runtime and immediately affect tokenization."""
        dic = load_dictionary(KO)
        # 도자기 ends in the nominalizer 기, which the heuristic strips
        assert KoreanMorphTokenizer(
            "도자기", dictionary=dic).get_tokens() == ["도자", "기"]
        dic.add_words("noun", ["도자기"])
        assert KoreanMorphTokenizer(
            "도자기", dictionary=dic).get_tokens() == ["도자기"]

    def test_missing_dir_raises(self, tmp_path):
        with pytest.raises(ValueError):
            load_dictionary(str(tmp_path))


class TestDictionaryEdgeCases:
    def test_empty_and_unknown_only_text(self):
        dic = compile_dictionary(JA)
        assert viterbi_segment_dict("", dic) == []
        # archaic kana with no dictionary entry: the unknown model still
        # produces a connected lattice (never raises, never drops text)
        out = viterbi_segment_dict("ゑゐ", dic)
        assert "".join(s for s, _, _ in out) == "ゑゐ"

    def test_no_entries_raises(self, tmp_path):
        (tmp_path / "matrix.def").write_text("1 1\n0 0 0\n")
        with pytest.raises(ValueError):
            compile_dictionary(str(tmp_path))

    def test_short_line_raises(self, tmp_path):
        (tmp_path / "bad.csv").write_text("只,1,2\n", encoding="utf-8")
        with pytest.raises(ValueError):
            compile_dictionary(str(tmp_path))
