"""Test configuration.

Tests run on the CPU backend with a virtual 8-device platform so multi-chip
sharding paths compile+execute without TPU hardware (SURVEY.md §4 implication
(c): single-process simulation of a pod), mirroring how the reference
simulates clusters in one JVM (local-mode Spark, embedded Aeron).

x64 is enabled for gradient-check precision (the reference forces double
precision in GradientCheckUtil).
"""
import os

os.environ["JAX_PLATFORMS"] = "cpu"  # force: the outer env may pin a TPU platform
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax

# The interpreter's sitecustomize may have force-registered a TPU platform
# before this file runs; the config update (not just the env var) wins.
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
