"""Test configuration.

Tests run on the CPU backend with a virtual 8-device platform so multi-chip
sharding paths compile+execute without TPU hardware (SURVEY.md §4 implication
(c): single-process simulation of a pod), mirroring how the reference
simulates clusters in one JVM (local-mode Spark, embedded Aeron).

x64 is enabled for gradient-check precision (the reference forces double
precision in GradientCheckUtil).

Tiering (pytest.ini): the default run skips tests marked `slow` /
`multiprocess` — the r3 full suite grew past a 9-minute wall and timed out
the reviewer the same way the unbuffered bench timed out the driver.
`--full-tier` (or DL4J_TPU_FULL_TESTS=1) runs everything. With the
persistent compilation cache below, the core tier measured 136 s warm /
359 s cold on a single-core box (r5) — the <300 s budget holds on every
run after the first without moving a single test out of the tier.
"""
import os

os.environ["JAX_PLATFORMS"] = "cpu"  # force: the outer env may pin a TPU platform
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax

# The interpreter's sitecustomize may have force-registered a TPU platform
# before this file runs; the config update (not just the env var) wins.
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

# The suite's wall clock is dominated by XLA compiles of hundreds of tiny
# programs (the r5 single-core timing: 444 s, top-25 tests = 220 s, almost
# all compile). A persistent compilation cache made warm runs ~3x faster —
# but on this jaxlib (0.4.37 CPU) reading entries back SEGFAULTS the
# interpreter roughly every other run (reproduced in isolation on the
# pristine seed tree: cold write passes, warm reads crash in executable
# deserialization), killing the whole pytest process mid-suite and making
# the tier-1 pass count a coin flip (r6 measured 144 vs 348 dots on
# identical code). Robustness beats warm-run speed: the cache is now
# OPT-IN via DL4J_TPU_TEST_CACHE=1 for environments whose jaxlib
# deserializes reliably; the uncached suite still fits the tier-1 budget.
if os.environ.get("DL4J_TPU_TEST_CACHE"):
    _cache_dir = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), ".jax_test_cache")
    jax.config.update("jax_compilation_cache_dir", _cache_dir)
    # 0.0: the suite is death-by-a-thousand sub-second compiles; store
    # them all (hundreds of small files, disk is cheap)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)

import pytest


def pytest_addoption(parser):
    parser.addoption(
        "--full-tier", action="store_true", default=False,
        help="run the full suite including slow/multiprocess tests")


def pytest_collection_modifyitems(config, items):
    if (config.getoption("--full-tier")
            or os.environ.get("DL4J_TPU_FULL_TESTS", "").lower()
            in ("1", "true", "yes", "on")):
        return
    skip = pytest.mark.skip(
        reason="full tier only (pass --full-tier or DL4J_TPU_FULL_TESTS=1)")
    for item in items:
        if "slow" in item.keywords or "multiprocess" in item.keywords:
            item.add_marker(skip)
