"""Generates the golden checkpoint zips under tests/fixtures/golden/.

Run once per format change (CPU, x64 off):

    JAX_PLATFORMS=cpu python tests/fixtures/make_golden_models.py

The zips are COMMITTED and then never regenerated casually — the regression
test (tests/test_regression_golden.py) restores them and asserts config,
params, updater state, and outputs stay bit-identical, so later rounds
cannot silently drift the checkpoint format (reference pattern:
regressiontest/RegressionTest050.java restoring 0.5.0-era zips).
"""
import json
import os

import numpy as np


def _out(name):
    d = os.path.join(os.path.dirname(os.path.abspath(__file__)), "golden")
    os.makedirs(d, exist_ok=True)
    return os.path.join(d, name)


def _train_a_bit(net, x, y, steps=3):
    from deeplearning4j_tpu.datasets.dataset import DataSet
    ds = DataSet(x, y)
    for _ in range(steps):
        net.fit(ds)
    return net


def make_mlp(rng):
    from deeplearning4j_tpu import (InputType, MultiLayerNetwork,
                                    NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
    conf = (NeuralNetConfiguration.Builder().seed(11)
            .updater("adam").learning_rate(0.01).list()
            .layer(0, DenseLayer(n_out=12, activation="relu"))
            .layer(1, OutputLayer(n_out=3, activation="softmax",
                                  loss_function="mcxent"))
            .set_input_type(InputType.feed_forward(5)).build())
    net = MultiLayerNetwork(conf).init()
    x = rng.random((16, 5)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 16)]
    return _train_a_bit(net, x, y), x


def make_lenet(rng):
    from deeplearning4j_tpu.models.zoo.lenet import lenet
    net = lenet()
    x = rng.random((4, 784)).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[rng.integers(0, 10, 4)]
    return _train_a_bit(net, x, y, steps=2), x


def make_lstm(rng):
    from deeplearning4j_tpu import (InputType, MultiLayerNetwork,
                                    NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.conf.layers import GravesLSTM, RnnOutputLayer
    conf = (NeuralNetConfiguration.Builder().seed(13)
            .updater("rmsprop").learning_rate(0.02).list()
            .layer(0, GravesLSTM(n_out=10, activation="tanh"))
            .layer(1, RnnOutputLayer(n_out=6, activation="softmax",
                                     loss_function="mcxent"))
            .set_input_type(InputType.recurrent(6)).build())
    net = MultiLayerNetwork(conf).init()
    x = np.eye(6, dtype=np.float32)[rng.integers(0, 6, (4, 7))]
    y = np.eye(6, dtype=np.float32)[rng.integers(0, 6, (4, 7))]
    return _train_a_bit(net, x, y), x


def make_cg(rng):
    from deeplearning4j_tpu import InputType, NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf.graph_vertices import MergeVertex
    from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.graph import ComputationGraph
    conf = (NeuralNetConfiguration.Builder().seed(17)
            .updater("nesterovs").momentum(0.9).learning_rate(0.05)
            .graph_builder()
            .add_inputs("in")
            .add_layer("a", DenseLayer(n_out=8, activation="relu"), "in")
            .add_layer("b", DenseLayer(n_out=8, activation="tanh"), "in")
            .add_vertex("m", MergeVertex(), "a", "b")
            .add_layer("out", OutputLayer(n_out=4, activation="softmax",
                                          loss_function="mcxent"), "m")
            .set_outputs("out")
            .set_input_types(InputType.feed_forward(5)).build())
    net = ComputationGraph(conf).init()
    x = rng.random((8, 5)).astype(np.float32)
    y = np.eye(4, dtype=np.float32)[rng.integers(0, 4, 8)]
    from deeplearning4j_tpu.datasets.dataset import MultiDataSet
    mds = MultiDataSet([x], [y])
    for _ in range(3):
        net.fit(mds)
    return net, x


def main():
    from deeplearning4j_tpu.util import model_serializer as ms
    rng = np.random.default_rng(1234)
    manifest = {}
    for name, maker in [("mlp", make_mlp), ("lenet", make_lenet),
                        ("lstm", make_lstm), ("cg", make_cg)]:
        net, x = maker(rng)
        zpath = _out(f"{name}.zip")
        ms.write_model(net, zpath)
        if name == "cg":
            out = np.asarray(net.output(x)[0])
        else:
            out = np.asarray(net.output(x))
        np.savez(_out(f"{name}_io.npz"), x=x, y=out,
                 params=np.asarray(net.params()))
        manifest[name] = {
            "type": type(net).__name__,
            "iteration_count": net.conf.iteration_count,
            "num_params": int(net.num_params()),
        }
        print(name, manifest[name])
    with open(_out("manifest.json"), "w") as fh:
        json.dump(manifest, fh, indent=2)


if __name__ == "__main__":
    main()
