"""graftlint fixture: a lock acquisition-order cycle (seeded bad)."""
import threading


class LockCycle:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()

    def ab(self):
        with self._a:
            with self._b:
                return 1

    def ba(self):
        with self._b:
            with self._a:
                return 2


class NoCycle:
    def __init__(self):
        self._x = threading.Lock()
        self._y = threading.Lock()

    def xy_only(self):
        with self._x:
            with self._y:
                return 3

    def xy_again(self):
        with self._x:
            with self._y:
                return 4
