"""graftlint fixture: a module in a declared stdlib-only layer that
imports device code (seeded layering violation)."""
import threading  # noqa: F401

import jax  # noqa: F401  -- the seeded violation


def measure():
    return threading.active_count()
