"""graftlint fixture: blocking calls under a held lock (seeded bad).

Never imported — tests/test_analyze.py parses it and asserts the
lock-discipline pass reports exactly the seeded findings.
"""
import queue
import socket
import threading
import time


class BlockingUnderLock:
    def __init__(self):
        self._lock = threading.Lock()
        self._q = queue.Queue(4)
        self._sock = socket.socket()

    def bad_send_under_lock(self):
        with self._lock:
            self._sock.sendall(b"payload")

    def bad_sleep_under_lock(self):
        with self._lock:
            time.sleep(0.1)

    def bad_join_under_lock(self):
        with self._lock:
            self._q.join()

    def bad_transitive_under_lock(self):
        with self._lock:
            self.blocking_helper()

    def blocking_helper(self):
        self._q.get(timeout=1.0)

    def ok_send_outside_lock(self):
        with self._lock:
            depth = self._q.qsize()
        self._sock.sendall(str(depth).encode())

    def ok_callback_not_scanned(self):
        with self._lock:
            cb = lambda: self._sock.sendall(b"later")  # noqa: E731
        return cb

    def suppressed_send(self):
        with self._lock:
            # graftlint: disable=lock-discipline -- fixture: the justified-suppression round-trip case
            self._sock.sendall(b"x")

    def suppressed_without_reason(self):
        with self._lock:
            # graftlint: disable=lock-discipline
            self._sock.sendall(b"y")
