"""graftlint fixture: metrics source with an eager-creation loop, a
snapshot surface, and (vs bad_metrics_pins.py) seeded drift both
ways."""


class ServingMetrics:
    def __init__(self):
        for key in ("alpha_total", "beta_total"):
            self.count(key, 0)

    def count(self, key, n=1):
        pass

    def snapshot(self):
        out = {}
        out["gamma_last"] = 1
        out.setdefault("delta", 0)
        out.setdefault("epsilon", 0)    # always-present but unpinned
        return out
