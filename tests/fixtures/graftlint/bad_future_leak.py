"""graftlint fixture: leaked / exception-swallowed Futures (seeded
bad) next to clean controls."""
import concurrent.futures as cf


def leaky_branch(cond):
    fut = cf.Future()
    if cond:
        fut.set_result(1)
    # fall-through with `fut` possibly pending, never handed off


def leaky_return(cond):
    fut = cf.Future()
    if cond:
        return fut
    return None          # pending future dropped on this path


def swallowed(registry, work):
    fut = cf.Future()
    try:
        fut.set_result(work())
    except ValueError:
        pass             # swallowed while `fut` may be pending...
    registry.append(fut)  # ...and it still escapes to a waiter


def clean_resolved(cond):
    fut = cf.Future()
    if cond:
        fut.set_result(1)
    else:
        fut.set_exception(RuntimeError("no"))
    return fut


def clean_escapes(sink):
    fut = cf.Future()
    sink.append(fut)     # ownership transferred at birth
    return fut


def clean_raise_path(work):
    fut = cf.Future()
    value = work()       # a raise here exits WITHOUT stranding anyone
    fut.set_result(value)
    return fut
