"""graftlint fixture: the pin tuple, with one key no source
registers ('ghost_key' — the seeded unregistered-pin drift)."""


class TestPins:
    PINNED_KEYS = ("alpha_total", "beta_total", "gamma_last", "delta",
                   "ghost_key")
