"""Generates tests/fixtures/keras_toy_residual.h5 + expected outputs.

Run with a real Keras installation (any version with legacy HDF5 save):

    python tests/fixtures/make_keras_fixture.py

The committed fixture is the ground truth the import tests assert against
(reference pattern: dl4j-test-resources ships real-Keras h5 files; the tests
in deeplearning4j-modelimport load them — KerasModelImport.java:135).
"""
import os

import numpy as np


def main():
    import keras
    from keras import layers

    here = os.path.dirname(os.path.abspath(__file__))
    rng = np.random.default_rng(42)

    inp = keras.Input(shape=(8, 8, 3), name="input_1")
    x = layers.Conv2D(4, (3, 3), padding="same", activation="relu",
                      name="conv_a")(inp)
    x = layers.BatchNormalization(name="bn_a")(x)
    y = layers.Conv2D(4, (1, 1), padding="same", name="conv_sc")(inp)
    z = layers.Add(name="add_1")([x, y])
    z = layers.Activation("relu", name="act_1")(z)
    z = layers.MaxPooling2D((2, 2), name="pool_1")(z)
    w = layers.Conv2D(3, (3, 3), padding="same", activation="tanh",
                      name="conv_b")(z)
    m2 = layers.Concatenate(name="cat_1")([z, w])
    f = layers.Flatten(name="flat_1")(m2)
    out = layers.Dense(10, activation="softmax", name="dense_out")(f)
    model = keras.Model(inp, out, name="toy_residual")

    # non-trivial BN running stats so inference uses them
    bn = model.get_layer("bn_a")
    mean = rng.normal(0, 0.3, (4,)).astype(np.float32)
    var = (0.5 + rng.random(4)).astype(np.float32)
    gamma = (0.8 + 0.4 * rng.random(4)).astype(np.float32)
    beta = rng.normal(0, 0.2, (4,)).astype(np.float32)
    bn.set_weights([gamma, beta, mean, var])

    xin = rng.standard_normal((5, 8, 8, 3)).astype(np.float32)
    yout = model.predict(xin, verbose=0)

    model.save(os.path.join(here, "keras_toy_residual.h5"))
    np.savez(os.path.join(here, "keras_toy_residual_io.npz"),
             x=xin, y=yout)
    print("wrote fixture; output shape", yout.shape)


if __name__ == "__main__":
    main()
