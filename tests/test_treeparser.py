"""Sentence -> parse-tree pipeline (reference deeplearning4j-nlp-uima
text/corpora/treeparser/: TreeParser, BinarizeTreeTransformer,
CollapseUnaries, HeadWordFinder, TreeVectorizer, TreeIterator)."""
import pytest

from deeplearning4j_tpu.text.sentence_iterator import \
    LabelAwareListSentenceIterator
from deeplearning4j_tpu.text.treeparser import (BinarizeTreeTransformer,
                                                CollapseUnaries,
                                                HeadWordFinder, Tree,
                                                TreeIterator, TreeParser,
                                                TreeVectorizer)


class TestTreeParser:
    def test_one_tree_per_sentence_with_spans(self):
        trees = TreeParser().get_trees("The quick cat sat on the mat. "
                                       "He was happy.")
        assert len(trees) == 2
        assert all(t.label == "S" for t in trees)
        # leaves reproduce the sentence tokens in order, spans increase
        words = trees[0].yield_words()
        assert words[0] == "The" and "cat" in words
        leaves = trees[0].leaves()
        assert all(leaves[i].begin < leaves[i + 1].begin
                   for i in range(len(leaves) - 1))

    def test_chunk_structure(self):
        """DT JJ NN sequences group into NPs; verbs into a VP; IN+NP
        into a PP — the shallow-parse contract."""
        (tree,) = TreeParser().get_trees("The big dog chased a small cat")
        labels = [c.label for c in tree.children]
        assert labels == ["NP", "VP", "NP"]
        assert tree.children[0].yield_words() == ["The", "big", "dog"]
        assert tree.children[1].children[0].label == "VBD"

    def test_pp_attachment(self):
        (tree,) = TreeParser().get_trees("He sat on the mat")
        pp = [c for c in tree.children if c.label == "PP"]
        assert len(pp) == 1
        assert pp[0].children[0].label == "IN"
        assert pp[0].children[1].label == "NP"

    def test_trees_with_labels_attach_tags(self):
        trees = TreeParser().get_trees_with_labels(
            "The cat sat.", ["pos", "neg"])
        for node in trees[0]:
            assert node.tags == ["POS", "NEG"]


class TestTransformers:
    def _nary(self):
        kids = [Tree("NN", value=w, begin=i, end=i + 1)
                for i, w in enumerate("a b c d".split())]
        return Tree("NP", kids, begin=0, end=4)

    def test_binarize_caps_fanout_and_preserves_yield(self):
        t = BinarizeTreeTransformer().transform(self._nary())
        assert t.yield_words() == ["a", "b", "c", "d"]
        for node in t:
            assert len(node.children) <= 2
        assert t.children[0].label == "@NP"

    def test_collapse_unaries(self):
        inner = Tree("NP", [Tree("NN", value="cat", begin=0, end=3)])
        chain = Tree("S", [Tree("X", [inner])])
        out = CollapseUnaries().transform(chain)
        assert out.label == "S"
        assert out.children[0].label == "NN"
        # original untouched (clone semantics)
        assert chain.children[0].label == "X"

    def test_head_word_finder(self):
        (tree,) = TreeParser().get_trees("The big dog chased a small cat")
        assert HeadWordFinder().find_head(tree).value == "chased"
        np = tree.children[0]
        assert HeadWordFinder().find_head(np).value == "dog"

    def test_head_pp_modes(self):
        (tree,) = TreeParser().get_trees("He sat on the mat")
        pp = [c for c in tree.children if c.label == "PP"][0]
        assert HeadWordFinder().find_head(pp).value == "on"
        assert HeadWordFinder(include_pp_head=True).find_head(
            pp).value == "mat"


class TestVectorizerAndIterator:
    def test_vectorizer_binarizes_and_labels(self):
        trees = TreeVectorizer().get_trees_with_labels(
            "The big dog chased a small cat in the garden", label="pos")
        t = trees[0]
        assert t.gold_label == "pos"
        for node in t:
            assert len(node.children) <= 2
        assert "POS" in t.tags

    def test_tree_iterator_batches_with_labels(self):
        it = LabelAwareListSentenceIterator(
            ["The cat sat", "The dog ran", "He was happy"],
            ["a", "b", "c"])
        ti = TreeIterator(it, labels=["a", "b", "c"], batch_size=2)
        batch = ti.next()
        assert len(batch) >= 2
        assert batch[0].gold_label == "a"
        ti.reset()
        assert ti.has_next()
        total = []
        while ti.has_next():
            total.extend(ti.next())
        assert len(total) == 3
