"""CNN layer tests: shapes, LeNet wiring, gradient checks.

Mirrors reference test suites CNNGradientCheckTest / BNGradientCheckTest /
LRNGradientCheckTests / ConvolutionLayerTest (SURVEY.md §4.1-4.2).
"""
import numpy as np
import pytest

from deeplearning4j_tpu import (InputType, MultiLayerNetwork,
                                NeuralNetConfiguration)
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.gradientcheck.gradient_check_util import check_gradients
from deeplearning4j_tpu.nn.conf.layers import (BatchNormalization,
                                               ConvolutionLayer, DenseLayer,
                                               GlobalPoolingLayer,
                                               LocalResponseNormalization,
                                               OutputLayer, SubsamplingLayer)


def small_cnn_conf(extra=None, h=8, w=8, c=2, n_classes=3, data_type="float64"):
    layers = [
        ConvolutionLayer(n_out=3, kernel_size=(3, 3), stride=(1, 1),
                         activation="tanh"),
    ]
    if extra:
        layers.extend(extra)
    layers.append(OutputLayer(n_out=n_classes, activation="softmax",
                              loss_function="mcxent"))
    b = (NeuralNetConfiguration.Builder().seed(12345).data_type(data_type)
         .learning_rate(0.1).weight_init("xavier").list())
    for i, l in enumerate(layers):
        b.layer(i, l)
    return b.set_input_type(InputType.convolutional(h, w, c)).build()


def rand_data(n=6, h=8, w=8, c=2, n_classes=3, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(0, 1, (n, h, w, c)).astype(np.float64)
    y = np.eye(n_classes, dtype=np.float64)[rng.integers(0, n_classes, n)]
    return x, y


class TestShapes:
    def test_conv_output_shape_valid(self):
        conf = small_cnn_conf()
        # conv 3x3 valid: 8->6
        it = conf.layers[0].get_output_type(InputType.convolutional(8, 8, 2))
        assert (it.height, it.width, it.channels) == (6, 6, 3)

    def test_conv_same_mode(self):
        layer = ConvolutionLayer(n_in=2, n_out=4, kernel_size=(3, 3),
                                 stride=(1, 1), convolution_mode="same")
        it = layer.get_output_type(InputType.convolutional(8, 8, 2))
        assert (it.height, it.width, it.channels) == (8, 8, 4)

    def test_subsampling_shape(self):
        layer = SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2))
        it = layer.get_output_type(InputType.convolutional(8, 8, 5))
        assert (it.height, it.width, it.channels) == (4, 4, 5)

    def test_lenet_wiring(self):
        from deeplearning4j_tpu.models.zoo.lenet import lenet_conf
        conf = lenet_conf()
        # conv(5x5): 28->24; pool: 12; conv(5x5): 8; pool: 4 -> 4*4*50=800
        assert conf.layers[4].n_in == 800
        assert conf.layers[5].n_in == 500

    def test_lenet_forward(self):
        from deeplearning4j_tpu.models.zoo.lenet import lenet
        net = lenet()
        x = np.random.default_rng(0).random((4, 784)).astype(np.float32)
        out = np.asarray(net.output(x))
        assert out.shape == (4, 10)
        np.testing.assert_allclose(out.sum(axis=1), 1.0, atol=1e-4)

    def test_lenet_param_count(self):
        from deeplearning4j_tpu.models.zoo.lenet import lenet
        net = lenet()
        # conv1 5*5*1*20+20=520; conv2 5*5*20*50+50=25050;
        # dense 800*500+500=400500; out 500*10+10=5010
        assert net.num_params() == 520 + 25050 + 400500 + 5010


class TestCnnTraining:
    def test_cnn_fit_reduces_score(self):
        x, y = rand_data(n=32)
        conf = small_cnn_conf(
            extra=[SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2))],
            data_type="float32")
        net = MultiLayerNetwork(conf).init()
        ds = DataSet(x.astype(np.float32), y.astype(np.float32))
        s0 = net.score(ds)
        for _ in range(30):
            net.fit(ds)
        assert net.score(ds) < s0 * 0.7


class TestCnnGradients:
    def test_gradcheck_conv(self):
        x, y = rand_data()
        net = MultiLayerNetwork(small_cnn_conf()).init()
        assert check_gradients(net, x, y, max_rel_error=1e-4, subset=60)

    def test_gradcheck_conv_pool(self):
        x, y = rand_data()
        for pool in ("max", "avg", "sum"):
            conf = small_cnn_conf(
                extra=[SubsamplingLayer(pooling_type=pool, kernel_size=(2, 2),
                                        stride=(2, 2))])
            net = MultiLayerNetwork(conf).init()
            assert check_gradients(net, x, y, max_rel_error=1e-4, subset=50), pool

    def test_gradcheck_conv_bn(self):
        x, y = rand_data()
        conf = small_cnn_conf(extra=[BatchNormalization()])
        net = MultiLayerNetwork(conf).init()
        assert check_gradients(net, x, y, max_rel_error=1e-4, subset=50)

    def test_gradcheck_conv_lrn(self):
        x, y = rand_data()
        conf = small_cnn_conf(extra=[LocalResponseNormalization()])
        net = MultiLayerNetwork(conf).init()
        assert check_gradients(net, x, y, max_rel_error=1e-4, subset=50)

    def test_gradcheck_global_pooling(self):
        x, y = rand_data()
        conf = small_cnn_conf(extra=[GlobalPoolingLayer(pooling_type="avg")])
        net = MultiLayerNetwork(conf).init()
        assert check_gradients(net, x, y, max_rel_error=1e-4, subset=40)


class TestBatchNormSemantics:
    def test_running_stats_update_and_inference(self):
        conf = small_cnn_conf(extra=[BatchNormalization(decay=0.5)],
                              data_type="float32")
        net = MultiLayerNetwork(conf).init()
        x, y = rand_data(n=16)
        ds = DataSet(x.astype(np.float32), y.astype(np.float32))
        st0 = np.asarray(net._model_state[1]["mean"]).copy()
        net.fit(ds)
        st1 = np.asarray(net._model_state[1]["mean"])
        assert not np.allclose(st0, st1), "BN running mean should update in training"
        # inference twice -> deterministic (uses running stats, not batch stats)
        o1 = np.asarray(net.output(x.astype(np.float32)))
        o2 = np.asarray(net.output(x.astype(np.float32)))
        np.testing.assert_allclose(o1, o2, rtol=1e-6)
