"""VAE + RBM + layerwise pretraining. Mirrors reference VaeGradientCheckTests
pattern (gradient-check the ELBO), RBM CD behavior, pretrain path."""
import numpy as np
import pytest

jax = __import__("jax")
jnp = jax.numpy

from deeplearning4j_tpu import (InputType, MultiLayerNetwork,
                                NeuralNetConfiguration)
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterators import ListDataSetIterator
from deeplearning4j_tpu.nn.conf.layers import (RBM, DenseLayer, OutputLayer,
                                               VariationalAutoencoder)
from deeplearning4j_tpu.nn.conf.layers.variational import \
    BernoulliReconstructionDistribution


def _x(n=16, d=8, seed=0, binary=False):
    r = np.random.default_rng(seed)
    x = r.random((n, d)).astype(np.float64)
    return (x > 0.5).astype(np.float64) if binary else x


class TestVAE:
    def _vae(self, dist=None, **kw):
        return VariationalAutoencoder(
            n_in=8, n_out=3, encoder_layer_sizes=(12,),
            decoder_layer_sizes=(12,), activation="tanh",
            reconstruction_distribution=dist, **kw
        ).apply_global_defaults({"weight_init": "xavier"})

    @pytest.mark.slow
    def test_elbo_gradcheck_gaussian(self):
        """Numerical-vs-analytic gradients of the negative ELBO (the
        reference's VaeGradientCheckTests approach)."""
        vae = self._vae()
        params = vae.init_params(jax.random.PRNGKey(0), jnp.float64)
        x = jnp.asarray(_x())
        rng = jax.random.PRNGKey(3)

        leaves, treedef = jax.tree_util.tree_flatten(params)
        flat = np.concatenate([np.asarray(l).ravel() for l in leaves])

        def unflatten(v):
            out, off = [], 0
            for l in leaves:
                n = l.size
                out.append(jnp.asarray(v[off:off + n]).reshape(l.shape))
                off += n
            return jax.tree_util.tree_unflatten(treedef, out)

        loss = jax.jit(lambda v: vae.pretrain_loss(unflatten(v), x, rng=rng))
        g = np.asarray(jax.jit(jax.grad(
            lambda v: vae.pretrain_loss(unflatten(v), x, rng=rng)))(
                jnp.asarray(flat)))
        rs = np.random.default_rng(1)
        idx = rs.choice(flat.size, 40, replace=False)
        eps = 1e-6
        for i in idx:
            v = flat.copy()
            v[i] += eps
            sp = float(loss(jnp.asarray(v)))
            v[i] -= 2 * eps
            sm = float(loss(jnp.asarray(v)))
            num = (sp - sm) / (2 * eps)
            denom = abs(g[i]) + abs(num)
            assert denom == 0 or abs(g[i] - num) / denom < 1e-4, \
                (i, g[i], num)

    @pytest.mark.slow
    def test_pretrain_reduces_elbo_and_recon_prob_orders(self):
        conf = (NeuralNetConfiguration.Builder().seed(5)
                .updater("adam").learning_rate(5e-3).list()
                .layer(0, VariationalAutoencoder(
                    n_out=4, encoder_layer_sizes=(16,),
                    decoder_layer_sizes=(16,), activation="tanh"))
                .set_input_type(InputType.feed_forward(8))
                .build())
        net = MultiLayerNetwork(conf).init()
        ds = DataSet(_x(64, seed=2).astype(np.float32),
                     np.zeros((64, 1), np.float32))
        vae = net.layers[0]
        p0 = {k: v for k, v in net._params[0].items()}
        l0 = float(vae.pretrain_loss(p0, jnp.asarray(ds.features)))
        net.pretrain_layer(0, ListDataSetIterator([ds]), num_epochs=60)
        p1 = net._params[0]
        l1 = float(vae.pretrain_loss(p1, jnp.asarray(ds.features)))
        assert l1 < l0
        # reconstruction probability: trained data scores higher than noise
        logp_data = np.asarray(vae.reconstruction_probability(
            p1, jnp.asarray(ds.features), num_samples=8))
        noise = np.random.default_rng(9).random((64, 8)) * 10 - 5
        logp_noise = np.asarray(vae.reconstruction_probability(
            p1, jnp.asarray(noise.astype(np.float32)), num_samples=8))
        assert logp_data.mean() > logp_noise.mean()

    def test_forward_is_latent_mean_and_supervised_stack(self):
        conf = (NeuralNetConfiguration.Builder().seed(1)
                .updater("adam").learning_rate(1e-2).list()
                .layer(0, VariationalAutoencoder(
                    n_out=4, encoder_layer_sizes=(8,),
                    decoder_layer_sizes=(8,), activation="tanh"))
                .layer(1, OutputLayer(n_out=2, activation="softmax",
                                      loss_function="mcxent"))
                .set_input_type(InputType.feed_forward(8))
                .build())
        net = MultiLayerNetwork(conf).init()
        x = _x(8).astype(np.float32)
        out = np.asarray(net.output(x))
        assert out.shape == (8, 2)
        y = np.eye(2, dtype=np.float32)[np.random.default_rng(0).integers(0, 2, 8)]
        net.fit(DataSet(x, y))   # supervised fine-tune path works
        assert np.isfinite(net.score())

    def test_bernoulli_distribution_and_generate(self):
        vae = self._vae(dist={"type": "bernoulli"})
        assert isinstance(vae._dist(), BernoulliReconstructionDistribution)
        params = vae.init_params(jax.random.PRNGKey(0), jnp.float32)
        x = jnp.asarray(_x(binary=True).astype(np.float32))
        loss = float(vae.pretrain_loss(params, x, rng=jax.random.PRNGKey(1)))
        assert np.isfinite(loss)
        z = jnp.zeros((4, 3), jnp.float32)
        recon = np.asarray(vae.generate_at_mean_given_z(params, z))
        assert recon.shape == (4, 8)
        assert (recon >= 0).all() and (recon <= 1).all()


class TestRBM:
    def test_cd_pretraining_reduces_reconstruction_error(self):
        conf = (NeuralNetConfiguration.Builder().seed(3)
                .updater("sgd").learning_rate(0.1).list()
                .layer(0, RBM(n_out=12))
                .set_input_type(InputType.feed_forward(8))
                .build())
        net = MultiLayerNetwork(conf).init()
        x = _x(32, binary=True, seed=4).astype(np.float32)
        ds = DataSet(x, np.zeros((32, 1), np.float32))
        rbm = net.layers[0]
        e0 = float(rbm.pretrain_loss(net._params[0], jnp.asarray(x)))
        net.pretrain_layer(0, ListDataSetIterator([ds]), num_epochs=80)
        e1 = float(rbm.pretrain_loss(net._params[0], jnp.asarray(x)))
        assert e1 < e0

    def test_propup_forward_shape(self):
        rbm = RBM(n_in=8, n_out=5).apply_global_defaults({})
        params = rbm.init_params(jax.random.PRNGKey(0), jnp.float32)
        out = np.asarray(rbm.forward(params, jnp.asarray(
            _x(4).astype(np.float32))))
        assert out.shape == (4, 5)
        assert (out >= 0).all() and (out <= 1).all()  # binary units

    @pytest.mark.slow
    def test_stacked_pretrain_then_finetune(self):
        """DBN-style: RBM + RBM + softmax, greedy pretrain then backprop."""
        conf = (NeuralNetConfiguration.Builder().seed(7)
                .updater("sgd").learning_rate(0.05).list()
                .layer(0, RBM(n_out=16))
                .layer(1, RBM(n_out=8))
                .layer(2, OutputLayer(n_out=3, activation="softmax",
                                      loss_function="mcxent"))
                .set_input_type(InputType.feed_forward(8))
                .build())
        net = MultiLayerNetwork(conf).init()
        r = np.random.default_rng(0)
        x = _x(48, binary=True, seed=5).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[r.integers(0, 3, 48)]
        ds = DataSet(x, y)
        net.pretrain(ListDataSetIterator([ds]), num_epochs=10)
        s0 = net.score(ds)
        for _ in range(20):
            net.fit(ds)
        assert net.score(ds) < s0
