"""Serving layer pins (ISSUE 4 acceptance criteria).

  (a) Determinism: a request's result is BIT-IDENTICAL whether it is
      served alone, co-batched with strangers, or bucket-padded — and
      matches the raw container forward on the same rows. (The bucket
      floor of 2 exists because XLA:CPU's M=1 gemv path accumulates in a
      different order than gemm; serving never dispatches M=1.)
  (b) Compile cache: a mixed-size request stream compiles at most
      len(buckets) programs per input structure — the set is pinned, not
      an LRU that churns under traffic.
  (c) Continuous decode: a request that JOINS a running fixed-slot batch
      emits the same token stream as a solo decode, and equal-arrival
      continuous decode matches `generate_batch` bit-for-bit.
  (d) Hot swap completes under concurrent load with zero dropped or
      failed in-flight requests, on both the micro-batch and the
      dual-version continuous-decode paths.
  (e) FaultInjector-driven deadline/shed/retry/screening paths through
      the REAL serving code (sites serve.request / serve.batch /
      serve.swap), and serving metrics ride the existing UI storage path.
"""
import threading
import time

import numpy as np
import pytest

from deeplearning4j_tpu import (ComputationGraph, InputType,
                                MultiLayerNetwork, NeuralNetConfiguration)
from deeplearning4j_tpu.common.resilience import (FaultInjected,
                                                  FaultInjector,
                                                  RetryPolicy)
from deeplearning4j_tpu.models.zoo.transformer import TransformerLM
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.serving import (ContinuousDecodeServer,
                                        DeadlineExceededError,
                                        InferenceServer, ServingMetrics,
                                        ServerOverloadedError,
                                        UnhealthyOutputError)


def _mln(seed=7, n_in=6, n_out=4):
    conf = (NeuralNetConfiguration.Builder().seed(seed)
            .updater("adam").learning_rate(0.01).list()
            .layer(0, DenseLayer(n_out=16, activation="relu"))
            .layer(1, OutputLayer(n_out=n_out, activation="softmax",
                                  loss_function="mcxent"))
            .set_input_type(InputType.feed_forward(n_in))
            .build())
    return MultiLayerNetwork(conf).init()


def _cg(seed=7):
    conf = (NeuralNetConfiguration.Builder().seed(seed)
            .updater("sgd").learning_rate(0.1).graph_builder()
            .add_inputs("in")
            .add_layer("d", DenseLayer(n_out=8, activation="tanh"), "in")
            .add_layer("out", OutputLayer(n_out=3, activation="softmax",
                                          loss_function="mcxent"), "d")
            .set_outputs("out")
            .set_input_types(InputType.feed_forward(5))
            .build())
    return ComputationGraph(conf).init()


def _lm(seed=3):
    return TransformerLM(64, d_model=32, n_heads=2, n_layers=2,
                         max_len=64, seed=seed)


def _bits_equal(a, b):
    a, b = np.asarray(a), np.asarray(b)
    return a.dtype == b.dtype and a.shape == b.shape and \
        np.array_equal(a.view(np.uint8), b.view(np.uint8))


# ---------------------------------------------------------------------------
# (a) determinism pins
# ---------------------------------------------------------------------------
class TestMicroBatchDeterminism:
    def test_cobatched_bit_identical_to_batch1(self):
        """The SAME request served solo and co-batched with 7 strangers
        returns bit-identical results."""
        net = _mln()
        rng = np.random.default_rng(0)
        xs = rng.standard_normal((8, 6)).astype(np.float32)
        with InferenceServer(net, max_batch=8, max_wait_ms=20.0) as srv:
            futs = [srv.submit(x) for x in xs]       # coalesce into one batch
            batched = [f.result(30) for f in futs]
            solo = srv.predict(xs[0], timeout=30)    # batch-1 call
        assert _bits_equal(solo, batched[0])

    def test_bucket_padded_bit_identical_to_unpadded(self):
        """3 requests pad to bucket 4; rows must match the raw unpadded
        batch-3 forward bit-for-bit (and the batch-16 one)."""
        net = _mln()
        rng = np.random.default_rng(1)
        xs = rng.standard_normal((16, 6)).astype(np.float32)
        with InferenceServer(net, max_batch=4, max_wait_ms=20.0,
                             buckets=(2, 4)) as srv:
            futs = [srv.submit(x) for x in xs[:3]]
            rows = [np.asarray(f.result(30)) for f in futs]
        direct3 = np.asarray(net.output(xs[:3]))
        direct16 = np.asarray(net.output(xs))
        for i in range(3):
            assert _bits_equal(rows[i], direct3[i])
            assert _bits_equal(rows[i], direct16[i])

    def test_computation_graph_served(self):
        """The CG twin serves through the same machinery (multi-output
        list results)."""
        cg = _cg()
        rng = np.random.default_rng(2)
        xs = rng.standard_normal((4, 5)).astype(np.float32)
        with InferenceServer(cg, max_batch=4, max_wait_ms=20.0) as srv:
            futs = [srv.submit(x) for x in xs]
            rows = [f.result(30) for f in futs]
        direct = np.asarray(cg.output(xs)[0])
        for i in range(4):
            assert isinstance(rows[i], list) and len(rows[i]) == 1
            assert _bits_equal(rows[i][0], direct[i])


# ---------------------------------------------------------------------------
# (b) compile-cache pin
# ---------------------------------------------------------------------------
class TestCompileCache:
    def test_mixed_sizes_compile_at_most_num_buckets(self):
        net = _mln()
        rng = np.random.default_rng(3)
        xs = rng.standard_normal((64, 6)).astype(np.float32)
        with InferenceServer(net, max_batch=8, max_wait_ms=1.0,
                             buckets=(2, 4, 8), max_queue=128) as srv:
            futs = []
            # mixed arrival pattern: bursts of 1..8 with pauses, so the
            # batcher forms micro-batches of many different real sizes
            i = 0
            for burst in (1, 3, 8, 2, 5, 7, 4, 6, 1, 8, 3, 2):
                for _ in range(burst):
                    futs.append(srv.submit(xs[i % 64]))
                    i += 1
                time.sleep(0.01)
            rows = [np.asarray(f.result(30)) for f in futs]
        assert len(srv.compiled_programs) <= 3
        direct = np.asarray(net.output(xs[:len(rows)]))
        for i, r in enumerate(rows):
            assert _bits_equal(r, direct[i % 64])

    def test_heterogeneous_structures_partition_not_fail(self):
        """Requests with DIFFERENT input widths landing in one coalescing
        window are partitioned by structure, not crashed together: each
        width gets its own dispatch and correct results."""
        net4 = _mln(7, n_in=6)
        rng = np.random.default_rng(18)
        xa = rng.standard_normal((3, 6)).astype(np.float32)
        xb = rng.standard_normal((3, 6)).astype(np.float64)  # other dtype
        with InferenceServer(net4, max_batch=8, max_wait_ms=30.0) as srv:
            futs = [srv.submit(x) for x in xa] + [srv.submit(x) for x in xb]
            rows = [np.asarray(f.result(30)) for f in futs]
        da = np.asarray(net4.output(xa))
        db = np.asarray(net4.output(xb))
        for i in range(3):
            assert _bits_equal(rows[i], da[i])
            # f64 requests are a separate program (separate struct key);
            # value-compare against the container run on the f64 batch
            np.testing.assert_array_equal(rows[3 + i], db[i])
        assert srv.metrics.snapshot().get("failed", 0) == 0


# ---------------------------------------------------------------------------
# (c) continuous decode
# ---------------------------------------------------------------------------
class TestContinuousDecode:
    def test_join_running_batch_equals_solo(self):
        """A request joining a batch mid-decode emits the same tokens as
        the same request decoding alone."""
        lm = _lm()
        rng = np.random.default_rng(4)
        pa = rng.integers(1, 64, 5).tolist()
        pb = rng.integers(1, 64, 8).tolist()
        pc = rng.integers(1, 64, 3).tolist()
        with ContinuousDecodeServer(lm, slots=4,
                                    prompt_buckets=(4, 8)) as srv:
            solo = srv.generate(pa, 10, timeout=60)
            flong = srv.submit(pb, 30)       # running batch
            time.sleep(0.05)                 # let pb decode a few tokens
            fa = srv.submit(pa, 10)          # joins mid-flight
            fc = srv.submit(pc, 6)
            joined = fa.result(60)
            flong.result(60)
            fc.result(60)
        assert joined == solo

    def test_equal_arrival_matches_generate_batch(self):
        """4 equal-length requests admitted together == generate_batch
        greedy rows, token-for-token."""
        lm = _lm()
        rng = np.random.default_rng(5)
        prompts = rng.integers(1, 64, (4, 4)).astype(np.int32)
        expect = lm.generate_batch(prompts, max_new_tokens=8)
        with ContinuousDecodeServer(lm, slots=4,
                                    prompt_buckets=(4,)) as srv:
            futs = [srv.submit(prompts[i], 8) for i in range(4)]
            rows = [f.result(60) for f in futs]
        for i in range(4):
            assert rows[i] == expect[i].tolist()

    def test_matches_generate_use_cache(self):
        """The serving path agrees with the pinned single-request
        generate(use_cache=True) reference."""
        lm = _lm()
        rng = np.random.default_rng(6)
        p = rng.integers(1, 64, 4).tolist()
        expect = lm.generate(p, max_new_tokens=9)
        with ContinuousDecodeServer(lm, slots=2,
                                    prompt_buckets=(4,)) as srv:
            got = srv.generate(p, 9, timeout=60)
        assert got == expect

    def test_one_token_request_resolves_at_prefill(self):
        lm = _lm()
        p = [5, 9, 2]
        expect = lm.generate(p, max_new_tokens=1)
        with ContinuousDecodeServer(lm, slots=2,
                                    prompt_buckets=(4,)) as srv:
            got = srv.generate(p, 1, timeout=60)
            with pytest.raises(ValueError, match="max_new_tokens"):
                srv.submit(p, 0)
        assert got == expect

    def test_prefill_compile_cache_bounded(self):
        lm = _lm()
        rng = np.random.default_rng(7)
        with ContinuousDecodeServer(lm, slots=2,
                                    prompt_buckets=(4, 8)) as srv:
            for n in (2, 3, 4, 5, 7, 8, 6, 1):
                srv.generate(rng.integers(1, 64, n).tolist(), 2,
                             timeout=60)
            assert len(srv.prefill_programs) <= 2


# ---------------------------------------------------------------------------
# (d) hot swap under load
# ---------------------------------------------------------------------------
class TestHotSwap:
    def test_microbatch_swap_zero_dropped(self):
        """Concurrent clients submit across a swap; every future resolves
        (zero dropped/failed), and post-swap results match the new net."""
        net1, net2 = _mln(7), _mln(99)
        rng = np.random.default_rng(8)
        xs = rng.standard_normal((32, 6)).astype(np.float32)
        srv = InferenceServer(net1, max_batch=4, max_wait_ms=1.0,
                              max_queue=512).start()
        futs = []

        def client():
            for i in range(150):
                futs.append(srv.submit(xs[i % 32]))
                time.sleep(0.0004)

        t = threading.Thread(target=client)
        t.start()
        time.sleep(0.02)
        srv.swap(net2)
        t.join()
        results = [f.result(60) for f in futs]   # raises on any failure
        assert len(results) == 150
        assert srv.metrics.snapshot().get("failed", 0) == 0
        after = srv.predict(xs[0], timeout=30)
        srv.stop()
        assert _bits_equal(after, np.asarray(net2.output(xs[:2]))[0])

    def test_swap_rejects_architecture_mismatch(self):
        net1 = _mln(7)
        other = _mln(7, n_in=6, n_out=7)      # different output width
        srv = InferenceServer(net1).start()
        try:
            with pytest.raises(ValueError, match="swap rejected"):
                srv.swap(other)
        finally:
            srv.stop()

    def test_swap_from_serializer_path(self, tmp_path):
        from deeplearning4j_tpu.util import model_serializer
        net1, net2 = _mln(7), _mln(99)
        path = str(tmp_path / "model.zip")
        model_serializer.write_model(net2, path)
        rng = np.random.default_rng(9)
        x = rng.standard_normal((6,)).astype(np.float32)
        with InferenceServer(net1, max_wait_ms=1.0) as srv:
            srv.swap_from_path(path)
            got = srv.predict(x, timeout=30)
        assert srv.metrics.snapshot().get("swaps") == 1
        assert _bits_equal(got, np.asarray(
            net2.output(np.stack([x, x])))[0])

    def test_decode_dual_version_drain(self):
        """In-flight decode requests finish on pre-swap params (token
        streams identical to a pre-swap solo run) while a post-swap
        request gets the new params — dual-version dispatch."""
        lm1, lm2 = _lm(3), _lm(11)
        rng = np.random.default_rng(10)
        pa = rng.integers(1, 64, 4).tolist()
        pb = rng.integers(1, 64, 4).tolist()
        with ContinuousDecodeServer(lm1, slots=2,
                                    prompt_buckets=(4,)) as srv:
            solo_old = srv.generate(pa, 14, timeout=60)
            fa = srv.submit(pa, 14)
            time.sleep(0.03)                  # pa decoding on v0
            srv.swap(lm2)
            fb = srv.submit(pb, 5)            # admitted on v1
            ra, rb = fa.result(60), fb.result(60)
        assert ra == solo_old                 # drained on old params
        expect_new = lm2.generate_batch(np.asarray([pb], np.int32),
                                        max_new_tokens=5)
        assert rb == expect_new[0].tolist()   # routed to new params
        assert srv.metrics.snapshot().get("failed", 0) == 0


# ---------------------------------------------------------------------------
# (e) faults, deadlines, backpressure, screening, metrics/UI
# ---------------------------------------------------------------------------
class TestOperationalHardening:
    def test_retry_recovers_transient_batch_fault(self):
        net = _mln()
        inj = FaultInjector(seed=1).plan("serve.batch", on_call=0,
                                         exc=FaultInjected)
        rp = RetryPolicy(max_retries=3, base_delay=0.001,
                         retryable=(ConnectionError,))
        rng = np.random.default_rng(11)
        x = rng.standard_normal((6,)).astype(np.float32)
        with InferenceServer(net, max_wait_ms=1.0, fault_injector=inj,
                             retry_policy=rp) as srv:
            got = srv.predict(x, timeout=30)
        snap = srv.metrics.snapshot()
        assert snap.get("retries") == 1 and snap.get("failed", 0) == 0
        assert inj.fired("serve.batch")
        assert _bits_equal(got, np.asarray(net.output(np.stack([x, x])))[0])

    def test_unretryable_batch_fault_fails_requests_loudly(self):
        net = _mln()
        inj = FaultInjector(seed=2).plan("serve.batch", on_call=0,
                                         exc=FaultInjected)
        rng = np.random.default_rng(12)
        x = rng.standard_normal((6,)).astype(np.float32)
        with InferenceServer(net, max_wait_ms=1.0,
                             fault_injector=inj) as srv:   # no retry policy
            f = srv.submit(x)
            with pytest.raises(FaultInjected):
                f.result(30)
            # the server survives: next request serves fine
            assert srv.predict(x, timeout=30) is not None
        assert srv.metrics.snapshot().get("failed") == 1

    def test_deadline_shed_before_dispatch(self):
        net = _mln()
        rng = np.random.default_rng(13)
        x = rng.standard_normal((6,)).astype(np.float32)
        with InferenceServer(net, max_batch=2, max_wait_ms=50.0) as srv:
            f = srv.submit(x, deadline_ms=0.001)
            with pytest.raises(DeadlineExceededError):
                f.result(30)
        assert srv.metrics.snapshot().get("shed_deadline") == 1

    def test_queue_full_backpressure(self):
        net = _mln()
        rng = np.random.default_rng(14)
        xs = rng.standard_normal((32, 6)).astype(np.float32)
        srv = InferenceServer(net, max_batch=2, max_wait_ms=100.0,
                              max_queue=2).start()
        try:
            with pytest.raises(ServerOverloadedError):
                for i in range(16):
                    srv.submit(xs[i])
            assert srv.metrics.snapshot().get("shed_queue_full", 0) >= 1
        finally:
            srv.stop()

    def test_corrupt_request_screened_not_fatal(self):
        """A NaN-poisoned request (FaultInjector corrupt at serve.request)
        fails ONLY that request; co-batched neighbours are unaffected."""
        net = _mln()
        inj = FaultInjector(seed=3).plan("serve.request", on_call=0,
                                         corrupt="nan")
        rng = np.random.default_rng(15)
        xs = rng.standard_normal((3, 6)).astype(np.float32)
        with InferenceServer(net, max_batch=4, max_wait_ms=20.0,
                             fault_injector=inj,
                             screen_outputs=True) as srv:
            f_bad = srv.submit(xs[0])        # poisoned
            f_ok = [srv.submit(x) for x in xs[1:]]
            with pytest.raises(UnhealthyOutputError):
                f_bad.result(30)
            rows = [np.asarray(f.result(30)) for f in f_ok]
        assert srv.metrics.snapshot().get("unhealthy_outputs") == 1
        direct = np.asarray(net.output(xs))
        for i, r in enumerate(rows):
            assert _bits_equal(r, direct[i + 1])

    def test_decode_thread_survives_terminal_dispatch_fault(self):
        """A non-retryable fault during a decode iteration fails the
        occupied requests LOUDLY, resets the slot state, and keeps the
        server serving — no dead thread stranding future requests."""
        lm = _lm()
        inj = FaultInjector(seed=5).plan("serve.batch", on_call=1,
                                         exc=FaultInjected)  # 0 = prefill
        rng = np.random.default_rng(19)
        p = rng.integers(1, 64, 4).tolist()
        with ContinuousDecodeServer(lm, slots=2, prompt_buckets=(4,),
                                    fault_injector=inj) as srv:
            f = srv.submit(p, 6)
            with pytest.raises(FaultInjected):
                f.result(60)
            # the server recovers: same request serves fine afterwards
            got = srv.generate(p, 6, timeout=60)
        assert got == lm.generate(p, max_new_tokens=6)
        assert srv.metrics.snapshot().get("failed") == 1

    def test_decode_stop_no_drain_fails_queued_fast(self):
        """stop(drain=False) must FAIL queued requests, not admit them
        into slots freed by the draining ones."""
        lm = _lm()
        rng = np.random.default_rng(20)
        with ContinuousDecodeServer(lm, slots=1,
                                    prompt_buckets=(4,)) as srv:
            busy = srv.submit(rng.integers(1, 64, 4).tolist(), 24)
            time.sleep(0.02)          # occupies the only slot
            queued = [srv.submit(rng.integers(1, 64, 4).tolist(), 24)
                      for _ in range(3)]
            srv.stop(drain=False)
            assert busy.result(60)    # in-flight work still completes
            for f in queued:
                with pytest.raises(Exception):
                    f.result(60)      # queued work failed, not served

    def test_max_batch_one_keeps_bucket_floor(self):
        """max_batch=1 must still pad to bucket 2 — never an M=1 gemv
        dispatch (the determinism-pin floor)."""
        net = _mln()
        rng = np.random.default_rng(21)
        x = rng.standard_normal((6,)).astype(np.float32)
        with InferenceServer(net, max_batch=1, max_wait_ms=1.0) as srv:
            assert srv.buckets == (2,)
            got = srv.predict(x, timeout=30)
        assert _bits_equal(got, np.asarray(net.output(np.stack([x, x])))[0])

    def test_decode_deadline_evicted_mid_decode(self):
        """A request whose deadline expires WHILE it occupies a slot is
        evicted between iterations: its future fails with
        DeadlineExceededError, the shed is counted, and the slot frees
        the same iteration (a queued request takes it over immediately —
        the server never rides a dead request to max_new)."""
        lm = _lm()
        rng = np.random.default_rng(22)
        p = rng.integers(1, 64, 4).tolist()
        # delay-only faults slow every decode iteration deterministically
        # so the deadline reliably lands mid-decode, not at admission
        inj = FaultInjector(seed=6).plan(
            "serve.batch", on_calls=range(1, 60), times=60,
            delay=0.02, exc=None)
        with ContinuousDecodeServer(lm, slots=1, prompt_buckets=(4,),
                                    fault_injector=inj) as srv:
            doomed = srv.submit(p, 40, deadline_ms=100)
            queued = srv.submit(p, 4)        # waits for the only slot
            with pytest.raises(DeadlineExceededError,
                               match="mid-decode"):
                doomed.result(60)
            assert queued.result(60) == lm.generate(p, max_new_tokens=4)
        snap = srv.metrics.snapshot()
        assert snap.get("evicted_mid_decode") == 1
        assert snap.get("shed_deadline") == 1

    def test_decode_cancelled_future_expiring_keeps_thread_alive(self):
        """A caller-cancel()ed future whose deadline then expires must not
        kill the serve thread (set_exception on a cancelled future raises
        InvalidStateError): the slot is released silently and the server
        keeps serving."""
        lm = _lm()
        p = [3, 9, 11, 4]
        inj = FaultInjector(seed=7).plan(
            "serve.batch", on_calls=range(1, 60), times=60,
            delay=0.02, exc=None)
        with ContinuousDecodeServer(lm, slots=1, prompt_buckets=(4,),
                                    fault_injector=inj) as srv:
            f = srv.submit(p, 40, deadline_ms=150)
            time.sleep(0.05)
            assert f.cancel() or f.done()
            time.sleep(0.4)           # deadline passes on the dead future
            got = srv.generate(p, 4, timeout=60)
        assert got == lm.generate(p, max_new_tokens=4)

    def test_decode_deadline_shed_and_swap_site(self):
        lm = _lm()
        inj = FaultInjector(seed=4)
        rng = np.random.default_rng(16)
        p = rng.integers(1, 64, 4).tolist()
        with ContinuousDecodeServer(lm, slots=2, prompt_buckets=(4,),
                                    fault_injector=inj) as srv:
            f = srv.submit(p, 4, deadline_ms=0.0)
            with pytest.raises(DeadlineExceededError):
                f.result(60)
            srv.swap(_lm(12))
        assert srv.metrics.snapshot().get("shed_deadline") == 1
        assert inj.calls("serve.swap") == 1
        assert inj.calls("serve.request") == 1

    def test_serving_metrics_reach_ui_storage(self):
        """ServingStatsReporter rides the ui/storage.py path: the same
        InMemoryStatsStorage the training UI reads sees serving updates."""
        from deeplearning4j_tpu.ui.stats import ServingStatsReporter
        from deeplearning4j_tpu.ui.storage import InMemoryStatsStorage
        net = _mln()
        storage = InMemoryStatsStorage()
        rep = ServingStatsReporter(storage, session_id="serve_test",
                                   model_info={"model": "mln"})
        rng = np.random.default_rng(17)
        xs = rng.standard_normal((8, 6)).astype(np.float32)
        with InferenceServer(net, max_batch=4, max_wait_ms=1.0,
                             stats_reporter=rep, report_every=1) as srv:
            for x in xs:
                srv.predict(x, timeout=30)
        assert "serve_test" in storage.list_session_ids()
        latest = storage.get_latest_update("serve_test")
        serving = latest["serving"]
        assert serving["completed"] == 8
        assert serving["latency_ms_p50"] is not None
        assert serving["latency_ms_p99"] is not None
        assert 0.0 < serving["batch_occupancy_mean"] <= 1.0
        static = storage.get_static_info("serve_test")
        assert static["serving"]["model"] == "mln"

    def test_metrics_snapshot_shape(self):
        m = ServingMetrics(window=8)
        for i in range(20):
            m.record_request(float(i))
        m.record_batch(3, 4, 2)
        snap = m.snapshot()
        assert snap["completed"] == 20
        # bounded reservoir: percentiles over the LAST 8 samples
        assert snap["latency_ms_p50"] >= 12.0
        assert snap["queue_depth_max"] == 2
        assert snap["batch_occupancy_mean"] == 0.75
