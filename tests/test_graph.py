"""Graph module: adjacency graph, loaders, random walks, DeepWalk
embeddings. Mirrors reference deeplearning4j-graph tests (walk coverage,
DeepWalk similarity structure)."""
import numpy as np
import pytest

from deeplearning4j_tpu.graph import (DeepWalk, Graph, GraphLoader,
                                      RandomWalkIterator,
                                      WeightedRandomWalkIterator)


def _two_cliques(k=6):
    """Two k-cliques joined by a single bridge edge."""
    g = Graph(2 * k)
    for base in (0, k):
        for i in range(k):
            for j in range(i + 1, k):
                g.add_edge(base + i, base + j)
    g.add_edge(0, k)  # bridge
    return g


class TestGraph:
    def test_adjacency_and_degree(self):
        g = Graph(4)
        g.add_edge(0, 1)
        g.add_edge(1, 2, directed=True)
        assert set(g.get_connected_vertex_indices(0)) == {1}
        assert set(g.get_connected_vertex_indices(1)) == {0, 2}
        assert g.get_connected_vertex_indices(2) == []  # directed edge
        assert g.degree(1) == 2

    def test_edge_list_loader(self, tmp_path):
        p = tmp_path / "edges.csv"
        p.write_text("0,1\n1,2,2.5\n# comment\n2,3\n")
        g = GraphLoader.load_undirected_graph_edge_list_file(str(p), 4)
        assert g.degree(1) == 2
        assert g.get_edges_out(1)[1].weight == 2.5

    def test_adjacency_list_loader(self, tmp_path):
        p = tmp_path / "adj.txt"
        p.write_text("0,1,2\n1,0\n2\n")
        g = GraphLoader.load_adjacency_list_file(str(p))
        assert set(g.get_connected_vertex_indices(0)) == {1, 2}
        assert g.get_connected_vertex_indices(2) == []


class TestWalks:
    def test_walk_shape_and_coverage(self):
        g = _two_cliques()
        it = RandomWalkIterator(g, walk_length=8, seed=1)
        walks = list(it)
        assert len(walks) == g.num_vertices()
        assert all(len(w) == 8 for w in walks)
        # every walk starts at its vertex and follows edges
        for start, w in enumerate(walks):
            assert w[0] == start
            for a, b in zip(w, w[1:]):
                assert b in g.get_connected_vertex_indices(a) or a == b

    def test_disconnected_self_loop(self):
        g = Graph(2)   # no edges at all
        walks = list(RandomWalkIterator(g, walk_length=4))
        assert walks[0] == [0, 0, 0, 0]

    def test_weighted_walk_bias(self):
        g = Graph(3, allow_multiple_edges=False)
        g.add_edge(0, 1, weight=100.0, directed=True)
        g.add_edge(0, 2, weight=0.01, directed=True)
        it = WeightedRandomWalkIterator(g, walk_length=2, seed=3)
        firsts = []
        for _ in range(30):
            it.reset()
            firsts.append(it.next()[1])
        assert firsts.count(1) > 25   # heavy edge dominates


class TestDeepWalk:
    def test_clique_structure(self):
        g = _two_cliques()
        dw = (DeepWalk.Builder().vector_size(16).window_size(3)
              .learning_rate(0.05).seed(7).epochs(10).build())
        dw.fit(g, walk_length=10)
        # same-clique vertices more similar than cross-clique (non-bridge)
        intra = dw.similarity(1, 2)
        inter = dw.similarity(1, 8)
        assert intra > inter, (intra, inter)
        assert dw.get_vertex_vector(0).shape == (16,)

    def test_save_load_round_trip(self, tmp_path):
        g = _two_cliques()
        dw = (DeepWalk.Builder().vector_size(8).seed(7).epochs(3).build())
        dw.fit(g, walk_length=6)
        p = str(tmp_path / "gv.json")
        dw.save(p)
        dw2 = DeepWalk.load(p)
        assert np.allclose(dw2.get_vertex_vector(3), dw.get_vertex_vector(3))
        assert dw2.num_vertices == dw.num_vertices
