"""Serving wire-protocol pins (ISSUE 14 acceptance criteria).

  (a) Transport: a request submitted over the wire resolves to the
      exact stream the model produces in-process; request-level
      verdicts (shed, deadline, bad input) cross the wire AS THEIR
      REAL TYPES; the no-fault cross-process path adds ZERO device
      dispatches per token vs the same fleet in-process
      (dispatch-counter A/B).
  (b) At-most-once: a seeded drop-after-ACK plan on `serve.wire.submit`
      yields exactly ONE decoded stream and exactly one `wire_retries`
      increment (the PS transport dedup argument, regression-pinned
      for serving); a sever on `serve.wire.stream` drops the result
      mid-flight and reconnect re-DELIVERS without re-decoding.
  (c) Liveness: heartbeat-ack silence (a HUNG process — the main
      socket still answers) decays `alive` past `heartbeat_timeout`
      and the fleet router reaps the replica; its in-flight requests
      fail over with streams bit-identical to solo. Retry-exhausted
      wire death fails every pending future loudly with
      `ReplicaDeadError` — never a hang.
  (d) Migration: `scale_down` of a wire replica ships
      `RequestArtifact` BYTES between endpoints and the resumed
      stream is bit-identical to solo (the durable-KV pin exercised
      across a real socket); a destination that REFUSES the artifact
      (version tag mismatch) degrades to prompt replay
      (`migrate_refused` counted) — never a lost request.

Every wire endpoint here is a REAL TCP socket on loopback; the
2-process version of (a)-(d) runs as the tier-1 smoke
(`tools/load_sweep.py --fleet-procs`, tests/test_loadgen.py).
"""
import time

import pytest

from deeplearning4j_tpu.common.resilience import FaultInjector, RetryPolicy
from deeplearning4j_tpu.models.zoo.transformer import TransformerLM
from deeplearning4j_tpu.serving import (ContinuousDecodeServer,
                                        FleetManager, RemoteReplica,
                                        ReplicaServer, ReplicaDeadError,
                                        ServingMetrics)


def _lm(seed=3):
    return TransformerLM(64, d_model=16, n_heads=2, n_layers=1,
                         max_len=64, seed=seed)


class _WireFleet:
    """N in-thread ReplicaServers behind RemoteReplicas — a REAL
    loopback wire under every verb, without subprocess startup cost
    (the 2-process arm is the tier-1 smoke)."""

    def __init__(self, lm, injector=None, paged=False, **mgr_kw):
        self.wrappers = {}
        self._lm = lm
        self._paged = paged
        self._injector = injector
        self.mgr = FleetManager(self._factory, **mgr_kw)

    def _factory(self, name):
        srv = ContinuousDecodeServer(
            self._lm, slots=2, prompt_buckets=(8, 16),
            paged=self._paged, block_size=8,
            metrics=ServingMetrics(name=name), instance=name)
        rs = ReplicaServer(srv)
        self.wrappers[name] = rs
        return RemoteReplica("127.0.0.1", rs.port, name=name,
                             heartbeat_interval=0.05,
                             fault_injector=self._injector)

    def __enter__(self):
        self.mgr.start()
        for n in self.mgr.replicas:     # compile off the clock
            self.mgr.replica(n).generate([1, 2, 3], 2, timeout=120)
        return self.mgr

    def __exit__(self, *exc):
        self.mgr.stop(timeout=60)
        for rs in self.wrappers.values():
            rs.close(stop_server=False)

    def received_total(self):
        """Sum of the replicas' own `received` counters — the decoded-
        stream census the at-most-once pins count."""
        total = 0
        for name in self.mgr.replicas:
            snap = self.mgr.replica(name).metrics.kind_snapshot()
            total += (snap.get("received") or {}).get("value") or 0
        return total


# ---------------------------------------------------------------------------
# (a) transport
# ---------------------------------------------------------------------------
class TestWireTransport:
    def test_submit_over_wire_bit_identical_and_verdicts_propagate(self):
        from deeplearning4j_tpu.serving import (DeadlineExceededError,
                                                ServerOverloadedError)
        lm = _lm()
        ref = list(lm.generate([1, 2, 3], 8))
        srv = ContinuousDecodeServer(lm, slots=2, prompt_buckets=(8, 16),
                                     metrics=ServingMetrics(name="i0"),
                                     instance="i0", max_queue=2)
        rs = ReplicaServer(srv)
        rr = RemoteReplica("127.0.0.1", rs.port, name="i0",
                           heartbeat_interval=0.05)
        try:
            assert list(rr.generate([1, 2, 3], 8, timeout=120)) == ref
            # request-level verdicts cross the wire as their REAL types
            # (the fleet manager's classification table depends on it)
            with pytest.raises(ValueError):
                rr.generate(list(range(1, 70)), 8, timeout=60)
            with pytest.raises(DeadlineExceededError):
                rr.generate([1, 2, 3], 8, deadline_ms=0.0, timeout=60)
            futs, shed = [], 0
            for _ in range(64):
                try:
                    futs.append(rr.submit([1, 2, 3], 30))
                except ServerOverloadedError:
                    shed += 1
            assert shed > 0             # backpressure reached the caller
            for f in futs:
                f.result(120)
        finally:
            rr.stop(drain=True)
            rs.close(stop_server=False)
        assert not rr.alive

    def test_wire_fleet_adds_zero_dispatches_vs_inprocess_fleet(self):
        """THE zero-added-dispatch acceptance pin: the same sequential
        round-robin workload through (1) a fleet of wire replicas on a
        real loopback socket and (2) the same fleet in-process —
        per-replica dispatch and token counters IDENTICAL, results
        bit-identical. The wire is host-side plumbing; it must never
        buy a token with an extra device dispatch."""
        lm = _lm()
        prompts = [[1 + i, 2, 3] for i in range(6)]
        counts, outs = {}, {}
        fleet = _WireFleet(lm, n_replicas=2, policy="round_robin")
        with fleet as mgr:
            outs["wire"] = [mgr.generate(p, 5, timeout=120)
                            for p in prompts]
            counts["wire"] = []
            for n in mgr.replicas:
                snap = mgr.replica(n).metrics.kind_snapshot()
                counts["wire"].append(
                    ((snap.get("dispatches") or {}).get("value") or 0,
                     (snap.get("tokens_out") or {}).get("value") or 0))

        def local_factory(name):
            return ContinuousDecodeServer(
                lm, slots=2, prompt_buckets=(8, 16),
                metrics=ServingMetrics(name=name), instance=name)
        with FleetManager(local_factory, n_replicas=2,
                          policy="round_robin") as mgr:
            for n in mgr.replicas:
                mgr.replica(n).generate([1, 2, 3], 2, timeout=120)
            outs["local"] = [mgr.generate(p, 5, timeout=120)
                             for p in prompts]
            counts["local"] = [
                (mgr.replica(n).metrics.count_value("dispatches"),
                 mgr.replica(n).metrics.count_value("tokens_out"))
                for n in mgr.replicas]
        assert counts["wire"] == counts["local"]
        assert [list(r) for r in outs["wire"]] == \
            [list(r) for r in outs["local"]]

    def test_wire_counters_always_present_on_fleet_snapshot(self):
        """The satellite surface pin: wire_reconnects / wire_retries /
        migrate_refused ride EVERY fleet snapshot as zeros on a fleet
        that never lost a connection (the PINNED_KEYS twin lives in
        test_obs)."""
        lm = _lm()
        with _WireFleet(lm, n_replicas=2) as mgr:
            snap = mgr.fleet_snapshot()
            for key in ("fleet_wire_reconnects", "fleet_wire_retries",
                        "fleet_migrate_refused"):
                assert snap[key] == 0
            assert mgr.heartbeat_timeout is None    # exposed config


# ---------------------------------------------------------------------------
# (b) at-most-once
# ---------------------------------------------------------------------------
class TestAtMostOnce:
    def test_drop_after_ack_decodes_once_one_wire_retry(self):
        """THE at-most-once pin (ISSUE 14 satellite): a seeded sever on
        `serve.wire.submit` fires AFTER the frame went out — the
        replica decodes, the ack dies with the connection. The retried
        SUBMIT must dedup: exactly one decoded stream (the replicas'
        summed `received` moves by 1), exactly one `wire_retries`
        increment, and the caller's future resolves bit-identically."""
        lm = _lm()
        ref = list(lm.generate([1, 2, 3], 24))
        inj = FaultInjector()
        fleet = _WireFleet(lm, injector=inj, n_replicas=2)
        with fleet as mgr:
            base_recv = fleet.received_total()
            base = mgr.fleet_snapshot()
            inj.plan("serve.wire.submit",
                     on_call=inj.calls("serve.wire.submit"),
                     sever=True, exc=None)
            fut = mgr.submit([1, 2, 3], 24)
            assert list(fut.result(120)) == ref
            snap = mgr.fleet_snapshot()
            assert snap["fleet_wire_retries"] \
                - base["fleet_wire_retries"] == 1
            assert snap["fleet_wire_reconnects"] \
                - base["fleet_wire_reconnects"] == 1
            assert fleet.received_total() - base_recv == 1

    def test_stream_sever_redelivers_without_redecoding(self):
        """A sever as the result frame arrives (`serve.wire.stream`)
        drops the stream mid-flight: reconnect re-SUBMITs, the dedup
        registry re-attaches, and the finished result is RE-DELIVERED
        — one decode, correct bits."""
        lm = _lm()
        ref = list(lm.generate([4, 5], 24))
        inj = FaultInjector()
        fleet = _WireFleet(lm, injector=inj, n_replicas=2)
        with fleet as mgr:
            base_recv = fleet.received_total()
            inj.plan("serve.wire.stream",
                     on_call=inj.calls("serve.wire.stream"),
                     sever=True, exc=None)
            fut = mgr.submit([4, 5], 24)
            assert list(fut.result(120)) == ref
            assert fleet.received_total() - base_recv == 1


# ---------------------------------------------------------------------------
# (c) liveness
# ---------------------------------------------------------------------------
class TestHeartbeatReap:
    def test_heartbeat_silence_reaps_and_fails_over_zero_lost(self):
        """A HUNG replica — heartbeats go silent while the main socket
        still answers — is reaped on `heartbeat_timeout`: `alive`
        decays, the control tick's probe crashes it, its in-flight
        requests fail over to survivors, every stream bit-identical
        to solo, zero lost."""
        lm = _lm()
        prompts = [[1, 2, 3], [4, 5], [6, 7, 8, 9]]
        refs = {tuple(p): list(lm.generate(p, 32)) for p in prompts}
        fleet = _WireFleet(lm, n_replicas=2, heartbeat_timeout=0.4)
        with fleet as mgr:
            victim = mgr.replicas[0]
            futs = [mgr.submit(prompts[i % 3], 32) for i in range(6)]
            fleet.wrappers[victim].pause_heartbeats = True
            deadline = time.monotonic() + 10
            while mgr.replica(victim).alive:
                if time.monotonic() > deadline:
                    raise TimeoutError("alive never decayed")
                time.sleep(0.02)
            tick = mgr.control_tick()
            assert tick["states"][victim] == "dead"
            assert tick["n_replicas"] == 2          # backfilled
            for i, f in enumerate(futs):
                assert list(f.result(120)) == refs[tuple(prompts[i % 3])]
            snap = mgr.fleet_snapshot()
            assert snap["fleet_replica_dead"] == 1

    def test_retry_exhaustion_fails_pending_loudly(self):
        """The wire dies for good (listener closed, replica gone):
        bounded reconnect exhausts and every pending future fails
        LOUDLY with ReplicaDeadError — never a silent hang."""
        lm = _lm()
        srv = ContinuousDecodeServer(lm, slots=2, prompt_buckets=(8, 16),
                                     metrics=ServingMetrics(name="i0"),
                                     instance="i0")
        rs = ReplicaServer(srv)
        rr = RemoteReplica(
            "127.0.0.1", rs.port, name="i0", heartbeat_interval=None,
            retry_policy=RetryPolicy(max_retries=1, base_delay=0.01,
                                     jitter=0.0))
        try:
            rr.generate([1, 2, 3], 2, timeout=120)      # warm + healthy
            fut = rr.submit([1, 2, 3], 56)
            # the wire dies mid-stream AND the listener is gone, so
            # reconnect gets ECONNREFUSED until the budget exhausts
            rs.close(stop_server=False)
            rr._sever_main()
            with pytest.raises(Exception) as ei:
                fut.result(30)
            assert isinstance(ei.value, ReplicaDeadError)
            assert not rr.alive
            with pytest.raises(ReplicaDeadError):
                rr.submit([1, 2, 3], 2)
        finally:
            rr.kill()
            srv.kill()
            rs.close(stop_server=False)


# ---------------------------------------------------------------------------
# (d) migration over the wire
# ---------------------------------------------------------------------------
class TestWireMigration:
    def _inflight_victim(self, mgr, timeout=0.5):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with mgr._lock:
                for r in mgr._replicas.values():
                    if r.inflight:
                        return r.name
            time.sleep(0.002)
        raise TimeoutError("no in-flight replica found")

    def test_scale_down_ships_artifact_bytes_bit_identical(self):
        """The PR 11 bit-identity pin across a REAL socket: scale_down
        drains a wire replica, the decode-phase request leaves as
        `RequestArtifact` BYTES (`to_bytes` over the DRAIN frame),
        lands on the survivor via `migrate_in`, and the caller's one
        future resolves to exactly the uninterrupted stream."""
        lm = _lm()
        refs = {tuple(p): list(lm.generate(p, 56))
                for p in ([1, 2, 3], [4, 5])}
        fleet = _WireFleet(lm, paged=True, n_replicas=2, min_replicas=1)
        with fleet as mgr:
            futs = [mgr.submit([1, 2, 3], 56), mgr.submit([4, 5], 56)]
            victim = self._inflight_victim(mgr)
            mgr.scale_down(victim)
            for f, p in zip(futs, ([1, 2, 3], [4, 5])):
                assert list(f.result(120)) == refs[tuple(p)]
            # at least one request really moved as an artifact (the
            # other may have been queued/prefilling -> replayed)
            migrated = 0
            for n in mgr.replicas:
                snap = mgr.replica(n).metrics.kind_snapshot()
                migrated += (snap.get("migrated") or {}).get("value") or 0
            assert migrated >= 1
            assert mgr.fleet_snapshot()["fleet_replica_drained"] == 1

    def test_refused_migration_degrades_to_replay_never_lost(self):
        """Mid-rollout fleet: the survivor runs NEW params, so the
        drained artifact's version tag is refused at `migrate_in`
        (KVStateVersionError over the wire). The manager counts
        `migrate_refused` and degrades to prompt replay on the
        survivor — the caller's future resolves with the survivor's
        (new-params) solo stream; nothing is lost."""
        lm = _lm()
        lm2 = _lm(seed=11)
        ref_new = list(lm2.generate([1, 2, 3], 56))
        fleet = _WireFleet(lm, paged=True, n_replicas=2, min_replicas=1)
        with fleet as mgr:
            fut = mgr.submit([1, 2, 3], 56)
            victim = self._inflight_victim(mgr)
            survivor = next(n for n in mgr.replicas if n != victim)
            mgr.replica(survivor).swap(lm2)     # SWAP over the wire
            mgr.scale_down(victim)
            assert list(fut.result(120)) == ref_new
            snap = mgr.fleet_snapshot()
            assert snap["fleet_migrate_refused"] >= 1


# ---------------------------------------------------------------------------
# (e) graftlint regressions (ISSUE 15): future-hygiene at the wire —
#     a registered op must NEVER be left for its caller to time out on
# ---------------------------------------------------------------------------
class TestWireFutureHygiene:
    def test_send_failure_after_close_fails_op_immediately(self):
        """A stop()/kill() racing past the submit-time usable check
        used to spawn a reconnector that exits on closed/dead without
        failing the just-registered op — stranding the caller for the
        full op timeout (120s). The op must fail LOUDLY the moment
        the send fails."""
        from deeplearning4j_tpu.serving.wire import OP_SUBMIT, _PendingOp
        from deeplearning4j_tpu.serving import ServerClosedError
        lm = _lm()
        srv = ContinuousDecodeServer(lm, slots=2, prompt_buckets=(8,),
                                     metrics=ServingMetrics(name="i0"),
                                     instance="i0")
        rs = ReplicaServer(srv)
        rr = RemoteReplica("127.0.0.1", rs.port, name="i0",
                           heartbeat_interval=None, op_timeout=120.0)
        try:
            # the race, made deterministic: close lands AFTER
            # _check_usable would have passed, BEFORE the send
            rr._closed = True
            rr._sock.close()        # raw close: next sendall raises
            p = _PendingOp("race:0", OP_SUBMIT,
                           {"id": "race:0", "prompt": [1],
                            "max_new": 1}, stream=True)
            t0 = time.monotonic()
            rr._send_op(p)
            with pytest.raises(ServerClosedError):
                p.ack.result(5.0)
            assert p.stream.done()
            with pytest.raises(ServerClosedError):
                p.stream.result(0)
            assert time.monotonic() - t0 < 5.0, \
                "op stranded until its timeout instead of failing"
        finally:
            rr._closed = False
            rr.kill()
            rs.close()

    def test_failed_op_is_forgotten_not_resent_forever(self):
        """An op whose ack never arrives (timeout -> ReplicaDeadError)
        used to stay in `_pending` forever: excluded from the done-op
        prune AND re-sent on every later reconnect. swap/migrate_out/
        drain now forget the op on failure."""
        from deeplearning4j_tpu.serving.wire import OP_SWAP
        lm = _lm()
        srv = ContinuousDecodeServer(lm, slots=2, prompt_buckets=(8,),
                                     metrics=ServingMetrics(name="i0"),
                                     instance="i0")
        rs = ReplicaServer(srv)
        orig = rs._dispatch

        def blackhole(conn, op, hdr, blob):
            if op == OP_SWAP:
                return True          # swallow: the lost-ack scenario
            return orig(conn, op, hdr, blob)

        rs._dispatch = blackhole
        rr = RemoteReplica("127.0.0.1", rs.port, name="i0",
                           heartbeat_interval=None, op_timeout=1.0)
        try:
            with pytest.raises(ReplicaDeadError):
                rr.swap(_lm(seed=3))
            with rr._plock:
                leftover = [p for p in rr._pending.values()
                            if p.op == OP_SWAP]
            assert not leftover, \
                "failed SWAP lingered in _pending (resent forever)"
        finally:
            rr.kill()
            rs.close()
